"""Preemption-safe checkpoints (ISSUE 14, lightgbm_tpu/checkpoint.py).

Pins the tentpole contracts: a restart from a checkpoint continues
BIT-IDENTICALLY on the same topology (model text, scores, RNG streams —
per-iteration AND fused-chunk paths, f32 and int8), the file format
rejects truncation/corruption/config-mismatch with a precise error
naming the field, the write discipline is atomic (a crash mid-write
leaves the previous checkpoint loadable), and the asynchronous writer
rides off the hot loop and never outlives run_training (the conftest
leak guard enforces the latter suite-wide)."""
import json
import os

import numpy as np
import pytest

from lightgbm_tpu import checkpoint as ckpt
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.utils import log


@pytest.fixture()
def data():
    rng = np.random.RandomState(7)
    x = rng.randn(1200, 10)
    y = (x[:, 0] - x[:, 1] + 0.4 * rng.randn(1200) > 0).astype(np.float32)
    return x, y


BASE = {"objective": "binary", "num_leaves": "8", "min_data_in_leaf": "5",
        "min_sum_hessian_in_leaf": "0.1", "learning_rate": "0.1",
        "verbose": "-1"}


def make_booster(x, y, extra=None, valid=None, metrics=()):
    params = dict(BASE)
    if extra:
        params.update(extra)
    cfg = OverallConfig()
    cfg.set(params, require_data=False)
    ds = Dataset.from_arrays(x, y, max_bin=63)
    b = GBDT()
    b.init(cfg.boosting_config, ds,
           create_objective(cfg.objective_type, cfg.objective_config),
           list(metrics))
    if valid is not None:
        vx, vy, vmetrics = valid
        vds = Dataset.from_arrays(vx, vy, max_bin=63)
        b.add_valid_dataset(vds, list(vmetrics))
    return b


def fingerprint(b):
    return ([t.to_string() for t in b.models], np.asarray(b.score))


@pytest.mark.parametrize("extra", [
    {},                                                    # f32 leafwise
    {"hist_dtype": "int8"},                                # int8 leafwise
    {"grow_policy": "depthwise"},                          # fused chunk f32
    {"grow_policy": "depthwise", "hist_dtype": "int8"},    # fused chunk int8
    {"bagging_fraction": "0.8", "bagging_freq": "2",       # RNG streams
     "feature_fraction": "0.8"},
], ids=["f32", "int8", "chunk_f32", "chunk_int8", "bagging_ff"])
def test_same_topology_restore_bit_identical(data, tmp_path, extra):
    """train(8) == train(4) -> checkpoint -> fresh booster -> restore ->
    train(4): model text AND scores bitwise, through a real file."""
    x, y = data
    a = make_booster(x, y, extra)
    a.run_training(8, is_eval=False, chunk_size=4)
    trees_a, score_a = fingerprint(a)

    b = make_booster(x, y, extra)
    b.run_training(4, is_eval=False, chunk_size=4)
    path = ckpt.write_checkpoint(str(tmp_path),
                                 ckpt.serialize_state(b.checkpoint_state()))
    c = make_booster(x, y, extra)
    c.restore_checkpoint(str(path))
    assert c.iter == 4 and len(c.models) == 4
    c.run_training(4, is_eval=False, chunk_size=4)

    trees_c, score_c = fingerprint(c)
    assert trees_a == trees_c
    np.testing.assert_array_equal(score_a, score_c)


def test_restore_preserves_early_stopping_state(data, tmp_path):
    """best_score/best_iter and valid scores survive the round trip:
    resumed training makes the same early-stopping decisions."""
    from lightgbm_tpu.metrics import create_metric
    x, y = data
    vx, vy = x[:300], y[:300]

    def make():
        cfg = OverallConfig()
        params = dict(BASE)
        params.update({"metric": "auc", "early_stopping_round": "50"})
        cfg.set(params, require_data=False)
        ds = Dataset.from_arrays(x[300:], y[300:], max_bin=63)
        vds = Dataset.from_arrays(vx, vy, max_bin=63)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config))
        b.add_valid_dataset(vds, [create_metric("auc", cfg.metric_config)])
        return b

    a = make()
    a.run_training(8, is_eval=True)
    b = make()
    b.run_training(4, is_eval=True)
    payload = ckpt.serialize_state(b.checkpoint_state())
    c = make()
    c.restore_checkpoint(json.loads(json.dumps(payload)))
    assert c.best_score == b.best_score
    assert c.best_iter == b.best_iter
    np.testing.assert_array_equal(
        np.asarray(c.valid_datasets[0]["score"]),
        np.asarray(b.valid_datasets[0]["score"]))
    c.run_training(4, is_eval=True)
    assert [t.to_string() for t in c.models] == \
        [t.to_string() for t in a.models]
    assert c.best_score == a.best_score
    assert c.best_iter == a.best_iter


def test_pipelined_checkpoint_describes_consumed_boundary(data):
    """With an iteration in flight (pipeline=readback), checkpoint_state
    snapshots the CONSUMED boundary — restoring it and retraining the
    tail reproduces the uninterrupted run exactly."""
    x, y = data
    a = make_booster(x, y)
    a.run_training(6, is_eval=False)
    trees_a, score_a = fingerprint(a)

    b = make_booster(x, y, {"pipeline": "readback"})
    for _ in range(3):
        b.train_one_iter(is_eval=False)
    # iteration 3 dispatched, 2 consumed: the snapshot must say 2
    assert b._pipe is not None
    state = b.checkpoint_state()
    assert state["iteration"] == 2
    assert len(state["models"]) == 2
    payload = ckpt.serialize_state(state)
    assert b.flush_pipeline() is False

    c = make_booster(x, y)
    c.restore_checkpoint(payload)
    c.run_training(4, is_eval=False)
    trees_c, score_c = fingerprint(c)
    assert trees_a == trees_c
    np.testing.assert_array_equal(score_a, score_c)


def test_run_training_async_writer_lifecycle(data, tmp_path):
    """checkpoint_interval= writes atomic files on the background writer,
    prunes to checkpoint_keep, writes a final sync checkpoint, and
    closes the writer (live_writers() == 0 afterwards — also enforced by
    the conftest leak guard)."""
    x, y = data
    cdir = str(tmp_path / "ck")
    b = make_booster(x, y, {"checkpoint_interval": "2",
                            "checkpoint_dir": cdir,
                            "checkpoint_keep": "2"})
    b.run_training(6, is_eval=False)
    assert ckpt.live_writers() == 0
    files = ckpt.list_checkpoints(cdir)
    assert 1 <= len(files) <= 2          # pruned to keep=2
    latest = ckpt.latest_checkpoint(cdir)
    payload = ckpt.load_checkpoint(latest)
    assert payload["iteration"] == 6     # the final sync checkpoint
    assert len(payload["trees"]) == 6

    c = make_booster(x, y)
    c.restore_checkpoint(payload)
    assert fingerprint(c)[0] == fingerprint(b)[0]
    np.testing.assert_array_equal(np.asarray(c.score), np.asarray(b.score))


def test_no_interval_no_writer(data, tmp_path):
    x, y = data
    b = make_booster(x, y)
    b.run_training(2, is_eval=False)
    assert ckpt.live_writers() == 0
    assert ckpt.list_checkpoints(str(tmp_path)) == []


def _valid_checkpoint(data, tmp_path):
    x, y = data
    b = make_booster(x, y)
    b.run_training(3, is_eval=False)
    path = ckpt.write_checkpoint(
        str(tmp_path), ckpt.serialize_state(b.checkpoint_state()))
    return b, path


def test_truncated_checkpoint_rejected(data, tmp_path):
    _, path = _valid_checkpoint(data, tmp_path)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])
    with pytest.raises(ckpt.CheckpointError, match="truncated"):
        ckpt.load_checkpoint(path)


def test_corrupt_checkpoint_rejected(data, tmp_path):
    _, path = _valid_checkpoint(data, tmp_path)
    blob = bytearray(open(path, "rb").read())
    blob[-20] ^= 0x41
    with open(path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(ckpt.CheckpointError, match="sha256"):
        ckpt.load_checkpoint(path)


def test_bad_header_rejected(tmp_path):
    path = str(tmp_path / "ckpt-00000001.json")
    with open(path, "w") as f:
        f.write("not a checkpoint at all\n{}")
    with pytest.raises(ckpt.CheckpointError, match="header"):
        ckpt.load_checkpoint(path)


def test_missing_field_named(data, tmp_path):
    """A structurally valid file missing a payload field names the field
    in the error — not a KeyError three layers down."""
    b, path = _valid_checkpoint(data, tmp_path)
    payload = ckpt.load_checkpoint(path)
    for field in ("rng", "trees", "score", "config"):
        broken = {k: v for k, v in payload.items() if k != field}
        p2 = ckpt.write_checkpoint(str(tmp_path / ("f_" + field)), broken)
        with pytest.raises(ckpt.CheckpointError, match="'%s'" % field):
            ckpt.load_checkpoint(p2)


def test_config_mismatch_names_field(data, tmp_path):
    x, y = data
    _, path = _valid_checkpoint(data, tmp_path)
    payload = ckpt.load_checkpoint(path)
    c = make_booster(x, y, {"num_leaves": "16"})
    with pytest.raises(log.LightGBMError, match="num_leaves"):
        c.restore_checkpoint(payload)
    d = make_booster(x, y, {"learning_rate": "0.2"})
    with pytest.raises(log.LightGBMError, match="learning_rate"):
        d.restore_checkpoint(payload)


def test_dataset_mismatch_names_field(data, tmp_path):
    x, y = data
    _, path = _valid_checkpoint(data, tmp_path)
    payload = ckpt.load_checkpoint(path)
    e = make_booster(x[:800], y[:800])
    with pytest.raises(log.LightGBMError, match="num_rows"):
        e.restore_checkpoint(payload)


def test_restore_requires_fresh_booster(data, tmp_path):
    x, y = data
    _, path = _valid_checkpoint(data, tmp_path)
    payload = ckpt.load_checkpoint(path)
    c = make_booster(x, y)
    c.restore_checkpoint(payload)
    with pytest.raises(log.LightGBMError, match="freshly initialized"):
        c.restore_checkpoint(payload)


def test_atomic_rename_discipline(data, tmp_path):
    """A crash mid-write leaves (a) the previous checkpoint loadable and
    (b) only a stray .tmp-* file the loader/lister ignore."""
    _, path = _valid_checkpoint(data, tmp_path)
    # simulate a writer killed mid-write: a partial temp file appears
    stray = str(tmp_path / ".tmp-9999-1")
    with open(stray, "w") as f:
        f.write("lightgbm_tpu_checkpoint v1 sha256=" + "0" * 64
                + " bytes=99999\n{\"partial")
    assert ckpt.list_checkpoints(str(tmp_path)) == [path]
    assert ckpt.latest_checkpoint(str(tmp_path)) == path
    payload = ckpt.load_checkpoint(path)     # previous still loadable
    assert payload["iteration"] == 3


def test_latest_checkpoint_orders_by_iteration(tmp_path):
    for it in (3, 12, 7):
        p = str(tmp_path / ("ckpt-%08d.json" % it))
        with open(p, "w") as f:
            f.write("x")
    assert ckpt.latest_checkpoint(str(tmp_path)).endswith(
        "ckpt-00000012.json")


def test_writer_latest_wins_and_close(tmp_path, data):
    """Backpressure contract: submit never blocks; a pending snapshot is
    replaced by a newer one (counted as dropped), and close drains."""
    x, y = data
    b = make_booster(x, y)
    b.run_training(2, is_eval=False)
    w = ckpt.CheckpointWriter(str(tmp_path), keep=5)
    try:
        for _ in range(5):
            w.submit(b.checkpoint_state())
    finally:
        w.close()
    assert not w.alive
    assert ckpt.live_writers() == 0
    assert w.written >= 1
    assert w.written + w.dropped == 5
    assert ckpt.latest_checkpoint(str(tmp_path)) is not None


def test_config_knob_rejects():
    cfg = OverallConfig()
    with pytest.raises(log.LightGBMError, match="checkpoint_dir"):
        cfg.set({"objective": "binary", "checkpoint_interval": "4"},
                require_data=False)
    cfg2 = OverallConfig()
    with pytest.raises(log.LightGBMError, match="checkpoint_keep"):
        cfg2.set({"objective": "binary", "checkpoint_interval": "4",
                  "checkpoint_dir": "/tmp/x", "checkpoint_keep": "0"},
                 require_data=False)
    cfg3 = OverallConfig()
    with pytest.raises(log.LightGBMError, match="straggler_k"):
        cfg3.set({"objective": "binary", "straggler_k": "0"},
                 require_data=False)
    cfg4 = OverallConfig()
    with pytest.raises(log.LightGBMError, match="elastic_shrink"):
        cfg4.set({"objective": "binary", "elastic_shrink": "true"},
                 require_data=False)
