"""Device (in-program) metric formulations must match the host evaluators
bit-for-bit-ish — the host versions are themselves differential-tested
against the reference binary."""
import numpy as np
import pytest

from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.metadata import Metadata
from lightgbm_tpu.metrics import create_metric


def _metadata(label, weights=None, query_sizes=None):
    md = Metadata()
    md.set_label(np.asarray(label, np.float32))
    if weights is not None:
        md.weights = np.asarray(weights, np.float32)
    if query_sizes is not None:
        md.query_boundaries = np.concatenate(
            ([0], np.cumsum(query_sizes))).astype(np.int32)
        md._load_query_weights()
    md.finalize(len(label))
    return md


def _cfg(**over):
    cfg = OverallConfig()
    cfg.set({k: str(v) for k, v in over.items()}, require_data=False)
    return cfg.metric_config


@pytest.mark.parametrize("metric_type,binary_label", [
    ("l2", False), ("l1", False),
    ("binary_logloss", True), ("binary_error", True), ("auc", True),
])
@pytest.mark.parametrize("weighted", [False, True])
def test_single_class_metrics(metric_type, binary_label, weighted):
    rng = np.random.RandomState(3)
    n = 500
    label = (rng.randint(0, 2, n).astype(np.float64) if binary_label
             else rng.randn(n))
    # include exact score ties to exercise AUC tie grouping
    score = np.round(rng.randn(n), 1)
    weights = np.abs(rng.rand(n)) + 0.5 if weighted else None

    m = create_metric(metric_type, _cfg())
    m.init("t", _metadata(label, weights), n)
    host = m.eval(score)

    key, params, fn = m.device_spec()
    import jax.numpy as jnp
    dev = np.asarray(fn(params, jnp.asarray(score, jnp.float32)))
    np.testing.assert_allclose(dev, host, rtol=2e-5, atol=1e-7)


@pytest.mark.parametrize("metric_type", ["multi_logloss", "multi_error"])
def test_multiclass_metrics(metric_type):
    rng = np.random.RandomState(4)
    n, k = 400, 5
    label = rng.randint(0, k, n).astype(np.float64)
    score = rng.randn(k, n)
    m = create_metric(metric_type, _cfg(num_class=k, objective="multiclass"))
    m.init("t", _metadata(label), n)
    host = m.eval(score.reshape(-1))
    key, params, fn = m.device_spec()
    import jax.numpy as jnp
    dev = np.asarray(fn(params, jnp.asarray(score, jnp.float32)))
    np.testing.assert_allclose(dev, host, rtol=2e-5, atol=1e-7)


def test_ndcg_metric_device():
    rng = np.random.RandomState(5)
    sizes = rng.randint(2, 30, size=40)
    n = int(sizes.sum())
    label = rng.randint(0, 4, n).astype(np.float64)
    # make a couple of queries all-negative (reference: count as 1.0)
    b = np.concatenate(([0], np.cumsum(sizes)))
    for q in (3, 17):
        label[b[q]:b[q + 1]] = 0
    score = rng.randn(n)
    m = create_metric("ndcg", _cfg(objective="lambdarank"))
    m.init("t", _metadata(label, query_sizes=sizes), n)
    host = m.eval(score)
    key, params, fn = m.device_spec()
    import jax.numpy as jnp
    dev = np.asarray(fn(params, jnp.asarray(score, jnp.float32)))
    np.testing.assert_allclose(dev, host, rtol=3e-5, atol=1e-7)


def test_binary_logloss_extreme_scores_finite():
    """Confidently-wrong rows must yield the host's clipped finite loss,
    not inf (f32 rounds 1-1e-15 to exactly 1.0, so a naive prob-clip
    overflows -log(1-p))."""
    label = np.array([0.0, 1.0, 0.0, 1.0])
    score = np.array([10.0, -10.0, 50.0, -50.0])   # all badly wrong
    m = create_metric("binary_logloss", _cfg())
    m.init("t", _metadata(label), 4)
    host = m.eval(score)
    key, params, fn = m.device_spec()
    import jax.numpy as jnp
    dev = np.asarray(fn(params, jnp.asarray(score, jnp.float32)))
    assert np.isfinite(dev).all()
    np.testing.assert_allclose(dev, host, rtol=1e-5)
