"""scripts/timeline_report.py tests: shard merge under deliberate clock
offsets, per-phase skew attribution, the persistent-straggler flag,
crash-tail tolerance vs mid-file corruption, and the Perfetto export."""
import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "timeline_report", os.path.join(REPO, "scripts", "timeline_report.py"))
tr = importlib.util.module_from_spec(spec)
spec.loader.exec_module(tr)


def _write_shard(path, index, count, offset, records, host="hostA",
                 truncate_tail=False):
    with open(path, "w") as f:
        f.write(json.dumps({"shard": {
            "process_index": index, "process_count": count,
            "pid": 1000 + index, "clock_offset_s": offset,
            "host": host, "started_unix": 0.0}}) + "\n")
        for rec in records:
            f.write(json.dumps(rec) + "\n")
        if truncate_tail:
            f.write('{"iter": 99, "phase_times"')   # killed mid-write
    return path


def _iter_rec(it, t, phase_times, **extra):
    rec = {"iter": it, "phase_times": phase_times, "counters": {},
           "eval_metrics": {}, "t": t}
    rec.update(extra)
    return rec


def test_merge_applies_clock_offsets_round_trip(tmp_path):
    """Host B's clock runs 100 s AHEAD of the leader (offset -100 maps it
    back).  True event order (leader clock): A1, B1, A2, B2 — raw local
    stamps would order every B event after every A event."""
    a = _write_shard(str(tmp_path / "s0"), 0, 2, 0.0, [
        _iter_rec(1, 10.0, {"histogram": 0.5}),
        _iter_rec(2, 12.0, {"histogram": 0.5}),
    ], host="A")
    b = _write_shard(str(tmp_path / "s1"), 1, 2, -100.0, [
        _iter_rec(1, 111.0, {"histogram": 0.5}),
        _iter_rec(2, 113.0, {"histogram": 0.5}),
    ], host="B")
    shards = [tr.load_shard(p) for p in (a, b)]
    events = tr.merge_timeline(shards)
    order = [(e["_host"], e["iter"]) for e in events]
    assert order == [("p0@A", 1), ("p1@B", 1), ("p0@A", 2), ("p1@B", 2)]
    assert [round(e["_t"], 3) for e in events] == [10.0, 11.0, 12.0, 13.0]


def test_skew_table_flags_slow_phase_and_straggler(tmp_path):
    """Host B is consistently 3x slower in histogram: max_phase_skew must
    price it and the persistent-straggler flag must name B after K
    consecutive slowest iterations."""
    iters = 4
    a = _write_shard(str(tmp_path / "s0"), 0, 2, 0.0, [
        _iter_rec(i, float(i), {"histogram": 0.1, "split_find": 0.05})
        for i in range(1, iters + 1)], host="A")
    b = _write_shard(str(tmp_path / "s1"), 1, 2, 0.0, [
        _iter_rec(i, float(i), {"histogram": 0.3, "split_find": 0.05})
        for i in range(1, iters + 1)], host="B")
    shards = [tr.load_shard(p) for p in (a, b)]
    skew = tr.skew_report(shards, straggler_k=3)
    assert skew["iterations_compared"] == iters
    assert skew["phases"]["histogram"]["max_skew"] == pytest.approx(1.5)
    assert skew["phases"]["split_find"]["max_skew"] == pytest.approx(1.0)
    assert skew["max_phase_skew"] == pytest.approx(1.5)
    assert skew["persistent_straggler"] == "p1@B"
    # A waits 0.2 s per iteration for B at the collectives
    assert skew["barrier_wait_s"]["p0@A"] == pytest.approx(0.2 * iters)
    assert skew["barrier_wait_s"]["p1@B"] == 0.0


def test_no_straggler_when_slowest_alternates(tmp_path):
    recs_a, recs_b = [], []
    for i in range(1, 7):
        slow_a = 0.3 if i % 2 else 0.1
        slow_b = 0.1 if i % 2 else 0.3
        recs_a.append(_iter_rec(i, float(i), {"histogram": slow_a}))
        recs_b.append(_iter_rec(i, float(i), {"histogram": slow_b}))
    a = _write_shard(str(tmp_path / "s0"), 0, 2, 0.0, recs_a, host="A")
    b = _write_shard(str(tmp_path / "s1"), 1, 2, 0.0, recs_b, host="B")
    skew = tr.skew_report([tr.load_shard(p) for p in (a, b)],
                          straggler_k=3)
    assert skew["persistent_straggler"] is None


def test_truncated_tail_tolerated_midfile_corruption_rejected(tmp_path):
    ok = _write_shard(str(tmp_path / "s0"), 0, 1, 0.0,
                      [_iter_rec(1, 1.0, {"histogram": 0.1})],
                      truncate_tail=True)
    shard = tr.load_shard(ok)
    assert shard["truncated"] and len(shard["records"]) == 1

    bad = str(tmp_path / "s1")
    with open(bad, "w") as f:
        f.write('{"iter": 1, "phase_times"\n')      # corrupt MID-file
        f.write(json.dumps(_iter_rec(2, 2.0, {})) + "\n")
    with pytest.raises(tr.ReportError):
        tr.load_shard(bad)


def test_cli_exit_codes(tmp_path, capsys):
    a = _write_shard(str(tmp_path / "s0"), 0, 2, 0.0, [
        _iter_rec(i, float(i), {"histogram": 0.1}) for i in range(1, 5)],
        host="A")
    b = _write_shard(str(tmp_path / "s1"), 1, 2, 0.0, [
        _iter_rec(i, float(i), {"histogram": 0.4}) for i in range(1, 5)],
        host="B")
    # persistent straggler -> exit 1; report names it
    assert tr.main([a, b]) == 1
    out = capsys.readouterr().out
    assert "PERSISTENT STRAGGLER" in out and "p1@B" in out
    # no shards -> exit 2
    assert tr.main([str(tmp_path / "nope-*")]) == 2
    # healthy pair -> exit 0 with a skew table
    c = _write_shard(str(tmp_path / "s2"), 1, 2, 0.0, [
        _iter_rec(i, float(i), {"histogram": 0.1}) for i in range(1, 5)],
        host="C")
    assert tr.main([a, c]) == 0
    assert "per-phase cross-host skew" in capsys.readouterr().out


def test_json_report_and_glob(tmp_path, capsys):
    a = _write_shard(str(tmp_path / "r.jsonl.shard-00000of00002.jsonl"),
                     0, 2, 0.0,
                     [_iter_rec(1, 1.0, {"histogram": 0.1})], host="A")
    _write_shard(str(tmp_path / "r.jsonl.shard-00001of00002.jsonl"),
                 1, 2, 0.0,
                 [_iter_rec(1, 1.0, {"histogram": 0.2})], host="B")
    assert tr.main(["--glob", str(tmp_path / "r.jsonl.shard-*"),
                    "--json"]) == 0
    skew = json.loads(capsys.readouterr().out)
    assert skew["iterations_compared"] == 1
    assert skew["phases"]["histogram"]["max_skew"] == pytest.approx(
        4 / 3, rel=1e-3)


def test_perfetto_export(tmp_path):
    a = _write_shard(str(tmp_path / "s0"), 0, 2, 0.0, [
        _iter_rec(1, 10.0, {"histogram": 0.5, "eval": 0.25})], host="A")
    b = _write_shard(str(tmp_path / "s1"), 1, 2, -5.0, [
        _iter_rec(1, 15.5, {"histogram": 0.5})], host="B")
    out = str(tmp_path / "trace.json")
    assert tr.main([a, b, "--perfetto", out]) == 0
    trace = json.load(open(out))["traceEvents"]
    slices = [e for e in trace if e["ph"] == "X"]
    metas = [e for e in trace if e["ph"] == "M"]
    assert {m["args"]["name"] for m in metas} == {"p0@A", "p1@B"}
    assert {s["name"] for s in slices} == {"histogram", "eval"}
    # host B's slice lands on the leader clock (15.5 - 5.0 = 10.5)
    b_slice = [s for s in slices if s["pid"] == 1][0]
    assert b_slice["ts"] + b_slice["dur"] == pytest.approx(10.5e6)


def test_wire_decomposition_from_interconnect(tmp_path):
    summary = {"summary": True, "t": 20.0, "phase_times": {},
               "interconnect": {"sites": {}, "phases": {
                   "grow": {"est_bytes": 10 ** 9, "span_seconds": 2.0,
                            "attained_gb_per_s": 0.5}}}}
    a = _write_shard(str(tmp_path / "s0"), 0, 2, 0.0, [
        _iter_rec(1, 1.0, {"histogram": 0.1}), summary], host="A")
    b = _write_shard(str(tmp_path / "s1"), 1, 2, 0.0, [
        _iter_rec(1, 1.0, {"histogram": 0.2})], host="B")
    skew = tr.skew_report([tr.load_shard(p) for p in (a, b)])
    assert skew["wire"]["est_bytes_total"] == 10 ** 9
    assert skew["wire"]["attained_gb_per_s"] == pytest.approx(0.5)
