"""Distributed-observability tests (ISSUE 5): per-collective wire
metrics, per-process shard sinks, clock-offset plumbing, and the
hung-collective flight recorder.

The acceptance invariant mirrors PR 1/2/4: training scores must be
BIT-identical with the distributed telemetry layer on or off — the
collective wrappers call the underlying collective unchanged and record
only at trace time, so nothing enters the compiled programs.
"""
import glob
import json
import os
import time

import numpy as np
import pytest

import jax

from lightgbm_tpu import telemetry
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel import create_parallel_learner


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _data(n=640, f=6, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.7 * x[:, 1] + 0.3 * rng.randn(n)) > 0).astype(
        np.float32)
    return Dataset.from_arrays(x, y, max_bin=16)


def _train(ds, learner_kind, *, schedule="psum", grow_policy="leafwise",
           hist_dtype="int8", iters=2, chunk=False):
    cfg = OverallConfig()
    params = {"objective": "binary", "num_leaves": "8",
              "min_data_in_leaf": "4", "min_sum_hessian_in_leaf": "0.1",
              "learning_rate": "0.1", "grow_policy": grow_policy,
              "hist_dtype": hist_dtype, "dp_schedule": schedule,
              "num_machines": "8"}
    if learner_kind != "serial":
        params["tree_learner"] = learner_kind
    cfg.set(params, require_data=False)
    booster = GBDT()
    learner = (create_parallel_learner(cfg)
               if learner_kind != "serial" else None)
    booster.init(cfg.boosting_config, ds,
                 create_objective(cfg.objective_type, cfg.objective_config),
                 learner=learner)
    if chunk:
        booster.train_chunk(iters)
    else:
        booster.run_training(iters, is_eval=False)
    return np.asarray(booster.score)


# ------------------------------------------------------ wire-metrics sites

def test_dp_reduce_scatter_records_collective_sites(tmp_path):
    telemetry.enable(str(tmp_path / "m.jsonl"))
    telemetry.reset()
    # unique shapes so the programs re-trace under this registry
    _train(_data(648, 7, seed=3), "data", schedule="reduce_scatter")
    sites = telemetry.collectives()
    scatter = [s for s in sites if "hist_scatter" in s]
    allred = [s for s in sites if "splitinfo_allreduce" in s]
    assert scatter and allred, sites
    for name in scatter + allred:
        rec = sites[name]
        assert rec["bytes_per_call"] > 0
        assert rec["traced_calls"] >= 1
        assert rec["axis"] == "data"
    snap = telemetry.snapshot()
    ic = snap["interconnect"]
    assert set(ic["sites"]) == set(sites)
    # per-split seams carry the fori_loop executed-calls estimate
    assert ic["sites"][scatter[0]]["est_calls"] >= 7  # num_leaves - 1
    assert "grow" in ic["phases"]
    assert ic["phases"]["grow"]["est_bytes"] > 0


def test_fp_records_splitinfo_allreduce(tmp_path):
    telemetry.enable(str(tmp_path / "m.jsonl"))
    telemetry.reset()
    _train(_data(656, 9, seed=4), "feature", grow_policy="depthwise",
           chunk=True)
    sites = telemetry.collectives()
    assert any("fp/splitinfo_allreduce" in s for s in sites), sites
    rec = sites["fp/splitinfo_allreduce"]
    assert rec["axis"] == "feature" and rec["bytes_per_call"] > 0


def test_interconnect_rides_summary_record(tmp_path):
    path = str(tmp_path / "m.jsonl")
    telemetry.enable(path)
    telemetry.reset()
    _train(_data(664, 6, seed=5), "data", schedule="reduce_scatter")
    telemetry.emit_summary()
    telemetry.disable()
    recs = [json.loads(line) for line in open(path)]
    summary = [r for r in recs if r.get("summary")]
    assert summary and "interconnect" in summary[-1]
    assert summary[-1]["interconnect"]["sites"]


def test_collective_span_passes_wrapped_fn_through():
    telemetry.enable()
    f = telemetry.collective_span("a/x", lambda v: v, kind="psum")
    g = telemetry.collective_span("b/x", f, kind="pmax")
    assert g is f and f._tl_collective_site == "a/x"
    assert telemetry.collective_span("c/x", None, kind="psum") is None


# ------------------------------------------------------- on/off bit-identity

@pytest.mark.parametrize("learner_kind,kwargs", [
    ("serial", dict()),
    ("data", dict(schedule="reduce_scatter")),
    ("feature", dict(grow_policy="depthwise", chunk=True)),
])
def test_scores_bit_identical_with_distributed_telemetry(tmp_path,
                                                         learner_kind,
                                                         kwargs):
    """The ISSUE 5 acceptance invariant: serial, DP reduce_scatter and FP
    scores are bit-identical with the full distributed layer (timeline
    shards + collective sites + watchdog) on vs off."""
    ds = _data(672, 6, seed=6)
    off = _train(ds, learner_kind, **kwargs)
    telemetry.enable(str(tmp_path / "m.jsonl"), timeline=True)
    telemetry.reset()
    telemetry.configure_watchdog(3600.0)
    on = _train(ds, learner_kind, **kwargs)
    telemetry.disable()
    np.testing.assert_array_equal(off, on)


# ------------------------------------------------- shard sinks / timestamps

def test_timeline_writes_shard_with_header_and_t(tmp_path):
    base = str(tmp_path / "run.jsonl")
    telemetry.set_clock_offset(1.25, rtt_s=0.002)
    telemetry.enable(base, timeline=True)
    telemetry.reset()
    _train(_data(680, 6, seed=7), "data", schedule="reduce_scatter")
    telemetry.emit_summary()
    telemetry.disable()
    shard = telemetry.shard_path(base, 0, 1)
    assert os.path.exists(shard) and not os.path.exists(base)
    recs = [json.loads(line) for line in open(shard)]
    header = recs[0]["shard"]
    assert header["process_index"] == 0 and header["process_count"] == 1
    assert header["clock_offset_s"] == 1.25
    assert header["clock_rtt_s"] == 0.002
    assert "host" in header and "pid" in header
    iters = [r for r in recs if "iter" in r]
    assert iters and all("t" in r for r in iters)
    # stamps are monotonic within one shard
    ts = [r["t"] for r in recs if "t" in r]
    assert ts == sorted(ts)


def test_shard_identity_override(tmp_path):
    base = str(tmp_path / "sim.jsonl")
    for idx in range(2):
        telemetry.set_shard_identity(idx, 2)
        telemetry.enable(base, timeline=True)
        telemetry.reset()
        telemetry.emit_iteration(1, {"histogram": 0.1})
        telemetry.disable()
    shards = sorted(glob.glob(base + ".shard-*"))
    assert len(shards) == 2
    idxs = [json.loads(open(s).readline())["shard"]["process_index"]
            for s in shards]
    assert idxs == [0, 1]


def test_dryrun_style_shard_merge_end_to_end(tmp_path):
    """The acceptance pipeline: two dryrun_multichip-style DP trainings,
    each writing its own shard (simulated host identities — the real
    shard writer and header), merged by scripts/timeline_report.py into
    ONE ordered timeline with a per-phase skew table."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "timeline_report",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "timeline_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)

    base = str(tmp_path / "job.jsonl")
    ds = _data(696, 6, seed=9)
    for idx in range(2):
        telemetry.set_shard_identity(idx, 2)
        telemetry.enable(base, timeline=True)
        telemetry.reset()
        _train(ds, "data", schedule="reduce_scatter", iters=3)
        telemetry.emit_summary()
        telemetry.disable()
    shard_files = sorted(glob.glob(base + ".shard-*"))
    assert len(shard_files) == 2
    shards = [tr.load_shard(p) for p in shard_files]
    events = tr.merge_timeline(shards)
    iter_events = [e for e in events if "iter" in e]
    assert len(iter_events) == 6           # 3 iterations x 2 shards
    stamps = [e["_t"] for e in iter_events]
    assert stamps == sorted(stamps)        # ordered on the merged clock
    assert {e["_host"] for e in iter_events} == {"p0", "p1"} or all(
        "@" in e["_host"] for e in iter_events)
    skew = tr.skew_report(shards)
    assert skew["iterations_compared"] == 3
    assert skew["phases"], "per-phase skew table is empty"
    assert skew["max_phase_skew"] >= 1.0


def test_non_timeline_sink_unchanged(tmp_path):
    """Leader-only single-file behavior is untouched without timeline."""
    path = str(tmp_path / "plain.jsonl")
    telemetry.enable(path)
    telemetry.reset()
    telemetry.emit_iteration(1, {"histogram": 0.1})
    telemetry.disable()
    assert os.path.exists(path)
    rec = json.loads(open(path).readline())
    assert "iter" in rec and "t" not in rec and "shard" not in rec


# ------------------------------------------------------ flight recorder

def test_injected_stall_dumps_flight_record(tmp_path):
    """A stalled run produces a flight-recorder dump naming the in-flight
    phase/iteration/collective — via a FAKE clock, no real waiting."""
    base = str(tmp_path / "stall.jsonl")
    telemetry.enable(base, timeline=True)
    telemetry.reset()
    clk = [0.0]
    assert telemetry.arm_watchdog(timeout_s=60.0, clock=lambda: clk[0],
                                  poll_s=0.005)
    with telemetry.span("grow"):
        pass
    telemetry.record_collective("dp_rs/leafwise/hist_scatter",
                                "psum_scatter", "data", 8192, loop=7,
                                phase="grow")
    telemetry.watchdog_checkin(phase="grow", iteration=5)
    clk[0] = 61.0   # the "hang": no further events
    deadline = time.time() + 5.0
    while telemetry.last_flight_record() is None \
            and time.time() < deadline:
        time.sleep(0.01)
    dump = telemetry.last_flight_record()
    assert dump is not None, "watchdog never fired"
    fr = dump["flight_recorder"]
    assert fr["phase"] == "grow"
    assert fr["iteration"] == 5
    assert fr["last_collective"] == "dp_rs/leafwise/hist_scatter"
    assert fr["stall_timeout_s"] == 60.0
    assert any(e["kind"] == "collective" for e in fr["ring"])
    assert "MainThread" in fr["threads"]
    telemetry.disarm_watchdog()
    # the dump reached the shard sink as a parseable record
    telemetry.disable()
    recs = [json.loads(line) for line in
            open(telemetry.shard_path(base, 0, 1))]
    assert any("flight_recorder" in r for r in recs)


def test_watchdog_quiet_run_never_fires(tmp_path):
    clk = [0.0]
    telemetry.enable()
    assert telemetry.arm_watchdog(timeout_s=60.0, clock=lambda: clk[0],
                                  poll_s=0.005)
    for i in range(20):
        clk[0] += 30.0              # progress beats the timeout
        telemetry.watchdog_checkin(iteration=i)
        time.sleep(0.002)
    assert telemetry.last_flight_record() is None
    telemetry.disarm_watchdog()
    assert not telemetry.watchdog_active()


def test_run_training_arms_and_disarms_watchdog(tmp_path):
    """gbdt.run_training arms the configured watchdog around training and
    always disarms it — no thread survives (conftest leak guard)."""
    telemetry.enable(str(tmp_path / "m.jsonl"))
    telemetry.reset()
    telemetry.configure_watchdog(3600.0)
    seen = []
    orig = telemetry.arm_watchdog

    def spy(*a, **k):
        out = orig(*a, **k)
        seen.append(out)
        return out

    telemetry.arm_watchdog = spy
    try:
        _train(_data(688, 6, seed=8), "serial")
    finally:
        telemetry.arm_watchdog = orig
    assert seen == [True]
    assert not telemetry.watchdog_active()


def test_watchdog_not_armed_without_config(tmp_path):
    telemetry.enable(str(tmp_path / "m.jsonl"))
    telemetry.reset()
    assert telemetry.watchdog_configured() == 0.0
    assert telemetry.arm_watchdog() is False


# --------------------------------------------------------------- config/cli

def test_config_options_parse():
    cfg = OverallConfig()
    cfg.set({"stall_timeout": "45.5", "timeline": "true",
             "metrics_out": "/tmp/x.jsonl"}, require_data=False)
    assert cfg.io_config.stall_timeout == 45.5
    assert cfg.io_config.timeline == "true"
    assert cfg.io_config.timeline_enabled()
    cfg2 = OverallConfig()
    cfg2.set({"metrics_out": "/tmp/x.jsonl"}, require_data=False)
    # auto: single-process runs keep the leader-only sink
    assert cfg2.io_config.timeline == "auto"
    assert not cfg2.io_config.timeline_enabled()
    cfg3 = OverallConfig()
    cfg3.set({}, require_data=False)
    assert cfg3.io_config.stall_timeout == 0.0


def test_config_rejects_bad_values():
    from lightgbm_tpu.utils import log
    with pytest.raises(log.LightGBMError):
        OverallConfig().set({"timeline": "yes"}, require_data=False)
    with pytest.raises(log.LightGBMError):
        OverallConfig().set({"stall_timeout": "-1"}, require_data=False)


def test_clock_handshake_single_process():
    from lightgbm_tpu.parallel.mesh import clock_handshake
    telemetry.set_clock_offset(99.0)
    assert clock_handshake() == 0.0
    assert telemetry.clock_offset() == 0.0
