"""Elastic training (ISSUE 14): the shared straggler logic, the elastic
mesh collectives, topology-elastic checkpoint restore, the live
drain-at-boundary mesh shrink, and the fault-injection hatch."""
import importlib.util
import json
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from lightgbm_tpu import checkpoint as ckpt
from lightgbm_tpu import elastic, faults, telemetry
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel import create_parallel_learner
from lightgbm_tpu.utils import log

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------ shared straggler logic

def test_straggler_tracker_run_length_and_ties():
    t = elastic.StragglerTracker(3)
    assert t.update(1, "p1") is None
    assert t.update(2, "p1") is None
    assert t.update(3, "p1") == "p1"        # 3 consecutive -> flagged
    t2 = elastic.StragglerTracker(3)
    t2.update(1, "p1")
    t2.update(2, None)                      # a tie resets the run
    t2.update(3, "p1")
    assert t2.update(4, "p1") is None
    assert t2.flagged is None


def test_straggler_tracker_gap_resets():
    t = elastic.StragglerTracker(2)
    t.update(1, "p0")
    assert t.update(3, "p0") is None        # iteration gap: no bridge
    assert t.update(4, "p0") == "p0"


def test_slowest_unique_semantics():
    assert elastic.slowest_unique({"a": 1.0, "b": 2.0}) == "b"
    assert elastic.slowest_unique({"a": 2.0, "b": 2.0}) is None
    assert elastic.slowest_unique({"a": 0.0, "b": 0.0}) is None
    assert elastic.slowest_unique({}) is None


def test_monitor_flags_on_chunk_boundaries():
    """The live monitor is fed once per iteration BOUNDARY — once per
    CHUNK on the fused path, where raw iteration numbers jump by
    chunk_size.  Consecutive OBSERVATIONS must count (the monitor feeds
    the tracker its own counter); raw-iteration gap-reset semantics stay
    in skew_from_rows for the post-mortem rows."""
    mon = elastic.StragglerMonitor(k=3)
    for it in (8, 16, 24):                  # chunk_size=8 boundaries
        mon.observe(it, {"p0": 1.0, "p1": 9.0})
    assert mon.take_flagged() == "p1"


def test_monitor_take_flagged_consumes_and_resets():
    mon = elastic.StragglerMonitor(k=2)
    mon.observe(1, {"p0": 1.0, "p1": 3.0})
    assert mon.take_flagged() is None
    mon.observe(2, {"p0": 1.0, "p1": 3.0})
    assert mon.take_flagged() == "p1"
    # consumed: the run-length state reset for the new topology
    assert mon.take_flagged() is None
    mon.observe(3, {"p0": 1.0, "p1": 3.0})
    assert mon.take_flagged() is None       # needs k fresh iterations


def test_skew_from_rows_is_the_script_implementation(tmp_path):
    """timeline_report.skew_report delegates to elastic.skew_from_rows:
    identical rows produce the identical verdict through both entries."""
    spec = importlib.util.spec_from_file_location(
        "timeline_report",
        os.path.join(REPO, "scripts", "timeline_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)

    rows = {it: {"p0": {"histogram": 0.1, "eval": 0.02},
                 "p1": {"histogram": 0.5, "eval": 0.02}}
            for it in range(1, 5)}
    direct = elastic.skew_from_rows(rows, straggler_k=3)
    assert direct["persistent_straggler"] == "p1"
    assert direct["iterations_compared"] == 4
    assert direct["phases"]["histogram"]["max_skew"] == pytest.approx(
        0.5 / 0.3, abs=1e-3)

    shards = []
    for idx, host in enumerate(("p0", "p1")):
        path = str(tmp_path / ("s%d.jsonl" % idx))
        with open(path, "w") as f:
            f.write(json.dumps({"shard": {"process_index": idx,
                                          "process_count": 2,
                                          "clock_offset_s": 0.0,
                                          "host": "vm"}}) + "\n")
            for it in range(1, 5):
                f.write(json.dumps({
                    "iter": it, "t": float(it),
                    "phase_times": rows[it][host]}) + "\n")
        shards.append(tr.load_shard(path))
    via_script = tr.skew_report(shards, straggler_k=3)
    assert via_script["persistent_straggler"] == "p1@vm"
    assert via_script["phases"]["histogram"]["max_skew"] == \
        direct["phases"]["histogram"]["max_skew"]
    assert via_script["barrier_wait_s"]["p0@vm"] == \
        direct["barrier_wait_s"]["p0"]


# ------------------------------------------------------ mesh collectives

def test_exchange_times_and_survivor_vote_sites():
    import jax
    from jax.sharding import Mesh
    from lightgbm_tpu.parallel.mesh import DATA_AXIS
    mesh = Mesh(np.array(jax.devices()[:2]), (DATA_AXIS,))
    telemetry.enable()
    telemetry.reset()
    try:
        gathered = elastic.exchange_times(mesh, 0.25)
        assert gathered.shape == (2,)
        np.testing.assert_allclose(gathered, 0.25)
        agreed = elastic.agree_survivors(mesh, np.array([1, 0, 1, 1]))
        np.testing.assert_array_equal(agreed, [1, 0, 1, 1])
        sites = telemetry.collectives()
        assert "elastic/times_allgather" in sites
        assert sites["elastic/times_allgather"]["kind"] == "all_gather"
        assert "elastic/survivor_pmin" in sites
        assert sites["elastic/survivor_pmin"]["kind"] == "pmin"
    finally:
        telemetry.disable()
        telemetry.reset()


def test_host_times_from_gather_labels():
    out = elastic.host_times_from_gather(
        np.array([1.0, 1.0, 5.0, 5.0], np.float32), slots_per_host=2)
    assert out == {"p0": 1.0, "p1": 5.0}


# ---------------------------------------------- elastic restart / shrink

@pytest.fixture()
def data():
    rng = np.random.RandomState(7)
    x = rng.randn(1600, 10)
    y = (x[:, 0] - x[:, 1] + 0.4 * rng.randn(1600) > 0).astype(np.float32)
    return x, y


def _make(x, y, num_machines, extra=None):
    params = {"objective": "binary", "num_leaves": "8",
              "min_data_in_leaf": "5", "min_sum_hessian_in_leaf": "0.1",
              "learning_rate": "0.1", "verbose": "-1",
              "grow_policy": "leafwise", "hist_dtype": "int8"}
    if extra:
        params.update(extra)
    if num_machines > 1:
        params.update({"tree_learner": "data",
                       "num_machines": str(num_machines)})
    cfg = OverallConfig()
    cfg.set(params, require_data=False)
    ds = Dataset.from_arrays(x, y, max_bin=63)
    b = GBDT()
    learner = create_parallel_learner(cfg) if num_machines > 1 else None
    b.init(cfg.boosting_config, ds,
           create_objective(cfg.objective_type, cfg.objective_config),
           learner=learner)
    return b, cfg


def test_elastic_restore_different_topology_int8_bit_exact(data):
    """Checkpoint on 4 machines, restore on 2: int8's ownership schedule
    is topology-invariant, so the continuation is BIT-exact vs an
    uninterrupted 2-machine run — the budget class asserted, not
    hoped."""
    x, y = data
    a, _ = _make(x, y, 2)
    a.run_training(8, is_eval=False)
    ref = [t.to_string() for t in a.models]

    b, _ = _make(x, y, 4)
    b.run_training(4, is_eval=False)
    payload = json.loads(json.dumps(
        ckpt.serialize_state(b.checkpoint_state())))
    c, _ = _make(x, y, 2)
    c.restore_checkpoint(payload)
    c.run_training(4, is_eval=False)
    assert [t.to_string() for t in c.models] == ref
    np.testing.assert_array_equal(np.asarray(c.score), np.asarray(a.score))


def test_elastic_restore_different_topology_f32_budget(data):
    """f32 across topologies: exact structure, leaf values within the
    documented cross-schedule budget (the psum grouping differs)."""
    x, y = data
    a, _ = _make(x, y, 2, {"hist_dtype": "float32"})
    a.run_training(8, is_eval=False)

    b, _ = _make(x, y, 4, {"hist_dtype": "float32"})
    b.run_training(4, is_eval=False)
    payload = ckpt.serialize_state(b.checkpoint_state())
    c, _ = _make(x, y, 2, {"hist_dtype": "float32"})
    c.restore_checkpoint(payload)
    c.run_training(4, is_eval=False)
    assert len(c.models) == len(a.models) == 8
    for t1, t2 in zip(a.models, c.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(a.score), np.asarray(c.score),
                               rtol=1e-3, atol=1e-4)


def test_live_mesh_shrink_drain_at_boundary(data):
    """The live policy: injected observations flag slot 3 as a
    persistent straggler; the trainer checkpoints at the boundary,
    re-factors 4 -> 3 machines mid-run, and the final model is bit-exact
    (int8) vs training on 3 machines from the start."""
    x, y = data
    ref, _ = _make(x, y, 3)
    ref.run_training(8, is_eval=False)
    ref_trees = [t.to_string() for t in ref.models]

    b, cfg = _make(x, y, 4)

    def factory(num_machines, _cfg=cfg):
        _cfg.network_config.num_machines = int(num_machines)
        return create_parallel_learner(_cfg)

    mon = b.enable_elastic(factory, exchange=False)
    fed = {"n": 0}
    orig_step = b._elastic_step

    def feed_then_step():
        # harness-injected observations (a real multi-process run feeds
        # these from exchange_times): slot 3 strictly slowest until the
        # shrink consumes the flag
        if b._learner.config.network_config.num_machines == 4:
            fed["n"] += 1
            mon.observe(fed["n"], {"p0": 1.0, "p1": 1.0, "p2": 1.0,
                                   "p3": 5.0})
        return orig_step()

    b._elastic_step = feed_then_step
    b.run_training(8, is_eval=False)
    assert b._learner.config.network_config.num_machines == 3
    assert len(b.models) == 8
    assert [t.to_string() for t in b.models] == ref_trees


def test_shrink_at_min_mesh_warns_and_disarms(data):
    x, y = data
    b, cfg = _make(x, y, 2)

    def factory(num_machines, _cfg=cfg):
        _cfg.network_config.num_machines = int(num_machines)
        return create_parallel_learner(_cfg)

    mon = b.enable_elastic(factory, exchange=False)
    # first shrink 2 -> 1 is refused? no: cur=2 > 1, shrinks to 1; the
    # NEXT flag on the 1-machine mesh must warn-and-disarm, never loop
    b._elastic_shrink("p1")
    assert b._learner.config.network_config.num_machines == 1
    b._straggler_monitor = mon
    assert b._elastic_shrink("p0") is False
    assert b._straggler_monitor is None


# -------------------------------------------------------- fault injection

def test_fault_parse_spec():
    assert faults.parse_spec("7") == (7, "kill")
    assert faults.parse_spec("3,stall") == (3, "stall")
    with pytest.raises(log.LightGBMError, match="kind"):
        faults.parse_spec("3,explode")
    with pytest.raises(log.LightGBMError, match="int"):
        faults.parse_spec("soon")


def test_fault_stall_and_raise(data, monkeypatch):
    x, y = data
    monkeypatch.setenv(faults.ENV_STALL_S, "0.01")
    faults.arm(2, "stall")
    try:
        b, _ = _make(x, y, 1)
        b.run_training(4, is_eval=False)
        assert len(b.models) == 4          # stall delays, never corrupts
        assert faults._fired
    finally:
        faults.disarm()
    faults.arm(2, "raise")
    try:
        c, _ = _make(x, y, 1)
        with pytest.raises(RuntimeError, match="injected fault"):
            c.run_training(4, is_eval=False)
        # fired at the boundary after 2 consumed iterations; the
        # crash-flush best-effort consumes a pipelined in-flight entry,
        # so 2 (synchronous) or 3 (pipelined) trees survive — never 4
        assert 2 <= len(c.models) <= 3
    finally:
        faults.disarm()
    assert not faults.armed()


def test_fault_kill_env_sigkills_training(tmp_path):
    """The env hatch SIGKILLs a real training process between
    iterations — and the checkpoints written before the kill survive."""
    script = textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import numpy as np
        from lightgbm_tpu.config import OverallConfig
        from lightgbm_tpu.io.dataset import Dataset
        from lightgbm_tpu.models.gbdt import GBDT
        from lightgbm_tpu.objectives import create_objective
        rng = np.random.RandomState(0)
        x = rng.randn(600, 6)
        y = (x[:, 0] > 0).astype(np.float32)
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "4",
                 "min_data_in_leaf": "4", "min_sum_hessian_in_leaf": "0.1",
                 "learning_rate": "0.1", "verbose": "-1",
                 "checkpoint_interval": "1",
                 "checkpoint_dir": %r}, require_data=False)
        ds = Dataset.from_arrays(x, y, max_bin=16)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config))
        b.run_training(8, is_eval=False)
        print("NOT_KILLED")
    """ % str(tmp_path / "ck"))
    env = dict(os.environ)
    env["LGBM_TPU_FAULT_AT"] = "3,kill"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == -signal.SIGKILL, (res.returncode, res.stderr)
    assert "NOT_KILLED" not in res.stdout
    latest = ckpt.latest_checkpoint(str(tmp_path / "ck"))
    assert latest is not None
    payload = ckpt.load_checkpoint(latest)
    assert payload["iteration"] >= 1
