"""BinMapper unit tests against hand-computed values (SURVEY §4 test plan b)."""
import numpy as np
import pytest

from lightgbm_tpu.io.binning import BinMapper


def test_distinct_values_path():
    # num distinct <= max_bin: boundaries are midpoints, last +inf
    values = np.array([1.0, 1.0, 2.0, 3.0, 3.0, 3.0])
    m = BinMapper()
    m.find_bin(values, max_bin=8)
    assert m.num_bin == 3
    np.testing.assert_allclose(m.bin_upper_bound[:2], [1.5, 2.5])
    assert np.isinf(m.bin_upper_bound[2])
    assert not m.is_trivial
    # sparse rate = share of bin 0 (value 1.0 appears twice in 6 samples)
    assert m.sparse_rate == pytest.approx(2 / 6)


def test_value_to_bin_boundaries():
    m = BinMapper()
    m.find_bin(np.array([0.0, 1.0, 2.0]), max_bin=8)
    # boundaries [0.5, 1.5, inf]; value <= upper → that bin
    assert m.value_to_bin(0.0) == 0
    assert m.value_to_bin(0.5) == 0
    assert m.value_to_bin(0.50001) == 1
    assert m.value_to_bin(1.5) == 1
    assert m.value_to_bin(100.0) == 2
    np.testing.assert_array_equal(
        m.value_to_bin(np.array([0.0, 0.6, 3.0])), [0, 1, 2])


def test_trivial_feature():
    m = BinMapper()
    m.find_bin(np.full(100, 3.14), max_bin=8)
    assert m.num_bin == 1
    assert m.is_trivial


def test_hybrid_path_dedicated_bins():
    # one dominant value gets a dedicated bin when count > mean_bin_size
    values = np.concatenate([np.zeros(900), np.arange(1, 101)])
    m = BinMapper()
    m.find_bin(values, max_bin=10)
    assert m.num_bin <= 10
    assert m.num_bin > 1
    # zero must map to its own dedicated bin: nothing else shares it
    zero_bin = int(m.value_to_bin(0.0))
    others = m.value_to_bin(np.arange(1, 101).astype(float))
    assert not np.any(others == zero_bin)


def test_bins_are_monotonic():
    rng = np.random.RandomState(3)
    values = rng.randn(5000)
    m = BinMapper()
    m.find_bin(values, max_bin=32)
    bounds = m.bin_upper_bound
    assert np.all(np.diff(bounds[:-1]) > 0)
    # every value maps into [0, num_bin)
    bins = m.value_to_bin(values)
    assert bins.min() >= 0 and bins.max() < m.num_bin


def test_roundtrip_serialization():
    m = BinMapper()
    m.find_bin(np.random.RandomState(0).randn(1000), max_bin=16)
    m2 = BinMapper.from_bytes(m.to_bytes())
    assert m2.num_bin == m.num_bin
    assert m2.is_trivial == m.is_trivial
    np.testing.assert_allclose(m2.bin_upper_bound, m.bin_upper_bound)


def _reference_find_bin_bounds(values, max_bin, tie_perm=None):
    """Literal re-implementation of BinMapper::FindBin
    (/root/reference/src/io/bin.cpp:42-132) used as a test oracle, with
    one twist: ``tie_perm`` (a numpy RandomState) permutes equal-count
    groups after the count sort, simulating the reference's UNSTABLE
    std::sort in Common::SortForPair (common.h:362-381) under an
    adversarial implementation.  Returns the bin_upper_bound array."""
    values = np.asarray(values, dtype=np.float64)
    sample_size = values.size
    distinct_values, counts = np.unique(values, return_counts=True)
    distinct_values = list(distinct_values)
    counts = [int(c) for c in counts]
    num_values = len(distinct_values)
    assert num_values > max_bin, "oracle exercises the hybrid path only"

    mean_bin_size = sample_size / float(max_bin)
    rest_sample_cnt = sample_size
    bin_cnt = 0
    upper_bounds = [np.inf] * max_bin
    lower_bounds = [np.inf] * max_bin
    order = sorted(range(num_values), key=lambda i: -counts[i])
    if tie_perm is not None:
        # shuffle within equal-count runs: any such order is a legal
        # std::sort outcome
        i = 0
        while i < len(order):
            j = i
            while (j < len(order)
                   and counts[order[j]] == counts[order[i]]):
                j += 1
            seg = order[i:j]
            tie_perm.shuffle(seg)
            order[i:j] = seg
            i = j
    counts = [counts[i] for i in order]
    distinct_values = [distinct_values[i] for i in order]
    while bin_cnt < num_values and counts[bin_cnt] > mean_bin_size:
        upper_bounds[bin_cnt] = distinct_values[bin_cnt]
        lower_bounds[bin_cnt] = distinct_values[bin_cnt]
        rest_sample_cnt -= counts[bin_cnt]
        bin_cnt += 1
    if bin_cnt < max_bin:
        rest = sorted(range(bin_cnt, num_values),
                      key=lambda i: distinct_values[i])
        distinct_values[bin_cnt:] = [distinct_values[i] for i in rest]
        counts[bin_cnt:] = [counts[i] for i in rest]
        mean_bin_size = rest_sample_cnt / float(max_bin - bin_cnt)
        lower_bounds[bin_cnt] = distinct_values[bin_cnt]
        cur_cnt_inbin = 0
        for i in range(bin_cnt, num_values - 1):
            rest_sample_cnt -= counts[i]
            cur_cnt_inbin += counts[i]
            if cur_cnt_inbin >= mean_bin_size:
                upper_bounds[bin_cnt] = distinct_values[i]
                bin_cnt += 1
                lower_bounds[bin_cnt] = distinct_values[i + 1]
                if bin_cnt >= max_bin - 1:
                    break
                cur_cnt_inbin = 0
                mean_bin_size = rest_sample_cnt / float(max_bin - bin_cnt)
    order2 = sorted(range(max_bin), key=lambda i: lower_bounds[i])
    lower_bounds = [lower_bounds[i] for i in order2]
    upper_bounds = [upper_bounds[i] for i in order2]
    bounds = np.empty(bin_cnt, dtype=np.float64)
    for i in range(bin_cnt - 1):
        bounds[i] = (upper_bounds[i] + lower_bounds[i + 1]) / 2.0
    bounds[bin_cnt - 1] = np.inf
    return bounds


def _adversarial_tie_values():
    """Counts engineered to tie exactly AT and ABOVE the mean_bin_size
    boundary (VERDICT r2 weak #6): sample_size=1000, max_bin=10 →
    mean_bin_size=100.  Three values at count 150 (dedicated: > mean),
    four at exactly 100 (NOT dedicated: the reference's `>` is strict),
    thirty at count 5 filling the remainder."""
    vals = []
    for v, c in [(7.0, 150), (-3.0, 150), (11.0, 150),
                 (1.0, 100), (2.0, 100), (4.0, 100), (5.5, 100)]:
        vals += [v] * c
    for k in range(30):
        vals += [20.0 + 0.25 * k] * 5
    values = np.asarray(vals)
    assert values.size == 1000
    return values


def test_adversarial_count_ties_match_reference_oracle():
    """Bin bounds must be INVARIANT to the order of equal-count values —
    the property that makes our stable sort equivalent to the reference's
    unstable SortForPair (dedicated-bin membership is decided by a strict
    threshold over contiguous tie runs, and both the remainder and the
    final bins are re-sorted by value).  Checked against the bin.cpp
    oracle under 64 adversarial tie permutations."""
    values = _adversarial_tie_values()
    max_bin = 10
    m = BinMapper()
    m.find_bin(values, max_bin)
    ours = np.asarray(m.bin_upper_bound)

    base = _reference_find_bin_bounds(values, max_bin)
    np.testing.assert_array_equal(ours, base)
    rng = np.random.RandomState(0)
    for _ in range(64):
        permuted = _reference_find_bin_bounds(values, max_bin,
                                              tie_perm=rng)
        np.testing.assert_array_equal(base, permuted)
