"""BinMapper unit tests against hand-computed values (SURVEY §4 test plan b)."""
import numpy as np
import pytest

from lightgbm_tpu.io.binning import BinMapper


def test_distinct_values_path():
    # num distinct <= max_bin: boundaries are midpoints, last +inf
    values = np.array([1.0, 1.0, 2.0, 3.0, 3.0, 3.0])
    m = BinMapper()
    m.find_bin(values, max_bin=8)
    assert m.num_bin == 3
    np.testing.assert_allclose(m.bin_upper_bound[:2], [1.5, 2.5])
    assert np.isinf(m.bin_upper_bound[2])
    assert not m.is_trivial
    # sparse rate = share of bin 0 (value 1.0 appears twice in 6 samples)
    assert m.sparse_rate == pytest.approx(2 / 6)


def test_value_to_bin_boundaries():
    m = BinMapper()
    m.find_bin(np.array([0.0, 1.0, 2.0]), max_bin=8)
    # boundaries [0.5, 1.5, inf]; value <= upper → that bin
    assert m.value_to_bin(0.0) == 0
    assert m.value_to_bin(0.5) == 0
    assert m.value_to_bin(0.50001) == 1
    assert m.value_to_bin(1.5) == 1
    assert m.value_to_bin(100.0) == 2
    np.testing.assert_array_equal(
        m.value_to_bin(np.array([0.0, 0.6, 3.0])), [0, 1, 2])


def test_trivial_feature():
    m = BinMapper()
    m.find_bin(np.full(100, 3.14), max_bin=8)
    assert m.num_bin == 1
    assert m.is_trivial


def test_hybrid_path_dedicated_bins():
    # one dominant value gets a dedicated bin when count > mean_bin_size
    values = np.concatenate([np.zeros(900), np.arange(1, 101)])
    m = BinMapper()
    m.find_bin(values, max_bin=10)
    assert m.num_bin <= 10
    assert m.num_bin > 1
    # zero must map to its own dedicated bin: nothing else shares it
    zero_bin = int(m.value_to_bin(0.0))
    others = m.value_to_bin(np.arange(1, 101).astype(float))
    assert not np.any(others == zero_bin)


def test_bins_are_monotonic():
    rng = np.random.RandomState(3)
    values = rng.randn(5000)
    m = BinMapper()
    m.find_bin(values, max_bin=32)
    bounds = m.bin_upper_bound
    assert np.all(np.diff(bounds[:-1]) > 0)
    # every value maps into [0, num_bin)
    bins = m.value_to_bin(values)
    assert bins.min() >= 0 and bins.max() < m.num_bin


def test_roundtrip_serialization():
    m = BinMapper()
    m.find_bin(np.random.RandomState(0).randn(1000), max_bin=16)
    m2 = BinMapper.from_bytes(m.to_bytes())
    assert m2.num_bin == m.num_bin
    assert m2.is_trivial == m.is_trivial
    np.testing.assert_allclose(m2.bin_upper_bound, m.bin_upper_bound)
