"""Pod-scope observability (ISSUE 17, lightgbm_tpu/podtrace.py +
scripts/pod_report.py + the multi-host half of scripts/trace_report.py).

Correctness bars, in the ISSUE's order:

(a) merge algebra: the merged timeline and the merged sketches are
    independent of the order dumps are passed in, conserve every event
    / every observation, and the sketch merge is associative;
(b) clock alignment: on a synthetically skewed host pair the estimated
    offset lands within the RECORDED collective-duration bound (the
    bound is part of the answer, checked against ground truth), and
    only pod-wide collectives qualify as sync points;
(c) tampering / bookkeeping: a per-host dump whose attribution identity
    was edited is caught by the pod check; mixed run ids are a loud
    BadDump in trace_report and a finding in podtrace; header identity
    drift (out-of-range process_index, inconsistent process_count,
    duplicate labels) is flagged;
(d) attribution rode along: the REAL streaming loader files pass/chunk
    ingest events whose tokenizer/bin/H2D percentages telescope to
    100%, and the serving front files queue-depth-at-enqueue plus
    per-bucket dispatch counters into the same ring;
(e) one rule: the post-mortem skew verdict over ring rows equals a live
    StragglerTracker fed the same totals;
(f) the file barrier's blocked windows honestly bound the participants'
    exit-stamp spread, and the seam roofline joins measured spans
    against the byte model (unmodeled seams flagged);
(g) perf_gate treats alignment/parity/check violations as ABSOLUTE
    findings and gates merge overhead must-not-grow; the config knob
    rejects junk loudly.
"""
import json
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import elastic, podtrace, telemetry, tracing
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.serving import ServingEngine, ServingFront
from lightgbm_tpu.utils.log import LightGBMError
from scripts import perf_gate, trace_report

BASE_T = 1_700_000_000.0  # synthetic wall-clock origin for sync stamps


@pytest.fixture()
def clean_tracing():
    """Recorder disarmed + identity cleared around each test."""
    tracing.disarm()
    tracing.set_identity(process_index=None, process_count=None,
                         run_id="")
    yield
    tracing.disarm()
    tracing.set_identity(process_index=None, process_count=None,
                         run_id="")


def _make_dump(tmp_path, name, index, fill, count=2, run_id="run-a"):
    """One REAL per-host dump: arm, set identity, run ``fill()``, dump,
    disarm, reload through podtrace.load_dump."""
    tracing.arm(ring_events=4096)
    tracing.set_identity(process_index=index, process_count=count,
                         run_id=run_id)
    fill()
    path = str(tmp_path / name)
    assert tracing.dump(path=path, reason="test") == path
    tracing.disarm()
    return podtrace.load_dump(path)


def _sync_fill(index, skew_s=0.0, iters=3, dur_s=0.010, jitter_s=0.001):
    """Pod-wide collectives at iters 1..n: every host exits the true
    collective at (nearly) the same true instant; a skewed host's clock
    reads truth + skew_s."""
    def fill():
        for k in range(1, iters + 1):
            t1 = BASE_T + k + skew_s + (jitter_s if index else 0.0)
            tracing.record_collective_sync("pod/barrier", k,
                                           t1 - dur_s, t1, pod=True)
            tracing.observe("train_iter_us", 1000.0 * (index + k))
            tracing.event("mark", host_tag=index, k=k)
    return fill


# ===================================== (a) merge algebra


def test_merge_timeline_order_independent_and_conserving(
        clean_tracing, tmp_path):
    dumps = [
        _make_dump(tmp_path, "d%d.jsonl" % i, i, _sync_fill(i), count=3)
        for i in range(3)]
    ref = podtrace.merge_timeline(dumps)
    for order in ((2, 0, 1), (1, 2, 0), (2, 1, 0)):
        again = podtrace.merge_timeline([dumps[i] for i in order])
        assert again == ref
    assert len(ref) == sum(len(d["events"]) for d in dumps)
    assert {e["_host"] for e in ref} == {"p0", "p1", "p2"}


def test_merge_sketches_order_independent_and_associative(
        clean_tracing, tmp_path):
    dumps = [
        _make_dump(tmp_path, "d%d.jsonl" % i, i, _sync_fill(i), count=3)
        for i in range(3)]
    ref = podtrace.merge_sketches(dumps)
    assert podtrace.merge_sketches(dumps[::-1]) == ref
    sks = [d["header"]["sketches"]["train_iter_us"] for d in dumps]
    left = podtrace.merge_sketch_dicts(
        podtrace.merge_sketch_dicts(sks[0], sks[1]), sks[2])
    right = podtrace.merge_sketch_dicts(
        sks[0], podtrace.merge_sketch_dicts(sks[1], sks[2]))
    assert left == right == ref["train_iter_us"]
    merged = tracing.LatencySketch.from_dict(ref["train_iter_us"])
    assert merged.count == sum(
        tracing.LatencySketch.from_dict(s).count for s in sks)


def test_merge_sketch_growth_mismatch_raises(clean_tracing):
    a = tracing.LatencySketch(growth=1.05)
    b = tracing.LatencySketch(growth=1.5)
    a.record(10.0)
    b.record(10.0)
    with pytest.raises(podtrace.PodTraceError):
        podtrace.merge_sketch_dicts(a.to_dict(), b.to_dict())


# ===================================== (b) clock alignment


def test_alignment_offset_within_recorded_bound(clean_tracing, tmp_path):
    """Ground truth: host p1's clock is 1.5s ahead.  The estimate must
    recover -1.5s to within the recorded collective-duration bound."""
    skew = 1.5
    d0 = _make_dump(tmp_path, "a.jsonl", 0, _sync_fill(0))
    d1 = _make_dump(tmp_path, "b.jsonl", 1, _sync_fill(1, skew_s=skew))
    al = podtrace.align([d0, d1])
    assert al["reference"] == "p0" and al["ok"], al
    off = al["offsets"]["p1"]
    assert off["consistent"] and off["sync_points"] == 3
    assert abs(off["offset_s"] - (-skew)) <= off["bound_s"] + 1e-9, off
    # merged timeline lands p1's marks back on the reference clock
    merged = podtrace.merge_timeline([d0, d1], al)
    assert len(merged) == len(d0["events"]) + len(d1["events"])


def test_process_local_collectives_are_not_sync_points(
        clean_tracing, tmp_path):
    def local_fill():
        for k in range(1, 4):
            t1 = BASE_T + k
            tracing.record_collective_sync("elastic/times_allgather", k,
                                           t1 - 0.01, t1, pod=False)
    d0 = _make_dump(tmp_path, "a.jsonl", 0, local_fill)
    d1 = _make_dump(tmp_path, "b.jsonl", 1, local_fill)
    al = podtrace.align([d0, d1])
    assert not al["ok"]
    assert al["offsets"]["p1"]["offset_s"] is None
    assert any("cannot be aligned" in f
               for f in podtrace.check([d0, d1], al))


# ===================================== (c) tampering / bookkeeping


def _serve_fill():
    comps = {"queue": 10, "linger": 5, "coalesce": 0, "dispatch": 7,
             "walk": 40, "scatter": 3}
    tracing.event("serve_complete", trace=1, wall_ns=sum(comps.values()),
                  components_ns=comps)


def test_tampered_attribution_caught_in_merge(clean_tracing, tmp_path):
    d0 = _make_dump(tmp_path, "a.jsonl",
                    0, lambda: (_sync_fill(0)(), _serve_fill()))
    d1 = _make_dump(tmp_path, "b.jsonl",
                    1, lambda: (_sync_fill(1)(), _serve_fill()))
    assert podtrace.check([d0, d1]) == []
    # tamper host p1's dump on disk: inflate one component
    lines = open(d1["path"]).read().splitlines()
    out = []
    for line in lines:
        rec = json.loads(line)
        if rec.get("kind") == "serve_complete":
            rec["components_ns"]["walk"] += 1
        out.append(json.dumps(rec))
    with open(d1["path"], "w") as f:
        f.write("\n".join(out) + "\n")
    bad = podtrace.check([d0, podtrace.load_dump(d1["path"])])
    assert any("attribution identity broken" in b for b in bad), bad


def test_run_mix_is_loud(clean_tracing, tmp_path):
    d0 = _make_dump(tmp_path, "a.jsonl", 0, _sync_fill(0),
                    run_id="run-a")
    d1 = _make_dump(tmp_path, "b.jsonl", 1, _sync_fill(1),
                    run_id="run-b")
    assert any("run" in f and "mix" in f
               for f in podtrace.check_headers([d0, d1]))
    loaded = [(d["path"], trace_report.load(d["path"])[0])
              for d in (d0, d1)]
    mix = trace_report.check_run_mix(loaded)
    assert mix and "run-a" in mix and "run-b" in mix


def test_header_identity_validation(clean_tracing, tmp_path):
    # out-of-range process_index caught by BOTH checkers
    d = _make_dump(tmp_path, "a.jsonl", 5, _sync_fill(0), count=2)
    header, events = trace_report.load(d["path"])
    assert any("process_index" in f
               for f in trace_report.check(d["path"], header, events))
    assert any("process_index" in f for f in podtrace.check_headers([d]))
    # duplicate labels (same identity twice) flagged
    d0 = _make_dump(tmp_path, "b.jsonl", 0, _sync_fill(0))
    d0b = _make_dump(tmp_path, "c.jsonl", 0, _sync_fill(0))
    assert any("label" in f or "duplicate" in f
               for f in podtrace.check_headers([d0, d0b]))


# ===================================== (d) ingest + serving attribution


def test_streaming_ingest_attribution_in_ring(clean_tracing, tmp_path):
    rng = np.random.RandomState(7)
    x = rng.randn(400, 5)
    y = (x[:, 0] > 0).astype(np.float64)
    csv = str(tmp_path / "ingest.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.6g", delimiter=",")

    def fill():
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "data": csv,
                 "streaming": "true"})
        Dataset.load_train(cfg.io_config)
    d = _make_dump(tmp_path, "d.jsonl", 0, fill, count=1)
    passes = [e for e in d["events"] if e["kind"] == "ingest_pass"]
    chunks = [e for e in d["events"] if e["kind"] == "ingest_chunk"]
    assert {int(e["pass"]) for e in passes} == {0, 1, 2}
    assert chunks and all(int(e["rows"]) > 0 for e in chunks)
    bd = podtrace.ingest_breakdown([d])["p0"]
    assert bd["rows"] == 400
    pcts = [v for v in bd["pcts"].values() if v is not None]
    assert pcts and abs(sum(pcts) - 100.0) < 0.5, bd["pcts"]


def test_serve_enqueue_depth_and_dispatch_counters(
        clean_tracing, tmp_path):
    rng = np.random.RandomState(3)
    x = rng.randn(256, 6)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    booster = lgb.train({"objective": "binary", "num_leaves": 8,
                         "min_data_in_leaf": 10,
                         "min_sum_hessian_in_leaf": 1.0,
                         "num_iterations": 2}, ds)

    def fill():
        front = ServingFront(ServingEngine(booster.export_flat()),
                             linger_us=1000)
        try:
            futs = [front.submit(x[i * 16:(i + 1) * 16])
                    for i in range(8)]
            for f in futs:
                f.result(30)
        finally:
            front.close()
    d = _make_dump(tmp_path, "d.jsonl", 0, fill, count=1)
    enq = [e for e in d["events"] if e["kind"] == "serve_enqueue"]
    assert len(enq) == 8
    assert all(isinstance(e.get("depth_rows"), int)
               and e["depth_rows"] >= 0 for e in enq)
    # the first request entered an empty queue
    assert min(e["depth_rows"] for e in enq) == 0
    counters = d["header"]["counters"]
    buckets = {k: v for k, v in counters.items()
               if k.startswith("serve/dispatch_bucket_")}
    assert buckets and sum(buckets.values()) >= 1, counters
    rows = sum(v for k, v in counters.items()
               if k.startswith("serve/dispatch_rows_bucket_"))
    assert rows == 8 * 16, counters


# ===================================== (e) one skew rule


def test_postmortem_skew_equals_live_tracker(clean_tracing, tmp_path):
    def iter_fill(index):
        def fill():
            for k in range(1, 5):
                pt = {ph: 0.010 * (1 + 2 * index)
                      for ph in elastic.CANONICAL_PHASES}
                tracing.record_train_iteration(k, pt)
        return fill
    dumps = [_make_dump(tmp_path, "d%d.jsonl" % i, i, iter_fill(i))
             for i in range(2)]
    rows = podtrace.skew_rows(dumps)
    post = elastic.skew_from_rows(rows, straggler_k=3)
    live = elastic.StragglerTracker(3)
    for k in sorted(rows):
        totals = {h: sum(pt.values()) for h, pt in rows[k].items()}
        live.update(k, elastic.slowest_unique(totals))
    assert live.flagged == "p1"
    assert post["persistent_straggler"] == live.flagged


# ===================================== (f) barrier + roofline


def test_file_barrier_bound_covers_exit_spread(tmp_path):
    res = {}

    def worker(i):
        res[i] = podtrace.file_barrier(str(tmp_path), "it", i, 2,
                                       payload={"v": i}, timeout=30.0)

    t = threading.Thread(target=worker, args=(1,))
    t.start()
    time.sleep(0.05)  # participant 0 arrives late: real exit skew
    worker(0)
    t.join(30)
    (p0, a0, b0), (p1, a1, b1) = res[0], res[1]
    assert p0 == p1 == {0: {"v": 0}, 1: {"v": 1}}
    assert abs(b0 - b1) <= max(b0 - a0, b1 - a1) + 1e-9
    with pytest.raises(TimeoutError):
        podtrace.file_barrier(str(tmp_path), "alone", 0, 2,
                              timeout=0.2)


def test_seam_roofline_joins_spans_and_flags_drift(
        clean_tracing, tmp_path):
    def fill():
        tracing.record_collective_sync("hist/psum", 1,
                                       BASE_T, BASE_T + 0.5, pod=True)
        tracing.record_collective_sync("hist/psum", 2,
                                       BASE_T + 1, BASE_T + 1.5,
                                       pod=True)
        tracing.record_collective_sync("orphan/seam", 1,
                                       BASE_T, BASE_T + 0.1, pod=False)
        tracing.event("wire_model", sites={
            "hist/psum": {"est_bytes": 2_000_000,
                          "bytes_per_call": 1_000_000, "est_calls": 2,
                          "kind": "psum"},
            "unmeasured/seam": {"est_bytes": 7}})
    d = _make_dump(tmp_path, "d.jsonl", 0, fill, count=1)
    roof = podtrace.seam_roofline(
        [d], peaks={"ici_bytes_per_sec": 8_000_000.0})
    row = roof["sites"]["hist/psum"]
    # 1 MB/call x 2 calls over 1.0s blocked -> 2 MB/s, 1/4 of the peak
    assert row["modeled"] and row["calls"] == 2
    assert abs(row["span_s"] - 1.0) < 1e-6
    assert abs(row["attained_gb_per_s"] - 0.002) < 1e-9
    assert abs(row["frac_of_ici_peak"] - 0.25) < 1e-9
    assert roof["unmodeled"] == ["orphan/seam"]
    # an unmeasured-but-modeled site stays in the table (coverage)
    assert roof["sites"]["unmeasured/seam"]["span_s"] is None
    # off-TPU: no peak -> fraction honestly None
    roof_cpu = podtrace.seam_roofline([d], peaks=None)
    assert roof_cpu["sites"]["hist/psum"]["frac_of_ici_peak"] is None


# ===================================== (g) gate lanes + knob


def _gate_entries(*pods):
    return [{"kind": "multichip", "round": r, "path": "m%d" % r,
             "rec": {"ok": True, "n_devices": 8, "podtrace": pt}}
            for r, pt in enumerate(pods, 1)]


def test_perf_gate_podtrace_absolute_findings():
    good = {"alignment_ok": True, "check_findings": 0, "unmodeled": 0,
            "parity": True, "merge_ms_per_kevent": 2.0}
    findings = []
    perf_gate._check_podtrace(_gate_entries(good), findings)
    assert findings == []
    for key, bad in (("alignment_ok", False), ("check_findings", 3),
                     ("unmodeled", 1), ("parity", False)):
        findings = []
        perf_gate._check_podtrace(
            _gate_entries(dict(good, **{key: bad})), findings)
        assert [f["key"] for f in findings] == ["podtrace/" + key]


def test_perf_gate_podtrace_merge_overhead_must_not_grow():
    good = {"alignment_ok": True, "check_findings": 0, "unmodeled": 0,
            "parity": True}
    hist = [dict(good, merge_ms_per_kevent=v) for v in (2.0, 2.2, 2.1)]
    findings = []
    perf_gate._check_podtrace(_gate_entries(*hist), findings)
    assert findings == []
    findings = []
    perf_gate._check_podtrace(
        _gate_entries(*hist, dict(good, merge_ms_per_kevent=40.0)),
        findings)
    assert [f["key"] for f in findings] == \
        ["podtrace/merge_ms_per_kevent"]


def test_perf_gate_parses_podtrace_from_tail():
    rec = {"ok": True, "n_devices": 8,
           "tail": "x\nMULTICHIP_PODTRACE " + json.dumps(
               {"alignment_ok": True, "parity": True}) + "\n"}
    perf_gate._attach_multichip_obs(rec)
    assert rec["podtrace"]["parity"] is True


def test_trace_run_id_knob_rejects_junk():
    for bad in ("has space", "x" * 129, "tab\tchar"):
        cfg = OverallConfig()
        with pytest.raises(LightGBMError):
            cfg.set({"objective": "binary", "trace_run_id": bad},
                    require_data=False)
