"""Pipelined boosting (ISSUE 6): pipeline=readback vs pipeline=off exact
equivalence.

The contract: pipelining only moves HOST WAITS (the model readback of
iteration/chunk i is consumed after iteration/chunk i+1's dispatch) — the
device work is dispatched in exactly the synchronous order, so trees,
scores, metric values, early-stopping decisions and RNG streams are
EXACT-identical, including when a stop (degenerate tree, early stopping)
is discovered one consumption late and the surplus dispatched work must be
rolled back from snapshots."""
import numpy as np
import pytest

import jax

from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.metrics import create_metric
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective


def _data(n=2000, f=8, seed=11):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    logits = x[:, 0] * 1.5 - x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    y = (logits + rng.randn(n) * 0.5 > 0).astype(np.float32)
    return x, y


@pytest.fixture(scope="module")
def train_ds():
    x, y = _data()
    return Dataset.from_arrays(x, y, max_bin=63)


def _train(ds, extra, iters=6, valid=None, via="run_training",
           is_eval=False):
    params = {"objective": "binary", "num_leaves": "15",
              "num_iterations": str(iters), "min_data_in_leaf": "20",
              "min_sum_hessian_in_leaf": "5.0", "learning_rate": "0.1"}
    params.update(extra)
    cfg = OverallConfig()
    cfg.set(params, require_data=False)
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, ds, obj)
    if valid is not None:
        vd = Dataset.from_arrays(valid[0], valid[1], reference=ds)
        b.add_valid_dataset(vd, [create_metric("binary_logloss",
                                               cfg.metric_config)])
    if via == "run_training":
        b.run_training(iters, is_eval=is_eval)
    elif via == "iter":
        for _ in range(iters):
            if b.train_one_iter(is_eval=is_eval):
                break
        b.flush_pipeline()
    elif via == "chunk":
        b.train_chunk(iters, is_eval=is_eval)
        b.flush_pipeline()
    return b


def _assert_equal(b1, b2, tag):
    assert len(b1.models) == len(b2.models), (
        tag, len(b1.models), len(b2.models))
    assert b1.iter == b2.iter, (tag, b1.iter, b2.iter)
    for t1, t2 in zip(b1.models, b2.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature,
                                      err_msg=tag)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin,
                                      err_msg=tag)
        np.testing.assert_array_equal(np.asarray(t1.leaf_value),
                                      np.asarray(t2.leaf_value),
                                      err_msg=tag)
    np.testing.assert_array_equal(np.asarray(b1.score),
                                  np.asarray(b2.score), err_msg=tag)
    for e1, e2 in zip(b1.valid_datasets, b2.valid_datasets):
        np.testing.assert_array_equal(np.asarray(e1["score"]),
                                      np.asarray(e2["score"]),
                                      err_msg=tag)


@pytest.mark.parametrize("grow", ["leafwise", "depthwise"])
def test_pipeline_exact_equivalence(train_ds, grow):
    extra = {"grow_policy": grow} if grow == "depthwise" else {}
    on = _train(train_ds, dict(extra, pipeline="readback"), iters=8)
    off = _train(train_ds, dict(extra, pipeline="off"), iters=8)
    _assert_equal(on, off, grow)


def test_pipeline_with_bagging_and_feature_fraction(train_ds):
    """The deferred path must replay the synchronous RNG stream exactly:
    bagging redraw cadence and per-class feature sampling included."""
    extra = {"bagging_fraction": "0.7", "bagging_freq": "2",
             "feature_fraction": "0.75"}
    on = _train(train_ds, dict(extra, pipeline="readback"), iters=8)
    off = _train(train_ds, dict(extra, pipeline="off"), iters=8)
    _assert_equal(on, off, "bagged")
    # RNG streams ended at the same point: one more draw matches
    assert (on._bag_rng.randint(1 << 30)
            == off._bag_rng.randint(1 << 30))


def test_pipeline_eval_and_early_stopping(train_ds):
    """Early stopping is discovered at consumption, one call after the
    surplus iteration was dispatched — the rollback must leave models,
    scores, valid scores and the stop iteration exactly synchronous."""
    rng = np.random.RandomState(99)
    xv = rng.randn(500, 8)            # label noise, uncorrelated with x:
    yv = (rng.rand(500) > 0.5).astype(np.float32)   # -> stops early
    extra = {"metric": "binary_logloss", "early_stopping_round": "1",
             "metric_freq": "1"}
    on = _train(train_ds, dict(extra, pipeline="readback"), iters=30,
                valid=(xv, yv), is_eval=True)
    off = _train(train_ds, dict(extra, pipeline="off"), iters=30,
                 valid=(xv, yv), is_eval=True)
    assert on.iter < 30, "test premise: early stopping must trigger"
    _assert_equal(on, off, "early-stop")
    assert on.best_score == off.best_score
    assert on.best_iter == off.best_iter


def test_pipeline_degenerate_stop_rollback(train_ds):
    """A degenerate (unsplittable) iteration is discovered one call late;
    the already-dispatched next iteration must be rolled back wholesale.
    min_data_in_leaf > N/2 makes the very first root split impossible."""
    extra = {"min_data_in_leaf": "1500"}
    on = _train(train_ds, dict(extra, pipeline="readback"), iters=5)
    off = _train(train_ds, dict(extra, pipeline="off"), iters=5)
    assert len(off.models) == 0 and off.iter == 0, "premise: degenerate"
    _assert_equal(on, off, "degenerate")


def test_pipeline_chunked_equivalence(train_ds):
    """Chunk-level pipelining: chunk N dispatches before chunk N-1's
    readback is consumed; run_training's chunk loop plus the final flush
    must land the identical state, including a truncated tail chunk."""
    extra = {"grow_policy": "depthwise"}
    # 20 iterations at chunk_size 8 -> 2 full chunks + a limit-4 tail
    on = _train(train_ds, dict(extra, pipeline="readback"), iters=20)
    off = _train(train_ds, dict(extra, pipeline="off"), iters=20)
    _assert_equal(on, off, "chunk-tail")


def test_pipeline_direct_chunk_calls(train_ds):
    """Direct train_chunk callers (bench.py) with pipeline=readback:
    every call consumes the previous chunk; flush_pipeline drains the
    last one."""
    extra = {"grow_policy": "depthwise"}
    on = _train(train_ds, dict(extra, pipeline="readback"), iters=8,
                via="chunk")
    off = _train(train_ds, dict(extra, pipeline="off"), iters=8,
                 via="chunk")
    _assert_equal(on, off, "direct-chunk")


def test_pipeline_auto_off_for_direct_calls(train_ds):
    """pipeline=auto engages only inside run_training: direct
    train_one_iter callers keep synchronous semantics (models complete
    after every call)."""
    params = {"objective": "binary", "num_leaves": "7",
              "num_iterations": "2", "min_data_in_leaf": "20",
              "min_sum_hessian_in_leaf": "5.0"}
    cfg = OverallConfig()
    cfg.set(params, require_data=False)
    assert cfg.boosting_config.pipeline == "auto"
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, train_ds, obj)
    b.train_one_iter(is_eval=False)
    assert len(b.models) == 1, "auto must stay synchronous outside " \
                               "run_training"
    assert b._pipe is None and b._pipe_chunk is None


def test_pipeline_env_hatch(train_ds, monkeypatch):
    """LGBM_TPU_PIPELINE=off beats a config that forces readback (A/B
    timing hatch)."""
    monkeypatch.setenv("LGBM_TPU_PIPELINE", "off")
    params = {"objective": "binary", "num_leaves": "7",
              "num_iterations": "2", "min_data_in_leaf": "20",
              "min_sum_hessian_in_leaf": "5.0", "pipeline": "readback"}
    cfg = OverallConfig()
    cfg.set(params, require_data=False)
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, train_ds, obj)
    b.train_one_iter(is_eval=False)
    assert len(b.models) == 1 and b._pipe is None


def test_pipeline_config_rejects_unknown():
    cfg = OverallConfig()
    with pytest.raises(Exception):
        cfg.set({"objective": "binary", "pipeline": "sideways"},
                require_data=False)
