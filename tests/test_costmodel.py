"""Cost-model / roofline-attribution tests (ISSUE 4): program capture
through the AOT path, graceful degradation on backends with partial or
absent analyses, the peak-table fallback for unknown device kinds, the
roofline/compile JSONL schema, crash-flush, and the tier-1 invariant that
the cost model never perturbs training numerics."""
import json

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import costmodel, telemetry
from lightgbm_tpu.io.dataset import Dataset


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _data(n=1100, seed=0, features=6):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, features)
    y = (x[:, 0] + 0.5 * x[:, 1] + 0.1 * rng.randn(n) > 0).astype(np.float32)
    return x, y


BASE = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
        "min_sum_hessian_in_leaf": 1.0, "learning_rate": 0.2}


# ----------------------------------------------------------- program capture

def test_instrument_captures_cost_and_serves_compiled():
    """First armed call of a signature AOT-compiles and records the
    backend's cost/memory analysis; later calls serve the cached
    executable with identical results and count invocations."""
    calls = {"n": 0}

    def f(a, b, *, k=1):
        calls["n"] += 1
        return (a @ b) * k

    wrapped = costmodel.instrument("test/prog", jax.jit(
        f, static_argnames=("k",)), phase="test_phase")
    a = jnp.ones((32, 32))
    costmodel.enable()
    out1 = wrapped(a, a, k=3)
    out2 = wrapped(a, a, k=3)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    comp = costmodel.compile_block()
    assert comp["program_count"] == 1
    prog = comp["programs"][0]
    assert prog["name"] == "test/prog" and prog["calls"] == 2
    assert prog["compile_seconds"] >= 0.0
    # the CPU backend provides flops/bytes; either way the fields exist
    # without error (graceful degradation is the contract, not a value)
    assert "flops" not in prog or prog["flops"] >= 0.0
    # plain jit path would re-trace per call; AOT traced exactly once
    assert calls["n"] == 1
    # numerics match the un-instrumented jit
    np.testing.assert_array_equal(
        np.asarray(out1), np.asarray((a @ a) * 3))


def test_instrument_disabled_is_passthrough():
    """Disarmed and capture-free, the wrapper is a straight call into the
    inner jit: nothing recorded, nothing compiled through AOT."""
    wrapped = costmodel.instrument("test/off", jax.jit(lambda x: x + 1))
    out = wrapped(jnp.arange(4))
    np.testing.assert_array_equal(np.asarray(out), np.arange(4) + 1)
    assert costmodel.compile_block()["program_count"] == 0
    assert not costmodel.active()


def test_capture_failure_degrades_to_plain_call():
    """A function without a .lower (or whose lowering fails) still runs —
    capture failure is recorded, never raised."""
    wrapped = costmodel.instrument("test/broken", lambda x: x * 2)
    costmodel.enable()
    assert wrapped(3) == 6
    comp = costmodel.compile_block()
    assert comp["program_count"] == 1
    assert comp["programs"][0]["error"]


def test_analyze_partial_cost_analysis():
    """Backends returning None / empty / throwing cost analyses yield
    None fields, not errors (the CPU degradation contract)."""
    class NoAnalysis:
        def cost_analysis(self):
            raise NotImplementedError

        def memory_analysis(self):
            return None

    class PartialAnalysis:
        def cost_analysis(self):
            return [{"flops": 12.0}]      # no "bytes accessed"

        def memory_analysis(self):
            raise RuntimeError("nope")

    a = costmodel._analyze(NoAnalysis())
    assert a["flops"] is None and a["bytes_accessed"] is None
    assert a["memory"] is None
    b = costmodel._analyze(PartialAnalysis())
    assert b["flops"] == 12.0 and b["bytes_accessed"] is None


# ------------------------------------------------------------------ peak table

def test_unknown_device_kind_degrades_to_peaks_unavailable():
    assert costmodel.resolve_peaks("banana9000") is None
    assert costmodel.resolve_peaks("") is None
    assert costmodel.resolve_peaks("cpu") is None
    block = costmodel.roofline({"grow": 1.0}, kind="banana9000")
    assert block["peaks"] == "unavailable"
    for blk in block["phases"].values():
        assert "frac_of_peak_flops" not in blk


def test_known_device_kinds_resolve():
    for kind in ("TPU v5 lite", "TPU v5e", "TPU v5p", "TPU v4", "tpu v6e"):
        peaks = costmodel.resolve_peaks(kind)
        assert peaks and peaks["flops_per_sec"] > 0
        assert peaks["hbm_bytes_per_sec"] > 0


def test_roofline_join_computes_fractions_on_known_kind():
    """Static cost x calls joined to measured seconds: attained rates and
    fraction-of-peak on a (simulated) v5e."""
    costmodel.enable()
    costmodel._records.append({
        "name": "x", "phase": "grow", "compile_seconds": 0.1,
        "flops": 197e10, "bytes_accessed": 819e7, "memory": None,
        "calls": 10, "warm": False, "gen": costmodel._generation})
    block = costmodel.roofline({"grow": 1.0}, kind="TPU v5 lite")
    g = block["phases"]["grow"]
    # 10 calls x 197e10 flops over 1s = 10% of the 197e12 peak
    assert g["frac_of_peak_flops"] == pytest.approx(0.1)
    assert g["frac_of_peak_bw"] == pytest.approx(0.1)
    assert g["arithmetic_intensity"] == pytest.approx(197e10 / 819e7)
    assert g["attained_flops_per_sec"] == pytest.approx(197e11)


def test_roofline_excludes_in_span_capture_compile_time():
    """The first armed call's AOT compile runs inside the caller's phase
    span: attained rates must price execution seconds only, or a cold
    compile cache would read as a kernel regression downstream
    (perf_gate)."""
    costmodel.enable()
    costmodel._records.append({
        "name": "x", "phase": "grow", "compile_seconds": 0.5,
        "capture_seconds": 0.5, "flops": 1e9, "bytes_accessed": 1e6,
        "memory": None, "calls": 1, "warm": False,
        "gen": costmodel._generation})
    blk = costmodel.roofline({"grow": 1.5},
                             kind="TPU v5 lite")["phases"]["grow"]
    assert blk["compile_seconds_excluded"] == 0.5
    assert blk["seconds"] == 1.5
    # 1e9 flops over (1.5 - 0.5) execution seconds
    assert blk["attained_flops_per_sec"] == pytest.approx(1e9)
    # span shorter than the capture (tiny run): no attained fields rather
    # than a nonsense rate
    blk2 = costmodel.roofline({"grow": 0.3},
                              kind="TPU v5 lite")["phases"]["grow"]
    assert "attained_flops_per_sec" not in blk2


# ------------------------------------------------------------- JSONL schema

def _roofline_schema(block):
    assert "device_kind" in block
    assert block["peaks"] == "unavailable" or isinstance(block["peaks"],
                                                         dict)
    assert isinstance(block["phases"], dict)
    for blk in block["phases"].values():
        for key in ("flops", "bytes_accessed", "programs", "calls",
                    "seconds"):
            assert key in blk


def _compile_schema(block):
    for key in ("program_count", "total_compile_seconds", "warm_programs",
                "backend_compiles", "persistent_cache_hits",
                "midrun_recompiles", "programs"):
        assert key in block
    for p in block["programs"]:
        assert p["name"] and p["phase"] and p["calls"] >= 1


def test_metrics_out_chunked_run_emits_roofline_and_compile(tmp_path):
    """A metrics_out= run on the CPU backend: the summary carries both
    blocks — fraction fields degraded (peaks unavailable), the chunk
    program captured with calls counted."""
    x, y = _data(n=1210, features=7)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")
    lgb.train(dict(BASE, num_iterations=10, grow_policy="depthwise",
                   metrics_out=path), ds)
    telemetry.disable()
    recs = [json.loads(line) for line in open(path)]
    summary = recs[-1]
    assert summary.get("summary") is True
    _roofline_schema(summary["roofline"])
    assert summary["roofline"]["peaks"] == "unavailable"  # CPU backend
    tc = summary["roofline"]["phases"]["train_chunk"]
    assert tc["calls"] >= 1 and tc["seconds"] > 0
    assert "attained_flops_per_sec" in tc
    _compile_schema(summary["compile"])
    names = [p["name"] for p in summary["compile"]["programs"]]
    assert "chunk/serial" in names
    # the analytic histogram pass notes rode along
    passes = summary["roofline"].get("traced_passes", [])
    assert any(n["phase"] == "histogram" and n["macs"] > 0 for n in passes)


def test_metrics_out_leafwise_run_emits_grow_program(tmp_path):
    x, y = _data(n=1490, features=5)
    ds = Dataset.from_arrays(x, y, max_bin=24)
    path = str(tmp_path / "m.jsonl")
    lgb.train(dict(BASE, num_iterations=3, num_leaves=11,
                   metrics_out=path), ds)
    telemetry.disable()
    summary = [json.loads(line) for line in open(path)][-1]
    names = [p["name"] for p in summary["compile"]["programs"]]
    assert "grow/leafwise" in names
    grow = summary["roofline"]["phases"]["grow"]
    assert grow["calls"] == 3


def test_snapshot_carries_blocks_and_disabled_mode_stays_empty():
    snap = telemetry.snapshot()
    assert "roofline" not in snap and "compile" not in snap
    telemetry.enable()
    wrapped = costmodel.instrument("test/snap", jax.jit(lambda x: x * 2))
    wrapped(jnp.arange(8))
    snap = telemetry.snapshot()
    _roofline_schema(snap["roofline"])
    _compile_schema(snap["compile"])


# ----------------------------------------------------------------- crash flush

def test_crash_flush_writes_summary_on_halt(tmp_path):
    """An exception escaping run_training (TrainingHealthError halt here)
    writes a final summary record marked ``aborted`` and flushes the sink
    before re-raising — an aborted run keeps its tail records."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.health import TrainingHealthError
    from lightgbm_tpu.models.gbdt import GBDT
    from test_health import _NaNObjective

    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")
    telemetry.enable(path)
    cfg = OverallConfig()
    cfg.set(dict({k: str(v) for k, v in BASE.items()},
                 objective="regression", health="true",
                 on_anomaly="halt"), require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds, _NaNObjective())
    with pytest.raises(TrainingHealthError):
        booster.run_training(3, False)
    telemetry.disable()
    recs = [json.loads(line) for line in open(path)]
    summary = recs[-1]
    assert summary.get("summary") is True
    assert summary["aborted"] == "TrainingHealthError"
    _roofline_schema(summary["roofline"])
    _compile_schema(summary["compile"])


def test_generic_exception_also_crash_flushes(tmp_path, monkeypatch):
    """Not just health halts: any exception out of the loop flushes."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")

    class Boom(RuntimeError):
        pass

    from lightgbm_tpu.models.gbdt import GBDT
    orig = GBDT.train_one_iter

    def boom(self, is_eval=True):
        if self.iter >= 1:
            raise Boom("mid-train failure")
        return orig(self, is_eval=is_eval)

    monkeypatch.setattr(GBDT, "train_one_iter", boom)
    with pytest.raises(Boom):
        lgb.train(dict(BASE, num_iterations=4, metrics_out=path), ds)
    telemetry.disable()
    recs = [json.loads(line) for line in open(path)]
    assert recs[-1].get("summary") is True
    assert recs[-1]["aborted"] == "Boom"
    # the completed iteration's record is in the file too
    assert any(r.get("iter") == 1 for r in recs)


# -------------------------------------------------- numerics non-perturbation

def test_scores_bit_identical_costmodel_on_vs_off():
    """Tier-1 invariant: routing programs through the AOT capture path
    must not change numerics — same HLO, same compile options, so scores
    are bit-identical with the cost model enabled vs disabled."""
    x, y = _data(seed=3)
    params = dict(BASE, num_iterations=4, bagging_fraction=0.7,
                  bagging_freq=1)

    def scores(with_costmodel):
        telemetry.disable()
        telemetry.reset()
        if with_costmodel:
            costmodel.enable()
        ds = Dataset.from_arrays(x, y, max_bin=32)
        booster = lgb.train(params, ds)
        out = np.asarray(booster.score)
        costmodel.disable()
        return out

    off = scores(False)
    on = scores(True)
    np.testing.assert_array_equal(off, on)


def test_telemetry_report_renders_blocks_and_rejects_malformed(tmp_path,
                                                               capsys):
    """scripts/telemetry_report.py renders the roofline/compile tables
    from a real sink and exits with a one-line error (code 2), not a
    stack trace, on truncated JSONL."""
    import os
    import sys as _sys
    _sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from scripts import telemetry_report

    x, y = _data(n=1010, features=5)
    ds = Dataset.from_arrays(x, y, max_bin=16)
    path = str(tmp_path / "m.jsonl")
    lgb.train(dict(BASE, num_iterations=2, num_leaves=7,
                   metrics_out=path), ds)
    telemetry.disable()
    assert telemetry_report.report(path) == 0
    out = capsys.readouterr().out
    assert "Roofline" in out and "Compile observability" in out
    assert "peaks: unavailable" in out

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"iter": 1, "phase_times"')
    assert telemetry_report.report(str(bad)) == 2
    err = capsys.readouterr().err
    assert "malformed" in err and "Traceback" not in err


def test_host_fingerprint_is_self_describing():
    fp = costmodel.host_fingerprint()
    assert fp["device_kind"]
    assert fp["backend"] == jax.default_backend()
    assert fp["jax_version"] == jax.__version__
    assert fp["process_count"] == 1
