"""Parallel sharded ingest tests (ISSUE 18, io/parallel_ingest.py +
io/parser.py byte ranges): byte-range split semantics (mid-line, CRLF,
EOF without trailing newline, inside-header candidates, and the
property that ANY candidate set reproduces the serial reader exactly),
parallel==serial bit-identity end to end (mappers, bin codes, streamed
cache bytes, metadata, trained model text — plain, GOSS and bagging —
at >= 2 worker counts), the masked multi-process shard path, the direct
columnar-binary ``data=<file>.bin`` train/predict inputs, the binary
streaming telemetry satellite, and the knob's reject/fallback surface."""
import os

import numpy as np
import pytest

from lightgbm_tpu import telemetry, tracing
from lightgbm_tpu.config import IOConfig, OverallConfig
from lightgbm_tpu.io import parallel_ingest, parser as parser_mod
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.utils.log import LightGBMError


def _write_csv(path, n, f=5, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int)
    with open(path, "w") as fh:
        for i in range(n):
            fh.write(",".join([str(y[i])]
                              + ["%.6f" % v for v in x[i]]) + "\n")
    return str(path)


def _load(path, rank=0, num_machines=1, **kw):
    return Dataset.load_train(IOConfig(data_filename=str(path), **kw),
                              rank=rank, num_machines=num_machines)


def _assert_identical(res, stm):
    assert res.num_data == stm.num_data
    assert list(res.used_feature_map.items()) == \
        list(stm.used_feature_map.items())
    for m1, m2 in zip(res.bin_mappers, stm.bin_mappers):
        assert m1.to_bytes() == m2.to_bytes()
    res_bins = (np.asarray(res.device_bins) if res.bins is None
                else res.bins)
    stm_bins = (np.asarray(stm.device_bins) if stm.bins is None
                else stm.bins)
    np.testing.assert_array_equal(res_bins, stm_bins)
    assert res_bins.dtype == stm_bins.dtype
    np.testing.assert_array_equal(res.metadata.label, stm.metadata.label)


def _train(ds, **params):
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "num_iterations": "4",
             "num_leaves": "8", "min_data_in_leaf": "5",
             **{k: str(v) for k, v in params.items()}},
            require_data=False)
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, ds, obj)
    b.run_training(int(cfg.boosting_config.num_iterations), False)
    return b


def _model_text(b):
    return "".join(t.to_string() for t in b.models)


needs_pool = pytest.mark.skipif(not parallel_ingest.available(),
                               reason="no worker interpreter to exec")


# ------------------------------------------------- byte-range splitting


def _assert_split_matches_serial(path, candidates, skip_header=False):
    """The split-semantics property: ANY candidate set must reproduce
    ``read_lines`` exactly — per-range lines concatenate to the serial
    read, counts match, and total equals ``count_data_rows``."""
    ranges, counts, total = parser_mod.split_byte_ranges_at(
        path, candidates, skip_header=skip_header)
    serial = parser_mod.read_lines(path, skip_header=skip_header)
    got = []
    for (s, e), cnt in zip(ranges, counts):
        lines = parser_mod.read_range_lines(path, s, e)
        assert len(lines) == cnt
        got.extend(lines)
    assert got == serial
    assert total == len(serial)
    assert total == parser_mod.count_data_rows(path,
                                               skip_header=skip_header)


def test_split_midline_candidates(tmp_path):
    path = str(tmp_path / "t.csv")
    with open(path, "w") as f:
        f.write("aaa,1\nbbbb,22\ncc,333\ndddd,4\n")
    # candidates land mid-line — each must snap FORWARD to the next
    # row start, never truncating or duplicating a row
    _assert_split_matches_serial(path, [2, 9, 17])


def test_split_crlf_and_blank_lines(tmp_path):
    path = str(tmp_path / "t.csv")
    with open(path, "wb") as f:
        f.write(b"a,1\r\nb,2\r\n\r\nc,3\nd,4\r\n")
    # \r\n rows and a \r\n "blank" line (dropped by the text reader's
    # truthiness filter) — any split through them must agree
    for cands in ([3], [4], [5], [10, 11, 12], [0, 23, 100]):
        _assert_split_matches_serial(path, cands)


def test_split_eof_without_trailing_newline(tmp_path):
    path = str(tmp_path / "t.csv")
    with open(path, "w") as f:
        f.write("a,1\nb,2\nc,3")  # final row unterminated
    _assert_split_matches_serial(path, [5])
    _assert_split_matches_serial(path, [9, 10, 11])  # inside final row


def test_split_inside_skipped_header(tmp_path):
    path = str(tmp_path / "t.csv")
    with open(path, "w") as f:
        f.write("col_a,col_b\n1,2\n3,4\n")
    # candidates INSIDE the header must snap to the first data row,
    # producing an empty leading range rather than re-reading the header
    _assert_split_matches_serial(path, [0, 3, 8], skip_header=True)
    assert parser_mod.data_byte_start(path, skip_header=True) == 12


def test_data_byte_start_variants(tmp_path):
    p1 = str(tmp_path / "lf.csv")
    open(p1, "w").write("h\na\n")
    assert parser_mod.data_byte_start(p1, skip_header=False) == 0
    assert parser_mod.data_byte_start(p1, skip_header=True) == 2
    p2 = str(tmp_path / "crlf.csv")
    open(p2, "wb").write(b"h\r\na\r\n")
    assert parser_mod.data_byte_start(p2, skip_header=True) == 3
    p3 = str(tmp_path / "noterm.csv")
    open(p3, "w").write("only-header-no-newline")
    # no terminator: the whole file is the header line
    assert parser_mod.data_byte_start(p3, skip_header=True) == \
        os.path.getsize(p3)


def test_split_property_random_candidates(tmp_path):
    """Property: arbitrary candidate sets (mid-line, duplicated, at 0,
    beyond EOF) over a messy file reproduce the serial reader."""
    path = str(tmp_path / "t.csv")
    rng = np.random.RandomState(3)
    with open(path, "wb") as f:
        for i in range(200):
            term = [b"\n", b"\r\n"][int(rng.randint(2))]
            f.write(b"%d,%d" % (i, i * 7) + term)
            if rng.rand() < 0.1:
                f.write([b"\n", b"\r\n"][int(rng.randint(2))])  # blank
    size = os.path.getsize(path)
    for _ in range(20):
        k = int(rng.randint(0, 8))
        cands = sorted(int(c) for c in rng.randint(0, size + 40, size=k))
        _assert_split_matches_serial(path, cands)
    # the byte-balanced planner rides the same primitive
    for n in (1, 2, 3, 7):
        ranges, counts, total = parser_mod.split_byte_ranges(path, n)
        assert total == sum(counts) == parser_mod.count_data_rows(path)


# ------------------------------------------- parallel == serial loads


@needs_pool
@pytest.mark.parametrize("workers", [2, 3])
def test_parallel_bit_identity(tmp_path, workers):
    path = _write_csv(tmp_path / "t.csv", 400)
    res = _load(path, streaming="false")
    par = _load(path, streaming="true", ingest_chunk_rows=64,
                ingest_workers=workers)
    assert par.ingest_workers_requested == workers
    assert par.ingest_workers_effective == workers
    _assert_identical(res, par)
    assert _model_text(_train(res)) == _model_text(_train(par))


@needs_pool
def test_parallel_cache_bytes_identical(tmp_path):
    """The streamed .bin cache written under workers is byte-identical
    to the serial streamed writer's."""
    path = _write_csv(tmp_path / "t.csv", 300)
    _load(path, streaming="true", ingest_chunk_rows=77,
          is_save_binary_file=True)
    serial_cache = open(path + ".bin", "rb").read()
    os.unlink(path + ".bin")
    _load(path, streaming="true", ingest_chunk_rows=77,
          ingest_workers=2, is_save_binary_file=True)
    assert open(path + ".bin", "rb").read() == serial_cache


@needs_pool
@pytest.mark.parametrize("params", [
    {"goss": "true", "top_rate": "0.3", "other_rate": "0.3"},
    {"bagging_fraction": "0.7", "bagging_freq": "2",
     "bagging_seed": "11"},
])
def test_parallel_goss_bagging_model_identity(tmp_path, params):
    """The sampled-training RNG streams ride the dataset's row order and
    the global seeds — a parallel load must not perturb either."""
    path = _write_csv(tmp_path / "t.csv", 400)
    ser = _load(path, streaming="true", ingest_chunk_rows=96)
    par = _load(path, streaming="true", ingest_chunk_rows=96,
                ingest_workers=2)
    assert _model_text(_train(ser, **params)) == \
        _model_text(_train(par, **params))


@needs_pool
def test_parallel_multiprocess_shard_bit_identity(tmp_path):
    """Tentpole (c): under num_machines > 1 each host parses pass 2 only
    over its own row shard — owned rows tile the dataset exactly and
    every shard matches the resident masked load bitwise."""
    path = _write_csv(tmp_path / "t.csv", 300)
    owned = []
    for rank in range(3):
        stm = _load(path, streaming="true", ingest_chunk_rows=64,
                    ingest_workers=2, rank=rank, num_machines=3)
        res = _load(path, streaming="false", rank=rank, num_machines=3)
        np.testing.assert_array_equal(
            np.asarray(stm.used_data_indices),
            np.asarray(res.used_data_indices))
        _assert_identical(res, stm)
        owned.append(np.asarray(stm.used_data_indices))
    union = np.concatenate(owned)
    assert np.unique(union).size == union.size  # zero overlap
    np.testing.assert_array_equal(np.sort(union), np.arange(300))


def test_parallel_unavailable_resolves_serial_loudly(tmp_path,
                                                     monkeypatch):
    """No exec'able worker interpreter → the load still succeeds through the serial path and the
    resolution is RECORDED (perf_gate's silent-serial finding reads
    these as bench keys)."""
    monkeypatch.setattr(parallel_ingest, "available", lambda: False)
    path = _write_csv(tmp_path / "t.csv", 120)
    ds = _load(path, streaming="true", ingest_chunk_rows=64,
               ingest_workers=4)
    assert ds.ingest_workers_requested == 4
    assert ds.ingest_workers_effective == 1
    res = _load(path, streaming="false")
    _assert_identical(res, ds)


def test_ingest_workers_config_surface():
    cfg = OverallConfig()
    cfg.set({"ingest_workers": "3"}, require_data=False)
    assert cfg.io_config.ingest_workers == 3
    cfg2 = OverallConfig()
    cfg2.set({"ingest_workers": "auto"}, require_data=False)
    assert cfg2.io_config.ingest_workers == (os.cpu_count() or 1)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"ingest_workers": "0"}, require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"ingest_workers": "-2"}, require_data=False)


# ------------------------------------------- direct columnar-binary input


def test_direct_binary_train_no_text_sibling(tmp_path):
    """Tentpole (b): task=train accepts the native cache as the PRIMARY
    data= input — moved away from any text sibling, it loads and trains
    byte-identically to the text-then-cache path."""
    path = _write_csv(tmp_path / "t.csv", 300)
    res = _load(path, streaming="false", is_save_binary_file=True)
    alone = str(tmp_path / "standalone.bin")
    os.rename(path + ".bin", alone)
    os.unlink(path)  # no text file anywhere
    direct = _load(alone, streaming="false")
    _assert_identical(res, direct)
    assert _model_text(_train(res)) == _model_text(_train(direct))
    streamed = _load(alone, streaming="true", ingest_chunk_rows=64)
    _assert_identical(res, streamed)
    assert _model_text(_train(res)) == _model_text(_train(streamed))


def test_direct_binary_corrupt_rejected(tmp_path):
    from lightgbm_tpu.io.dataset import BINARY_MAGIC
    path = str(tmp_path / "broken.bin")
    with open(path, "wb") as f:
        f.write(BINARY_MAGIC[:12])  # truncated magic prefix
    with pytest.raises(LightGBMError):
        _load(path, streaming="false")


def test_direct_binary_predict_identical(tmp_path):
    """predict_file on the .bin cache scores without any text parse and
    writes a byte-identical result file (bin representatives land in
    the same bins, and tree thresholds ARE bin bounds)."""
    from lightgbm_tpu.models.predictor import Predictor
    path = _write_csv(tmp_path / "t.csv", 300)
    ds = _load(path, streaming="false", is_save_binary_file=True)
    booster = _train(ds)
    pred = Predictor(booster, is_sigmoid=True,
                     is_predict_leaf_index=False, num_used_model=-1)
    out_txt = str(tmp_path / "from_text.tsv")
    out_bin = str(tmp_path / "from_bin.tsv")
    pred.predict_file(path, out_txt, has_header=False, chunk_lines=128)
    pred.predict_file(path + ".bin", out_bin, has_header=False,
                      chunk_lines=128)
    assert open(out_txt, "rb").read() == open(out_bin, "rb").read()


# --------------------------------------------------- telemetry satellites


@pytest.fixture
def clean_tracing():
    telemetry.enable()
    telemetry.reset()
    yield
    tracing.disarm()
    telemetry.reset()
    telemetry.disable()


def test_binary_streaming_files_ingest_events(tmp_path, clean_tracing):
    """Satellite 1: load_binary_streaming files the same ingest
    pass/chunk attribution as the text path (pass 2 only, parse_us=0)
    and counts ingest/h2d_us."""
    path = _write_csv(tmp_path / "t.csv", 300)
    _load(path, streaming="false", is_save_binary_file=True)
    tracing.arm(ring_events=4096)
    telemetry.reset()
    _load(path, streaming="true", ingest_chunk_rows=64)  # reads .bin
    dumped = tracing.dump(path=str(tmp_path / "d.jsonl"), reason="test")
    assert dumped
    import json
    events = [json.loads(l) for l in open(dumped)][1:]
    passes = [e for e in events if e.get("kind") == "ingest_pass"]
    chunks = [e for e in events if e.get("kind") == "ingest_chunk"]
    assert {int(e["pass"]) for e in passes} == {2}
    assert chunks and all(int(e["pass"]) == 2 for e in chunks)
    assert all(float(e["parse_us"]) == 0.0 for e in chunks)
    assert sum(int(e["rows"]) for e in chunks) == 300
    c = telemetry.counters()
    assert c.get("ingest/chunks", 0) > 0
    assert "ingest/h2d_us" in c


def test_cpu_staged_writer_files_overlap_counter(tmp_path,
                                                 clean_tracing):
    """Satellite 2: the DeviceRowWriter CPU staged path files
    ingest/overlap_hidden_us (zero) so the derived overlap column in
    telemetry_report has its denominator."""
    path = _write_csv(tmp_path / "t.csv", 200)
    telemetry.reset()
    ds = _load(path, streaming="true", ingest_chunk_rows=64)
    assert ds.device_bins is not None
    c = telemetry.counters()
    assert "ingest/overlap_hidden_us" in c
    assert c["ingest/overlap_hidden_us"] >= 0


@needs_pool
def test_parallel_load_counts_and_tags_workers(tmp_path, clean_tracing):
    """The worker pool feeds the same telemetry family: parse/bin
    counters move and pass-2 chunk events carry the worker pid tag."""
    path = _write_csv(tmp_path / "t.csv", 300)
    tracing.arm(ring_events=4096)
    telemetry.reset()
    _load(path, streaming="true", ingest_chunk_rows=64,
          ingest_workers=2)
    dumped = tracing.dump(path=str(tmp_path / "d.jsonl"), reason="test")
    import json
    events = [json.loads(l) for l in open(dumped)][1:]
    passes = {int(e["pass"]) for e in events
              if e.get("kind") == "ingest_pass"}
    assert passes == {0, 1, 2}
    tagged = [e for e in events if e.get("kind") == "ingest_chunk"
              and "worker" in e]
    assert tagged, "no worker-tagged parse spans in the ring"
    c = telemetry.counters()
    assert c.get("ingest/parse_us", 0) > 0
    assert c.get("ingest/bin_us", 0) > 0
    assert c.get("ingest/rows", 0) == 300
