"""Float-gradient Pallas histogram path (ops/hist_pallas.py bf16v):
bf16 single-pass and f32x2 hi/lo variants vs the exact scatter oracle.

This is the round-3 mitigation for the environment's XLA einsum-lowering
regression (BASELINE.md): the hist_dtype=float32/bfloat16 paths route to a
hand-scheduled Pallas kernel on TPU.  These tests pin the kernel's math in
interpret mode; the dispatch itself is TPU-gated (histogram._pallas_hist_ok)
so the CPU einsum oracle below stays the reference.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tests._pltpu_probe import requires_pltpu_interpret

from lightgbm_tpu.ops.histogram import (histogram_leafbatch,
                                        histogram_leafbatch_segsum)
from lightgbm_tpu.ops.hist_pallas import hist_pallas_float_leafbatch


@pytest.fixture(scope="module")
def hist_inputs():
    rng = np.random.RandomState(7)
    F, N, B, C = 5, 4000, 32, 7
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.int8))
    grad = jnp.asarray((rng.randn(N) * 0.4).astype(np.float32))
    hess = jnp.asarray((rng.rand(N) * 0.25).astype(np.float32))
    cid = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    ok = jnp.asarray(rng.rand(N) < 0.85)
    return bins, grad, hess, cid, ok, F, N, B, C


@requires_pltpu_interpret
def test_bf16_variant_matches_rounded_oracle(hist_inputs):
    """Single-pass bf16: equal to the exact oracle fed bf16-rounded
    grad/hess (to f32 accumulation-order noise), counts exact."""
    from jax.experimental.pallas import tpu as pltpu
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    g16 = grad.astype(jnp.bfloat16).astype(jnp.float32)
    h16 = hess.astype(jnp.bfloat16).astype(jnp.float32)
    want = histogram_leafbatch_segsum(bins, g16, h16, cid, ok, C, B)
    with pltpu.force_tpu_interpret_mode():
        got = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C, B,
                                          chunk=1024, precision="bf16")
    np.testing.assert_array_equal(np.asarray(want[..., 2]),
                                  np.asarray(got[..., 2]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


@requires_pltpu_interpret
def test_f32x2_variant_near_exact(hist_inputs):
    """Two-pass hi/lo split recovers ~16 operand mantissa bits: per-cell
    error must sit far below the single-pass bf16 rounding floor."""
    from jax.experimental.pallas import tpu as pltpu
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    want = histogram_leafbatch_segsum(bins, grad, hess, cid, ok, C, B)
    with pltpu.force_tpu_interpret_mode():
        got = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C, B,
                                          chunk=1024, precision="f32x2")
        got_bf = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C,
                                             B, chunk=1024,
                                             precision="bf16")
    np.testing.assert_array_equal(np.asarray(want[..., 2]),
                                  np.asarray(got[..., 2]))
    w = np.asarray(want)
    err_x2 = np.abs(np.asarray(got) - w)[..., :2]
    err_bf = np.abs(np.asarray(got_bf) - w)[..., :2]
    # bound the hi/lo error by the operand split: |eps| <= 2^-16 per value,
    # so a cell of n rows with max |v| drifts <= n * maxv * 2^-16 (+ f32
    # accumulation noise)
    counts = w[..., 2:3][..., 0][..., None]
    maxv = max(float(jnp.max(jnp.abs(grad))), float(jnp.max(jnp.abs(hess))))
    bound = counts * maxv * 2.0**-15 + 1e-5
    assert (err_x2 <= bound).all()
    assert err_x2.sum() < 0.05 * err_bf.sum() + 1e-6


@requires_pltpu_interpret
def test_wide_level_grouping(hist_inputs):
    """>64 columns split into groups; results must tile back exactly."""
    from jax.experimental.pallas import tpu as pltpu
    rng = np.random.RandomState(11)
    F, N, B, C = 3, 2000, 16, 100
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.int8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray(rng.rand(N).astype(np.float32))
    cid = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    ok = jnp.asarray(rng.rand(N) < 0.9)
    g16 = grad.astype(jnp.bfloat16).astype(jnp.float32)
    h16 = hess.astype(jnp.bfloat16).astype(jnp.float32)
    want = histogram_leafbatch_segsum(bins, g16, h16, cid, ok, C, B)
    with pltpu.force_tpu_interpret_mode():
        got = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C, B,
                                          chunk=512, precision="bf16")
    assert got.shape == (C, F, B, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


@requires_pltpu_interpret
def test_uint8_bins_above_127_not_dropped():
    """max_bin=255 bins ride as uint8 bit-patterns; the kernel must mask
    the int8 sign-extension back off (same guarantee as the int8 path)."""
    from jax.experimental.pallas import tpu as pltpu
    rng = np.random.RandomState(13)
    F, N, B, C = 4, 3000, 255, 5
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray(rng.rand(N).astype(np.float32))
    cid = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    ok = jnp.ones(N, bool)
    want = histogram_leafbatch_segsum(bins, grad, hess, cid, ok, C, B)
    with pltpu.force_tpu_interpret_mode():
        got = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C, B,
                                          chunk=1024, precision="f32x2")
    np.testing.assert_array_equal(np.asarray(want[..., 2]),
                                  np.asarray(got[..., 2]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-3)


def test_einsum_dispatch_unaffected_off_tpu(hist_inputs):
    """On the CPU backend _pallas_hist_ok is False, so the einsum branch
    still serves float dtypes (the differential-test oracle path)."""
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    assert jax.default_backend() != "tpu"
    a = histogram_leafbatch(bins, grad, hess, cid, ok, C, B,
                            compute_dtype=jnp.float32)
    b = histogram_leafbatch_segsum(bins, grad, hess, cid, ok, C, B)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-3)


@requires_pltpu_interpret
def test_wide_dataset_feature_grid():
    """Datasets wider than one VMEM accumulator block (feature_block() =
    96 at B=256/lanes=128) ride the kernel's feature-block grid axis —
    int8 stays bit-identical to the XLA oracle, bf16v matches the rounded
    oracle; pad features are sliced off."""
    from jax.experimental.pallas import tpu as pltpu
    from lightgbm_tpu.ops.hist_pallas import (feature_block,
                                              hist_pallas_leafbatch,
                                              hist_quant_xla)
    rng = np.random.RandomState(17)
    F, N, B, C = 100, 1024, 256, 5
    assert F > feature_block(B, 128)
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray(rng.rand(N).astype(np.float32))
    cid = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    ok = jnp.asarray(rng.rand(N) < 0.9)
    want_int = hist_quant_xla(bins, grad, hess, cid, ok, C, B)
    g16 = grad.astype(jnp.bfloat16).astype(jnp.float32)
    h16 = hess.astype(jnp.bfloat16).astype(jnp.float32)
    want_f = histogram_leafbatch_segsum(bins, g16, h16, cid, ok, C, B)
    with pltpu.force_tpu_interpret_mode():
        got_int = hist_pallas_leafbatch(bins, grad, hess, cid, ok, C, B,
                                        chunk=512, dtype="int8")
        got_f = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C,
                                            B, chunk=512,
                                            precision="bf16")
    np.testing.assert_array_equal(np.asarray(want_int), np.asarray(got_int))
    assert got_f.shape == (C, F, B, 3)
    np.testing.assert_allclose(np.asarray(got_f), np.asarray(want_f),
                               rtol=1e-5, atol=1e-4)


@requires_pltpu_interpret
def test_f32x1_bit_identical_to_f32x2(hist_inputs):
    """The single-pass 5-stat packing accumulates the same per-lane f32
    partial sums as the two-pass variant — outputs must be bit-equal
    (including across the 38-column grouping boundary)."""
    from jax.experimental.pallas import tpu as pltpu
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    with pltpu.force_tpu_interpret_mode():
        one = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C, B,
                                          chunk=1024, precision="f32x1")
        two = hist_pallas_float_leafbatch(bins, grad, hess, cid, ok, C, B,
                                          chunk=1024, precision="f32x2")
    np.testing.assert_array_equal(np.asarray(one), np.asarray(two))

    rng = np.random.RandomState(23)
    for C2 in (32, 50):
        # 32: the 192-lane single 5-stat pass (the depthwise depth-5
        # production route, 192 % 5 leaves 2 partial lanes);
        # 50: > 38, grouped into two 5-stat passes
        cid2 = jnp.asarray(rng.randint(0, C2, N).astype(np.int32))
        want = histogram_leafbatch_segsum(bins, grad, hess, cid2, ok,
                                          C2, B)
        with pltpu.force_tpu_interpret_mode():
            got = hist_pallas_float_leafbatch(bins, grad, hess, cid2, ok,
                                              C2, B, chunk=1024,
                                              precision="f32x1")
        assert got.shape == (C2, F, B, 3)
        np.testing.assert_array_equal(np.asarray(want[..., 2]),
                                      np.asarray(got[..., 2]))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-3)
