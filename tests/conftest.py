"""Test configuration: force an 8-device virtual CPU platform so sharding
and parallel-learner tests run without TPU hardware (SURVEY.md §4).

Note: the environment's sitecustomize imports jax before pytest starts, so
plain env vars are too late — use jax.config.update, which takes effect any
time before backend initialization.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the suite: the tier-1 wall is
# compile-bound (the unrolled grower programs dominate), and the cache
# is content-addressed on the HLO — edited programs recompile, unchanged
# ones load hot.  Local per-machine path, never shared across hosts, so
# the heterogeneous-host SIGILL hazard that keeps the CPU cache off in
# lightgbm_tpu/__init__.py does not arise.
if jax.config.jax_compilation_cache_dir is None:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.expanduser("~/.cache/lightgbm_tpu_xla_tests"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import shutil
import subprocess

import numpy as np
import pytest

REFERENCE_EXAMPLES = "/root/reference/examples"
REFERENCE_SRC = "/root/reference"
REFERENCE_BUILD = "/tmp/lightgbm_reference_build"
REFERENCE_BINARY = os.path.join(REFERENCE_BUILD, "lightgbm")


@pytest.fixture(autouse=True)
def _telemetry_leak_guard():
    """Telemetry is process-global state: a test that leaves the registry
    enabled (or a sink open) silently poisons every later test — route
    counters bleed across tests and sinks append foreign records.  Fail
    the offender, then clean up so the rest of the suite still runs on a
    clean registry.  Set up before (torn down after) per-test fixtures,
    so tests that disable telemetry in their own teardown pass."""
    from lightgbm_tpu import telemetry
    yield
    leaked_enabled = telemetry.enabled()
    leaked_sink = telemetry.sink_open()
    # ISSUE 5 surface: timeline/shard mode left on makes the next
    # metrics_out test write an unexpected shard file instead of its
    # configured path (an unmerged shard surviving the test)
    leaked_timeline = telemetry.timeline_enabled()
    # ISSUE 10 surface: graftlint's jaxpr layer arms telemetry in
    # trace-census mode (analysis.jaxpr_rules.begin_census) to record
    # the seam inventory while tracing; a test that leaves it armed
    # makes every later record_collective land in a foreign census AND
    # leaves telemetry enabled.  Check BEFORE the disable below (the
    # census teardown owns its own telemetry restore).
    from lightgbm_tpu.analysis import jaxpr_rules as _graftlint_census
    leaked_census = _graftlint_census.trace_census_active()
    if leaked_census:
        _graftlint_census.end_census()
    # ISSUE 15: every thread-owning subsystem (checkpoint writers, the
    # serving front, prefetch threads, the telemetry watchdog) and the
    # armed fault hatch register with ONE shared inventory
    # (lightgbm_tpu/lifecycle.py) — the guard reads it here instead of
    # hand-enumerating per module, and graftlint C1 gates that every new
    # thread spawn site keeps registering.  Read BEFORE the disable
    # below (disable() disarms — and deregisters — the watchdog).
    from lightgbm_tpu import faults as _faults  # noqa: F401 — importing
    # registers its armed-hatch probe; without this a test that set
    # LGBM_TPU_FAULT_AT without ever importing faults would slip past
    # the guard and SIGKILL a LATER test's training loop
    from lightgbm_tpu import tracing as _tracing  # noqa: F401 — same
    # deal for the flight recorder (ISSUE 16): importing registers the
    # trace-recorder probe, so a test that leaves the recorder armed —
    # a later test's serving/training events silently filing into a
    # foreign ring and foreign percentile sketches — fails here and is
    # disarmed by the probe's closer (which also flushes any configured
    # dump dir)
    from lightgbm_tpu import lifecycle as _lifecycle
    leaked_objects = _lifecycle.leaks()
    for _kind, _name, _closer in leaked_objects:
        try:
            _closer()
        except Exception:
            pass
    telemetry.disable()
    telemetry.reset()
    # ISSUE 9 surface: a test that enters ``with mesh:`` and leaks it
    # (an exception before __exit__, a kept generator) leaves a global
    # mesh context installed — later tests' jit'd reductions silently
    # become GSPMD-partitioned over it, breaking the serial growers'
    # bit-identity pins in ways that only reproduce under THIS test
    # order.  The learners never install a global mesh (shard_map takes
    # the mesh explicitly), so any non-default mesh here is a leak.
    leaked_mesh = None
    try:
        from jax._src import mesh as _mesh_lib
        env_mesh = _mesh_lib.thread_resources.env.physical_mesh
        if not env_mesh.empty:
            leaked_mesh = env_mesh
            _mesh_lib.thread_resources.env = _mesh_lib.EMPTY_ENV
    except (ImportError, AttributeError):  # pragma: no cover - jax drift
        pass
    assert not (leaked_enabled or leaked_sink or leaked_timeline
                or leaked_census or leaked_objects
                or leaked_mesh is not None), (
        "test left %s — clean up (telemetry.disable() / end_census() / "
        "close()/disarm the leaked object / exit the mesh context, or "
        "use a fixture) so state cannot leak between tests"
        % ("live lifecycle registrations: %s"
           % ", ".join(sorted("%s(%s)" % (k, n)
                              for k, n, _c in leaked_objects))
           if leaked_objects
           else "telemetry in timeline/shard mode" if leaked_timeline
           else "graftlint trace-census armed" if leaked_census
           else "telemetry enabled with an open sink" if leaked_sink
           else "telemetry enabled" if leaked_enabled
           else "a global mesh context installed (%r)" % (leaked_mesh,)))


@pytest.fixture(scope="session")
def reference_binary():
    """Compile the reference from source once per session (differential
    oracle, SURVEY §4); skip when source/toolchain are unavailable."""
    if os.path.exists(REFERENCE_BINARY):
        return REFERENCE_BINARY
    if not os.path.isdir(os.path.join(REFERENCE_SRC, "src")):
        pytest.skip("reference source not available")
    if shutil.which("cmake") is None or shutil.which("make") is None:
        pytest.skip("no native toolchain")
    shutil.copytree(REFERENCE_SRC, REFERENCE_BUILD, dirs_exist_ok=True,
                    ignore=shutil.ignore_patterns(".git", "windows"))
    bdir = os.path.join(REFERENCE_BUILD, "build")
    os.makedirs(bdir, exist_ok=True)
    try:
        subprocess.run(["cmake", "..", "-DCMAKE_BUILD_TYPE=Release"],
                       cwd=bdir, check=True, capture_output=True)
        subprocess.run(["make", f"-j{os.cpu_count()}"], cwd=bdir,
                       check=True, capture_output=True)
    except subprocess.CalledProcessError as e:  # pragma: no cover
        pytest.skip(f"reference build failed: {e.stderr[-500:]}")
    assert os.path.exists(REFERENCE_BINARY)
    return REFERENCE_BINARY


@pytest.fixture(scope="session")
def binary_example_paths():
    base = os.path.join(REFERENCE_EXAMPLES, "binary_classification")
    if not os.path.isdir(base):
        pytest.skip("reference examples not available")
    return {
        "train": os.path.join(base, "binary.train"),
        "test": os.path.join(base, "binary.test"),
        "train_conf": os.path.join(base, "train.conf"),
        "predict_conf": os.path.join(base, "predict.conf"),
    }


@pytest.fixture()
def synthetic_binary():
    """Small deterministic binary-classification dataset."""
    rng = np.random.RandomState(7)
    n, f = 2000, 12
    x = rng.randn(n, f)
    logits = x[:, 0] * 1.5 - x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    y = (logits + rng.randn(n) * 0.5 > 0).astype(np.float32)
    return x, y


@pytest.fixture()
def synthetic_regression():
    rng = np.random.RandomState(11)
    n, f = 1500, 8
    x = rng.randn(n, f)
    y = (2.0 * x[:, 0] - x[:, 1] + 0.3 * x[:, 2] ** 2
         + rng.randn(n) * 0.1).astype(np.float32)
    return x, y
