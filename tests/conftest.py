"""Test configuration: force an 8-device virtual CPU platform so sharding
and parallel-learner tests run without TPU hardware (SURVEY.md §4).

Note: the environment's sitecustomize imports jax before pytest starts, so
plain env vars are too late — use jax.config.update, which takes effect any
time before backend initialization.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

REFERENCE_EXAMPLES = "/root/reference/examples"


@pytest.fixture(scope="session")
def binary_example_paths():
    base = os.path.join(REFERENCE_EXAMPLES, "binary_classification")
    if not os.path.isdir(base):
        pytest.skip("reference examples not available")
    return {
        "train": os.path.join(base, "binary.train"),
        "test": os.path.join(base, "binary.test"),
        "train_conf": os.path.join(base, "train.conf"),
        "predict_conf": os.path.join(base, "predict.conf"),
    }


@pytest.fixture()
def synthetic_binary():
    """Small deterministic binary-classification dataset."""
    rng = np.random.RandomState(7)
    n, f = 2000, 12
    x = rng.randn(n, f)
    logits = x[:, 0] * 1.5 - x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    y = (logits + rng.randn(n) * 0.5 > 0).astype(np.float32)
    return x, y


@pytest.fixture()
def synthetic_regression():
    rng = np.random.RandomState(11)
    n, f = 1500, 8
    x = rng.randn(n, f)
    y = (2.0 * x[:, 0] - x[:, 1] + 0.3 * x[:, 2] ** 2
         + rng.randn(n) * 0.1).astype(np.float32)
    return x, y
