"""graftlint (ISSUE 10): golden bad-code fixtures per rule, the clean-tree
tier-1 gate, and the J2 census cross-check against the (2,2)-mesh dryrun
programs.

Three layers of pins:

1. **Golden fixtures** — for every rule (R1-R4, J1-J2) a minimal bad
   module/program makes the rule fire with the right rule id and
   ``path:line``, and a minimally-corrected twin stays clean — the rules
   detect the defect CLASS, not an incidental pattern of today's tree.
2. **Clean tree** — the AST layer over the shipped package and the jaxpr
   layer over the canonical small-schema programs produce ZERO findings
   against the committed (empty) GRAFTLINT_BASELINE.json.  This is the
   tier-1 integration the pre-merge ``scripts/graftlint.py --check``
   mirrors; jaxpr traces are cached per session (driver lru_cache), so
   the layer prices one trace pass per pytest run.
3. **Census cross-check** (ISSUE 10 acceptance) — the jaxpr collective
   census of the (2,2)-mesh data/hybrid/voting grow programs agrees with
   the telemetry wire-site inventory recorded while tracing them (the
   same inventory ``__graft_entry__.measure_wire_bytes`` prices and
   perf_gate gates), and with any recorded MULTICHIP_WIRE site inventory
   found in MULTICHIP_r*.json.
"""
import glob
import json
import os
import subprocess
import sys
import textwrap

import pytest

from lightgbm_tpu.analysis import (Baseline, GraftlintError, LintConfig,
                                   RULES, default_baseline_path,
                                   run_ast_rules)
from lightgbm_tpu.analysis import driver as gl_driver
from lightgbm_tpu.analysis.findings import Finding, split_baseline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, path="fixture.py", **cfg):
    return run_ast_rules({path: textwrap.dedent(src)},
                         LintConfig(**cfg) if cfg else None)


# ===================================================== R1: seam coverage

R1_BAD = """
import jax

def leaf_sum(x, axis):
    return jax.lax.psum(x, axis)
"""

R1_OK = """
import functools
import jax
from lightgbm_tpu import telemetry

_c = functools.partial(telemetry.collective_span, axis="data")

def build(site):
    def seam(h):
        return jax.lax.psum(h, "data")
    wrapped = _c(site, seam, kind="psum")
    other = telemetry.collective_span(
        "s2", lambda h: jax.lax.psum_scatter(h, "data"), kind="psum_scatter")
    return wrapped, other

def recorded(x):
    telemetry.record_collective("site", "pmax", "data", 4)
    return jax.lax.pmax(x, "data")
"""


def test_r1_fires_on_raw_collective():
    (f,) = _lint(R1_BAD)
    assert f.rule == "R1" and f.path == "fixture.py" and f.line == 5
    assert f.site == "lax.psum" and f.symbol == "leaf_sum"


def test_r1_clean_on_all_three_coverage_forms():
    # partial-alias wrap, direct collective_span lambda, record_collective
    assert _lint(R1_OK) == []


R1_NAME_COLLISION = """
import jax
from lightgbm_tpu import telemetry

def wrapped_home():
    def _reduce(h):
        return jax.lax.psum(h, "data")
    return telemetry.collective_span("site", _reduce, kind="psum")

def unwrapped_home():
    def _reduce(h):
        return jax.lax.psum(h, "data")
    return _reduce
"""


def test_r1_wrap_coverage_is_scope_local_not_name_global():
    # a wrapped function name in one scope must not cover a same-named
    # unwrapped function elsewhere in the module
    (f,) = _lint(R1_NAME_COLLISION)
    assert f.rule == "R1" and f.symbol == "unwrapped_home._reduce"
    assert f.line == 12


# ================================================ R2: cache-key complete

R2_BAD = """
from lightgbm_tpu.ops.compact import partition_overlap_on
_MY_PROGRAMS = {}

def get_program(n):
    overlap = partition_overlap_on()
    key = (n,)
    prog = _MY_PROGRAMS.get(key)
    if prog is None:
        prog = make(n, overlap)
        _MY_PROGRAMS[key] = prog
    return prog
"""

R2_OK = """
from lightgbm_tpu.ops.compact import partition_overlap_on
_MY_PROGRAMS = {}

def get_program(n):
    use_pp = n > 2 and partition_overlap_on()
    key = (n, use_pp)
    prog = _MY_PROGRAMS.get(key)
    if prog is None:
        prog = make(n, use_pp)
        _MY_PROGRAMS[key] = prog
    return prog
"""

R2_READ_BAD = """
_MY_PROGRAMS = {}

def get_program(self, n):
    mesh = make_mesh(getattr(self.config, "device_type", ""))
    key = (n, mesh.size)
    _MY_PROGRAMS[key] = build(mesh)
    return _MY_PROGRAMS[key]
"""


def test_r2_fires_on_key_missing_resolved_call():
    (f,) = _lint(R2_BAD)
    assert f.rule == "R2" and f.site == "partition_overlap_on()"
    assert f.symbol == "get_program" and f.line == 6


def test_r2_clean_when_key_carries_the_bit_through_a_local():
    assert _lint(R2_OK) == []


def test_r2_fires_on_laundered_device_type_read():
    # mesh.size DERIVES from device_type but loses its identity — two
    # backends with equal device counts would collide on the key (the
    # exact FP chunk-program gap this PR fixed in parallel/learners.py)
    (f,) = _lint(R2_READ_BAD)
    assert f.rule == "R2" and f.site == "device_type"


# the booster's resolved mixed-bin layout spec is a cache-key bit like
# the kernel-routing flags (ISSUE 12): the traced program bakes the
# per-class histogram pass structure (and, block-locally, the canonical
# reorder gathers) in, so a cached program built while reading
# ``_pack_spec`` must thread the spec (or a digest) into its key
R2_PACK_BAD = """
_MY_PROGRAMS = {}

def get_program(self, gbdt, n):
    packing = getattr(gbdt, "_pack_spec", None)
    key = (n,)
    _MY_PROGRAMS[key] = build(n, packing)
    return _MY_PROGRAMS[key]
"""

R2_PACK_OK = """
_MY_PROGRAMS = {}

def get_program(self, gbdt, n):
    packing = getattr(gbdt, "_pack_spec", None)
    key = (n, packing)
    _MY_PROGRAMS[key] = build(n, packing)
    return _MY_PROGRAMS[key]
"""


def test_r2_fires_on_unkeyed_pack_spec_read():
    (f,) = _lint(R2_PACK_BAD)
    assert f.rule == "R2" and f.site == "_pack_spec"
    assert f.symbol == "get_program"


def test_r2_clean_when_pack_spec_rides_the_key():
    assert _lint(R2_PACK_OK) == []


# ======================================================= R3: span fences

R3_BAD = """
from lightgbm_tpu import telemetry

def predict(prog, x):
    with telemetry.span("predict"):
        return prog(x)
"""

R3_OK = """
from lightgbm_tpu import telemetry

def predict(prog, x):
    with telemetry.span("predict") as sp:
        return sp.fence(prog(x))

def readback(dev):
    with telemetry.span("model_readback"):
        return fetch(dev)
"""


def test_r3_fires_on_unfenced_device_span():
    (f,) = _lint(R3_BAD)
    assert f.rule == "R3" and f.line == 5 and f.site == "span('predict')"


def test_r3_clean_when_fenced_and_for_host_spans():
    assert _lint(R3_OK) == []


# ============================================ R4: banned in traced code

R4_BAD = """
import numpy as np
import time
import jax.numpy as jnp

def traced(x):
    t = time.time()
    r = np.random.rand(4)
    y = x.astype(jnp.float64)
    return t, r, y

def sized(n):
    return jnp.zeros((n,), dtype="float64")
"""


def test_r4_fires_on_each_banned_pattern():
    found = _lint(R4_BAD, path="fix_r4.py",
                  traced_suffixes=("fix_r4.py",))
    sites = {f.site for f in found}
    assert all(f.rule == "R4" for f in found)
    assert "time.time" in sites
    assert "np.random.rand" in sites
    assert "jnp.float64" in sites
    assert 'dtype="float64"' in sites


def test_r4_scoped_to_traced_modules_only():
    # same source outside the traced-module set is host-side code
    assert _lint(R4_BAD, path="host_helper.py",
                 traced_suffixes=("fix_r4.py",)) == []


R4_NESTED = """
import numpy as np

def outer(x):
    def inner(y):
        return np.sum(y)
    return inner(x)
"""


def test_r4_reports_nested_closure_violations_exactly_once():
    # one violation inside a nested closure must yield ONE finding,
    # attributed to the innermost function — not once per enclosing level
    found = _lint(R4_NESTED, path="fix_r4.py",
                  traced_suffixes=("fix_r4.py",))
    assert len(found) == 1
    assert found[0].symbol == "outer.inner" and found[0].site == "np.sum"


# ============================================ J1: jaxpr dtype discipline

@pytest.fixture(scope="module")
def jax_mod():
    import jax
    import jax.numpy as jnp
    return jax, jnp


def test_j1_fires_on_float_contamination_of_int_chain(jax_mod):
    jax, jnp = jax_mod
    from lightgbm_tpu.analysis.jaxpr_rules import check_dtype_discipline

    def bad(v):
        f = v.astype(jnp.float32)       # int8 -> f32: contamination
        return jax.lax.psum(f.astype(jnp.int32), "data")

    jaxpr = jax.make_jaxpr(bad, axis_env=[("data", 2)])(
        jnp.zeros((4,), jnp.int8))
    found = check_dtype_discipline(jaxpr, program="fix/int_chain",
                                   feature_width=12, bin_width=16)
    assert any(f.rule == "J1" and "float conversion" in f.message
               for f in found)


def test_j1_follows_contamination_across_a_loop_carry(jax_mod):
    # the int8 accumulator psum lives inside scan/fori bodies in the real
    # programs — contamination introduced OUTSIDE and carried in must
    # still be caught (backward slice follows sub-jaxpr invar bindings
    # out to the enclosing eqn's operands)
    jax, jnp = jax_mod
    from lightgbm_tpu.analysis.jaxpr_rules import check_dtype_discipline

    def bad(v):
        poisoned = v.astype(jnp.float32).astype(jnp.int32)

        def body(carry, _):
            return jax.lax.psum(carry, "data"), None

        out, _ = jax.lax.scan(body, poisoned, None, length=2)
        return out

    jaxpr = jax.make_jaxpr(bad, axis_env=[("data", 2)])(
        jnp.zeros((4,), jnp.int8))
    found = check_dtype_discipline(jaxpr, program="fix/carry",
                                   feature_width=12, bin_width=16)
    assert any(f.rule == "J1" and "float conversion" in f.message
               for f in found)


def test_j1_clean_on_pure_int_chain_with_quantize_boundary(jax_mod):
    jax, jnp = jax_mod
    from lightgbm_tpu.analysis.jaxpr_rules import check_dtype_discipline

    def good(g):
        q = jnp.clip(jnp.round(g * 4.0), -127, 127).astype(jnp.int8)
        return jax.lax.psum(q.astype(jnp.int32), "data")

    jaxpr = jax.make_jaxpr(good, axis_env=[("data", 2)])(
        jnp.zeros((4,), jnp.float32))
    assert check_dtype_discipline(jaxpr, program="fix/quantized",
                                  feature_width=12, bin_width=16) == []


def test_j1_fires_on_id_narrowing_below_global_width(jax_mod):
    jax, jnp = jax_mod
    from lightgbm_tpu.analysis.jaxpr_rules import check_dtype_discipline

    def bad(ids):
        return ids.astype(jnp.bfloat16)   # 256-exact < F_global=300

    jaxpr = jax.make_jaxpr(bad)(jnp.zeros((4,), jnp.int32))
    found = check_dtype_discipline(jaxpr, program="fix/narrow",
                                   feature_width=300, bin_width=16)
    assert any(f.rule == "J1" and "narrowing" in f.message for f in found)
    # the same convert is SAFE when the global width fits bf16 exactly
    assert check_dtype_discipline(jaxpr, program="fix/narrow_ok",
                                  feature_width=28, bin_width=255) == []


# =========================================== J2: jaxpr collective census

def test_j2_fires_on_unwrapped_collective(jax_mod):
    jax, jnp = jax_mod
    from lightgbm_tpu.analysis.jaxpr_rules import (check_collective_census,
                                                   trace_census)

    def raw(x):
        return jax.lax.psum(x, "data")

    with trace_census() as holder:
        jaxpr = jax.make_jaxpr(raw, axis_env=[("data", 2)])(jnp.zeros((4,)))
    found = check_collective_census("fix/raw", jaxpr, holder.sites)
    assert any(f.rule == "J2" and f.site == "psum"
               and "ZERO declared" in f.message for f in found)


def test_j2_generic_reduce_covers_only_reduction_kinds(jax_mod):
    # wrap_schedule's fallback kind="reduce" may stand in for psum/pmax —
    # never for an all_gather, and a generic record with NO reduction
    # eqns at all is itself stale
    jax, jnp = jax_mod
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.analysis.jaxpr_rules import (check_collective_census,
                                                   trace_census)

    def gathered(x):
        telemetry.record_collective("seam", "reduce", "data", 4)
        return jax.lax.all_gather(x, "data")

    with trace_census() as holder:
        jaxpr = jax.make_jaxpr(gathered, axis_env=[("data", 2)])(
            jnp.zeros((4,)))
    found = check_collective_census("fix/generic", jaxpr, holder.sites)
    assert any(f.rule == "J2" and f.site == "all_gather" for f in found)

    def no_collectives(x):
        telemetry.record_collective("seam", "reduce", "data", 4)
        return x + 1.0

    with trace_census() as holder:
        jaxpr = jax.make_jaxpr(no_collectives)(jnp.zeros((4,)))
    found = check_collective_census("fix/generic_stale", jaxpr,
                                    holder.sites)
    assert any(f.rule == "J2" and f.site == "reduce" for f in found)

    def reduced(x):
        telemetry.record_collective("seam", "reduce", "data", 4)
        return jax.lax.psum(x, "data")

    with trace_census() as holder:
        jaxpr = jax.make_jaxpr(reduced, axis_env=[("data", 2)])(
            jnp.zeros((4,)))
    assert check_collective_census("fix/generic_ok", jaxpr,
                                   holder.sites) == []


def test_j2_fires_on_stale_declared_site(jax_mod):
    jax, jnp = jax_mod
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.analysis.jaxpr_rules import (check_collective_census,
                                                   trace_census)

    def stale(x):
        telemetry.record_collective("ghost", "all_gather", "data", 4)
        return x + 1.0

    with trace_census() as holder:
        jaxpr = jax.make_jaxpr(stale)(jnp.zeros((4,)))
    found = check_collective_census("fix/stale", jaxpr, holder.sites)
    assert any(f.rule == "J2" and f.site == "all_gather"
               and "contains none" in f.message for f in found)


def test_trace_census_restores_telemetry_state(jax_mod):
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.analysis import jaxpr_rules
    assert not telemetry.enabled()
    jaxpr_rules.begin_census()
    assert jaxpr_rules.trace_census_active() and telemetry.enabled()
    with pytest.raises(RuntimeError):
        jaxpr_rules.begin_census()     # unbalanced arming is loud
    jaxpr_rules.end_census()
    assert not jaxpr_rules.trace_census_active()
    assert not telemetry.enabled()


def test_trace_census_refuses_to_destroy_a_live_registry(jax_mod):
    # arming over an enabled telemetry session would reset (lose) its
    # accumulated counters/sites — refuse loudly instead
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.analysis import jaxpr_rules
    telemetry.enable()
    try:
        with pytest.raises(RuntimeError, match="already enabled"):
            jaxpr_rules.begin_census()
        assert not jaxpr_rules.trace_census_active()
    finally:
        telemetry.disable()
        telemetry.reset()


# ============================== C1-C4: concurrency-lifecycle (ISSUE 15)

from lightgbm_tpu.analysis.concurrency_rules import (ConcurrencyConfig,
                                                     run_concurrency_rules)


def _clint(src, path="fix_c.py", **cfg):
    return run_concurrency_rules(
        {path: textwrap.dedent(src)},
        ConcurrencyConfig(**cfg) if cfg else ConcurrencyConfig(
            hatch_inventory=set()))


C1_BAD_CLASS = """
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def close(self):
        self._t.join()
"""

C1_BAD_NO_CLOSE = """
import threading

class FireAndForget:
    def __init__(self):
        threading.Thread(target=self._run, daemon=True).start()
"""

C1_OK_CLASS = """
import threading
from lightgbm_tpu import lifecycle

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        lifecycle.track("pump", self, self.close)
        self._t.start()

    def close(self):
        self._t.join()
        lifecycle.untrack(self)
"""

C1_BAD_BARE = """
import threading

def prefetch(it):
    threading.Thread(target=lambda: list(it), daemon=True).start()
"""

C1_OK_BARE = """
import threading
from lightgbm_tpu import lifecycle

def prefetch(it):
    t = threading.Thread(target=lambda: list(it), daemon=True)
    lifecycle.track("prefetch", t, t.join)
    t.start()
"""


def test_c1_fires_on_unregistered_thread_class():
    (f,) = _clint(C1_BAD_CLASS)
    assert f.rule == "C1" and f.line == 6
    assert "lifecycle.track" in f.message and "Pump" in f.message


def test_c1_fires_on_class_without_close_entry_point():
    (f,) = _clint(C1_BAD_NO_CLOSE)
    assert f.rule == "C1" and "close" in f.message


def test_c1_clean_on_registered_class_with_close():
    assert _clint(C1_OK_CLASS) == []


def test_c1_bare_function_spawn_needs_track_in_same_function():
    (f,) = _clint(C1_BAD_BARE)
    assert f.rule == "C1" and f.symbol == "prefetch"
    assert _clint(C1_OK_BARE) == []


C2_BAD = """
def deliver(batch, scores):
    ofs = 0
    for r in batch:
        if not r.future.cancelled():
            r.future.set_result(scores[:, ofs:ofs + r.rows])
        ofs += r.rows
"""

C2_OK = """
def deliver(batch, scores):
    ofs = 0
    for r in batch:
        try:
            if not r.future.cancelled():
                r.future.set_result(scores[:, ofs:ofs + r.rows])
        except Exception:
            pass
        ofs += r.rows

def fail(batch, e):
    for r in batch:
        try:
            r.future.set_exception(e)
        except (RuntimeError, InvalidStateError):
            pass
"""


def test_c2_fires_on_unguarded_future_set():
    # the cancelled() pre-check is NOT enough: the check->set window IS
    # the race (the exact PR 13 ServingFront bug, generalized)
    (f,) = _clint(C2_BAD)
    assert f.rule == "C2" and f.line == 6 and f.site == ".set_result"


def test_c2_clean_when_set_rides_an_absorbing_try():
    assert _clint(C2_OK) == []


C3_BAD = """
import time

class Front:
    def flush(self):
        with self._cond:
            self._cond.wait(0.05)
            self._thread.join()
            time.sleep(0.5)
            data = open(self.path).read()
            self._queue.put(data)
        return data
"""

C3_OK = """
class Front:
    def flush(self):
        with self._cond:
            while self._pending is None and not self._closing:
                self._cond.wait()
            item, self._pending = self._pending, None
            self._cond.notify_all()
        self._io.write(item)
        self._thread.join()

    def drain(self):
        with self._cond:
            self._queue.put(1, timeout=0.1)
            got = self._table.get("key")
"""


def test_c3_fires_on_each_blocking_op_under_the_lock():
    found = _clint(C3_BAD)
    sites = {f.site for f in found}
    assert all(f.rule == "C3" for f in found)
    assert {"self._thread.join", "time.sleep", "open",
            "self._queue.put"} <= sites
    # cv.wait on the lock object itself is exempt (wait RELEASES it)
    assert not any("cond" in s for s in sites)


def test_c3_clean_on_lock_waits_timed_queue_ops_and_outside_io():
    assert _clint(C3_OK) == []


C4_BAD_RAW = """
import os

def no_pallas():
    return os.environ.get("LGBM_TPU_NO_PALLAS", "") == "1"
"""

C4_BAD_ALIAS = """
import os
ENV_VAR = "LGBM_TPU_FAULT_AT"

def spec():
    return os.environ.get(ENV_VAR)
"""

C4_BAD_UNREGISTERED = """
from lightgbm_tpu import hatches

def ghost():
    return hatches.flag("LGBM_TPU_GHOST")
"""

C4_OK = """
from lightgbm_tpu import hatches

def no_pallas():
    return hatches.flag("LGBM_TPU_NO_PALLAS")
"""


def test_c4_fires_on_raw_env_read():
    (f,) = _clint(C4_BAD_RAW)
    assert f.rule == "C4" and f.site == "LGBM_TPU_NO_PALLAS"
    assert f.line == 5


def test_c4_resolves_module_constant_aliases():
    (f,) = _clint(C4_BAD_ALIAS)
    assert f.rule == "C4" and f.site == "LGBM_TPU_FAULT_AT"


def test_c4_fires_on_helper_read_missing_from_inventory():
    (f,) = _clint(C4_BAD_UNREGISTERED,
                  hatch_inventory={"LGBM_TPU_NO_PALLAS"})
    assert f.rule == "C4" and f.site == "LGBM_TPU_GHOST"
    assert "inventory" in f.message


def test_c4_clean_on_registered_helper_read():
    assert _clint(C4_OK, hatch_inventory={"LGBM_TPU_NO_PALLAS"}) == []


def test_hatches_helper_loud_rejects(monkeypatch):
    """The runtime half of C4: a typo'd hatch VALUE must reject, not
    silently do nothing."""
    from lightgbm_tpu import hatches
    from lightgbm_tpu.utils import log
    monkeypatch.setenv("LGBM_TPU_NO_PALLAS", "true")
    with pytest.raises(log.LightGBMError):
        hatches.flag("LGBM_TPU_NO_PALLAS")
    monkeypatch.setenv("LGBM_TPU_NO_PALLAS", "1")
    assert hatches.flag("LGBM_TPU_NO_PALLAS") is True
    monkeypatch.delenv("LGBM_TPU_NO_PALLAS")
    assert hatches.flag("LGBM_TPU_NO_PALLAS") is False
    with pytest.raises(log.LightGBMError):
        hatches.flag("LGBM_TPU_UNREGISTERED_GHOST")


# =============================== D1-D3: cross-artifact drift (ISSUE 15)

from lightgbm_tpu.analysis import drift_rules


D1_FILES_OK = {
    "pkg/serving.py": textwrap.dedent("""
        from . import telemetry
        def go(n):
            telemetry.count("serve/rows", n)
            telemetry.count(f"serve/bucket_{n}")
            with telemetry.span("predict"):
                telemetry.record_collective("serve/tree_psum", "psum",
                                            "tree", 4)
    """),
}
D1_INV_OK = {
    "counter": ("serve/rows", "serve/bucket_*"),
    "span": ("predict",),
    "wire": ("serve/tree_psum",),
    "dynamic": (),
}


def test_d1_clean_when_census_matches_inventory():
    assert drift_rules.check_telemetry_inventory(
        D1_FILES_OK, D1_INV_OK, telemetry_path="pkg/telemetry.py") == []


def test_d1_fires_on_undocumented_usage():
    # deleting a documented family line makes the census fire — the
    # acceptance-criteria liveness direction
    inv = dict(D1_INV_OK, counter=("serve/bucket_*",))
    found = drift_rules.check_telemetry_inventory(
        D1_FILES_OK, inv, telemetry_path="pkg/telemetry.py")
    assert any(f.rule == "D1" and f.site == "serve/rows"
               and f.path == "pkg/serving.py" and f.line == 4
               for f in found)


def test_d1_fires_on_stale_documentation():
    inv = dict(D1_INV_OK, span=("predict", "ghost_span"))
    found = drift_rules.check_telemetry_inventory(
        D1_FILES_OK, inv, telemetry_path="pkg/telemetry.py")
    assert any(f.rule == "D1" and f.site == "ghost_span"
               and "stale" in f.message for f in found)


def test_d1_real_inventory_census_is_live():
    """Acceptance: deleting any one STATIC documented telemetry family
    line from the real inventory makes the census (and therefore
    ``--check``) flag it."""
    from lightgbm_tpu import telemetry
    files = {p: open(p).read()
             for p in glob.glob(os.path.join(
                 REPO, "lightgbm_tpu", "**", "*.py"), recursive=True)}
    tel_path = next(p for p in files if p.endswith("telemetry.py"))
    for dropped in ("serve/swaps", "ckpt/written"):
        inv = {
            "counter": tuple(n for n in telemetry.COUNTER_FAMILIES
                             if n != dropped),
            "span": telemetry.SPAN_FAMILIES,
            "wire": telemetry.WIRE_SITE_FAMILIES,
            "dynamic": telemetry.DYNAMIC_WIRE_SITES,
        }
        found = drift_rules.check_telemetry_inventory(
            files, inv, telemetry_path=tel_path)
        assert any(f.rule == "D1" and f.site == dropped
                   for f in found), dropped


D2_GATES_OK = {
    "RATE_KEYS": (("value", "spread"), ("x_rows_per_sec", "x_spread")),
    "LATENCY_KEYS": (("x_p99_us", "x_spread"),),
    "ABSOLUTE_ZERO_KEYS": (("x_recompiles", "d"),),
    "ABSOLUTE_TRUE_KEYS": (("x_restore_exact", "d"),),
    "_source": "",
}
D2_BENCH_OK = ('out = {"value": 1, "spread": 0, "x_rows_per_sec": 2,'
               ' "x_spread": 0, "x_p99_us": 3, "x_recompiles": 0,'
               ' "x_restore_exact": True}')


def test_d2_clean_when_gates_cover_emissions():
    assert drift_rules.check_perf_gate_coverage(
        D2_GATES_OK, D2_BENCH_OK, informational={}) == []


def test_d2_fires_on_stale_gate_key():
    gates = dict(D2_GATES_OK,
                 RATE_KEYS=D2_GATES_OK["RATE_KEYS"]
                 + (("ghost_rows_per_sec", "ghost_spread"),))
    found = drift_rules.check_perf_gate_coverage(gates, D2_BENCH_OK,
                                                 informational={})
    assert {f.site for f in found} == {"ghost_rows_per_sec",
                                       "ghost_spread"}
    assert all("gates nothing" in f.message for f in found)


def test_d2_fires_on_ungated_emission():
    # deleting a gate key whose lane bench still emits — the acceptance
    # liveness direction
    gates = dict(D2_GATES_OK, RATE_KEYS=(("value", "spread"),),
                 LATENCY_KEYS=())
    found = drift_rules.check_perf_gate_coverage(gates, D2_BENCH_OK,
                                                 informational={})
    sites = {f.site for f in found}
    assert {"x_rows_per_sec", "x_spread", "x_p99_us"} <= sites


def test_d2_real_gate_census_is_live():
    """Acceptance: deleting any one perf_gate key pair while bench.py
    still emits the lane makes the census flag it."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_pg_test", os.path.join(REPO, "scripts", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    bench_src = open(os.path.join(REPO, "bench.py")).read()
    gates = {
        "RATE_KEYS": tuple(p for p in pg.RATE_KEYS
                           if p[0] != "ingest_rows_per_sec"),
        "LATENCY_KEYS": pg.LATENCY_KEYS,
        "ABSOLUTE_ZERO_KEYS": pg.ABSOLUTE_ZERO_KEYS,
        "ABSOLUTE_TRUE_KEYS": pg.ABSOLUTE_TRUE_KEYS,
        "_source": "",
    }
    found = drift_rules.check_perf_gate_coverage(gates, bench_src)
    assert any(f.rule == "D2" and f.site == "ingest_rows_per_sec"
               for f in found)


D3_CONFIG_OK = """
import dataclasses
from .utils import log

@dataclasses.dataclass
class IOConfig:
    max_bin: int = 256
    mode: str = "auto"

    def set(self, params):
        self.max_bin = _get_int(params, "max_bin", self.max_bin)
        if "mode" in params:
            value = params["mode"].lower()
            log.check(value in ("auto", "x"), "mode must be auto or x")
            self.mode = value
"""

D3_CLI_OK = """
KNOB_INVENTORY = {
    "max_bin": "max bins per feature",
    "mode": "auto or x",
}
"""


def test_d3_clean_on_matching_inventory():
    assert drift_rules.check_knob_inventory(
        textwrap.dedent(D3_CONFIG_OK), textwrap.dedent(D3_CLI_OK),
        freeform={}, internal={}) == []


def test_d3_fires_on_undocumented_knob_and_stale_entry():
    cli = 'KNOB_INVENTORY = {"max_bin": "x", "ghost_knob": "gone"}'
    found = drift_rules.check_knob_inventory(
        textwrap.dedent(D3_CONFIG_OK), cli, freeform={}, internal={})
    sites = {(f.site, f.symbol) for f in found}
    assert ("mode", "set") in sites          # undocumented knob
    assert ("ghost_knob", "cli") in sites    # stale inventory entry


def test_d3_fires_on_unvalidated_knob_and_unreachable_field():
    src = """
import dataclasses

@dataclasses.dataclass
class IOConfig:
    path: str = ""
    orphan: int = 0

    def set(self, params):
        self.path = _get_str(params, "path", self.path)
"""
    cli = 'KNOB_INVENTORY = {"path": "a path"}'
    found = drift_rules.check_knob_inventory(
        textwrap.dedent(src), cli, freeform={}, internal={})
    assert any(f.site == "path" and "silently" in f.message
               for f in found)
    assert any(f.site == "orphan" and "unreachable" in f.message
               for f in found)
    # the same free-form knob with a written justification passes
    found2 = drift_rules.check_knob_inventory(
        textwrap.dedent(src), cli,
        freeform={"path": "output path; open() surfaces failures"},
        internal={"orphan": "derived"})
    assert found2 == []


# ==================== tier-1 gates: layers 3a/3b clean on the tree

def test_concurrency_layer_clean_on_shipped_tree():
    """The tier-1 C-rule gate: zero findings over the whole package
    against the committed (empty) baseline — the in-suite mirror of
    ``python scripts/graftlint.py --concurrency-only``."""
    baseline = Baseline.load(default_baseline_path())
    findings, _sup = split_baseline(
        gl_driver.run_concurrency_layer(), baseline)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_drift_layer_clean_on_shipped_tree():
    """The tier-1 D-rule gate: the telemetry inventory, perf_gate key
    coverage and CLI knob inventory all census clean."""
    baseline = Baseline.load(default_baseline_path())
    findings, _sup = split_baseline(gl_driver.run_drift_layer(), baseline)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_graftlint_script_all_four_layers_exit_zero():
    """ISSUE 15 acceptance: ``scripts/graftlint.py --check`` exits 0
    over ast+jaxpr+concurrency+drift with the EMPTY committed
    baseline."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         "--check"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ast+jaxpr+concurrency+drift" in r.stdout


def test_stale_baseline_reported_for_new_rule_ids(tmp_path):
    """The stale-suppression finding covers the C/D ids too: an entry
    naming a C1/D2 site that matches nothing must flag."""
    bad = tmp_path / "stale_cd.json"
    bad.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "C1", "path": "nowhere.py", "symbol": "ghost",
         "justification": "obsolete"},
        {"rule": "D2", "path": "bench.py", "symbol": "bench",
         "site": "ghost_rows_per_sec", "justification": "obsolete"}]}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         "--concurrency-only", "--drift-only", "--baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert r.stdout.count("STALE BASELINE") == 2


# ================== shared lifecycle inventory (ISSUE 15 satellite)

def test_lifecycle_tracks_and_reports_leaks():
    from lightgbm_tpu import lifecycle

    class Obj:
        closed = False

        def close(self):
            self.closed = True
            lifecycle.untrack(self)

    o = Obj()
    lifecycle.track("test-kind", o, o.close)
    assert lifecycle.live_count("test-kind") == 1
    assert any(k == "test-kind" for k, _n, _c in lifecycle.leaks())
    o.close()
    assert lifecycle.live_count("test-kind") == 0
    lifecycle.untrack(o)                      # idempotent


def test_lifecycle_sees_leaked_checkpoint_writer(tmp_path):
    """The conftest guard's new single read: a CheckpointWriter left
    open appears in lifecycle.leaks() under its kind, and its closer
    reaps it."""
    from lightgbm_tpu import checkpoint as ckpt
    from lightgbm_tpu import lifecycle
    w = ckpt.CheckpointWriter(str(tmp_path))
    assert ckpt.live_writers() == 1
    leak = [e for e in lifecycle.leaks() if e[0] == ckpt.WRITER_KIND]
    assert len(leak) == 1
    leak[0][2]()                              # the guard's cleanup path
    assert ckpt.live_writers() == 0 and not w.alive


def test_lifecycle_sees_armed_fault_probe(monkeypatch):
    from lightgbm_tpu import faults, lifecycle
    faults.arm(3, "stall")
    try:
        assert any(k == "fault-hatch" for k, _n, _c in lifecycle.leaks())
    finally:
        faults.clear()
    assert not any(k == "fault-hatch" for k, _n, _c in lifecycle.leaks())


def test_prefetch_thread_registers_and_deregisters():
    from lightgbm_tpu import lifecycle
    from lightgbm_tpu.io import parser

    gen = parser.prefetch_chunks(iter([[1], [2], [3]]))
    assert next(gen) == [1]
    # early drop: the generator's finally must stop AND deregister
    gen.close()
    assert lifecycle.live_count("prefetch") == 0
    # full drain deregisters too
    assert list(parser.prefetch_chunks(iter([[4], [5]]))) == [[4], [5]]
    assert lifecycle.live_count("prefetch") == 0


# ================================== baseline / suppression mechanics

def test_baseline_suppresses_and_reports_stale(tmp_path):
    f = Finding("R1", "lightgbm_tpu/foo.py", 10, "fn", "lax.psum", "m")
    base = Baseline([
        {"rule": "R1", "path": "foo.py", "symbol": "fn",
         "site": "lax.psum", "justification": "measured, deliberate"},
        {"rule": "R3", "path": "gone.py", "symbol": "x",
         "justification": "stale"},
    ])
    kept, suppressed = split_baseline([f], base)
    assert kept == [] and suppressed == [f]
    assert [e["path"] for e in base.stale_entries()] == ["gone.py"]
    p = tmp_path / "b.json"
    base.save(str(p))
    loaded = Baseline.load(str(p))
    assert len(loaded.entries) == 2


def test_baseline_rejects_entries_without_justification(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"suppressions": [
        {"rule": "R1", "path": "x.py", "symbol": "f"}]}))
    with pytest.raises(ValueError):
        Baseline.load(str(p))


def test_rule_catalog_covers_every_rule_id():
    assert set(RULES) == {"R1", "R2", "R3", "R4", "J1", "J2",
                          "C1", "C2", "C3", "C4", "D1", "D2", "D3"}
    for title, hint in RULES.values():
        assert title and hint


# ====================================== tier-1 gate: the clean tree

def test_ast_layer_clean_on_shipped_tree():
    """The tier-1 AST gate: zero findings over the whole package against
    the committed baseline — the in-suite mirror of
    ``python scripts/graftlint.py --ast-only``."""
    baseline = Baseline.load(default_baseline_path())
    findings, _sup = split_baseline(gl_driver.run_ast_layer(), baseline)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert baseline.stale_entries() == []


def test_jaxpr_layer_clean_on_canonical_programs():
    """The tier-1 jaxpr gate: J1+J2 clean over the canonical small-schema
    programs (serial policies, int8 exchange, serving BFS, (2,2)-mesh
    learners).  Traces are cached per session (driver lru_cache), so the
    census cross-check below reuses this pass."""
    findings = gl_driver.run_jaxpr_layer()
    assert findings == [], "\n".join(f.format() for f in findings)


# ================== ISSUE 10 acceptance: census vs wire-site inventory

@pytest.fixture(scope="module")
def mesh22_traces():
    from lightgbm_tpu.analysis.programs import (parallel_grow_program,
                                                trace_program)
    out = {}
    for tl in ("data", "hybrid", "voting"):
        prog = parallel_grow_program(tl)
        out[tl] = trace_program(prog)
    return out


# the PR 9 seam inventory per learner on the (2,2) mesh — the same site
# names __graft_entry__._wire_smoke records into MULTICHIP_WIRE
EXPECTED_SITES = {
    "data": {"dp_psum/leafwise/hist_allreduce",
             "dp_psum/leafwise/root_hist",
             "dp_psum/leafwise/root_stats"},
    "hybrid": {"hybrid/leafwise/hist_allreduce",
               "hybrid/leafwise/root_hist",
               "hybrid/leafwise/root_stats",
               "hybrid/leafwise/splitinfo_allreduce"},
    "voting": {"voting/leafwise/votes_allgather",
               "voting/leafwise/voted_hist_allreduce",
               "voting/leafwise/splitinfo_allreduce",
               "voting/leafwise/root_votes_allgather",
               "voting/leafwise/root_voted_hist_allreduce",
               "voting/leafwise/root_splitinfo_allreduce",
               "voting/leafwise/root_stats"},
}


def test_census_agrees_with_wire_site_inventory(mesh22_traces):
    """J2 on the (2,2)-mesh dryrun programs: what XLA will execute (the
    jaxpr collective eqns) agrees with the declared wire-site inventory
    the gated MULTICHIP_WIRE model prices — per kind, presence matches
    exactly and eqns >= declared traced calls (one record may cover the
    several eqns of a tree-mapped allreduce)."""
    from lightgbm_tpu.analysis.jaxpr_rules import (check_collective_census,
                                                   collective_census,
                                                   declared_census)
    for tl, (jaxpr, sites) in mesh22_traces.items():
        assert check_collective_census("grow/%s" % tl, jaxpr, sites) == []
        assert set(sites) == EXPECTED_SITES[tl], tl
        actual = collective_census(jaxpr)
        declared = declared_census(sites)
        assert set(actual) == set(declared), tl
        for kind, n in declared.items():
            assert actual[kind] >= n, (tl, kind, dict(actual),
                                       dict(declared))


def test_census_matches_recorded_multichip_wire_rows():
    """Cross-check against the RECORDED MULTICHIP trajectory: wherever a
    MULTICHIP_r*.json round carries a MULTICHIP_WIRE line (PR 9 onward),
    its per-learner site names must be a superset of the canonical grow
    programs' declared inventory — the gated wire-byte model and the
    census can never silently diverge.  Rounds without the line (r01-r05
    predate the smoke) are skipped by design."""
    import re
    rows = []
    for path in sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json"))):
        with open(path) as fh:
            rec = json.load(fh)
        m = re.search(r"MULTICHIP_WIRE (\{.*\})", rec.get("tail", "") or "")
        if m:
            rows.append((path, json.loads(m.group(1))))
    if not rows:
        pytest.skip("no recorded MULTICHIP_WIRE rounds yet (pre-PR 9 "
                    "history)")
    for path, wire in rows:
        for tl, expected in EXPECTED_SITES.items():
            recorded = set(wire.get("sites", {}).get(tl, {}))
            assert expected <= recorded, (path, tl,
                                          expected - recorded)


# ======================================== driver script exit contract

def test_graftlint_script_ast_only_exits_zero():
    """``scripts/graftlint.py --ast-only`` on the shipped tree: exit 0,
    no JAX needed (layer-1 contract)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         "--ast-only"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_graftlint_script_flags_stale_baseline(tmp_path):
    """Exit 1 with a pointed finding when the baseline holds a
    suppression that matches nothing (stale entries may only be removed
    consciously)."""
    bad = tmp_path / "stale.json"
    bad.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "R1", "path": "nowhere.py", "symbol": "ghost",
         "justification": "obsolete"}]}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         "--ast-only", "--baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "STALE BASELINE" in r.stdout


def test_graftlint_script_explain_allowlist():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         "--explain-allowlist"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


# ==================================== compat-shim surface stays shrunk

SHIM_SURFACES = {
    "lightgbm_tpu.models.grower": {
        "build_histogram", "grow_tree", "grow_tree_impl",
        "grow_tree_segmented", "grow_tree_unified", "SeamSchedule"},
    "lightgbm_tpu.models.grower_depthwise": {
        "histogram_leafbatch", "grow_tree_depthwise",
        "grow_tree_depthwise_jit", "grow_tree_unified", "num_levels",
        "SeamSchedule"},
    "lightgbm_tpu.models.grower_leafcompact": {
        "build_histogram", "grow_tree_leafcompact",
        "grow_tree_leafcompact_impl", "grow_tree_unified", "SeamSchedule"},
}


def test_shim_surface_is_exactly_the_documented_set():
    """The ~50-line compat shims keep ONLY the documented keyword-seam
    entry points and patchable histogram attributes (ISSUE 10 satellite:
    the dead re-exports the AST pass proved unreachable stay deleted)."""
    import importlib
    for modname, expected in SHIM_SURFACES.items():
        mod = importlib.import_module(modname)
        public = {n for n in vars(mod)
                  if not n.startswith("_") and n not in ("annotations",)
                  and not isinstance(vars(mod)[n], type(os))}
        assert public == expected, (modname, public ^ expected)


def test_shim_annotations_resolve():
    """No dangling names in shim signatures: every annotation must
    resolve against the shrunk module namespace (get_type_hints is what
    doc/typing tooling runs)."""
    import typing
    from lightgbm_tpu.models import (grower, grower_depthwise,
                                     grower_leafcompact)
    for fn in (grower.grow_tree_impl,
               grower_depthwise.grow_tree_depthwise,
               grower_leafcompact.grow_tree_leafcompact_impl):
        typing.get_type_hints(fn)
