"""Telemetry subsystem tests (ISSUE 1): route counters, span nesting,
zero-overhead disabled mode, the JSONL sink's per-iteration schema, and the
tier-1 invariant that instrumentation never perturbs training numerics."""
import json
import time

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.io.dataset import Dataset


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Telemetry is process-global state: every test starts disabled/zeroed
    and leaves nothing armed for the rest of the suite."""
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _data(n=1200, seed=0, features=6):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, features)
    y = (x[:, 0] + 0.5 * x[:, 1] + 0.1 * rng.randn(n) > 0).astype(np.float32)
    return x, y


BASE = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
        "min_sum_hessian_in_leaf": 1.0, "learning_rate": 0.2}


# ----------------------------------------------------------------- counters

def test_counters_increment_on_forced_fallback(monkeypatch):
    """LGBM_TPU_NO_PALLAS=1 must leave a runtime record: the env-trip
    counter and the XLA fallback route counter both tick."""
    monkeypatch.setenv("LGBM_TPU_NO_PALLAS", "1")
    telemetry.enable()
    from lightgbm_tpu.ops.histogram import histogram_leafbatch
    bins = jnp.zeros((2, 16), jnp.uint8)
    g = jnp.ones((16,), jnp.float32)
    h = jnp.ones((16,), jnp.float32)
    cid = jnp.zeros((16,), jnp.int32)
    ok = jnp.ones((16,), bool)
    out = histogram_leafbatch(bins, g, h, cid, ok, 1, 4,
                              compute_dtype="int8")
    assert out.shape == (1, 2, 4, 3)
    c = telemetry.counters()
    assert c.get("hist/env_no_pallas", 0) >= 1
    assert c.get("hist/xla_int8", 0) >= 1
    # the partition eligibility rule trips the same hatch
    from lightgbm_tpu.ops.compact import pallas_partition_ok
    assert pallas_partition_ok() is False
    assert telemetry.counters().get("partition/env_no_pallas", 0) >= 1


def test_route_counters_float_fallback():
    telemetry.enable()
    from lightgbm_tpu.ops.histogram import histogram_leafbatch
    bins = jnp.zeros((2, 16), jnp.uint8)
    g = jnp.ones((16,), jnp.float32)
    h = jnp.ones((16,), jnp.float32)
    histogram_leafbatch(bins, g, h, jnp.zeros((16,), jnp.int32),
                        jnp.ones((16,), bool), 1, 4,
                        compute_dtype=jnp.float32)
    c = telemetry.counters()
    # CPU backend: Pallas ineligible, einsum fallback taken
    assert c.get("hist/xla_einsum", 0) >= 1
    assert c.get("hist/pallas_ineligible", 0) >= 1


# -------------------------------------------------------------------- spans

def test_spans_nest_correctly():
    telemetry.enable()
    with telemetry.span("outer"):
        time.sleep(0.002)
        with telemetry.span("inner"):
            time.sleep(0.002)
    snap = telemetry.snapshot()
    assert snap["phase_times"]["outer"] >= snap["phase_times"]["inner"] > 0
    assert snap["phase_counts"] == {"outer": 1, "inner": 1}
    # re-entrant same-name spans are suppressed (recursive helpers must
    # not double-count wall time under one name)
    with telemetry.span("outer"):
        with telemetry.span("outer"):
            time.sleep(0.001)
    assert telemetry.snapshot()["phase_counts"]["outer"] == 2
    # the stack unwinds on exceptions
    with pytest.raises(RuntimeError):
        with telemetry.span("boom"):
            raise RuntimeError("x")
    with telemetry.span("after"):
        pass
    assert "after" in telemetry.snapshot()["phase_times"]


def test_disabled_mode_records_nothing(tmp_path):
    assert not telemetry.enabled()
    with telemetry.span("phantom"):
        pass
    telemetry.count("phantom_counter")
    snap = telemetry.snapshot()
    assert snap["phase_times"] == {} and snap["counters"] == {}
    # a train without metrics_out writes no file and leaves no records
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    lgb.train(dict(BASE, num_iterations=2), ds)
    snap = telemetry.snapshot()
    assert snap["phase_times"] == {} and snap["counters"] == {}


# --------------------------------------------------------------------- sink

def _check_record_schema(rec):
    assert isinstance(rec["iter"], int)
    for key in telemetry.CANONICAL_PHASES:
        assert key in rec["phase_times"]
    for v in rec["phase_times"].values():
        assert isinstance(v, (int, float)) and v >= 0
    assert isinstance(rec["counters"], dict)
    assert isinstance(rec["eval_metrics"], dict)
    # ISSUE 2: metrics_out= armed runs resolve health="auto" and
    # memory_stats="auto" ON — every record carries both blocks
    from lightgbm_tpu import health as health_mod
    for key in health_mod.HEALTH_VEC_KEYS + health_mod.TREE_HEALTH_KEYS:
        assert key in rec["health"], key
    assert rec["memory"]["peak_bytes_in_use"] >= 0
    assert rec["memory"]["source"] in ("device", "host_rss", "unavailable")


def test_jsonl_sink_per_iteration_schema(tmp_path):
    """3-iteration CPU train (per-iteration leaf-wise path): one
    schema-valid record per iteration plus the summary.

    Route counters fire at TRACE time, so the dataset shape must be unique
    to this test — a shape any earlier test already compiled would replay
    its cached program and record no new route decisions."""
    x, y = _data(n=1357, features=7)
    ds = Dataset.from_arrays(x, y, max_bin=48)
    path = str(tmp_path / "m.jsonl")
    lgb.train(dict(BASE, num_iterations=3, num_leaves=13,
                   metric="binary_logloss",
                   is_training_metric="true", metrics_out=path), ds)
    telemetry.disable()
    recs = [json.loads(line) for line in open(path)]
    iter_recs = [r for r in recs if "iter" in r]
    assert [r["iter"] for r in iter_recs] == [1, 2, 3]
    for rec in iter_recs:
        _check_record_schema(rec)
    # eval metrics ride the records
    assert any("training/" in k for r in iter_recs
               for k in r["eval_metrics"])
    # route counters are present and monotonic across records
    hist_counts = [sum(v for k, v in r["counters"].items()
                       if k.startswith("hist/")) for r in iter_recs]
    assert hist_counts[0] > 0
    assert hist_counts == sorted(hist_counts)
    assert recs[-1].get("summary") is True
    # ISSUE 2: the one-shot residency record precedes the iterations, and
    # the summary carries cumulative health + memory blocks
    residency = [r for r in recs if "residency" in r]
    assert residency and residency[0]["residency"]["bin_matrix_bytes"] > 0
    assert recs[-1]["health"]["anomalous_iterations"] == 0
    assert recs[-1]["memory"]["peak_bytes_in_use"] > 0


def test_jsonl_sink_chunked_one_record_per_iteration(tmp_path):
    """10-iteration depthwise CPU train rides the fused chunk path; the
    sink still gets exactly one record per iteration (amortized)."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")
    lgb.train(dict(BASE, num_iterations=10, grow_policy="depthwise",
                   metrics_out=path), ds)
    telemetry.disable()
    recs = [json.loads(line) for line in open(path)]
    iter_recs = [r for r in recs if "iter" in r]
    assert [r["iter"] for r in iter_recs] == list(range(1, 11))
    for rec in iter_recs:
        _check_record_schema(rec)
        assert rec["amortized_over"] >= 1


def test_sink_closed_after_train_no_leak(tmp_path):
    """A train() that armed the sink closes it: a later train() without
    metrics_out must not append records to the first run's file."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")
    lgb.train(dict(BASE, num_iterations=2, metrics_out=path), ds)
    assert not telemetry.sink_active()
    n_lines = len(open(path).read().splitlines())
    ds2 = Dataset.from_arrays(x, y, max_bin=32)
    lgb.train(dict(BASE, num_iterations=2), ds2)
    assert len(open(path).read().splitlines()) == n_lines


# ------------------------------------------------------------ memory gauges

def test_memory_peak_rebaselines_across_reset():
    """The allocator's peak_bytes_in_use is monotonic over the PROCESS: a
    small run after a big one must not inherit the big run's peak, but
    growth past the post-reset baseline (a transient spike between
    samples) does count (white-box: stubs the device handle)."""
    class FakeDev:
        stats = {}

        def memory_stats(self):
            return dict(self.stats)

    dev = FakeDev()
    telemetry._mem_device = dev
    try:
        telemetry.reset()
        dev.stats = {"bytes_in_use": 9_000, "peak_bytes_in_use": 10_000}
        telemetry._mem_sample()
        assert telemetry.mem_peak_bytes() == 9_000
        telemetry.reset()   # fresh run: 10_000 lifetime peak is history
        dev.stats = {"bytes_in_use": 2_000, "peak_bytes_in_use": 10_000}
        telemetry._mem_sample()
        assert telemetry.mem_peak_bytes() == 2_000
        # allocator peak GREW past the baseline -> this run's spike
        dev.stats = {"bytes_in_use": 3_000, "peak_bytes_in_use": 11_000}
        telemetry._mem_sample()
        assert telemetry.mem_peak_bytes() == 11_000
    finally:
        telemetry._mem_device = None
        telemetry.reset()


# ---------------------------------------------------- numerics non-perturbation

def test_scores_identical_with_telemetry_on_vs_off(tmp_path):
    """Tier-1 invariant: instrumentation must not perturb numerics or jit
    caching — train_one_iter produces bit-identical scores either way."""
    x, y = _data(seed=3)
    params = dict(BASE, num_iterations=4, bagging_fraction=0.7,
                  bagging_freq=1)

    def scores(with_telemetry):
        if with_telemetry:
            telemetry.enable(str(tmp_path / "on.jsonl"), fence=True)
        else:
            telemetry.disable()
        telemetry.reset()
        ds = Dataset.from_arrays(x, y, max_bin=32)
        booster = lgb.train(params, ds)
        out = np.asarray(booster.score)
        telemetry.disable()
        return out

    off = scores(False)
    on = scores(True)
    np.testing.assert_array_equal(off, on)
