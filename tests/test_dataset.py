"""Dataset/parser tests: format sniffing, column roles, side files,
native-vs-Python parser equality."""
import os

import numpy as np
import pytest

from lightgbm_tpu.config import IOConfig
from lightgbm_tpu.io import parser as parser_mod
from lightgbm_tpu.io.dataset import Dataset, _resolve_columns


def _write(path, text):
    with open(path, "w") as f:
        f.write(text)
    return str(path)


def test_format_sniffing(tmp_path):
    csv = _write(tmp_path / "a.csv", "1,2,3\n4,5,6\n")
    tsv = _write(tmp_path / "a.tsv", "1\t2\t3\n4\t5\t6\n")
    svm = _write(tmp_path / "a.svm", "1 0:0.5 2:1.5\n0 1:2.0\n")
    assert parser_mod.create_parser(csv, False, 0, 0).format_name == "csv"
    assert parser_mod.create_parser(tsv, False, 0, 0).format_name == "tsv"
    assert parser_mod.create_parser(svm, False, 0, 0).format_name == "libsvm"


def test_csv_parse_with_label():
    p = parser_mod.CSVParser(label_idx=0)
    parsed = p.parse(["1,0.5,na,2.0", "0,1.5,3.0,0"])
    np.testing.assert_allclose(parsed.labels, [1.0, 0.0])
    np.testing.assert_allclose(parsed.features,
                               [[0.5, 0.0, 2.0], [1.5, 3.0, 0.0]])


def test_libsvm_parse():
    p = parser_mod.LibSVMParser(label_idx=0)
    parsed = p.parse(["1 0:0.5 3:2.0", "0 1:1.5"])
    np.testing.assert_allclose(parsed.labels, [1.0, 0.0])
    assert parsed.features.shape == (2, 4)
    assert parsed.features[0, 3] == 2.0
    assert parsed.features[1, 1] == 1.5


def test_predict_time_label_heuristic(tmp_path):
    # file with num_features columns (no label) → label_idx becomes -1
    path = _write(tmp_path / "nolabel.csv", "1,2,3\n4,5,6\n")
    p = parser_mod.create_parser(path, False, 3, 0)
    assert p.label_idx == -1
    parsed = p.parse(["1,2,3"])
    assert parsed.features.shape == (1, 3)
    np.testing.assert_allclose(parsed.labels, [0.0])


def test_column_resolution_by_name(tmp_path):
    data = _write(tmp_path / "d.csv",
                  "lbl,f1,wgt,f2\n1,0.5,2.0,3.0\n0,1.5,1.0,4.0\n")
    cfg = IOConfig(data_filename=data, has_header=True,
                   label_column="name:lbl", weight_column="name:wgt")
    label_idx, weight_idx, group_idx, ignore, names = _resolve_columns(cfg)
    assert label_idx == 0
    # wgt is raw col 2 → feature-space 1 after label removal
    assert weight_idx == 1
    assert weight_idx in ignore
    assert names == ["f1", "wgt", "f2"]


def test_load_train_weight_column(tmp_path):
    data = _write(tmp_path / "d.csv",
                  "lbl,f1,wgt,f2\n" + "\n".join(
                      f"{i % 2},{i * 0.1},{1.0 + i},{3.0 - i * 0.1}"
                      for i in range(50)) + "\n")
    cfg = IOConfig(data_filename=data, has_header=True,
                   label_column="name:lbl", weight_column="name:wgt")
    ds = Dataset.load_train(cfg)
    # weight column captured into metadata, excluded from features
    np.testing.assert_allclose(ds.metadata.weights,
                               [1.0 + i for i in range(50)])
    assert all(j != 1 for j in ds.used_feature_map)  # wgt not a feature
    assert ds.metadata.label[1] == 1.0


def test_side_files(tmp_path):
    data = _write(tmp_path / "rank.txt", "\n".join(
        f"{i % 3}\t{i * 0.1}\t{i * 0.2}" for i in range(30)) + "\n")
    _write(tmp_path / "rank.txt.weight",
           "\n".join("1.5" for _ in range(30)) + "\n")
    _write(tmp_path / "rank.txt.query", "10\n20\n")
    cfg = IOConfig(data_filename=data)
    ds = Dataset.load_train(cfg)
    np.testing.assert_allclose(ds.metadata.weights, 1.5)
    np.testing.assert_array_equal(ds.metadata.query_boundaries, [0, 10, 30])
    # query weights = per-query mean of record weights
    np.testing.assert_allclose(ds.metadata.query_weights, [1.5, 1.5])


def test_trivial_feature_dropped(tmp_path):
    data = _write(tmp_path / "t.csv", "\n".join(
        f"{i % 2},{i * 1.0},7.0" for i in range(20)) + "\n")
    cfg = IOConfig(data_filename=data)
    ds = Dataset.load_train(cfg)
    # constant column dropped; real_feature_idx keeps original numbering
    assert ds.num_features == 1
    assert list(ds.real_feature_idx) == [0]


def test_native_parser_matches_python():
    from lightgbm_tpu.native import lib
    if not lib.available():
        pytest.skip("native library not built")
    rng = np.random.RandomState(0)
    rows = []
    for _ in range(200):
        vals = rng.randn(5).round(4)
        rows.append(",".join(str(v) for v in vals))
    rows[7] = "na,1.0,nan,-2.5,0"
    native = lib.parse_delimited(rows, ",")
    python = np.array([[parser_mod._atof(t) for t in r.split(",")]
                       for r in rows])
    np.testing.assert_allclose(native, python)


def test_two_round_loading_identical(tmp_path):
    """use_two_round_loading streams the file twice instead of
    materializing the float matrix; the resulting Dataset must be
    identical (bins, labels, weights, metadata)."""
    import shutil
    src = "/root/reference/examples/binary_classification"
    if not os.path.isdir(src):
        pytest.skip("reference examples not available")
    for f in ("binary.train", "binary.train.weight"):
        shutil.copy(os.path.join(src, f), tmp_path / f)
    from lightgbm_tpu.config import IOConfig

    def load(two_round):
        io = IOConfig()
        io.set({"data": str(tmp_path / "binary.train"),
                "use_two_round_loading": str(two_round).lower()})
        return Dataset.load_train(io)

    d1 = load(False)
    d2 = load(True)
    assert d1.num_data == d2.num_data
    assert d1.num_features == d2.num_features
    np.testing.assert_array_equal(d1.bins, d2.bins)
    np.testing.assert_array_equal(d1.metadata.label, d2.metadata.label)
    np.testing.assert_allclose(d1.metadata.weights, d2.metadata.weights)
    np.testing.assert_array_equal(d1.num_bins, d2.num_bins)
    for m1, m2 in zip(d1.bin_mappers, d2.bin_mappers):
        np.testing.assert_allclose(m1.bin_upper_bound, m2.bin_upper_bound)


def test_two_round_loading_sharded(tmp_path):
    """Two-round + distributed sharding: shards partition the rows exactly
    like the one-round path (same data_random_seed draw)."""
    import shutil
    src = "/root/reference/examples/binary_classification"
    if not os.path.isdir(src):
        pytest.skip("reference examples not available")
    shutil.copy(os.path.join(src, "binary.train"), tmp_path / "binary.train")
    from lightgbm_tpu.config import IOConfig

    def load(two_round, rank):
        io = IOConfig()
        io.set({"data": str(tmp_path / "binary.train"),
                "use_two_round_loading": str(two_round).lower()})
        return Dataset.load_train(io, rank=rank, num_machines=4)

    for rank in (0, 3):
        d1 = load(False, rank)
        d2 = load(True, rank)
        assert d1.num_data == d2.num_data
        np.testing.assert_array_equal(d1.bins, d2.bins)
        np.testing.assert_array_equal(d1.metadata.label, d2.metadata.label)


def test_two_round_loading_reservoir_branch(tmp_path):
    """Files larger than the 50k-row bin-finding sample exercise the
    replacement branch of the streaming reservoir.  Sampling differs from
    the one-round path (choice vs reservoir), so compare structure and
    labels, not bins bit-for-bit."""
    rng = np.random.RandomState(0)
    n = 60_000
    x = rng.randn(n, 4)
    y = (x[:, 0] > 0).astype(int)
    path = tmp_path / "big.csv"
    with open(path, "w") as f:
        for i in range(n):
            f.write(",".join([str(y[i])] + ["%.6f" % v for v in x[i]]) + "\n")
    from lightgbm_tpu.config import IOConfig

    def load(two_round):
        io = IOConfig()
        io.set({"data": str(path), "max_bin": "64",
                "use_two_round_loading": str(two_round).lower()})
        return Dataset.load_train(io)

    d1 = load(False)
    d2 = load(True)
    assert d1.num_data == d2.num_data == n
    assert d1.bins.shape == d2.bins.shape
    np.testing.assert_array_equal(d1.metadata.label, d2.metadata.label)
    # equal-frequency bins from two independent 50k samples of the same
    # distribution: bounds agree closely
    for m1, m2 in zip(d1.bin_mappers, d2.bin_mappers):
        assert abs(m1.num_bin - m2.num_bin) <= 2


def test_num_threads_caps_native_pool():
    """num_threads must actually reach the native OpenMP pool
    (Application::Application, application.cpp:30-34) — VERDICT r2 flagged
    it as parsed-but-never-applied."""
    import ctypes
    from lightgbm_tpu.native import lib
    if not lib.available():
        pytest.skip("native library not built")
    so = lib._load()
    if not hasattr(so, "set_num_threads"):
        pytest.skip("stale cached .so without set_num_threads "
                    "(no compiler to rebuild)")
    lib.set_num_threads(1)
    assert int(so.num_threads()) == 1
    lib.set_num_threads(2)
    assert int(so.num_threads()) in (1, 2)  # capped by the host's cores
