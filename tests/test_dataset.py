"""Dataset/parser tests: format sniffing, column roles, side files,
native-vs-Python parser equality."""
import os

import numpy as np
import pytest

from lightgbm_tpu.config import IOConfig
from lightgbm_tpu.io import parser as parser_mod
from lightgbm_tpu.io.dataset import Dataset, _resolve_columns


def _write(path, text):
    with open(path, "w") as f:
        f.write(text)
    return str(path)


def test_format_sniffing(tmp_path):
    csv = _write(tmp_path / "a.csv", "1,2,3\n4,5,6\n")
    tsv = _write(tmp_path / "a.tsv", "1\t2\t3\n4\t5\t6\n")
    svm = _write(tmp_path / "a.svm", "1 0:0.5 2:1.5\n0 1:2.0\n")
    assert parser_mod.create_parser(csv, False, 0, 0).format_name == "csv"
    assert parser_mod.create_parser(tsv, False, 0, 0).format_name == "tsv"
    assert parser_mod.create_parser(svm, False, 0, 0).format_name == "libsvm"


def test_csv_parse_with_label():
    p = parser_mod.CSVParser(label_idx=0)
    parsed = p.parse(["1,0.5,na,2.0", "0,1.5,3.0,0"])
    np.testing.assert_allclose(parsed.labels, [1.0, 0.0])
    np.testing.assert_allclose(parsed.features,
                               [[0.5, 0.0, 2.0], [1.5, 3.0, 0.0]])


def test_libsvm_parse():
    p = parser_mod.LibSVMParser(label_idx=0)
    parsed = p.parse(["1 0:0.5 3:2.0", "0 1:1.5"])
    np.testing.assert_allclose(parsed.labels, [1.0, 0.0])
    assert parsed.features.shape == (2, 4)
    assert parsed.features[0, 3] == 2.0
    assert parsed.features[1, 1] == 1.5


def test_predict_time_label_heuristic(tmp_path):
    # file with num_features columns (no label) → label_idx becomes -1
    path = _write(tmp_path / "nolabel.csv", "1,2,3\n4,5,6\n")
    p = parser_mod.create_parser(path, False, 3, 0)
    assert p.label_idx == -1
    parsed = p.parse(["1,2,3"])
    assert parsed.features.shape == (1, 3)
    np.testing.assert_allclose(parsed.labels, [0.0])


def test_column_resolution_by_name(tmp_path):
    data = _write(tmp_path / "d.csv",
                  "lbl,f1,wgt,f2\n1,0.5,2.0,3.0\n0,1.5,1.0,4.0\n")
    cfg = IOConfig(data_filename=data, has_header=True,
                   label_column="name:lbl", weight_column="name:wgt")
    label_idx, weight_idx, group_idx, ignore, names = _resolve_columns(cfg)
    assert label_idx == 0
    # wgt is raw col 2 → feature-space 1 after label removal
    assert weight_idx == 1
    assert weight_idx in ignore
    assert names == ["f1", "wgt", "f2"]


def test_load_train_weight_column(tmp_path):
    data = _write(tmp_path / "d.csv",
                  "lbl,f1,wgt,f2\n" + "\n".join(
                      f"{i % 2},{i * 0.1},{1.0 + i},{3.0 - i * 0.1}"
                      for i in range(50)) + "\n")
    cfg = IOConfig(data_filename=data, has_header=True,
                   label_column="name:lbl", weight_column="name:wgt")
    ds = Dataset.load_train(cfg)
    # weight column captured into metadata, excluded from features
    np.testing.assert_allclose(ds.metadata.weights,
                               [1.0 + i for i in range(50)])
    assert all(j != 1 for j in ds.used_feature_map)  # wgt not a feature
    assert ds.metadata.label[1] == 1.0


def test_side_files(tmp_path):
    data = _write(tmp_path / "rank.txt", "\n".join(
        f"{i % 3}\t{i * 0.1}\t{i * 0.2}" for i in range(30)) + "\n")
    _write(tmp_path / "rank.txt.weight",
           "\n".join("1.5" for _ in range(30)) + "\n")
    _write(tmp_path / "rank.txt.query", "10\n20\n")
    cfg = IOConfig(data_filename=data)
    ds = Dataset.load_train(cfg)
    np.testing.assert_allclose(ds.metadata.weights, 1.5)
    np.testing.assert_array_equal(ds.metadata.query_boundaries, [0, 10, 30])
    # query weights = per-query mean of record weights
    np.testing.assert_allclose(ds.metadata.query_weights, [1.5, 1.5])


def test_trivial_feature_dropped(tmp_path):
    data = _write(tmp_path / "t.csv", "\n".join(
        f"{i % 2},{i * 1.0},7.0" for i in range(20)) + "\n")
    cfg = IOConfig(data_filename=data)
    ds = Dataset.load_train(cfg)
    # constant column dropped; real_feature_idx keeps original numbering
    assert ds.num_features == 1
    assert list(ds.real_feature_idx) == [0]


def test_native_parser_matches_python():
    from lightgbm_tpu.native import lib
    if not lib.available():
        pytest.skip("native library not built")
    rng = np.random.RandomState(0)
    rows = []
    for _ in range(200):
        vals = rng.randn(5).round(4)
        rows.append(",".join(str(v) for v in vals))
    rows[7] = "na,1.0,nan,-2.5,0"
    native = lib.parse_delimited(rows, ",")
    python = np.array([[parser_mod._atof(t) for t in r.split(",")]
                       for r in rows])
    np.testing.assert_allclose(native, python)
