"""Split-search tests against a literal NumPy port of the reference scan
(feature_histogram.hpp:106-165)."""
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.ops.split import find_best_split, K_EPSILON


def _reference_scan(hist_f, num_bin, sum_g, sum_h_raw, num_data,
                    min_data, min_hess):
    """Literal port of FindBestThreshold for one feature."""
    sum_hessians = sum_h_raw + 2 * K_EPSILON
    best_gain = -np.inf
    best_threshold = num_bin
    sum_right_g = 0.0
    sum_right_h = K_EPSILON
    right_count = 0
    gain_shift = sum_g * sum_g / sum_hessians
    for t in range(num_bin - 1, 0, -1):
        sum_right_g += hist_f[t, 0]
        sum_right_h += hist_f[t, 1]
        right_count += hist_f[t, 2]
        if right_count < min_data or sum_right_h < min_hess:
            continue
        left_count = num_data - right_count
        if left_count < min_data:
            break
        sum_left_h = sum_hessians - sum_right_h
        if sum_left_h < min_hess:
            break
        sum_left_g = sum_g - sum_right_g
        gain = (sum_left_g ** 2 / sum_left_h + sum_right_g ** 2 / sum_right_h)
        if gain < gain_shift:
            continue
        if gain > best_gain:
            best_threshold = t - 1
            best_gain = gain
    return best_threshold, best_gain - gain_shift


def _run_case(seed, F=4, B=16, min_data=3, min_hess=1e-3):
    rng = np.random.RandomState(seed)
    hist = np.zeros((F, B, 3), dtype=np.float64)
    n = 500
    # one shared row population: every feature is a different binning of the
    # SAME rows, so per-feature histogram totals agree (as in real data)
    g = rng.randn(n)
    h = rng.rand(n) + 0.1
    for f in range(F):
        bins = rng.randint(0, B, size=n)
        for b_, g_, h_ in zip(bins, g, h):
            hist[f, b_] += [g_, h_, 1.0]
    sum_g = hist[0, :, 0].sum()
    sum_h = hist[0, :, 1].sum()
    num_data = hist[0, :, 2].sum()

    res = find_best_split(
        jnp.asarray(hist, jnp.float32), jnp.float32(sum_g),
        jnp.float32(sum_h), jnp.float32(num_data),
        jnp.full((F,), B, jnp.int32), jnp.ones((F,), bool),
        float(min_data), float(min_hess))

    # oracle: best across features, smaller feature wins ties
    best = (-np.inf, None, None)
    for f in range(F):
        t, gain = _reference_scan(hist[f], B, sum_g, sum_h, num_data,
                                  min_data, min_hess)
        if gain > best[0]:
            best = (gain, f, t)
    assert int(res.feature) == best[1], (int(res.feature), best)
    assert int(res.threshold) == best[2]
    np.testing.assert_allclose(float(res.gain), best[0], rtol=1e-4)


def test_split_matches_reference_scan():
    for seed in range(5):
        _run_case(seed)


def test_min_data_constraint_blocks_split():
    # all data in one bin → no valid split
    F, B = 2, 8
    hist = np.zeros((F, B, 3), dtype=np.float32)
    hist[:, 3] = [5.0, 10.0, 100.0]
    res = find_best_split(
        jnp.asarray(hist), jnp.float32(5.0), jnp.float32(10.0),
        jnp.float32(100.0), jnp.full((F,), B, jnp.int32),
        jnp.ones((F,), bool), 1.0, 1e-3)
    assert float(res.gain) == -np.inf


def test_feature_mask_respected():
    rng = np.random.RandomState(2)
    F, B = 3, 8
    hist = rng.rand(F, B, 3).astype(np.float32) * 10
    hist[:, :, 1] += 1
    sum_g = float(hist[0, :, 0].sum())
    sum_h = float(hist[0, :, 1].sum())
    cnt = float(hist[0, :, 2].sum())
    mask = jnp.asarray([False, True, False])
    res = find_best_split(
        jnp.asarray(hist), jnp.float32(sum_g), jnp.float32(sum_h),
        jnp.float32(cnt), jnp.full((F,), B, jnp.int32), mask, 0.0, 0.0)
    assert int(res.feature) == 1


def test_left_right_outputs_consistent():
    rng = np.random.RandomState(4)
    F, B = 2, 8
    hist = np.abs(rng.rand(F, B, 3)).astype(np.float32) * 5
    hist[:, :, 2] = np.round(hist[:, :, 2] * 10)
    sum_g = float(hist[0, :, 0].sum())
    sum_h = float(hist[0, :, 1].sum())
    cnt = float(hist[0, :, 2].sum())
    res = find_best_split(
        jnp.asarray(hist), jnp.float32(sum_g), jnp.float32(sum_h),
        jnp.float32(cnt), jnp.full((F,), B, jnp.int32),
        jnp.ones((F,), bool), 1.0, 1e-3)
    if np.isfinite(float(res.gain)):
        f, t = int(res.feature), int(res.threshold)
        lg = hist[f, :t + 1, 0].sum()
        lh = hist[f, :t + 1, 1].sum()
        np.testing.assert_allclose(float(res.left_sum_grad), lg, rtol=1e-4)
        np.testing.assert_allclose(float(res.left_output),
                                   -lg / (lh + K_EPSILON), rtol=1e-3)
