"""Differential tests: serial ≡ data-parallel ≡ feature-parallel trees on a
virtual 8-device CPU mesh — the reference's own invariant
(data_parallel_tree_learner.cpp: every worker ends each split with the
identical global best split), which SURVEY §4 recommends encoding as a test.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective


def _make_config(tree_learner, num_machines):
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "num_leaves": "15",
             "min_data_in_leaf": "20", "min_sum_hessian_in_leaf": "1.0",
             "num_iterations": "5", "learning_rate": "0.2",
             "tree_learner": tree_learner,
             "num_machines": str(num_machines)}, require_data=False)
    return cfg


def _train_with(tree_learner, num_machines, x, y):
    cfg = _make_config(tree_learner, num_machines)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    booster = GBDT()
    objective = create_objective(cfg.objective_type, cfg.objective_config)
    learner = None
    if tree_learner != "serial":
        from lightgbm_tpu.parallel import create_parallel_learner
        learner = create_parallel_learner(cfg)
    booster.init(cfg.boosting_config, ds, objective, learner=learner)
    for _ in range(cfg.boosting_config.num_iterations):
        if booster.train_one_iter(is_eval=False):
            break
    return booster


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(21)
    n, f = 1600, 10
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.2 * rng.randn(n)) > 0).astype(np.float32)
    return x, y


def _tree_fingerprint(booster):
    out = []
    for t in booster.models:
        out.append((t.num_leaves, tuple(t.split_feature_real),
                    tuple(t.threshold_bin), tuple(np.round(t.leaf_value, 5))))
    return out


def test_requires_8_devices():
    assert len(jax.devices()) >= 8


def _assert_equivalent_to_serial(serial, parallel, x):
    """Parallel learners must reproduce serial trees up to f32 near-ties.

    Bitwise serial≡parallel equality is not achievable: reductions run in a
    different order (single-device sum vs psum of partials), so a split
    whose two candidates differ by < 1 ulp may resolve differently.  The
    reference has the same property (its guarantee is identical trees
    ACROSS WORKERS, which here holds by construction since the split search
    is replicated on reduced histograms).

    Tie-keyed comparison: splits are compared in order until the FIRST
    divergence per tree; a divergence is only acceptable when both sides'
    chosen gains agree to ~f32 reduction noise (a genuine near-tie —
    each learner picked ITS best, so if the decisions differ yet both
    maxima match, the candidates were tied).  Past the first divergence the
    partitions differ and structures are legitimately incomparable, so the
    remaining assertions are on predictions.
    """
    assert len(serial.models) == len(parallel.models)
    diverged = False
    for k, (ts, tp) in enumerate(zip(serial.models, parallel.models)):
        if diverged:
            # scores differ past the first divergence; later trees grow on
            # different residuals and are legitimately incomparable
            break
        n = min(ts.num_leaves, tp.num_leaves) - 1
        for i in range(n):
            same = (ts.split_feature_real[i] == tp.split_feature_real[i]
                    and ts.threshold_bin[i] == tp.threshold_bin[i])
            gs, gp = float(ts.split_gain[i]), float(tp.split_gain[i])
            tol = max(1e-4 * max(1.0, abs(gs), abs(gp)), 1e-3)
            if not same:
                # divergence must be a genuine near-tie, not a lost split
                assert abs(gs - gp) < tol, (
                    f"tree {k} split {i}: diverged with gain gap "
                    f"{gs} vs {gp} (not a near-tie)")
                diverged = True
                break
            # identical decision -> gains must agree to reduction noise too
            assert abs(gs - gp) < tol, (
                f"tree {k} split {i}: same split, gain {gs} vs {gp}")
        if not diverged:
            # identical prefix must mean identical size: a shorter parallel
            # tree with no near-tie divergence is a LOST split, not noise
            assert ts.num_leaves == tp.num_leaves, (
                f"tree {k}: identical split prefix but {ts.num_leaves} vs "
                f"{tp.num_leaves} leaves (lost splits)")
    diff = np.abs(serial.predict_raw(x) - parallel.predict_raw(x))
    # rows rerouted by a diverged near-tie split may shift; they must be few
    assert (diff > 1e-3).mean() < 0.05
    assert np.median(diff) < 1e-4


def test_data_parallel_matches_serial(data):
    x, y = data
    serial = _train_with("serial", 1, x, y)
    dp = _train_with("data", 8, x, y)
    _assert_equivalent_to_serial(serial, dp, x)


def test_feature_parallel_matches_serial(data):
    x, y = data
    serial = _train_with("serial", 1, x, y)
    fp = _train_with("feature", 8, x, y)
    _assert_equivalent_to_serial(serial, fp, x)


def test_feature_parallel_uneven_features(data):
    """F=10 not divisible by 8 shards — exercises the feature-padding path."""
    x, y = data
    fp = _train_with("feature", 8, x, y)
    # padded phantom features must never be chosen
    for t in fp.models:
        assert (np.asarray(t.split_feature_real) < x.shape[1]).all()


def test_data_parallel_uneven_rows(data):
    x, y = data
    # 1601 rows not divisible by 8
    x2 = np.concatenate([x, x[:1]])
    y2 = np.concatenate([y, y[:1]])
    serial = _train_with("serial", 1, x2, y2)
    dp = _train_with("data", 8, x2, y2)
    _assert_equivalent_to_serial(serial, dp, x2)


def test_data_parallel_chunked_eval_early_stop(synthetic_binary):
    """The data-parallel chunk evaluates metrics IN-PROGRAM (train metrics
    on the all_gathered global score — AUC's global sort included — and
    valid sets replicated per shard), so DP chunked runs early-stop with
    identical bookkeeping to the serial chunked path (VERDICT r1 #5;
    reference evaluates every iteration in parallel mode too,
    gbdt.cpp:225-259)."""
    from lightgbm_tpu.metrics import create_metric

    x, y = synthetic_binary
    xt, yt = x[:1500], y[:1500]
    rng = np.random.RandomState(0)
    xv = x[1500:]
    yv = rng.randint(0, 2, size=len(xv)).astype(np.float32)  # noise valid
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 30, "learning_rate": 0.4,
              "early_stopping_round": 3, "metric": "auc,binary_logloss",
              "grow_policy": "depthwise"}

    def make(tree_learner, machines):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner, num_machines=machines)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        ds = Dataset.from_arrays(xt, yt, max_bin=32)
        dsv = Dataset.from_arrays(xv, yv, max_bin=32, reference=ds)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = None
        if tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        tm = [m for m in (create_metric(t, cfg.metric_config)
                          for t in cfg.metric_types) if m is not None]
        b.init(cfg.boosting_config, ds, obj, tm, learner=learner)
        vm = [m for m in (create_metric(t, cfg.metric_config)
                          for t in cfg.metric_types) if m is not None]
        b.add_valid_dataset(dsv, vm)
        return b

    b_serial = make("serial", 1)
    assert b_serial.chunkable_for(True)
    b_serial.run_training(30, is_eval=True, chunk_size=5)

    b_dp = make("data", 8)
    assert b_dp.chunk_supported(True) and b_dp.chunkable_for(True)
    b_dp.run_training(30, is_eval=True, chunk_size=5)

    # identical early-stop iteration, model pop-back and best-score
    # bookkeeping; trees equal up to f32 psum near-ties (compare structure)
    assert b_serial.iter == b_dp.iter
    assert len(b_serial.models) == len(b_dp.models)
    np.testing.assert_array_equal(b_serial.best_iter[0], b_dp.best_iter[0])
    np.testing.assert_allclose(b_serial.best_score[0], b_dp.best_score[0],
                               rtol=1e-4)
    for t1, t2 in zip(b_serial.models, b_dp.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)


def test_feature_parallel_chunked_matches_serial(synthetic_binary):
    """The fused feature-parallel chunk program (ownership-sliced
    histograms + packed SplitInfo allreduce, everything else replicated)
    must reproduce the serial chunked trees exactly: every shard
    histograms its owned features over ALL rows, so per-feature sums are
    bit-identical to serial and the allreduce picks the identical global
    best (tie-break by smaller feature id preserved)."""
    x, y = synthetic_binary
    x, y = x[:1999], y[:1999]
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 4, "learning_rate": 0.2,
              "grow_policy": "depthwise",
              "bagging_fraction": 0.8, "bagging_freq": 2, "bagging_seed": 5}
    ds = Dataset.from_arrays(x, y, max_bin=32)

    def make(tree_learner, machines):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner, num_machines=machines)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = None
        if tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        b.init(cfg.boosting_config, ds, obj, learner=learner)
        return b

    b_serial = make("serial", 1)
    for _ in range(4):
        b_serial.train_one_iter(is_eval=False)

    b_fp = make("feature", 8)
    assert b_fp.chunk_supported(False) and b_fp.chunkable_for(False)
    stop = b_fp.train_chunk(4)
    assert not stop

    assert len(b_serial.models) == len(b_fp.models) == 4
    for t1, t2 in zip(b_serial.models, b_fp.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(b_serial.score),
                               np.asarray(b_fp.score),
                               rtol=1e-4, atol=1e-5)


def test_balanced_ownership_partition():
    """LPT bin-count balancing: every feature owned exactly once, loads
    within one max-feature of each other (feature_parallel_tree_learner
    .cpp:27-44 analog)."""
    from lightgbm_tpu.parallel.learners import balanced_ownership
    rng = np.random.RandomState(3)
    num_bins = rng.randint(2, 256, size=29)
    own, ownmask = balanced_ownership(num_bins, 8)
    owned = sorted(int(f) for f in own[ownmask])
    assert owned == list(range(29))
    loads = [int(num_bins[own[s][ownmask[s]]].sum()) for s in range(8)]
    assert max(loads) - min(loads) <= int(num_bins.max())


@pytest.mark.parametrize("grow_policy", ["leafwise", "depthwise"])
def test_data_parallel_chunked_matches_serial(synthetic_binary, grow_policy):
    """The fused data-parallel chunk program (shard_map over the whole
    k-iteration scan) must produce the same trees as serial training —
    rows sharded on a non-divisible N exercises the padding/valid_rows
    path."""
    x, y = synthetic_binary
    x, y = x[:1999], y[:1999]        # 1999 % 8 != 0 -> padding
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 4, "learning_rate": 0.2,
              "grow_policy": grow_policy,
              "bagging_fraction": 0.8, "bagging_freq": 2, "bagging_seed": 5}
    ds = Dataset.from_arrays(x, y, max_bin=32)

    def make(tree_learner, machines):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner, num_machines=machines)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = None
        if tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        b.init(cfg.boosting_config, ds, obj, learner=learner)
        return b

    b_serial = make("serial", 1)
    for _ in range(4):
        b_serial.train_one_iter(is_eval=False)

    b_dp = make("data", 8)
    assert b_dp.chunk_supported(False)
    if grow_policy == "depthwise":
        assert b_dp.chunkable_for(False)   # run_training would chunk
    stop = b_dp.train_chunk(4)
    assert not stop

    assert len(b_serial.models) == len(b_dp.models) == 4
    for t1, t2 in zip(b_serial.models, b_dp.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(b_serial.score),
                               np.asarray(b_dp.score),
                               rtol=1e-3, atol=1e-4)


def test_data_parallel_chunked_lambdarank_matches_serial():
    """DP-chunked lambdarank: pairwise lambdas need whole queries, and
    device-level row blocks cut queries mid-way — so the chunk program
    gathers the score shards, computes the full lambda vector replicated,
    and slices each shard's rows (needs_global_score protocol; the
    reference's per-machine path is rank_objective.hpp:68-192).  Trees and
    the NDCG trajectory must match the serial per-iteration run."""
    rng = np.random.RandomState(17)
    nq, qsize = 40, 13          # 520 rows: NOT divisible by 8 (shard pad)
    n = nq * qsize
    x = rng.randn(n, 5)
    rel = np.clip((x[:, 0] + 0.3 * rng.randn(n)) * 1.2 + 1, 0, 3).round()
    boundaries = np.arange(0, n + 1, qsize)
    # row weights exercise the padded-weight path (the DP chunk's lambda
    # vector is shard-padded; weights must tail-pad to match)
    weights = (0.5 + rng.rand(n)).astype(np.float32)
    ds = Dataset.from_arrays(x, rel.astype(np.float32), max_bin=32,
                             weights=weights,
                             query_boundaries=boundaries)
    # int8 quantized histograms: scales are pmax-synced and the psum runs
    # in the int domain, so DP trees are BIT-identical to serial (f32
    # psum reduction order would otherwise show through lambdarank's
    # cancellation-heavy gradients)
    params = {"objective": "lambdarank", "num_leaves": 15,
              "min_data_in_leaf": 10, "min_sum_hessian_in_leaf": 1e-3,
              "num_iterations": 4, "learning_rate": 0.1,
              "grow_policy": "depthwise", "hist_dtype": "int8"}

    def make(tree_learner, machines):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner, num_machines=machines)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = None
        if tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        b.init(cfg.boosting_config, ds, obj, learner=learner)
        return b

    b_serial = make("serial", 1)
    for _ in range(4):
        b_serial.train_one_iter(is_eval=False)

    b_dp = make("data", 8)
    assert b_dp.chunk_supported(False) and b_dp.chunkable_for(False)
    stop = b_dp.train_chunk(4)
    assert not stop

    assert len(b_serial.models) == len(b_dp.models) == 4
    for t1, t2 in zip(b_serial.models, b_dp.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(b_serial.score)[:, :n],
                               np.asarray(b_dp.score)[:, :n],
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("hist_dtype", ["int8", "float32"])
def test_data_parallel_reduce_scatter_matches_psum(hist_dtype):
    """The reference's ReduceScatter ownership schedule
    (data_parallel_tree_learner.cpp:135-235) as psum_scatter + owned-block
    search + SplitInfo allreduce must produce the SAME trees as the
    full-psum schedule: bit-identical under int8 (the int accumulators are
    scattered in the int domain), and equal-structure within float
    tolerance under f32.  F=10 is deliberately not divisible by the
    8-shard mesh (feature padding path)."""
    rng = np.random.RandomState(23)
    n, f = 1999, 10
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.4 * rng.randn(n)) > 0).astype(int)
    ds = Dataset.from_arrays(x, y.astype(np.float32), max_bin=32)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 4, "learning_rate": 0.2,
              "grow_policy": "depthwise", "hist_dtype": hist_dtype,
              "bagging_fraction": 0.8, "bagging_freq": 2, "bagging_seed": 5}

    def make(schedule):
        cfg = OverallConfig()
        p = dict(params, tree_learner="data", num_machines=8,
                 dp_schedule=schedule)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        from lightgbm_tpu.parallel import create_parallel_learner
        learner = create_parallel_learner(cfg)
        b.init(cfg.boosting_config, ds, obj, learner=learner)
        assert b.chunk_supported(False)
        b.train_chunk(4)
        return b

    b_psum = make("psum")
    b_rs = make("reduce_scatter")
    assert len(b_psum.models) == len(b_rs.models) == 4
    for k, (t1, t2) in enumerate(zip(b_psum.models, b_rs.models)):
        assert t1.num_leaves == t2.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature,
                                      err_msg=f"tree {k}")
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin,
                                      err_msg=f"tree {k}")
        if hist_dtype == "int8":
            # the int accumulators are identical by construction (int
            # sums are order-free), so the histograms agree bit-for-bit;
            # the f32 post-processing (dequantize/cumsum/outputs) is
            # compiled per schedule and XLA's fusion/FMA choices may
            # differ by a couple ulps — assert at ulp scale (1e-6, the
            # same cross-program budget the other schedule tests use;
            # this environment's XLA CPU measures up to ~5e-7)
            np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                       rtol=1e-6, atol=1e-9,
                                       err_msg=f"tree {k}")
        else:
            np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                       rtol=1e-5, atol=1e-7,
                                       err_msg=f"tree {k}")


@pytest.mark.parametrize("hist_dtype", ["int8", "float32"])
def test_data_parallel_leafwise_reduce_scatter(hist_dtype):
    """Leaf-wise growth under the reference's ReduceScatter ownership
    schedule — its ACTUAL N-machine mode
    (data_parallel_tree_learner.cpp:135-235 driving
    serial_tree_learner.cpp:119-153): per-split smaller-child histograms
    psum_scatter'd by feature block (int domain for int8), owned-feature
    search, packed SplitInfo allreduce.  Must match serial trees and the
    psum schedule; the dispatch-SEGMENTED variant (leafwise_segments=3,
    VERDICT r4 #4) must match the one-dispatch variant.  F=10 is not
    divisible by the 8-shard mesh, so one shard owns only feature
    padding — the replicated-root-stat path."""
    rng = np.random.RandomState(29)
    n, f = 1999, 10
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.4 * rng.randn(n)) > 0)
    ds = Dataset.from_arrays(x, y.astype(np.float32), max_bin=32)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 4, "learning_rate": 0.2,
              "grow_policy": "leafwise", "hist_dtype": hist_dtype,
              "bagging_fraction": 0.8, "bagging_freq": 2, "bagging_seed": 5}

    def make(tree_learner, **extra):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner, **extra)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = None
        if tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        b.init(cfg.boosting_config, ds, obj, learner=learner)
        for _ in range(4):
            b.train_one_iter(is_eval=False)
        return b

    b_serial = make("serial")
    b_rs = make("data", num_machines=8, dp_schedule="reduce_scatter")
    b_seg = make("data", num_machines=8, dp_schedule="reduce_scatter",
                 leafwise_segments=3)
    b_psum = make("data", num_machines=8, dp_schedule="psum")

    for name, b in (("rs", b_rs), ("rs-seg", b_seg), ("psum", b_psum)):
        assert len(b.models) == 4, name
        for k, (t1, t2) in enumerate(zip(b_serial.models, b.models)):
            assert t1.num_leaves == t2.num_leaves, f"{name} tree {k}"
            np.testing.assert_array_equal(
                t1.split_feature, t2.split_feature,
                err_msg=f"{name} tree {k}")
            np.testing.assert_array_equal(
                t1.threshold_bin, t2.threshold_bin,
                err_msg=f"{name} tree {k}")
            # int8: int accumulators identical by construction, only the
            # per-program f32 dequantize/search fusion may differ by an
            # ulp; f32: psum reduction order differs from the serial sum
            tol = dict(rtol=3e-7, atol=1e-9) if hist_dtype == "int8" \
                else dict(rtol=1e-5, atol=1e-7)
            np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                       err_msg=f"{name} tree {k}", **tol)
    # segmented == unsegmented: same shard closure, split loop cut into
    # dispatches — trees must agree to the same per-program tolerance
    for k, (t1, t2) in enumerate(zip(b_rs.models, b_seg.models)):
        assert t1.num_leaves == t2.num_leaves, f"seg tree {k}"
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=3e-7, atol=1e-9,
                                   err_msg=f"seg tree {k}")


@pytest.mark.parametrize("hist_dtype", ["int8", "float32"])
def test_data_parallel_leafwise_compact_schedules(hist_dtype):
    """The COMPACTED leaf-wise grower under BOTH data-parallel
    histogram-reduction schedules: serial ≡ compact-reduce_scatter ≡
    compact-psum trees.  The reduce_scatter path composes the reference's
    ownership schedule (feature-block psum_scatter — int domain for the
    quantized path — owned-slice hist cache + split search, packed
    SplitInfo allreduce) onto the compacted grower; there is no
    masked-grower fall-through anymore.  f32 asserts exact tree
    structure; int8 leaf values to 1 ulp (the int accumulators are
    order-free, only per-program f32 dequantize/search fusion differs).
    F=6 on the 8-shard mesh leaves two shards owning only feature
    padding — the replicated-root-stat path."""
    from lightgbm_tpu import telemetry
    rng = np.random.RandomState(31)
    n, f = 2999, 6                       # 2999 % 8 != 0 -> row padding
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.3 * rng.randn(n)) > 0)
    ds = Dataset.from_arrays(x, y.astype(np.float32), max_bin=32)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
              "num_iterations": 4, "learning_rate": 0.1,
              "grow_policy": "leafwise", "hist_dtype": hist_dtype,
              "leafwise_compact": "true"}

    def make(tree_learner, **extra):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner, **extra)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = None
        if tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        b.init(cfg.boosting_config, ds, obj, learner=learner)
        for _ in range(4):
            b.train_one_iter(is_eval=False)
        return b

    b_serial = make("serial")
    telemetry.enable()
    try:
        b_rs = make("data", num_machines=8, dp_schedule="reduce_scatter")
        # the compacted grower actually ran under the ownership schedule
        # (the route counter is the runtime record of the fall-through's
        # absence)
        assert telemetry.counters().get("learner/dp_compact_rs", 0) > 0
    finally:
        telemetry.disable()
    b_psum = make("data", num_machines=8, dp_schedule="psum")

    for name, b in (("compact-rs", b_rs), ("compact-psum", b_psum)):
        assert len(b.models) == 4, name
        for k, (t1, t2) in enumerate(zip(b_serial.models, b.models)):
            assert t1.num_leaves == t2.num_leaves, f"{name} tree {k}"
            np.testing.assert_array_equal(
                t1.split_feature, t2.split_feature,
                err_msg=f"{name} tree {k}")
            np.testing.assert_array_equal(
                t1.threshold_bin, t2.threshold_bin,
                err_msg=f"{name} tree {k}")
            # int8: int-domain reductions are order-free — 1 ulp of
            # per-program f32 dequantize/search fusion is the only slack;
            # f32: psum reduction order differs from the serial sum
            # (same budget the other compact e2e tests use)
            tol = dict(rtol=1e-6, atol=1e-9) if hist_dtype == "int8" \
                else dict(rtol=1e-4, atol=1e-6)
            np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                       err_msg=f"{name} tree {k}", **tol)
    # the two schedules agree with each other to the same budget
    for k, (t1, t2) in enumerate(zip(b_rs.models, b_psum.models)):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-6 if hist_dtype == "int8"
                                   else 1e-4,
                                   atol=1e-9 if hist_dtype == "int8"
                                   else 1e-6, err_msg=f"tree {k}")


def test_dp_schedule_auto_resolution(monkeypatch):
    """dp_schedule=auto follows the reference: psum on a single-process
    mesh, the ReduceScatter ownership schedule on true multi-process runs
    (the reference's N-machine mode IS that schedule)."""
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "tree_learner": "data",
             "num_machines": "8"}, require_data=False)
    assert cfg.boosting_config.tree_config.dp_schedule == "auto"
    from lightgbm_tpu.parallel.learners import DataParallelLearner
    learner = DataParallelLearner(cfg)
    assert learner._schedule() == "psum"          # process_count() == 1
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert learner._schedule() == "reduce_scatter"
    cfg2 = OverallConfig()
    cfg2.set({"objective": "binary", "tree_learner": "data",
              "num_machines": "8", "dp_schedule": "psum"},
             require_data=False)
    assert DataParallelLearner(cfg2)._schedule() == "psum"  # explicit wins
