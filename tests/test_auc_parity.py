"""AUC-parity quality gate vs the compiled reference binary (slow).

The north-star quality axis (BASELINE.md: "AUC parity with reference
LightGBM") as an automated test: 100 boosting iterations on 100k
Higgs-style rows, held-out AUC within 0.005 of the reference binary, for
the depthwise (headline), leafwise (reference-parity order) and
quantized-int8 configurations.

Split-finding math is identical to production; only the histogram
ACCUMULATION is routed through the scatter-add oracles
(histogram_leafbatch_segsum / hist_quant_segsum) because the dense one-hot
matmul is a TPU formulation that would take hours on the CPU CI mesh —
f32 sums differ from the matmul path only in reduction order, and the int8
path is bit-identical (int32 accumulation is order-free).
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRAIN_ROWS = 100_000
TEST_ROWS = 30_000
ITERS = 100
AUC_TOL = 0.005


def _auc(labels, scores):
    order = np.argsort(scores)
    ranks = np.empty(len(scores))
    sv = np.asarray(scores)[order]
    uniq, inv, counts = np.unique(sv, return_inverse=True,
                                  return_counts=True)
    start = np.zeros(len(uniq))
    start[1:] = np.cumsum(counts)[:-1]
    ranks[order] = (start + (counts + 1) / 2.0)[inv]
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


@pytest.fixture(scope="module")
def parity_data():
    from bench import make_data
    x, y = make_data(TRAIN_ROWS + TEST_ROWS, 28, seed=17)
    return (x[:TRAIN_ROWS], y[:TRAIN_ROWS],
            x[TRAIN_ROWS:], y[TRAIN_ROWS:])


CONF = {"objective": "binary", "learning_rate": "0.1", "num_leaves": "255",
        "max_bin": "255", "min_data_in_leaf": "100",
        "min_sum_hessian_in_leaf": "10.0"}


@pytest.fixture(scope="module")
def reference_auc(reference_binary, parity_data, tmp_path_factory):
    xtr, ytr, xte, yte = parity_data
    d = tmp_path_factory.mktemp("auc_parity")
    tr, te = str(d / "tr.csv"), str(d / "te.csv")
    np.savetxt(tr, np.column_stack([ytr, xtr]), fmt="%.7g", delimiter=",")
    np.savetxt(te, np.column_stack([yte, xte]), fmt="%.7g", delimiter=",")
    model = str(d / "model.txt")
    conf = str(d / "train.conf")
    with open(conf, "w") as f:
        f.write("task=train\n" + f"data={tr}\nnum_trees={ITERS}\n"
                + "".join(f"{k}={v}\n" for k, v in CONF.items())
                + f"metric_freq=1000\noutput_model={model}\n")
    subprocess.run([reference_binary, f"config={conf}"], check=True,
                   capture_output=True, text=True)
    pconf = str(d / "pred.conf")
    out = str(d / "pred.txt")
    with open(pconf, "w") as f:
        f.write(f"task=predict\ndata={te}\ninput_model={model}\n"
                f"output_result={out}\nis_sigmoid=false\n")
    subprocess.run([reference_binary, f"config={pconf}"], check=True,
                   capture_output=True, text=True)
    return _auc(yte, np.loadtxt(out))


def _train_ours(parity_data, grow_policy, hist_dtype, monkeypatch):
    import jax
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.models import grower as grower_mod
    from lightgbm_tpu.models import grower_depthwise as gd_mod
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.ops import histogram as hist_mod

    # CPU-fast scatter-add accumulation (see module docstring)
    if hist_dtype == "int8":
        monkeypatch.setattr(gd_mod, "histogram_leafbatch",
                            hist_mod.hist_quant_segsum)
    elif hist_dtype == "bfloat16":
        # model the TPU float-gradient Pallas kernel's operand rounding
        # (ops/hist_pallas bf16v: grad/hess ride bf16, f32 accumulation;
        # order differs from the kernel like the f32 oracle does)
        import jax.numpy as jnp

        def bf16_seg(bins, grad, hess, cid, ok, C, B, **kw):
            g = grad.astype(jnp.bfloat16).astype(jnp.float32)
            h = hess.astype(jnp.bfloat16).astype(jnp.float32)
            return hist_mod.histogram_leafbatch_segsum(bins, g, h, cid,
                                                       ok, C, B)
        monkeypatch.setattr(gd_mod, "histogram_leafbatch", bf16_seg)
        # keep hist_dtype=float32 in the config below: the segsum stub
        # above carries the bf16 semantics, and the real bfloat16 config
        # value would re-route to the einsum with bf16 operands (slow on
        # the CPU mesh)
        hist_dtype = "float32"
    else:
        monkeypatch.setattr(gd_mod, "histogram_leafbatch",
                            hist_mod.histogram_leafbatch_segsum)

        def fast_build(bins, grad, hess, mask, num_bins_max, **kw):
            return hist_mod.histogram_segsum(bins, grad, hess, mask,
                                             num_bins_max)
        monkeypatch.setattr(grower_mod, "build_histogram", fast_build)

    xtr, ytr, xte, yte = parity_data
    ds = Dataset.from_arrays(xtr, ytr, max_bin=255)
    cfg = OverallConfig()
    cfg.set({**CONF, "num_iterations": str(ITERS),
             "grow_policy": grow_policy, "hist_dtype": hist_dtype},
            require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds,
                 create_objective(cfg.objective_type, cfg.objective_config))
    done = 0
    while done < ITERS:
        k = min(25, ITERS - done)
        booster.train_chunk(k)
        done += k
    jax.block_until_ready(booster.score)
    return _auc(yte, booster.predict_raw(xte))


@pytest.mark.slow
@pytest.mark.parametrize("grow_policy,hist_dtype", [
    ("depthwise", "float32"),
    ("leafwise", "float32"),
    ("depthwise", "int8"),
    ("depthwise", "bfloat16"),
])
def test_auc_parity_vs_reference(parity_data, reference_auc, grow_policy,
                                 hist_dtype, monkeypatch):
    ours = _train_ours(parity_data, grow_policy, hist_dtype, monkeypatch)
    assert ours >= reference_auc - AUC_TOL, (
        f"{grow_policy}/{hist_dtype}: AUC {ours:.6f} vs reference "
        f"{reference_auc:.6f} (tol {AUC_TOL})")
