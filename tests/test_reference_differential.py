"""Differential tests against the compiled reference binary.

The reference's own design guarantees deterministic trees for deterministic
configs (no bagging, feature_fraction=1), so the compiled reference binary
is an exact oracle for binning, split finding, leaf values, model-file
format and prediction (SURVEY §4: "a powerful differential-testing oracle
the original authors never encoded as a test").

What is (and isn't) asserted: the FIRST boosting iteration's trees must
match the reference exactly — same binning, histogram sums, split gains,
tie-breaks and leaf values.  Later trees are NOT compared structurally: the
reference accumulates histograms in double (bin.h:15-17) while the TPU
kernels accumulate f32 via matmul tree-reduction, so one near-tied gain can
legitimately pick a different feature and every subsequent tree cascades
(observed: tree 0 and 25/30 splits of tree 1 identical, then divergence).
Model-format interchangeability and end-metric parity are asserted instead.

The binary is built once per host into /tmp (the reference's CMake insists
on writing the executable into its own source dir, so the source tree is
copied to /tmp first; /root/reference itself is never touched).  Tests skip
if the toolchain or examples are unavailable.
"""
import os
import re
import shutil
import subprocess

import numpy as np
import pytest

REFERENCE = "/root/reference"


# reference_binary fixture lives in conftest.py (shared with
# test_auc_parity.py)


DET = ["feature_fraction=1.0", "bagging_fraction=1.0", "bagging_freq=0",
       "early_stopping_round=0"]

EXAMPLES = {
    "binary_classification": ("binary.train", "binary.test",
                              "binary.train.weight", "binary.test.weight",
                              "train.conf", "predict.conf"),
    "regression": ("regression.train", "regression.test",
                   "train.conf", "predict.conf"),
    "multiclass_classification": ("multiclass.train", "multiclass.test",
                                  "train.conf", "predict.conf"),
    "lambdarank": ("rank.train", "rank.test", "rank.train.query",
                   "rank.test.query", "train.conf", "predict.conf"),
}


def _parse_model_trees(path):
    """Parse a LightGBM text model into per-tree dicts (format of
    Tree::ToString, /root/reference/src/io/tree.cpp:111-136)."""
    trees = []
    cur = None
    for line in open(path):
        line = line.strip()
        if line.startswith("Tree="):
            cur = {}
            trees.append(cur)
        elif "=" in line and cur is not None:
            k, v = line.split("=", 1)
            cur[k] = v
    parsed = []
    for t in trees:
        d = {"num_leaves": int(t["num_leaves"])}
        for key in ("split_feature", "threshold", "leaf_value", "split_gain",
                    "left_child", "right_child"):
            if key in t and t[key]:
                vals = t[key].split()
                d[key] = (np.asarray(vals, dtype=float)
                          if key in ("threshold", "leaf_value", "split_gain")
                          else np.asarray(vals, dtype=int))
        parsed.append(d)
    return parsed


def _run_reference(binary, workdir, conf, extra):
    return subprocess.run([binary, f"config={conf}"] + extra, cwd=workdir,
                          check=True, capture_output=True, text=True)


def _setup_example(tmp_path, task):
    src = os.path.join(REFERENCE, "examples", task)
    if not os.path.isdir(src):
        pytest.skip("reference examples not available")
    for f in EXAMPLES[task]:
        p = os.path.join(src, f)
        if os.path.exists(p):
            shutil.copy(p, tmp_path / f)
    return tmp_path


def _run_ours(tmp_path, monkeypatch, extra):
    from lightgbm_tpu.cli import Application
    monkeypatch.chdir(tmp_path)
    Application(["config=train.conf"] + extra).run()


def _assert_tree_equal(rt, tt, label, leaf_rtol=5e-4):
    __tracebackhide__ = True
    assert rt["num_leaves"] == tt["num_leaves"], f"{label} shape"
    np.testing.assert_array_equal(rt["split_feature"], tt["split_feature"],
                                  err_msg=f"{label} split features")
    np.testing.assert_allclose(rt["threshold"], tt["threshold"],
                               rtol=1e-6, atol=1e-12,
                               err_msg=f"{label} thresholds")
    np.testing.assert_array_equal(rt["left_child"], tt["left_child"],
                                  err_msg=f"{label} left children")
    np.testing.assert_array_equal(rt["right_child"], tt["right_child"],
                                  err_msg=f"{label} right children")
    np.testing.assert_allclose(rt["leaf_value"], tt["leaf_value"],
                               rtol=leaf_rtol, atol=1e-6,
                               err_msg=f"{label} leaf values")


def _assert_tree_prefix(rt, tt, label, min_prefix):
    """Exact agreement up to the first divergence, which must not occur
    before ``min_prefix`` splits.  A single near-tied gain flipped by the
    double-vs-f32 histogram accumulation legitimately changes every split
    after it (the tree's candidate set changes), so the provable property is
    a long exact prefix, not bitwise identity."""
    __tracebackhide__ = True
    assert rt["num_leaves"] == tt["num_leaves"], f"{label} shape"
    n = len(rt["split_feature"])
    same = ((rt["split_feature"] == tt["split_feature"])
            & np.isclose(rt["threshold"], tt["threshold"],
                         rtol=1e-6, atol=1e-12))
    div = int(np.argmin(same)) if not same.all() else n
    assert div >= min_prefix, (
        f"{label}: diverges at split {div} (< {min_prefix}); "
        f"features {rt['split_feature'][div]} vs {tt['split_feature'][div]}")


@pytest.mark.parametrize("task,extra,first_trees,min_prefix", [
    ("binary_classification", ["num_leaves=31", "min_data_in_leaf=50"], 1, 30),
    ("binary_classification", ["num_leaves=7", "min_data_in_leaf=20"], 1, 6),
    ("binary_classification", ["num_leaves=63", "min_data_in_leaf=100",
                               "min_sum_hessian_in_leaf=10.0"], 1, 62),
    ("regression", ["num_leaves=31", "min_data_in_leaf=50"], 1, 30),
    # multiclass: all 5 class trees of iteration 0 are first trees; the
    # uniform softmax start (p=1/5 everywhere) makes near-tied gains
    # common, so require a long exact prefix instead of full identity
    ("multiclass_classification", ["num_leaves=31", "min_data_in_leaf=50"],
     5, 15),
])
def test_first_iteration_trees_exact(reference_binary, tmp_path, monkeypatch,
                                     task, extra, first_trees, min_prefix):
    """First-iteration trees match the reference binary exactly (or to a
    long exact prefix where knife-edge ties exist): one shot validates
    binning, (weighted) gradients, histogram sums, gain formula, constraint
    handling, tie-breaking and leaf outputs for each objective."""
    _setup_example(tmp_path, task)
    cfg = DET + ["num_trees=2"] + extra
    _run_reference(reference_binary, tmp_path, "train.conf",
                   cfg + ["output_model=ref_model.txt"])
    _run_ours(tmp_path, monkeypatch, cfg + ["output_model=tpu_model.txt"])
    ref = _parse_model_trees(tmp_path / "ref_model.txt")
    tpu = _parse_model_trees(tmp_path / "tpu_model.txt")
    assert len(ref) == len(tpu)
    for i in range(first_trees):
        nsplits = len(ref[i]["split_feature"])
        if min_prefix >= nsplits:
            _assert_tree_equal(ref[i], tpu[i], f"{task} tree {i}")
        else:
            _assert_tree_prefix(ref[i], tpu[i], f"{task} tree {i}",
                                min_prefix)


def test_lambdarank_ndcg_parity(reference_binary, tmp_path, monkeypatch,
                                capfd):
    """Lambdarank cannot be compared tree-for-tree: the reference ranks
    tied scores with UNSTABLE std::sort (rank_objective.hpp:98-99), and at
    iteration 1 ALL scores are tied, so its own gradients depend on the
    sort implementation.  Learning quality (NDCG trajectory) is the
    comparable contract."""
    _setup_example(tmp_path, "lambdarank")
    cfg = DET + ["num_trees=20", "num_leaves=31", "min_data_in_leaf=50"]
    res = _run_reference(reference_binary, tmp_path, "train.conf",
                         cfg + ["output_model=ref_model.txt"])
    ref_ndcg = _metric_values(res.stdout.splitlines(), "NDCG@5")

    _run_ours(tmp_path, monkeypatch, cfg + ["output_model=tpu_model.txt"])
    out = capfd.readouterr()
    tpu_ndcg = _metric_values((out.out + out.err).splitlines(), "NDCG@5")

    ref_last = ref_ndcg[max(ref_ndcg)]
    tpu_last = tpu_ndcg[max(tpu_ndcg)]
    # one-sided: we must not rank meaningfully worse (being better is fine;
    # observed: 0.555 vs the reference's 0.522 on the example data)
    assert tpu_last >= ref_last - 0.02, (ref_last, tpu_last)


def test_model_format_interchangeable(reference_binary, tmp_path,
                                      monkeypatch):
    """Each side predicts with the OTHER side's model file and must
    reproduce the owner's predictions — the text model format and the
    prediction semantics are interchangeable."""
    _setup_example(tmp_path, "binary_classification")
    cfg = DET + ["num_trees=8", "num_leaves=31", "min_data_in_leaf=50"]
    _run_reference(reference_binary, tmp_path, "train.conf",
                   cfg + ["output_model=ref_model.txt"])
    _run_ours(tmp_path, monkeypatch, cfg + ["output_model=tpu_model.txt"])

    from lightgbm_tpu.cli import Application

    # reference predicts with our model vs us with our model
    _run_reference(reference_binary, tmp_path, "predict.conf",
                   ["input_model=tpu_model.txt",
                    "output_result=ref_on_tpu.txt"])
    Application(["config=predict.conf", "input_model=tpu_model.txt",
                 "output_result=tpu_on_tpu.txt"]).run()
    np.testing.assert_allclose(np.loadtxt(tmp_path / "ref_on_tpu.txt"),
                               np.loadtxt(tmp_path / "tpu_on_tpu.txt"),
                               rtol=1e-5, atol=1e-7)

    # we predict with the reference's model vs reference with its model
    _run_reference(reference_binary, tmp_path, "predict.conf",
                   ["input_model=ref_model.txt",
                    "output_result=ref_on_ref.txt"])
    Application(["config=predict.conf", "input_model=ref_model.txt",
                 "output_result=tpu_on_ref.txt"]).run()
    np.testing.assert_allclose(np.loadtxt(tmp_path / "ref_on_ref.txt"),
                               np.loadtxt(tmp_path / "tpu_on_ref.txt"),
                               rtol=1e-5, atol=1e-7)


def _metric_values(lines, metric_substr):
    out = {}
    for l in lines:
        m = re.search(r"Iteration:(\d+), ([^:]+) : ([0-9.eE+-]+)", l)
        if m and metric_substr in m.group(2):
            out[int(m.group(1))] = float(m.group(3))
    return out


def test_metric_parity(reference_binary, tmp_path, monkeypatch, capfd):
    """First-iteration metrics match tightly (identical trees); final
    metrics stay within a few percent despite structural divergence —
    learning quality parity."""
    _setup_example(tmp_path, "binary_classification")
    cfg = DET + ["num_trees=20", "num_leaves=31", "min_data_in_leaf=50"]
    res = _run_reference(reference_binary, tmp_path, "train.conf",
                         cfg + ["output_model=ref_model.txt"])
    ref_auc = _metric_values(res.stdout.splitlines(), "AUC")
    ref_ll = _metric_values(res.stdout.splitlines(), "log loss")

    _run_ours(tmp_path, monkeypatch, cfg + ["output_model=tpu_model.txt"])
    out = capfd.readouterr()
    lines = (out.out + out.err).splitlines()
    tpu_auc = _metric_values(lines, "AUC")
    tpu_ll = _metric_values(lines, "log loss")

    assert set(ref_auc) == set(tpu_auc) and len(ref_auc) >= 20
    # iteration 1: identical trees -> near-identical metrics
    assert abs(ref_auc[1] - tpu_auc[1]) < 1e-6
    assert abs(ref_ll[1] - tpu_ll[1]) < 1e-4
    # final iteration: parity within a few percent
    last = max(ref_auc)
    assert abs(ref_auc[last] - tpu_auc[last]) < 0.01
    assert abs(ref_ll[last] - tpu_ll[last]) / ref_ll[last] < 0.03


def test_depthwise_first_tree_split_set(reference_binary, tmp_path,
                                        monkeypatch):
    """grow_policy=depthwise on a full binary tree (num_leaves=4 = two full
    levels) finds the same split set and leaf values as the reference's
    leaf-wise order for the first tree."""
    _setup_example(tmp_path, "binary_classification")
    cfg = DET + ["num_trees=1", "num_leaves=4", "min_data_in_leaf=50"]
    _run_reference(reference_binary, tmp_path, "train.conf",
                   cfg + ["output_model=ref_model.txt"])
    _run_ours(tmp_path, monkeypatch,
              cfg + ["grow_policy=depthwise", "output_model=tpu_model.txt"])
    ref = _parse_model_trees(tmp_path / "ref_model.txt")
    tpu = _parse_model_trees(tmp_path / "tpu_model.txt")
    assert len(ref) == len(tpu) == 1
    rt, tt = ref[0], tpu[0]
    assert rt["num_leaves"] == tt["num_leaves"]
    # the leafbatch einsum rounds differently from the leafwise matmul, so
    # one near-tied gain may flip (observed: 1 of 3); require the majority
    # of the split set to agree and the root split to be identical
    assert rt["split_feature"][0] == tt["split_feature"][0]
    from collections import Counter
    cr = Counter(rt["split_feature"].tolist())
    ct = Counter(tt["split_feature"].tolist())
    n_common = sum((cr & ct).values())
    assert n_common >= len(rt["split_feature"]) - 1, (cr, ct)


def test_binning_count_ties_reference_sortforpair_defect(
        reference_binary, tmp_path, monkeypatch):
    """Adversarial count-tie binning (VERDICT r2 weak #6) — this probe
    surfaced a genuine REFERENCE DEFECT rather than a divergence bug on
    our side: Common::SortForPair (common.h:362-381) writes back
    ``keys[i] = arr[i]`` for i in [start, arr.size()) although ``arr`` is
    0-indexed from ``start``, so the remainder value sort in
    BinMapper::FindBin (bin.cpp:93, start=bin_cnt) DROPS the bin_cnt
    smallest remainder values and leaves a stale tail whose content
    depends on std::sort's unstable tie order.  On a feature with three
    dedicated (count>mean) values the reference therefore loses the
    boundaries around its smallest remainder values (verified against a
    harness linking the reference's own bin.cpp: bounds
    [1.25 6.25 9 15.5 22 inf] — 1.25 is midpoint(-3, 5.5) because values
    1, 2, 4 vanished).

    We implement the INTENDED algorithm (documented divergence,
    PARITY.md): bit-for-bit emulation is not even well-defined, since the
    stale tail varies with the C++ standard library's introsort.  This
    test pins both behaviors so any drift on either side is caught, and
    asserts our intended bins find a strictly better first split (the
    defect loses real split candidates)."""
    from tests.test_binning import _adversarial_tie_values
    rng = np.random.RandomState(77)
    f1 = _adversarial_tie_values().copy()
    rng.shuffle(f1)
    n = f1.size
    f2 = rng.randn(n)
    y = ((f1 > 5.0) ^ (rng.rand(n) < 0.15)).astype(int)
    np.savetxt(tmp_path / "ties.csv", np.column_stack([y, f1, f2]),
               fmt="%.7g", delimiter=",")
    conf = tmp_path / "train.conf"
    conf.write_text(
        "task=train\ndata=ties.csv\nobjective=binary\nnum_leaves=2\n"
        "min_data_in_leaf=20\nmax_bin=10\nnum_iterations=1\n"
        "learning_rate=0.1\nmetric_freq=100\n")

    _run_reference(reference_binary, tmp_path, "train.conf",
                   ["output_model=ref.txt"] + DET)
    _run_ours(tmp_path, monkeypatch, ["output_model=ours.txt"] + DET)

    rt = _parse_model_trees(tmp_path / "ref.txt")[0]
    tt = _parse_model_trees(tmp_path / "ours.txt")[0]
    # the reference's defect-lossy bins pick threshold 6.25 (it no longer
    # HAS a 4.75 boundary — midpoint of the dropped 4 and surviving 5.5)
    assert rt["split_feature"][0] == 0 and tt["split_feature"][0] == 0
    assert np.isclose(rt["threshold"][0], 6.25)
    # ours keeps the intended boundary and finds the strictly better
    # split the reference lost
    assert np.isclose(tt["threshold"][0], 4.75)
    assert tt["split_gain"][0] > rt["split_gain"][0] * 1.2

    # non-adversarial binning agreement is covered by the exact-tree
    # differential suite; this test only pins the defect feature


def test_reference_bin_cache_fallback(reference_binary, tmp_path,
                                      monkeypatch):
    """A reference-written <data>.bin next to the data file (the reference
    auto-loads it, dataset.cpp:653-898) must not break 'configs run
    unchanged': our loader now loads the reference cache NATIVELY
    (io/dataset._load_reference_binary, see test_reference_bin_cache.py
    for the format differentials) and leaves it untouched even under
    is_save_binary_file=true (VERDICT r2 missing #4)."""
    _setup_example(tmp_path, "binary_classification")
    # have the reference binary write its own cache
    _run_reference(reference_binary, tmp_path, "train.conf",
                   ["num_trees=1", "is_save_binary_file=true",
                    "output_model=ref.txt"] + DET)
    bin_path = tmp_path / "binary.train.bin"
    assert bin_path.exists()
    ref_cache = bin_path.read_bytes()

    _run_ours(tmp_path, monkeypatch,
              ["num_trees=2", "num_leaves=15",
               "is_save_binary_file=true", "output_model=ours.txt"] + DET)
    model = (tmp_path / "ours.txt").read_text()
    assert model.count("Tree=") == 2          # trained (from the cache)
    assert bin_path.read_bytes() == ref_cache  # cache left untouched
