"""Differential pin across the three growth policies (ISSUE 9).

Recorded BEFORE the three grower modules were collapsed into
``models/grower_unified.py``: the same dataset/config trained under every
growth policy, asserting the known-equal surfaces —

- masked leaf-wise == compacted leaf-wise: identical split STRUCTURE
  (features, thresholds, leaf counts), leaf values within the repo's
  documented cross-program budget (recorded here: XLA CPU contracts the
  two growers' value math into different fusions — max observed delta
  ~3e-7 relative on this container, i.e. ulp dust, NOT bitwise — so the
  collapse must not be held to a bar the pre-collapse growers never met);
- every policy's model text matches the digest recorded from the
  pre-collapse growers on this container's CPU backend, so any silent
  behavioral drift introduced by the collapse (a seam applied twice, a
  reordered reduction, a changed tie-break) is caught here, not in a
  downstream bench round.

Digests are CPU-golden (the tier-1 environment pins JAX_PLATFORMS=cpu);
other backends skip the digest rows and keep the cross-policy equalities.
Set LGBM_TPU_PRINT_DIGESTS=1 to print current digests for re-recording.
"""
import hashlib
import os

import numpy as np
import jax
import pytest

from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective


def _data():
    rng = np.random.RandomState(97)
    n, f = 1200, 8
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.6 * x[:, 1] + 0.25 * x[:, 2]
          + 0.3 * rng.randn(n)) > 0).astype(np.float32)
    return x, y


def _train(x, y, *, grow_policy, leafwise_compact="false",
           hist_dtype="float32", iters=4):
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "num_leaves": "15",
             "min_data_in_leaf": "20", "min_sum_hessian_in_leaf": "1.0",
             "learning_rate": "0.2", "grow_policy": grow_policy,
             "leafwise_compact": leafwise_compact,
             "hist_dtype": hist_dtype}, require_data=False)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    b = GBDT()
    b.init(cfg.boosting_config, ds,
           create_objective(cfg.objective_type, cfg.objective_config))
    for _ in range(iters):
        if b.train_one_iter(is_eval=False):
            break
    return b


def _model_text(booster) -> str:
    return "\n".join("Tree=%d\n%s" % (i, t.to_string())
                     for i, t in enumerate(booster.models))


def _digest(booster) -> str:
    return hashlib.sha256(_model_text(booster).encode()).hexdigest()[:16]


# model-text digests recorded from the PRE-collapse growers (grower.py /
# grower_depthwise.py / grower_leafcompact.py as of PR 8) on this
# container's XLA CPU backend — the collapse must reproduce them exactly
RECORDED_CPU_DIGESTS = {
    "leafwise": "e339cc60be3d84e6",
    "leafwise_compact": "aabd036b9d78bc5d",
    "depthwise": "1d10ebf030a5c580",
}


@pytest.fixture(scope="module")
def boosters():
    x, y = _data()
    return {
        "leafwise": _train(x, y, grow_policy="leafwise"),
        "leafwise_compact": _train(x, y, grow_policy="leafwise",
                                   leafwise_compact="true"),
        "depthwise": _train(x, y, grow_policy="depthwise"),
    }


def test_all_policies_trained(boosters):
    for name, b in boosters.items():
        assert len(b.models) == 4, name
        for t in b.models:
            assert t.num_leaves > 1, name


def test_masked_equals_compact(boosters):
    """The compacted leaf-wise grower is the masked grower's split
    sequence with compacted data movement: identical split structure and
    leaf counts; leaf values/scores within the documented cross-program
    f32 budget (recorded pre-collapse: ulp-level fusion dust, see module
    docstring — NOT bitwise on XLA CPU)."""
    a, b = boosters["leafwise"], boosters["leafwise_compact"]
    for k, (t1, t2) in enumerate(zip(a.models, b.models)):
        assert t1.num_leaves == t2.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-5, atol=5e-7, err_msg=f"tree {k}")
    np.testing.assert_allclose(np.asarray(a.score), np.asarray(b.score),
                               rtol=1e-5, atol=2e-6)


def test_masked_equals_compact_int8():
    """Same pin under int8 histograms: structure exact; leaf values
    within the documented cross-program 1-ulp budget (XLA CPU contracts
    the dequantize multiply into an FMA in some program contexts —
    grower_leafcompact module docstring)."""
    x, y = _data()
    a = _train(x, y, grow_policy="leafwise", hist_dtype="int8")
    b = _train(x, y, grow_policy="leafwise", leafwise_compact="true",
               hist_dtype="int8")
    for k, (t1, t2) in enumerate(zip(a.models, b.models)):
        assert t1.num_leaves == t2.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-6, atol=1e-9, err_msg=f"tree {k}")


@pytest.mark.parametrize("policy", sorted(RECORDED_CPU_DIGESTS))
def test_model_text_digest_pinned(boosters, policy):
    """Every policy's model text matches the digest recorded from the
    pre-collapse growers — the drift detector for the collapse."""
    if jax.default_backend() != "cpu":
        pytest.skip("digests recorded on the XLA CPU backend")
    got = _digest(boosters[policy])
    if os.environ.get("LGBM_TPU_PRINT_DIGESTS") == "1":
        print("DIGEST %s %s" % (policy, got))
    assert got == RECORDED_CPU_DIGESTS[policy], (
        "%s model text drifted from the pre-collapse grower (got %s)"
        % (policy, got))
