"""Flight recorder + per-request latency attribution (ISSUE 16,
lightgbm_tpu/tracing.py + scripts/trace_report.py).

Correctness bars, in the ISSUE's order:

(a) the attribution identity: every traced request's six components
    (queue/linger/coalesce/dispatch/walk/scatter) sum EXACTLY to its
    observed wall time — per request, including across a mid-load
    ``swap_engine`` — an integer identity, not a tolerance;
(b) ring-overflow determinism: a full ring drops OLDEST events first
    and ``trace/dropped`` counts every overwrite exactly;
(c) streaming sketches: merge is associative (bucket-count addition)
    and any quantile is within a factor sqrt(growth) of the true sorted
    sample quantile at the same nearest-rank;
(d) dump-on-fault: an injected-raise training fault leaves a parseable
    JSONL dump that trace_report --check validates;
(e) lifecycle: the armed recorder is leak-guard-visible and
    ``telemetry.disable()`` disarms it; config knobs reject junk loudly.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import faults, lifecycle, telemetry, tracing
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.serving import ServingEngine, ServingFront
from lightgbm_tpu.utils.log import LightGBMError
from scripts import trace_report

BASE = {"num_leaves": 15, "min_data_in_leaf": 20,
        "min_sum_hessian_in_leaf": 1.0, "num_iterations": 8,
        "learning_rate": 0.2}

_CASE = {}


def _case():
    """(trained binary booster, features), cached once per session."""
    if not _CASE:
        rng = np.random.RandomState(3)
        x = rng.randn(500, 6)
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
        ds = Dataset.from_arrays(x, y, max_bin=64)
        _CASE["v"] = (lgb.train(dict(BASE, objective="binary"), ds), x)
    return _CASE["v"]


@pytest.fixture()
def recorder():
    """Armed recorder with telemetry enabled (counter mirror live);
    disarmed + disabled afterwards whatever the test did."""
    telemetry.enable(None)
    telemetry.reset()
    tracing.arm(ring_events=4096)
    yield
    tracing.disarm()
    telemetry.disable()
    telemetry.reset()


# ===================================== (a) the exact attribution identity


def test_attribute_identity_exhaustive_fuzz():
    """sum(components) == wall EXACTLY for any boundary junk: missing
    marks (None), boundaries before enqueue, after completion, or out of
    order — the clamp makes the telescoping unconditional."""
    rng = np.random.RandomState(11)
    for _ in range(2000):
        ts = int(rng.randint(0, 10_000))
        td = ts + int(rng.randint(0, 10_000))
        bounds = []
        for _k in range(5):
            r = rng.rand()
            if r < 0.2:
                bounds.append(None)
            else:
                bounds.append(int(rng.randint(-5000, 25_000)))
        comps = tracing.attribute(ts, td, bounds)
        assert set(comps) == set(tracing.COMPONENTS)
        assert all(v >= 0 for v in comps.values())
        assert sum(comps.values()) == td - ts


def test_attribute_known_decomposition():
    comps = tracing.attribute(100, 1100, (200, 300, None, 500, 900))
    assert comps == {"queue": 100, "linger": 100, "coalesce": 0,
                     "dispatch": 200, "walk": 400, "scatter": 200}


def _dump_events(tmp_path, name="d.jsonl"):
    path = str(tmp_path / name)
    assert tracing.dump(path=path, reason="test") == path
    header, events = trace_report.load(path)
    return path, header, events


def test_serve_identity_end_to_end(recorder, tmp_path):
    """Every request through the coalescing front gets a serve_complete
    whose components telescope exactly to its wall, with a unique
    nonzero trace id and its enqueue event earlier in ring order."""
    booster, x = _case()
    front = ServingFront(ServingEngine(booster.export_flat()),
                         linger_us=2000)
    try:
        futs = [front.submit(x[i * 10:(i + 1) * 10]) for i in range(20)]
        for f in futs:
            f.result(30)
    finally:
        front.close()
    path, header, events = _dump_events(tmp_path)
    comp = [e for e in events if e["kind"] == "serve_complete"]
    enq = [e for e in events if e["kind"] == "serve_enqueue"]
    assert len(comp) == 20 and len(enq) == 20
    ids = [e["trace"] for e in comp]
    assert len(set(ids)) == 20 and all(i > 0 for i in ids)
    for e in comp:
        assert sum(e["components_ns"][c]
                   for c in tracing.COMPONENTS) == e["wall_ns"]
        assert all(e["components_ns"][c] >= 0
                   for c in tracing.COMPONENTS)
    # the shipped validator agrees: zero findings on a clean dump
    assert trace_report.check(path, header, events) == []
    # sketches saw every request (wall + each component family)
    snap = tracing.snapshot()
    assert snap["sketches"]["serve_wall_us"]["count"] == 20
    for c in tracing.COMPONENTS:
        assert snap["sketches"]["serve_%s_us" % c]["count"] == 20


def test_serve_identity_across_mid_load_swap(recorder, tmp_path):
    """The identity holds for every request completed across a mid-load
    drain-and-flip swap, and the swap events land on the timeline."""
    booster, x = _case()
    eng_a = ServingEngine(booster.export_flat(len(booster.models) - 2))
    eng_b = ServingEngine(booster.export_flat())
    front = ServingFront(eng_a, linger_us=500)
    stop = threading.Event()
    futs = []

    def load():
        i = 0
        while not stop.is_set():
            s = (i * 20) % 480
            futs.append(front.submit(x[s:s + 20]))
            i += 1
            time.sleep(0.001)

    t = threading.Thread(target=load)
    try:
        t.start()
        time.sleep(0.1)
        front.swap_engine(eng_b)
        time.sleep(0.1)
    finally:
        stop.set()
        t.join(30)
        front.close()
    path, header, events = _dump_events(tmp_path)
    kinds = {e["kind"] for e in events}
    assert {"serve_swap_enqueue", "serve_swap_flip",
            "serve_complete"} <= kinds
    comp = [e for e in events if e["kind"] == "serve_complete"]
    assert len(comp) == len(futs) >= 20
    for e in comp:
        assert sum(e["components_ns"][c]
                   for c in tracing.COMPONENTS) == e["wall_ns"]
    assert trace_report.check(path, header, events) == []


# ========================================= (b) ring-overflow determinism


def test_ring_drops_oldest_and_counts_exactly(recorder, tmp_path):
    tracing.arm(ring_events=8)
    for i in range(21):
        tracing.event("tick", seq=i)
    snap = tracing.snapshot()
    assert snap["appended"] == 21
    assert snap["dropped"] == 13
    assert snap["events"] == 8
    # the counter mirror is exact, and repeated snapshots never
    # double-count (delta sync)
    assert telemetry.counters()["trace/dropped"] == 13
    tracing.snapshot()
    assert telemetry.counters()["trace/dropped"] == 13
    # retained window is the NEWEST 8, oldest-first
    _path, header, events = _dump_events(tmp_path)
    assert [e["seq"] for e in events] == list(range(13, 21))
    assert header["dropped"] == 13


def test_ring_keeps_everything_below_capacity(recorder):
    tracing.arm(ring_events=64)
    for i in range(10):
        tracing.event("tick", seq=i)
    snap = tracing.snapshot()
    assert (snap["appended"], snap["dropped"], snap["events"]) == (10, 0,
                                                                   10)
    assert telemetry.counters().get("trace/dropped", 0) == 0


# ============================================= (c) streaming sketches


def test_sketch_quantile_error_bound():
    """Any reported quantile is within a factor sqrt(growth) of the
    sorted sample's nearest-rank value — the bucket-resolution bound."""
    rng = np.random.RandomState(5)
    vals = np.exp(rng.randn(5000) * 1.5 + 3.0)
    sk = tracing.LatencySketch(1.05)
    for v in vals:
        sk.record(float(v))
    srt = np.sort(vals)
    tol = 1.05 ** 0.5 * (1 + 1e-9)
    for q in (0.01, 0.25, 0.50, 0.90, 0.99, 0.999):
        rank = min(len(srt) - 1, max(0, int(np.ceil(q * len(srt))) - 1))
        exact = float(srt[rank])
        got = sk.quantile(q)
        assert 1 / tol <= got / exact <= tol, (q, got, exact)
    # the mean holds the same relative bound
    assert 1 / tol <= sk.mean() / float(np.mean(vals)) <= tol


def test_sketch_merge_associative_and_lossless():
    """(a+b)+c == a+(b+c) bucket-for-bucket, and either equals the
    sketch of the concatenated sample — merge loses nothing."""
    rng = np.random.RandomState(9)
    parts = [np.exp(rng.randn(n)) * s
             for n, s in ((400, 10.0), (300, 200.0), (500, 1.0))]

    def _sk(arrays):
        sk = tracing.LatencySketch(1.05)
        for a in arrays:
            for v in a:
                sk.record(float(v))
        return sk

    a, b, c = (_sk([p]) for p in parts)
    left = _sk([parts[0]]).merge(_sk([parts[1]])).merge(_sk([parts[2]]))
    right_bc = _sk([parts[1]]).merge(_sk([parts[2]]))
    right = _sk([parts[0]]).merge(right_bc)
    whole = _sk(parts)
    for other in (right, whole):
        assert left.buckets == other.buckets
        assert left.zero == other.zero
    assert left.count == sum(len(p) for p in parts)
    # round-trips through the dump serialization unchanged
    back = tracing.LatencySketch.from_dict(
        json.loads(json.dumps(whole.to_dict())))
    assert back.buckets == whole.buckets and back.zero == whole.zero
    assert back.quantile(0.99) == whole.quantile(0.99)


def test_sketch_zero_bucket_and_guardrails():
    sk = tracing.LatencySketch()
    sk.record(0.0)
    sk.record(-5.0)
    sk.record(1.0)
    assert sk.zero == 2 and sk.count == 3
    assert sk.quantile(0.5) == 0.0
    with pytest.raises(ValueError):
        tracing.LatencySketch(1.0001)
    with pytest.raises(ValueError):
        tracing.LatencySketch(2.5)
    with pytest.raises(ValueError):
        tracing.LatencySketch(1.05).merge(tracing.LatencySketch(1.1))


# ================================================== (d) dump on fault


def _train_with_recorder(tmp_path, iters=6):
    rng = np.random.RandomState(7)
    x = rng.randn(400, 5)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    return lgb.train({"objective": "binary", "num_leaves": 7,
                      "min_data_in_leaf": 10,
                      "min_sum_hessian_in_leaf": 1.0,
                      "num_iterations": iters, "learning_rate": 0.2,
                      "bagging_fraction": 0.5, "bagging_freq": 1}, ds)


def test_dump_on_injected_fault_is_valid_jsonl(tmp_path):
    """faults raise-kind hatch: the ring flushes a parseable dump with
    reason fault:injected_raise BEFORE the raise escapes, and the dump
    passes trace_report --check."""
    # a real sink: per-iteration records (and so the recorder's
    # train_iter events) ride the metrics_out path, like the shipped
    # cli wiring that arms the recorder
    telemetry.enable(str(tmp_path / "metrics.jsonl"))
    telemetry.reset()
    tracing.arm(ring_events=1024, dump_dir=str(tmp_path))
    faults.arm(2, "raise")
    try:
        with pytest.raises(RuntimeError, match="injected fault"):
            _train_with_recorder(tmp_path)
    finally:
        faults.disarm()
        tracing.disarm()
        telemetry.disable()
        telemetry.reset()
    dumps = [f for f in os.listdir(str(tmp_path))
             if f.startswith("trace-") and f.endswith(".jsonl")]
    assert dumps, "fault path left no trace dump"
    path = str(tmp_path / sorted(dumps)[0])
    header, events = trace_report.load(path)
    assert header["reason"] == "fault:injected_raise"
    assert events, "fault dump retained no events"
    kinds = {e["kind"] for e in events}
    assert "train_iter" in kinds
    assert "bagging_draw" in kinds
    assert trace_report.check(path, header, events) == []


def test_clean_close_dumps_and_training_events_recorded(tmp_path):
    """telemetry.disable() disarms the recorder, which flushes a
    reason=close dump; the ring holds the training timeline (train_iter
    + bagging draws) and the train_iter_us sketch saw every iteration."""
    telemetry.enable(str(tmp_path / "metrics.jsonl"))
    telemetry.reset()
    tracing.arm(dump_dir=str(tmp_path))
    try:
        _train_with_recorder(tmp_path, iters=5)
        snap = tracing.snapshot()
        assert snap["sketches"]["train_iter_us"]["count"] == 5
        assert snap["default_ring"] is True
    finally:
        telemetry.disable()   # disarms tracing -> dumps reason=close
        telemetry.reset()
    assert not tracing.active()
    dumps = [f for f in os.listdir(str(tmp_path))
             if f.startswith("trace-")]
    assert len(dumps) == 1
    header, events = trace_report.load(str(tmp_path / dumps[0]))
    assert header["reason"] == "close"
    assert sum(1 for e in events if e["kind"] == "train_iter") == 5
    assert telemetry.counters() == {}   # reset cleared the mirror


def test_trace_report_check_catches_violations(tmp_path):
    """--check fails on a broken identity, an enqueue ordered after its
    completion, wrong header bookkeeping, and unparseable JSONL."""
    telemetry.enable(None)
    tracing.arm(ring_events=64)
    tracing.event("serve_enqueue", trace=1, rows=4, t_ns=100)
    tracing.record_serve_request(1, None, 100, 1100,
                                 (200, 300, 400, 500, 900), rows=4)
    path = str(tmp_path / "ok.jsonl")
    tracing.dump(path=path, reason="test")
    tracing.disarm()
    telemetry.disable()
    telemetry.reset()
    header, events = trace_report.load(path)
    assert trace_report.check(path, header, events) == []

    def _rewrite(name, header, events):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            f.write(json.dumps({"trace_header": header}) + "\n")
            for e in events:
                f.write(json.dumps(e) + "\n")
        return p

    # broken identity
    bad = [dict(e) for e in events]
    bad[-1] = dict(bad[-1], components_ns=dict(
        bad[-1]["components_ns"], walk=bad[-1]["components_ns"]["walk"]
        + 1))
    p = _rewrite("bad_identity.jsonl", header, bad)
    found = trace_report.check(p, *trace_report.load(p)[0:2])
    assert any("identity" in f for f in found)
    # enqueue after completion
    p = _rewrite("bad_order.jsonl", header, [events[1], events[0]])
    found = trace_report.check(p, *trace_report.load(p)[0:2])
    assert any("AFTER" in f for f in found)
    # header bookkeeping drift
    p = _rewrite("bad_header.jsonl", dict(header, events=7), events)
    found = trace_report.check(p, *trace_report.load(p)[0:2])
    assert any("lines present" in f for f in found)
    # unparseable JSONL
    p = str(tmp_path / "junk.jsonl")
    with open(p, "w") as f:
        f.write('{"trace_header": {}}\n{not json\n')
    with pytest.raises(trace_report.BadDump):
        trace_report.load(p)
    # completion with no enqueue is tolerated ONLY when events dropped
    orphan = [events[1]]
    p = _rewrite("orphan0.jsonl",
                 dict(header, events=1, appended=1, dropped=0), orphan)
    found = trace_report.check(p, *trace_report.load(p)[0:2])
    assert any("no enqueue" in f for f in found)
    p = _rewrite("orphan1.jsonl",
                 dict(header, events=1, appended=2, dropped=1), orphan)
    assert trace_report.check(p, *trace_report.load(p)[0:2]) == []


# ======================================== (e) lifecycle + config knobs


def test_leak_guard_sees_armed_recorder():
    """The trace-recorder lifecycle probe: armed shows up in leaks(),
    its closer disarms, and telemetry.disable() also disarms."""
    tracing.arm(ring_events=16)
    leaked = [(k, n, c) for k, n, c in lifecycle.leaks()
              if k == "trace-recorder"]
    assert leaked, "armed recorder invisible to the lifecycle registry"
    leaked[0][2]()                # the probe's closer (what conftest runs)
    assert not tracing.active()
    tracing.arm(ring_events=16)
    telemetry.disable()
    assert not tracing.active()
    telemetry.reset()


def test_disarmed_recorder_is_inert():
    assert not tracing.active()
    assert tracing.next_trace_id() == 0
    tracing.event("tick")          # all no-ops, nothing raises
    tracing.observe("serve_wall_us", 1.0)
    assert tracing.snapshot() == {}
    assert tracing.dump(reason="test") is None
    comps = tracing.record_serve_request(0, None, 0, 100,
                                         (10, 20, 30, 40, 50), rows=1)
    assert sum(comps.values()) == 100


def test_config_knobs_reject_junk_loudly(tmp_path):
    from lightgbm_tpu.config import OverallConfig
    with pytest.raises(LightGBMError):
        OverallConfig().set({"trace_ring_events": "0"}, require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"trace_sketch_growth": "3.0"},
                            require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"trace_sketch_growth": "1.00001"},
                            require_data=False)
    # a dump dir that cannot exist (parent is a FILE) rejects at parse
    blocker = tmp_path / "blocker"
    blocker.write_text("x")
    with pytest.raises(LightGBMError):
        OverallConfig().set(
            {"trace_dump_dir": str(blocker / "sub")}, require_data=False)
    # valid values round-trip
    cfg = OverallConfig()
    cfg.set({"trace_ring_events": "128",
             "trace_sketch_growth": "1.2",
             "trace_dump_dir": str(tmp_path / "dumps")},
            require_data=False)
    assert cfg.io_config.trace_ring_events == 128
    assert cfg.io_config.trace_sketch_growth == 1.2
    assert os.path.isdir(str(tmp_path / "dumps"))
    with pytest.raises(ValueError):
        tracing.arm(ring_events=0)
    with pytest.raises(ValueError):
        tracing.arm(sketch_growth=9.0)
