"""Live production monitoring (ISSUE 20, lightgbm_tpu/monitor.py +
scripts/monitor_report.py).

Correctness bars, in the ISSUE's order:

(a) window-delta conservation: over any fuzzed interleaving of counter
    bumps, traced latencies and ticks, the sum of the emitted window
    deltas equals the cumulative totals EXACTLY — counters and sketch
    counts both, and monitor_report --check agrees;
(b) sketch-subtraction exactness: window sketch = per-bucket integer
    subtraction of two cumulative sketches, never negative, and the
    window deltas re-merge to the cumulative sketch bucket-for-bucket;
(c) burn rate: hand-built bad/total decompositions produce the exact
    multi-window fast/slow burn rates, breach fires iff fast >= 5 AND
    slow >= 1, and zero traffic burns nothing;
(d) drift verdict: a synthetic shift trips PSI > 0.2 while the A/A
    self-check on the same healthy stream stays under the 0.05 bound;
(e) lifecycle: the emitter thread is leak-guard-visible while armed
    and joined on disarm; telemetry.disable() disarms the monitor;
(f) crash path: an injected-raise fault flushes a ``fault:*`` close
    record and the JSONL passes monitor_report --check;
(g) knobs reject junk loudly: monitor_interval_s <= 0,
    slo_window_s <= 0, and slo_p99_us > 0 without task=predict.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import faults, lifecycle, monitor, telemetry, tracing
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.utils.log import LightGBMError
from scripts import monitor_report


@pytest.fixture()
def armed(tmp_path):
    """Telemetry + recorder + monitor armed (manual ticks, no emitter
    thread); everything torn down whatever the test did.  Yields the
    monitor JSONL path."""
    path = str(tmp_path / "monitor.jsonl")
    telemetry.enable(None)
    telemetry.reset()
    tracing.arm(ring_events=4096)
    monitor.arm(out_path=path, interval_s=100.0, emitter=False)
    yield path
    monitor.disarm()
    tracing.disarm()
    telemetry.disable()
    telemetry.reset()


def _checked(path):
    header, windows, close, after = monitor_report.load(path)
    findings = monitor_report.check(path, header, windows, close, after)
    assert findings == [], findings
    return header, windows, close


# ======================================= (a) window-delta conservation


def test_window_delta_conservation_fuzz(armed):
    """Random counter bumps + traced latencies across random tick
    boundaries: sum(per-window deltas) == final cumulative totals,
    exactly, for every counter and every sketch family — and the
    shipped validator re-checks the same identity from the JSONL."""
    rng = np.random.RandomState(7)
    fams = ["serve_wall_us", "serve_queue_us", "ingest_parse_us"]
    keys = ["serve/requests", "serve/rows", "ingest/chunks"]
    bumped = {k: 0 for k in keys}
    observed = {f: 0 for f in fams}
    for _ in range(12):
        for _ in range(int(rng.randint(0, 40))):
            k = keys[rng.randint(len(keys))]
            n = int(rng.randint(1, 9))
            telemetry.count(k, n)
            bumped[k] += n
            f = fams[rng.randint(len(fams))]
            v = float(rng.randint(1, 100_000))
            tracing.observe(f, v)
            observed[f] += 1
        assert monitor.tick() is not None
    path = monitor.disarm()
    header, windows, close = _checked(path)
    assert close is not None and close["reason"] == "close"
    # counters: window deltas telescope to the close totals
    for k, total in bumped.items():
        assert sum(w["counters"].get(k, 0) for w in windows) == total
        assert close["counters_total"].get(k, 0) == total
    # sketch counts: same identity per family
    for f, total in observed.items():
        got = sum(
            sum((w["sketches"].get(f) or {"buckets": {}})["buckets"]
                .values()) + (w["sketches"].get(f) or {"zero": 0})["zero"]
            for w in windows)
        assert got == total
        assert windows[-1]["sketch_counts_total"].get(f, 0) == total


def test_empty_windows_are_empty(armed):
    """Ticks with zero traffic emit structurally valid, delta-empty
    windows — no phantom counts, ids still consecutive."""
    for _ in range(4):
        rec = monitor.tick()
        assert rec["counters"] == {} or set(rec["counters"]) <= {
            "monitor/windows"}
        for skd in rec["sketches"].values():
            assert skd["zero"] + sum(skd["buckets"].values()) == 0
    path = monitor.disarm()
    _header, windows, _close = _checked(path)
    assert [w["window"] for w in windows] == list(
        range(1, len(windows) + 1))


# ======================================= (b) sketch-subtraction exact


def test_sketch_subtract_exact_and_nonnegative():
    """cur - prev is per-bucket integer subtraction; merging the delta
    back onto prev reproduces cur bucket-for-bucket (the associativity
    that makes windowed sketches exact, not approximate)."""
    rng = np.random.RandomState(3)
    prev = tracing.LatencySketch()
    for v in rng.randint(1, 1_000_000, size=500):
        prev.record(float(v))
    cur = tracing.LatencySketch.from_dict(prev.to_dict())
    extra = rng.randint(1, 1_000_000, size=700)
    for v in extra:
        cur.record(float(v))
    delta = monitor.sketch_subtract(cur, prev)
    assert delta.count == len(extra)
    assert all(c >= 0 for c in delta.buckets.values())
    # remerge: prev + delta == cur, exactly
    merged = tracing.LatencySketch.from_dict(prev.to_dict())
    merged.merge(delta)
    assert merged.to_dict() == cur.to_dict()
    # against None/empty, the delta IS the cumulative sketch
    assert monitor.sketch_subtract(cur, None).to_dict() == cur.to_dict()


def test_bad_count_threshold_boundary():
    """bad_count uses the bucket representative (growth**(i+0.5)): a
    bucket counts as bad iff its representative exceeds the target, so
    hand-placed values decompose exactly."""
    sk = tracing.LatencySketch()
    for v in (10.0, 10.0, 50_000.0, 50_000.0, 50_000.0):
        sk.record(v)
    assert monitor.bad_count(sk, 1_000.0) == 3
    assert monitor.bad_count(sk, 1.0) == 5
    assert monitor.bad_count(sk, 10_000_000.0) == 0


# ============================================= (c) burn-rate arithmetic


def _slo_windows(pattern, slo_us=1_000.0, interval=10.0,
                 window_s=120.0):
    """Arm with a 12:1 short:long split (short=1, long=12 windows) and
    play ``pattern`` — a list of (n_bad, n_good) per window, bad =
    above slo_us.  Returns the per-window slo blocks."""
    monitor.arm(interval_s=interval, slo_p99_us=slo_us,
                slo_window_s=window_s, emitter=False)
    out = []
    for n_bad, n_good in pattern:
        for _ in range(n_bad):
            tracing.observe("serve_wall_us", slo_us * 100.0)
        for _ in range(n_good):
            tracing.observe("serve_wall_us", slo_us / 100.0)
        out.append(monitor.tick()["slo"])
    return out


def test_burn_rate_known_decompositions(armed):
    """Hand-built windows: burn = (bad/total)/budget over the trailing
    short (1) and long (12) windows; breach iff fast >= 5 AND slow >= 1."""
    # window 1: 5 bad / 100 -> 5% bad = 5x budget on BOTH arms (ring
    # only holds one window) -> breach
    # window 2: clean 100 -> fast 0, slow (5/200)/0.01 = 2.5 -> no breach
    # window 3: 1 bad / 100 -> fast (1/100)/0.01 = 1.0 < 5 -> no breach
    s = _slo_windows([(5, 95), (0, 100), (1, 99)])
    assert s[0]["bad"] == 5 and s[0]["total"] == 100
    assert s[0]["fast_burn"] == pytest.approx(5.0)
    assert s[0]["slow_burn"] == pytest.approx(5.0)
    assert s[0]["breach"] is True
    assert s[1]["fast_burn"] == pytest.approx(0.0)
    assert s[1]["slow_burn"] == pytest.approx(2.5)
    assert s[1]["breach"] is False
    assert s[2]["fast_burn"] == pytest.approx(1.0)
    assert s[2]["breach"] is False
    snap = monitor.monitor_snapshot()
    assert snap["breaches"] == 1
    assert snap["slo"]["short_windows"] == 1
    assert snap["slo"]["long_windows"] == 12


def test_burn_rate_zero_traffic_is_zero(armed):
    """An idle service is not burning budget: no traffic -> burn 0.0,
    never a division error, never a breach."""
    s = _slo_windows([(0, 0), (0, 0)])
    for blk in s:
        assert blk["total"] == 0
        assert blk["fast_burn"] == 0.0
        assert blk["slow_burn"] == 0.0
        assert blk["breach"] is False


def test_breach_files_trace_event_with_window_id(armed, tmp_path):
    """A breach lands an slo_breach event in the trace ring whose
    window id matches an emitted monitor_window — the linkage
    trace_report --check validates."""
    _slo_windows([(50, 50)])
    dump = tracing.dump(path=str(tmp_path / "t.jsonl"), reason="test")
    events = [json.loads(ln)
              for ln in open(dump).read().splitlines()[1:]]
    breaches = [e for e in events if e["kind"] == "slo_breach"]
    wids = {e["window"] for e in events
            if e["kind"] == "monitor_window"}
    assert len(breaches) == 1
    assert breaches[0]["window"] in wids
    assert telemetry.counters().get("monitor/slo_breaches") == 1


# ========================================= (d) drift verdict vs A/A


def test_drift_verdict_shift_vs_aa(armed):
    """A +3 mean shift trips PSI > 0.2; the healthy stream's own A/A
    split stays under the 0.05 bound and its reference-PSI under the
    drift threshold (sample size >= 4096: above the measured noise
    floor of the growth-2 clamped buckets)."""
    rng = np.random.RandomState(11)
    base = rng.randn(8192)
    ref = monitor.ScoreHistogram()
    ref.record_many(base)
    reference = ref.to_dict()

    monitor.record_scores("healthy", rng.randn(8192),
                          reference=reference)
    monitor.record_scores("shifted", rng.randn(8192) + 3.0,
                          reference=reference)

    healthy = monitor.engine_drift("healthy")
    shifted = monitor.engine_drift("shifted")
    assert healthy["drift"] is False
    assert healthy["psi"] < monitor.DRIFT_PSI_THRESHOLD
    assert healthy["aa"]["ok"] is True
    assert healthy["aa"]["psi"] <= monitor.AA_PSI_BOUND
    assert shifted["drift"] is True
    assert shifted["psi"] > monitor.DRIFT_PSI_THRESHOLD
    # the close record serializes both lanes and the validator
    # re-derives every verdict from the raw buckets
    path = monitor.disarm()
    _h, _w, close = _checked(path)
    assert close["drift"]["shifted"]["drift"] is True
    assert close["drift"]["healthy"]["drift"] is False
    assert close["drift"]["healthy"]["aa_psi"] <= monitor.AA_PSI_BOUND


def test_drift_tamper_detected(armed):
    """Flipping a recorded verdict in the close record is caught: the
    validator recomputes PSI from the serialized buckets."""
    rng = np.random.RandomState(2)
    ref = monitor.ScoreHistogram()
    ref.record_many(rng.randn(4096))
    monitor.record_scores("eng", rng.randn(4096) + 3.0,
                          reference=ref.to_dict())
    path = monitor.disarm()
    lines = open(path).read().splitlines()
    rec = json.loads(lines[-1])
    rec["monitor_close"]["drift"]["eng"]["drift"] = False
    rec["monitor_close"]["drift"]["eng"]["psi"] = 0.001
    with open(path, "w") as fh:
        fh.write("\n".join(lines[:-1] + [json.dumps(rec)]) + "\n")
    header, windows, close, after = monitor_report.load(path)
    findings = monitor_report.check(path, header, windows, close, after)
    assert findings, "tampered drift verdict passed --check"


def test_score_histogram_junk_and_parity_split():
    """Non-finite scores land in the zero bucket (never a crash, never
    a lost count) and the A/A split partitions the live stream exactly
    across ragged batch boundaries."""
    h = monitor.ScoreHistogram()
    n = h.record_many([float("nan"), float("inf"), -float("inf"),
                       0.0, 1e-300, 5.0, -5.0])
    assert n == 7
    assert h.zero == 5
    assert h.count == 7
    # parity split: odd-sized batches keep a+b == live exactly
    telemetry.enable(None)
    tracing.arm(ring_events=256)
    monitor.arm(emitter=False)
    try:
        rng = np.random.RandomState(5)
        total = 0
        for size in (1, 7, 2, 33, 10):
            total += monitor.record_scores("k", rng.randn(size))
        snap = monitor.monitor_snapshot()
        assert snap["drift"]["k"]["n"] == total == 53
        aa = monitor.aa_verdict("k")
        assert aa["count"] == total
    finally:
        monitor.disarm()
        tracing.disarm()
        telemetry.disable()
        telemetry.reset()


# ============================================ (e) emitter lifecycle


def test_emitter_thread_leakguard_and_disable(tmp_path):
    """The emitter thread is lifecycle-tracked while armed (the
    conftest leak guard would flag an orphan), ticks on its own, joins
    on disarm — and telemetry.disable() disarms the whole monitor."""
    path = str(tmp_path / "m.jsonl")
    telemetry.enable(None)
    telemetry.reset()
    tracing.arm(ring_events=1024)
    monitor.arm(out_path=path, interval_s=0.05)
    try:
        assert monitor.active()
        assert lifecycle.live_count("monitor-emitter") == 1
        deadline = time.time() + 10.0
        while time.time() < deadline:
            snap = monitor.monitor_snapshot()
            if snap.get("window_seq", 0) >= 2:
                break
            time.sleep(0.02)
        assert monitor.monitor_snapshot()["window_seq"] >= 2, \
            "emitter thread produced no windows"
    finally:
        telemetry.disable()
        tracing.disarm()
        telemetry.reset()
    # disable() disarmed the monitor and joined the thread
    assert not monitor.active()
    assert lifecycle.live_count("monitor-emitter") == 0
    _header, windows, close = _checked(path)
    assert close is not None and len(windows) >= 2


# ================================================== (f) crash flush


def test_fault_flush_parseable(tmp_path):
    """An injected-raise training fault flushes a ``fault:*`` close
    record BEFORE the raise escapes; the JSONL stays parseable and
    passes monitor_report --check."""
    path = str(tmp_path / "m.jsonl")
    rng = np.random.RandomState(4)
    x = rng.randn(400, 5)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    telemetry.enable(None)
    telemetry.reset()
    tracing.arm(ring_events=1024, dump_dir=str(tmp_path))
    monitor.arm(out_path=path, interval_s=100.0, emitter=False)
    faults.arm(2, "raise")
    try:
        with pytest.raises(RuntimeError, match="injected fault"):
            lgb.train({"objective": "binary", "num_leaves": 7,
                       "min_data_in_leaf": 20,
                       "min_sum_hessian_in_leaf": 1.0,
                       "num_iterations": 6, "learning_rate": 0.2}, ds)
    finally:
        faults.disarm()
        monitor.disarm()
        tracing.disarm()
        telemetry.disable()
        telemetry.reset()
    header, windows, close, after = monitor_report.load(path)
    # the fault close landed first; the teardown disarm appends nothing
    # after it (already closed)
    assert close["reason"] == "fault:injected_raise"
    assert windows, "fault flush captured no in-flight window"
    assert monitor_report.check(path, header, windows, close,
                                after) == []
    # the training deltas made it into the flushed window
    merged = {}
    for w in windows:
        for k, v in w["counters"].items():
            merged[k] = merged.get(k, 0) + v
    # at least one non-monitor training counter delta landed (the exact
    # families depend on compile-cache state across a shared process)
    assert any(not k.startswith("monitor/") for k in merged), merged


# ===================================================== (g) knob rejects


def _cfg(params):
    cfg = OverallConfig()
    cfg.set(dict(params), require_data=False)
    return cfg


def test_knob_rejects():
    with pytest.raises(LightGBMError):
        _cfg({"monitor_interval_s": "0"})
    with pytest.raises(LightGBMError):
        _cfg({"monitor_interval_s": "-1"})
    with pytest.raises(LightGBMError):
        _cfg({"slo_window_s": "0"})
    with pytest.raises(LightGBMError):
        _cfg({"slo_p99_us": "-5"})
    # SLO without a serving task is a loud config error, not a silent
    # no-op: a training run has no serving latency to burn
    with pytest.raises(LightGBMError):
        _cfg({"task": "train", "slo_p99_us": "50000"})
    # ... and the same knob under task=predict parses fine
    cfg = _cfg({"task": "predict", "slo_p99_us": "50000"})
    assert cfg.io_config.slo_p99_us == 50000.0
    # arm() itself re-validates (the programmatic path)
    with pytest.raises(ValueError):
        monitor.arm(interval_s=0.0)
    with pytest.raises(ValueError):
        monitor.arm(slo_window_s=-1.0)
    with pytest.raises(ValueError):
        monitor.arm(ring_windows=0)
    assert not monitor.active()
