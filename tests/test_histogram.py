"""Histogram kernel tests: matmul backend vs a NumPy oracle."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import build_histogram


def _numpy_hist(bins, grad, hess, mask, B):
    F, N = bins.shape
    out = np.zeros((F, B, 3), dtype=np.float64)
    for f in range(F):
        for n in range(N):
            if mask[n]:
                b = bins[f, n]
                out[f, b, 0] += grad[n]
                out[f, b, 1] += hess[n]
                out[f, b, 2] += 1.0
    return out


@pytest.mark.parametrize("backend", ["matmul", "segsum"])
@pytest.mark.parametrize("n", [37, 100])
def test_histogram_matches_oracle(backend, n):
    rng = np.random.RandomState(0)
    F, B = 5, 16
    bins = rng.randint(0, B, size=(F, n)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = rng.rand(n) > 0.3
    hist = np.asarray(build_histogram(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), B, backend=backend))
    oracle = _numpy_hist(bins, grad, hess, mask, B)
    np.testing.assert_allclose(hist, oracle, rtol=1e-5, atol=1e-5)


def test_histogram_chunked_padding():
    """N not divisible by chunk: padded rows must not contribute."""
    rng = np.random.RandomState(1)
    F, B, n = 3, 8, 1000
    bins = rng.randint(0, B, size=(F, n)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = np.ones(n, dtype=bool)
    hist = np.asarray(build_histogram(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), B, backend="matmul", chunk=128))
    oracle = _numpy_hist(bins, grad, hess, mask, B)
    np.testing.assert_allclose(hist, oracle, rtol=1e-5, atol=1e-5)
    # counts must be exact integers
    np.testing.assert_array_equal(hist[:, :, 2].sum(axis=1),
                                  np.full(F, n, dtype=np.float32))
