"""Histogram kernel tests: matmul backend vs a NumPy oracle."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import build_histogram


def _numpy_hist(bins, grad, hess, mask, B):
    F, N = bins.shape
    out = np.zeros((F, B, 3), dtype=np.float64)
    for f in range(F):
        for n in range(N):
            if mask[n]:
                b = bins[f, n]
                out[f, b, 0] += grad[n]
                out[f, b, 1] += hess[n]
                out[f, b, 2] += 1.0
    return out


@pytest.mark.parametrize("backend", ["matmul", "segsum"])
@pytest.mark.parametrize("n", [37, 100])
def test_histogram_matches_oracle(backend, n):
    rng = np.random.RandomState(0)
    F, B = 5, 16
    bins = rng.randint(0, B, size=(F, n)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = rng.rand(n) > 0.3
    hist = np.asarray(build_histogram(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), B, backend=backend))
    oracle = _numpy_hist(bins, grad, hess, mask, B)
    np.testing.assert_allclose(hist, oracle, rtol=1e-5, atol=1e-5)


def test_histogram_chunked_padding():
    """N not divisible by chunk: padded rows must not contribute."""
    rng = np.random.RandomState(1)
    F, B, n = 3, 8, 1000
    bins = rng.randint(0, B, size=(F, n)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = np.ones(n, dtype=bool)
    hist = np.asarray(build_histogram(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), B, backend="matmul", chunk=128))
    oracle = _numpy_hist(bins, grad, hess, mask, B)
    np.testing.assert_allclose(hist, oracle, rtol=1e-5, atol=1e-5)
    # counts must be exact integers
    np.testing.assert_array_equal(hist[:, :, 2].sum(axis=1),
                                  np.full(F, n, dtype=np.float32))


@pytest.mark.parametrize("num_cols", [64, 128, 100])
def test_leafbatch_wide_tiling_matches_oracle(num_cols):
    """num_cols > 42 tiles into balanced single-MXU-tile groups; the
    col_id re-basing and window masks must reproduce the untiled result
    (this is the num_leaves=255 deep-level production path)."""
    from lightgbm_tpu.ops.histogram import histogram_leafbatch
    rng = np.random.RandomState(3)
    F, B, n = 4, 16, 4096
    bins = rng.randint(0, B, size=(F, n)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    col_id = rng.randint(0, num_cols, size=n).astype(np.int32)
    col_ok = rng.rand(n) > 0.4
    hist = np.asarray(histogram_leafbatch(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(col_id), jnp.asarray(col_ok), num_cols, B,
        compute_dtype=jnp.float32))
    assert hist.shape == (num_cols, F, B, 3)
    for c in range(num_cols):
        m = col_ok & (col_id == c)
        np.testing.assert_allclose(
            hist[c], _numpy_hist(bins, grad, hess, m, B),
            rtol=1e-5, atol=1e-5)
