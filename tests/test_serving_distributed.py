"""Distributed elastic serving (ISSUE 13, lightgbm_tpu/serving.py).

Correctness bars, in the ISSUE's order:

(a) the tree-sharded engine scores BIT-EQUAL to the single-device
    engine — f32 AND int8, all four objectives, dividing and
    non-dividing shard counts — on the virtual-device mesh, with each
    device holding only its tree block;
(b) the cross-request coalescing front returns results bit-identical to
    scoring each request alone (rows are independent through the walk),
    under the bucket ladder and the linger deadline;
(c) the drain-and-flip hot swap drops and misscores ZERO requests
    mid-load: every result matches the old or the new engine exactly,
    and the queue-order flip point is atomic;
(d) streamed ``predict_file`` writes a BYTE-IDENTICAL result file at
    any chunk length (out-of-core scoring == resident scoring).

Heavy load-generator cells ride the slow lane.
"""
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import costmodel, telemetry
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.predictor import Predictor
from lightgbm_tpu.serving import ServingEngine, ServingFront
from lightgbm_tpu.utils.log import LightGBMError

BASE = {"num_leaves": 15, "min_data_in_leaf": 20,
        "min_sum_hessian_in_leaf": 1.0, "num_iterations": 8,
        "learning_rate": 0.2}

OBJECTIVES = ("regression", "binary", "lambdarank", "multiclass")

_CASES = {}


def _case(objective, n=500, f=6, seed=3):
    """(trained booster, features), cached per objective — the sharded
    equivalence matrix reuses one model per objective."""
    key = (objective, n, f, seed)
    if key in _CASES:
        return _CASES[key]
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    params = dict(BASE, objective=objective)
    ds_kwargs = {}
    if objective == "regression":
        y = (x[:, 0] + 0.3 * x[:, 1] ** 2
             + 0.1 * rng.randn(n)).astype(np.float32)
    elif objective == "binary":
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    elif objective == "lambdarank":
        y = np.clip(np.digitize(x[:, 0], [-0.6, 0.2, 1.0]),
                    0, 3).astype(np.float32)
        ds_kwargs["query_boundaries"] = np.arange(0, n + 1, 50)
    else:
        y = np.digitize(x[:, 0], [-0.5, 0.5]).astype(np.float32)
        params["num_class"] = 3
        params["num_iterations"] = 4
    ds = Dataset.from_arrays(x, y, max_bin=64, **ds_kwargs)
    _CASES[key] = (lgb.train(params, ds), x)
    return _CASES[key]


# ========================== (a) tree-sharded bit-equality on the mesh


@pytest.mark.parametrize("objective", OBJECTIVES)
@pytest.mark.parametrize("quantize", ["float32", "int8"])
def test_sharded_bit_equal_to_single_device(objective, quantize):
    """shards=2: every (objective, precision) cell scores bit-equal —
    the canonical-order carry chain reproduces the single-device f32
    add sequence exactly (ops/scoring.py sharding block comment)."""
    booster, x = _case(objective)
    flat = booster.export_flat()
    base = ServingEngine(flat, quantize=quantize).scores(x)
    sharded = ServingEngine(flat, quantize=quantize, shards=2).scores(x)
    np.testing.assert_array_equal(base, sharded)


@pytest.mark.parametrize("shards", [3, 4])
def test_sharded_bit_equal_nondividing_and_wider(shards):
    """Non-dividing tree counts pad with inert stumps that are MASKED
    out of the accumulate (never added, not even as zeros) — bit
    equality holds at any shard count the mesh can host."""
    booster, x = _case("binary")
    flat = booster.export_flat()
    base = ServingEngine(flat).scores(x)
    np.testing.assert_array_equal(
        base, ServingEngine(flat, shards=shards).scores(x))
    b8 = ServingEngine(flat, quantize="int8").scores(x)
    np.testing.assert_array_equal(
        b8, ServingEngine(flat, quantize="int8", shards=shards).scores(x))


def test_sharded_leaf_indices_match():
    booster, x = _case("binary")
    flat = booster.export_flat()
    np.testing.assert_array_equal(
        ServingEngine(flat).leaf_indices(x),
        ServingEngine(flat, shards=2).leaf_indices(x))


def test_sharded_tables_live_on_their_shards():
    """The HBM contract behind the multi-GB-ensemble claim: each mesh
    device holds ONLY its contiguous tree block of the node tables."""
    booster, x = _case("binary")
    flat = booster.export_flat()
    eng = ServingEngine(flat, shards=2)
    eng.scores(x[:8])
    t = eng._device_tables()
    T_pad = flat.num_trees + (-flat.num_trees) % 2
    shards = t["sf"].addressable_shards
    assert len(shards) == 2
    assert all(s.data.shape == (T_pad // 2, flat.max_nodes)
               for s in shards)
    devices = {s.device for s in shards}
    assert len(devices) == 2


def test_sharded_rejects_oversubscribed_mesh():
    """serve_shards beyond the device count fails at ENGINE CONSTRUCTION
    (loudly — never a silent shrink that would change the shard layout
    mid-deployment)."""
    booster, _ = _case("binary")
    flat = booster.export_flat()
    with pytest.raises(LightGBMError):
        ServingEngine(flat, shards=4096)


def test_sharded_rejects_scan_algo():
    booster, _ = _case("binary")
    with pytest.raises(ValueError):
        ServingEngine(booster.export_flat(), shards=2, algo="scan")


def test_sharded_no_recompile_on_repeated_bucketed_calls():
    """The closed-program contract survives sharding: repeated bucketed
    calls on the sharded engine bump calls on existing programs and
    never add a signature."""
    booster, x = _case("binary")
    telemetry.enable()
    telemetry.reset()
    try:
        eng = ServingEngine(booster.export_flat(), buckets=(1, 32, 1024),
                            shards=2)
        for n in (5, 9, 31):
            eng.scores(x[:n])
        progs = costmodel.phase_program_records("predict")
        n_programs = len(progs)
        assert n_programs >= 1
        for n in (6, 17, 32, 2, 30):
            eng.scores(x[:n])
        assert len(costmodel.phase_program_records("predict")) \
            == n_programs, "sharded bucketed repeat calls recompiled"
    finally:
        telemetry.disable()
        telemetry.reset()


def test_warmup_precompiles_every_bucket():
    """warmup() (the hot-swap double-buffer step) compiles the whole
    bucket ladder: serving afterwards adds zero program signatures."""
    booster, x = _case("binary")
    telemetry.enable()
    telemetry.reset()
    try:
        eng = ServingEngine(booster.export_flat(), buckets=(1, 32, 1024))
        eng.warmup()
        n_programs = len(costmodel.phase_program_records("predict"))
        for n in (1, 7, 31, 33, 1000):
            eng.scores(x[:n])
        assert len(costmodel.phase_program_records("predict")) \
            == n_programs, "warmup left a bucket uncompiled"
    finally:
        telemetry.disable()
        telemetry.reset()


# =============================== (b) cross-request coalescing front


def test_front_results_bit_equal_to_individual_scoring():
    """Coalescing never changes a bit: every request's Future resolves
    to exactly the slice the engine returns for that request alone."""
    booster, x = _case("binary")
    flat = booster.export_flat()
    base = ServingEngine(flat).scores(x)
    front = ServingFront(ServingEngine(flat), linger_us=5000)
    try:
        futs = [(s, n, front.submit(x[s:s + n]))
                for s, n in ((0, 50), (50, 1), (51, 200), (251, 37),
                             (288, 212))]
        for s, n, fut in futs:
            np.testing.assert_array_equal(fut.result(30),
                                          base[:, s:s + n])
        assert front.stats["requests"] == 5
        assert front.stats["rows"] == 500
        assert 1 <= front.stats["batches"] <= 5
    finally:
        front.close()


def test_front_coalesces_under_linger():
    """With a generous linger and the worker pinned behind a first
    request, later submissions join ONE batch (the coalesced-batch
    stats prove cross-request packing actually happened)."""
    booster, x = _case("binary")
    front = ServingFront(ServingEngine(booster.export_flat()),
                         linger_us=200_000)
    try:
        futs = [front.submit(x[i * 20:(i + 1) * 20]) for i in range(10)]
        for fut in futs:
            fut.result(30)
        # all 10 landed within one linger window -> far fewer batches
        assert front.stats["batches"] < 10
        assert front.stats["coalesced_rows"] == 200
    finally:
        front.close()


def test_front_linger_zero_dispatches_immediately():
    booster, x = _case("binary")
    front = ServingFront(ServingEngine(booster.export_flat()),
                         linger_us=0)
    try:
        t0 = time.perf_counter()
        np.testing.assert_array_equal(
            front.predict(x[:4], timeout=30),
            ServingEngine(booster.export_flat()).scores(x[:4]))
        assert time.perf_counter() - t0 < 5.0
    finally:
        front.close()


def test_front_close_drains_queue_and_rejects_new_work():
    """Zero-drop also at shutdown: everything queued before close()
    resolves; submit afterwards raises."""
    booster, x = _case("binary")
    flat = booster.export_flat()
    base = ServingEngine(flat).scores(x)
    front = ServingFront(ServingEngine(flat), linger_us=100_000)
    futs = [front.submit(x[i * 10:(i + 1) * 10]) for i in range(8)]
    front.close()
    for i, fut in enumerate(futs):
        np.testing.assert_array_equal(fut.result(1),
                                      base[:, i * 10:(i + 1) * 10])
    with pytest.raises(RuntimeError):
        front.submit(x[:4])


# ===================================== (c) zero-drop hot swap mid-load


def _swap_refs():
    """Two engines over the SAME booster at different tree prefixes —
    the continued-training swap pair, with provably different scores."""
    booster, x = _case("binary")
    flat_a = booster.export_flat(len(booster.models) - 2)
    flat_b = booster.export_flat()
    eng_a, eng_b = ServingEngine(flat_a), ServingEngine(flat_b)
    ref_a, ref_b = eng_a.scores(x), eng_b.scores(x)
    assert not np.array_equal(ref_a, ref_b)
    return x, eng_a, eng_b, ref_a, ref_b


def test_hot_swap_mid_load_zero_drop():
    """The axis-c contract: concurrent submitters keep firing while
    swap_engine drains and flips.  Every request resolves, every result
    equals the OLD or the NEW engine exactly (no torn scores), and
    everything submitted after the swap returns is new-engine."""
    x, eng_a, eng_b, ref_a, ref_b = _swap_refs()
    front = ServingFront(eng_a, linger_us=500)
    results = []
    stop = threading.Event()

    def load():
        i = 0
        while not stop.is_set():
            s = (i * 20) % 480
            results.append((s, 20, front.submit(x[s:s + 20])))
            i += 1
            time.sleep(0.001)

    threads = [threading.Thread(target=load) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.2)
        drain = front.swap_engine(eng_b)          # warms, drains, flips
        assert drain >= 0.0
        assert front.stats["swaps"] == 1
        # post-swap requests MUST score on the new engine
        post = [(s, front.submit(x[s:s + 20])) for s in (0, 100, 460)]
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(30)
        front.close()
    assert len(results) > 20
    dropped = misscored = 0
    for s, n, fut in results:
        if not fut.done() or fut.exception() is not None:
            dropped += 1
            continue
        got = np.asarray(fut.result())
        if not (np.array_equal(got, ref_a[:, s:s + n])
                or np.array_equal(got, ref_b[:, s:s + n])):
            misscored += 1
    assert dropped == 0, f"{dropped} requests dropped across the swap"
    assert misscored == 0, f"{misscored} requests torn across the swap"
    for s, fut in post:
        np.testing.assert_array_equal(np.asarray(fut.result(30)),
                                      ref_b[:, s:s + 20])


def test_swap_flip_is_atomic_in_queue_order():
    """Requests queued BEHIND the swap marker (while the worker is
    stalled on the pre-swap batch) score on the new engine — the flip
    point is a queue position, not a wall-clock race."""
    x, eng_a, eng_b, ref_a, ref_b = _swap_refs()
    front = ServingFront(eng_a, linger_us=300_000)   # pin the worker
    try:
        pre = front.submit(x[:30])
        swap_done = {}
        t = threading.Thread(target=lambda: swap_done.__setitem__(
            "drain", front.swap_engine(eng_b, timeout=60)))
        t.start()
        while front.stats["swaps"] == 0 and t.is_alive():
            time.sleep(0.01)
        t.join(60)
        post = front.submit(x[30:60])
        np.testing.assert_array_equal(np.asarray(pre.result(60)),
                                      ref_a[:, :30])
        np.testing.assert_array_equal(np.asarray(post.result(60)),
                                      ref_b[:, 30:60])
        assert swap_done["drain"] >= 0.0
    finally:
        front.close()


@pytest.mark.slow
def test_hot_swap_under_sustained_open_loop_load():
    """The heavy cell: a sustained multi-second open-loop load (sharded
    old engine -> single-device new engine) with a mid-load swap — the
    bench_serve contract at test scale.  Slow lane by design."""
    booster, x = _case("binary")
    flat = booster.export_flat()
    eng_a = ServingEngine(flat, shards=2, linger_us=1000)
    eng_b = ServingEngine(flat, quantize="int8")
    ref_a = ServingEngine(flat).scores(x)          # sharded == single
    ref_b = ServingEngine(flat, quantize="int8").scores(x)
    front = ServingFront(eng_a)
    records = []
    try:
        t0 = time.perf_counter()
        swapped = False
        i = 0
        while time.perf_counter() - t0 < 4.0:
            if not swapped and time.perf_counter() - t0 > 2.0:
                front.swap_engine(eng_b)
                swapped = True
            s = (i * 16) % 480
            records.append((s, front.submit(x[s:s + 16])))
            i += 1
            time.sleep(0.002)
    finally:
        front.close()
    assert len(records) > 100
    for s, fut in records:
        assert fut.done() and fut.exception() is None
        got = np.asarray(fut.result())
        assert (np.array_equal(got, ref_a[:, s:s + 16])
                or np.array_equal(got, ref_b[:, s:s + 16]))


# ============================= (d) streamed out-of-core predict_file


def _write_tsv(tmp_path, x, name="pred.tsv"):
    data = tmp_path / name
    np.savetxt(data, np.column_stack([np.zeros(len(x)), x]),
               delimiter="\t", fmt="%.8f")
    return data


@pytest.mark.parametrize("objective", ["binary", "multiclass"])
def test_streamed_predict_file_byte_equal_to_resident(tmp_path, objective):
    """predict_file at ANY chunk length writes byte-identical output:
    the streamed parse->encode->score pipeline composes with the engine
    without moving a single result bit (rows are independent through
    bucket padding and the per-row output format)."""
    booster, x = _case(objective)
    data = _write_tsv(tmp_path, x)
    predictor = Predictor(booster, True, False, -1)
    out_resident = tmp_path / "resident.txt"
    out_streamed = tmp_path / "streamed.txt"
    predictor.predict_file(str(data), str(out_resident),
                           has_header=False, chunk_lines=10 ** 6)
    predictor.predict_file(str(data), str(out_streamed),
                           has_header=False, chunk_lines=33)
    assert out_streamed.read_bytes() == out_resident.read_bytes()
    assert out_streamed.stat().st_size > 0


def test_streamed_predict_file_sharded_engine(tmp_path):
    """The composed configuration: out-of-core chunking THROUGH the
    tree-sharded engine — still byte-identical to the single-device
    resident pass, and still one ensemble flatten for the whole file."""
    from lightgbm_tpu import serving
    booster, x = _case("binary")
    data = _write_tsv(tmp_path, x)
    base = tmp_path / "base.txt"
    Predictor(booster, True, False, -1).predict_file(
        str(data), str(base), has_header=False, chunk_lines=10 ** 6)
    count0 = serving.FLATTEN_COUNT
    sharded = tmp_path / "sharded.txt"
    p = Predictor(booster, True, False, -1,
                  serving_options={"shards": 2, "queue": 3})
    p.predict_file(str(data), str(sharded), has_header=False,
                   chunk_lines=41)
    assert serving.FLATTEN_COUNT == count0 + 1
    assert sharded.read_bytes() == base.read_bytes()
