"""Config system tests (aliases, conflicts, file parsing) —
/root/reference config.cpp parity."""
import os

import pytest

from lightgbm_tpu.config import (OverallConfig, apply_aliases, load_config,
                                 parse_config_file)
from lightgbm_tpu.utils.log import LightGBMError


def _set(params, **kw):
    cfg = OverallConfig()
    cfg.set(dict(params), require_data=kw.get("require_data", False))
    return cfg


def test_aliases():
    out = apply_aliases({"num_tree": "50", "sub_feature": "0.5",
                         "min_data": "10"})
    assert out["num_iterations"] == "50"
    assert out["feature_fraction"] == "0.5"
    assert out["min_data_in_leaf"] == "10"


def test_alias_does_not_override_canonical():
    out = apply_aliases({"num_tree": "50", "num_iterations": "99"})
    assert out["num_iterations"] == "99"


def test_defaults():
    cfg = _set({})
    assert cfg.boosting_config.num_iterations == 10
    assert cfg.boosting_config.learning_rate == 0.1
    assert cfg.boosting_config.tree_config.num_leaves == 127
    assert cfg.boosting_config.tree_config.min_data_in_leaf == 100
    assert cfg.io_config.max_bin == 256
    assert cfg.metric_config.eval_at == [1, 2, 3, 4, 5]
    assert cfg.objective_config.label_gain[2] == 3.0  # 2^2-1


def test_multiclass_conflict():
    with pytest.raises(LightGBMError):
        _set({"objective": "multiclass", "num_class": "1"})
    with pytest.raises(LightGBMError):
        _set({"objective": "binary", "num_class": "3"})
    with pytest.raises(LightGBMError):
        _set({"objective": "binary", "metric": "multi_logloss"})


def test_parallel_conflict_resolution():
    # serial forces num_machines=1 (config.cpp:164-167)
    cfg = _set({"tree_learner": "serial", "num_machines": "4"})
    assert cfg.network_config.num_machines == 1
    assert not cfg.is_parallel
    # data-parallel keeps machines and enables parallel bin finding
    cfg = _set({"tree_learner": "data", "num_machines": "4"})
    assert cfg.is_parallel
    assert cfg.is_parallel_find_bin


def test_hybrid_voting_learners_accepted():
    # the reference snapshot Fatals on tree_learner=voting
    # (config.cpp:311-313); ISSUE 9 realizes it, plus the 2-D hybrid
    # learner, with the mesh-factoring / vote-width knobs
    cfg = _set({"tree_learner": "voting", "num_machines": "2"})
    assert cfg.boosting_config.tree_learner == "voting"
    assert cfg.is_parallel
    assert cfg.boosting_config.tree_config.top_k == 20  # PV-tree default
    cfg = _set({"tree_learner": "hybrid", "num_machines": "4",
                "feature_shards": "2", "topk": "7"})
    assert cfg.boosting_config.tree_learner == "hybrid"
    assert cfg.boosting_config.tree_config.feature_shards == 2
    assert cfg.boosting_config.tree_config.top_k == 7  # topk alias
    with pytest.raises(LightGBMError):
        _set({"feature_shards": "-1"})
    with pytest.raises(LightGBMError):
        _set({"top_k": "0"})


def test_bad_values():
    with pytest.raises(LightGBMError):
        _set({"num_leaves": "1"})
    with pytest.raises(LightGBMError):
        _set({"learning_rate": "abc"})
    with pytest.raises(LightGBMError):
        _set({"bagging_fraction": "1.5"})
    with pytest.raises(LightGBMError):
        _set({"task": "explode"})


def test_config_file_and_argv_priority(tmp_path):
    conf = tmp_path / "t.conf"
    conf.write_text("# comment\nnum_trees = 77\nlearning_rate = 0.3  # tail\n"
                    "data = train.txt\n")
    params = parse_config_file(str(conf))
    assert params["num_trees"] == "77"
    assert params["learning_rate"] == "0.3"
    # argv wins over file (application.cpp:98)
    cfg = load_config([f"config={conf}", "num_trees=5"])
    assert cfg.boosting_config.num_iterations == 5


def test_metric_dedup():
    cfg = _set({"metric": "auc,auc,binary_logloss"})
    assert cfg.metric_types == ["auc", "binary_logloss"]


def test_verbosity_wires_log_level(capsys):
    """verbosity=3 (the ``verbosity`` alias included) must actually enable
    log.debug output at config/CLI startup — the reference's rule
    (config.cpp:59-70), single-homed in log.set_level_from_verbosity."""
    from lightgbm_tpu.utils import log
    old = log.get_level()
    try:
        _set({"verbosity": "3"})
        assert log.get_level() == log.DEBUG
        log.debug("debug-visible")
        assert "debug-visible" in capsys.readouterr().out
        _set({"verbose": "0"})
        assert log.get_level() == log.WARNING
        log.debug("debug-hidden")
        assert "debug-hidden" not in capsys.readouterr().out
        _set({"verbosity": "-1"})
        assert log.get_level() == log.FATAL
    finally:
        log.set_level(old)


def test_metrics_out_option(tmp_path):
    cfg = _set({"metrics_out": str(tmp_path / "m.jsonl"),
                "metrics_fence": "true"})
    assert cfg.io_config.metrics_out == str(tmp_path / "m.jsonl")
    assert cfg.io_config.metrics_fence is True
    assert _set({}).io_config.metrics_out == ""
