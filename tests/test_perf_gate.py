"""Perf-regression gate tests (ISSUE 4, tier-1): scripts/perf_gate.py must
flag an injected 3-sigma throughput/attained-fraction regression in a
synthetic bench history, pass the repo's REAL BENCH_r*/MULTICHIP_r*
trajectory, refuse cross-hardware comparisons, and fail cleanly on
malformed files."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts import perf_gate  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_round(tmp_path, n, value, spread=0.02, metric="iters_11m",
                 host=None, extra=None):
    rec = {"metric": metric, "value": value, "unit": "iters/sec",
           "spread": spread}
    if host is not None:
        rec["host"] = host
    if extra:
        rec.update(extra)
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({"n": n, "rc": 0, "parsed": rec}))
    return str(path)


def _history(tmp_path, values, **kw):
    return [_write_round(tmp_path, i + 1, v, **kw)
            for i, v in enumerate(values)]


# ------------------------------------------------------------ synthetic gate

def test_flags_injected_3sigma_regression(tmp_path):
    """Noise band 0.02 (recorded spread) -> sigma 1%, 3-sigma allowance
    3%: a 13% drop in the latest round must be flagged."""
    paths = _history(tmp_path, [1.67, 1.672, 1.669, 1.671, 1.45])
    report = perf_gate.check_files(paths)
    assert report["findings"], "injected regression not flagged"
    f = report["findings"][0]
    assert f["key"] == "value" and f["latest_round"] == 5
    assert f["drop"] > f["allowed_drop"]
    # CLI surface: exit code 1
    assert perf_gate.main(["--check", str(tmp_path / "BENCH_r*.json")]) == 1


def test_regressed_round_cannot_widen_its_own_band(tmp_path):
    """A regressed round that also reports a wide spread must not mask
    itself: the noise band comes from the PRIOR rounds only."""
    paths = _history(tmp_path, [1.67, 1.67, 1.67])
    paths.append(_write_round(tmp_path, 4, 1.34, spread=0.30))
    report = perf_gate.check_files(paths)
    assert any(f["key"] == "value" and f["latest_round"] == 4
               for f in report["findings"]), "self-masked regression"


def test_passes_within_noise_band(tmp_path):
    paths = _history(tmp_path, [1.67, 1.672, 1.669, 1.671, 1.665])
    assert perf_gate.check_files(paths)["findings"] == []
    assert perf_gate.main(["--check", str(tmp_path / "BENCH_r*.json")]) == 0


def test_flags_attained_fraction_regression(tmp_path):
    """A throughput-neutral roofline fraction drop (slower kernel hidden
    behind a faster host) is still flagged."""
    def roof(frac):
        return {"roofline": {"phases": {"train_chunk": {
            "frac_of_peak_flops": frac}}}}

    paths = [_write_round(tmp_path, i + 1, 1.67, extra=roof(f))
             for i, f in enumerate([0.93, 0.931, 0.929, 0.93, 0.70])]
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "roofline/train_chunk/frac_of_peak_flops" in keys


def test_satellite_keys_checked(tmp_path):
    paths = _history(
        tmp_path, [1.67, 1.67, 1.67],
        extra={"parity_leafwise_f32_iters_per_sec": 0.39,
               "parity_spread": 0.03})
    # regress only the parity satellite in a 4th round
    paths.append(_write_round(
        tmp_path, 4, 1.67,
        extra={"parity_leafwise_f32_iters_per_sec": 0.30,
               "parity_spread": 0.03}))
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert keys == ["parity_leafwise_f32_iters_per_sec"]


def test_serving_recompiles_flagged_absolutely(tmp_path):
    """predict_recompiles > 0 in the latest round is an absolute red
    flag (the bucket ladder stopped being closed) — no trajectory or
    noise band applies, and zero passes clean."""
    paths = _history(tmp_path, [1.67, 1.67, 1.67],
                     extra={"predict_recompiles": 0})
    report = perf_gate.check_files(paths)
    assert not report["findings"]
    paths.append(_write_round(tmp_path, 4, 1.67,
                              extra={"predict_recompiles": 2}))
    report = perf_gate.check_files(paths)
    assert any(f["key"] == "predict_recompiles" and f["latest"] == 2
               for f in report["findings"])


def test_serve_zero_drop_contract_flagged_absolutely(tmp_path):
    """ISSUE 13: a single dropped or misscored request across the
    mid-load hot swap — or a serve-lane recompile — fails the gate with
    no trajectory needed."""
    for key in ("serve_recompiles", "serve_dropped", "serve_misscored"):
        d = tmp_path / key
        d.mkdir()
        path = _write_round(d, 7, 2.0e5, metric="serve_4k",
                            extra={key: 1})
        report = perf_gate.check_files([path])
        assert any(f["key"] == key for f in report["findings"]), key
        clean = _write_round(d, 8, 2.0e5, metric="serve_4k",
                             extra={key: 0})
        assert perf_gate.check_files([clean])["findings"] == []


def test_serve_p99_growth_flagged_and_rate_gated(tmp_path):
    """The serve lanes join the trajectory: serve_rows_per_sec gates in
    the DROP direction like every rate key, serve_p99_us in the GROW
    direction under the wide latency band (floor 0.5 -> 75% allowed
    growth at 3 sigma: order-of-magnitude breaks, not percent drift)."""
    def extra(rps, p99):
        return {"serve_rows_per_sec": rps, "serve_spread": 0.02,
                "serve_p99_us": p99}

    paths = [_write_round(tmp_path, i + 1, 1.67, metric="serve_4k",
                          extra=extra(rps, p99))
             for i, (rps, p99) in enumerate(
                 [(2.0e5, 5000.0), (2.01e5, 5200.0), (1.99e5, 4900.0),
                  (2.0e5, 25000.0)])]     # p99 5x the prior median
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "serve_p99_us" in keys
    # within-band p99 wobble passes
    ok = _write_round(tmp_path, 5, 1.67, metric="serve_4k",
                      extra=extra(2.0e5, 6000.0))
    report = perf_gate.check_files(paths[:-1] + [ok])
    assert report["findings"] == []
    # a serve throughput collapse is a rate finding
    slow = _write_round(tmp_path, 6, 1.67, metric="serve_4k",
                        extra=extra(0.8e5, 5000.0))
    report = perf_gate.check_files(paths[:-1] + [slow])
    assert any(f["key"] == "serve_rows_per_sec"
               for f in report["findings"])


def test_mixedbin_resolution_flagged_absolutely(tmp_path):
    """ISSUE 12: a hybrid/voting round that requested mixed_bin
    auto/true on a mixed table but resolved the uniform layout is an
    absolute finding — no trajectory needed (the silent
    needs_uniform_layout fallback class)."""
    bad = _write_round(tmp_path, 1, 2.0, extra={
        "tree_learner": "hybrid", "mixed_bin_requested": "auto",
        "mixedbin_expected": True, "mixed_bin_on": False})
    report = perf_gate.check_files([bad])
    assert any(f["key"] == "headline_mixed_bin_resolution"
               for f in report["findings"])
    # the satellite-lane prefix is checked too
    bad2 = _write_round(tmp_path, 2, 2.0, extra={
        "mixedbin_hybrid_tree_learner": "hybrid",
        "mixedbin_hybrid_mixed_bin_requested": "true",
        "mixedbin_hybrid_mixed_bin_on": False})
    report = perf_gate.check_files([bad2])
    assert any(f["key"] == "mixedbin_hybrid_mixed_bin_resolution"
               for f in report["findings"])
    # legit resolutions pass: packed ON; auto on a single-class table;
    # a serial round carrying no learner keys
    for extra in (
            {"tree_learner": "hybrid", "mixed_bin_requested": "auto",
             "mixedbin_expected": True, "mixed_bin_on": True},
            {"tree_learner": "voting", "mixed_bin_requested": "auto",
             "mixedbin_expected": False, "mixed_bin_on": False},
            {"tree_learner": "serial", "mixed_bin_requested": "true",
             "mixedbin_expected": True, "mixed_bin_on": False}):
        ok = _write_round(tmp_path, 3, 2.0, extra=extra)
        assert not perf_gate.check_files([ok])["findings"], extra


def test_mixedbin_hybrid_lane_gated(tmp_path):
    """The composed packing-on-the-2-D-mesh lane rides RATE_KEYS: a
    3-sigma drop in mixedbin_hybrid_iters_per_sec is flagged."""
    paths = _history(
        tmp_path, [1.0, 1.0, 1.0, 1.0],
        extra={"mixedbin_hybrid_iters_per_sec": 3.0,
               "mixedbin_hybrid_spread": 0.02})
    paths.append(_write_round(
        tmp_path, 5, 1.0,
        extra={"mixedbin_hybrid_iters_per_sec": 2.0,
               "mixedbin_hybrid_spread": 0.02}))
    report = perf_gate.check_files(paths)
    assert any(f["key"] == "mixedbin_hybrid_iters_per_sec"
               for f in report["findings"])


def test_metric_groups_are_not_cross_compared(tmp_path):
    """A 1M round followed by 11M rounds (the real r01->r02 shape): the
    scale change must not read as an 80% regression."""
    paths = [_write_round(tmp_path, 1, 7.99, metric="iters_1m")]
    paths += [_write_round(tmp_path, n, v, metric="iters_11m")
              for n, v in ((2, 1.674), (3, 1.672))]
    assert perf_gate.check_files(paths)["findings"] == []


def test_refuses_cross_hardware_comparison(tmp_path):
    paths = [
        _write_round(tmp_path, 1, 1.67, host={"device_kind": "TPU v5 lite"}),
        _write_round(tmp_path, 2, 0.9, host={"device_kind": "TPU v4"}),
    ]
    with pytest.raises(perf_gate.GateError, match="device kinds"):
        perf_gate.check_files(paths)
    assert perf_gate.main(["--check", str(tmp_path / "BENCH_r*.json")]) == 2
    # explicit override compares anyway (and then flags the drop)
    report = perf_gate.check_files(paths, allow_cross_hardware=True)
    assert report["findings"]


def test_ckpt_restore_exact_false_flagged_absolutely(tmp_path):
    """ISSUE 14: a round recording a non-bit-identical same-topology
    checkpoint restore fails the gate with NO trajectory — on any round,
    not only the latest."""
    paths = _history(tmp_path, [1.67, 1.67],
                     extra={"ckpt_restore_exact": True})
    paths.append(_write_round(tmp_path, 3, 1.67,
                              extra={"ckpt_restore_exact": False}))
    paths.append(_write_round(tmp_path, 4, 1.67,
                              extra={"ckpt_restore_exact": True}))
    report = perf_gate.check_files(paths)
    assert any(f["key"] == "ckpt_restore_exact"
               and f["latest_round"] == 3 for f in report["findings"])
    # True everywhere (or absent on older rounds) passes
    sub = tmp_path / "clean"
    sub.mkdir()
    clean = perf_gate.check_files(_history(
        sub, [1.67, 1.67, 1.67], extra={"ckpt_restore_exact": True}))
    assert not clean["findings"]


def test_ckpt_overhead_growth_flagged(tmp_path):
    """ckpt_overhead_pct rides the must-not-grow latency lane at the
    wide observability floor: stable passes, an order-of-magnitude
    growth is flagged."""
    stable = _history(tmp_path, [1.67, 1.67, 1.67],
                      extra={"ckpt_overhead_pct": 2.0})
    assert not perf_gate.check_files(stable)["findings"]
    grown = list(stable)
    grown.append(_write_round(tmp_path, 4, 1.67,
                              extra={"ckpt_overhead_pct": 40.0}))
    report = perf_gate.check_files(grown)
    assert any(f["key"] == "ckpt_overhead_pct" for f in report["findings"])


def test_multichip_elastic_contracts_flagged(tmp_path):
    """ISSUE 14: the kill-restart row's restore_match/metrics_complete
    False are absolute findings, parsed from the MULTICHIP_ELASTIC tail
    line like the OBS/WIRE blocks."""
    good = tmp_path / "MULTICHIP_r01.json"
    good.write_text(json.dumps({
        "n_devices": 8, "rc": 0, "ok": True,
        "tail": "MULTICHIP_ELASTIC " + json.dumps(
            {"restore_match": True, "metrics_complete": True,
             "trees": 8}) + "\n"}))
    assert not perf_gate.check_files([str(good)])["findings"]
    bad = tmp_path / "MULTICHIP_r02.json"
    bad.write_text(json.dumps({
        "n_devices": 8, "rc": 0, "ok": True,
        "tail": "MULTICHIP_ELASTIC " + json.dumps(
            {"restore_match": False, "metrics_complete": True}) + "\n"}))
    report = perf_gate.check_files([str(good), str(bad)])
    assert any(f["key"] == "elastic/restore_match"
               for f in report["findings"])
    lost = tmp_path / "MULTICHIP_r03.json"
    lost.write_text(json.dumps({
        "n_devices": 8, "rc": 0, "ok": True,
        "elastic": {"restore_match": True, "metrics_complete": False}}))
    report = perf_gate.check_files([str(good), str(lost)])
    assert any(f["key"] == "elastic/metrics_complete"
               for f in report["findings"])


def test_multichip_ok_to_notok_flagged(tmp_path):
    ok = tmp_path / "MULTICHIP_r01.json"
    ok.write_text(json.dumps({"n_devices": 8, "rc": 0, "ok": True}))
    bad = tmp_path / "MULTICHIP_r02.json"
    bad.write_text(json.dumps({"n_devices": 8, "rc": 1, "ok": False}))
    report = perf_gate.check_files([str(ok), str(bad)])
    assert any(f["metric"] == "multichip" for f in report["findings"])


# ----------------------------------------- multichip skew / interconnect gate

def _write_multichip(tmp_path, n, skew=None, gbps=None, via_tail=False):
    rec = {"n_devices": 8, "rc": 0, "ok": True}
    obs = {}
    if skew is not None:
        obs["skew"] = {"max_phase_skew": skew, "iterations_compared": 3,
                       "phases": {"grow": {"max_skew": skew}}}
    if gbps is not None:
        obs["interconnect"] = {"sites": 4, "est_bytes_total": 4000,
                               "attained_gb_per_s": gbps}
    if via_tail:
        rec["tail"] = ("[LightGBM] [Info] whatever\nMULTICHIP_OBS "
                       + json.dumps(obs) + "\n")
    else:
        rec.update(obs)
    path = tmp_path / f"MULTICHIP_r{n:02d}.json"
    path.write_text(json.dumps(rec))
    return str(path)


def test_multichip_skew_growth_flagged(tmp_path):
    """ISSUE 5: a latest round whose max per-phase skew grows past the
    (wide, order-of-magnitude) noise band — a new straggler or an
    unbalanced schedule — is a regression even with the ok flag green."""
    paths = [_write_multichip(tmp_path, n, skew=s)
             for n, s in enumerate([1.2, 1.21, 1.19, 4.5], start=1)]
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "skew/max_phase_skew" in keys


def test_multichip_interconnect_drop_flagged(tmp_path):
    paths = [_write_multichip(tmp_path, n, gbps=g)
             for n, g in enumerate([4.0, 4.05, 3.98, 0.4], start=1)]
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "interconnect/attained_gb_per_s" in keys


def test_multichip_obs_stable_passes(tmp_path):
    paths = [_write_multichip(tmp_path, n, skew=s, gbps=g)
             for n, (s, g) in enumerate(
                 [(1.2, 4.0), (1.21, 4.02), (1.19, 3.99)], start=1)]
    assert perf_gate.check_files(paths)["findings"] == []


def test_multichip_obs_parsed_from_tail(tmp_path):
    """dryrun_multichip prints one MULTICHIP_OBS JSON line; the gate reads
    the block out of the captured tail when the wrapper did not lift it."""
    paths = [_write_multichip(tmp_path, n, skew=s, via_tail=True)
             for n, s in enumerate([1.2, 1.21, 4.8], start=1)]
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "skew/max_phase_skew" in keys


def test_multichip_rounds_without_obs_are_not_compared(tmp_path):
    """Pre-ISSUE-5 rounds (no skew block) must not break the gate or
    read as regressions against obs-carrying rounds."""
    paths = [_write_multichip(tmp_path, 1),
             _write_multichip(tmp_path, 2, skew=1.2, gbps=4.0)]
    assert perf_gate.check_files(paths)["findings"] == []


# --------------------------------------------------- wire-bytes gate (ISSUE 9)

def _write_wire(tmp_path, n, data=None, hybrid=None, voting=None,
                n_devices=4, via_tail=False):
    rec = {"n_devices": 8, "rc": 0, "ok": True}
    w = {k: v for k, v in
         (("data", data), ("hybrid", hybrid), ("voting", voting))
         if v is not None}
    wire = {"n_devices": n_devices, "schema": {"F": 28, "B": 255},
            "wire_bytes_per_iter": w, "sites": {}}
    if via_tail:
        rec["tail"] = ("[LightGBM] [Info] whatever\nMULTICHIP_WIRE "
                       + json.dumps(wire) + "\n")
    else:
        rec["wire"] = wire
    path = tmp_path / f"MULTICHIP_r{n:02d}.json"
    path.write_text(json.dumps(rec))
    return str(path)


def test_wire_hybrid_not_below_dp_flagged_absolutely(tmp_path):
    """hybrid >= pure-DP bytes on the same device count is an absolute
    finding — no trajectory needed (and voting >= hybrid likewise)."""
    p = _write_wire(tmp_path, 1, data=1000, hybrid=1000, voting=1200)
    report = perf_gate.check_files([p])
    keys = [f["key"] for f in report["findings"]]
    assert "wire/hybrid_vs_data" in keys
    assert "wire/voting_vs_hybrid" in keys


def test_wire_growth_flagged(tmp_path):
    """The logical series is deterministic, so the must-not-grow band is
    the tight rate-key floor: a 10% growth flags."""
    paths = [_write_wire(tmp_path, n, data=10000, hybrid=h, voting=3000)
             for n, h in enumerate([5000, 5000, 5500], start=1)]
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "wire/hybrid" in keys


def test_wire_stable_ordering_passes(tmp_path):
    paths = [_write_wire(tmp_path, n, data=10000, hybrid=5000, voting=3000,
                         via_tail=(n == 3))
             for n in (1, 2, 3)]
    assert perf_gate.check_files(paths)["findings"] == []


def test_wire_cross_device_counts_not_compared(tmp_path):
    """A round measured at a different device count starts its own wire
    series (more shards legitimately move different bytes)."""
    paths = [_write_wire(tmp_path, 1, data=10000, hybrid=5000,
                         n_devices=4),
             _write_wire(tmp_path, 2, data=20000, hybrid=9000,
                         n_devices=8)]
    assert perf_gate.check_files(paths)["findings"] == []


def test_wire_rounds_without_block_are_not_compared(tmp_path):
    """Pre-ISSUE-9 rounds (no wire block) must not break the gate."""
    ok = tmp_path / "MULTICHIP_r01.json"
    ok.write_text(json.dumps({"n_devices": 8, "rc": 0, "ok": True}))
    p2 = _write_wire(tmp_path, 2, data=10000, hybrid=5000, voting=3000)
    assert perf_gate.check_files([str(ok), p2])["findings"] == []


def test_malformed_file_is_a_one_line_error(tmp_path):
    p = tmp_path / "BENCH_r01.json"
    p.write_text("{not json")
    with pytest.raises(perf_gate.GateError):
        perf_gate.check_files([str(p)])
    assert perf_gate.main(["--check", str(p)]) == 2
    with pytest.raises(perf_gate.GateError, match="no bench history"):
        perf_gate.check_files([])


# ------------------------------------------------------------ real trajectory

def test_real_bench_trajectory_passes():
    """The repo's committed BENCH_r*/MULTICHIP_r* history is the no-false-
    positive gate: the documented pre-merge check
    (``python scripts/perf_gate.py --check 'BENCH_r*.json'``) must pass."""
    import glob
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))
                   + glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    if not paths:
        pytest.skip("no committed bench history")
    report = perf_gate.check_files(paths)
    assert report["findings"] == [], report["findings"]
    assert len(report["groups"]) >= 1


# ------------------------------------------------- parallel-ingest lanes

def test_ingest_workers_must_grow_flagged(tmp_path):
    """ISSUE 18: a round that ran the byte-range worker pool but whose
    ingest_rows_per_sec sits at/below the serial-round median is a
    finding — the fan-out stopped paying."""
    paths = _history(tmp_path, [1.67, 1.67, 1.67],
                     extra={"ingest_rows_per_sec": 116000.0})
    paths.append(_write_round(
        tmp_path, 4, 1.67,
        extra={"ingest_rows_per_sec": 115000.0, "ingest_workers": 2,
               "ingest_workers_effective": 2}))
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "ingest_rows_per_sec_must_grow" in keys


def test_ingest_workers_growth_passes(tmp_path):
    paths = _history(tmp_path, [1.67, 1.67, 1.67],
                     extra={"ingest_rows_per_sec": 116000.0})
    paths.append(_write_round(
        tmp_path, 4, 1.67,
        extra={"ingest_rows_per_sec": 140000.0, "ingest_workers": 2,
               "ingest_workers_effective": 2}))
    assert perf_gate.check_files(paths)["findings"] == []


def test_ingest_workers_own_serial_lane_is_the_baseline(tmp_path):
    """A workers round that records its own serial reference lane is
    judged against THAT (same file, same scale, same host) — beating a
    cross-round median while losing to the matched serial lane is still
    a finding, and vice versa."""
    paths = _history(tmp_path, [1.67, 1.67],
                     extra={"ingest_rows_per_sec": 116000.0})
    paths.append(_write_round(
        tmp_path, 3, 1.67,
        extra={"ingest_rows_per_sec": 150000.0,
               "ingest_serial_rows_per_sec": 155000.0,
               "ingest_workers": 2, "ingest_workers_effective": 2}))
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "ingest_rows_per_sec_must_grow" in keys
    # and the matched lane passing is a pass even with a higher median
    ok = _write_round(
        tmp_path, 4, 1.67,
        extra={"ingest_rows_per_sec": 170000.0,
               "ingest_serial_rows_per_sec": 155000.0,
               "ingest_workers": 2, "ingest_workers_effective": 2})
    report2 = perf_gate.check_files(paths[:2] + [ok])
    assert report2["findings"] == []


def test_ingest_workers_silent_serial_flagged(tmp_path):
    """A round that REQUESTED workers but resolved to the serial loader
    (effective <= 1) must not gate serial numbers as parallel ones."""
    paths = _history(tmp_path, [1.67, 1.67],
                     extra={"ingest_rows_per_sec": 116000.0})
    paths.append(_write_round(
        tmp_path, 3, 1.67,
        extra={"ingest_rows_per_sec": 150000.0, "ingest_workers": 4,
               "ingest_workers_effective": 1}))
    report = perf_gate.check_files(paths)
    keys = [f["key"] for f in report["findings"]]
    assert "ingest_workers_effective" in keys


def test_ingest_workers_no_serial_prior_skipped(tmp_path):
    """A trajectory whose EVERY round ran workers has no serial baseline
    to grow past — the must-GROW lane stays silent."""
    paths = _history(tmp_path, [1.67, 1.67, 1.67],
                     extra={"ingest_rows_per_sec": 140000.0,
                            "ingest_workers": 2,
                            "ingest_workers_effective": 2})
    assert perf_gate.check_files(paths)["findings"] == []


# ------------------------------------------------- sharded-ingest contracts

def _write_sharded(tmp_path, n, si, via_tail=False):
    rec = {"n_devices": 8, "rc": 0, "ok": True}
    if via_tail:
        rec["tail"] = ("[LightGBM] [Info] whatever\n"
                       "MULTICHIP_SHARDED_INGEST " + json.dumps(si) + "\n")
    else:
        rec["sharded_ingest"] = si
    path = tmp_path / f"MULTICHIP_r{n:02d}.json"
    path.write_text(json.dumps(rec))
    return str(path)


def _good_sharded():
    return {"n_hosts": 4, "total": 409, "host_rows": [113, 101, 93, 102],
            "overlap": 0, "coverage_ok": True, "bit_identical": True,
            "workers": 2, "ok": True}


def test_sharded_ingest_clean_row_passes(tmp_path):
    paths = [_write_sharded(tmp_path, 1, _good_sharded()),
             _write_sharded(tmp_path, 2, _good_sharded(), via_tail=True)]
    assert perf_gate.check_files(paths)["findings"] == []


def test_sharded_ingest_contracts_flagged(tmp_path):
    """Per-host rows failing to tile the dataset, any overlap, or a
    bit-identity break are absolute findings on the recording round."""
    bad = _good_sharded()
    bad.update({"host_rows": [113, 101, 93, 107], "overlap": 5,
                "bit_identical": False})
    paths = [_write_sharded(tmp_path, 1, _good_sharded()),
             _write_sharded(tmp_path, 2, bad, via_tail=True)]
    report = perf_gate.check_files(paths)
    keys = {f["key"] for f in report["findings"]}
    assert "sharded_ingest/host_rows_sum" in keys
    assert "sharded_ingest/overlap" in keys
    assert "sharded_ingest/bit_identical" in keys
    assert all(f["latest_round"] == 2 for f in report["findings"])
