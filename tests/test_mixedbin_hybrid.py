"""Block-local mixed-bin packing on the 2-D ownership mesh (ISSUE 12).

Through PR 11 the hybrid/voting learners forced the uniform layout
(``needs_uniform_layout``): the global class-contiguous permutation and
contiguous feature-block ownership did not compose.  The block-local
layout (io/binning.BlockedPackSpec) computes the bin-width-class
permutation PER owned feature block — it never crosses a block boundary,
so packing commutes with ownership and the owned-block psum /
packed-SplitInfo allreduce ride unchanged.  Pinned here:

- plan rules: per-block-uniform class counts (the min across blocks),
  degenerate cases (a block without narrow features -> uniform layout),
  the block_view / global ranges / c2p contracts;
- packed-vs-uniform BIT-identity (trees, thresholds, leaf values,
  scores, model text, valid replay) under hybrid AND voting, int8 f32,
  per-iteration AND fused-chunk, on the (2,2) dryrun mesh.  int8 is
  robustly bitwise (the canonical reorder happens IN the int domain
  before dequantize — ops/hist_pallas feat_gather); f32 bitwise holds at
  the pinned schemas (XLA-CPU's dot reduction order is shape-dependent,
  the same property PR 6's serial f32 pins rely on);
- serial == packed-hybrid == packed-voting under int8 (the ISSUE 12
  acceptance row; bitwise at the pinned schema — like the PR 9 pins,
  int8 cross-schedule identity is exact where the root-stat bin-sums
  round identically, 1-ulp elsewhere).
"""
import numpy as np
import pytest

from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.binning import (BlockedPackSpec, NARROW_BINS,
                                     plan_feature_packing_blocked)
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel.learners import create_parallel_learner


# --------------------------------------------------------------- plan rules

def test_blocked_plan_per_block_uniform_counts():
    # blocks of 4: narrow counts 2 and 1 -> uniform c_n = 1
    nb = np.array([5, 9, 255, 255,   7, 255, 255, 255])
    spec = plan_feature_packing_blocked(nb, 255, block=4)
    assert isinstance(spec, BlockedPackSpec)
    assert spec.counts == (1, 3)
    assert spec.block == 4
    # block 0 stores its first narrow feature (0) first; surplus narrow
    # feature 1 rides the wide segment in canonical order
    assert spec.perm == (0, 1, 2, 3, 4, 5, 6, 7)
    # global ranges interleave per block: (narrow, wide) x 2 blocks
    assert spec.ranges == ((0, 1, NARROW_BINS), (1, 3, 255),
                           (4, 1, NARROW_BINS), (5, 3, 255))
    # the shard-uniform block view: identity perm, per-block counts
    bv = spec.block_view
    assert bv.counts == (1, 3) and bv.perm == (0, 1, 2, 3)


def test_blocked_plan_permutes_within_blocks_only():
    nb = np.array([255, 5, 255, 9,   255, 255, 7, 255])
    spec = plan_feature_packing_blocked(nb, 255, block=4)
    assert spec.counts == (1, 3)
    # narrow-first WITHIN each block, remainder canonical; the
    # permutation never crosses the block boundary
    assert spec.perm == (1, 0, 2, 3, 6, 4, 5, 7)
    assert all(p // 4 == i // 4 for i, p in enumerate(spec.perm))
    # c2p inverts perm
    for f, p in enumerate(spec.c2p):
        assert spec.perm[p] == f


def test_blocked_plan_degenerates_without_narrow_in_a_block():
    # block 1 is all wide -> c_n = 0 -> uniform layout
    nb = np.array([5, 9, 255, 255,   255, 255, 255, 255])
    assert plan_feature_packing_blocked(nb, 255, block=4) is None
    # single class and env-style off behave like the global plan
    assert plan_feature_packing_blocked(
        np.array([5, 9, 7, 3]), 9, block=2) is None
    assert plan_feature_packing_blocked(nb, 255, block=4,
                                        mode="false") is None


def test_blocked_plan_refuses_all_padding_shard():
    # F=5 over 4 shards (block=2): shard 3 owns only ownership padding —
    # its clamped duplicate lanes would land a wide feature in the
    # narrow segment, so the plan refuses the mesh (uniform layout)
    nb = np.array([5, 255, 9, 255, 7])
    assert plan_feature_packing_blocked(nb, 255, block=2, shards=4) is None
    # the same feature set on 2 shards (block=3) packs fine
    assert plan_feature_packing_blocked(nb, 255, block=3,
                                        shards=2) is not None


def test_blocked_plan_partial_last_block():
    # F=6, block=4: the last block has 2 real features (1 narrow) ->
    # c_n = min(2, 1) = 1
    nb = np.array([5, 9, 255, 255,   7, 255])
    spec = plan_feature_packing_blocked(nb, 255, block=4)
    assert spec.counts == (1, 3)
    assert spec.ranges == ((0, 1, NARROW_BINS), (1, 3, 255),
                           (4, 1, NARROW_BINS), (5, 1, 255))
    assert sum(cnt for _, cnt, _ in spec.ranges) == 6


# ------------------------------------------------------------ training pins

def _mixed_xy(n, f, seed):
    rng = np.random.RandomState(seed)
    cols = [rng.randn(n) if j % 2 == 0
            else rng.randint(0, 4 + j, n).astype(float) for j in range(f)]
    x = np.stack(cols, axis=1)
    w = rng.randn(f)
    y = (((x - x.mean(0)) / (x.std(0) + 1e-9)) @ w
         + rng.randn(n) > 0).astype(np.float32)
    return x, y


@pytest.fixture(scope="module")
def mixed_ds():
    x, y = _mixed_xy(1500, 8, 3)
    ds = Dataset.from_arrays(x, y, max_bin=255)
    nb = ds.num_bins
    assert (nb <= NARROW_BINS).any() and (nb > NARROW_BINS).any()
    return ds


@pytest.fixture(scope="module")
def valid_ds():
    x, y = _mixed_xy(400, 8, 17)
    return Dataset.from_arrays(x, y, max_bin=255)


def _train(ds, tl, mixed, extra=None, iters=3, chunk=False, valid=None):
    p = {"objective": "binary", "num_leaves": "15", "min_data_in_leaf": "20",
         "min_sum_hessian_in_leaf": "1.0", "learning_rate": "0.1",
         "tree_learner": tl, "num_machines": "4", "mixed_bin": mixed}
    p.update(extra or {})
    cfg = OverallConfig()
    cfg.set(p, require_data=False)
    b = GBDT()
    learner = None if tl == "serial" else create_parallel_learner(cfg)
    b.init(cfg.boosting_config, ds,
           create_objective(cfg.objective_type, cfg.objective_config),
           learner=learner)
    if valid is not None:
        from lightgbm_tpu.metrics import create_metric
        b.add_valid_dataset(valid, [create_metric("auc", cfg.metric_config)])
    if chunk:
        b.train_chunk(iters)
        b.flush_pipeline()
    else:
        for _ in range(iters):
            if b.train_one_iter(is_eval=valid is not None):
                break
    return b


def _assert_bitwise(on, off, tag, model_text=False):
    assert on._pack_spec is not None, tag
    assert off._pack_spec is None, tag
    assert len(on.models) == len(off.models), tag
    for k, (t1, t2) in enumerate(zip(on.models, off.models)):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature,
                                      err_msg=f"{tag} tree {k}")
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin,
                                      err_msg=f"{tag} tree {k}")
        np.testing.assert_array_equal(np.asarray(t1.leaf_value),
                                      np.asarray(t2.leaf_value),
                                      err_msg=f"{tag} tree {k}")
        np.testing.assert_array_equal(np.asarray(t1.threshold),
                                      np.asarray(t2.threshold),
                                      err_msg=f"{tag} tree {k}")
        if model_text:
            assert t1.to_string() == t2.to_string(), f"{tag} tree {k}"
    np.testing.assert_array_equal(np.asarray(on.score),
                                  np.asarray(off.score), err_msg=tag)
    for e1, e2 in zip(on.valid_datasets, off.valid_datasets):
        np.testing.assert_array_equal(np.asarray(e1["score"]),
                                      np.asarray(e2["score"]),
                                      err_msg=tag + " valid replay")


def test_hybrid_int8_packed_bit_identity(mixed_ds, valid_ds):
    # per-iteration leaf-wise, model text + scores + valid replay
    extra = {"feature_shards": "2", "hist_dtype": "int8",
             "grow_policy": "leafwise"}
    on = _train(mixed_ds, "hybrid", "true", extra, valid=valid_ds)
    off = _train(mixed_ds, "hybrid", "false", extra, valid=valid_ds)
    assert hasattr(on._pack_spec, "block")   # the BLOCK-LOCAL spec
    _assert_bitwise(on, off, "hybrid int8 leafwise", model_text=True)


def test_voting_int8_packed_bit_identity(mixed_ds):
    extra = {"feature_shards": "2", "top_k": "4", "hist_dtype": "int8",
             "grow_policy": "leafwise"}
    _assert_bitwise(_train(mixed_ds, "voting", "true", extra),
                    _train(mixed_ds, "voting", "false", extra),
                    "voting int8 leafwise")


def test_hybrid_int8_fused_chunk_packed_bit_identity(mixed_ds):
    extra = {"feature_shards": "2", "hist_dtype": "int8",
             "grow_policy": "depthwise"}
    _assert_bitwise(
        _train(mixed_ds, "hybrid", "true", extra, iters=3, chunk=True),
        _train(mixed_ds, "hybrid", "false", extra, iters=3, chunk=True),
        "hybrid int8 depthwise chunk")


def test_serial_equals_packed_hybrid_and_voting_int8():
    # the ISSUE 12 acceptance row: serial == hybrid == voting under int8
    # WITH block-local packing ON.  Bitwise at this pinned schema (int8
    # cross-schedule identity is exact where the root-stat bin sums
    # round identically — the same schema-pinning the PR 9 claims use).
    x, y = _mixed_xy(3000, 12, 3)
    ds = Dataset.from_arrays(x, y, max_bin=255)
    extra8 = {"hist_dtype": "int8", "grow_policy": "leafwise"}
    s = _train(ds, "serial", "false", extra8)
    h = _train(ds, "hybrid", "true", dict(extra8, feature_shards="2"))
    v = _train(ds, "voting", "true",
               dict(extra8, feature_shards="2", top_k="12"))
    assert h._pack_spec is not None and v._pack_spec is not None
    for tag, o in (("hybrid", h), ("voting", v)):
        assert len(s.models) == len(o.models)
        for k, (t1, t2) in enumerate(zip(s.models, o.models)):
            np.testing.assert_array_equal(
                t1.split_feature, t2.split_feature,
                err_msg=f"serial vs packed-{tag} tree {k}")
            np.testing.assert_array_equal(
                t1.threshold_bin, t2.threshold_bin,
                err_msg=f"serial vs packed-{tag} tree {k}")
            np.testing.assert_array_equal(
                np.asarray(t1.leaf_value), np.asarray(t2.leaf_value),
                err_msg=f"serial vs packed-{tag} tree {k}")


def test_mixed_bin_true_warns_and_degenerates_on_narrowless_block(caplog):
    # fs=2 over 4 features: block 1 = two wide features -> no narrow ->
    # the blocked plan degenerates to the uniform layout with a warning
    rng = np.random.RandomState(0)
    n = 600
    x = np.stack([rng.randint(0, 5, n).astype(float), rng.randn(n),
                  rng.randn(n), rng.randn(n)], axis=1)
    y = ((x[:, 1] > 0)).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=255)
    b = _train(ds, "hybrid", "true",
               {"feature_shards": "2", "grow_policy": "leafwise"}, iters=1)
    assert b._pack_spec is None


@pytest.mark.slow
@pytest.mark.parametrize("tl,extra", [
    ("hybrid", {"feature_shards": "2"}),
    ("voting", {"feature_shards": "2", "top_k": "2"}),
])
def test_f32_packed_bit_identity(tl, extra):
    # f32 bitwise needs per-pass shapes where the XLA-CPU dot reduction
    # order coincides between the per-class and uniform passes (the same
    # shape-dependence PR 6's serial f32 pins live with): pinned at
    # n=5000 rows (2500 per data shard)
    x, y = _mixed_xy(5000, 8, 3)
    ds = Dataset.from_arrays(x, y, max_bin=255)
    e = dict(extra, hist_dtype="float32", grow_policy="leafwise")
    _assert_bitwise(_train(ds, tl, "true", e), _train(ds, tl, "false", e),
                    "%s f32 leafwise" % tl)


@pytest.mark.slow
@pytest.mark.parametrize("tl,extra", [
    ("hybrid", {"feature_shards": "2", "leafwise_compact": "true"}),
    ("voting", {"feature_shards": "2", "top_k": "4",
                "leafwise_compact": "true"}),
    ("hybrid", {"feature_shards": "4"}),
])
def test_packed_bit_identity_more_cells(mixed_ds, tl, extra):
    # compacted pane (full-F canonical assembly via the global blocked
    # ranges) and the fs=4 mesh factoring
    e = dict(extra, hist_dtype="int8", grow_policy="leafwise")
    _assert_bitwise(_train(mixed_ds, tl, "true", e),
                    _train(mixed_ds, tl, "false", e),
                    "%s int8 %s" % (tl, extra))
