"""Quantized-gradient (int8) histogram path: XLA oracle ≡ Pallas kernel,
exact counts, and end-to-end training sanity.

The int8 path is the TPU throughput option (ops/hist_pallas.py): grad/hess
are rounded to 1/127 of their per-pass max and contracted on the int8 MXU.
The reference accumulates in double (bin.h:15-17); LightGBM's later
quantized-training work showed coarse gradient quantization preserves model
quality — these tests pin the machinery, scripts/auc_parity.py pins quality
at scale.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tests._pltpu_probe import requires_pltpu_interpret

from lightgbm_tpu.ops.histogram import histogram_leafbatch
from lightgbm_tpu.ops.hist_pallas import (hist_pallas_leafbatch,
                                          hist_quant_xla, quantize_values)


@pytest.fixture(scope="module")
def hist_inputs():
    rng = np.random.RandomState(3)
    F, N, B, C = 6, 5000, 32, 9
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.int8))
    grad = jnp.asarray((rng.randn(N) * 0.4).astype(np.float32))
    hess = jnp.asarray((rng.rand(N) * 0.25).astype(np.float32))
    cid = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    ok = jnp.asarray(rng.rand(N) < 0.85)
    return bins, grad, hess, cid, ok, F, N, B, C


@requires_pltpu_interpret
def test_xla_quant_matches_pallas_interpret(hist_inputs):
    from jax.experimental.pallas import tpu as pltpu
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    via_xla = hist_quant_xla(bins, grad, hess, cid, ok, C, B)
    with pltpu.force_tpu_interpret_mode():
        via_pl = hist_pallas_leafbatch(bins, grad, hess, cid, ok, C, B,
                                       chunk=1024, dtype="int8")
    np.testing.assert_array_equal(np.asarray(via_xla), np.asarray(via_pl))


def test_quantized_counts_exact_and_sums_close(hist_inputs):
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    exact = histogram_leafbatch(bins, grad, hess, cid, ok, C, B,
                                compute_dtype=jnp.float32)
    quant = hist_quant_xla(bins, grad, hess, cid, ok, C, B)
    np.testing.assert_array_equal(np.asarray(exact[..., 2]),
                                  np.asarray(quant[..., 2]))
    # per-cell error bounded by n_cell * scale/2 (round-to-nearest)
    gscale = float(jnp.max(jnp.abs(grad))) / 127.0
    counts = np.asarray(exact[..., 2])
    err = np.abs(np.asarray(exact[..., 0]) - np.asarray(quant[..., 0]))
    assert (err <= 0.5 * gscale * counts + 1e-5).all()


def test_dispatch_through_leafbatch(hist_inputs):
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    a = histogram_leafbatch(bins, grad, hess, cid, ok, C, B,
                            compute_dtype="int8")
    b = hist_quant_xla(bins, grad, hess, cid, ok, C, B)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@requires_pltpu_interpret
def test_uint8_bins_above_127_not_dropped():
    """Production max_bin=255 stores bins as uint8 with values up to 254;
    the Pallas kernel must mask the int8 sign-extension back off (a plain
    int8 cast wraps 200 -> -56 and silently drops the row)."""
    from jax.experimental.pallas import tpu as pltpu
    rng = np.random.RandomState(9)
    F, N, B, C = 4, 3000, 255, 5
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray(rng.rand(N).astype(np.float32))
    cid = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    ok = jnp.ones(N, bool)
    via_xla = hist_quant_xla(bins, grad, hess, cid, ok, C, B)
    with pltpu.force_tpu_interpret_mode():
        via_pl = hist_pallas_leafbatch(bins, grad, hess, cid, ok, C, B,
                                       chunk=1024, dtype="int8")
    np.testing.assert_array_equal(np.asarray(via_xla), np.asarray(via_pl))
    # every row must land somewhere: total count == N per feature
    assert float(via_pl[..., 2].sum()) == float(N * F)


def test_wide_bins_int16_dispatch():
    """max_bin > 256 stores int16 bins; the int8 dispatch must route them
    through the XLA int formulation (the Pallas kernel's int8 bit-pattern
    trick only covers 8-bit bin ids) and still be exact."""
    rng = np.random.RandomState(5)
    F, N, B, C = 3, 2000, 300, 4
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.int16))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray(rng.rand(N).astype(np.float32))
    cid = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    ok = jnp.ones(N, bool)
    a = histogram_leafbatch(bins, grad, hess, cid, ok, C, B,
                            compute_dtype="int8")
    b = hist_quant_xla(bins, grad, hess, cid, ok, C, B)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(a[..., 2].sum()) == float(N * F)


def test_stochastic_rounding_unbiased(hist_inputs):
    bins, grad, hess, cid, ok, F, N, B, C = hist_inputs
    key = jax.random.PRNGKey(0)
    bits = jax.random.bits(key, (2, N), jnp.uint32)
    vals, scale = quantize_values(grad, hess, ok, rng_bits=bits)
    # SR keeps values within 1 ulp and is mean-preserving to ~sqrt(N) noise
    g_deq = np.asarray(vals[0], np.float32) * float(scale[0])
    gm = np.asarray(grad) * np.asarray(ok, np.float32)
    assert np.abs(g_deq - gm).max() <= float(scale[0]) + 1e-7
    assert abs((g_deq - gm).sum()) < float(scale[0]) * np.sqrt(N) * 4


def test_train_multiclass_int8(synthetic_binary):
    """int8 histograms under the multiclass objective (per-class gradient
    slices quantize with their own per-pass scales)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.io.dataset import Dataset
    x, _ = synthetic_binary
    rng = np.random.RandomState(4)
    y = ((x[:, 0] > 0).astype(int) + (x[:, 1] > 0.5).astype(int)).astype(
        np.float32)  # 3 classes

    def train(hist_dtype):
        ds = Dataset.from_arrays(x, y, max_bin=64)
        params = {"objective": "multiclass", "num_class": "3",
                  "num_leaves": "15", "min_data_in_leaf": "20",
                  "min_sum_hessian_in_leaf": "1.0",
                  "num_iterations": "10", "learning_rate": "0.2",
                  "grow_policy": "depthwise", "hist_dtype": hist_dtype}
        booster = lgb.train(params, ds)
        p = booster.predict_multiclass(x)
        return float(np.mean(np.argmax(p, axis=1) != y))

    err_f32 = train("float32")
    err_int8 = train("int8")
    assert err_int8 <= err_f32 + 0.02, (err_f32, err_int8)


def test_train_depthwise_int8_quality(synthetic_binary):
    """End-to-end: int8 histograms must reach f32-comparable train error."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.io.dataset import Dataset
    x, y = synthetic_binary

    def train(hist_dtype):
        ds = Dataset.from_arrays(x, y, max_bin=64)
        params = {"objective": "binary", "num_leaves": "31",
                  "min_data_in_leaf": "20", "min_sum_hessian_in_leaf": "1.0",
                  "num_iterations": "30", "learning_rate": "0.1",
                  "grow_policy": "depthwise", "hist_dtype": hist_dtype}
        booster = lgb.train(params, ds)
        p = booster.predict(x)
        return float(np.mean((p > 0.5) != (y > 0.5)))

    err_f32 = train("float32")
    err_int8 = train("int8")
    assert err_int8 <= err_f32 + 0.02, (err_f32, err_int8)


def test_int8_row_capacity_guard():
    """ADVICE r2 (medium): a histogram cell's int32 accumulator holds at
    most 2^31/127 rows (iteration-0 binary hessians all quantize to 127,
    and a single-bin feature concentrates every row into one cell) —
    beyond that the booster must refuse int8 loudly, not wrap silently."""
    from lightgbm_tpu.models.gbdt import (check_int8_row_capacity,
                                          INT8_HIST_MAX_ROWS)
    from lightgbm_tpu.utils.log import LightGBMError
    check_int8_row_capacity(INT8_HIST_MAX_ROWS)       # at the limit: fine
    check_int8_row_capacity(11_000_000)               # bench scale: fine
    with pytest.raises(LightGBMError):
        check_int8_row_capacity(INT8_HIST_MAX_ROWS + 1)


def test_stochastic_rounding_unbiased_and_deterministic():
    """quant_rounding=stochastic: value-keyed bits make rounding unbiased
    in expectation over many distinct values (mean quantization error well
    below the half-quantum bias a floor/ceil would give) and fully
    deterministic (same inputs -> same bits -> same ints)."""
    from lightgbm_tpu.ops.hist_pallas import quantize_values
    rng = np.random.RandomState(0)
    n = 200_000
    grad = rng.randn(n).astype(np.float32)
    hess = (0.1 + rng.rand(n)).astype(np.float32)
    ok = np.ones(n, bool)
    v1, s1 = quantize_values(jnp.asarray(grad), jnp.asarray(hess),
                             jnp.asarray(ok), stochastic=True, salt=7)
    v2, s2 = quantize_values(jnp.asarray(grad), jnp.asarray(hess),
                             jnp.asarray(ok), stochastic=True, salt=7)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))

    # unbiasedness: the mean signed quantization error of the SUM is tiny
    # relative to the one-ulp-per-row worst case
    gs = float(np.asarray(s1)[0])
    err = np.asarray(v1)[0].astype(np.float64) * gs - grad
    assert abs(err.mean()) < 0.02 * gs   # nearest-rounding is also ~0; the
    # distinguishing property is variance behavior, checked via the sum:
    assert abs(err.sum()) < 3 * gs * np.sqrt(n)

    # different salt -> different rounding realization (not a constant fn)
    v3, _ = quantize_values(jnp.asarray(grad), jnp.asarray(hess),
                            jnp.asarray(ok), stochastic=True, salt=8)
    assert (np.asarray(v3)[0] != np.asarray(v1)[0]).any()


def test_stochastic_int8_dp_bit_identical_to_serial():
    """The stochastic bits are keyed on the row's (grad, hess) VALUES, not
    its position — so serial and data-parallel programs quantize every
    physical row identically and the int8 bit-identity chain survives
    (both dp_schedule variants)."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    rng = np.random.RandomState(3)
    n, f = 1999, 8
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.4 * rng.randn(n)) > 0).astype(
        np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 4, "learning_rate": 0.2,
              "grow_policy": "depthwise", "hist_dtype": "int8",
              "quant_rounding": "stochastic"}

    def make(tree_learner, machines, schedule="psum"):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner, num_machines=machines,
                 dp_schedule=schedule)
        cfg.set({k: str(v) for k, v in p.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = None
        if tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        b.init(cfg.boosting_config, ds, obj, learner=learner)
        return b

    bs = make("serial", 1)
    for _ in range(4):
        bs.train_one_iter(is_eval=False)
    for sched in ("psum", "reduce_scatter"):
        bd = make("data", 8, sched)
        bd.train_chunk(4)
        for k, (t1, t2) in enumerate(zip(bs.models, bd.models)):
            assert t1.num_leaves == t2.num_leaves, (sched, k)
            np.testing.assert_array_equal(t1.split_feature,
                                          t2.split_feature,
                                          err_msg=f"{sched} tree {k}")
            np.testing.assert_array_equal(t1.threshold_bin,
                                          t2.threshold_bin,
                                          err_msg=f"{sched} tree {k}")
