"""Training-health monitor tests (ISSUE 2): injected-NaN gradients raise
health events (and halt cleanly under on_anomaly=halt), both training paths
emit health/memory sink blocks, eval-divergence detection fires, and the
tier-1 invariant that the monitor never perturbs training numerics."""
import json

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu import health as health_mod
from lightgbm_tpu.health import HealthMonitor, TrainingHealthError
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _data(n=1100, seed=5, features=6):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, features)
    y = (x[:, 0] + 0.5 * x[:, 1] + 0.1 * rng.randn(n) > 0).astype(np.float32)
    return x, y


BASE = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
        "min_sum_hessian_in_leaf": 1.0, "learning_rate": 0.2}


class _NaNObjective:
    """Regression-like objective that poisons the first ``bad`` gradients
    with NaN from iteration ``start_iter`` on — the injected-fault fixture
    the health monitor must catch."""
    sigmoid = -1.0
    num_class = 1

    def __init__(self, bad=7, start_iter=0):
        self.bad = bad
        self.start_iter = start_iter
        self._calls = 0

    def init(self, metadata, num_data):
        self.label = jnp.asarray(np.asarray(metadata.label), jnp.float32)

    def get_gradients(self, score):
        grad = score - self.label
        if self._calls >= self.start_iter:
            grad = grad.at[:self.bad].set(jnp.nan)
        self._calls += 1
        return grad, jnp.ones_like(grad)


def _nan_booster(ds, on_anomaly, **extra):
    cfg = OverallConfig()
    cfg.set(dict({k: str(v) for k, v in BASE.items()},
                 objective="regression", health="true",
                 on_anomaly=on_anomaly, **extra), require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds, _NaNObjective())
    return booster


# ---------------------------------------------------------- injected faults

def test_nan_gradients_recorded_and_warn(tmp_path):
    """NaN gradients produce a nonzero grad_nan count in the sink records
    and in the cumulative summary; on_anomaly=warn keeps training alive
    (the NaN root histogram rejects every split, so training stops on the
    degenerate tree, not on the monitor)."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")
    telemetry.enable(path)
    booster = _nan_booster(ds, "warn")
    booster.run_training(3, False)
    telemetry.disable()
    recs = [json.loads(line) for line in open(path)]
    blocks = [r["health"] for r in recs if "iter" in r and "health" in r]
    assert blocks and blocks[0]["grad_nan"] == 7
    assert booster.health_summary()["grad_nan"] >= 7
    assert booster.health_summary()["anomalous_iterations"] >= 1


def test_on_anomaly_halt_stops_cleanly(tmp_path):
    """on_anomaly=halt raises TrainingHealthError (a LightGBMError: the
    CLI maps it to exit 1), naming the offending counts — and the record
    explaining the stop is already in the sink."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")
    telemetry.enable(path)
    booster = _nan_booster(ds, "halt")
    with pytest.raises(TrainingHealthError, match="grad_nan=7"):
        booster.run_training(3, False)
    telemetry.disable()
    from lightgbm_tpu.utils import log
    assert issubclass(TrainingHealthError, log.LightGBMError)
    recs = [json.loads(line) for line in open(path)]
    assert any(r.get("health", {}).get("grad_nan") == 7 for r in recs)


def test_on_anomaly_halt_mid_training():
    """Faults appearing mid-run (start_iter=2) halt at that iteration,
    keeping the clean iterations' trees."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    telemetry.enable()  # no sink: monitor alone must still halt
    cfg = OverallConfig()
    cfg.set(dict({k: str(v) for k, v in BASE.items()},
                 objective="regression", health="true",
                 on_anomaly="halt"), require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds, _NaNObjective(start_iter=2))
    with pytest.raises(TrainingHealthError):
        booster.run_training(5, False)
    assert len(booster.models) >= 2
    telemetry.disable()


def test_nan_in_chunked_path_detected(tmp_path):
    """The fused depthwise chunk accumulates the health vector in-program:
    NaN gradients surface with on_anomaly=halt on the chunk path too."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    telemetry.enable(str(tmp_path / "m.jsonl"))
    cfg = OverallConfig()
    cfg.set(dict({k: str(v) for k, v in BASE.items()},
                 objective="regression", health="true", on_anomaly="halt",
                 grow_policy="depthwise"), require_data=False)
    booster = GBDT()
    obj = _NaNObjective()

    # chunk_spec closing over the instance: NaN from iteration 0 in-scan
    def grad_fn(params, score):
        grad = score - params["label"]
        grad = grad.at[:7].set(jnp.nan)
        return grad, jnp.ones_like(grad)

    obj.chunk_spec = lambda: (("nan_test",),
                              {"label": obj.label}, grad_fn)
    booster.init(cfg.boosting_config, ds, obj)
    with pytest.raises(TrainingHealthError, match="grad_nan"):
        booster.train_chunk(4)
    telemetry.disable()


# ------------------------------------------------------------- sink schema

def test_health_memory_blocks_on_both_paths(tmp_path):
    """Acceptance: a CPU train with metrics_out= emits per-iteration
    records containing health and memory blocks — per-iteration leaf-wise
    AND fused depthwise chunk paths."""
    x, y = _data(n=1234)
    for tag, extra in (("leafwise", {"num_iterations": 3}),
                       ("depthwise", {"num_iterations": 8,
                                      "grow_policy": "depthwise"})):
        ds = Dataset.from_arrays(x, y, max_bin=32)
        path = str(tmp_path / (tag + ".jsonl"))
        lgb.train(dict(BASE, metrics_out=path, **extra), ds)
        telemetry.disable()
        recs = [json.loads(line) for line in open(path)]
        iter_recs = [r for r in recs if "iter" in r]
        assert len(iter_recs) == extra["num_iterations"], tag
        for rec in iter_recs:
            for key in (health_mod.HEALTH_VEC_KEYS
                        + health_mod.TREE_HEALTH_KEYS):
                assert key in rec["health"], (tag, key)
            assert rec["health"]["grad_nan"] == 0
            assert rec["memory"]["peak_bytes_in_use"] > 0
        # residency is filed once, before the first iteration record
        assert "residency" in recs[0]
        assert recs[0]["residency"]["num_rows"] == 1234


def test_health_off_means_no_blocks(tmp_path):
    """health=false with a sink: records carry NO health block (and no
    monitor runs), so the setting is a true kill switch."""
    x, y = _data()
    ds = Dataset.from_arrays(x, y, max_bin=32)
    path = str(tmp_path / "m.jsonl")
    booster = lgb.train(dict(BASE, num_iterations=2, metrics_out=path,
                             health="false"), ds)
    telemetry.disable()
    assert booster.health_summary() is None
    recs = [json.loads(line) for line in open(path)]
    assert all("health" not in r for r in recs if "iter" in r)


# ------------------------------------------------------------- divergence

def test_eval_divergence_detection():
    """k consecutive worsening metric values flag an eval_divergence
    anomaly (unit-level: the monitor's streak logic, both directions)."""
    mon = HealthMonitor(on_anomaly="record", divergence_rounds=3)
    # bigger_better=False (loss): strictly increasing = worsening
    for v in (0.5, 0.6, 0.7):  # two worsenings after the first value
        mon.observe_eval("valid/loss", v, False)
    assert not mon._pending_divergence
    mon.observe_eval("valid/loss", 0.8, False)  # third consecutive
    block = mon.assemble(None)
    assert block["eval_divergence"][0]["metric"] == "valid/loss"
    assert block["eval_divergence"][0]["rounds"] == 3
    assert mon.anomalies(block) == ["eval_divergence:valid/loss"]
    # an improvement resets the streak (bigger_better=True: decreasing is
    # worsening; the bump to 0.75 arrives before the streak reaches 3)
    mon2 = HealthMonitor(on_anomaly="record", divergence_rounds=3)
    for v in (0.9, 0.8, 0.7, 0.75, 0.74, 0.73):
        mon2.observe_eval("t/auc", v, True)
    assert not mon2._pending_divergence


def test_divergence_halts_training(tmp_path):
    """End-to-end: a validation metric forced to worsen every iteration
    trips health_divergence_rounds under on_anomaly=halt."""
    x, y = _data(seed=11)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    # validate on ANTI-labels: every boosting iteration makes the valid
    # logloss strictly worse, a textbook divergence
    vs = Dataset.from_arrays(x[:400], 1.0 - y[:400], reference=ds)
    with pytest.raises(TrainingHealthError, match="eval divergence"):
        lgb.train(dict(BASE, num_iterations=12, metric="binary_logloss",
                       health="true", on_anomaly="halt",
                       health_divergence_rounds=3,
                       metrics_out=str(tmp_path / "m.jsonl")),
                  ds, valid_sets=[vs])
    telemetry.disable()


def test_divergence_halt_mid_chunk_leaves_consistent_state(tmp_path):
    """A halt raised inside the fused chunk loop must leave the booster
    exactly like an early stop at that iteration: surplus scan iterations
    rolled back, models/iter/score in agreement."""
    x, y = _data(seed=13)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    vs = Dataset.from_arrays(x[:400], 1.0 - y[:400], reference=ds)
    telemetry.enable(str(tmp_path / "m.jsonl"))
    cfg = OverallConfig()
    cfg.set(dict({k: str(v) for k, v in BASE.items()},
                 grow_policy="depthwise", metric="binary_logloss",
                 health="true", on_anomaly="halt",
                 health_divergence_rounds=3), require_data=False)
    booster = GBDT()
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.metrics import create_metric
    booster.init(cfg.boosting_config, ds,
                 create_objective("binary", cfg.objective_config))
    booster.add_valid_dataset(vs, [create_metric("binary_logloss",
                                                 cfg.metric_config)])
    with pytest.raises(TrainingHealthError, match="eval divergence"):
        booster.train_chunk(12, is_eval=True)
    telemetry.disable()
    # halted at the divergence iteration, state truncated there
    assert 0 < booster.iter < 12
    assert len(booster.models) == booster.iter
    # the rolled-back score matches replaying exactly the kept trees
    replay = np.zeros(ds.num_data)
    for tree in booster.models:
        replay += tree.predict(x)
    np.testing.assert_allclose(np.asarray(booster.score[0]), replay,
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------- numerics non-perturbation

def test_scores_bit_identical_health_on_vs_off(tmp_path):
    """Tier-1 invariant: the monitor computes FROM training arrays, never
    into them — scores are bit-identical with health on vs off, telemetry
    armed both times, on both growth paths."""
    x, y = _data(seed=9)

    def scores(health, grow_policy):
        telemetry.disable()
        telemetry.reset()
        ds = Dataset.from_arrays(x, y, max_bin=32)
        booster = lgb.train(dict(BASE, num_iterations=4,
                                 grow_policy=grow_policy, health=health,
                                 metrics_out=str(tmp_path / "m.jsonl"),
                                 bagging_fraction=0.8, bagging_freq=1), ds)
        out = np.asarray(booster.score)
        telemetry.disable()
        return out

    for gp in ("leafwise", "depthwise"):
        np.testing.assert_array_equal(scores("false", gp),
                                      scores("true", gp))


# ------------------------------------------------------------------ config

def test_health_config_options():
    cfg = OverallConfig()
    cfg.set({"health": "true", "on_anomaly": "halt",
             "health_divergence_rounds": "4", "memory_stats": "false"},
            require_data=False)
    assert cfg.boosting_config.health == "true"
    assert cfg.boosting_config.on_anomaly == "halt"
    assert cfg.boosting_config.health_divergence_rounds == 4
    assert cfg.io_config.memory_stats == "false"
    # defaults
    d = OverallConfig()
    assert d.boosting_config.health == "auto"
    assert d.boosting_config.on_anomaly == "warn"
    assert d.io_config.memory_stats == "auto"
    from lightgbm_tpu.utils import log
    with pytest.raises(log.LightGBMError):
        OverallConfig().set({"on_anomaly": "explode"}, require_data=False)


def test_quant_saturation_gauge():
    """int8 saturation gauge: uniform magnitudes all sit at the per-pass
    max → every entry saturates; a spread distribution saturates only the
    max row (per channel)."""
    from lightgbm_tpu.ops.hist_pallas import quant_saturation_count
    g = jnp.full((64,), 3.0)
    h = jnp.linspace(0.1, 1.0, 64)
    sat = float(quant_saturation_count(g, h))
    assert sat == 64 + 1  # all grads + the single max hessian
