"""Mixed-bin feature packing (ISSUE 6): bit-identity vs the uniform-255
oracle and the packing-plan rules.

The contract under test: partitioning features into bin-width classes and
running one histogram pass per class must be INVISIBLE to everything
downstream — split decisions, thresholds, leaf values, scores bit-identical
to the uniform single-pass path on every grower and both precision modes,
serial and under the data-parallel reduce_scatter ownership schedule
(per-class accumulators reassemble into canonical feature order BEFORE any
reduction/argmax, so tie-breaks and ownership blocks never see the packed
layout)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.binning import (NARROW_BINS, PackSpec,
                                     plan_feature_packing)
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective


# ------------------------------------------------------------- plan rules


def test_plan_splits_classes_stably():
    nb = np.array([254, 5, 254, 30, 2, 254], np.int32)
    spec = plan_feature_packing(nb, 254)
    assert spec.widths == (NARROW_BINS, 254)
    assert spec.counts == (3, 3)
    # stable within class: narrow features in canonical order, then wide
    assert spec.perm == (1, 3, 4, 0, 2, 5)
    # c2p inverts perm
    for p, f in enumerate(spec.perm):
        assert spec.c2p[f] == p
    assert spec.ranges == ((0, 3, NARROW_BINS), (3, 3, 254))


def test_plan_collapses_single_class():
    # every feature wide -> no packing (the degenerate case the growers
    # must serve via the single-class path)
    assert plan_feature_packing(np.array([254, 200, 255]), 255) is None
    # every feature narrow -> num_bins_max is already small; no packing
    assert plan_feature_packing(np.array([5, 30, 2]), 30) is None
    # empty
    assert plan_feature_packing(np.array([], np.int32), 255) is None


def test_plan_mode_and_env_hatch(monkeypatch):
    nb = np.array([254, 5], np.int32)
    assert plan_feature_packing(nb, 254, mode="false") is None
    assert plan_feature_packing(nb, 254, mode="true") is not None
    monkeypatch.setenv("LGBM_TPU_NO_MIXEDBIN", "1")
    assert plan_feature_packing(nb, 254) is None


def test_config_parses_mixed_bin():
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "mixed_bin": "false"},
            require_data=False)
    assert cfg.boosting_config.tree_config.mixed_bin == "false"
    with pytest.raises(Exception):
        cfg.set({"objective": "binary", "mixed_bin": "sometimes"},
                require_data=False)


# ------------------------------------------------ end-to-end bit-identity


def _mixed_dataset(n=2500, seed=3):
    """Narrow (counts/flags) and wide (continuous) features interleaved."""
    rng = np.random.RandomState(seed)
    cont = rng.randn(n, 3)
    x = np.stack([
        cont[:, 0],
        rng.randint(0, 5, n).astype(float),
        rng.randint(0, 40, n).astype(float),
        cont[:, 1],
        (rng.rand(n) < 0.4).astype(float),
        rng.randint(0, 3, n).astype(float),
        cont[:, 2],
    ], axis=1).astype(np.float64)
    w = rng.randn(x.shape[1])
    logits = (x - x.mean(0)) / (x.std(0) + 1e-9) @ w
    y = (logits + rng.randn(n) > 0).astype(np.float32)
    return Dataset.from_arrays(x, y, max_bin=255)


@pytest.fixture(scope="module")
def mixed_ds():
    ds = _mixed_dataset()
    # the fixture only makes sense if the data actually mixes classes
    nb = ds.num_bins
    assert (nb <= NARROW_BINS).any() and (nb > NARROW_BINS).any()
    return ds


def _train(ds, extra, iters=5, learner_kind=None):
    params = {"objective": "binary", "num_leaves": "15",
              "num_iterations": str(iters), "min_data_in_leaf": "20",
              "min_sum_hessian_in_leaf": "5.0", "learning_rate": "0.1"}
    params.update(extra)
    cfg = OverallConfig()
    cfg.set(params, require_data=False)
    booster = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    learner = None
    if learner_kind is not None:
        from lightgbm_tpu.parallel.learners import create_parallel_learner
        cfg.boosting_config.tree_learner = learner_kind
        learner = create_parallel_learner(cfg)
    booster.init(cfg.boosting_config, ds, obj, learner=learner)
    booster.run_training(iters, is_eval=False)
    return booster


def _assert_identical(b_on, b_off, tag):
    assert b_on._pack_spec is not None, tag
    assert b_off._pack_spec is None, tag
    assert len(b_on.models) == len(b_off.models), tag
    for t1, t2 in zip(b_on.models, b_off.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature,
                                      err_msg=tag)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin,
                                      err_msg=tag)
        np.testing.assert_array_equal(np.asarray(t1.leaf_value),
                                      np.asarray(t2.leaf_value),
                                      err_msg=tag)
        np.testing.assert_array_equal(np.asarray(t1.threshold),
                                      np.asarray(t2.threshold),
                                      err_msg=tag)
    np.testing.assert_array_equal(np.asarray(b_on.score),
                                  np.asarray(b_off.score), err_msg=tag)


@pytest.mark.parametrize("hist_dtype", ["float32", "int8"])
@pytest.mark.parametrize("grower", ["leafwise", "leafcompact", "depthwise"])
def test_serial_bit_identity(mixed_ds, grower, hist_dtype):
    extra = {"hist_dtype": hist_dtype}
    if grower == "depthwise":
        extra["grow_policy"] = "depthwise"
    else:
        extra["leafwise_compact"] = ("true" if grower == "leafcompact"
                                     else "false")
    on = _train(mixed_ds, dict(extra, mixed_bin="true"))
    off = _train(mixed_ds, dict(extra, mixed_bin="false"))
    _assert_identical(on, off, f"{grower}/{hist_dtype}")


@pytest.mark.parametrize("hist_dtype", ["float32", "int8"])
def test_dp_reduce_scatter_bit_identity(mixed_ds, hist_dtype):
    """The per-class accumulators must ride the existing DP ownership
    schedule: feature-block psum_scatter over the CANONICAL reassembled
    histogram/int-accumulator, owned-slice search, SplitInfo allreduce —
    packed == uniform, and (int8) == serial, bit for bit."""
    extra = {"dp_schedule": "reduce_scatter", "hist_dtype": hist_dtype,
             "leafwise_compact": "true"}
    on = _train(mixed_ds, dict(extra, mixed_bin="true"),
                learner_kind="data")
    off = _train(mixed_ds, dict(extra, mixed_bin="false"),
                 learner_kind="data")
    _assert_identical(on, off, f"dp-rs/{hist_dtype}")
    if hist_dtype == "int8":
        serial = _train(mixed_ds, {"hist_dtype": "int8",
                                   "leafwise_compact": "true",
                                   "mixed_bin": "true"})
        for t1, t2 in zip(on.models, serial.models):
            np.testing.assert_array_equal(t1.split_feature,
                                          t2.split_feature)
            np.testing.assert_array_equal(t1.threshold_bin,
                                          t2.threshold_bin)


def test_dp_depthwise_chunk_bit_identity(mixed_ds):
    extra = {"dp_schedule": "reduce_scatter", "grow_policy": "depthwise"}
    on = _train(mixed_ds, dict(extra, mixed_bin="true"),
                learner_kind="data", iters=10)
    off = _train(mixed_ds, dict(extra, mixed_bin="false"),
                 learner_kind="data", iters=10)
    _assert_identical(on, off, "dp-rs/depthwise")


def test_all_wide_collapses_to_single_class():
    """Degenerate case: a continuous-only table must not pack at all —
    mixed_bin=true resolves to the identity layout (pack spec None) and
    training proceeds on the historical single-pass path."""
    rng = np.random.RandomState(5)
    x = rng.randn(1200, 5)
    y = (x @ rng.randn(5) + rng.randn(1200) > 0).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=255)
    assert (ds.num_bins > NARROW_BINS).all()
    b = _train(ds, {"mixed_bin": "true"}, iters=3)
    assert b._pack_spec is None
    assert len(b.models) == 3


def test_feature_parallel_keeps_uniform_layout(mixed_ds):
    b = _train(mixed_ds, {"mixed_bin": "true"}, learner_kind="feature",
               iters=3)
    assert b._pack_spec is None
    assert len(b.models) == 3


def test_valid_scores_and_model_file_canonical(mixed_ds, tmp_path):
    """Trees leave the booster in canonical/real feature space: the saved
    model and validation-set replay must be identical packed vs not."""
    rng = np.random.RandomState(9)
    xv = np.stack([
        rng.randn(400),
        rng.randint(0, 5, 400).astype(float),
        rng.randint(0, 40, 400).astype(float),
        rng.randn(400),
        (rng.rand(400) < 0.4).astype(float),
        rng.randint(0, 3, 400).astype(float),
        rng.randn(400),
    ], axis=1).astype(np.float64)
    yv = (rng.rand(400) > 0.5).astype(np.float32)
    outs = {}
    for mode in ("true", "false"):
        params = {"objective": "binary", "num_leaves": "7",
                  "num_iterations": "4", "min_data_in_leaf": "20",
                  "min_sum_hessian_in_leaf": "5.0", "mixed_bin": mode}
        cfg = OverallConfig()
        cfg.set(params, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, mixed_ds, obj)
        vd = Dataset.from_arrays(xv, yv, reference=mixed_ds)
        from lightgbm_tpu.metrics import create_metric
        b.add_valid_dataset(vd, [create_metric("binary_logloss",
                                               cfg.metric_config)])
        b.run_training(4, is_eval=True)
        path = str(tmp_path / ("model_%s.txt" % mode))
        b.save_model_to_file(True, path)
        outs[mode] = (open(path).read(),
                      np.asarray(b.valid_datasets[0]["score"]).copy(),
                      b.predict(xv))
    assert outs["true"][0] == outs["false"][0]
    np.testing.assert_array_equal(outs["true"][1], outs["false"][1])
    np.testing.assert_array_equal(outs["true"][2], outs["false"][2])


def test_histogram_leafbatch_packed_matches_uniform():
    """Kernel-level check on the XLA routes (f32 einsum + int8 einsum):
    canonical-order histograms from the packed layout equal the uniform
    pass cell for cell."""
    from lightgbm_tpu.ops.histogram import histogram_leafbatch
    rng = np.random.RandomState(1)
    F, N, C, B = 7, 3000, 4, 200
    num_bins = np.array([200, 5, 30, 200, 2, 60, 200])
    bins = np.stack([rng.randint(0, nb, N)
                     for nb in num_bins]).astype(np.uint8)
    spec = plan_feature_packing(num_bins, B)
    bins_packed = bins[np.asarray(spec.perm)]
    grad = rng.randn(N).astype(np.float32)
    hess = rng.rand(N).astype(np.float32)
    cid = rng.randint(0, C, N).astype(np.int32)
    ok = rng.rand(N) < 0.9
    for dt in (jnp.float32, "int8"):
        uni = histogram_leafbatch(
            jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(cid), jnp.asarray(ok), C, B, compute_dtype=dt)
        packed = histogram_leafbatch(
            jnp.asarray(bins_packed), jnp.asarray(grad),
            jnp.asarray(hess), jnp.asarray(cid), jnp.asarray(ok), C, B,
            compute_dtype=dt, packing=spec)
        np.testing.assert_array_equal(np.asarray(uni), np.asarray(packed),
                                      err_msg=str(dt))
