"""Compiled serving engine (ISSUE 7, lightgbm_tpu/serving.py).

Correctness bar: the breadth-first lockstep engine scores BIT-EQUAL to
the training-side scorer (ops/scoring.ensemble_scores — the engine's
algo="scan" path drives the identical kernels) on every objective, leaf
indices match the host replay exactly, bucket padding never leaks into
results, and steady-state bucketed calls keep a CLOSED compiled-program
inventory (zero recompiles, pinned via the costmodel registry).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import costmodel, serving, telemetry
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.predictor import Predictor
from lightgbm_tpu.models.tree import Tree
from lightgbm_tpu.ops.scoring import ensemble_scores
from lightgbm_tpu.serving import FlatEnsemble, ServingEngine

BASE = {"num_leaves": 15, "min_data_in_leaf": 20,
        "min_sum_hessian_in_leaf": 1.0, "num_iterations": 8,
        "learning_rate": 0.2}

OBJECTIVES = ("regression", "binary", "lambdarank", "multiclass")


def _case(objective, n=500, f=6, seed=3):
    """(trained booster, features) for one objective."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    params = dict(BASE, objective=objective)
    ds_kwargs = {}
    if objective == "regression":
        y = (x[:, 0] + 0.3 * x[:, 1] ** 2
             + 0.1 * rng.randn(n)).astype(np.float32)
    elif objective == "binary":
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    elif objective == "lambdarank":
        y = np.clip(np.digitize(x[:, 0], [-0.6, 0.2, 1.0]),
                    0, 3).astype(np.float32)
        ds_kwargs["query_boundaries"] = np.arange(0, n + 1, 50)
    else:
        y = np.digitize(x[:, 0], [-0.5, 0.5]).astype(np.float32)
        params["num_class"] = 3
        params["num_iterations"] = 4   # 4 iters x 3 class trees
    ds = Dataset.from_arrays(x, y, max_bin=64, **ds_kwargs)
    return lgb.train(params, ds), x


def _host_scores(flat, leaf_value, features):
    """Sequential f32 per-class accumulation from a host replay of the
    flattened model — the engine's exact accumulation order."""
    codes = flat.encode(features)
    N = features.shape[0]
    score = np.zeros((flat.num_class, N), np.float32)
    for t in range(flat.num_trees):
        # replay the BFS walk per tree on host
        states = np.full(N, int(flat.root_state[t]), np.int32)
        for _ in range(max(flat.max_depth, 1)):
            node = np.maximum(states, 0)
            sf = flat.split_feature[t][node]
            go_right = codes[sf, np.arange(N)] > flat.threshold_rank[t][node]
            nxt = np.where(go_right, flat.right_child[t][node],
                           flat.left_child[t][node])
            states = np.where(states >= 0, nxt, states)
        leaf = -states - 1
        score[flat.tree_class[t]] += leaf_value[t][leaf]
    return score


@pytest.mark.parametrize("objective", OBJECTIVES)
def test_bit_equal_vs_training_scorer(objective):
    """f32 engine scores == the training-side per-tree scan scorer,
    bitwise, on every objective (and close to the f64 host tree walk)."""
    booster, x = _case(objective)
    flat = booster.export_flat()
    bfs = ServingEngine(flat).scores(x)
    scan = ServingEngine(flat, algo="scan").scores(x)
    np.testing.assert_array_equal(bfs, scan)
    # sanity vs the f64 host walk: same leaves, f32 accumulation only
    host = np.zeros((booster.num_class, x.shape[0]))
    for k, t in enumerate(booster.models):
        host[k % booster.num_class] += t.predict(x)
    np.testing.assert_allclose(bfs, host, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("objective", OBJECTIVES)
def test_int8_engine_bit_equal_to_dequantized_replay(objective):
    """int8 engine == host replay of the SAME quantized leaf table,
    bitwise (routing is untouched by quantization), and within the
    per-tree quantization-step bound of the f32 scores."""
    booster, x = _case(objective)
    flat = booster.export_flat()
    eng8 = ServingEngine(flat, quantize="int8")
    s8 = eng8.scores(x)
    expected = _host_scores(flat, flat.dequantized_leaf_value(), x)
    np.testing.assert_array_equal(s8, expected.astype(np.float64))
    s32 = ServingEngine(flat).scores(x)
    _, scale = flat.int8_tables()
    # each tree rounds by at most scale/2
    assert np.abs(s8 - s32).max() <= scale.sum() / 2 + 1e-6
    # the scan A/B path must score the SAME quantized model (it serves
    # the dequantized table, never silently full precision)
    s8_scan = ServingEngine(flat, quantize="int8", algo="scan").scores(x)
    np.testing.assert_array_equal(s8, s8_scan)


def test_bucket_padding_correctness():
    """Pad-to-bucket must never leak into results: every batch size maps
    to the exact-shape reference (the training scorer run UNPADDED)."""
    booster, x = _case("binary", n=1200)
    flat = booster.export_flat()
    eng = ServingEngine(flat, buckets=(1, 32, 1024, 65536))
    import jax.numpy as jnp
    for n in (1, 31, 33, 1000):
        got = eng.scores(x[:n])
        codes = flat.encode(x[:n])
        exact = ensemble_scores(
            jnp.asarray(codes), jnp.asarray(flat.split_feature),
            jnp.asarray(flat.threshold_rank), jnp.asarray(flat.left_child),
            jnp.asarray(flat.right_child), jnp.asarray(flat.leaf_value),
            jnp.asarray(flat.num_leaves), jnp.asarray(flat.tree_class),
            max_nodes=flat.max_nodes, num_class=flat.num_class)
        np.testing.assert_array_equal(got, np.asarray(exact, np.float64))


def test_chunking_beyond_largest_bucket():
    """N above the biggest bucket chunks internally and still matches."""
    booster, x = _case("binary", n=700)
    flat = booster.export_flat()
    small = ServingEngine(flat, buckets=(1, 256))
    big = ServingEngine(flat, buckets=(1024,))
    np.testing.assert_array_equal(small.scores(x), big.scores(x))


@pytest.mark.parametrize("quantize", ["float32", "int8"])
def test_leaf_index_parity(quantize):
    """Engine leaf indices == the host replay walk, exactly — in both
    ensemble modes (quantization never touches routing)."""
    booster, x = _case("binary")
    eng = ServingEngine(booster.export_flat(), quantize=quantize)
    host = booster.predict_leaf_index(x)   # host path (below threshold)
    np.testing.assert_array_equal(eng.leaf_indices(x), host)


def test_nan_routes_left_through_engine():
    booster, x = _case("binary")
    xe = x[:64].copy()
    xe[:, :3] = np.nan
    host = np.zeros(64)
    for t in booster.models:
        host += t.predict(xe)
    got = ServingEngine(booster.export_flat()).scores(xe)[0]
    np.testing.assert_allclose(got, host, rtol=1e-5, atol=1e-6)


def test_stump_trees_supported():
    """num_leaves==1 trees (degenerate stops) flatten to a ~0 root state
    and contribute their constant leaf everywhere."""
    stump = Tree(1, *[np.zeros(0)] * 8, leaf_value=np.array([0.25]))
    booster, x = _case("binary", n=200)
    models = [stump] + booster.models
    flat = FlatEnsemble.from_models(models, 1)
    got = ServingEngine(flat).scores(x)[0]
    base = ServingEngine(booster.export_flat()).scores(x)[0]
    # the stump's constant enters the f32 accumulation FIRST on device
    # (tree order), while `base + 0.25` adds it last in f64 — identical
    # leaves, rounding-order-only difference
    np.testing.assert_allclose(got, base + np.float32(0.25),
                               rtol=1e-5, atol=1e-6)


def test_no_recompile_on_repeated_bucketed_calls():
    """Steady-state contract: repeated calls across batch sizes within
    the bucket ladder bump call counts on EXISTING compiled programs and
    never add a new signature (costmodel registry — the compile
    counters)."""
    booster, x = _case("binary")
    telemetry.enable()
    telemetry.reset()
    try:
        eng = ServingEngine(booster.export_flat(), buckets=(1, 32, 1024))
        for n in (5, 9, 31):          # all land in the 32 bucket
            eng.scores(x[:n])
        progs = costmodel.phase_program_records("predict")
        n_programs = len(progs)
        assert n_programs >= 1
        calls0 = sum(r["calls"] for r in progs)
        for n in (6, 17, 32, 2, 30):  # same bucket, five more calls
            eng.scores(x[:n])
        progs = costmodel.phase_program_records("predict")
        assert len(progs) == n_programs, \
            "bucketed repeat calls added a compiled program (recompile)"
        assert sum(r["calls"] for r in progs) == calls0 + 5
    finally:
        telemetry.disable()
        telemetry.reset()


def test_donation_smoke():
    """Forced donation stays correct across repeated calls (the donated
    codes buffer is rebuilt per call; CPU ignores donation with a
    warning — the contract is correctness, not the recycle)."""
    booster, x = _case("binary")
    flat = booster.export_flat()
    base = ServingEngine(flat, donate="false").scores(x[:40])
    eng = ServingEngine(flat, donate="true")
    for _ in range(2):
        np.testing.assert_array_equal(eng.scores(x[:40]), base)


def test_predict_file_flattens_ensemble_once(tmp_path):
    """predict_file's chunk loop must NOT re-encode the ensemble per
    chunk: one flatten for the whole file (the old per-call
    _device_predict_encode re-ran it every 500k lines)."""
    booster, x = _case("binary", n=200)
    data = tmp_path / "pred.tsv"
    np.savetxt(data, np.column_stack([np.zeros(len(x)), x]),
               delimiter="\t", fmt="%.8f")
    base_count = serving.FLATTEN_COUNT
    predictor = Predictor(booster, True, False, -1)
    predictor.predict_file(str(data), str(tmp_path / "out.txt"),
                           has_header=False, chunk_lines=40)  # 5 chunks
    assert serving.FLATTEN_COUNT == base_count + 1
    preds = np.loadtxt(tmp_path / "out.txt")
    assert preds.shape == (200,)
    assert np.all((preds >= 0) & (preds <= 1))
    # the file path agrees with the in-memory engine path (6-decimal
    # text round-trip)
    expected = predictor.predict_matrix(x)
    np.testing.assert_allclose(preds, expected, atol=5e-7)


def test_predict_matrix_pads_in_input_dtype():
    """The short-row pad must use the INPUT dtype — np.zeros' f64
    default silently upcast f32 matrices on concatenate."""
    booster, x = _case("binary")
    predictor = Predictor(booster, True, False, -1)
    seen = {}
    orig = predictor.engine.scores

    def spy(features):
        seen["dtype"] = features.dtype
        return orig(features)

    predictor.engine.scores = spy
    predictor.predict_matrix(x[:, :-1].astype(np.float32))
    assert seen["dtype"] == np.float32


def test_predictor_modes_match_gbdt():
    """Predictor transforms (sigmoid / softmax / leaf index) equal the
    GBDT host-path predictions."""
    booster, x = _case("binary")
    p = Predictor(booster, True, False, -1)
    np.testing.assert_allclose(p.predict_matrix(x), booster.predict(x),
                               rtol=1e-5, atol=1e-6)
    p_leaf = Predictor(booster, True, True, -1)
    np.testing.assert_array_equal(p_leaf.predict_matrix(x),
                                  booster.predict_leaf_index(x))
    mbooster, mx = _case("multiclass")
    mp = Predictor(mbooster, True, False, -1)
    np.testing.assert_allclose(mp.predict_matrix(mx),
                               mbooster.predict_multiclass(mx),
                               rtol=1e-5, atol=1e-6)


def test_gbdt_engine_cache_invalidates_on_new_trees():
    """serving_engine caches across calls but re-flattens once the model
    grows (continued training must not serve stale trees)."""
    booster, x = _case("binary")
    e1 = booster.serving_engine()
    assert booster.serving_engine() is e1
    booster.train_one_iter(is_eval=False)
    e2 = booster.serving_engine()
    assert e2 is not e1
    assert e2.flat.num_trees == e1.flat.num_trees + 1


def test_serving_config_options():
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.serving import engine_options_from_config
    from lightgbm_tpu.utils.log import LightGBMError
    cfg = OverallConfig()
    cfg.set({"predict_buckets": "64,8", "predict_quantize": "int8",
             "predict_algo": "scan", "predict_donate": "false"},
            require_data=False)
    assert cfg.io_config.predict_bucket_list() == (8, 64)
    opts = engine_options_from_config(cfg.io_config)
    assert opts == {"buckets": (8, 64), "quantize": "int8",
                    "donate": "false", "algo": "scan",
                    "shards": 0, "linger_us": 200, "queue": 4}
    cfg2 = OverallConfig()
    cfg2.set({"serve_shards": "2", "predict_linger_us": "1000",
              "predict_queue": "8"}, require_data=False)
    opts2 = engine_options_from_config(cfg2.io_config)
    assert (opts2["shards"], opts2["linger_us"], opts2["queue"]) \
        == (2, 1000, 8)
    for bad in ({"predict_quantize": "int4"}, {"predict_algo": "dfs"},
                {"predict_donate": "maybe"}, {"predict_buckets": "0,4"},
                {"predict_buckets": "a,b"}, {"serve_shards": "-1"},
                {"predict_linger_us": "-5"}, {"predict_queue": "0"},
                {"serve_shards": "2", "predict_algo": "scan"}):
        with pytest.raises(LightGBMError):
            OverallConfig().set(dict(bad), require_data=False)
