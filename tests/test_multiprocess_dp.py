"""True multi-PROCESS data-parallel training (the reference's N-machine
mode, data_parallel_tree_learner.cpp + linkers_socket.cpp).

Launches 2 OS processes, each with 4 virtual CPU devices, joined by
``jax.distributed.initialize`` into one 8-device job.  Each process loads
its own random row shard from the same CSV (dataset.cpp:172-216 semantics),
bin finding is distributed (feature slices + allgather), row-aligned state
is lifted to global mesh-sharded arrays (parallel/mesh.make_global_rows),
and the fused shard_map chunk program trains across both processes.

Asserts the reference's own invariant — every worker ends with the
IDENTICAL model — and serial equivalence of the distributed model.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Standard JAX multihost practice: the launcher bootstraps
# jax.distributed BEFORE anything touches the backend (the in-cli
# init_distributed then sees an initialized client and skips).  The
# platform is forced via jax.config.update — this environment's
# sitecustomize overrides the JAX_PLATFORMS env var.
WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
from lightgbm_tpu.parallel.mesh import init_distributed
init_distributed()
sys.argv = ["lightgbm_tpu"] + sys.argv[1:]
from lightgbm_tpu.cli import main
rc = main()
print("POST process_count:", jax.process_count(),
      "index:", jax.process_index(), "rc:", rc, flush=True)
sys.exit(rc)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# Multi-process collectives on the CPU backend are a jaxlib build
# capability: this container's jaxlib raises "Multiprocess computations
# aren't implemented on the CPU backend" from the very first allgather
# (sync_up_by_min), so every test below would fail on environment, not
# code.  Probe ONCE with a minimal 2-process job and skip-mark the module
# with the real reason — on a jaxlib with CPU collectives (or a TPU pod)
# the suite runs in full, so a code regression is still visible there.
_PROBE = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
from jax.experimental import multihost_utils
multihost_utils.process_allgather(np.asarray(1))
print("PROBE_OK", flush=True)
"""


def _probe_multiprocess_cpu():
    port = _free_port()
    env = dict(os.environ)
    env.pop("LGBM_TPU_COORDINATOR", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE, f"127.0.0.1:{port}", str(rank)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for rank in range(2)]
    try:
        outs = [p.communicate(timeout=120)[0] for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False, "2-process CPU collective probe timed out"
    if all(p.returncode == 0 and "PROBE_OK" in o
           for p, o in zip(procs, outs)):
        return True, ""
    reason = next((line.strip() for out in outs
                   for line in out.splitlines()
                   if "aren't implemented" in line
                   or "Error" in line), outs[0].strip()[-200:])
    return False, reason


_MP_OK, _MP_REASON = _probe_multiprocess_cpu()
pytestmark = pytest.mark.skipif(
    not _MP_OK,
    reason="multi-process collectives unavailable on this jaxlib CPU "
           "backend: %s" % _MP_REASON)


def _write_conf(path, data_csv, model_out, tree_learner, num_machines,
                grow_policy="depthwise", extra="", metric_freq=1000,
                num_iterations=8, objective="binary"):
    # hist_dtype=int8: quantization scales are pmax-synced across shards and
    # int32 accumulation is order-free, so the distributed histograms (and
    # therefore trees) are BIT-identical to serial — the strongest form of
    # the reference's every-worker-identical-model invariant.
    # dp_schedule is PINNED to psum: these tests assert exact tree
    # equality vs serial, which the ownership schedule does not promise
    # on near-tie data (an ulp in the owning shard's differently-compiled
    # search can flip a tie — see the lambdarank reduce_scatter
    # parametrization, which covers that schedule's multi-process path)
    with open(path, "w") as f:
        f.write(f"""task=train
data={data_csv}
objective={objective}
num_leaves=15
min_data_in_leaf=20
min_sum_hessian_in_leaf=1.0
num_iterations={num_iterations}
learning_rate=0.2
max_bin=32
metric_freq={metric_freq}
hist_dtype=int8
dp_schedule=psum
grow_policy={grow_policy}
tree_learner={tree_learner}
num_machines={num_machines}
output_model={model_out}
{extra}
""")


def _run(conf, extra_env=None, n_devices=4, timeout=900):
    env = dict(os.environ)
    env.pop("LGBM_TPU_COORDINATOR", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-c", WORKER, f"config={conf}"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _load_trees(model_path):
    from lightgbm_tpu.models.gbdt import GBDT
    return GBDT.from_model_file(model_path).models


def test_two_process_data_parallel_matches_serial(tmp_path):
    rng = np.random.RandomState(33)
    n, f = 1600, 8
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.2 * rng.randn(n)) > 0).astype(int)
    csv = str(tmp_path / "train.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.7g", delimiter=",")

    # ---- 2-process distributed run
    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"train_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "data", 2)
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=900)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert "POST process_count: 2" in out, (
            f"rank {rank} never joined the distributed job:\n{out[-3000:]}")

    # ---- serial baseline (same pipeline, one process)
    sconf = str(tmp_path / "train_serial.conf")
    _write_conf(sconf, csv, str(tmp_path / "model_serial.txt"), "serial", 1)
    sp = _run(sconf)
    sout, _ = sp.communicate(timeout=900)
    assert sp.returncode == 0, f"serial failed:\n{sout[-3000:]}"

    # reference invariant: every worker holds the identical model
    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    assert m0 == m1, "workers diverged"

    # distributed == serial trees: int8 histograms are bit-identical (see
    # _write_conf), so split decisions and leaf values must match exactly
    # (leaf values to f64-formatting noise of the text round-trip)
    trees_dp = _load_trees(str(tmp_path / "model_r0.txt"))
    trees_s = _load_trees(str(tmp_path / "model_serial.txt"))
    assert len(trees_dp) == len(trees_s) == 8
    for k, (td, ts) in enumerate(zip(trees_dp, trees_s)):
        assert td.num_leaves == ts.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(td.split_feature, ts.split_feature,
                                      err_msg=f"tree {k}")
        np.testing.assert_array_equal(td.threshold_bin, ts.threshold_bin,
                                      err_msg=f"tree {k}")
        np.testing.assert_allclose(td.leaf_value, ts.leaf_value,
                                   rtol=1e-6, atol=1e-8,
                                   err_msg=f"tree {k}")

    # the run actually exercised the distributed pieces
    assert "Finished train" in outs[0]


def test_two_process_bagging_workers_identical(tmp_path):
    """Multi-process bagging: each process bags its LOCAL shard (the
    reference's per-machine Bagging); the invariant is worker-identical
    models (trees are not serial-identical — the bagged subsets differ
    from a single-machine draw, as in the reference)."""
    rng = np.random.RandomState(7)
    n, f = 1600, 6
    x = rng.randn(n, f)
    y = ((x[:, 0] + 0.3 * rng.randn(n)) > 0).astype(int)
    csv = str(tmp_path / "train.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.7g", delimiter=",")

    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"train_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "data", 2,
                    extra="bagging_fraction=0.8\nbagging_freq=2\n"
                          "bagging_seed=9")
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert "POST process_count: 2" in out
    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    assert m0 == m1, "workers diverged under bagging"
    assert m0.count("Tree=") == 8


def _parse_metric_lines(out):
    """-> {(iteration, metric_name): [values]} from the CLI log."""
    import re
    vals = {}
    for m in re.finditer(
            r"Iteration:(\d+), (.+?) : ([-\d.e+ ]+)\n", out):
        it, name, nums = int(m.group(1)), m.group(2), m.group(3)
        vals[(it, name)] = [float(v) for v in nums.split()]
    return vals


def _gen_valid_run(tmp_path, grow_policy, num_iterations, early_stop):
    """Shared harness: 2-process DP with a validation set + metrics
    (+ optional early stopping) vs the identical serial run.  The
    reference's N-machine mode evaluates metrics/early-stop every
    iteration exactly like serial (application.cpp:119-199 loads valid
    data per machine, gbdt.cpp:225-259 evaluates each iteration)."""
    rng = np.random.RandomState(11)
    n, nv, f = 1600, 400, 8

    def make(n_):
        x = rng.randn(n_, f)
        y = ((x[:, 0] - 0.5 * x[:, 1] + 0.6 * rng.randn(n_)) > 0).astype(int)
        return np.column_stack([y, x])
    csv = str(tmp_path / "train.csv")
    vcsv = str(tmp_path / "valid.csv")
    np.savetxt(csv, make(n), fmt="%.7g", delimiter=",")
    np.savetxt(vcsv, make(nv), fmt="%.7g", delimiter=",")

    extra = (f"valid_data={vcsv}\nmetric=binary_logloss,auc\n"
             "is_training_metric=true\n")
    if early_stop:
        extra += "early_stopping_round=3\n"

    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"train_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "data", 2, grow_policy=grow_policy, extra=extra,
                    metric_freq=1, num_iterations=num_iterations)
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert "POST process_count: 2" in out

    sconf = str(tmp_path / "train_serial.conf")
    _write_conf(sconf, csv, str(tmp_path / "model_serial.txt"),
                "serial", 1, grow_policy=grow_policy, extra=extra,
                metric_freq=1, num_iterations=num_iterations)
    sp = _run(sconf)
    sout, _ = sp.communicate(timeout=900)
    assert sp.returncode == 0, f"serial failed:\n{sout[-4000:]}"
    return outs, sout


def test_two_process_dp_eval_early_stop_matches_serial(tmp_path):
    """Chunked multi-process DP with valid set + logloss/AUC + early
    stopping: metric trajectory and the early-stop decision must match the
    serial run (train metrics run on the gathered global score — the
    trajectory is the serial one, not a per-machine local value)."""
    outs, sout = _gen_valid_run(tmp_path, "depthwise",
                                num_iterations=30, early_stop=True)
    dp_vals = _parse_metric_lines(outs[0])
    s_vals = _parse_metric_lines(sout)
    assert dp_vals.keys() == s_vals.keys(), (
        f"metric trajectories diverge:\nDP:{sorted(dp_vals)}\n"
        f"serial:{sorted(s_vals)}")
    assert len(dp_vals) > 0
    for key in s_vals:
        np.testing.assert_allclose(
            dp_vals[key], s_vals[key], rtol=2e-5, atol=1e-7,
            err_msg=f"metric {key}")

    # identical early-stopping decision (or identical full-length run):
    # same tree count on every worker and serially
    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    ms = open(tmp_path / "model_serial.txt").read()
    assert m0 == m1, "workers diverged"
    assert m0.count("Tree=") == ms.count("Tree=")
    es_dp = [l for l in outs[0].splitlines() if "Early stopping" in l]
    es_s = [l for l in sout.splitlines() if "Early stopping" in l]
    assert es_dp == es_s


def test_two_process_dp_eval_leafwise_periter(tmp_path):
    """Leaf-wise multi-process DP runs the per-iteration path: training
    metrics evaluate host-side on the gathered global score and valid
    scores update via tree replay — trajectory must still match serial."""
    outs, sout = _gen_valid_run(tmp_path, "leafwise",
                                num_iterations=8, early_stop=False)
    dp_vals = _parse_metric_lines(outs[0])
    s_vals = _parse_metric_lines(sout)
    assert dp_vals.keys() == s_vals.keys()
    assert len(dp_vals) > 0
    for key in s_vals:
        np.testing.assert_allclose(
            dp_vals[key], s_vals[key], rtol=2e-5, atol=1e-7,
            err_msg=f"metric {key}")


@pytest.mark.parametrize("schedule,val_tol", [
    # psum: every shard dequantizes the identical full int histogram —
    # leaf values match serial to program-fusion ulps, every tree.
    # reduce_scatter (the auto default for true multi-process runs): the
    # owning shard's search is a differently-compiled program, so an
    # ulp-level gain difference can flip a near-tie split from tree 1 on
    # (this integer-featured ranking set is tie-dense) — tree 0 is still
    # asserted against serial, later trees via worker lockstep + quality
    ("psum", dict(rtol=1e-6, atol=1e-8)),
    ("reduce_scatter", dict(rtol=1e-3, atol=1e-6)),
])
def test_two_process_dp_lambdarank_matches_serial(tmp_path, schedule,
                                                  val_tol):
    """Distributed lambdarank (the reference's flagship parallel mode gap):
    query-atomic row sharding (dataset.cpp:189-206) + per-query tables
    rebuilt in padded-global coordinates (LambdarankNDCG.globalize_layout)
    + gathered-score lambdas in the DP chunk.  Trees must be identical on
    every worker AND match the serial run (int8 histograms are bit-exact
    across shardings); the NDCG trajectory must match serial."""
    ex = "/root/reference/examples/lambdarank"
    import shutil
    for f in ["rank.train", "rank.train.query", "rank.test",
              "rank.test.query"]:
        shutil.copy(os.path.join(ex, f), tmp_path / f)
    train = str(tmp_path / "rank.train")
    test = str(tmp_path / "rank.test")
    # row weights: exercises the padded-global weight scatter
    # (globalize_layout's w[pad_pos]) and the weighted-lambda path
    nrows = sum(1 for _ in open(train))
    wrng = np.random.RandomState(3)
    np.savetxt(str(tmp_path / "rank.train.weight"),
               (0.5 + wrng.rand(nrows)).astype(np.float32), fmt="%.5f")

    extra = (f"objective=lambdarank\nvalid_data={test}\nmetric=ndcg\n"
             "is_training_metric=true\nndcg_at=1,3,5\n")

    def conf_for(path, model, learner, machines):
        # _write_conf hardcodes objective=binary; write a rank conf directly
        with open(path, "w") as f:
            f.write(f"""task=train
data={train}
num_leaves=15
min_data_in_leaf=10
min_sum_hessian_in_leaf=0.001
num_iterations=8
learning_rate=0.1
max_bin=32
metric_freq=1
hist_dtype=int8
dp_schedule={schedule}
grow_policy=depthwise
tree_learner={learner}
num_machines={machines}
output_model={model}
{extra}
""")

    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"rank_r{rank}.conf")
        conf_for(conf, str(tmp_path / f"model_r{rank}.txt"), "data", 2)
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert "POST process_count: 2" in out

    sconf = str(tmp_path / "rank_serial.conf")
    conf_for(sconf, str(tmp_path / "model_serial.txt"), "serial", 1)
    sp = _run(sconf)
    sout, _ = sp.communicate(timeout=900)
    assert sp.returncode == 0, f"serial failed:\n{sout[-4000:]}"

    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    assert m0 == m1, "workers diverged"

    trees_dp = _load_trees(str(tmp_path / "model_r0.txt"))
    trees_s = _load_trees(str(tmp_path / "model_serial.txt"))
    assert len(trees_dp) == len(trees_s) == 8
    # psum: every tree matches serial.  reduce_scatter: an ulp-level
    # tie-flip in the owning shard's differently-compiled search can
    # legitimately change a later tree's structure (the score cascade
    # makes everything after the first flip diverge) — but tree 0 sees
    # identical gradients, so it MUST still match, which is what catches
    # a garbage-tree regression
    ntrees_checked = 8 if schedule == "psum" else 1
    for k in range(ntrees_checked):
        td, ts = trees_dp[k], trees_s[k]
        assert td.num_leaves == ts.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(td.split_feature, ts.split_feature,
                                      err_msg=f"tree {k}")
        np.testing.assert_array_equal(td.threshold_bin, ts.threshold_bin,
                                      err_msg=f"tree {k}")
        np.testing.assert_allclose(td.leaf_value, ts.leaf_value,
                                   err_msg=f"tree {k}", **val_tol)

    dp_vals = _parse_metric_lines(outs[0])
    s_vals = _parse_metric_lines(sout)
    assert dp_vals.keys() == s_vals.keys()
    assert len(dp_vals) > 0
    # NDCG trajectory: psum matches serial to reduction ulps; under
    # reduce_scatter this integer-featured ranking set is near-tie-dense
    # and the owning shard's differently-compiled gain can flip a tie by
    # an ulp — a genuinely (equivalently-scoring) different tree, exactly
    # as the reference's own parallel mode diverges from ITS serial on
    # ties.  The guaranteed invariant is worker lockstep (m0 == m1,
    # asserted above) + serial-equivalent QUALITY
    mtol = (dict(rtol=2e-5, atol=1e-7) if schedule == "psum"
            else dict(rtol=2e-2, atol=2e-3))
    for key in s_vals:
        np.testing.assert_allclose(
            dp_vals[key], s_vals[key], err_msg=f"metric {key}", **mtol)


def test_two_process_feature_parallel_matches_serial(tmp_path):
    """Multi-process FEATURE parallel (feature_parallel_tree_learner.cpp
    on N machines): every process loads the FULL rows (the reference sets
    is_parallel_find_bin=false for FP — io/config.cpp:164-172) and the
    replicated-rows fused chunk runs over the global mesh.  Each feature's
    histogram is built by exactly one owner from the full rows, so trees
    must be identical on every worker AND identical to serial."""
    rng = np.random.RandomState(41)
    n, f = 1600, 8
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.6 * rng.randn(n)) > 0).astype(int)
    csv = str(tmp_path / "train.csv")
    vcsv = str(tmp_path / "valid.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.7g", delimiter=",")
    xv = rng.randn(400, f)
    yv = ((xv[:, 0] - 0.5 * xv[:, 1] + 0.6 * rng.randn(400)) > 0).astype(int)
    np.savetxt(vcsv, np.column_stack([yv, xv]), fmt="%.7g", delimiter=",")
    extra = (f"valid_data={vcsv}\nmetric=binary_logloss,auc\n"
             "is_training_metric=true\n")

    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"train_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "feature", 2, extra=extra, metric_freq=1)
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert "POST process_count: 2" in out

    sconf = str(tmp_path / "train_serial.conf")
    _write_conf(sconf, csv, str(tmp_path / "model_serial.txt"),
                "serial", 1, extra=extra, metric_freq=1)
    sp = _run(sconf)
    sout, _ = sp.communicate(timeout=900)
    assert sp.returncode == 0, f"serial failed:\n{sout[-4000:]}"

    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    assert m0 == m1, "workers diverged"
    trees_fp = _load_trees(str(tmp_path / "model_r0.txt"))
    trees_s = _load_trees(str(tmp_path / "model_serial.txt"))
    assert len(trees_fp) == len(trees_s) == 8
    for k, (td, ts) in enumerate(zip(trees_fp, trees_s)):
        assert td.num_leaves == ts.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(td.split_feature, ts.split_feature,
                                      err_msg=f"tree {k}")
        np.testing.assert_array_equal(td.threshold_bin, ts.threshold_bin,
                                      err_msg=f"tree {k}")
    dp_vals = _parse_metric_lines(outs[0])
    s_vals = _parse_metric_lines(sout)
    assert dp_vals.keys() == s_vals.keys() and len(dp_vals) > 0
    for key in s_vals:
        np.testing.assert_allclose(dp_vals[key], s_vals[key],
                                   rtol=2e-5, atol=1e-7,
                                   err_msg=f"metric {key}")


def test_two_process_feature_parallel_leafwise_fails_loudly(tmp_path):
    """Leaf-wise FP multi-process is unsupported — it must log.fatal with
    a clear message at init, not mis-train or fail obscurely."""
    rng = np.random.RandomState(5)
    n, f = 400, 4
    x = rng.randn(n, f)
    y = (x[:, 0] > 0).astype(int)
    csv = str(tmp_path / "train.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.7g", delimiter=",")

    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"train_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "feature", 2, grow_policy="leafwise")
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode != 0, f"rank {rank} unexpectedly succeeded"
        assert "multi-process feature-parallel training requires" in out


def test_two_process_dp_multiclass_matches_serial(tmp_path):
    """Multi-process DP multiclass (k trees per iteration interleaved,
    gbdt.cpp:175-195): worker-identical AND serial-identical trees under
    int8, with multi_logloss evaluated on the gathered global score."""
    rng = np.random.RandomState(13)
    n, f, k = 1500, 6, 3
    x = rng.randn(n, f)
    y = (x[:, 0] + 0.5 * rng.randn(n) > 0.5).astype(int) + \
        (x[:, 1] + 0.5 * rng.randn(n) > 0).astype(int)
    csv = str(tmp_path / "train.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.7g", delimiter=",")
    extra = (f"num_class={k}\nmetric=multi_logloss\n"
             "is_training_metric=true\n")

    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"train_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "data", 2, extra=extra, metric_freq=1,
                    objective="multiclass")
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert "POST process_count: 2" in out

    sconf = str(tmp_path / "train_serial.conf")
    _write_conf(sconf, csv, str(tmp_path / "model_serial.txt"),
                "serial", 1, extra=extra, metric_freq=1,
                objective="multiclass")
    sp = _run(sconf)
    sout, _ = sp.communicate(timeout=900)
    assert sp.returncode == 0, f"serial failed:\n{sout[-4000:]}"

    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    assert m0 == m1, "workers diverged"
    trees_dp = _load_trees(str(tmp_path / "model_r0.txt"))
    trees_s = _load_trees(str(tmp_path / "model_serial.txt"))
    assert len(trees_dp) == len(trees_s) == 8 * k
    for i, (td, ts) in enumerate(zip(trees_dp, trees_s)):
        np.testing.assert_array_equal(td.split_feature, ts.split_feature,
                                      err_msg=f"tree {i}")
        np.testing.assert_array_equal(td.threshold_bin, ts.threshold_bin,
                                      err_msg=f"tree {i}")
    dp_vals = _parse_metric_lines(outs[0])
    s_vals = _parse_metric_lines(sout)
    assert dp_vals.keys() == s_vals.keys() and len(dp_vals) > 0
    for key in s_vals:
        np.testing.assert_allclose(dp_vals[key], s_vals[key],
                                   rtol=2e-5, atol=1e-7,
                                   err_msg=f"metric {key}")


def test_two_process_dp_weighted_regression_matches_serial(tmp_path):
    """Multi-process DP L2 regression with row weights (a .weight side
    file, sharded with the rows): worker-identical, serial-identical
    trees; weighted l2 metric trajectory equal to serial."""
    rng = np.random.RandomState(29)
    n, f = 1600, 6
    x = rng.randn(n, f)
    y = x[:, 0] * 2.0 - x[:, 1] + 0.3 * rng.randn(n)
    csv = str(tmp_path / "train.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.7g", delimiter=",")
    np.savetxt(csv + ".weight", (0.5 + rng.rand(n)).astype(np.float32),
               fmt="%.5f")
    extra = "metric=l2\nis_training_metric=true\n"

    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"train_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "data", 2, extra=extra, metric_freq=1,
                    objective="regression")
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert "POST process_count: 2" in out

    sconf = str(tmp_path / "train_serial.conf")
    _write_conf(sconf, csv, str(tmp_path / "model_serial.txt"),
                "serial", 1, extra=extra, metric_freq=1,
                objective="regression")
    sp = _run(sconf)
    sout, _ = sp.communicate(timeout=900)
    assert sp.returncode == 0, f"serial failed:\n{sout[-4000:]}"

    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    assert m0 == m1, "workers diverged"
    trees_dp = _load_trees(str(tmp_path / "model_r0.txt"))
    trees_s = _load_trees(str(tmp_path / "model_serial.txt"))
    assert len(trees_dp) == len(trees_s) == 8
    for i, (td, ts) in enumerate(zip(trees_dp, trees_s)):
        np.testing.assert_array_equal(td.split_feature, ts.split_feature,
                                      err_msg=f"tree {i}")
        np.testing.assert_array_equal(td.threshold_bin, ts.threshold_bin,
                                      err_msg=f"tree {i}")
    dp_vals = _parse_metric_lines(outs[0])
    s_vals = _parse_metric_lines(sout)
    assert dp_vals.keys() == s_vals.keys() and len(dp_vals) > 0
    for key in s_vals:
        np.testing.assert_allclose(dp_vals[key], s_vals[key],
                                   rtol=2e-5, atol=1e-7,
                                   err_msg=f"metric {key}")


def test_two_process_dp_continued_training_from_reference_model(
        tmp_path, reference_binary):
    """Continued training (``input_model``) under TRUE multi-process data
    parallelism, seeded by a REFERENCE-WRITTEN model file — the
    reference's own N-machine continued-training shape
    (application.cpp:119-131 loading input_model + dataset.cpp:546-581
    init scores): 2-OS-process DP continued run must stay in worker
    lockstep and reproduce the serial continued run exactly (int8 +
    psum)."""
    rng = np.random.RandomState(44)
    n, f = 1600, 8
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.2 * rng.randn(n)) > 0).astype(int)
    csv = str(tmp_path / "train.csv")
    np.savetxt(csv, np.column_stack([y, x]), fmt="%.7g", delimiter=",")

    # 1) the reference binary trains the base model (3 trees)
    base_model = str(tmp_path / "ref_base_model.txt")
    with open(tmp_path / "ref_base.conf", "w") as fh:
        fh.write(f"""task=train
data={csv}
objective=binary
num_trees=3
num_leaves=15
min_data_in_leaf=20
min_sum_hessian_in_leaf=1.0
learning_rate=0.2
max_bin=32
output_model={base_model}
""")
    subprocess.run([reference_binary,
                    f"config={tmp_path / 'ref_base.conf'}"],
                   check=True, capture_output=True, text=True)
    assert os.path.exists(base_model)

    # 2) serial continued run: +5 trees on top of the reference model
    extra = f"input_model={base_model}\n"
    sconf = str(tmp_path / "cont_serial.conf")
    _write_conf(sconf, csv, str(tmp_path / "model_serial.txt"), "serial",
                1, num_iterations=5, extra=extra)
    sp = _run(sconf)
    sout, _ = sp.communicate(timeout=900)
    assert sp.returncode == 0, f"serial failed:\n{sout[-4000:]}"

    # 3) 2-process DP continued run, same input model
    port = _free_port()
    procs = []
    for rank in range(2):
        conf = str(tmp_path / f"cont_r{rank}.conf")
        _write_conf(conf, csv, str(tmp_path / f"model_r{rank}.txt"),
                    "data", 2, num_iterations=5, extra=extra)
        procs.append(_run(conf, extra_env={
            "LGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LGBM_TPU_NUM_PROCS": "2",
            "LGBM_TPU_PROC_ID": str(rank),
        }))
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert "POST process_count: 2" in out

    m0 = open(tmp_path / "model_r0.txt").read()
    m1 = open(tmp_path / "model_r1.txt").read()
    assert m0 == m1, "workers diverged"

    trees_dp = _load_trees(str(tmp_path / "model_r0.txt"))
    trees_s = _load_trees(str(tmp_path / "model_serial.txt"))
    # 3 reference trees carried over + 5 continued
    assert len(trees_dp) == len(trees_s) == 8
    for k, (td, ts) in enumerate(zip(trees_dp, trees_s)):
        assert td.num_leaves == ts.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(td.split_feature, ts.split_feature,
                                      err_msg=f"tree {k}")
        np.testing.assert_array_equal(td.threshold_bin, ts.threshold_bin,
                                      err_msg=f"tree {k}")
        np.testing.assert_allclose(td.leaf_value, ts.leaf_value,
                                   rtol=1e-6, atol=1e-8,
                                   err_msg=f"tree {k}")
