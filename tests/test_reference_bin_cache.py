"""Reference binary-cache compatibility (io/dataset.py
_load_reference_binary vs Dataset::SaveBinaryFile, dataset.cpp:653-713).

The compiled reference writes `<data>.bin` with is_save_binary_file=true;
a user switching to lightgbm_tpu keeps those caches.  These differential
tests have the reference binary write a cache and assert our loader
reproduces the dataset we build from the text file ourselves (same
FindBin port, all rows sampled at this size), including the sparse-bin
delta stream and trivial-feature dropping, and that training can run
from the cache with the text file gone.

Tolerance note: the reference parses floats with a hand-rolled Atof
(/root/reference/src/io/parser.hpp via common.h) that differs from
strtod by ~1 ulp on a quarter of values, so cache-borne bin bounds
differ from our strtod-exact text parse by ulps, and rows whose value
sits within an ulp of a boundary may land one bin over.  The cache is
AUTHORITATIVE for what the reference uses — the asserts below allow
exactly (and only) that ulp story.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

from lightgbm_tpu.config import IOConfig
from lightgbm_tpu.io.dataset import Dataset


def _write_synthetic(path, n=1200, seed=3):
    """Label + dense feature + 95%-zero feature (sparse bin in the
    reference) + NONZERO constant feature (trivial → dropped from used
    features but still counted in num_total_features; an all-zero column
    would be zero-dropped by the reference's parser and never counted)."""
    rng = np.random.RandomState(seed)
    dense = rng.randn(n)
    sparse = np.where(rng.rand(n) < 0.95, 0.0, rng.rand(n) * 4 + 1)
    const = np.full(n, 7.0)
    y = (dense + sparse * 0.3 + rng.randn(n) * 0.3 > 0).astype(int)
    cols = np.column_stack([y, dense, sparse, const])
    np.savetxt(path, cols, delimiter="\t",
               fmt=["%d", "%.10g", "%.10g", "%.10g"])


def _reference_save_bin(reference_binary, workdir, data_name):
    res = subprocess.run(
        [reference_binary, "task=train", f"data={data_name}",
         "objective=binary", "num_trees=1", "num_leaves=4",
         "min_data_in_leaf=5", "is_save_binary_file=true",
         "output_model=ref_model.txt"],
        cwd=workdir, capture_output=True, text=True)
    assert res.returncode == 0, res.stderr + res.stdout
    bin_path = os.path.join(workdir, data_name + ".bin")
    assert os.path.exists(bin_path)
    return bin_path


@pytest.fixture(scope="module")
def synth_dir(reference_binary, tmp_path_factory):
    d = tmp_path_factory.mktemp("refbin")
    _write_synthetic(str(d / "synth.tsv"))
    _reference_save_bin(reference_binary, str(d), "synth.tsv")
    return d


def test_reference_bin_loads_identical_dataset(synth_dir):
    text_dir = synth_dir / "text_only"
    text_dir.mkdir(exist_ok=True)
    shutil.copy(synth_dir / "synth.tsv", text_dir / "synth.tsv")

    from_text = Dataset.load_train(
        IOConfig(data_filename=str(text_dir / "synth.tsv")))
    from_bin = Dataset.load_train(
        IOConfig(data_filename=str(synth_dir / "synth.tsv")))

    # trivial constant feature dropped by both; mapping identical
    assert from_bin.num_features == from_text.num_features == 2
    assert from_bin.used_feature_map == from_text.used_feature_map
    assert from_bin.num_total_features == from_text.num_total_features
    np.testing.assert_array_equal(from_bin.num_bins, from_text.num_bins)
    for mb, mt in zip(from_bin.bin_mappers, from_text.bin_mappers):
        assert mb.num_bin == mt.num_bin
        np.testing.assert_allclose(mb.bin_upper_bound, mt.bin_upper_bound,
                                   rtol=1e-13)     # Atof-vs-strtod ulps
    np.testing.assert_array_equal(np.asarray(from_bin.metadata.label),
                                  np.asarray(from_text.metadata.label))
    # dense feature: bins equal up to boundary-ulp flips (|Δ| <= 1, rare).
    # sparse feature: the reference stores only bins above default_bin and
    # reads absent rows as bin 0 (sparse_bin.hpp Push /
    # SparseBinIterator::Get) — assert exactly that
    _assert_bins_match_to_boundary_ulp(from_bin.bins[0], from_text.bins[0])
    sp_bin, sp_text = from_bin.bins[1], from_text.bins[1]
    default_bin = from_text.bin_mappers[1].default_bin
    stored = sp_text > default_bin
    _assert_bins_match_to_boundary_ulp(sp_bin[stored], sp_text[stored])
    assert (sp_bin[~stored] == 0).all()


def _assert_bins_match_to_boundary_ulp(got, want, max_flip_frac=1e-3):
    got = np.asarray(got, np.int64)
    want = np.asarray(want, np.int64)
    flips = got != want
    assert np.abs(got - want)[flips].max(initial=0) <= 1
    assert flips.mean() <= max_flip_frac, flips.mean()


def test_train_from_reference_bin_without_text(synth_dir, tmp_path):
    """The cache alone must be enough to train (text file gone)."""
    shutil.copy(synth_dir / "synth.tsv.bin", tmp_path / "synth.tsv.bin")
    env = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
    res = subprocess.run(
        ["python", "-m", "lightgbm_tpu", "task=train", "data=synth.tsv",
         "objective=binary", "num_trees=2", "num_leaves=4",
         "min_data_in_leaf=5", "output_model=model.txt"],
        cwd=str(tmp_path), capture_output=True, text=True, env=env,
        timeout=600)
    assert res.returncode == 0, res.stderr + res.stdout
    assert (tmp_path / "model.txt").exists()
    assert "reference-format binary" in res.stdout + res.stderr


def test_reference_example_bin_cache(reference_binary, tmp_path):
    """The reference's own binary_classification example round-trips
    through its cache into our loader (7000 rows, 28 features, weights).

    The cache is compared against the TEXT VALUES binned with the CACHE'S
    OWN mappers — not against our text-load mappers: on a few features the
    reference's SortForPair defect (common.h:362-381, see
    tests/test_binning.py and PARITY.md) makes ITS stored bounds differ
    from the intended equal-frequency algorithm we implement, and the
    loader's job is to reproduce faithfully what the reference stored."""
    src = "/root/reference/examples/binary_classification"
    if not os.path.isdir(src):
        pytest.skip("reference examples not available")
    for f in ("binary.train", "binary.train.weight"):
        shutil.copy(os.path.join(src, f), tmp_path / f)
    _reference_save_bin(reference_binary, str(tmp_path), "binary.train")

    text_dir = tmp_path / "text_only"
    text_dir.mkdir()
    for f in ("binary.train", "binary.train.weight"):
        shutil.copy(os.path.join(src, f), text_dir / f)

    from_text = Dataset.load_train(
        IOConfig(data_filename=str(text_dir / "binary.train")))
    from_bin = Dataset.load_train(
        IOConfig(data_filename=str(tmp_path / "binary.train")))
    assert from_bin.num_features == from_text.num_features
    np.testing.assert_array_equal(from_bin.num_bins, from_text.num_bins)
    np.testing.assert_array_equal(np.asarray(from_bin.metadata.label),
                                  np.asarray(from_text.metadata.label))
    np.testing.assert_allclose(np.asarray(from_bin.metadata.weights),
                               np.asarray(from_text.metadata.weights),
                               rtol=1e-6)
    # most features don't hit the remainder-sort defect: their cache
    # bounds equal our intended-algorithm bounds to Atof-vs-strtod ulps
    agree = sum(
        int(np.allclose(mb.bin_upper_bound, mt.bin_upper_bound, rtol=1e-13))
        for mb, mt in zip(from_bin.bin_mappers, from_text.bin_mappers))
    assert agree >= from_text.num_features * 2 // 3, agree

    # faithfulness: re-binning the raw text values with the CACHE's
    # mappers reproduces the cache's bin matrix (boundary-ulp flips from
    # the reference's Atof aside); sparse-stored features additionally
    # zero out at-or-below-default bins (sparse_bin.hpp Push/Get).  The
    # oracle is the REFERENCE'S ValueToBin binary search (bin.h:296-309)
    # — on the defect-bearing features the stored bounds are
    # NON-monotonic (stale SortForPair tail, e.g. an inf mid-array) and
    # np.searchsorted would disagree with the reference's own search
    raw = np.loadtxt(tmp_path / "binary.train")
    values = np.delete(raw, from_bin.label_idx, axis=1)
    for j, real in enumerate(from_bin.real_feature_idx):
        m = from_bin.bin_mappers[j]
        expect = _reference_value_to_bin(m.bin_upper_bound,
                                         values[:, real])
        got = from_bin.bins[j].astype(np.int64)
        default_bin = int(_reference_value_to_bin(m.bin_upper_bound,
                                                  np.zeros(1))[0])
        stored = expect > default_bin
        if (got[~stored] == 0).all():
            _assert_bins_match_to_boundary_ulp(got[stored], expect[stored])
        else:
            _assert_bins_match_to_boundary_ulp(got, expect)


def test_reference_rank_bin_cache_queries(reference_binary, tmp_path):
    """A lambdarank cache carries query boundaries; they must round-trip
    (metadata.cpp:335-350 — NOTE the reference's own LoadFromMemory
    mis-advances past the label block when weights are absent,
    metadata.cpp:313, so the reference itself garbles this cache; we
    parse what SaveBinaryToFile wrote)."""
    src = "/root/reference/examples/lambdarank"
    if not os.path.isdir(src):
        pytest.skip("reference examples not available")
    for f in ("rank.train", "rank.train.query"):
        shutil.copy(os.path.join(src, f), tmp_path / f)
    res = subprocess.run(
        [reference_binary, "task=train", "data=rank.train",
         "objective=lambdarank", "num_trees=1", "num_leaves=4",
         "min_data_in_leaf=5", "is_save_binary_file=true",
         "output_model=ref_model.txt"],
        cwd=str(tmp_path), capture_output=True, text=True)
    assert res.returncode == 0, res.stderr + res.stdout

    text_dir = tmp_path / "text_only"
    text_dir.mkdir()
    for f in ("rank.train", "rank.train.query"):
        shutil.copy(os.path.join(src, f), text_dir / f)
    from_text = Dataset.load_train(
        IOConfig(data_filename=str(text_dir / "rank.train")))
    from_bin = Dataset.load_train(
        IOConfig(data_filename=str(tmp_path / "rank.train")))
    np.testing.assert_array_equal(
        np.asarray(from_bin.metadata.query_boundaries),
        np.asarray(from_text.metadata.query_boundaries))
    np.testing.assert_array_equal(np.asarray(from_bin.metadata.label),
                                  np.asarray(from_text.metadata.label))


def _reference_value_to_bin(upper, values):
    """BinMapper::ValueToBin (bin.h:296-309), vectorized verbatim — the
    loop is deterministic even on non-monotonic (defective) bounds,
    where a conventional sorted search would differ."""
    values = np.asarray(values, np.float64)
    l = np.zeros(values.shape, np.int64)
    r = np.full(values.shape, len(upper) - 1, np.int64)
    active = l < r
    while active.any():
        m = (r + l - 1) // 2
        le = values <= upper[np.clip(m, 0, len(upper) - 1)]
        r = np.where(active & le, m, r)
        l = np.where(active & ~le, m + 1, l)
        active = l < r
    return l


def test_reference_bin_multimachine_reshard(synth_dir, tmp_path):
    """Distributed loading from a reference cache: every row lands on
    exactly one machine (dataset.cpp:840-872 re-shard semantics, same
    seeded assignment as our own cache loader), and each shard's
    metadata/bins stay row-aligned.  The cache sits in a directory
    WITHOUT the text file, so the silent re-bin fallback cannot mask a
    parser regression — these loads either parse the reference format or
    fatal."""
    shutil.copy(synth_dir / "synth.tsv.bin", tmp_path / "synth.tsv.bin")
    full = Dataset.load_train(
        IOConfig(data_filename=str(tmp_path / "synth.tsv")))
    M = 4
    shards = [Dataset.load_train(
        IOConfig(data_filename=str(tmp_path / "synth.tsv")),
        rank=r, num_machines=M) for r in range(M)]
    assert sum(s.num_data for s in shards) == full.num_data
    for s in shards:
        assert s.bins.shape == (s.num_features, s.num_data)
        assert s.metadata.label.shape == (s.num_data,)
        assert s.global_num_data == full.num_data
    # same seed => same assignment across loads; shard labels partition
    # the full label multiset
    all_labels = np.sort(np.concatenate(
        [np.asarray(s.metadata.label) for s in shards]))
    np.testing.assert_array_equal(all_labels,
                                  np.sort(np.asarray(full.metadata.label)))
    # pre-partition mode loads everything everywhere
    pre = Dataset.load_train(
        IOConfig(data_filename=str(tmp_path / "synth.tsv"),
                 is_pre_partition=True),
        rank=1, num_machines=M)
    assert pre.num_data == full.num_data


# ---------------------------------------------------------------- write side


def test_write_side_reference_bin_roundtrip(tmp_path):
    """save_binary_reference -> our own reference-format reader: the
    written cache must reproduce the dataset bit for bit (mappers, bin
    matrix, metadata) — the write-side twin of the read-side tests."""
    rng = np.random.RandomState(11)
    n = 900
    x = np.column_stack([rng.randn(n), rng.rand(n) * 5,
                         np.where(rng.rand(n) < 0.9, 0.0, 1.0 + rng.rand(n))])
    y = (x[:, 0] > 0).astype(np.float32)
    w = (0.5 + rng.rand(n)).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32, weights=w)
    ds.feature_names = ["f%d" % i for i in range(3)]
    path = str(tmp_path / "ours.bin")
    ds.save_binary_reference(path)

    back = Dataset()
    back._load_reference_binary(path, 0, 1, False)
    assert back.num_data == ds.num_data
    assert back.num_features == ds.num_features
    assert back.used_feature_map == ds.used_feature_map
    np.testing.assert_array_equal(back.bins, ds.bins)
    for m1, m2 in zip(back.bin_mappers, ds.bin_mappers):
        assert m1.num_bin == m2.num_bin
        np.testing.assert_array_equal(m1.bin_upper_bound,
                                      m2.bin_upper_bound)
    np.testing.assert_array_equal(back.metadata.label, ds.metadata.label)
    np.testing.assert_array_equal(back.metadata.weights,
                                  ds.metadata.weights)


def test_reference_binary_trains_from_our_cache(reference_binary, tmp_path):
    """The reference binary trains DIRECTLY from a cache we wrote
    (VERDICT r4 missing #3): `<data>.bin` written by
    save_binary_reference, text file absent in the run directory — the
    model must equal the reference's own text-trained model on the same
    data (same bins: the reference loads OUR mappers/columns from the
    cache, and bin boundaries agree by the FindBin parity the read-side
    tests pin)."""
    rng = np.random.RandomState(5)
    n = 1500
    x = np.column_stack([rng.randn(n), rng.randn(n) * 2 + 1,
                         rng.rand(n) * 9])
    y = ((x[:, 0] - 0.4 * x[:, 1] + 0.3 * rng.randn(n)) > 0).astype(int)

    # reference trains from TEXT (its own parse + binning)
    text_dir = tmp_path / "from_text"
    text_dir.mkdir()
    np.savetxt(str(text_dir / "d.tsv"), np.column_stack([y, x]),
               delimiter="\t", fmt="%.6g")
    res = subprocess.run(
        [reference_binary, "task=train", "data=d.tsv", "objective=binary",
         "num_trees=4", "num_leaves=8", "min_data_in_leaf=20",
         "max_bin=32", "output_model=model_text.txt"],
        cwd=str(text_dir), capture_output=True, text=True)
    assert res.returncode == 0, res.stderr + res.stdout

    # reference trains from OUR reference-format cache, no text file
    cache_dir = tmp_path / "from_cache"
    cache_dir.mkdir()
    ds = Dataset.load_train(
        IOConfig(data_filename=str(text_dir / "d.tsv"), max_bin=32))
    ds.save_binary_reference(str(cache_dir / "d.tsv.bin"))
    res2 = subprocess.run(
        [reference_binary, "task=train", "data=d.tsv", "objective=binary",
         "num_trees=4", "num_leaves=8", "min_data_in_leaf=20",
         "max_bin=32", "output_model=model_cache.txt"],
        cwd=str(cache_dir), capture_output=True, text=True)
    assert res2.returncode == 0, res2.stderr + res2.stdout
    assert not os.path.exists(cache_dir / "d.tsv"), "text file must be absent"

    # the models must agree line for line, EXCEPT threshold real values,
    # which carry the module-docstring ulp story: our cache holds
    # strtod-exact bin bounds while the text path re-parses with the
    # reference's hand-rolled Atof (~1 ulp apart on a quarter of values)
    a = open(text_dir / "model_text.txt").read().splitlines()
    b = open(cache_dir / "model_cache.txt").read().splitlines()
    assert len(a) == len(b)
    for la, lb in zip(a, b):
        if la.startswith("threshold="):
            va = np.array([float(v) for v in la.split("=")[1].split()])
            vb = np.array([float(v) for v in lb.split("=")[1].split()])
            np.testing.assert_allclose(va, vb, rtol=1e-5, atol=1e-9)
        elif not la.startswith("feature_names"):
            assert la == lb, (la, lb)
