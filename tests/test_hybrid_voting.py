"""Differential pins for the 2-D mesh learners (ISSUE 9):
serial ≡ data ≡ hybrid ≡ voting, every growth policy, per-iteration AND
fused-chunk paths, on the virtual 8-device CPU mesh.

The repo's standing equivalence bar (tests/test_parallel.py):

- **int8** histograms: the int-domain accumulators are order-free
  (pmax-synced scales, int32 sums), so parallel trees are BIT-identical
  to serial — pinned exactly here for hybrid and voting, all three
  growth policies, both dispatch paths.
- **f32** histograms: reductions run in a different order (single-device
  sum vs psum of partials), so near-tied splits may legitimately resolve
  differently; equivalence is tie-keyed (identical splits up to genuine
  near-ties, values within reduction noise).

Voting exactness: the voted set covers the true best feature whenever
2·top_k >= the owned block width (the schedule then degenerates to a
full exchange of the block) — these pins run in that regime, so voting
is held to the same bar as hybrid, not just the PV-tree approximation
argument.
"""
import numpy as np
import jax
import pytest

from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel import create_parallel_learner
from lightgbm_tpu.parallel.mesh import factor_machines

from test_parallel import _assert_equivalent_to_serial


# (grow_policy, leafwise_compact) cells of the policy matrix
POLICIES = [("leafwise", "false"), ("leafwise", "true"),
            ("depthwise", "false")]


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(11)
    n, f = 1200, 10
    x = rng.randn(n, f)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.2 * rng.randn(n)) > 0).astype(
        np.float32)
    return x, y


def _make(tl, nm, x, y, extra=None):
    cfg = OverallConfig()
    # num_leaves=7: depthwise programs trace per level (3 levels vs 4 at
    # 15 leaves) and every cell compiles fresh shard_map programs on the
    # 8-device CPU platform — the bit-identity claims are leaf-count-
    # independent, so the smallest non-trivial tree keeps tier-1 time down
    p = {"objective": "binary", "num_leaves": "7",
         "min_data_in_leaf": "20", "min_sum_hessian_in_leaf": "1.0",
         "learning_rate": "0.2", "tree_learner": tl,
         "num_machines": str(nm)}
    p.update(extra or {})
    cfg.set(p, require_data=False)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    b = GBDT()
    learner = None if tl == "serial" else create_parallel_learner(cfg)
    b.init(cfg.boosting_config, ds,
           create_objective(cfg.objective_type, cfg.objective_config),
           learner=learner)
    return b


def _train(tl, nm, x, y, extra=None, iters=3):
    b = _make(tl, nm, x, y, extra)
    for _ in range(iters):
        if b.train_one_iter(is_eval=False):
            break
    return b


_SERIAL_CACHE: dict = {}


def _serial(x, y, base):
    """Serial oracle boosters, trained once per (policy, compact,
    hist_dtype) for the whole module — every equivalence cell compares
    against the same 3-iteration serial run."""
    key = tuple(sorted(base.items()))
    if key not in _SERIAL_CACHE:
        _SERIAL_CACHE[key] = _train("serial", 1, x, y, base)
    return _SERIAL_CACHE[key]


def _assert_bit_identical(a, b, what):
    assert len(a.models) == len(b.models), what
    for k, (t1, t2) in enumerate(zip(a.models, b.models)):
        assert t1.num_leaves == t2.num_leaves, f"{what} tree {k}"
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature,
                                      err_msg=f"{what} tree {k}")
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin,
                                      err_msg=f"{what} tree {k}")
        np.testing.assert_array_equal(np.asarray(t1.leaf_value),
                                      np.asarray(t2.leaf_value),
                                      err_msg=f"{what} tree {k}")
    np.testing.assert_array_equal(np.asarray(a.score), np.asarray(b.score),
                                  err_msg=what)


def test_factor_machines():
    assert factor_machines(4) == (2, 2)
    assert factor_machines(8) == (4, 2)
    assert factor_machines(6) == (3, 2)
    assert factor_machines(7) == (7, 1)          # primes: pure DP
    assert factor_machines(8, feature_shards=4) == (2, 4)
    assert factor_machines(4, voting=True) == (4, 1)
    assert factor_machines(4, feature_shards=2, voting=True) == (2, 2)
    with pytest.raises(Exception):
        factor_machines(4, feature_shards=3)     # must divide


@pytest.mark.parametrize("tl,extra", [
    ("hybrid", {"feature_shards": "2"}),
    ("voting", {"top_k": "10"}),                 # 2k >= block width: exact
    # voting × explicit feature sharding composes the two restrictions —
    # pinned, but redundant with the two cells above for tier-1 time
    pytest.param("voting", {"feature_shards": "2", "top_k": "10"},
                 marks=pytest.mark.slow),
])
@pytest.mark.parametrize("policy,compact", POLICIES)
def test_int8_bit_identical_per_iteration(data, tl, extra, policy,
                                          compact):
    """int8 histograms: hybrid/voting trees, scores and model text are
    BIT-identical to serial for every growth policy (per-iteration
    path)."""
    x, y = data
    base = {"grow_policy": policy, "leafwise_compact": compact,
            "hist_dtype": "int8"}
    serial = _serial(x, y, base)
    e = dict(base)
    e.update(extra)
    par = _train(tl, 4, x, y, e)
    _assert_bit_identical(serial, par, f"{tl} {policy} compact={compact}")
    # model text (the serialized surface) must match too
    st = "\n".join(t.to_string() for t in serial.models)
    pt = "\n".join(t.to_string() for t in par.models)
    assert st == pt


@pytest.mark.parametrize("tl,extra", [
    ("hybrid", {"feature_shards": "2"}),
    ("voting", {"top_k": "10"}),
])
@pytest.mark.parametrize("policy,compact,hd", [
    ("depthwise", "false", "int8"),
    # depthwise f32 chunk: pinned but redundant for tier-1 time — the
    # int8 cell above holds the depthwise chunk to the BITWISE bar and
    # the leafwise cell below covers the f32 chunk equivalence
    pytest.param("depthwise", "false", "float32",
                 marks=pytest.mark.slow),
    ("leafwise", "false", "float32"),
])
def test_fused_chunk_matches_serial(data, tl, extra, policy, compact, hd):
    """The fused k-iteration chunk program under the 2-D learners must
    reproduce the serial per-iteration trees (int8: bitwise; f32:
    near-tie equivalence — identical to the 1-D DP chunk bar)."""
    x, y = data
    base = {"grow_policy": policy, "leafwise_compact": compact,
            "hist_dtype": hd}
    serial = _serial(x, y, base)
    e = dict(base)
    e.update(extra)
    par = _make(tl, 4, x, y, e)
    par.train_chunk(3)
    if hd == "int8":
        _assert_bit_identical(serial, par, f"{tl} chunk {policy}")
    else:
        _assert_equivalent_to_serial(serial, par, x)


_F32_BASE = {"grow_policy": "leafwise", "leafwise_compact": "false",
             "hist_dtype": "float32"}


def test_hybrid_f32_equivalent_to_serial(data):
    x, y = data
    serial = _serial(x, y, _F32_BASE)
    hy = _train("hybrid", 4, x, y,
                dict(_F32_BASE, feature_shards="2"))
    _assert_equivalent_to_serial(serial, hy, x)


@pytest.mark.slow
def test_voting_f32_equivalent_to_serial(data):
    """Pinned, but rides the slow lane for tier-1 time: the leafwise f32
    fused-chunk cell above holds voting to the same f32 bar on every
    default run."""
    x, y = data
    serial = _serial(x, y, _F32_BASE)
    vo = _train("voting", 4, x, y, dict(_F32_BASE, top_k="10"))
    _assert_equivalent_to_serial(serial, vo, x)


def test_voting_small_topk_still_trains(data):
    """Below the exactness threshold (2·top_k < block width) voting is
    the PV-tree approximation: trees may differ from serial but training
    must stay healthy (every tree grows, predictions separate classes)."""
    x, y = data
    vo = _train("voting", 4, x, y, {"top_k": "2"}, iters=4)
    assert len(vo.models) == 4
    for t in vo.models:
        assert t.num_leaves > 1
    pred = vo.predict_raw(x)
    auc_ish = float(np.mean(pred[y > 0.5]) - np.mean(pred[y < 0.5]))
    assert auc_ish > 0.1


@pytest.mark.slow
def test_hybrid_uneven_rows_and_features(data):
    """Row padding (N % data_shards != 0) and feature-block padding
    (F % feature_shards != 0) both stay exact.  Slow lane (its 8-device
    4-feature-shard mesh compiles a one-off program set); the padding
    arithmetic itself is single-homed in _owned_block."""
    x, y = data
    x2, y2 = x[:1111], y[:1111]            # 1111 rows, 10 features, fs=2
    base = {"hist_dtype": "int8"}
    serial = _train("serial", 1, x2, y2, base)
    hy = _train("hybrid", 8, x2, y2,
                {"feature_shards": "4", "hist_dtype": "int8"})  # Fb=3 pads
    _assert_bit_identical(serial, hy, "hybrid uneven")
