"""Objective gradient/hessian tests against closed forms
(/root/reference/src/objective parity)."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.config import ObjectiveConfig
from lightgbm_tpu.io.metadata import Metadata
from lightgbm_tpu.objectives import create_objective


def _meta(label, weights=None, boundaries=None):
    m = Metadata()
    m.set_label(np.asarray(label, np.float32))
    if weights is not None:
        m.weights = np.asarray(weights, np.float32)
    if boundaries is not None:
        m.query_boundaries = np.asarray(boundaries, np.int32)
    return m


def test_regression_l2():
    obj = create_objective("regression", ObjectiveConfig())
    label = np.array([1.0, -2.0, 0.5])
    obj.init(_meta(label), 3)
    score = jnp.array([0.0, 1.0, 0.5])
    g, h = obj.get_gradients(score)
    np.testing.assert_allclose(np.asarray(g), [-1.0, 3.0, 0.0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), [1.0, 1.0, 1.0])


def test_regression_weighted():
    obj = create_objective("regression", ObjectiveConfig())
    obj.init(_meta([1.0, 0.0], weights=[2.0, 0.5]), 2)
    g, h = obj.get_gradients(jnp.array([0.0, 1.0]))
    np.testing.assert_allclose(np.asarray(g), [-2.0, 0.5], atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), [2.0, 0.5])


def test_binary_logloss_closed_form():
    cfg = ObjectiveConfig()
    cfg.sigmoid = 1.0
    obj = create_objective("binary", cfg)
    label = np.array([1.0, 0.0, 1.0, 0.0])
    obj.init(_meta(label), 4)
    score = np.array([0.3, -0.7, 0.0, 2.0], np.float32)
    g, h = obj.get_gradients(jnp.asarray(score))
    # reference formula (binary_objective.hpp:55-81)
    sign = np.where(label == 1, 1.0, -1.0)
    response = -2.0 * sign / (1.0 + np.exp(2.0 * sign * score))
    np.testing.assert_allclose(np.asarray(g), response, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h),
                               np.abs(response) * (2.0 - np.abs(response)),
                               rtol=1e-5)


def test_binary_single_class_fatal():
    from lightgbm_tpu.utils.log import LightGBMError
    obj = create_objective("binary", ObjectiveConfig())
    with pytest.raises(LightGBMError):
        obj.init(_meta([1.0, 1.0, 1.0]), 3)


def test_binary_unbalance_weights():
    cfg = ObjectiveConfig()
    cfg.is_unbalance = True
    obj = create_objective("binary", cfg)
    label = np.array([1.0, 0.0, 0.0, 0.0])  # pos/neg = 1/3
    obj.init(_meta(label), 4)
    g, _ = obj.get_gradients(jnp.zeros(4))
    # negatives reweighted by cnt_pos/cnt_neg = 1/3 (binary_objective.hpp:49-52)
    assert abs(g[1]) == pytest.approx(abs(g[0]) / 3, rel=1e-5)


def test_multiclass_softmax():
    cfg = ObjectiveConfig()
    cfg.num_class = 3
    obj = create_objective("multiclass", cfg)
    label = np.array([0.0, 2.0, 1.0])
    obj.init(_meta(label), 3)
    score = np.array([[1.0, 0.0, -1.0],
                      [0.0, 1.0, 0.5],
                      [2.0, -1.0, 0.0]], np.float32)  # [K, N]
    g, h = obj.get_gradients(jnp.asarray(score))
    z = np.exp(score - score.max(axis=0))
    p = z / z.sum(axis=0)
    onehot = np.eye(3)[label.astype(int)].T
    np.testing.assert_allclose(np.asarray(g), p - onehot, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h), 2 * p * (1 - p), rtol=1e-5)


def test_lambdarank_gradients_sane():
    cfg = ObjectiveConfig()
    obj = create_objective("lambdarank", cfg)
    # two queries: [3 docs], [2 docs]
    label = np.array([2.0, 0.0, 1.0, 1.0, 0.0])
    obj.init(_meta(label, boundaries=[0, 3, 5]), 5)
    score = jnp.array([0.1, 0.9, 0.2, 0.0, 0.3])
    g, h = obj.get_gradients(score)
    g, h = np.asarray(g), np.asarray(h)
    # lambdas sum to ~0 within a query (pairwise antisymmetry)
    assert abs(g[:3].sum()) < 1e-4
    assert abs(g[3:].sum()) < 1e-4
    # the best-labeled doc with low score is pushed up (negative gradient)
    assert g[0] < 0
    # hessians nonnegative
    assert (h >= -1e-6).all()


def test_lambdarank_requires_queries():
    from lightgbm_tpu.utils.log import LightGBMError
    obj = create_objective("lambdarank", ObjectiveConfig())
    with pytest.raises(LightGBMError):
        obj.init(_meta([1.0, 0.0]), 2)
