"""Golden end-to-end fixtures: the reference's bundled example tasks run
through the CLI Application with configs unchanged (apart from speed
overrides), asserting metric trajectories — the reference's de-facto test
suite (SURVEY §4, examples/README.md)."""
import os
import shutil

import numpy as np
import pytest

from lightgbm_tpu.cli import Application

EXAMPLES = "/root/reference/examples"


def _run_example(tmp_path, task_dir, files, overrides, monkeypatch):
    src = os.path.join(EXAMPLES, task_dir)
    if not os.path.isdir(src):
        pytest.skip("reference examples not available")
    for f in files:
        shutil.copy(os.path.join(src, f), tmp_path / f)
    monkeypatch.chdir(tmp_path)
    app = Application(["config=train.conf"] + overrides)
    app.run()
    return app


def _predict_example(tmp_path, monkeypatch, overrides=()):
    monkeypatch.chdir(tmp_path)
    app = Application(["config=predict.conf"] + list(overrides))
    app.run()
    return np.loadtxt(tmp_path / "LightGBM_predict_result.txt")


FAST = ["num_trees=5", "num_leaves=15", "min_data_in_leaf=20"]


def test_binary_classification(tmp_path, monkeypatch):
    app = _run_example(
        tmp_path, "binary_classification",
        ["binary.train", "binary.test", "binary.train.weight",
         "binary.test.weight", "train.conf", "predict.conf"],
        FAST, monkeypatch)
    # model written in reference format
    model_text = (tmp_path / "LightGBM_model.txt").read_text()
    assert model_text.startswith("gbdt\n")
    assert model_text.count("Tree=") == 5
    assert "feature importances:" in model_text
    # AUC above chance after 5 trees
    auc = app.boosting.valid_metrics[0][1].eval(
        np.asarray(app.boosting.valid_datasets[0]["score"][0]))[0]
    assert auc > 0.7
    preds = _predict_example(tmp_path, monkeypatch)
    assert preds.shape[0] == 500
    assert ((preds >= 0) & (preds <= 1)).all()


def test_regression(tmp_path, monkeypatch):
    app = _run_example(
        tmp_path, "regression",
        ["regression.train", "regression.test", "train.conf", "predict.conf"],
        FAST, monkeypatch)
    metric = app.boosting.valid_metrics[0][0]
    rmse = metric.eval(np.asarray(app.boosting.valid_datasets[0]["score"][0]))[0]
    # labels are 0/1 in this example; scores start at 0 → initial RMSE ≈
    # sqrt(mean(y²)) ≈ 0.707; five small trees at lr=0.05 must cut it
    assert rmse < 0.68
    preds = _predict_example(tmp_path, monkeypatch)
    assert np.isfinite(preds).all()


def test_multiclass(tmp_path, monkeypatch):
    app = _run_example(
        tmp_path, "multiclass_classification",
        ["multiclass.train", "multiclass.test", "train.conf", "predict.conf"],
        ["num_trees=3", "num_leaves=15", "min_data_in_leaf=20"], monkeypatch)
    assert len(app.boosting.models) == 3 * 5  # interleaved per class
    preds = _predict_example(tmp_path, monkeypatch)
    assert preds.shape == (500, 5)
    np.testing.assert_allclose(preds.sum(axis=1), 1.0, atol=1e-4)


def test_lambdarank(tmp_path, monkeypatch):
    app = _run_example(
        tmp_path, "lambdarank",
        ["rank.train", "rank.test", "rank.train.query", "rank.test.query",
         "train.conf", "predict.conf"],
        ["num_trees=5", "num_leaves=15", "min_data_in_leaf=10"], monkeypatch)
    metric = app.boosting.valid_metrics[0][0]
    ndcgs = metric.eval(np.asarray(app.boosting.valid_datasets[0]["score"][0]))
    assert all(v > 0.4 for v in ndcgs)
    preds = _predict_example(tmp_path, monkeypatch)
    assert np.isfinite(preds).all()


def test_binary_save_binary_cache(tmp_path, monkeypatch):
    """Dataset binary cache: second run loads <data>.bin (dataset.cpp:653-898)."""
    app = _run_example(
        tmp_path, "binary_classification",
        ["binary.train", "binary.test", "binary.train.weight",
         "binary.test.weight", "train.conf", "predict.conf"],
        FAST + ["is_save_binary_file=true"], monkeypatch)
    assert (tmp_path / "binary.train.bin").exists()
    score1 = np.asarray(app.boosting.score[0]).copy()
    # retrain from the cache; identical data → identical first-model scores
    app2 = Application(["config=train.conf"] + FAST)
    app2.run()
    np.testing.assert_allclose(np.asarray(app2.boosting.score[0]), score1,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("learner", ["feature", "data"])
def test_parallel_learning(tmp_path, monkeypatch, learner):
    """examples/parallel_learning runs with its config unchanged: the
    machine-list/port bootstrap keys are accepted and the mesh replaces the
    socket cluster (README steps 1-3; tree_learner=feature in train.conf,
    data-parallel via the documented override)."""
    app = _run_example(
        tmp_path, "parallel_learning",
        ["binary.train", "binary.test", "mlist.txt", "train.conf",
         "predict.conf"],
        FAST + [f"tree_learner={learner}"], monkeypatch)
    assert len(app.boosting.models) == 5
    auc = app.boosting.valid_metrics[0][1].eval(
        np.asarray(app.boosting.valid_datasets[0]["score"][0]))[0]
    assert auc > 0.7
    preds = _predict_example(tmp_path, monkeypatch)
    assert ((preds >= 0) & (preds <= 1)).all()
