"""Tree model tests: text round-trip, replay prediction vs naive traversal."""
import numpy as np

from lightgbm_tpu.models.tree import Tree


def _manual_tree():
    """Hand-built 4-leaf tree mirroring Tree::Split's construction
    (tree.cpp:50-83): split leaf 0 on f0<=0.5 (node 0), then leaf 0 on
    f1<=1.5 (node 1), then leaf 1 on f0<=-0.5 (node 2)."""
    t = Tree(
        num_leaves=4,
        split_feature=[0, 1, 0],
        split_feature_real=[0, 1, 0],
        threshold_bin=[0, 0, 0],
        threshold=[0.5, 1.5, -0.5],
        split_gain=[10.0, 5.0, 2.0],
        # node0: left=node1(leaf0 split later), right=node2(leaf1 split later)
        left_child=[1, ~0, ~1],
        right_child=[2, ~2, ~3],
        leaf_parent=[1, 2, 1, 2],
        leaf_value=[1.0, 2.0, 3.0, 4.0],
    )
    return t


def _naive_predict(tree: Tree, row: np.ndarray) -> float:
    """Pointer-walk oracle (tree.h:177-187)."""
    node = 0
    while node >= 0:
        if row[tree.split_feature_real[node]] <= tree.threshold[node]:
            node = tree.left_child[node]
        else:
            node = tree.right_child[node]
    return tree.leaf_value[~node]


def test_replay_matches_naive_traversal():
    t = _manual_tree()
    rng = np.random.RandomState(0)
    rows = rng.randn(200, 2) * 2
    expected = np.array([_naive_predict(t, r) for r in rows])
    got = t.predict(rows)
    np.testing.assert_allclose(got, expected)


def test_text_roundtrip():
    t = _manual_tree()
    s = t.to_string()
    t2 = Tree.from_string(s)
    assert t2.num_leaves == 4
    np.testing.assert_array_equal(t2.left_child, t.left_child)
    np.testing.assert_array_equal(t2.right_child, t.right_child)
    np.testing.assert_allclose(t2.threshold, t.threshold)
    np.testing.assert_allclose(t2.leaf_value, t.leaf_value)
    rng = np.random.RandomState(1)
    rows = rng.randn(50, 2)
    np.testing.assert_allclose(t2.predict(rows), t.predict(rows))


def test_single_leaf_tree():
    t = Tree(num_leaves=1, split_feature=[], split_feature_real=[],
             threshold_bin=[], threshold=[], split_gain=[], left_child=[],
             right_child=[], leaf_parent=[-1], leaf_value=[0.25])
    rows = np.zeros((5, 3))
    np.testing.assert_allclose(t.predict(rows), 0.25)


def test_shrinkage():
    t = _manual_tree()
    t.shrinkage(0.1)
    np.testing.assert_allclose(t.leaf_value, [0.1, 0.2, 0.3, 0.4])
