"""Depth-wise (level-batched) grower tests.

The depthwise policy (models/grower_depthwise.py) is the TPU throughput
path: identical split math to the leaf-wise grower, level-batched order.
Tests keep shapes tiny — the unrolled level program is expensive to compile
on CPU."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.grower import grow_tree
from lightgbm_tpu.models.grower_depthwise import grow_tree_depthwise, num_levels
from lightgbm_tpu.ops.histogram import histogram_leafbatch, histogram_segsum


@pytest.fixture(scope="module")
def small_problem():
    rng = np.random.RandomState(3)
    n, f = 800, 5
    x = rng.randn(n, f)
    y = ((x[:, 0] - x[:, 1] + 0.3 * rng.randn(n)) > 0).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=16)
    p = 0.5 * np.ones(n, np.float32)
    grad = jnp.asarray(p - y)
    hess = jnp.asarray(p * (1 - p))
    return dict(
        ds=ds, x=x, y=y,
        bins=jnp.asarray(ds.bins), grad=grad, hess=hess,
        row_mask=jnp.ones(n, bool), fmask=jnp.ones(f, bool),
        nbins=jnp.asarray([m.num_bin for m in ds.bin_mappers], jnp.int32))


def _grow(p, policy, num_leaves, row_mask=None, **kw):
    fn = grow_tree_depthwise if policy == "depthwise" else grow_tree
    return fn(p["bins"], p["grad"], p["hess"],
              p["row_mask"] if row_mask is None else row_mask,
              p["fmask"], p["nbins"], num_leaves=num_leaves, num_bins_max=16,
              min_data_in_leaf=10, min_sum_hessian_in_leaf=0.5, **kw)


def test_leafbatch_histogram_matches_segsum_oracle(small_problem):
    p = small_problem
    rng = np.random.RandomState(0)
    cid = jnp.asarray(rng.randint(0, 4, 800), jnp.int32)
    ok = jnp.asarray(rng.rand(800) < 0.7)
    got = histogram_leafbatch(p["bins"], p["grad"], p["hess"], cid, ok, 4, 16)
    for c in range(4):
        want = histogram_segsum(p["bins"], p["grad"], p["hess"],
                                ok & (cid == c), 16)
        np.testing.assert_allclose(np.asarray(got[c]), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


def test_depthwise_tree_structure(small_problem):
    p = small_problem
    tree = _grow(p, "depthwise", 8)
    n = int(tree.num_leaves)
    assert 2 <= n <= 8
    counts = np.asarray(tree.leaf_count)[:n]
    assert counts.sum() == 800 and (counts >= 10).all()
    # every row's leaf value via the recorded partition equals a tree replay
    from lightgbm_tpu.ops.scoring import add_tree_score

    def pad(a, size):
        out = np.zeros(size, np.asarray(a).dtype)
        out[:min(len(np.asarray(a)), size)] = np.asarray(a)[:size]
        return jnp.asarray(out)

    lv = np.zeros(9, np.float32)
    lv[:n] = np.asarray(tree.leaf_value)[:n]
    replay = add_tree_score(
        p["bins"], jnp.zeros(800), pad(tree.split_feature, 7),
        pad(tree.threshold_bin, 7), pad(tree.left_child, 7),
        pad(tree.right_child, 7), jnp.asarray(lv), tree.num_leaves,
        max_nodes=7)
    by_ids = np.asarray(tree.leaf_value)[np.asarray(tree.leaf_ids)]
    np.testing.assert_allclose(np.asarray(replay), by_ids, atol=1e-6)


def test_depthwise_stump_matches_leafwise(small_problem):
    p = small_problem
    td = _grow(p, "depthwise", 2)
    tl = _grow(p, "leafwise", 2)
    assert int(td.split_feature[0]) == int(tl.split_feature[0])
    assert int(td.threshold_bin[0]) == int(tl.threshold_bin[0])
    np.testing.assert_allclose(np.asarray(td.leaf_value)[:2],
                               np.asarray(tl.leaf_value)[:2], rtol=1e-4)


def test_depthwise_respects_leaf_budget_and_bagging(small_problem):
    p = small_problem
    rng = np.random.RandomState(1)
    bag = jnp.asarray(rng.rand(800) < 0.6)
    tree = _grow(p, "depthwise", 6, row_mask=bag)
    n = int(tree.num_leaves)
    assert n <= 6
    counts = np.asarray(tree.leaf_count)[:n]
    assert counts.sum() == int(np.asarray(bag).sum())


def test_num_levels():
    assert num_levels(2) == 1
    assert num_levels(255) == 8
    assert num_levels(256) == 8
    assert num_levels(63) == 6
    # max_depth semantics match the leaf-wise rule: a leaf at depth >=
    # max_depth (root depth 1) cannot split → max_depth-1 split levels
    assert num_levels(255, max_depth=5) == 4
    assert num_levels(255, max_depth=2) == 1


def test_depthwise_data_parallel_matches_serial(small_problem):
    """Data-parallel depthwise over the 8-device CPU mesh grows the same
    tree as single-device depthwise (the reference's serial≡parallel
    invariant, data_parallel_tree_learner.cpp:237-243)."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.parallel import create_parallel_learner

    p = small_problem
    params = {"objective": "binary", "num_leaves": "8",
              "min_data_in_leaf": "10", "min_sum_hessian_in_leaf": "0.5",
              "learning_rate": "0.1", "grow_policy": "depthwise"}
    trees = {}
    for learner_kind, machines in (("serial", 1), ("data", 8),
                                   ("feature", 4)):
        cfg = OverallConfig()
        cfg.set(dict(params, tree_learner=learner_kind,
                     num_machines=str(machines)), require_data=False)
        ds = Dataset.from_arrays(p["x"], p["y"], max_bin=16)
        booster = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        learner = (create_parallel_learner(cfg)
                   if learner_kind != "serial" else None)
        booster.init(cfg.boosting_config, ds, obj, learner=learner)
        booster.train_one_iter(is_eval=False)
        trees[learner_kind] = booster.models[0]
    a = trees["serial"]
    for kind in ("data", "feature"):
        b = trees[kind]
        np.testing.assert_array_equal(a.split_feature, b.split_feature)
        np.testing.assert_array_equal(a.threshold_bin, b.threshold_bin)
        np.testing.assert_allclose(a.leaf_value, b.leaf_value, rtol=1e-3)


def test_gbdt_trains_with_depthwise_policy(small_problem):
    p = small_problem
    ds = Dataset.from_arrays(p["x"], p["y"], max_bin=16)
    booster = lgb.train(
        {"objective": "binary", "num_leaves": 8, "min_data_in_leaf": 10,
         "min_sum_hessian_in_leaf": 0.5, "num_iterations": 8,
         "learning_rate": 0.2, "grow_policy": "depthwise"}, ds)
    prob = booster.predict(p["x"])
    acc = ((prob > 0.5).astype(np.float32) == p["y"]).mean()
    assert acc > 0.85
