"""Compacted leaf-wise grower: streaming partition op + tree equivalence.

The compacted grower (models/grower_leafcompact.py) must grow EXACTLY the
trees of the masked grower (models/grower.py) — same structure, and
bit-identical values in the int8 mode whose arithmetic is order-free.  The
partition op itself is differentially tested: Pallas kernel (interpret
mode on CPU) vs the stable-argsort XLA oracle.
"""
import numpy as np
import pytest
import jax.numpy as jnp

from lightgbm_tpu.ops.compact import (BLOCK, bucket_table, pack_planes,
                                      partition_segment, unpack_values)


def _random_case(rng, R, W, delta, cnt):
    seg = rng.randint(-128, 128, (R, W)).astype(np.int8)
    m = rng.randint(0, 2, W).astype(np.int8)
    lane = np.arange(W)
    mask3 = np.where((lane >= delta) & (lane < delta + cnt), m, -1)
    return seg, mask3.astype(np.int8), int((mask3 == 1).sum())


@pytest.mark.parametrize("delta,cnt", [
    (0, 4096), (0, 4000), (100, 3000), (4095, 1), (0, 1), (123, 0),
])
def test_partition_kernel_matches_oracle(delta, cnt):
    rng = np.random.RandomState(delta + cnt)
    R, W = 11, 4096
    seg, mask3, plcnt = _random_case(rng, R, W, delta, cnt)
    args = (jnp.asarray(seg), jnp.asarray(mask3), jnp.int32(delta),
            jnp.int32(cnt), jnp.int32(plcnt))
    oracle = np.asarray(partition_segment(*args, block=2048))
    kernel = np.asarray(partition_segment(*args, block=2048,
                                          use_pallas=True, interpret=True))
    np.testing.assert_array_equal(oracle, kernel)


@pytest.mark.parametrize("delta,cnt", [
    (0, 8192), (777, 6000), (2047, 4097), (100, 3000), (4095, 2049),
])
def test_partition_dma_overlap_bit_identity(delta, cnt):
    """The overlapped-DMA kernel schedule (both window reads up front,
    left write-back under the right blend, VMEM-side merge of the fresh
    left lanes into the right window) must be BIT-identical to both the
    serialized schedule and the oracle.  W=8192 runs 4 lane blocks, so
    the running offsets and the cross-block window overlaps (the lanes
    the merge exists for) are genuinely exercised."""
    rng = np.random.RandomState(delta * 7 + cnt)
    R, W = 13, 8192
    seg, mask3, plcnt = _random_case(rng, R, W, delta, cnt)
    args = (jnp.asarray(seg), jnp.asarray(mask3), jnp.int32(delta),
            jnp.int32(cnt), jnp.int32(plcnt))
    oracle = np.asarray(partition_segment(*args, block=2048))
    serial = np.asarray(partition_segment(*args, block=2048,
                                          use_pallas=True, interpret=True,
                                          overlap=False))
    overlap = np.asarray(partition_segment(*args, block=2048,
                                           use_pallas=True, interpret=True,
                                           overlap=True))
    np.testing.assert_array_equal(oracle, serial)
    np.testing.assert_array_equal(oracle, overlap)


def test_partition_wide_feature_eligibility(monkeypatch):
    """Wide-feature datasets whose plane pane blows the kernel's VMEM
    working set must fall back to the XLA argsort oracle at the
    ELIGIBILITY rule (pallas_partition_ok), not as a Mosaic compile
    error — and the fallback is a counted route."""
    import jax
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.ops.compact import (PARTITION_VMEM_BUDGET,
                                          pallas_partition_ok,
                                          partition_vmem_bytes)
    # the byte estimate is monotone in F and crosses the budget in the
    # F ≈ 100-200 band PROFILE.md flags
    assert partition_vmem_bytes(28) < PARTITION_VMEM_BUDGET
    assert partition_vmem_bytes(200) > PARTITION_VMEM_BUDGET
    # the gate must hold even where the backend says yes
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    telemetry.enable()
    try:
        assert pallas_partition_ok(28) is True
        assert pallas_partition_ok(200) is False
        assert telemetry.counters().get(
            "partition/wide_f_fallback", 0) > 0
        # F-less callers (back-compat) keep the backend-only rule
        assert pallas_partition_ok() is True
    finally:
        telemetry.disable()


def test_partition_oracle_semantics():
    """Stable partition of the in-segment lanes; everything else
    preserved byte for byte."""
    rng = np.random.RandomState(3)
    R, W, delta, cnt = 5, 8192, 777, 6000
    seg, mask3, plcnt = _random_case(rng, R, W, delta, cnt)
    out = np.asarray(partition_segment(
        jnp.asarray(seg), jnp.asarray(mask3), jnp.int32(delta),
        jnp.int32(cnt), jnp.int32(plcnt)))
    m = mask3[delta:delta + cnt]
    inner = seg[:, delta:delta + cnt]
    np.testing.assert_array_equal(out[:, delta:delta + plcnt],
                                  inner[:, m == 1])
    np.testing.assert_array_equal(out[:, delta + plcnt:delta + cnt],
                                  inner[:, m == 0])
    np.testing.assert_array_equal(out[:, :delta], seg[:, :delta])
    np.testing.assert_array_equal(out[:, delta + cnt:], seg[:, delta + cnt:])


def test_plane_pack_roundtrip():
    rng = np.random.RandomState(1)
    N, F = 1000, 4
    bins = rng.randint(0, 256, (F, N)).astype(np.uint8)
    grad = rng.randn(N).astype(np.float32) * 1e3
    hess = np.abs(rng.randn(N)).astype(np.float32) * 1e-3
    mask = rng.rand(N) < 0.7
    from lightgbm_tpu.ops.compact import pane_rows
    pane = pack_planes(jnp.asarray(bins), jnp.asarray(grad),
                       jnp.asarray(hess), jnp.asarray(mask), 2048)
    assert pane.shape == (pane_rows(F), 2048)
    assert pane_rows(F) % 8 == 0
    b, g, h, v = unpack_values(pane[:, :N], F)
    np.testing.assert_array_equal(np.asarray(b), bins)
    np.testing.assert_array_equal(np.asarray(g), grad)   # bit-exact planes
    np.testing.assert_array_equal(np.asarray(h), hess)
    np.testing.assert_array_equal(np.asarray(v), mask)


def test_bucket_table_invariants():
    for n in (1, 2048, 100_000, 1_000_000, 11_000_000):
        t = bucket_table(n)
        assert t[0] >= n and t[0] % BLOCK == 0
        for a, b in zip(t, t[1:]):
            assert b % BLOCK == 0 and b < a
            # a tier-k child (<= ceil(parent/2) rows) fits tier k+1
            assert b >= -(-a // 2) - BLOCK


def _grow_both(seed, *, compute_dtype, bagging, num_leaves=31, N=4000,
               F=5, B=32, min_data=20):
    from lightgbm_tpu.models.grower import grow_tree
    from lightgbm_tpu.models.grower_leafcompact import grow_tree_leafcompact

    rng = np.random.RandomState(seed)
    x = rng.randn(N, F)
    lo, hi = x.min(0), x.max(0)
    bins = np.clip((x - lo) / (hi - lo) * (B - 1), 0, B - 1)
    bins = bins.astype(np.uint8).T
    y = (x[:, 0] - x[:, 1] + 0.5 * np.sin(3 * x[:, 2])
         + 0.3 * rng.randn(N) > 0)
    pr = np.full(N, 0.5, np.float32)
    grad = (pr - y).astype(np.float32)
    hess = (pr * (1 - pr)).astype(np.float32)
    row_mask = np.ones(N, bool)
    if bagging:
        row_mask[rng.rand(N) < 0.4] = False
    kw = dict(num_leaves=num_leaves, num_bins_max=B,
              min_data_in_leaf=min_data, min_sum_hessian_in_leaf=1e-3,
              compute_dtype=compute_dtype)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(row_mask), jnp.asarray(np.ones(F, bool)),
            jnp.asarray(np.full(F, B, np.int32)))
    return grow_tree(*args, **kw), grow_tree_leafcompact(*args, **kw)


@pytest.mark.parametrize("bagging", [False, True])
@pytest.mark.parametrize("dtype", ["int8", "float32"])
def test_compact_grower_matches_masked_grower(dtype, bagging):
    dt = "int8" if dtype == "int8" else jnp.float32
    t1, t2 = _grow_both(11, compute_dtype=dt, bagging=bagging)
    assert int(t1.num_leaves) == int(t2.num_leaves) > 8
    for field in ("split_feature", "threshold_bin", "left_child",
                  "right_child", "leaf_count", "leaf_ids"):
        np.testing.assert_array_equal(np.asarray(getattr(t1, field)),
                                      np.asarray(getattr(t2, field)),
                                      err_msg=field)
    if dtype == "float32":
        # no trailing dequantize multiply -> nothing for XLA CPU's FMA
        # contraction to grab: bit-identical across the two programs
        np.testing.assert_array_equal(np.asarray(t1.leaf_value),
                                      np.asarray(t2.leaf_value))
    else:
        # XLA CPU contracts the MASKED grower's int8 dequantize multiply
        # into the subtraction as a single-rounding FMA (sub-ulp dust the
        # compacted program doesn't get; see grower_leafcompact.py) —
        # value-tolerant here, with the bitwise anchor provided by
        # test_compact_grower_matches_jitfree_replay
        np.testing.assert_allclose(np.asarray(t1.leaf_value),
                                   np.asarray(t2.leaf_value),
                                   rtol=1e-4, atol=1e-7)


def _manual_replay(bins, grad, hess, row_mask, num_bins, feature_mask, *,
                   num_leaves, num_bins_max, min_data, min_hess, dtype):
    """jit-free leaf-wise replay: the same library ops (build_histogram /
    find_best_split), dispatched one by one so no cross-op fusion can
    alter rounding.  The reference algorithm in ~30 lines
    (serial_tree_learner.cpp:119-153)."""
    from lightgbm_tpu.ops.histogram import build_histogram
    from lightgbm_tpu.ops.split import find_best_split

    N = bins.shape[1]
    bj, gj, hj = map(jnp.asarray, (bins, grad, hess))
    nb, fm = jnp.asarray(num_bins), jnp.asarray(feature_mask)
    leaf_ids = np.zeros(N, np.int32)
    hist, cand = {}, {}
    root = np.asarray(build_histogram(bj, gj, hj, jnp.asarray(row_mask),
                                      num_bins_max, compute_dtype=dtype))
    if dtype == "int8":
        st = root[0].sum(axis=0)
    else:
        st = np.array([(grad * row_mask).sum(), (hess * row_mask).sum(),
                       row_mask.sum()], np.float32)
    hist[0] = root
    cand[0] = find_best_split(jnp.asarray(root), *map(jnp.float32, st),
                              nb, fm, float(min_data), float(min_hess))
    values = np.zeros(num_leaves, np.float32)
    for split in range(num_leaves - 1):
        bl = max(cand, key=lambda k: float(cand[k].gain))
        best = cand[bl]
        if not float(best.gain) > 0:
            break
        new = split + 1
        feat, thr = int(best.feature), int(best.threshold)
        go_r = (bins[feat] > thr) & (leaf_ids == bl)
        leaf_ids[go_r] = new
        lcnt, rcnt = int(best.left_count), int(best.right_count)
        small = bl if lcnt <= rcnt else new
        sm = row_mask & (leaf_ids == small)
        sh = np.asarray(build_histogram(bj, gj, hj, jnp.asarray(sm),
                                        num_bins_max, compute_dtype=dtype,
                                        salt=new))
        large = hist[bl] - sh
        hist[bl], hist[new] = ((sh, large) if lcnt <= rcnt
                               else (large, sh))
        values[bl] = float(best.left_output)
        values[new] = float(best.right_output)
        for leaf, g_, h_, c_ in ((bl, best.left_sum_grad,
                                  best.left_sum_hess, lcnt),
                                 (new, best.right_sum_grad,
                                  best.right_sum_hess, rcnt)):
            cand[leaf] = find_best_split(
                jnp.asarray(hist[leaf]), jnp.float32(g_), jnp.float32(h_),
                jnp.float32(c_), nb, fm, float(min_data), float(min_hess))
    return leaf_ids, values


@pytest.mark.parametrize("dtype", ["int8", "float32"])
def test_compact_grower_matches_jitfree_replay(dtype):
    """The compacted grower reproduces a jit-free op-by-op replay of the
    reference algorithm BIT FOR BIT — the strongest equivalence anchor
    available on CPU (the masked grower deviates by FMA-contraction dust
    in the int8 mode; the replay and the compacted program do not)."""
    from lightgbm_tpu.models.grower_leafcompact import grow_tree_leafcompact

    rng = np.random.RandomState(23)
    N, F, B, L = 4000, 5, 32, 15
    x = rng.randn(N, F)
    lo, hi = x.min(0), x.max(0)
    bins = np.clip((x - lo) / (hi - lo) * (B - 1), 0, B - 1)
    bins = bins.astype(np.uint8).T
    y = (x[:, 0] - x[:, 1] + 0.3 * rng.randn(N) > 0)
    pr = np.full(N, 0.5, np.float32)
    grad = (pr - y).astype(np.float32)
    hess = (pr * (1 - pr)).astype(np.float32)
    row_mask = np.ones(N, bool)
    row_mask[rng.rand(N) < 0.3] = False
    nb = np.full(F, B, np.int32)
    fm = np.ones(F, bool)
    dt = "int8" if dtype == "int8" else jnp.float32

    tree = grow_tree_leafcompact(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(row_mask), jnp.asarray(fm), jnp.asarray(nb),
        num_leaves=L, num_bins_max=B, min_data_in_leaf=20,
        min_sum_hessian_in_leaf=1e-3, compute_dtype=dt)
    leaf_ids, values = _manual_replay(
        bins, grad, hess, row_mask, nb, fm, num_leaves=L, num_bins_max=B,
        min_data=20, min_hess=1e-3,
        dtype="int8" if dtype == "int8" else jnp.float32)
    np.testing.assert_array_equal(np.asarray(tree.leaf_ids), leaf_ids)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree.leaf_value)[:nl],
                                  values[:nl])


def test_compact_training_end_to_end():
    """Config-driven training with leafwise_compact=true reproduces the
    masked grower's boosting trajectory: identical tree structure every
    iteration, leaf values to reduction-order rounding (real-gradient
    [N]-sum reductions fuse differently across the two compiled programs
    on CPU — the bitwise anchor is test_compact_grower_matches_jitfree_
    replay)."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    rng = np.random.RandomState(5)
    N = 3000
    x = rng.randn(N, 6)
    y = ((x[:, 0] + 0.5 * x[:, 1] * x[:, 2] + 0.3 * rng.randn(N)) > 0)
    ds = Dataset.from_arrays(x, y.astype(np.float32), max_bin=64)

    def run(compact):
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "15",
                 "min_data_in_leaf": "20", "min_sum_hessian_in_leaf": "1e-3",
                 "learning_rate": "0.1", "num_iterations": "5",
                 "grow_policy": "leafwise", "hist_dtype": "float32",
                 "leafwise_compact": compact}, require_data=False)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config))
        for _ in range(5):
            b.train_one_iter(is_eval=False)
        return b

    b1, b2 = run("false"), run("true")
    assert len(b1.models) == len(b2.models) == 5
    for t1, t2 in zip(b1.models, b2.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(b1.score),
                               np.asarray(b2.score), rtol=1e-3, atol=1e-5)


def test_compact_grower_max_depth():
    """The depth guard must block splits identically in both growers."""
    from lightgbm_tpu.models.grower import grow_tree
    from lightgbm_tpu.models.grower_leafcompact import grow_tree_leafcompact

    rng = np.random.RandomState(3)
    N, F, B = 3000, 5, 32
    x = rng.randn(N, F)
    lo, hi = x.min(0), x.max(0)
    bins = ((x - lo) / (hi - lo) * (B - 1)).astype(np.uint8).T
    y = (x[:, 0] + 0.5 * x[:, 1] > 0)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(N, 0.25, np.float32)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(np.ones(N, bool)), jnp.asarray(np.ones(F, bool)),
            jnp.asarray(np.full(F, B, np.int32)))
    kw = dict(num_leaves=31, num_bins_max=B, min_data_in_leaf=10,
              min_sum_hessian_in_leaf=1e-3, max_depth=3,
              compute_dtype=jnp.float32)
    t1, t2 = grow_tree(*args, **kw), grow_tree_leafcompact(*args, **kw)
    assert int(t1.num_leaves) == int(t2.num_leaves) <= 4   # 2^(3-1)
    np.testing.assert_array_equal(np.asarray(t1.leaf_ids),
                                  np.asarray(t2.leaf_ids))
    np.testing.assert_array_equal(np.asarray(t1.leaf_value),
                                  np.asarray(t2.leaf_value))


def test_compact_training_multiclass():
    """Multiclass boosting (per-class interleaved trees) through the
    compacted grower matches the masked grower's structure/scores."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    rng = np.random.RandomState(8)
    N = 2400
    x = rng.randn(N, 5)
    y = (np.digitize(x[:, 0] + 0.3 * x[:, 1], [-0.5, 0.5])
         ).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32)

    def run(compact):
        cfg = OverallConfig()
        cfg.set({"objective": "multiclass", "num_class": "3",
                 "num_leaves": "7", "min_data_in_leaf": "20",
                 "min_sum_hessian_in_leaf": "1e-3",
                 "learning_rate": "0.1", "num_iterations": "3",
                 "grow_policy": "leafwise", "hist_dtype": "float32",
                 "leafwise_compact": compact}, require_data=False)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config))
        for _ in range(3):
            b.train_one_iter(is_eval=False)
        return b

    b1, b2 = run("false"), run("true")
    assert len(b1.models) == len(b2.models) == 9      # 3 classes x 3 iters
    for t1, t2 in zip(b1.models, b2.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-4, atol=1e-6)


def test_compact_grower_data_parallel_matches_serial():
    """The compacted grower under the data-parallel psum schedule: each
    shard keeps its LOCAL rows physically partitioned, per-split
    histograms are psum'd with a pmax-synced slice tier.  int8 trees
    must be bit-identical to the serial compacted run (int-domain
    reduction is order-free); rows not divisible by 8 exercises the
    shard padding path."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.parallel import create_parallel_learner

    rng = np.random.RandomState(19)
    n = 2999                                # 2999 % 8 != 0
    x = rng.randn(n, 6)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.3 * rng.randn(n)) > 0)
    ds = Dataset.from_arrays(x, y.astype(np.float32), max_bin=32)
    params = {"objective": "binary", "num_leaves": "15",
              "min_data_in_leaf": "20", "min_sum_hessian_in_leaf": "1e-3",
              "learning_rate": "0.1", "num_iterations": "4",
              "grow_policy": "leafwise", "hist_dtype": "int8",
              "leafwise_compact": "true", "dp_schedule": "psum"}

    def run(tree_learner, machines):
        cfg = OverallConfig()
        p = dict(params, tree_learner=tree_learner,
                 num_machines=str(machines))
        cfg.set(p, require_data=False)
        b = GBDT()
        learner = (create_parallel_learner(cfg)
                   if tree_learner != "serial" else None)
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config),
               learner=learner)
        for _ in range(4):
            b.train_one_iter(is_eval=False)
        return b

    b_s, b_dp = run("serial", 1), run("data", 8)
    assert len(b_s.models) == len(b_dp.models) == 4
    for k, (t1, t2) in enumerate(zip(b_s.models, b_dp.models)):
        assert t1.num_leaves == t2.num_leaves, f"tree {k}"
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature,
                                      err_msg=f"tree {k}")
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin,
                                      err_msg=f"tree {k}")
        # int accumulators identical; per-program f32 dequantize/search
        # fusion may differ by a couple ulps (cross-program FMA story)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-6, atol=1e-9,
                                   err_msg=f"tree {k}")


def test_compact_chunk_path_matches_per_iteration():
    """Direct train_chunk calls (the CPU-test chunk seam) must ride the
    SAME compacted grower as the per-iteration path for the same
    config."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    rng = np.random.RandomState(21)
    n = 2000
    x = rng.randn(n, 5)
    y = ((x[:, 0] + 0.4 * x[:, 1] + 0.3 * rng.randn(n)) > 0)
    ds = Dataset.from_arrays(x, y.astype(np.float32), max_bin=32)

    def run(chunked):
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "15",
                 "min_data_in_leaf": "20",
                 "min_sum_hessian_in_leaf": "1e-3",
                 "learning_rate": "0.1", "num_iterations": "4",
                 "grow_policy": "leafwise", "hist_dtype": "int8",
                 "leafwise_compact": "true"}, require_data=False)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config))
        if chunked:
            b.train_chunk(4)
        else:
            for _ in range(4):
                b.train_one_iter(is_eval=False)
        return b

    b_it, b_ch = run(False), run(True)
    assert len(b_it.models) == len(b_ch.models) == 4
    for t1, t2 in zip(b_it.models, b_ch.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-6, atol=1e-9)


def test_compact_training_bagging_feature_fraction():
    """Bagging + feature_fraction through the compacted grower: the RNG
    streams and masks are shared machinery, so trajectories must match
    the masked grower exactly in structure."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    rng = np.random.RandomState(13)
    n = 2500
    x = rng.randn(n, 8)
    y = ((x[:, 0] - 0.5 * x[:, 1] + 0.3 * rng.randn(n)) > 0)
    ds = Dataset.from_arrays(x, y.astype(np.float32), max_bin=32)

    def run(compact):
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "15",
                 "min_data_in_leaf": "20",
                 "min_sum_hessian_in_leaf": "1e-3",
                 "learning_rate": "0.1", "num_iterations": "4",
                 "bagging_fraction": "0.8", "bagging_freq": "2",
                 "bagging_seed": "7", "feature_fraction": "0.6",
                 "feature_fraction_seed": "3",
                 "grow_policy": "leafwise", "hist_dtype": "int8",
                 "leafwise_compact": compact}, require_data=False)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config))
        for _ in range(4):
            b.train_one_iter(is_eval=False)
        return b

    b1, b2 = run("false"), run("true")
    assert len(b1.models) == len(b2.models) == 4
    for t1, t2 in zip(b1.models, b2.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-4, atol=1e-6)
