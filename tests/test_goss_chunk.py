"""In-chunk GOSS (ISSUE 12): goss=true no longer excludes the fused
chunk path.

The selection — top_rate rows by |grad| + an amplified other_rate random
remainder — is traced INTO the chunk scan body (models/gbdt.make_goss_fn
for the serial/FP full-row layouts; the data-parallel variant in
parallel/learners.chunk_program all_gathers the per-row scores over the
data axis, draws on the COMPACTED true-row layout and slices each
shard's mask/weights back out).  The key stream is
``fold_in(PRNGKey(bagging_seed), iteration)`` — the per-iteration path's
— so fused == per-iteration selection is bit-identical.  Pinned here:

- chunk_supported no longer returns False for goss=true;
- fused-chunk == per-iteration model equivalence (f32 and int8);
- GOSS under single-process DP == serial GOSS (the acceptance row);
- GOSS iterations dispatch through the fused chunk program — the
  costmodel program inventory shows no per-iteration grow programs;
- the per-iteration multi-process guard stays a precise fatal.
"""
import numpy as np
import pytest

from lightgbm_tpu import costmodel, telemetry
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel.learners import create_parallel_learner
from lightgbm_tpu.utils.log import LightGBMError


@pytest.fixture(scope="module")
def goss_ds():
    rng = np.random.RandomState(7)
    n = 3000
    x = rng.randn(n, 10)
    y = ((x[:, 0] - 0.5 * x[:, 1]
          + 0.3 * rng.randn(n)) > 0).astype(np.float32)
    return Dataset.from_arrays(x, y, max_bin=63)


def _mk(ds, tl="serial", extra=None):
    p = {"objective": "binary", "num_leaves": "15", "min_data_in_leaf": "20",
         "min_sum_hessian_in_leaf": "1.0", "learning_rate": "0.1",
         "goss": "true", "top_rate": "0.2", "other_rate": "0.2",
         "grow_policy": "depthwise", "tree_learner": tl}
    p.update(extra or {})
    cfg = OverallConfig()
    cfg.set(p, require_data=False)
    b = GBDT()
    learner = None if tl == "serial" else create_parallel_learner(cfg)
    b.init(cfg.boosting_config, ds,
           create_objective(cfg.objective_type, cfg.objective_config),
           learner=learner)
    return b


def _assert_models_equal(a, b, tag):
    assert len(a.models) == len(b.models), tag
    for k, (t1, t2) in enumerate(zip(a.models, b.models)):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature,
                                      err_msg=f"{tag} tree {k}")
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin,
                                      err_msg=f"{tag} tree {k}")
        np.testing.assert_array_equal(np.asarray(t1.leaf_value),
                                      np.asarray(t2.leaf_value),
                                      err_msg=f"{tag} tree {k}")
    np.testing.assert_array_equal(np.asarray(a.score), np.asarray(b.score),
                                  err_msg=tag)


def test_goss_no_longer_excludes_chunking(goss_ds):
    b = _mk(goss_ds)
    assert b.chunk_supported(False)
    assert b.chunkable_for(False)


@pytest.mark.parametrize("hd", ["float32", "int8"])
def test_goss_fused_chunk_equals_per_iteration(goss_ds, hd):
    b1 = _mk(goss_ds, extra={"hist_dtype": hd})
    b2 = _mk(goss_ds, extra={"hist_dtype": hd})
    for _ in range(6):
        b1.train_one_iter(is_eval=False)
    b2.train_chunk(6)
    b2.flush_pipeline()
    _assert_models_equal(b1, b2, "goss chunk == per-iteration %s" % hd)


def test_goss_dp_chunk_equals_serial(goss_ds):
    # the acceptance row: GOSS under single-process DP == serial GOSS —
    # the gathered-score selection reproduces the serial draw exactly,
    # and the int8 histogram chain keeps the result bit-identical
    bs = _mk(goss_ds, extra={"hist_dtype": "int8"})
    bs.train_chunk(6)
    bs.flush_pipeline()
    bd = _mk(goss_ds, "data", {"num_machines": "4", "hist_dtype": "int8"})
    bd.train_chunk(6)
    bd.flush_pipeline()
    _assert_models_equal(bs, bd, "goss DP chunk == serial chunk (int8)")


def test_goss_dp_per_iteration_equals_serial(goss_ds):
    bs = _mk(goss_ds, extra={"hist_dtype": "int8",
                             "grow_policy": "leafwise"})
    bd = _mk(goss_ds, "data", {"num_machines": "4", "hist_dtype": "int8",
                               "grow_policy": "leafwise"})
    for _ in range(3):
        bs.train_one_iter(is_eval=False)
        bd.train_one_iter(is_eval=False)
    _assert_models_equal(bs, bd, "goss DP per-iter == serial per-iter")


def test_goss_hybrid_chunk_equals_serial(goss_ds):
    # the 2-D learners inherit the DP chunk program — GOSS composes with
    # the ownership mesh
    bs = _mk(goss_ds, extra={"hist_dtype": "int8"})
    bs.train_chunk(4)
    bs.flush_pipeline()
    bh = _mk(goss_ds, "hybrid", {"num_machines": "4",
                                 "feature_shards": "2",
                                 "hist_dtype": "int8"})
    bh.train_chunk(4)
    bh.flush_pipeline()
    _assert_models_equal(bs, bh, "goss hybrid chunk == serial chunk")


def test_goss_dispatches_through_chunk_program(goss_ds):
    # the acceptance pin: with goss=true, run_training routes through
    # the fused chunk program — no per-iteration grow programs appear in
    # the costmodel inventory
    telemetry.enable()
    telemetry.reset()
    try:
        b = _mk(goss_ds)
        b.run_training(8, is_eval=False)
        grow_progs = costmodel.phase_program_records("grow")
        chunk_progs = costmodel.phase_program_records("train_chunk")
    finally:
        telemetry.disable()
        telemetry.reset()
    assert len(chunk_progs) >= 1
    assert len(grow_progs) == 0, [r["name"] for r in grow_progs]
    assert len(b.models) == 8


def test_goss_per_iteration_multiprocess_guard(goss_ds):
    # the precise fatal: per-iteration multi-process GOSS is the one
    # still-unsupported case (the chunk path serves multi-process)
    b = _mk(goss_ds)
    b._host_inputs = True
    with pytest.raises(LightGBMError, match="per-iteration multi-process"):
        b._goss_masks(np.zeros((1, 4), np.float32),
                      np.zeros((1, 4), np.float32))
