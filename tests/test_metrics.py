"""Metric tests vs manual computations."""
import numpy as np
import pytest

from lightgbm_tpu.config import MetricConfig
from lightgbm_tpu.io.metadata import Metadata
from lightgbm_tpu.metrics import create_metric
from lightgbm_tpu.metrics.dcg import DCGCalculator


def _meta(label, weights=None, boundaries=None):
    m = Metadata()
    m.set_label(np.asarray(label, np.float32))
    if weights is not None:
        m.weights = np.asarray(weights, np.float32)
    if boundaries is not None:
        m.query_boundaries = np.asarray(boundaries, np.int32)
        m._load_query_weights()
    return m


def test_l2_reports_rmse():
    metric = create_metric("l2", MetricConfig())
    metric.init("t", _meta([0.0, 0.0]), 2)
    # errors 1, 3 → mse 5 → rmse sqrt(5) (regression_metric.hpp:100-103)
    assert metric.eval(np.array([1.0, 3.0]))[0] == pytest.approx(np.sqrt(5))


def test_l1():
    metric = create_metric("l1", MetricConfig())
    metric.init("t", _meta([1.0, -1.0]), 2)
    assert metric.eval(np.array([2.0, 1.0]))[0] == pytest.approx(1.5)


def test_binary_logloss():
    metric = create_metric("binary_logloss", MetricConfig())
    label = np.array([1.0, 0.0])
    metric.init("t", _meta(label), 2)
    score = np.array([0.5, -0.5])
    prob = 1 / (1 + np.exp(-2 * score))
    expected = np.mean([-np.log(prob[0]), -np.log(1 - prob[1])])
    assert metric.eval(score)[0] == pytest.approx(expected, rel=1e-6)


def test_binary_error():
    metric = create_metric("binary_error", MetricConfig())
    metric.init("t", _meta([1.0, 1.0, 0.0, 0.0]), 4)
    # scores: +,-,+,- → predictions 1,0,1,0 → errors at idx 1,2
    assert metric.eval(np.array([1.0, -1.0, 1.0, -1.0]))[0] == pytest.approx(0.5)


def test_auc_perfect_and_random():
    metric = create_metric("auc", MetricConfig())
    label = np.array([1.0, 1.0, 0.0, 0.0])
    metric.init("t", _meta(label), 4)
    assert metric.eval(np.array([4.0, 3.0, 2.0, 1.0]))[0] == pytest.approx(1.0)
    assert metric.eval(np.array([1.0, 2.0, 3.0, 4.0]))[0] == pytest.approx(0.0)
    # all-tied scores → AUC 0.5
    assert metric.eval(np.zeros(4))[0] == pytest.approx(0.5)


def test_auc_matches_pairwise_definition():
    rng = np.random.RandomState(0)
    label = (rng.rand(300) > 0.6).astype(np.float32)
    score = rng.randn(300)
    metric = create_metric("auc", MetricConfig())
    metric.init("t", _meta(label), 300)
    got = metric.eval(score)[0]
    pos = score[label == 1]
    neg = score[label == 0]
    cmp = (pos[:, None] > neg[None, :]).sum() + 0.5 * (pos[:, None] == neg[None, :]).sum()
    expected = cmp / (pos.size * neg.size)
    assert got == pytest.approx(expected, rel=1e-9)


def test_multi_metrics():
    cfg = MetricConfig()
    cfg.num_class = 3
    label = np.array([0.0, 1.0, 2.0])
    score = np.array([[2.0, 0.1, 0.0],
                      [0.1, 0.2, 0.1],
                      [0.0, 0.1, 3.0]])  # [K, N], argmax = 0, 1, 2
    err = create_metric("multi_error", cfg)
    err.init("t", _meta(label), 3)
    assert err.eval(score.reshape(-1))[0] == pytest.approx(0.0)
    ll = create_metric("multi_logloss", cfg)
    ll.init("t", _meta(label), 3)
    z = np.exp(score - score.max(axis=0))
    p = z / z.sum(axis=0)
    expected = -np.mean([np.log(p[0, 0]), np.log(p[1, 1]), np.log(p[2, 2])])
    assert ll.eval(score.reshape(-1))[0] == pytest.approx(expected, rel=1e-6)


def test_ndcg():
    cfg = MetricConfig()
    cfg.eval_at = [1, 2]
    metric = create_metric("ndcg", cfg)
    label = np.array([2.0, 1.0, 0.0, 1.0, 0.0])
    metric.init("t", _meta(label, boundaries=[0, 3, 5]), 5)
    # perfect ordering → NDCG 1 at every k
    out = metric.eval(np.array([3.0, 2.0, 1.0, 2.0, 1.0]))
    assert out[0] == pytest.approx(1.0)
    assert out[1] == pytest.approx(1.0)


def test_ndcg_all_negative_query_counts_one():
    cfg = MetricConfig()
    cfg.eval_at = [1]
    metric = create_metric("ndcg", cfg)
    label = np.array([0.0, 0.0, 2.0, 0.0])
    metric.init("t", _meta(label, boundaries=[0, 2, 4]), 4)
    out = metric.eval(np.array([1.0, 0.0, 1.0, 0.0]))
    # query 1 all-negative → 1.0; query 2 perfect → 1.0 (rank_metric.hpp:98-101)
    assert out[0] == pytest.approx(1.0)


def test_dcg_calculator():
    gains = [0.0, 1.0, 3.0, 7.0]
    dcg = DCGCalculator(gains)
    label = np.array([3, 1, 2])
    # max DCG@3: sorted labels 3,2,1 → 7/log2(2)+3/log2(3)+1/log2(4)
    expected = 7 / np.log2(2) + 3 / np.log2(3) + 1 / np.log2(4)
    assert dcg.cal_max_dcg_at_k(3, label) == pytest.approx(expected)
    # DCG under score order [10, 5, 1] = label order 3,1,2
    got = dcg.cal_dcg([3], label, np.array([10.0, 5.0, 1.0]))[0]
    expected2 = 7 / np.log2(2) + 1 / np.log2(3) + 3 / np.log2(4)
    assert got == pytest.approx(expected2)
