"""Capability probe for ``pltpu.force_tpu_interpret_mode`` (ISSUE 6).

This container's jax (0.4.x) predates the TPU-interpret-mode context
manager, so every test that cross-checks a Pallas kernel against its XLA
oracle under interpretation fails on ENVIRONMENT (AttributeError at the
``with`` statement), not on code — the 8 red tests every tier-1 run has
carried since the kernels landed.  Same pattern as the PR-5 multiprocess-
on-CPU probe (tests/test_multiprocess_dp.py): probe ONCE, skip with the
real reason, and on a jax that ships the API (or a real TPU pod) the
tests run in full so a kernel regression is still visible there.

The probe goes beyond ``hasattr``: it runs a one-element pallas_call under
the context manager, so a present-but-broken interpret mode (partial API,
Mosaic-interpreter gaps) also reads as a clean skip with its own message.
"""
import numpy as np
import pytest


def _probe():
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
    except Exception as e:  # pragma: no cover - no pallas at all
        return False, "pallas unavailable: %r" % (e,)
    if not hasattr(pltpu, "force_tpu_interpret_mode"):
        return False, ("this jax's pallas.tpu has no "
                       "force_tpu_interpret_mode (API added in a later "
                       "jax than this container ships)")
    try:
        def k(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1

        with pltpu.force_tpu_interpret_mode():
            out = pl.pallas_call(
                k, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            )(jnp.zeros((8, 128), jnp.float32))
        if not np.allclose(np.asarray(out), 1.0):  # pragma: no cover
            return False, "interpret-mode pallas_call returned wrong data"
    except Exception as e:  # pragma: no cover - partial API
        return False, "interpret-mode pallas_call failed: %r" % (e,)
    return True, ""


INTERPRET_OK, INTERPRET_REASON = _probe()

requires_pltpu_interpret = pytest.mark.skipif(
    not INTERPRET_OK,
    reason="pltpu interpret mode unavailable on this jax: %s"
           % INTERPRET_REASON)
