"""Boosting-loop tests on synthetic data: learning works, model IO
round-trips, prediction paths agree."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import Dataset


def _train(x, y, params, **kw):
    ds = Dataset.from_arrays(x, y, max_bin=params.get("max_bin", 64))
    return lgb.train(params, ds, **kw), ds


BASE = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
        "min_sum_hessian_in_leaf": 1.0, "num_iterations": 10,
        "learning_rate": 0.2, "metric": "binary_logloss,auc"}


def test_binary_learning_reduces_loss(synthetic_binary):
    x, y = synthetic_binary
    booster, ds = _train(x, y, BASE)
    prob = booster.predict(x)
    ll = -np.mean(y * np.log(np.clip(prob, 1e-9, 1))
                  + (1 - y) * np.log(np.clip(1 - prob, 1e-9, 1)))
    assert ll < 0.55  # well below ln 2
    pred = (prob > 0.5).astype(np.float32)
    assert (pred == y).mean() > 0.8


def test_regression_learning(synthetic_regression):
    x, y = synthetic_regression
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 20, "learning_rate": 0.2, "metric": "l2"}
    booster, _ = _train(x, y, params)
    pred = booster.predict_raw(x)
    baseline = np.var(y)
    mse = np.mean((pred - y) ** 2)
    assert mse < 0.4 * baseline


def test_model_roundtrip_prediction_identical(tmp_path, synthetic_binary):
    x, y = synthetic_binary
    booster, _ = _train(x, y, BASE)
    path = str(tmp_path / "model.txt")
    booster.save_model_to_file(True, path)
    loaded = lgb.GBDT.from_model_file(path)
    np.testing.assert_allclose(loaded.predict_raw(x), booster.predict_raw(x),
                               rtol=1e-12)


def test_train_scores_match_predictor(synthetic_binary):
    """The incremental train-score path (leaf-id gather) must equal
    rescoring with the final model (the reference's two AddScore paths,
    score_updater.hpp:41-69)."""
    x, y = synthetic_binary
    params = dict(BASE, num_iterations=5)
    booster, ds = _train(x, y, params)
    incremental = np.asarray(booster.score[0])
    rescored = booster.predict_raw(x)
    np.testing.assert_allclose(incremental, rescored, rtol=1e-3, atol=1e-4)


def test_bagging_and_feature_fraction(synthetic_binary):
    x, y = synthetic_binary
    params = dict(BASE, bagging_fraction=0.5, bagging_freq=1,
                  feature_fraction=0.5, num_iterations=8)
    booster, _ = _train(x, y, params)
    prob = booster.predict(x)
    assert ((prob > 0.5) == y).mean() > 0.75


def test_early_stopping(synthetic_binary):
    x, y = synthetic_binary
    train_ds = Dataset.from_arrays(x[:1500], y[:1500], max_bin=64)
    rng = np.random.RandomState(0)
    # pure-noise validation labels → no sustained improvement → early stop
    valid_ds = Dataset.from_arrays(
        x[1500:], rng.randint(0, 2, 500).astype(np.float32), max_bin=64)
    params = dict(BASE, num_iterations=60, early_stopping_round=3,
                  metric="binary_logloss")
    booster = lgb.train(params, train_ds, valid_sets=[valid_ds])
    assert len(booster.models) < 60


def test_multiclass_training():
    rng = np.random.RandomState(5)
    n, f, k = 1200, 6, 3
    x = rng.randn(n, f)
    y = np.argmax(x[:, :k] + 0.5 * rng.randn(n, k), axis=1).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 8, "learning_rate": 0.3,
              "metric": "multi_logloss"}
    booster = lgb.train(params, ds)
    # trees interleaved per class (gbdt.cpp:175-195)
    assert len(booster.models) == 8 * 3
    probs = booster.predict_multiclass(x)
    assert probs.shape == (n, 3)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-6)
    assert (probs.argmax(axis=1) == y).mean() > 0.6


def test_lambdarank_training():
    rng = np.random.RandomState(9)
    nq, qsize = 40, 12
    n = nq * qsize
    x = rng.randn(n, 5)
    rel = np.clip((x[:, 0] + 0.3 * rng.randn(n)) * 1.2 + 1, 0, 3).round()
    boundaries = np.arange(0, n + 1, qsize)
    ds = Dataset.from_arrays(x, rel.astype(np.float32), max_bin=32,
                             query_boundaries=boundaries)
    params = {"objective": "lambdarank", "num_leaves": 15,
              "min_data_in_leaf": 10, "min_sum_hessian_in_leaf": 1e-3,
              "num_iterations": 10, "learning_rate": 0.1, "metric": "ndcg"}
    booster = lgb.train(params, ds)
    from lightgbm_tpu.config import MetricConfig
    from lightgbm_tpu.metrics import create_metric
    m = create_metric("ndcg", MetricConfig())
    m.init("t", ds.metadata, n)
    ndcg = m.eval(booster.predict_raw(x))
    assert ndcg[-1] > 0.65


def test_continued_training_via_init_score(synthetic_binary):
    x, y = synthetic_binary
    booster1, _ = _train(x, y, dict(BASE, num_iterations=5))
    init = booster1.predict_raw(x).astype(np.float32)
    ds2 = Dataset.from_arrays(x, y, max_bin=64)
    ds2.metadata.init_score = init
    booster2 = lgb.train(dict(BASE, num_iterations=5), ds2)
    total = init + booster2.predict_raw(x)
    prob = 1 / (1 + np.exp(-2 * total))
    ll = -np.mean(y * np.log(np.clip(prob, 1e-9, 1))
                  + (1 - y) * np.log(np.clip(1 - prob, 1e-9, 1)))
    # continued training improves over the 5-tree model alone
    prob1 = booster1.predict(x)
    ll1 = -np.mean(y * np.log(np.clip(prob1, 1e-9, 1))
                   + (1 - y) * np.log(np.clip(1 - prob1, 1e-9, 1)))
    assert ll < ll1


def test_chunked_training_matches_per_iter(synthetic_binary):
    """train_chunk(k) must reproduce k train_one_iter calls exactly: same
    trees, same scores, same RNG stream for bagging/feature sampling."""
    x, y = synthetic_binary
    params = dict(BASE, num_iterations=6, metric="",
                  bagging_fraction=0.7, bagging_freq=2, bagging_seed=3,
                  feature_fraction=0.6)
    del params["metric"]
    ds = Dataset.from_arrays(x, y, max_bin=64)

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    def make():
        cfg = OverallConfig()
        cfg.set({k: str(v) for k, v in params.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, ds, obj)
        return b

    b1 = make()
    for _ in range(6):
        b1.train_one_iter(is_eval=False)

    b2 = make()
    assert b2.supports_chunking
    stop = b2.train_chunk(4)
    assert not stop
    b2.train_chunk(2)

    assert len(b1.models) == len(b2.models) == 6
    for t1, t2 in zip(b1.models, b2.models):
        assert t1.num_leaves == t2.num_leaves
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(b1.score), np.asarray(b2.score),
                               rtol=1e-3, atol=1e-4)


def test_chunked_training_depthwise(synthetic_binary):
    x, y = synthetic_binary
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
              "min_sum_hessian_in_leaf": 1.0, "num_iterations": 4,
              "learning_rate": 0.2, "grow_policy": "depthwise"}
    ds = Dataset.from_arrays(x, y, max_bin=64)
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    def make():
        cfg = OverallConfig()
        cfg.set({k: str(v) for k, v in params.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, ds, obj)
        return b

    b1 = make()
    for _ in range(4):
        b1.train_one_iter(is_eval=False)
    b2 = make()
    b2.train_chunk(4)
    assert len(b1.models) == len(b2.models) == 4
    for t1, t2 in zip(b1.models, b2.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)


def test_chunked_training_multiclass(synthetic_binary):
    x, _ = synthetic_binary
    rng = np.random.RandomState(5)
    y = rng.randint(0, 3, size=x.shape[0]).astype(np.float32)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1.0,
              "num_iterations": 3, "learning_rate": 0.2}
    ds = Dataset.from_arrays(x, y, max_bin=32)
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    def make():
        cfg = OverallConfig()
        cfg.set({k: str(v) for k, v in params.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, ds, obj)
        return b

    b1 = make()
    for _ in range(3):
        b1.train_one_iter(is_eval=False)
    b2 = make()
    b2.train_chunk(3)
    assert len(b1.models) == len(b2.models) == 9
    for t1, t2 in zip(b1.models, b2.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)


def test_chunked_degenerate_stop_matches_per_iter():
    """A mid-chunk can't-split-anymore stop must leave models, iter, score
    and RNG streams exactly as the per-iteration path would."""
    rng = np.random.RandomState(0)
    n = 60
    bit = (np.arange(n) % 2).astype(np.float64)       # exactly two values
    x = np.stack([bit, bit, bit], axis=1)             # every feature fits y
    y = bit.astype(np.float32)
    # y IS each feature: with lr=1 the first tree fits it exactly (leaf
    # outputs are in-bag residual means over constant-y leaves), so every
    # later tree has all-zero gradients, gain 0, and degenerates ->
    # mid-chunk stop (feature_fraction can drop any column, they all work)
    params = {"objective": "regression", "num_leaves": 2,
              "min_data_in_leaf": 5, "min_sum_hessian_in_leaf": 1e-3,
              "num_iterations": 8, "learning_rate": 1.0,
              "bagging_fraction": 0.9, "bagging_freq": 1, "bagging_seed": 1,
              "feature_fraction": 0.99}
    ds = Dataset.from_arrays(x, y, max_bin=16)
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    def make():
        cfg = OverallConfig()
        cfg.set({k: str(v) for k, v in params.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, ds, obj)
        return b

    b1 = make()
    stopped1 = False
    for _ in range(8):
        if b1.train_one_iter(is_eval=False):
            stopped1 = True
            break
    b2 = make()
    stopped2 = b2.train_chunk(8)
    if not stopped1:
        pytest.skip("fixture did not produce a degenerate tree")
    assert stopped2
    assert b1.iter == b2.iter
    assert len(b1.models) == len(b2.models)
    for t1, t2 in zip(b1.models, b2.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
    np.testing.assert_allclose(np.asarray(b1.score), np.asarray(b2.score),
                               rtol=1e-4, atol=1e-5)
    # RNG streams line up for continued training
    np.testing.assert_array_equal(b1._bag_rng.randint(0, 1 << 30, 5),
                                  b2._bag_rng.randint(0, 1 << 30, 5))


def test_run_training_tail_truncation(synthetic_binary):
    """num_iterations not divisible by chunk_size: the tail is served by the
    full-size program and rolled back — models, iter, score and RNG must
    match the per-iteration path."""
    x, y = synthetic_binary
    # depthwise: run_training only chunks the depthwise policy (the
    # leaf-wise fori_loop inside the scan crashes the TPU runtime)
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
              "min_sum_hessian_in_leaf": 1.0, "num_iterations": 5,
              "learning_rate": 0.2, "bagging_fraction": 0.8,
              "bagging_freq": 2, "bagging_seed": 9, "feature_fraction": 0.7,
              "grow_policy": "depthwise"}
    # chunk_size=4 < num_iterations=5 so the chunked branch runs: one full
    # chunk then a tail chunk(4, limit=1) exercising the rollback path
    ds = Dataset.from_arrays(x, y, max_bin=64)
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    def make():
        cfg = OverallConfig()
        cfg.set({k: str(v) for k, v in params.items()}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, ds, obj)
        return b

    b1 = make()
    for _ in range(5):
        b1.train_one_iter(is_eval=False)
    b2 = make()
    b2.run_training(5, is_eval=False, chunk_size=4)
    assert b1.iter == b2.iter == 5
    assert len(b1.models) == len(b2.models) == 5
    for t1, t2 in zip(b1.models, b2.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_array_equal(t1.threshold_bin, t2.threshold_bin)
    np.testing.assert_allclose(np.asarray(b1.score), np.asarray(b2.score),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_array_equal(b1._bag_rng.randint(0, 1 << 30, 5),
                                  b2._bag_rng.randint(0, 1 << 30, 5))
    np.testing.assert_array_equal(b1._feat_rngs[0].randint(0, 1 << 30, 5),
                                  b2._feat_rngs[0].randint(0, 1 << 30, 5))


def _make_booster(ds, params, valid=None):
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.metrics import create_metric
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    cfg = OverallConfig()
    cfg.set({k: str(v) for k, v in params.items()}, require_data=False)
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    train_metrics = [m for m in (create_metric(t, cfg.metric_config)
                                 for t in cfg.metric_types) if m is not None]
    b.init(cfg.boosting_config, ds, obj, train_metrics)
    if valid is not None:
        metrics = [m for m in (create_metric(t, cfg.metric_config)
                               for t in cfg.metric_types) if m is not None]
        b.add_valid_dataset(valid, metrics)
    return b


def test_chunked_eval_matches_per_iter(synthetic_binary):
    """Chunked training WITH metrics/valid sets: same models, same valid
    scores, same early-stop bookkeeping as the per-iteration path."""
    x, y = synthetic_binary
    xt, yt = x[:1500], y[:1500]
    xv, yv = x[1500:], y[1500:]
    params = dict(BASE, num_iterations=6, grow_policy="depthwise")
    ds = Dataset.from_arrays(xt, yt, max_bin=64)
    dsv = Dataset.from_arrays(xv, yv, max_bin=64, reference=ds)

    b1 = _make_booster(ds, params, valid=dsv)
    for _ in range(6):
        if b1.train_one_iter(is_eval=True):
            break

    b2 = _make_booster(ds, params, valid=dsv)
    assert b2.supports_chunking and b2.chunkable_for(True)
    b2.run_training(6, is_eval=True, chunk_size=3)

    assert len(b1.models) == len(b2.models)
    for t1, t2 in zip(b1.models, b2.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
    np.testing.assert_allclose(
        np.asarray(b1.valid_datasets[0]["score"]),
        np.asarray(b2.valid_datasets[0]["score"]), rtol=1e-3, atol=1e-4)
    # early-stop bookkeeping tracked identically (within device-f32 noise)
    np.testing.assert_allclose(b1.best_score[0], b2.best_score[0], rtol=1e-4)


def test_chunked_early_stopping_matches_per_iter(synthetic_binary):
    """Early stopping fires at the same iteration with the same model
    pop-back whether evaluation runs per-iteration on host or in-chunk on
    device."""
    x, y = synthetic_binary
    # tiny noisy valid set -> early overfitting -> stop triggers
    xt, yt = x[:1800], y[:1800]
    rng = np.random.RandomState(0)
    xv = x[1800:]
    yv = rng.randint(0, 2, size=len(xv)).astype(np.float32)  # pure noise
    params = dict(BASE, num_iterations=40, learning_rate=0.4,
                  early_stopping_round=3, metric="binary_logloss",
                  grow_policy="depthwise")
    ds = Dataset.from_arrays(xt, yt, max_bin=64)
    dsv = Dataset.from_arrays(xv, yv, max_bin=64, reference=ds)

    b1 = _make_booster(ds, params, valid=dsv)
    stopped1 = False
    for _ in range(40):
        if b1.train_one_iter(is_eval=True):
            stopped1 = True
            break

    b2 = _make_booster(ds, params, valid=dsv)
    assert b2.supports_chunking and b2.chunkable_for(True)
    b2.run_training(40, is_eval=True, chunk_size=5)

    if not stopped1:
        pytest.skip("fixture did not early-stop")
    assert b1.iter == b2.iter
    assert len(b1.models) == len(b2.models)
    for t1, t2 in zip(b1.models, b2.models):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
    np.testing.assert_array_equal(b1.best_iter[0], b2.best_iter[0])


def test_device_batch_prediction_exact(synthetic_binary):
    """The device ensemble predictor (rank-encoded thresholds + integer
    replay) must route every row exactly like the host float64 tree walk."""
    x, y = synthetic_binary
    booster, ds = _train(x, y, dict(BASE, num_iterations=8))
    models = booster.models
    host = np.zeros(x.shape[0])
    for t in models:
        host += t.predict(x)
    dev = booster._predict_scores_device(x, models)[0]
    # same leaves -> identical sums up to f32 accumulation of leaf values
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)

    # threshold gate: force the device path through the public API
    old = booster._DEVICE_PREDICT_THRESHOLD
    try:
        GBDT = type(booster)
        GBDT._DEVICE_PREDICT_THRESHOLD = 1
        via_api = booster.predict_raw(x)
    finally:
        GBDT._DEVICE_PREDICT_THRESHOLD = old
    np.testing.assert_allclose(via_api, host, rtol=1e-5, atol=1e-6)

    # values exactly ON a threshold route left identically
    t0 = models[0]
    f0 = int(t0.split_feature_real[0])
    xe = x[:64].copy()
    xe[:, f0] = t0.threshold[0]          # exact tie with the threshold
    host_e = np.zeros(64)
    for t in models:
        host_e += t.predict(xe)
    dev_e = booster._predict_scores_device(xe, models)[0]
    np.testing.assert_allclose(dev_e, host_e, rtol=1e-5, atol=1e-6)


def test_device_prediction_nan_routes_left(synthetic_binary):
    """NaN feature values must route left on the device path exactly like
    the host walk's `value > threshold` (False for NaN)."""
    x, y = synthetic_binary
    booster, _ = _train(x, y, dict(BASE, num_iterations=4))
    xe = x[:128].copy()
    xe[:, :3] = np.nan
    host = np.zeros(128)
    for t in booster.models:
        host += t.predict(xe)
    dev = booster._predict_scores_device(xe, booster.models)[0]
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


def test_device_leaf_index_matches_host(synthetic_binary):
    x, y = synthetic_binary
    booster, _ = _train(x, y, dict(BASE, num_iterations=4))
    host = booster.predict_leaf_index(x)
    from lightgbm_tpu.models.gbdt import GBDT as _G
    old = _G._DEVICE_PREDICT_THRESHOLD
    try:
        _G._DEVICE_PREDICT_THRESHOLD = 1
        dev = booster.predict_leaf_index(x)
    finally:
        _G._DEVICE_PREDICT_THRESHOLD = old
    np.testing.assert_array_equal(host, dev)


@pytest.mark.parametrize("policy", ["leafwise", "depthwise"])
def test_hist_tuning_knobs_train(synthetic_binary, policy):
    """hist_chunk / hist_dtype are honored on both grow policies: a bf16
    histogram with a tiny scan chunk still learns and predicts sanely."""
    x, y = synthetic_binary
    params = dict(BASE, grow_policy=policy, hist_chunk=512,
                  hist_dtype="bfloat16")
    booster, _ = _train(x, y, params)
    prob = booster.predict(x)
    assert np.all(np.isfinite(prob)) and prob.min() >= 0 and prob.max() <= 1
    pred = (prob > 0.5).astype(np.float32)
    assert (pred == y).mean() > 0.8


def test_hist_chunk_predictions_close(synthetic_binary):
    """Chunk size only reorders f32 partial-histogram adds; the model may
    differ in last-bit tie-breaks but predictions must stay close."""
    x, y = synthetic_binary
    b1, _ = _train(x, y, dict(BASE, hist_chunk=512))
    b2, _ = _train(x, y, dict(BASE, hist_chunk=4096))
    p1, p2 = b1.predict(x), b2.predict(x)
    assert np.mean(np.abs(p1 - p2)) < 0.02
