"""Streaming ingestion tier tests (ISSUE 8, io/streaming.py +
ops/sampling.py): streaming==resident bit-identity (bin codes, mappers,
metadata, trained model text) on text and binary-cache sources,
chunk-boundary edge cases, pinned-sample determinism, unified reader
semantics, device-bagging==oracle, GOSS selection shape/scaling, and
config parsing/rejects."""
import os

import numpy as np
import pytest

import jax

from lightgbm_tpu.config import IOConfig, OverallConfig
from lightgbm_tpu.io import parser as parser_mod
from lightgbm_tpu.io import streaming
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.utils.log import LightGBMError


def _write_csv(path, n, f=5, seed=0, label_fn=None):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    y = ((x[:, 0] + 0.5 * x[:, 1] > 0).astype(int)
         if label_fn is None else label_fn(x))
    with open(path, "w") as fh:
        for i in range(n):
            fh.write(",".join([str(y[i])]
                              + ["%.6f" % v for v in x[i]]) + "\n")
    return str(path)


def _load(path, **kw):
    return Dataset.load_train(IOConfig(data_filename=str(path), **kw))


def _assert_datasets_identical(res, stm):
    """Resident vs streamed Dataset: mappers, codes, metadata — bitwise."""
    assert res.num_data == stm.num_data
    assert res.num_total_features == stm.num_total_features
    assert list(res.used_feature_map.items()) == \
        list(stm.used_feature_map.items())
    assert len(res.bin_mappers) == len(stm.bin_mappers)
    for m1, m2 in zip(res.bin_mappers, stm.bin_mappers):
        assert m1.to_bytes() == m2.to_bytes()
    stm_bins = (np.asarray(stm.device_bins) if stm.bins is None
                else stm.bins)
    np.testing.assert_array_equal(res.bins, stm_bins)
    assert res.bins.dtype == stm_bins.dtype
    np.testing.assert_array_equal(res.metadata.label, stm.metadata.label)
    if res.metadata.weights is None:
        assert stm.metadata.weights is None
    else:
        np.testing.assert_array_equal(res.metadata.weights,
                                      stm.metadata.weights)
    if res.metadata.query_boundaries is None:
        assert stm.metadata.query_boundaries is None
    else:
        np.testing.assert_array_equal(res.metadata.query_boundaries,
                                      stm.metadata.query_boundaries)


def _train(ds, **params):
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "num_iterations": "4",
             "num_leaves": "8", "min_data_in_leaf": "5",
             **{k: str(v) for k, v in params.items()}},
            require_data=False)
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, ds, obj)
    b.run_training(int(cfg.boosting_config.num_iterations), False)
    return b


def _model_text(b):
    return "".join(t.to_string() for t in b.models)


# ------------------------------------------------- streaming == resident


@pytest.mark.parametrize("n,chunk", [
    (50, 200),     # N below one chunk
    (128, 128),    # N exactly one chunk
    (300, 128),    # N above one chunk, ragged tail (300 = 2*128 + 44)
    (256, 128),    # exact multiple, no tail
])
def test_streaming_bit_identity_text(tmp_path, n, chunk):
    path = _write_csv(tmp_path / "t.csv", n)
    res = _load(path, streaming="false")
    stm = _load(path, streaming="true", ingest_chunk_rows=chunk)
    assert stm.bins is None and stm.device_bins is not None
    _assert_datasets_identical(res, stm)


def test_streaming_trained_model_text_identical(tmp_path):
    path = _write_csv(tmp_path / "t.csv", 400)
    res = _load(path, streaming="false")
    stm = _load(path, streaming="true", ingest_chunk_rows=128)
    assert _model_text(_train(res)) == _model_text(_train(stm))


def test_streaming_pinned_sample_beyond_sample_cnt(tmp_path,
                                                   monkeypatch):
    """Past SAMPLE_CNT rows the binning sample is the pinned-index draw —
    mappers (and so codes) must still match the resident loader."""
    from lightgbm_tpu.io import dataset as dataset_mod
    monkeypatch.setattr(dataset_mod, "SAMPLE_CNT", 100)
    path = _write_csv(tmp_path / "t.csv", 350)
    res = _load(path, streaming="false")
    stm = _load(path, streaming="true", ingest_chunk_rows=96)
    _assert_datasets_identical(res, stm)


def test_pinned_sample_indices_deterministic():
    a = streaming.pinned_sample_indices(1000, 7, 100)
    b = streaming.pinned_sample_indices(1000, 7, 100)
    np.testing.assert_array_equal(a, b)
    assert a.size == 100 and np.all(np.diff(a) > 0)
    # the resident loader's exact draw, single-homed
    rng = np.random.RandomState(7)
    np.testing.assert_array_equal(
        a, np.sort(rng.choice(1000, 100, replace=False)))
    assert streaming.pinned_sample_indices(50, 7, 100) is None


def test_streaming_sharded_load_matches_resident(tmp_path):
    """Multi-machine parse identity: every rank's streamed shard equals
    the resident loader's shard (same shard draw, same metadata
    partition)."""
    path = _write_csv(tmp_path / "t.csv", 240)
    for rank in range(3):
        res = Dataset.load_train(
            IOConfig(data_filename=path, streaming="false"),
            rank=rank, num_machines=3)
        stm = Dataset.load_train(
            IOConfig(data_filename=path, streaming="true",
                     ingest_chunk_rows=64),
            rank=rank, num_machines=3)
        # multi-process streamed loads keep the binned LOCAL shard
        # host-side (gbdt's global NamedSharding lift consumes it)
        assert stm.device_bins is None and stm.bins is not None
        assert res.num_data == stm.num_data
        np.testing.assert_array_equal(res.bins, stm.bins)
        np.testing.assert_array_equal(res.metadata.label,
                                      stm.metadata.label)


def test_streaming_weight_column(tmp_path):
    path = tmp_path / "w.csv"
    with open(path, "w") as f:
        f.write("lbl,f1,wgt,f2\n")
        for i in range(60):
            f.write("%d,%.3f,%.3f,%.3f\n"
                    % (i % 2, i * 0.1, 1.0 + i, 3.0 - i * 0.1))
    kw = dict(has_header=True, label_column="name:lbl",
              weight_column="name:wgt")
    res = _load(path, streaming="false", **kw)
    stm = _load(path, streaming="true", ingest_chunk_rows=16, **kw)
    _assert_datasets_identical(res, stm)
    np.testing.assert_allclose(stm.metadata.weights,
                               [1.0 + i for i in range(60)])


def test_streaming_shard_rows_dp_reduce_scatter_bit_identity(tmp_path):
    """Single-process DP (8 virtual devices): a streamed load with
    shard_rows=True places the device matrix row-sharded over the
    (data,) mesh axis, and training under the reduce_scatter ownership
    schedule reproduces the resident loader's model text exactly."""
    path = _write_csv(tmp_path / "t.csv", 640, f=6)
    res = _load(path, streaming="false")
    stm = Dataset.load_train(
        IOConfig(data_filename=path, streaming="true",
                 ingest_chunk_rows=96),
        shard_rows=True)
    assert stm.bins is None and stm.device_bins is not None
    # 640 rows divide the 8-device mesh: every device holds one [F, 80]
    # row shard (explicit NamedSharding placement, not replication)
    shards = stm.device_bins.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape == (stm.device_bins.shape[0], 80)
               for s in shards)
    _assert_datasets_identical(res, stm)
    assert _model_text(_train_dp8(res, 4)) == \
        _model_text(_train_dp8(stm, 4))


def _train_dp8(ds, iters=3):
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "num_iterations": str(iters),
             "num_leaves": "8", "min_data_in_leaf": "5",
             "tree_learner": "data", "num_machines": "8",
             "dp_schedule": "reduce_scatter"}, require_data=False)
    from lightgbm_tpu.parallel import create_parallel_learner
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, ds, obj,
           learner=create_parallel_learner(cfg))
    b.run_training(iters, False)
    return b


def test_streaming_shard_rows_nondividing_replicates_on_learner_mesh(
        tmp_path):
    """A row count that does NOT divide the mesh must fall back to
    replication on the LEARNER's 8-device mesh (not a one-device commit,
    which the DP shard_map would reject as incompatible devices) — and
    still train identically to the resident loader."""
    path = _write_csv(tmp_path / "t.csv", 636, f=6)   # 636 % 8 != 0
    res = _load(path, streaming="false")
    stm = Dataset.load_train(
        IOConfig(data_filename=path, streaming="true",
                 ingest_chunk_rows=100),
        shard_rows=True, shard_devices=8)
    assert stm.device_bins is not None
    assert len(stm.device_bins.sharding.mesh.devices.reshape(-1)) == 8
    _assert_datasets_identical(res, stm)
    assert _model_text(_train_dp8(res)) == _model_text(_train_dp8(stm))


def test_streaming_cache_rerun_keeps_shard_rows(tmp_path):
    """The binary-cache branch must thread shard_rows/shard_devices: a
    cached rerun of a single-process DP run gets the same row-sharded
    placement (and trains) instead of a one-device commit crash."""
    path = _write_csv(tmp_path / "t.csv", 640, f=6)
    _load(path, streaming="true", is_save_binary_file=True)
    stm = Dataset.load_train(
        IOConfig(data_filename=path, streaming="true",
                 ingest_chunk_rows=128),
        shard_rows=True, shard_devices=8)          # hits the .bin branch
    assert stm.device_bins is not None
    shards = stm.device_bins.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape == (stm.device_bins.shape[0], 80)
               for s in shards)
    assert len(_train_dp8(stm).models) == 3
    os.unlink(path + ".bin")


def test_streaming_multi_process_stays_host_side(tmp_path, monkeypatch):
    """Multi-process runs that load with num_machines=1 (the
    feature-parallel learner) must NOT get a device-resident dataset:
    gbdt's host-input paths lift HOST arrays.  single_process() gates
    device residency on the process count."""
    path = _write_csv(tmp_path / "t.csv", 200)
    monkeypatch.setattr(streaming, "single_process", lambda: False)
    stm = _load(path, streaming="true", ingest_chunk_rows=64)
    assert stm.device_bins is None and stm.bins is not None
    res = _load(path, streaming="false")
    np.testing.assert_array_equal(res.bins, stm.bins)


def test_streamed_mixed_bin_packs_and_releases_device_matrix(tmp_path):
    """Mixed-bin packing on a streamed dataset reorders via one device
    gather and then RELEASES the unpacked [F, N] original (keeping both
    would double peak HBM at the scale streaming exists for); model text
    still matches the resident loader, and a second init on the consumed
    dataset fails loudly instead of crashing."""
    rng = np.random.RandomState(4)
    path = tmp_path / "m.csv"
    with open(path, "w") as f:
        for i in range(300):
            f.write("%d,%d,%d,%.6f,%.6f\n"
                    % (rng.randint(2), rng.randint(5), rng.randint(3),
                       rng.randn(), rng.randn()))
    res = _load(path, streaming="false")
    stm = _load(path, streaming="true", ingest_chunk_rows=90)
    b_stm = _train(stm)
    assert b_stm._pack_spec is not None   # narrow + wide classes present
    assert _model_text(_train(res)) == _model_text(b_stm)
    assert stm.device_bins is None and stm.device_bins_consumed
    with pytest.raises(LightGBMError):
        _train(stm)


# ------------------------------------------------------- binary caches


def test_streaming_cache_write_byte_identical(tmp_path):
    """is_save_binary_file under streaming writes the native cache through
    a pass-2 memmap — byte-identical to the resident save_binary."""
    path = _write_csv(tmp_path / "t.csv", 300)
    _load(path, streaming="false", is_save_binary_file=True)
    resident_cache = open(path + ".bin", "rb").read()
    os.unlink(path + ".bin")
    _load(path, streaming="true", ingest_chunk_rows=77,
          is_save_binary_file=True)
    assert open(path + ".bin", "rb").read() == resident_cache


def test_streaming_cache_load_bit_identity(tmp_path):
    path = _write_csv(tmp_path / "t.csv", 300)
    res = _load(path, streaming="false", is_save_binary_file=True)
    stm = _load(path, streaming="true", ingest_chunk_rows=64)  # reads .bin
    assert stm.device_bins is not None
    _assert_datasets_identical(res, stm)
    assert _model_text(_train(res)) == _model_text(_train(stm))


def test_streamed_dataset_save_binary_rejected(tmp_path):
    """A streamed dataset has no host bin matrix; a post-hoc save_binary
    must fail loudly (the cache is written during ingestion instead)."""
    path = _write_csv(tmp_path / "t.csv", 100)
    stm = _load(path, streaming="true", ingest_chunk_rows=64)
    with pytest.raises(LightGBMError):
        stm.save_binary(str(tmp_path / "out.bin"))


# ------------------------------------------------ reader unification


def test_readers_one_semantics(tmp_path):
    """read_lines is implemented ON TOP of read_line_chunks: identical
    row sets on blank lines, headers, and splitlines-only separators
    (\\f, \\v, \\u2028 are NOT row boundaries for file iteration — the
    old str.splitlines-based read_lines split on them)."""
    path = tmp_path / "zoo.txt"
    content = ("header,line\n"
               "\n"                      # first data line blank
               "1,2\fX\n"                # \f inside a row, not a boundary
               "\n"
               "3,4 5\n"            #   inside a row
               "5,6\n"
               "\n")
    with open(path, "w") as f:
        f.write(content)
    for skip in (False, True):
        lines = parser_mod.read_lines(str(path), skip_header=skip)
        chunked = [ln for ch in parser_mod.read_line_chunks(
            str(path), skip_header=skip, chunk_lines=2) for ln in ch]
        assert lines == chunked
        assert parser_mod.count_data_rows(str(path), skip_header=skip) \
            == len(lines)
    assert parser_mod.read_lines(str(path), skip_header=True) == \
        ["1,2\fX", "3,4 5", "5,6"]


# ------------------------------------------------------ device bagging


def _bag_ds():
    rng = np.random.RandomState(3)
    x = rng.randn(300, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    return Dataset.from_arrays(x, y, max_bin=32)


def _bag_booster(ds, **params):
    cfg = OverallConfig()
    cfg.set({"objective": "binary", "num_leaves": "8",
             "min_data_in_leaf": "5", "bagging_fraction": "0.7",
             "bagging_freq": "2", "bagging_seed": "11",
             "bagging_device": "true", "grow_policy": "depthwise",
             **{k: str(v) for k, v in params.items()}},
            require_data=False)
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, ds, obj)
    return b


def test_device_bag_mask_oracle():
    """The device draw is a pure function of (seed, draw_index): one
    threefry fold_in + uniform + argsort, replayed here host-side."""
    from lightgbm_tpu.ops import sampling
    n, cnt = 257, 180
    for draw in (0, 1, 5):
        mask = np.asarray(sampling.bag_mask_for_draw(
            sampling.bag_key(11), draw, n, cnt))
        u = jax.random.uniform(
            jax.random.fold_in(jax.random.PRNGKey(11), draw), (n,))
        oracle = np.zeros(n, bool)
        oracle[np.argsort(np.asarray(u), kind="stable")[:cnt]] = True
        np.testing.assert_array_equal(mask, oracle)
        assert mask.sum() == cnt


def test_device_bagging_trains_and_uses_device_route():
    from lightgbm_tpu import telemetry
    ds = _bag_ds()
    telemetry.enable()
    try:
        b = _bag_booster(ds)
        assert b._bag_device
        for _ in range(4):
            b.train_one_iter(is_eval=False)
        routes = telemetry.counters()
        assert routes.get("bagging/device", 0) >= 1
        assert "bagging/host" not in routes
    finally:
        telemetry.disable()
        telemetry.reset()
    assert len(b.models) == 4


def test_device_bagging_chunk_and_pipeline_equivalence():
    """Device-bagged training is exact-identical across the per-iteration,
    fused-chunk and pipelined paths (the draw counter is the whole
    rewindable stream state)."""
    ds = _bag_ds()
    b1 = _bag_booster(ds)
    for _ in range(6):
        b1.train_one_iter(is_eval=False)
    b2 = _bag_booster(ds)
    b2.train_chunk(4)
    b2.train_chunk(4, limit=2)   # surplus rollback rewinds the counter
    assert _model_text(b1) == _model_text(b2)
    os.environ["LGBM_TPU_PIPELINE"] = "readback"
    try:
        b3 = _bag_booster(ds)
        for _ in range(6):
            b3.train_one_iter(is_eval=False)
        b3.flush_pipeline()
    finally:
        del os.environ["LGBM_TPU_PIPELINE"]
    assert _model_text(b1) == _model_text(b3)


def test_host_bagging_env_hatch():
    ds = _bag_ds()
    os.environ["LGBM_TPU_HOST_BAGGING"] = "1"
    try:
        b = _bag_booster(ds)
        assert not b._bag_device
    finally:
        del os.environ["LGBM_TPU_HOST_BAGGING"]
    b2 = _bag_booster(ds, bagging_device="false")
    assert not b2._bag_device
    # auto on CPU keeps the historical host draw
    b3 = _bag_booster(ds, bagging_device="auto")
    assert not b3._bag_device


def test_bagging_device_true_falls_back_per_query():
    """Per-query bagging draws are a host loop — bagging_device=true
    warns and keeps the host path instead of mis-drawing."""
    rng = np.random.RandomState(0)
    x = rng.randn(90, 4).astype(np.float32)
    y = rng.randint(0, 3, 90).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=16)
    ds.metadata.query_boundaries = np.array([0, 30, 60, 90])
    cfg = OverallConfig()
    cfg.set({"objective": "lambdarank", "num_leaves": "4",
             "min_data_in_leaf": "2", "bagging_fraction": "0.5",
             "bagging_freq": "1", "bagging_device": "true"},
            require_data=False)
    b = GBDT()
    obj = create_objective(cfg.objective_type, cfg.objective_config)
    b.init(cfg.boosting_config, ds, obj)
    assert not b._bag_device


# ----------------------------------------------------------------- GOSS


def test_goss_select_shape_and_scaling():
    """Top rows kept unamplified; sampled remainder amplified on BOTH
    gradients and hessians; mask has exactly top+other rows."""
    from lightgbm_tpu.ops import sampling
    rng = np.random.RandomState(5)
    n = 200
    grad = rng.randn(1, n).astype(np.float32)
    hess = np.abs(rng.randn(1, n)).astype(np.float32)
    top_cnt, other_cnt, amp = sampling.goss_counts(n, 0.2, 0.1)
    assert (top_cnt, other_cnt) == (40, 20)
    assert amp == pytest.approx(8.0)
    g, h, mask = sampling.goss_select(
        jax.random.PRNGKey(0), grad, hess, top_cnt, other_cnt, amp)
    g, h, mask = np.asarray(g), np.asarray(h), np.asarray(mask)
    assert mask.sum() == top_cnt + other_cnt
    order = np.argsort(-np.abs(grad[0]), kind="stable")
    top = order[:top_cnt]
    assert mask[top].all()
    # top rows keep raw values; selected non-top rows carry the amp
    np.testing.assert_allclose(g[0, top], grad[0, top])
    np.testing.assert_allclose(h[0, top], hess[0, top])
    rest = np.setdiff1d(np.nonzero(mask)[0], top)
    assert rest.size == other_cnt
    np.testing.assert_allclose(g[0, rest], grad[0, rest] * amp,
                               rtol=1e-6)
    np.testing.assert_allclose(h[0, rest], hess[0, rest] * amp,
                               rtol=1e-6)


def test_goss_training_runs_and_beats_random():
    """GOSS end-to-end: trains on the per-iteration path (chunking is
    excluded), model differs from full-data training, and the train-set
    AUC anchor holds (sampled iterations still learn the signal)."""
    rng = np.random.RandomState(9)
    n = 600
    x = rng.randn(n, 5).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] + 0.3 * rng.randn(n) > 0).astype(
        np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=32)

    def booster(**p):
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "8",
                 "min_data_in_leaf": "5", "num_iterations": "10",
                 **{k: str(v) for k, v in p.items()}},
                require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, ds, obj)
        # ISSUE 12 flipped the ISSUE-8 exclusion: GOSS selection is now
        # traced INSIDE the chunk programs, so goss=true keeps the fused
        # path (equivalence pinned in tests/test_goss_chunk.py)
        assert b.chunk_supported(False) if p.get("goss") else True
        b.run_training(10, False)
        return b

    b_goss = booster(goss="true", top_rate=0.2, other_rate=0.2)
    assert b_goss._goss_on and len(b_goss.models) == 10
    scores = np.asarray(b_goss.score)[0]
    # recorded-anchor style check: GOSS at (0.2, 0.2) must rank the
    # train set essentially as well as the full-data model on this
    # separable synthetic (full-data AUC here ~0.99)
    order = np.argsort(scores)
    ranks = np.empty(n); ranks[order] = np.arange(n)
    pos, neg = ranks[y == 1], ranks[y == 0]
    auc = (pos[:, None] > neg[None, :]).mean()
    assert auc > 0.95


def test_goss_deterministic_given_seed():
    rng = np.random.RandomState(2)
    x = rng.randn(300, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=16)

    def run():
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "6",
                 "min_data_in_leaf": "5", "goss": "true",
                 "bagging_seed": "17"}, require_data=False)
        b = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        b.init(cfg.boosting_config, ds, obj)
        for _ in range(4):
            b.train_one_iter(is_eval=False)
        return _model_text(b)

    assert run() == run()


# --------------------------------------------------------------- config


def test_config_streaming_knobs():
    cfg = OverallConfig()
    cfg.set({"streaming": "true", "ingest_chunk_rows": "1000"},
            require_data=False)
    assert cfg.io_config.streaming == "true"
    assert cfg.io_config.ingest_chunk_rows == 1000
    with pytest.raises(LightGBMError):
        OverallConfig().set({"streaming": "maybe"}, require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"ingest_chunk_rows": "0"},
                            require_data=False)


def test_config_sampling_knobs():
    cfg = OverallConfig()
    cfg.set({"bagging_device": "true", "goss": "true",
             "top_rate": "0.3", "other_rate": "0.2"}, require_data=False)
    assert cfg.boosting_config.bagging_device == "true"
    assert cfg.boosting_config.goss
    assert cfg.boosting_config.top_rate == pytest.approx(0.3)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"bagging_device": "sometimes"},
                            require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"goss": "true", "top_rate": "1.0"},
                            require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"goss": "true", "other_rate": "0.0"},
                            require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"goss": "true", "top_rate": "0.7",
                             "other_rate": "0.5"}, require_data=False)
    with pytest.raises(LightGBMError):
        OverallConfig().set({"goss": "true", "bagging_fraction": "0.5",
                             "bagging_freq": "1"}, require_data=False)


def test_resolve_streaming(tmp_path, monkeypatch):
    small = tmp_path / "small.csv"
    small.write_text("1,2\n")
    io = IOConfig(data_filename=str(small), streaming="auto")
    assert not streaming.resolve_streaming(io, str(small))
    monkeypatch.setattr(streaming, "AUTO_MIN_BYTES", 1)
    assert streaming.resolve_streaming(io, str(small))
    io.streaming = "false"
    assert not streaming.resolve_streaming(io, str(small))
    io.streaming = "true"
    assert streaming.resolve_streaming(io, str(small))
    io.streaming = "auto"
    assert not streaming.resolve_streaming(io, str(tmp_path / "absent"))


def test_ingest_telemetry_counters(tmp_path):
    from lightgbm_tpu import telemetry
    path = _write_csv(tmp_path / "t.csv", 200)
    telemetry.enable()
    try:
        _load(path, streaming="true", ingest_chunk_rows=64)
        c = telemetry.counters()
        assert c.get("ingest/chunks", 0) == 4     # ceil(200/64)
        assert c.get("ingest/rows", 0) == 200
        assert c.get("ingest/h2d_bytes", 0) > 0
    finally:
        telemetry.disable()
        telemetry.reset()
