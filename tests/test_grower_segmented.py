"""Leaf-wise dispatch segmentation (models/grower.grow_tree_segmented):
running the split fori_loop as N shorter dispatches with the grow state
carried device-resident must be bit-identical to the single-dispatch tree
— the body never reads the loop index, so the program is the same.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.models.grower import grow_tree, grow_tree_segmented


@pytest.fixture(scope="module")
def grow_inputs():
    rng = np.random.RandomState(21)
    F, N, B = 8, 4000, 64
    bins = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8))
    x = rng.randn(N, F)
    y = (x[:, 0] + 0.5 * x[:, 1] * x[:, 2] + 0.3 * rng.randn(N) > 0)
    p = np.full(N, y.mean())
    grad = jnp.asarray((p - y).astype(np.float32))
    hess = jnp.asarray((p * (1 - p)).astype(np.float32))
    row_mask = jnp.asarray(rng.rand(N) < 0.9)
    feature_mask = jnp.ones((F,), bool)
    num_bins = jnp.full((F,), B, jnp.int32)
    return bins, grad, hess, row_mask, feature_mask, num_bins, B


@pytest.mark.parametrize("segments", [2, 5, 31])
@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_segmented_tree_bit_identical(grow_inputs, segments, dtype):
    bins, grad, hess, row_mask, feature_mask, num_bins, B = grow_inputs
    kwargs = dict(num_leaves=31, num_bins_max=B, min_data_in_leaf=20,
                  min_sum_hessian_in_leaf=1e-3,
                  compute_dtype=(dtype if dtype == "int8" else jnp.float32))
    one = grow_tree(bins, grad, hess, row_mask, feature_mask, num_bins,
                    **kwargs)
    seg = grow_tree_segmented(bins, grad, hess, row_mask, feature_mask,
                              num_bins, segments=segments, **kwargs)
    for a, b in zip(one, seg):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_leafwise_segments_config_e2e(grow_inputs, tmp_path):
    """leafwise_segments plumbs config → gbdt → segmented grower and trains
    the same model as the default single-dispatch path."""
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    rng = np.random.RandomState(5)
    N, F = 3000, 6
    x = rng.randn(N, F)
    y = (x[:, 0] + 0.4 * x[:, 1] > 0).astype(np.float64)
    ds = Dataset.from_arrays(x, y, max_bin=63)

    def train(extra, tmpdir):
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "15",
                 "num_iterations": "4", "min_data_in_leaf": "20",
                 **extra}, require_data=False)
        booster = GBDT()
        obj = create_objective(cfg.objective_type, cfg.objective_config)
        booster.init(cfg.boosting_config, ds, obj)
        for _ in range(4):
            if booster.train_one_iter(is_eval=False):
                break
        path = str(tmpdir / ("model_%s.txt" % bool(extra)))
        booster.save_model_to_file(True, path)
        with open(path) as fh:
            return fh.read()

    assert train({"leafwise_segments": "4"}, tmp_path) == train({}, tmp_path)
