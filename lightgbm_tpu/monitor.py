"""Live production monitoring (ISSUE 20): windowed metrics, SLO burn
rate, serving-side score drift.

Every observability tier below this one is post-mortem: telemetry
counters are cumulative-since-enable, the flight recorder's sketches
are all-time, and trace/pod reports analyze dumps after the run ends.
This module is the LIVE tier a pager can watch, layered strictly ON TOP
of telemetry.py and tracing.py — it owns no instrumentation sites of
its own, it only differences the cumulative state those layers already
maintain:

1. **Windowed metrics.**  A fixed-memory ring of per-interval
   snapshots.  Each closed window carries the counter DELTAS
   (telemetry registry) and the per-family latency-sketch DELTAS since
   the previous window.  Because :class:`tracing.LatencySketch` merge
   is associative bucket addition, a window sketch is the exact
   per-bucket SUBTRACTION of two cumulative sketches
   (:func:`sketch_subtract`) — no sampling, no decay, and the window
   percentiles carry the same sqrt(growth) resolution contract as the
   cumulative ones.  Both cumulative reads come from ONE lock
   acquisition (``tracing.cumulative_state``), so the conservation
   identity ``sum(window deltas) == cumulative total`` holds exactly;
   ``scripts/monitor_report.py --check`` validates it per window.
   Exposed live via :func:`monitor_snapshot` and appended per window to
   a JSONL file by a periodic emitter thread (``monitor_out=`` /
   ``monitor_interval_s=`` knobs; the thread is registered with
   ``lifecycle.track`` so the conftest leak guard sees it).  The file
   is flushed on ``telemetry.disable()`` and from the faults.py crash
   path (:func:`flush_on_fault`), like trace dumps.

2. **SLO burn rate.**  Declarative latency objective for one serve
   family (``slo_p99_us=`` target, ``slo_window_s=`` budget window).
   A p99 objective grants a 1% error budget (``SLO_BUDGET``); a
   window's bad fraction is the sketch mass in buckets whose
   representative exceeds the target.  The multi-window rule pages only
   when BOTH the fast short window burns >= 5x (``FAST_BURN``) and the
   slow long window burns >= 1x (``SLOW_BURN``) — the standard
   fast+slow guard against one-interval blips.  Short window =
   long/12, in whole intervals.  Every breach is filed into the trace
   ring (``slo_breach`` event carrying the window id) next to a
   per-window ``monitor_window`` marker, so a post-mortem dump shows
   WHEN the budget started burning; ``trace_report.py --check``
   validates the id linkage.

3. **Score drift.**  :class:`ScoreHistogram` is a reservoir-free
   signed log-bucket histogram (positive and negative buckets around a
   zero bucket — raw ensemble scores are signed, unlike latencies).
   ``ServingFront`` feeds predicted scores into a per-engine live
   histogram; :func:`drift_verdict` computes a PSI-style divergence
   over the matched bucket union against the training-time reference
   captured at model build (``score_reference=`` line in the model
   file) — ROADMAP item 4's candidate-swap gate.  An A/A self-check
   (alternate scores split into two halves, :func:`aa_verdict`) bounds
   the false-positive rate: the halves are draws from the SAME
   distribution, so their PSI must stay under ``AA_PSI_BOUND``.

Pure stdlib (numpy used opportunistically for bulk score bucketing) —
safe from fault/crash paths.  The armed monitor is process-global
state like the recorder: a lifecycle probe (``monitor``) makes the
leak guard fail any test that leaves it armed.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional

from . import lifecycle, telemetry, tracing

DEFAULT_INTERVAL_S = 1.0
DEFAULT_SLO_WINDOW_S = 60.0
DEFAULT_WINDOW_RING = 240
DEFAULT_SLO_FAMILY = "serve_wall_us"

# a p99 latency objective grants a 1% error budget; burn rate is the
# window's bad fraction divided by this budget
SLO_BUDGET = 0.01
FAST_BURN = 5.0       # short-window burn threshold (the "is it NOW" arm)
SLOW_BURN = 1.0       # long-window burn threshold (the "does it matter" arm)
SHORT_WINDOW_RATIO = 12   # short window = slo_window_s / 12 (SRE convention)

DRIFT_GROWTH = 2.0        # score-bucket growth (much coarser than latency:
#                           PSI sampling noise grows with bucket count, so
#                           drift wants few well-filled buckets, not tails)
DRIFT_MIN_BUCKET = -6     # |score| < growth**-6 collapses into one bucket
DRIFT_MAX_BUCKET = 24     # ... and the far overflow tail into another;
#                           both clamps bound the PSI union size (and with
#                           it the A/A noise floor) regardless of score range
DRIFT_PSI_THRESHOLD = 0.2  # industry PSI rule: > 0.2 = significant shift
AA_PSI_BOUND = 0.05        # documented A/A false-positive bound
#                            (perf_gate flags bench drift_aa_psi above it)
_TINY = 1e-12              # |score| below this lands in the zero bucket
_PSI_EPSILON = 1e-4        # additive smoothing over the bucket union


# ------------------------------------------------------------ score buckets

class ScoreHistogram:
    """Signed log-bucket histogram for model scores.

    Latency sketches are positive-only; raw ensemble margins are
    signed, so this keeps SEPARATE positive and negative bucket maps
    around a zero bucket: value ``v`` lands in bucket
    ``floor(log(|v|)/log(g))`` of its sign's map, clamped into
    ``[DRIFT_MIN_BUCKET, DRIFT_MAX_BUCKET]`` (non-finite and
    ``|v| < 1e-12`` land in zero).  The clamp bounds the PSI bucket
    union — sparse log-tail buckets would otherwise dominate the PSI
    sampling-noise floor and sink the A/A bound.  ``merge`` is per-sign
    bucket
    addition — associative, the cross-batch fold — and
    ``to_dict``/``from_dict`` round-trip through the model file's
    ``score_reference=`` metadata line."""

    __slots__ = ("growth", "_log_g", "zero", "pos", "neg")

    def __init__(self, growth: float = DRIFT_GROWTH):
        growth = float(growth)
        if not (1.0005 <= growth <= 4.0):
            raise ValueError("score-histogram growth must be in "
                             "[1.0005, 4.0], got %g" % growth)
        self.growth = growth
        self._log_g = math.log(growth)
        self.zero = 0
        self.pos: Dict[int, int] = {}
        self.neg: Dict[int, int] = {}

    def record(self, value: float, n: int = 1) -> None:
        v = float(value)
        if not math.isfinite(v) or abs(v) < _TINY:
            self.zero += n
            return
        i = int(math.floor(math.log(abs(v)) / self._log_g))
        i = min(max(i, DRIFT_MIN_BUCKET), DRIFT_MAX_BUCKET)
        d = self.pos if v > 0 else self.neg
        d[i] = d.get(i, 0) + n

    def record_many(self, values) -> int:
        """Bulk record (numpy-vectorized when available; bucket indices
        are identical to scalar :meth:`record` — both float64).
        Returns the number of values recorded."""
        try:
            import numpy as np
        except Exception:  # pragma: no cover - numpy is always present
            np = None
        if np is None:  # pragma: no cover
            cnt = 0
            for x in values:
                self.record(float(x))
                cnt += 1
            return cnt
        v = np.asarray(values, dtype=np.float64).ravel()
        if v.size == 0:
            return 0
        keep = np.isfinite(v)
        self.zero += int(v.size - keep.sum())
        v = v[keep]
        tiny = np.abs(v) < _TINY
        self.zero += int(tiny.sum())
        v = v[~tiny]
        if v.size:
            idx = np.floor(np.log(np.abs(v)) / self._log_g).astype(np.int64)
            idx = np.clip(idx, DRIFT_MIN_BUCKET, DRIFT_MAX_BUCKET)
            sign = v > 0
            for mask, d in ((sign, self.pos), (~sign, self.neg)):
                ii, cc = np.unique(idx[mask], return_counts=True)
                for i, c in zip(ii.tolist(), cc.tolist()):
                    d[i] = d.get(i, 0) + int(c)
        return int(keep.size)

    @property
    def count(self) -> int:
        return self.zero + sum(self.pos.values()) + sum(self.neg.values())

    def merge(self, other: "ScoreHistogram") -> "ScoreHistogram":
        if abs(other.growth - self.growth) > 1e-12:
            raise ValueError("cannot merge score histograms with different "
                             "growth (%g vs %g)" % (self.growth, other.growth))
        self.zero += other.zero
        for src, dst in ((other.pos, self.pos), (other.neg, self.neg)):
            for i, c in src.items():
                dst[i] = dst.get(i, 0) + c
        return self

    def to_dict(self) -> dict:
        return {"growth": self.growth, "zero": self.zero,
                "pos": {str(i): c for i, c in self.pos.items()},
                "neg": {str(i): c for i, c in self.neg.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "ScoreHistogram":
        h = cls(d.get("growth", DRIFT_GROWTH))
        h.zero = int(d.get("zero", 0))
        h.pos = {int(i): int(c) for i, c in (d.get("pos") or {}).items()}
        h.neg = {int(i): int(c) for i, c in (d.get("neg") or {}).items()}
        return h


def psi(reference, live, epsilon: float = _PSI_EPSILON) -> Optional[float]:
    """PSI-style divergence over the matched bucket union of two score
    histograms (dicts or :class:`ScoreHistogram`).  Each term is
    ``(q - p) * ln(q / p)`` with additive ``epsilon`` smoothing, so the
    sum is >= 0 and symmetric.  None when either side is empty (no
    verdict without data)."""
    ref = ScoreHistogram.from_dict(reference) if isinstance(reference, dict) \
        else reference
    liv = ScoreHistogram.from_dict(live) if isinstance(live, dict) else live
    if ref is None or liv is None:
        return None
    if ref.count == 0 or liv.count == 0:
        return None
    if abs(ref.growth - liv.growth) > 1e-12:
        raise ValueError("cannot compare score histograms with different "
                         "growth (%g vs %g)" % (ref.growth, liv.growth))
    keys = {("z", 0)}
    for h in (ref, liv):
        keys.update(("p", i) for i in h.pos)
        keys.update(("n", i) for i in h.neg)
    k = len(keys)
    rt, lt = float(ref.count), float(liv.count)
    total = 0.0
    for sign, i in keys:
        if sign == "z":
            rc, lc = ref.zero, liv.zero
        elif sign == "p":
            rc, lc = ref.pos.get(i, 0), liv.pos.get(i, 0)
        else:
            rc, lc = ref.neg.get(i, 0), liv.neg.get(i, 0)
        p = (rc + epsilon) / (rt + epsilon * k)
        q = (lc + epsilon) / (lt + epsilon * k)
        total += (q - p) * math.log(q / p)
    return total


def drift_verdict(reference, live,
                  threshold: float = DRIFT_PSI_THRESHOLD) -> dict:
    """The swap-gate primitive: PSI of live scores against the
    training-time reference, plus the boolean verdict.  ``psi`` is None
    (and ``drift`` False) when either histogram is empty."""
    ref = ScoreHistogram.from_dict(reference) if isinstance(reference, dict) \
        else reference
    liv = ScoreHistogram.from_dict(live) if isinstance(live, dict) else live
    value = psi(ref, liv)
    return {
        "psi": value,
        "threshold": float(threshold),
        "drift": bool(value is not None and value > threshold),
        "ref_count": 0 if ref is None else ref.count,
        "live_count": 0 if liv is None else liv.count,
    }


# --------------------------------------------------------- window subtraction

def sketch_subtract(cur: "tracing.LatencySketch",
                    prev: Optional["tracing.LatencySketch"]
                    ) -> "tracing.LatencySketch":
    """Exact window sketch: per-bucket subtraction of two cumulative
    sketches (the inverse of the associative merge).  Raises when the
    growth factors differ or any count would go negative — a cumulative
    sketch is monotone, so a negative delta means the caller mixed
    baselines, never a rounding artifact."""
    delta = tracing.LatencySketch(cur.growth)
    if prev is None:
        delta.zero = cur.zero
        delta.buckets = dict(cur.buckets)
        return delta
    if abs(cur.growth - prev.growth) > 1e-12:
        raise ValueError("cannot subtract sketches with different growth "
                         "(%g vs %g)" % (cur.growth, prev.growth))
    delta.zero = cur.zero - prev.zero
    if delta.zero < 0:
        raise ValueError("window sketch subtraction went negative "
                         "(zero bucket)")
    for i, c in cur.buckets.items():
        d = c - prev.buckets.get(i, 0)
        if d < 0:
            raise ValueError("window sketch subtraction went negative "
                             "(bucket %d)" % i)
        if d:
            delta.buckets[i] = d
    for i, c in prev.buckets.items():
        if i not in cur.buckets and c > 0:
            raise ValueError("window sketch subtraction went negative "
                             "(bucket %d vanished)" % i)
    return delta


def bad_count(sketch: "tracing.LatencySketch", threshold_us: float) -> int:
    """Observations whose bucket representative exceeds the SLO target —
    the window's error count at sketch resolution (the zero bucket is
    always good)."""
    return sum(c for i, c in sketch.buckets.items()
               if sketch.growth ** (i + 0.5) > threshold_us)


# ------------------------------------------------------------- monitor state

_lock = threading.RLock()
_armed = False
_closed = False               # a close/fault record was already written
_out_path = ""
_file = None
_interval_s = DEFAULT_INTERVAL_S
_ring: List[dict] = []
_ring_cap = DEFAULT_WINDOW_RING
_window_seq = 0
_emitted = 0
_breaches = 0
_prev: Optional[dict] = None  # previous cumulative baseline
_slo_p99_us = 0.0
_slo_window_s = DEFAULT_SLO_WINDOW_S
_slo_family = DEFAULT_SLO_FAMILY
_short_n = 1
_long_n = 1
_thread: Optional[threading.Thread] = None
_stop: Optional[threading.Event] = None
_drift: Dict[str, dict] = {}
_engine_seq = 0


def active() -> bool:
    """True while the monitor is armed — the hot-path gate serving
    checks before feeding scores (one module-global read)."""
    return _armed


def engine_key() -> str:
    """Fresh per-engine drift key — the front takes one at install and
    at every swap flip, so a swapped-in candidate starts a clean live
    histogram instead of inheriting the old model's score mass."""
    global _engine_seq
    with _lock:
        _engine_seq += 1
        return "engine-%d" % _engine_seq


def _capture_locked() -> dict:
    """One cumulative baseline: telemetry counters + tracing sketches,
    each from a single consistent read."""
    return {
        "t": time.time(),
        "counters": dict(telemetry.counters()),
        "trace": tracing.cumulative_state(),
    }


def arm(out_path: str = "", interval_s: float = DEFAULT_INTERVAL_S,
        slo_p99_us: float = 0.0,
        slo_window_s: float = DEFAULT_SLO_WINDOW_S,
        ring_windows: int = DEFAULT_WINDOW_RING,
        slo_family: str = DEFAULT_SLO_FAMILY,
        emitter: Optional[bool] = None) -> None:
    """Arm (or re-arm, resetting ring/drift state) the live monitor.

    ``out_path`` (optional) is the JSONL the emitter appends one line
    per window to; ``interval_s`` the window length; ``slo_p99_us`` > 0
    enables SLO tracking for ``slo_family`` with budget window
    ``slo_window_s``.  ``emitter`` forces the background thread on/off
    (default: on iff ``out_path`` is set).  Invalid values raise —
    config.py rejects them loudly before they ever reach here."""
    global _armed, _closed, _out_path, _file, _interval_s, _ring, _ring_cap
    global _window_seq, _emitted, _breaches, _prev, _slo_p99_us
    global _slo_window_s, _slo_family, _short_n, _long_n, _thread, _stop
    interval_s = float(interval_s)
    slo_window_s = float(slo_window_s)
    slo_p99_us = float(slo_p99_us)
    ring_windows = int(ring_windows)
    if interval_s <= 0:
        raise ValueError("monitor_interval_s must be > 0, got %g"
                         % interval_s)
    if slo_window_s <= 0:
        raise ValueError("slo_window_s must be > 0, got %g" % slo_window_s)
    if slo_p99_us < 0:
        raise ValueError("slo_p99_us must be >= 0, got %g" % slo_p99_us)
    if ring_windows <= 0:
        raise ValueError("monitor ring_windows must be > 0, got %d"
                         % ring_windows)
    disarm()
    long_n = max(1, int(math.ceil(slo_window_s / interval_s)))
    short_n = max(1, int(math.ceil(
        slo_window_s / SHORT_WINDOW_RATIO / interval_s)))
    # the slow window must fit in the ring or its burn rate lies
    ring_cap = max(ring_windows, long_n)
    out_path = str(out_path or "")
    slo_family = str(slo_family or DEFAULT_SLO_FAMILY)
    # the open + header append run OUTSIDE the lock: arm follows disarm
    # so nothing ticks yet, and slow IO must never stall a reader
    fh = None
    if out_path:
        fh = open(out_path, "a")
        ident = tracing.identity()
        header = {"monitor_header": {
            "t": round(time.time(), 6),
            "interval_s": interval_s,
            "ring_windows": ring_cap,
            "host": ident.get("host"),
            "pid": ident.get("pid"),
            "run_id": ident.get("run_id"),
            "slo": None if slo_p99_us <= 0 else {
                "family": slo_family,
                "p99_us": slo_p99_us,
                "window_s": slo_window_s,
                "budget": SLO_BUDGET,
                "short_windows": short_n,
                "long_windows": long_n,
                "fast_burn": FAST_BURN,
                "slow_burn": SLOW_BURN,
            },
            "drift_growth": DRIFT_GROWTH,
            "drift_threshold": DRIFT_PSI_THRESHOLD,
            "aa_bound": AA_PSI_BOUND,
        }}
        fh.write(json.dumps(header) + "\n")
        fh.flush()
    with _lock:
        _interval_s = interval_s
        _slo_p99_us = slo_p99_us
        _slo_window_s = slo_window_s
        _slo_family = slo_family
        _long_n = long_n
        _short_n = short_n
        _ring_cap = ring_cap
        _ring = []
        _window_seq = 0
        _emitted = 0
        _breaches = 0
        _drift.clear()
        _out_path = out_path
        _file = fh
        _closed = False
        _prev = _capture_locked()
        _armed = True
    run_emitter = bool(_out_path) if emitter is None else bool(emitter)
    if run_emitter:
        _stop = threading.Event()
        _thread = threading.Thread(
            target=_emit_loop, args=(_stop, interval_s),
            name="lgbm-monitor-emitter", daemon=True)
        lifecycle.track("monitor-emitter", _thread, disarm)
        _thread.start()


def _emit_loop(stop: threading.Event, interval_s: float) -> None:
    while not stop.wait(interval_s):
        try:
            tick()
        except Exception:  # pragma: no cover - emitter must never die loud
            pass


def _counter_deltas(cur: Dict[str, int], prev: Dict[str, int]):
    """(deltas, rebased) — a counter running backwards means the
    registry was reset under us; rebase to a zero baseline instead of
    reporting a negative delta."""
    for k, v in prev.items():
        if cur.get(k, 0) < v:
            prev = {}
            break
    deltas = {}
    for k, v in cur.items():
        d = v - prev.get(k, 0)
        if d:
            deltas[k] = d
    return deltas, prev


def tick(now: Optional[float] = None) -> Optional[dict]:
    """Close the current window: difference the cumulative state
    against the previous baseline, evaluate the SLO burn rule, file the
    ``monitor_window`` (and any ``slo_breach``) trace event, append the
    window to the ring and the JSONL file.  Returns the window record
    (None while disarmed).  The emitter thread calls this once per
    interval; tests and bench call it directly for deterministic
    windows."""
    global _window_seq, _emitted, _breaches, _prev
    with _lock:
        if not _armed:
            return None
        now = time.time() if now is None else float(now)
        cur = _capture_locked()
        prev = _prev or {"t": now, "counters": {}, "trace": None}
        counters, prev_counters = _counter_deltas(
            cur["counters"], prev["counters"])
        prev_trace = prev.get("trace")
        cur_trace = cur.get("trace")
        if (prev_trace is not None and cur_trace is not None
                and (cur_trace["appended"] < prev_trace["appended"]
                     or abs(cur_trace["sketch_growth"]
                            - prev_trace["sketch_growth"]) > 1e-12)):
            prev_trace = None  # recorder re-armed: rebase to zero
        sketches: Dict[str, "tracing.LatencySketch"] = {}
        totals: Dict[str, int] = {}
        if cur_trace is not None:
            prev_sk = {} if prev_trace is None else prev_trace["sketches"]
            for fam, sk in cur_trace["sketches"].items():
                sketches[fam] = sketch_subtract(sk, prev_sk.get(fam))
                totals[fam] = sk.count
        _window_seq += 1
        wid = _window_seq
        rec = {
            "window": wid,
            "t0": round(prev["t"], 6),
            "t1": round(now, 6),
            "counters": counters,
            "counters_total": {k: v for k, v in cur["counters"].items()
                               if v},
            "sketches": {f: sk.to_dict()
                         for f, sk in sorted(sketches.items())},
            "sketch_counts_total": dict(sorted(totals.items())),
        }
        _ring.append(rec)
        if len(_ring) > _ring_cap:
            del _ring[0]
        if _slo_p99_us > 0:
            sk = sketches.get(_slo_family)
            bad = 0 if sk is None else bad_count(sk, _slo_p99_us)
            total = 0 if sk is None else sk.count
            # the ring already holds this window, so both trailing
            # sums include it — the same arithmetic monitor_report
            # recomputes from the emitted records
            fast = _burn_rate(_short_n)
            slow = _burn_rate(_long_n)
            breach = fast >= FAST_BURN and slow >= SLOW_BURN
            rec["slo"] = {
                "family": _slo_family,
                "p99_us": _slo_p99_us,
                "bad": bad,
                "total": total,
                "fast_burn": fast,
                "slow_burn": slow,
                "breach": breach,
            }
            if breach:
                _breaches += 1
                telemetry.count("monitor/slo_breaches")
                tracing.event("slo_breach", window=wid,
                              family=_slo_family, p99_us=_slo_p99_us,
                              fast_burn=round(fast, 4),
                              slow_burn=round(slow, 4))
        telemetry.count("monitor/windows")
        tracing.event("monitor_window", window=wid,
                      t0=rec["t0"], t1=rec["t1"])
        if _file is not None and not _closed:
            _file.write(json.dumps({"monitor_window": rec}) + "\n")
            _file.flush()
            _emitted += 1
        del prev_counters  # rebase already folded into the deltas
        _prev = {"t": now, "counters": dict(cur["counters"]),
                 "trace": cur_trace}
        return rec


def _burn_rate(n_windows: int) -> float:
    """Error-budget burn over the trailing ``n_windows`` ring entries:
    (sum bad / sum total) / budget.  0.0 with no traffic — an idle
    service is not burning budget.  Caller holds the lock; the window
    under evaluation must already be in the ring.

    NOTE: ``slo`` blocks are attached after ring insertion, so this
    reads each window's delta sketch directly — the same arithmetic
    monitor_report recomputes from the emitted records."""
    bad = 0
    total = 0
    for rec in _ring[-n_windows:]:
        skd = (rec.get("sketches") or {}).get(_slo_family)
        if not skd:
            continue
        sk = tracing.LatencySketch.from_dict(skd)
        bad += bad_count(sk, _slo_p99_us)
        total += sk.count
    if total == 0:
        return 0.0
    return (bad / total) / SLO_BUDGET


# ------------------------------------------------------------------- drift

def _new_drift_state() -> dict:
    return {"hist": ScoreHistogram(), "a": ScoreHistogram(),
            "b": ScoreHistogram(), "n": 0, "reference": None}


def register_reference(key: str, reference: Optional[dict]) -> None:
    """Attach a model's training-time reference histogram (the parsed
    ``score_reference=`` block) to an engine drift key.  None clears —
    a model without a captured reference still gets the A/A lane."""
    with _lock:
        st = _drift.setdefault(str(key), _new_drift_state())
        st["reference"] = dict(reference) if reference else None


def record_scores(key: str, values, reference: Optional[dict] = None
                  ) -> int:
    """Feed a batch of predicted scores into the engine's live
    histogram.  Alternate stream positions split into the A/A halves
    (deterministic — the parity of the global per-key sequence, not a
    random draw).  ``reference`` lazily attaches the engine's
    training-time histogram on first contact, so the feed works
    whichever of front/monitor armed first.  Returns the number
    recorded; no-op while disarmed."""
    if not _armed:
        return 0
    with _lock:
        if not _armed:
            return 0
        st = _drift.setdefault(str(key), _new_drift_state())
        if st["reference"] is None and reference:
            st["reference"] = dict(reference)
        try:
            import numpy as np
            vals = np.asarray(values, dtype=np.float64).ravel()
        except Exception:  # pragma: no cover - numpy is always present
            vals = [float(v) for v in values]
        n0 = st["n"]
        cnt = st["hist"].record_many(vals)
        st["a"].record_many(vals[(n0 % 2)::2])
        st["b"].record_many(vals[((n0 + 1) % 2)::2])
        st["n"] = n0 + len(vals)
    telemetry.count("monitor/drift_scores", cnt)
    return cnt


def aa_verdict(key: str) -> dict:
    """The A/A self-check: PSI between the two alternate halves of one
    engine's OWN live scores.  Both halves are draws from the same
    distribution, so a healthy pipeline keeps this under
    ``AA_PSI_BOUND`` — the measured false-positive floor the real
    drift threshold must clear."""
    with _lock:
        st = _drift.get(str(key))
        if st is None:
            return {"psi": None, "bound": AA_PSI_BOUND, "ok": True,
                    "count": 0}
        value = psi(st["a"], st["b"])
        return {"psi": value, "bound": AA_PSI_BOUND,
                "ok": bool(value is None or value <= AA_PSI_BOUND),
                "count": st["hist"].count}


def engine_drift(key: str) -> dict:
    """Live drift verdict for one engine key (reference vs live), plus
    the A/A lane."""
    with _lock:
        st = _drift.get(str(key))
        if st is None:
            return drift_verdict(None, None)
        out = drift_verdict(st["reference"], st["hist"])
    out["aa"] = aa_verdict(key)
    return out


def _drift_block_locked() -> dict:
    """Serializable close-record drift state: reference + live + A/A
    histograms with their recomputable verdicts (monitor_report
    --check re-derives every PSI from the serialized buckets, so a
    tampered reference cannot hide)."""
    block = {}
    for key, st in sorted(_drift.items()):
        value = psi(st["reference"], st["hist"]) \
            if st["reference"] else None
        aa = psi(st["a"], st["b"])
        block[key] = {
            "reference": st["reference"],
            "live": st["hist"].to_dict(),
            "a": st["a"].to_dict(),
            "b": st["b"].to_dict(),
            "n": st["n"],
            "psi": value,
            "threshold": DRIFT_PSI_THRESHOLD,
            "drift": bool(value is not None
                          and value > DRIFT_PSI_THRESHOLD),
            "aa_psi": aa,
            "aa_bound": AA_PSI_BOUND,
        }
    return block


# ------------------------------------------------------------------ output

def monitor_snapshot() -> dict:
    """Live monitor state: the window ring, SLO posture, per-engine
    drift verdicts.  {} while disarmed."""
    with _lock:
        if not _armed:
            return {}
        out = {
            "interval_s": _interval_s,
            "ring_windows": _ring_cap,
            "windows": [dict(w) for w in _ring],
            "window_seq": _window_seq,
            "emitted": _emitted,
            "breaches": _breaches,
            "out_path": _out_path,
        }
        if _slo_p99_us > 0:
            out["slo"] = {
                "family": _slo_family,
                "p99_us": _slo_p99_us,
                "window_s": _slo_window_s,
                "budget": SLO_BUDGET,
                "short_windows": _short_n,
                "long_windows": _long_n,
                "fast_burn": _burn_rate(_short_n),
                "slow_burn": _burn_rate(_long_n),
            }
        out["drift"] = {
            key: {"count": st["hist"].count, "n": st["n"],
                  "psi": psi(st["reference"], st["hist"])
                  if st["reference"] else None,
                  "aa_psi": psi(st["a"], st["b"])}
            for key, st in sorted(_drift.items())
        }
        return out


def _write_close_locked(reason: str) -> None:
    global _closed, _emitted
    if _file is None or _closed:
        return
    rec = {"monitor_close": {
        "reason": str(reason),
        "t": round(time.time(), 6),
        "windows": _window_seq,
        "emitted": _emitted,
        "breaches": _breaches,
        "counters_total": {
            k: v for k, v in (_prev or {}).get("counters", {}).items()
            if v},
        "drift": _drift_block_locked(),
    }}
    _file.write(json.dumps(rec) + "\n")
    _file.flush()
    try:
        os.fsync(_file.fileno())
    except OSError:  # pragma: no cover
        pass
    _closed = True


def disarm(reason: str = "close") -> Optional[str]:
    """Stop the emitter, close the tail window, append the close record
    (drift state + final totals) and release the file.  Returns the
    JSONL path (or None).  Idempotent — the conftest leak guard and
    ``telemetry.disable()`` both call it."""
    global _armed, _thread, _stop, _file, _out_path, _prev, _ring
    if not _armed:
        return None
    thread, stop = _thread, _stop
    _thread = None
    _stop = None
    if stop is not None:
        stop.set()
    if thread is not None:
        thread.join(timeout=5.0)
        lifecycle.untrack(thread)
    tick()  # capture the partial tail window
    with _lock:
        if not _armed:
            return None
        path = _out_path or None
        _write_close_locked(reason)
        if _file is not None:
            try:
                _file.close()
            except OSError:  # pragma: no cover
                pass
            _file = None
        _armed = False
        _out_path = ""
        _prev = None
        _ring = list(_ring)  # keep a post-mortem copy harmless to reads
        _drift.clear()
    return path


def flush_on_fault(reason: str) -> Optional[str]:
    """Best-effort crash flush — the faults.py raise hatch calls this
    next to the trace dump.  Closes the in-flight window and appends a
    ``fault:*`` close record so the JSONL stays parseable by
    ``monitor_report.py --check``.  The monitor stays armed (the
    process is about to die anyway; a test harness can still disarm
    cleanly).  Never raises."""
    try:
        if not _armed:
            return None
        tick()
        with _lock:
            if not _armed:
                return None
            path = _out_path or None
            _write_close_locked("fault:%s" % reason)
        return path
    except Exception:  # pragma: no cover - absolute last resort
        return None


# the armed monitor is process-global state like the fault hatch: ONE
# registry feeds the conftest leak guard and graftlint's C1 census
lifecycle.probe("monitor", active, disarm)
