"""Training-health monitor: device-side numerical health, host-side policy.

The telemetry registry (telemetry.py, ISSUE 1) records what the HOST does —
phase wall times, kernel-route decisions.  This module watches what the
DEVICE PROGRAM computes: a NaN gradient, an Inf score, an int8 quantization
collapsing to the saturation ceiling, or a tree full of zero-gain splits all
degrade accuracy silently — nothing in the phase timers or route counters
moves.  The reference C++ had neither problem nor remedy (doubles on a CPU
fail loudly); quantized gradients on an accelerator need an instrument.

Design constraints (the same two that shaped telemetry.py):

1. **Never perturb training numerics.**  The health vector is computed FROM
   the training arrays (gradients, hessians, scores, tree arrays), never
   fed back into them.  On the per-iteration path it runs as separate tiny
   jitted programs over the already-materialized device arrays — the
   grower/chunk programs and their jit caches are untouched.  On the fused
   chunk path the vector is accumulated inside the scan (the only place the
   per-iteration values exist) as extra, independent reductions stacked
   next to the metric values; the score/tree math is byte-for-byte the same
   expression graph (tests/test_health.py locks score bit-identity in, on
   vs off).

2. **One host fetch per iteration.**  The per-iteration path dispatches the
   health programs asynchronously and starts their host copies alongside
   the model readback the boosting loop already pays; the chunk path reads
   the stacked [k, H] vector with the stacked trees.  No extra
   synchronization points, no effect on async dispatch.

The host-side :class:`HealthMonitor` assembles the device vector with
tree-derived counts (zero-gain splits, empty leaves, degenerate trees —
free from the model readback), applies the ``on_anomaly`` policy
(``warn`` / ``halt`` / ``record``), tracks eval-metric divergence (k
consecutive worsening iterations, ``health_divergence_rounds``), and mirrors
anomaly totals into telemetry counters so multi-process runs fold them into
the leader's summary through the existing cross-host aggregation
(parallel/learners.aggregate_telemetry).
"""
from __future__ import annotations

import functools
from typing import Dict, Optional

import numpy as np

from . import telemetry
from .utils import log

# Device health-vector layout: indices 0..5 are plain COUNTS (cross-shard
# psum), 6 the saturation gauge (already cross-shard global inside
# quant_saturation_count), 7 a WATERMARK (cross-shard pmax).  health_vector
# relies on this split; keep new plain counts before index 6.
HEALTH_VEC_KEYS = (
    "grad_nan", "grad_inf", "hess_nan", "hess_inf",
    "score_nan", "score_inf", "quant_sat",
    "score_max_abs",
)

# Tree-derived keys appended on host from the model readback.
TREE_HEALTH_KEYS = ("zero_gain_splits", "empty_leaves", "degenerate_trees")

# Keys whose nonzero value is an ANOMALY under the on_anomaly policy.
# quant_sat and zero_gain/empty-leaf counts are gauges, not faults: the int8
# per-pass max scale saturates its max row by construction, and zero-gain
# nodes appear in healthy late training.
ANOMALY_KEYS = ("grad_nan", "grad_inf", "hess_nan", "hess_inf",
                "score_nan", "score_inf")


class TrainingHealthError(log.LightGBMError):
    """Raised by ``on_anomaly=halt`` — a clean, catchable training stop
    (the CLI maps it to exit code 1 like every LightGBMError)."""


def health_vector(grad, hess, score, *, quantized: bool = False,
                  axis_name: Optional[str] = None):
    """[8] f32 device health vector over one iteration's arrays.

    grad/hess: [C, N] (or [N]) gradients/hessians; score: [C, N] raw
    scores AFTER this iteration's update.  ``quantized`` adds the int8
    saturation gauge (ops/hist_pallas.quant_saturation_count — rows whose
    magnitude quantizes to the ±127 ceiling under the per-pass max scale).
    ``axis_name``: under shard_map, counts are psum'd and the watermark
    pmax'd so every shard carries the identical global vector.
    """
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32

    def count(pred):
        return jnp.sum(pred.astype(f32))

    counts = [count(jnp.isnan(grad)), count(jnp.isinf(grad)),
              count(jnp.isnan(hess)), count(jnp.isinf(hess)),
              count(jnp.isnan(score)), count(jnp.isinf(score))]
    if quantized:
        # quant_saturation_count is ALREADY cross-shard global (pmax'd
        # scale, psum'd count) — it must stay out of the psum below or
        # data-parallel runs would multiply it by the shard count
        from .ops.hist_pallas import quant_saturation_count
        qsat = quant_saturation_count(grad, hess, axis_name=axis_name)
    else:
        qsat = jnp.zeros((), f32)
    # watermark over FINITE scores only (a NaN would poison the max and
    # hide the magnitude trend that precedes overflow)
    finite = jnp.isfinite(score)
    smax = jnp.max(jnp.where(finite, jnp.abs(score), 0.0))
    vec_counts = jnp.stack(counts)
    if axis_name is not None:
        # wire-metrics coverage (ISSUE 5 / graftlint R1): tiny payloads,
        # but a full collective latency each — they belong in the
        # interconnect inventory like every other seam
        from . import telemetry
        telemetry.record_collective("health/vector_psum", "psum", axis_name,
                                    telemetry._tree_nbytes(vec_counts))
        telemetry.record_collective("health/score_pmax", "pmax", axis_name,
                                    telemetry._tree_nbytes(smax))
        vec_counts = jax.lax.psum(vec_counts, axis_name)
        smax = jax.lax.pmax(smax, axis_name)
    return jnp.concatenate([vec_counts, qsat[None], smax[None]])


@functools.lru_cache(maxsize=None)
def make_health_fn(quantized: bool, axis_name: Optional[str] = None):
    """Cached (grad, hess, score) -> [8] f32 closure for the fused chunk
    programs.  lru_cache keeps the closure identity stable so the chunk
    program caches (keyed on callable ids) hit across boosters."""
    def fn(grad, hess, score):
        return health_vector(grad, hess, score, quantized=quantized,
                             axis_name=axis_name)
    return fn


@functools.lru_cache(maxsize=None)
def _jitted_health(quantized: bool):
    """Per-iteration-path health program: one tiny jitted fn over the
    existing device arrays (grower programs and their caches untouched)."""
    import jax
    return jax.jit(functools.partial(health_vector, quantized=quantized))


def tree_health_counts(num_leaves: int, split_gain, leaf_count) -> dict:
    """Host-side tree health from an already-fetched TreeArrays: counts of
    zero/negative-gain recorded splits, empty leaves, and whether the tree
    is degenerate (unsplit root) — free with the model readback."""
    n = int(num_leaves)
    zero_gain = int(np.sum(np.asarray(split_gain)[:max(n - 1, 0)] <= 0.0))
    empty = int(np.sum(np.asarray(leaf_count)[:n] == 0)) if n > 1 else 0
    return {"zero_gain_splits": zero_gain, "empty_leaves": empty,
            "degenerate_trees": int(n <= 1)}


def resolve_enabled(health_setting: str) -> bool:
    """The ``health=`` resolution rule, single-homed: "auto" (default)
    follows the telemetry registry — armed telemetry (metrics_out= or
    library enable()) turns the monitor on; "true"/"false" force it."""
    if health_setting == "true":
        return True
    if health_setting == "false":
        return False
    return telemetry.enabled()


class HealthMonitor:
    """Per-booster health state: assembles iteration health blocks, applies
    the ``on_anomaly`` policy, tracks eval-metric divergence.

    The monitor never touches device state itself — GBDT hands it device
    vectors (or host numpy copies of them) and tree readbacks; everything
    here is host-side bookkeeping.
    """

    def __init__(self, on_anomaly: str = "warn",
                 divergence_rounds: int = 0, quantized: bool = False):
        self.on_anomaly = on_anomaly
        self.divergence_rounds = int(divergence_rounds)
        self.quantized = bool(quantized)
        self.totals: Dict[str, float] = {}
        self.anomalous_iterations = 0
        self._iter_tree: Dict[str, int] = {}
        self._warned: set = set()
        # eval divergence state: per "dataset/metric" key, the last value
        # and the current consecutive-worsening streak
        self._eval_last: Dict[str, float] = {}
        self._eval_streak: Dict[str, int] = {}
        self._pending_divergence: list = []

    # ------------------------------------------------------ device programs

    def grad_health_async(self, grad, hess, score):
        """Dispatch the health program and start its host copy; the result
        is fetched at finish_iteration, overlapping the link latency with
        the iteration's remaining device work."""
        vec = _jitted_health(self.quantized)(grad, hess, score)
        try:
            vec.copy_to_host_async()
        except Exception:
            pass
        return vec

    def chunk_health_fn(self, axis_name: Optional[str] = None):
        return make_health_fn(self.quantized, axis_name)

    # -------------------------------------------------------- accumulation

    def add_tree(self, num_leaves: int, split_gain, leaf_count) -> None:
        """Fold one tree's readback into the current iteration's counts."""
        for k, v in tree_health_counts(num_leaves, split_gain,
                                       leaf_count).items():
            self._iter_tree[k] = self._iter_tree.get(k, 0) + v

    def observe_eval(self, key: str, value: float,
                     bigger_better: bool) -> None:
        """Track one eval metric value; k consecutive worsening iterations
        (health_divergence_rounds) flag an ``eval_divergence`` anomaly."""
        if self.divergence_rounds <= 0:
            return
        last = self._eval_last.get(key)
        self._eval_last[key] = value
        if last is None:
            return
        if value != value:          # NaN metric: the most extreme
            worse = True            # divergence, not a streak reset
        elif last != last:
            worse = False           # recovery from NaN re-arms the streak
        else:
            worse = value < last if bigger_better else value > last
        streak = self._eval_streak.get(key, 0) + 1 if worse else 0
        self._eval_streak[key] = streak
        if streak >= self.divergence_rounds:
            self._pending_divergence.append(
                (key, streak, last, value))
            self._eval_streak[key] = 0   # re-arm, don't re-fire every iter

    # ------------------------------------------------------------- assembly

    def assemble(self, vec) -> dict:
        """Build the iteration's ``health`` block from the device vector
        (or None when the iteration produced no gradients) plus the
        accumulated tree counts.  Resets the per-iteration tree state."""
        block: Dict[str, float] = {}
        if vec is not None:
            vals = np.asarray(vec, np.float64)
            for i, k in enumerate(HEALTH_VEC_KEYS):
                block[k] = (float(vals[i]) if k == "score_max_abs"
                            else int(vals[i]))
        for k in TREE_HEALTH_KEYS:
            block[k] = self._iter_tree.get(k, 0)
        self._iter_tree = {}
        if self._pending_divergence:
            block["eval_divergence"] = [
                {"metric": k, "rounds": s,
                 "from": round(a, 6), "to": round(b, 6)}
                for k, s, a, b in self._pending_divergence]
        for k, v in block.items():
            if k == "eval_divergence":
                continue
            if k == "score_max_abs":
                self.totals[k] = max(self.totals.get(k, 0.0), v)
            else:
                self.totals[k] = self.totals.get(k, 0) + v
        return block

    def anomalies(self, block: dict) -> list:
        out = [k for k in ANOMALY_KEYS if block.get(k, 0)]
        out += ["eval_divergence:" + d["metric"]
                for d in block.get("eval_divergence", ())]
        return out

    def apply_policy(self, block: dict, iteration: int) -> None:
        """warn / halt / record on the iteration's anomalies.  Counters
        mirror every anomaly (``health/<kind>``) so cross-host aggregation
        and bench summaries see them regardless of policy."""
        found = self.anomalies(block)
        self._pending_divergence = []
        if not found:
            return
        self.anomalous_iterations += 1
        telemetry.count("health/anomalous_iterations")
        for kind in found:
            telemetry.count("health/" + kind.split(":")[0])
        detail = ", ".join(
            "%s=%s" % (k, block.get(k)) for k in ANOMALY_KEYS
            if block.get(k, 0))
        if block.get("eval_divergence"):
            detail = (detail + ("; " if detail else "")
                      + "eval divergence: " + ", ".join(
                          "%s (%d rounds)" % (d["metric"], d["rounds"])
                          for d in block["eval_divergence"]))
        if self.on_anomaly == "halt":
            log.error("training health anomaly at iteration %d (%s); "
                      "on_anomaly=halt — stopping" % (iteration, detail))
            raise TrainingHealthError(
                "training halted by health monitor at iteration %d: %s"
                % (iteration, detail))
        if self.on_anomaly == "warn":
            key = tuple(sorted(set(k.split(":")[0] for k in found)))
            if key not in self._warned:
                self._warned.add(key)
                log.warning("training health anomaly at iteration %d (%s); "
                            "recording every iteration, warning once per "
                            "anomaly kind (on_anomaly=warn)"
                            % (iteration, detail))

    def summary(self) -> dict:
        """Cumulative health totals (the end-of-run ``health`` summary
        block; bench.py attaches it to BENCH JSON lines)."""
        out = dict(self.totals)
        out["anomalous_iterations"] = self.anomalous_iterations
        return out
