"""Model layer: Tree, grower, GBDT booster, predictor."""
from .tree import Tree
from .gbdt import GBDT
