"""The tree grower: ONE schedule-parameterized module, three growth
policies (ISSUE 9).

Until PR 9 the repo carried three grower modules — masked leaf-wise
(``grower.py``), level-batched depth-wise (``grower_depthwise.py``) and
compacted leaf-wise (``grower_leafcompact.py``) — that each re-implemented
the same parallel seams (histogram reduce, int-domain reduce, root-stat
reduce, owned-slice cache, split finder, partition-index translate) and
had to be patched in lockstep by every parallel-layer change (PRs 3/5/6).
This module collapses them: the growth POLICY (``leafwise`` /
``depthwise`` / ``leafcompact``) and a declarative :class:`SeamSchedule`
are parameters, the policy bodies are instances sharing one copy of the
seam plumbing, and every seam is telemetry-wrapped exactly once
(:func:`wrap_schedule`).

Growth policies (semantics unchanged from the pre-collapse modules,
pinned by tests/test_grower_unified.py's recorded digests):

- ``leafwise`` — the reference's strict best-first growth
  (serial_tree_learner.cpp:119-153) as a ``lax.fori_loop`` over
  ``num_leaves - 1`` splits; DataPartition is a masked ``[N]`` leaf-id
  vector, each split builds ONE smaller-child histogram and derives the
  sibling by parent − smaller (serial_tree_learner.cpp:262-283).
- ``depthwise`` — level-batched growth for MXU throughput: all leaves of
  a level histogram in one leaf-batched matmul pass
  (ops/histogram.histogram_leafbatch), levels unrolled in Python.  Split
  ORDER is by level (documented TPU-first trade); the num_leaves budget
  is honored best-first within each level.
- ``leafcompact`` — reference-parity leaf-wise growth at the reference's
  geometric-series cost: rows kept physically partitioned in an
  ``[F+9, P]`` plane pane (ops/compact.py), per-split histograms run
  over the smaller child's bucketed lane range only.

Seam schedule — the parallel learners' customization surface
(parallel/learners.py builds these; ``None`` fields mean serial):

- ``hist_reduce`` / ``int_hist_reduce``: per-histogram cross-shard
  reduction (f32 / int-domain) — psum for data-parallel, a feature-block
  psum_scatter under the reduce_scatter ownership schedule, an
  owned-block-slice + data-axis psum for the 2-D hybrid learner.
- ``stat_reduce`` / ``root_hist_reduce`` / ``own_slice``: root-init
  seams (replicated full-F root, owned-block cache).
- ``split_finder``: replacement for ops/split.find_best_split — the
  ownership learners wrap it with the packed-SplitInfo argmax allreduce
  and must return GLOBAL feature indices; the voting learner's finder
  additionally runs the top-k vote + voted-feature histogram exchange.
- ``hist_reduce_level`` / ``int_reduce_level``: the depthwise policy's
  level-granularity variants.
- ``hist_local``: voting mode — histogram caches stay LOCAL (the voted
  exchange lives inside ``split_finder``), so int8-derived root stats
  must go through ``stat_reduce``.
- partition-index translate: the canonical→storage feature map applied
  when splits are APPLIED (mixed-bin packing's c2p permutation) — shared
  here as :func:`partition_feature`, the one copy of what each grower
  used to re-derive.
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histogram, histogram_leafbatch
from ..ops.split import SplitResult, find_best_split

GROW_POLICIES = ("leafwise", "depthwise", "leafcompact")

# out-of-bounds scatter index → mode="drop".  A plain int, NOT jnp.int32:
# creating a jax array at import time would initialize the XLA backend
# before jax.distributed.initialize can run (multi-process bootstrap).
BIG = 1 << 28


class TreeArrays(NamedTuple):
    """Fixed-shape device tree (mirrors tree.h:124-149)."""
    num_leaves: jax.Array       # i32 scalar
    split_feature: jax.Array    # [L-1] i32
    threshold_bin: jax.Array    # [L-1] i32
    split_gain: jax.Array       # [L-1] f32
    left_child: jax.Array       # [L-1] i32 (~leaf encoding)
    right_child: jax.Array      # [L-1] i32
    leaf_parent: jax.Array      # [L] i32
    leaf_value: jax.Array       # [L] f32
    leaf_count: jax.Array       # [L] i32
    leaf_ids: jax.Array         # [N] i32 — final row → leaf partition


class SeamSchedule(NamedTuple):
    """Declarative parallel-seam schedule (see module docstring).  A
    plain namedtuple of callables/flags: constructed per shard closure by
    the learners, never a jit static — the closures capture it."""
    hist_axis: Optional[str] = None
    hist_reduce: Optional[object] = None
    int_hist_reduce: Optional[object] = None
    stat_reduce: Optional[object] = None
    root_hist_reduce: Optional[object] = None
    own_slice: Optional[object] = None
    split_finder: Optional[object] = None
    # root candidate search: the leaf-wise policies run ONE root search
    # but trace the body finder inside the split fori_loop, so a finder
    # that carries collectives (voting) files its root exchange here at
    # a loop=1 executed-calls estimate instead of inheriting the body's
    # per-split loop factor (wire-metrics accuracy; values identical)
    root_split_finder: Optional[object] = None
    hist_reduce_level: Optional[object] = None
    int_reduce_level: Optional[object] = None
    hist_local: bool = False
    # TRACED [F] storage->canonical gather indices handed to every
    # histogram build (ops/histogram feat_gather): the block-local
    # mixed-bin layout's owned slice is built in PACKED order, and the
    # kernels gather it back to canonical order IN THE INT DOMAIN (before
    # dequantize/psum), so the cache, root stats, subtraction and split
    # search are all canonical and the downstream f32 graph is
    # shape-identical to the uniform layout's — packed-vs-uniform stays
    # bit-identical including argmax tie-breaks and XLA FMA-contraction
    # choices (ISSUE 12; learners derive it from the shard rank, so the
    # SPMD program is shard-uniform even though each block's permutation
    # differs)
    hist_feat_gather: Optional[object] = None


_SERIAL = SeamSchedule()

# seam field → telemetry site suffix; per-split loop marks the seams that
# run inside the leaf-wise/compact split fori_loop (traced once, executed
# once per split) — the depthwise level seams trace once PER LEVEL
_SEAM_SITES = (
    ("hist_reduce", "hist_reduce", True),
    ("int_hist_reduce", "int_hist_reduce", True),
    ("stat_reduce", "root_stats", False),
    ("root_hist_reduce", "root_hist", False),
    ("hist_reduce_level", "level_hist_reduce", False),
    ("int_reduce_level", "level_int_reduce", False),
)


def wrap_schedule(policy: str, schedule: Optional[SeamSchedule],
                  num_splits: int) -> SeamSchedule:
    """Wire-metrics hook point (ISSUE 5), applied ONCE for every policy:
    any seam not already labeled by the learner that built it
    (telemetry.collective_span passes wrapped fns through) gets a
    grower-generic ``<policy>/<seam>`` site here, so custom learners'
    collectives still show up in the interconnect block.  The wrappers
    call the seam unchanged — traced programs are bit-identical."""
    from .. import telemetry as _tl
    s = schedule if schedule is not None else _SERIAL
    per_split = policy != "depthwise"
    updates = {}
    for field, suffix, split_loop in _SEAM_SITES:
        fn = getattr(s, field)
        if fn is None:
            continue
        loop = num_splits if (split_loop and per_split) else 1
        updates[field] = _tl.collective_span(
            "%s/%s" % (policy, suffix), fn, kind="reduce",
            axis=s.hist_axis, loop=loop, phase="grow")
    return s._replace(**updates) if updates else s


def _is_int8(compute_dtype) -> bool:
    return str(compute_dtype).startswith("int8")


def _patchable(module_name: str, attr: str, default):
    """Resolve a histogram entry through its historical compat module at
    trace time: tests and scripts/profile_phases.py monkeypatch
    ``grower.build_histogram`` / ``grower_depthwise.histogram_leafbatch``
    (the established stub seams), and the collapse must not silently
    disconnect them."""
    import importlib
    try:
        mod = importlib.import_module("%s.%s" % (__package__, module_name))
        return getattr(mod, attr, default)
    except Exception:  # pragma: no cover - import cycle during bootstrap
        return default


def partition_feature(packing, feat):
    """The partition-index-translate seam, single-homed: canonical split
    feature → row index of the STORAGE-layout bin matrix (mixed-bin
    packing reorders rows into bin-width classes; split results stay
    canonical — io/binning.PackSpec)."""
    if packing is not None and len(packing.widths) > 1:
        return jnp.asarray(packing.c2p, jnp.int32)[feat]
    return feat


def _apply_hist_reduce(hist, s: SeamSchedule, compute_dtype):
    """The shared reduce rule: the quantized path reduces its INT
    accumulators internally over hist_axis (bit-exactness;
    ops/hist_pallas.quantize_values) — psum by default, the ownership
    feature-block scatter when int_hist_reduce is set — so the f32
    hist_reduce must not run again on top."""
    if s.hist_reduce is not None and not (
            _is_int8(compute_dtype) and s.hist_axis is not None):
        hist = s.hist_reduce(hist)
    return hist


def _root_stats_of(full_hist, s: SeamSchedule, compute_dtype, grad, hess,
                   row_mask):
    """Root stats, shared by the leaf-wise and compact policies.

    int8: derive from the histogram — the int accumulators are
    bit-identical across serial/data-parallel (scales pmax-synced, int32
    sums order-free) and any feature's bins sum to the same exact
    quantized totals, so this also holds under feature-parallel ownership
    slices.  Under an ownership schedule the stats must come from the
    replicated full-F root, not the owned block (a feature-padding
    shard's block is all zeros); under ``hist_local`` (voting) the local
    totals must still be stat_reduce'd to global.

    f32: root sums come from the gradient vectors, not from any one
    feature's histogram — per-feature f32 bin-order rounding would make
    the totals shard-dependent under feature ownership (the reference
    likewise computes root sums once from gradients,
    serial_tree_learner.cpp:178-198)."""
    if _is_int8(compute_dtype):
        root_stats = jnp.sum(full_hist[0], axis=0)
        if s.hist_local and s.stat_reduce is not None:
            root_stats = s.stat_reduce(root_stats)
        return root_stats
    maskf = row_mask.astype(jnp.float32)
    root_stats = jnp.stack([jnp.sum(grad * maskf), jnp.sum(hess * maskf),
                            jnp.sum(maskf)])
    if s.stat_reduce is not None:
        root_stats = s.stat_reduce(root_stats)
    return root_stats


def _root_hist_pair(hist_full_fn, hist_of_fn, s: SeamSchedule,
                    compute_dtype):
    """(full, cached-root) histograms, shared by leaf-wise and compact:
    under an ownership schedule (own_slice set) the ROOT is built
    replicated — full F, plain psum — so root stats are exact on every
    shard including feature-PADDING shards, then only the owned slice is
    cached.  ``hist_full_fn`` builds the unreduced full histogram;
    ``hist_of_fn`` the seam-reduced one."""
    if s.own_slice is not None:
        full = hist_full_fn()
        if s.root_hist_reduce is not None and not (
                _is_int8(compute_dtype) and s.hist_axis is not None):
            full = s.root_hist_reduce(full)
        return full, s.own_slice(full)
    if s.root_hist_reduce is not None and not (
            _is_int8(compute_dtype) and s.hist_axis is not None):
        # masked psum schedules: the ONE root exchange rides its own
        # root-loop-labeled site — letting it ride hist_reduce would file
        # it at the body's per-split executed-calls estimate and inflate
        # the wire-bytes series (same psum, values bit-identical)
        full = s.root_hist_reduce(hist_full_fn())
        return full, full
    full = hist_of_fn()
    return full, full


def _depth_gated(res: SplitResult, depth, max_depth: int) -> SplitResult:
    """depth-limited leaves cannot split (serial_tree_learner.cpp:240-249)"""
    if max_depth > 0:
        res = res._replace(gain=jnp.where(depth >= max_depth, -jnp.inf,
                                          res.gain))
    return res


# ===================================================================== API

_GROW_STATICS = ("policy", "num_leaves", "num_bins_max", "min_data_in_leaf",
                 "min_sum_hessian_in_leaf", "max_depth", "hist_backend",
                 "hist_chunk", "compute_dtype", "packing",
                 "partition_packing",
                 "use_pallas_partition", "partition_overlap", "interpret")


def grow_tree_unified(bins, grad, hess, row_mask, feature_mask, num_bins,
                      *, policy: str, num_leaves: int, num_bins_max: int,
                      min_data_in_leaf: int, min_sum_hessian_in_leaf: float,
                      max_depth: int = -1, hist_backend: str = "matmul",
                      hist_chunk: int = 0, compute_dtype=jnp.float32,
                      packing=None, partition_packing=None,
                      use_pallas_partition: bool = False,
                      partition_overlap: bool = True,
                      interpret: bool = False,
                      schedule: Optional[SeamSchedule] = None,
                      partition_bins=None,
                      init_state=None, loop_count=None,
                      return_state: bool = False):
    """Grow one tree (TreeLearner::Train) under any growth policy × seam
    schedule.  Not jitted; callers wrap it (the module-level jits below,
    the learners' shard closures, the chunk-program builders).

    Parameters
    ----------
    bins : [F, N] integer bin matrix (N may be the local row shard under
        shard_map; F may be an owned feature slice under feature
        ownership — ``partition_bins`` then carries the full matrix)
    grad, hess : [N] f32 gradients/hessians from the objective
    row_mask : [N] bool — bagging × validity mask; masked rows still get
        leaf ids (OOB score updates come free, unlike gbdt.cpp:159-165)
    feature_mask, num_bins : [F] feature_fraction mask / real bin counts
        (owned slices under feature ownership)
    policy : leafwise | depthwise | leafcompact (see module docstring)
    schedule : SeamSchedule — the parallel seams; None = serial
    partition_bins : [F_global, N] matrix used to APPLY splits when
        ``bins`` is only an owned feature slice; split_finder must then
        return GLOBAL feature indices
    hist_chunk : row-chunk length of the histogram scan; 0 = the
        policy's default (16384 leaf-wise/compact, 65536 depthwise)
    packing / partition_packing : mixed-bin layout specs.  ``packing``
        describes the layout of ``bins`` (the histogram passes);
        ``partition_packing`` (default: ``packing``) the layout of
        ``partition_bins`` — they differ under the block-local ownership
        layout (io/binning.BlockedPackSpec), where the owned slice uses
        the shard-uniform ``block_view`` while splits apply on the full
        blocked storage matrix via the GLOBAL canonical->storage map
    use_pallas_partition / partition_overlap / interpret : the compact
        policy's partition-kernel routing (ops/compact.partition_segment)
    init_state / loop_count / return_state : the leaf-wise policy's
        dispatch-segmentation seam (grow_tree_segmented): resume from a
        carried _GrowState, run only ``loop_count`` split attempts,
        return the full state.  The split body never reads the loop
        index, so segmenting fori_loop(0, L-1) is EXACTLY the same
        program.
    """
    if policy not in GROW_POLICIES:
        raise ValueError("unknown grow policy %r" % (policy,))
    if hist_chunk <= 0:
        hist_chunk = 65536 if policy == "depthwise" else 16384
    s = wrap_schedule(policy, schedule, max(num_leaves - 1, 1))
    kwargs = dict(num_leaves=num_leaves, num_bins_max=num_bins_max,
                  min_data_in_leaf=min_data_in_leaf,
                  min_sum_hessian_in_leaf=min_sum_hessian_in_leaf,
                  max_depth=max_depth, hist_chunk=hist_chunk,
                  compute_dtype=compute_dtype, packing=packing,
                  partition_packing=(partition_packing
                                     if partition_packing is not None
                                     else packing))
    if policy == "depthwise":
        if return_state or init_state is not None:
            raise ValueError("dispatch segmentation is a leafwise seam")
        return _grow_depthwise(bins, grad, hess, row_mask, feature_mask,
                               num_bins, s, partition_bins, **kwargs)
    if policy == "leafcompact":
        if init_state is not None or loop_count is not None:
            raise ValueError("dispatch segmentation is a leafwise seam")
        return _grow_leafcompact(bins, grad, hess, row_mask, feature_mask,
                                 num_bins, s, hist_backend=hist_backend,
                                 use_pallas_partition=use_pallas_partition,
                                 partition_overlap=partition_overlap,
                                 interpret=interpret,
                                 return_state=return_state, **kwargs)
    return _grow_leafwise(bins, grad, hess, row_mask, feature_mask,
                          num_bins, s, partition_bins,
                          hist_backend=hist_backend,
                          init_state=init_state, loop_count=loop_count,
                          return_state=return_state, **kwargs)


# ====================================================== leaf-wise policy

class _GrowState(NamedTuple):
    tree: TreeArrays
    hist_cache: jax.Array       # [L, F, B, 3]
    cand_gain: jax.Array        # [L]
    cand_feature: jax.Array     # [L]
    cand_threshold: jax.Array   # [L]
    cand_left_out: jax.Array    # [L]
    cand_right_out: jax.Array
    cand_left_cnt: jax.Array    # [L] i32
    cand_right_cnt: jax.Array
    cand_left_g: jax.Array
    cand_left_h: jax.Array
    cand_right_g: jax.Array
    cand_right_h: jax.Array
    leaf_sum_g: jax.Array       # [L]
    leaf_sum_h: jax.Array
    leaf_cnt: jax.Array         # [L] i32
    leaf_depth: jax.Array       # [L] i32
    done: jax.Array             # bool scalar


def _grow_leafwise(bins, grad, hess, row_mask, feature_mask, num_bins,
                   s: SeamSchedule, partition_bins, *, num_leaves: int,
                   num_bins_max: int, min_data_in_leaf: int,
                   min_sum_hessian_in_leaf: float, max_depth: int,
                   hist_backend: str, hist_chunk: int, compute_dtype,
                   packing, partition_packing=None, init_state=None,
                   loop_count=None, return_state: bool = False):
    """Masked leaf-wise growth (the reference's TreeLearner::Train,
    serial_tree_learner.cpp:119-153): DataPartition's permuted index
    lists become a [N] leaf-id vector, the LRU histogram pool a dense
    [L, F, B, 3] cache carried through the split fori_loop, and the
    smaller-leaf + subtraction trick is kept per split."""
    F, N = bins.shape
    L = num_leaves
    B = num_bins_max
    f32 = jnp.float32
    finder = s.split_finder or find_best_split
    build_hist = _patchable("grower", "build_histogram", build_histogram)
    if partition_bins is None:
        partition_bins = bins
    _fg = ({"feat_gather": s.hist_feat_gather}
           if s.hist_feat_gather is not None else {})

    def hist_of(mask, salt=0):
        hist = build_hist(bins, grad, hess, mask, B,
                               backend=hist_backend, chunk=hist_chunk,
                               compute_dtype=compute_dtype,
                               axis_name=s.hist_axis,
                               int_reduce=s.int_hist_reduce, salt=salt,
                               packing=packing, **_fg)
        return _apply_hist_reduce(hist, s, compute_dtype)

    def best_of(hist, sum_g, sum_h, cnt, depth, root=False):
        f = (s.root_split_finder or finder) if root else finder
        res = f(hist, sum_g, sum_h, cnt, num_bins, feature_mask,
                float(min_data_in_leaf),
                float(min_sum_hessian_in_leaf))
        return _depth_gated(res, depth, max_depth)

    # ---- root init (BeforeTrain, serial_tree_learner.cpp:155-236);
    # skipped entirely when resuming from a carried state (segmentation)
    def _root_state() -> _GrowState:
        full, root_hist = _root_hist_pair(
            lambda: build_hist(bins, grad, hess, row_mask, B,
                               backend=hist_backend, chunk=hist_chunk,
                               compute_dtype=compute_dtype,
                               axis_name=s.hist_axis, packing=packing,
                               **_fg),
            lambda: hist_of(row_mask), s, compute_dtype)
        root_stats = _root_stats_of(full, s, compute_dtype, grad, hess,
                                    row_mask)
        root_g, root_h, root_c = root_stats[0], root_stats[1], root_stats[2]
        root_best = best_of(root_hist, root_g, root_h, root_c,
                            jnp.asarray(1, jnp.int32), root=True)

        neg_inf = jnp.full((L,), -jnp.inf, dtype=f32)
        zeros_i = jnp.zeros((L,), dtype=jnp.int32)
        zeros_f = jnp.zeros((L,), dtype=f32)

        tree = TreeArrays(
            num_leaves=jnp.asarray(1, jnp.int32),
            split_feature=jnp.zeros((L - 1,), jnp.int32),
            threshold_bin=jnp.zeros((L - 1,), jnp.int32),
            split_gain=jnp.zeros((L - 1,), f32),
            left_child=jnp.zeros((L - 1,), jnp.int32),
            right_child=jnp.zeros((L - 1,), jnp.int32),
            leaf_parent=jnp.full((L,), -1, jnp.int32),
            leaf_value=zeros_f,
            leaf_count=zeros_i.at[0].set(root_c.astype(jnp.int32)),
            leaf_ids=jnp.zeros((N,), jnp.int32),
        )
        return _GrowState(
            tree=tree,
            hist_cache=jnp.zeros((L,) + root_hist.shape,
                                 f32).at[0].set(root_hist),
            cand_gain=neg_inf.at[0].set(root_best.gain),
            cand_feature=zeros_i.at[0].set(root_best.feature),
            cand_threshold=zeros_i.at[0].set(root_best.threshold),
            cand_left_out=zeros_f.at[0].set(root_best.left_output),
            cand_right_out=zeros_f.at[0].set(root_best.right_output),
            cand_left_cnt=zeros_i.at[0].set(root_best.left_count),
            cand_right_cnt=zeros_i.at[0].set(root_best.right_count),
            cand_left_g=zeros_f.at[0].set(root_best.left_sum_grad),
            cand_left_h=zeros_f.at[0].set(root_best.left_sum_hess),
            cand_right_g=zeros_f.at[0].set(root_best.right_sum_grad),
            cand_right_h=zeros_f.at[0].set(root_best.right_sum_hess),
            leaf_sum_g=zeros_f.at[0].set(root_g),
            leaf_sum_h=zeros_f.at[0].set(root_h),
            leaf_cnt=zeros_i.at[0].set(root_c.astype(jnp.int32)),
            leaf_depth=zeros_i.at[0].set(1),
            done=jnp.asarray(False),
        )

    state = init_state if init_state is not None else _root_state()

    def body(_, state: _GrowState) -> _GrowState:
        # pick the best leaf to split (FindBestSplitsForLeaves argmax,
        # serial_tree_learner.cpp:140-147)
        best_leaf = jnp.argmax(state.cand_gain).astype(jnp.int32)
        best_gain = state.cand_gain[best_leaf]
        should_split = jnp.logical_and(~state.done, best_gain > 0.0)

        def do_split(state: _GrowState) -> _GrowState:
            tree = state.tree
            bl = best_leaf
            nl = tree.num_leaves
            node = nl - 1
            new_leaf = nl

            feat = state.cand_feature[bl]
            thr = state.cand_threshold[bl]

            # --- record the node (Tree::Split, tree.cpp:50-83)
            p = tree.leaf_parent[bl]
            pp = jnp.maximum(p, 0)
            lc_at_p = jnp.where((p >= 0) & (tree.left_child[pp] == ~bl),
                                node, tree.left_child[pp])
            rc_at_p = jnp.where((p >= 0) & (tree.right_child[pp] == ~bl),
                                node, tree.right_child[pp])
            left_child = tree.left_child.at[pp].set(lc_at_p).at[node].set(~bl)
            right_child = (tree.right_child.at[pp].set(rc_at_p)
                           .at[node].set(~new_leaf))

            # --- partition rows (DataPartition::Split as masked where,
            # data_partition.hpp:93-139), split feature translated through
            # the storage-layout map (partition-index-translate seam)
            pfeat = partition_feature(partition_packing, feat)
            fbin = jax.lax.dynamic_index_in_dim(
                partition_bins, pfeat, axis=0, keepdims=False).astype(jnp.int32)
            go_right = fbin > thr
            leaf_ids = jnp.where((tree.leaf_ids == bl) & go_right,
                                 new_leaf, tree.leaf_ids)

            # --- child histograms: build the smaller, subtract for the larger
            # (serial_tree_learner.cpp:262-283)
            lcnt = state.cand_left_cnt[bl]
            rcnt = state.cand_right_cnt[bl]
            left_is_smaller = lcnt <= rcnt
            small_leaf = jnp.where(left_is_smaller, bl, new_leaf)
            small_mask = row_mask & (leaf_ids == small_leaf)
            # salt = the new leaf index: varies per split pass so the
            # stochastic-rounding bits decorrelate across passes
            small_hist = hist_of(small_mask, salt=new_leaf)
            parent_hist = state.hist_cache[bl]
            large_hist = parent_hist - small_hist
            lhist = jnp.where(left_is_smaller, small_hist, large_hist)
            rhist = jnp.where(left_is_smaller, large_hist, small_hist)

            # --- child stats
            lg, lh = state.cand_left_g[bl], state.cand_left_h[bl]
            rg, rh = state.cand_right_g[bl], state.cand_right_h[bl]
            depth = state.leaf_depth[bl] + 1

            # --- new candidate splits for both children.  Issued BEFORE
            # the [L, F, B, 3] cache scatter below: under an ownership
            # schedule the finder carries the packed-SplitInfo allgather,
            # and putting it first in program order lets XLA's async
            # collective scheduler overlap the wire latency with the
            # cache writeback's HBM traffic and the node bookkeeping that
            # dispatches the next split (ISSUE 9 overlap seam; pure
            # scheduling — the traced values are bit-identical)
            lbest = best_of(lhist, lg, lh, lcnt.astype(f32), depth)
            rbest = best_of(rhist, rg, rh, rcnt.astype(f32), depth)
            hist_cache = state.hist_cache.at[bl].set(lhist).at[new_leaf].set(rhist)

            tree = tree._replace(
                num_leaves=nl + 1,
                split_feature=tree.split_feature.at[node].set(feat),
                threshold_bin=tree.threshold_bin.at[node].set(thr),
                split_gain=tree.split_gain.at[node].set(best_gain),
                left_child=left_child,
                right_child=right_child,
                leaf_parent=tree.leaf_parent.at[bl].set(node)
                                            .at[new_leaf].set(node),
                leaf_value=tree.leaf_value.at[bl].set(state.cand_left_out[bl])
                                          .at[new_leaf].set(state.cand_right_out[bl]),
                leaf_count=tree.leaf_count.at[bl].set(lcnt)
                                          .at[new_leaf].set(rcnt),
                leaf_ids=leaf_ids,
            )
            return state._replace(
                tree=tree,
                hist_cache=hist_cache,
                cand_gain=state.cand_gain.at[bl].set(lbest.gain)
                                         .at[new_leaf].set(rbest.gain),
                cand_feature=state.cand_feature.at[bl].set(lbest.feature)
                                               .at[new_leaf].set(rbest.feature),
                cand_threshold=state.cand_threshold.at[bl].set(lbest.threshold)
                                                   .at[new_leaf].set(rbest.threshold),
                cand_left_out=state.cand_left_out.at[bl].set(lbest.left_output)
                                                 .at[new_leaf].set(rbest.left_output),
                cand_right_out=state.cand_right_out.at[bl].set(lbest.right_output)
                                                   .at[new_leaf].set(rbest.right_output),
                cand_left_cnt=state.cand_left_cnt.at[bl].set(lbest.left_count)
                                                 .at[new_leaf].set(rbest.left_count),
                cand_right_cnt=state.cand_right_cnt.at[bl].set(lbest.right_count)
                                                   .at[new_leaf].set(rbest.right_count),
                cand_left_g=state.cand_left_g.at[bl].set(lbest.left_sum_grad)
                                             .at[new_leaf].set(rbest.left_sum_grad),
                cand_left_h=state.cand_left_h.at[bl].set(lbest.left_sum_hess)
                                             .at[new_leaf].set(rbest.left_sum_hess),
                cand_right_g=state.cand_right_g.at[bl].set(lbest.right_sum_grad)
                                               .at[new_leaf].set(rbest.right_sum_grad),
                cand_right_h=state.cand_right_h.at[bl].set(lbest.right_sum_hess)
                                               .at[new_leaf].set(rbest.right_sum_hess),
                leaf_sum_g=state.leaf_sum_g.at[bl].set(lg).at[new_leaf].set(rg),
                leaf_sum_h=state.leaf_sum_h.at[bl].set(lh).at[new_leaf].set(rh),
                leaf_cnt=state.leaf_cnt.at[bl].set(lcnt).at[new_leaf].set(rcnt),
                leaf_depth=state.leaf_depth.at[bl].set(depth)
                                           .at[new_leaf].set(depth),
            )

        def no_split(state: _GrowState) -> _GrowState:
            return state._replace(done=jnp.asarray(True))

        # profiler alignment (ISSUE 2): the whole split body is labeled in
        # HLO metadata so profile_dir= traces group the per-split ops
        with jax.named_scope("leafwise_split"):
            return jax.lax.cond(should_split, do_split, no_split, state)

    count = L - 1 if loop_count is None else loop_count
    state = jax.lax.fori_loop(0, count, body, state)
    return state if return_state else state.tree


# ====================================================== depthwise policy

def num_levels(num_leaves: int, max_depth: int = -1) -> int:
    """Number of split levels.  Matches the leaf-wise depth rule (a leaf
    at depth >= max_depth cannot split, root depth 1), so max_depth
    allows max_depth - 1 split levels."""
    d = max(1, math.ceil(math.log2(max(num_leaves, 2))))
    if max_depth > 0:
        d = min(d, max(max_depth - 1, 1))
    return d


def _grow_depthwise(bins, grad, hess, row_mask, feature_mask, num_bins,
                    s: SeamSchedule, partition_bins, *, num_leaves: int,
                    num_bins_max: int, min_data_in_leaf: int,
                    min_sum_hessian_in_leaf: float, max_depth: int,
                    hist_chunk: int, compute_dtype, packing,
                    partition_packing=None) -> TreeArrays:
    """Depth-wise (level-batched) growth — the TPU throughput path: the
    histograms of ALL leaves of a level build in ONE leaf-batched matmul
    pass (3·P value columns fill the MXU; 8 batched passes for a 255-leaf
    tree instead of 254 single-leaf passes), levels unrolled in Python
    with static [P = 2^d] slot shapes.  The smaller-child + subtraction
    trick is kept at level granularity.  Split-finding math is identical
    to leaf-wise; split ORDER is by level (documented TPU-first trade),
    the num_leaves budget honored best-first within each level."""
    F, N = bins.shape
    L = num_leaves
    D = num_levels(L, max_depth)
    B = num_bins_max
    f32 = jnp.float32
    i32 = jnp.int32

    from .. import telemetry

    maskf = row_mask.astype(f32)
    mind = float(min_data_in_leaf)
    minh = float(min_sum_hessian_in_leaf)
    leafbatch = _patchable("grower_depthwise", "histogram_leafbatch",
                           histogram_leafbatch)

    def batch_hist_rows(b, g, h, col_id, col_ok, C, level=False, salt=0):
        # level passes may use the scatter schedule; the root pass always
        # reduces in full
        int_red = s.int_reduce_level if level else None
        # forward optional kwargs only when set: drop-in replacements
        # (histogram_leafbatch_segsum, test/profiling stubs) don't take
        # them
        extra = {"int_reduce": int_red} if int_red is not None else {}
        if s.hist_feat_gather is not None:
            extra["feat_gather"] = s.hist_feat_gather
        if salt and compute_dtype == "int8_sr":
            extra["salt"] = salt
        out = leafbatch(b, g, h, col_id, col_ok, C, B,
                        chunk=hist_chunk,
                        compute_dtype=compute_dtype,
                        axis_name=s.hist_axis,
                        **({"packing": packing}
                           if packing is not None else {}),
                        **extra)
        # the quantized path reduces its INT accumulators internally over
        # hist_axis (bit-exactness); applying hist_reduce again would
        # double-count
        if _is_int8(compute_dtype) and s.hist_axis is not None:
            return out
        red = (s.hist_reduce_level or s.hist_reduce) if level \
            else s.hist_reduce
        if red is not None:
            out = red(out)
        return out

    def batch_hist(col_id, col_ok, C, level=False, salt=0):
        return batch_hist_rows(bins, grad, hess, col_id, col_ok, C,
                               level=level, salt=salt)

    vsplit = jax.vmap(s.split_finder or find_best_split,
                      in_axes=(0, 0, 0, 0, None, None, None, None))
    if partition_bins is None:
        partition_bins = bins

    # ---- root (BeforeTrain: serial_tree_learner.cpp:155-236).
    # named_scope per level (ISSUE 2): profile_dir= Perfetto traces show
    # the unrolled level structure ("level0/histogram", ...) instead of a
    # flat op soup — unconditional, so it can't perturb program identity
    with jax.named_scope("level0"):
        hists = batch_hist(jnp.zeros((N,), i32), row_mask, 1)  # [1,F,B,3]
    root_stats = _root_stats_of(hists[0], s, compute_dtype, grad, hess,
                                row_mask)
    if s.own_slice is not None:
        # ownership schedule: keep only this shard's contiguous feature
        # block from here on (root stats above came from the full
        # replicated histogram, so they stay bit-identical to the psum
        # schedule)
        hists = s.own_slice(hists)

    # per-slot level state (slot s at level d holds one candidate leaf)
    alive = jnp.ones((1,), bool)
    leaf_of = jnp.zeros((1,), i32)          # output leaf index per slot
    parent_node = jnp.full((1,), -1, i32)   # node owning this slot's leaf
    slot_g = root_stats[0][None]
    slot_h = root_stats[1][None]
    slot_c = root_stats[2][None]

    slot_id = jnp.zeros((N,), i32)          # row → level-local slot
    out_leaf = jnp.zeros((N,), i32)         # row → output leaf index

    # output tree arrays (static size L)
    leaf_value = jnp.zeros((L,), f32)
    leaf_count = jnp.zeros((L,), i32).at[0].set(root_stats[2].astype(i32))
    leaf_parent = jnp.full((L,), -1, i32)
    split_feature = jnp.zeros((max(L - 1, 1),), i32)
    threshold_bin = jnp.zeros((max(L - 1, 1),), i32)
    split_gain = jnp.zeros((max(L - 1, 1),), f32)
    left_child = jnp.zeros((max(L - 1, 1),), i32)
    right_child = jnp.zeros((max(L - 1, 1),), i32)

    n_nodes = jnp.asarray(0, i32)           # == num_leaves_cur - 1

    for d in range(D):
        P = 1 << d

        # ---- best split per slot (vmapped FindBestThreshold scan).  The
        # span wraps the CALL (not the vmapped body — a batching trace is
        # never "execution"), so eager runs (jax.disable_jit telemetry
        # profiling) attribute real split-search time
        with telemetry.span("split_find") as _sp:
            res = _sp.fence(vsplit(hists, slot_g, slot_h, slot_c, num_bins,
                                   feature_mask, mind, minh))
        can = alive & (res.gain > 0.0) & jnp.isfinite(res.gain)

        # ---- budget: split the top-gain slots first (within-level
        # best-first, matching the leaf-wise selection rule at level scope)
        budget = (L - 1) - n_nodes
        gains_m = jnp.where(can, res.gain, -jnp.inf)
        order = jnp.argsort(-gains_m)                 # best slot first
        rank = jnp.argsort(order).astype(i32)         # slot → rank
        chosen = can & (rank < budget)
        n_chosen = jnp.sum(chosen.astype(i32))

        # ---- index assignment, in slot order (deterministic)
        csum = jnp.cumsum(chosen.astype(i32))
        node_of = n_nodes + csum - 1                  # node per chosen slot
        right_leaf = (n_nodes + 1) + csum - 1         # new leaf per chosen
        bl = leaf_of

        nidx = jnp.where(chosen, node_of, BIG)
        blx = jnp.where(chosen, bl, BIG)
        rlx = jnp.where(chosen, right_leaf, BIG)

        # ---- node records (Tree::Split, tree.cpp:50-83)
        split_feature = split_feature.at[nidx].set(res.feature, mode="drop")
        threshold_bin = threshold_bin.at[nidx].set(res.threshold, mode="drop")
        split_gain = split_gain.at[nidx].set(res.gain, mode="drop")
        left_child = left_child.at[nidx].set(~bl, mode="drop")
        right_child = right_child.at[nidx].set(~right_leaf, mode="drop")

        # parent child-pointer fixup: slot parity says which side this
        # slot's leaf sits on in its parent node (even = left)
        pfix = jnp.where(chosen & (parent_node >= 0), parent_node, BIG)
        if d > 0:
            is_left = (jnp.arange(P, dtype=i32) % 2) == 0
            left_child = left_child.at[
                jnp.where(is_left, pfix, BIG)].set(node_of, mode="drop")
            right_child = right_child.at[
                jnp.where(is_left, BIG, pfix)].set(node_of, mode="drop")

        # ---- leaf records
        leaf_value = leaf_value.at[blx].set(res.left_output, mode="drop")
        leaf_value = leaf_value.at[rlx].set(res.right_output, mode="drop")
        leaf_count = leaf_count.at[blx].set(res.left_count, mode="drop")
        leaf_count = leaf_count.at[rlx].set(res.right_count, mode="drop")
        leaf_parent = leaf_parent.at[blx].set(node_of, mode="drop")
        leaf_parent = leaf_parent.at[rlx].set(node_of, mode="drop")

        n_nodes = n_nodes + n_chosen

        # ---- partition rows (DataPartition::Split as fused masked passes)
        # All per-slot attributes a row needs (split feature, threshold,
        # chosen flag, new right-leaf id, smaller-child side) ride ONE
        # [P, N] one-hot matmul instead of one pass per attribute: the
        # slot-select one-hot is the expensive object (O(P·N) comparisons),
        # so it is generated once and contracted against a packed [P, K]
        # table.
        small_is_right = res.right_count < res.left_count        # ties → left
        with telemetry.span("partition") as _sp:
            # mixed-bin packing stores the matrix rows in packed order;
            # the per-slot partition feature must address that layout
            # (the recorded split_feature above stays canonical)
            feat_part = partition_feature(partition_packing, res.feature)
            table = jnp.stack([feat_part.astype(f32),
                               res.threshold.astype(f32),
                               chosen.astype(f32),
                               right_leaf.astype(f32),
                               small_is_right.astype(f32)], axis=1)  # [P, 5]
            lsel = (slot_id[None, :] ==
                    jnp.arange(P, dtype=i32)[:, None]).astype(f32)   # [P, N]
            # The table carries integer ids (feature, threshold, leaf).
            # Default TPU matmul precision truncates f32 operands to bf16,
            # which is EXACT for integers <= 256 — and exactly one lsel
            # entry matches per row, so there is no accumulation error
            # either.  Only configs with ids beyond 256 need the 6-pass
            # HIGHEST decomposition (measured 2.27 ms vs 0.72 ms per level
            # at 11M rows).  Feature ids are GLOBAL (split_finder returns
            # canonical ids even when ``bins`` is an owned slice), so the
            # guard must use the global width, not the sliced F.
            ids_bf16_exact = max(partition_bins.shape[0], B, L) <= 256
            attr_prec = (None if ids_bf16_exact
                         else jax.lax.Precision.HIGHEST)
            attrs = jnp.einsum("pn,pk->kn", lsel, table,
                               precision=attr_prec,
                               preferred_element_type=jnp.float32)   # [5, N]
            feat_row = attrs[0].astype(i32)
            thr_row = attrs[1].astype(i32)
            in_chosen = attrs[2] > 0.5
            rl_row = attrs[3].astype(i32)
            small_right_row = attrs[4] > 0.5

            # the row's bin on its slot's split feature: an O(F·N) feature
            # one-hot avoids materializing the old [P, N] row gather, but
            # its cost grows with the dataset width — for wide datasets a
            # direct per-row gather is cheaper than F·N comparisons
            Fg = partition_bins.shape[0]
            if Fg <= 128:
                fsel = (feat_row[None, :]
                        == jnp.arange(Fg, dtype=i32)[:, None])
                # bins < 256 are bf16-exact and one fsel entry matches per
                # row
                row_bin = jnp.einsum(
                    "fn,fn->n", fsel.astype(f32), partition_bins.astype(f32),
                    precision=(None if B <= 256
                               else jax.lax.Precision.HIGHEST)).astype(i32)
            else:
                row_bin = jnp.take_along_axis(
                    partition_bins, feat_row[None, :], axis=0)[0].astype(i32)
            go_right = row_bin > thr_row
            out_leaf = jnp.where(in_chosen & go_right, rl_row, out_leaf)
            slot_id = (2 * slot_id
                       + jnp.where(in_chosen, go_right.astype(i32), 0))
            _sp.fence((out_leaf, slot_id))

        if d + 1 >= D:
            break

        # ---- next-level slot state (children of slot s at 2s / 2s+1)
        def interleave(a, b):
            return jnp.stack([a, b], axis=1).reshape(2 * P, *a.shape[1:])

        alive = interleave(chosen, chosen)
        leaf_of = interleave(bl, right_leaf)
        parent_node = interleave(node_of, node_of)
        slot_g = interleave(res.left_sum_grad, res.right_sum_grad)
        slot_h = interleave(res.left_sum_hess, res.right_sum_hess)
        slot_c = interleave(res.left_count.astype(f32),
                            res.right_count.astype(f32))

        # ---- level histogram: build ONLY the smaller child of every chosen
        # parent in one batched pass, derive the sibling by subtraction
        par_of_row = slot_id // 2
        # Smaller-child choice from the SplitResult counts (integer-valued
        # f32 histogram sums; replicated under the data-parallel learner,
        # whose counts come from psum'd histograms).  Above 2^24 rows per
        # node the f32 rounding could mis-order near-equal children — that
        # only means the pass histograms the slightly larger child (the
        # sibling is still exact via subtraction), a perf non-event, so no
        # recount is needed at any scale.
        sel = in_chosen & (go_right == small_right_row) & row_mask
        # The masked full-N pass is the fastest smaller-child schedule
        # measured on v5e (1M and 11M rows): gathering the selected rows
        # into a compact N/2 buffer first (the masked-dense analog of the
        # reference's per-leaf index lists, data_partition.hpp) costs more
        # in cumsum/scatter/gather plumbing than the halved histogram pass
        # saves — see git history for the removed compaction path.
        with jax.named_scope("level%d" % (d + 1)):
            hist_small = batch_hist(par_of_row, sel, P, level=True,
                                    salt=d + 1)
        hist_large = hists - hist_small
        hsmall_slot = interleave(jnp.where(small_is_right[:, None, None, None],
                                           hist_large, hist_small),
                                 jnp.where(small_is_right[:, None, None, None],
                                           hist_small, hist_large))
        hists = hsmall_slot

    num_leaves_final = n_nodes + 1
    return TreeArrays(
        num_leaves=num_leaves_final,
        split_feature=split_feature[:max(L - 1, 1)],
        threshold_bin=threshold_bin,
        split_gain=split_gain,
        left_child=left_child,
        right_child=right_child,
        leaf_parent=leaf_parent,
        leaf_value=leaf_value,
        leaf_count=leaf_count,
        leaf_ids=out_leaf,
    )


# ==================================================== leafcompact policy

class _CompactState(NamedTuple):
    tree: TreeArrays
    pane: jax.Array             # [F+9, P] int8 — partitioned plane pane
    seg_start: jax.Array        # [L] i32 — leaf -> lane range start
    seg_cnt: jax.Array          # [L] i32 — physical lane count
    seg_bucket: jax.Array       # [L] i32 — static width tier
    hist_cache: jax.Array       # [L, F, B, 3] (owned Fb block under an
                                # ownership schedule)
    cand_gain: jax.Array        # [L]
    cand_feature: jax.Array
    cand_threshold: jax.Array
    cand_left_out: jax.Array
    cand_right_out: jax.Array
    cand_left_cnt: jax.Array
    cand_right_cnt: jax.Array
    cand_left_g: jax.Array
    cand_left_h: jax.Array
    cand_right_g: jax.Array
    cand_right_h: jax.Array
    leaf_depth: jax.Array       # [L] i32
    done: jax.Array             # bool


def _grow_leafcompact(bins, grad, hess, row_mask, feature_mask, num_bins,
                      s: SeamSchedule, *, num_leaves: int,
                      num_bins_max: int, min_data_in_leaf: int,
                      min_sum_hessian_in_leaf: float, max_depth: int,
                      hist_backend: str, hist_chunk: int, compute_dtype,
                      packing, partition_packing=None,
                      use_pallas_partition: bool,
                      partition_overlap: bool, interpret: bool,
                      return_state: bool = False):
    """Compacted leaf-wise growth — reference-parity split order at the
    reference's geometric-series histogram cost (~N·log L instead of
    N·(L-1)): every leaf's rows stay contiguous in one [F+9, P] plane
    pane (bin rows + grad/hess bit-planes + validity), each split stably
    partitions the parent's lane range (Pallas MXU selection-matmul
    kernel on TPU, stable argsort oracle elsewhere) and histograms ONLY
    the physically-smaller child's bucketed range, deriving the sibling
    by subtraction.  Ranges are sliced at bucketed widths
    (ops/compact.bucket_table) under a lax.switch; the histogram tier is
    pmax-synced over hist_axis so collectives inside the tier switch
    stay uniform across shards.  Equivalence to the masked policy:
    structure-exact, values within the documented cross-program ulp
    budget (XLA CPU contracts the int8 dequantize into split-dependent
    FMAs; see tests/test_leafcompact.py)."""
    from ..ops.compact import (BLOCK, bucket_table, pack_planes, pane_rows,
                               partition_segment, unpack_values)
    from .. import telemetry as _tl

    F, N = bins.shape
    R = pane_rows(F)            # plane-pane rows (ops/compact.pack_planes)
    L = num_leaves
    B = num_bins_max
    f32 = jnp.float32
    ppack = partition_packing if partition_packing is not None else packing
    c2p_arr = (jnp.asarray(ppack.c2p, jnp.int32)
               if ppack is not None and len(ppack.widths) > 1 else None)
    table = bucket_table(N, min_width=max(BLOCK, (-(-N // BLOCK) * BLOCK)
                                          >> 9))
    P = table[0]
    K = len(table)
    table_arr = jnp.asarray(table, jnp.int32)

    def bucket_of(x):
        return (jnp.sum(table_arr >= jnp.maximum(x, 1)) - 1).astype(
            jnp.int32)

    build_hist = _patchable("grower_leafcompact", "build_histogram",
                            build_histogram)
    _fg = ({"feat_gather": s.hist_feat_gather}
           if s.hist_feat_gather is not None else {})

    def hist_of(hbins, hg, hh, hmask, salt=0):
        hist = build_hist(hbins, hg, hh, hmask, B,
                               backend=hist_backend, chunk=hist_chunk,
                               compute_dtype=compute_dtype,
                               axis_name=s.hist_axis,
                               int_reduce=s.int_hist_reduce, salt=salt,
                               packing=packing, **_fg)
        return _apply_hist_reduce(hist, s, compute_dtype)

    finder = s.split_finder or find_best_split

    def _finder(hist, sum_g, sum_h, cnt):
        return finder(hist, sum_g, sum_h, cnt, num_bins,
                      feature_mask, float(min_data_in_leaf),
                      float(min_sum_hessian_in_leaf))

    def best_of(hist, sum_g, sum_h, cnt, depth, root=False):
        f = (s.root_split_finder or finder) if root else finder
        if root:
            return _depth_gated(
                f(hist, sum_g, sum_h, cnt, num_bins, feature_mask,
                  float(min_data_in_leaf),
                  float(min_sum_hessian_in_leaf)), depth, max_depth)
        return _depth_gated(_finder(hist, sum_g, sum_h, cnt), depth,
                            max_depth)

    def best_of_pair(lhist, rhist, lg, lh, lc, rg, rh, rc, depth):
        """Both children's candidate searches in ONE batched finder call
        (vmap over a [2, F, B, 3] stack): the finder's cumsum/argmax work
        is tiny, so per-call XLA overhead — paid 2x per split otherwise —
        is the cost that matters.  Elementwise math is identical to two
        single calls (both children share the same depth)."""
        both = _depth_gated(
            jax.vmap(_finder)(jnp.stack([lhist, rhist]),
                              jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                              jnp.stack([lc, rc])), depth, max_depth)
        lbest = jax.tree.map(lambda x: x[0], both)
        rbest = jax.tree.map(lambda x: x[1], both)
        return lbest, rbest

    # ---- root (BeforeTrain): full-data pass over the ORIGINAL arrays —
    # identical to the masked policy's root, so the two policies share
    # root histograms bit for bit
    full, root_hist = _root_hist_pair(
        lambda: build_hist(bins, grad, hess, row_mask, B,
                           backend=hist_backend, chunk=hist_chunk,
                           compute_dtype=compute_dtype,
                           axis_name=s.hist_axis, packing=packing, **_fg),
        lambda: hist_of(bins, grad, hess, row_mask), s, compute_dtype)
    root_stats = _root_stats_of(full, s, compute_dtype, grad, hess,
                                row_mask)
    root_g, root_h, root_c = root_stats[0], root_stats[1], root_stats[2]
    root_best = best_of(root_hist, root_g, root_h, root_c,
                        jnp.asarray(1, jnp.int32), root=True)

    neg_inf = jnp.full((L,), -jnp.inf, dtype=f32)
    zeros_i = jnp.zeros((L,), dtype=jnp.int32)
    zeros_f = jnp.zeros((L,), dtype=f32)

    tree = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        split_gain=jnp.zeros((L - 1,), f32),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_value=zeros_f,
        leaf_count=zeros_i.at[0].set(root_c.astype(jnp.int32)),
        leaf_ids=jnp.zeros((N,), jnp.int32),
    )
    state = _CompactState(
        tree=tree,
        pane=pack_planes(bins, grad, hess, row_mask, P),
        seg_start=zeros_i,
        seg_cnt=zeros_i.at[0].set(N),
        seg_bucket=zeros_i.at[0].set(bucket_of(N)),
        # owned-block shape under an ownership schedule, full F otherwise
        hist_cache=jnp.zeros((L,) + root_hist.shape, f32).at[0].set(
            root_hist),
        cand_gain=neg_inf.at[0].set(root_best.gain),
        cand_feature=zeros_i.at[0].set(root_best.feature),
        cand_threshold=zeros_i.at[0].set(root_best.threshold),
        cand_left_out=zeros_f.at[0].set(root_best.left_output),
        cand_right_out=zeros_f.at[0].set(root_best.right_output),
        cand_left_cnt=zeros_i.at[0].set(root_best.left_count),
        cand_right_cnt=zeros_i.at[0].set(root_best.right_count),
        cand_left_g=zeros_f.at[0].set(root_best.left_sum_grad),
        cand_left_h=zeros_f.at[0].set(root_best.left_sum_hess),
        cand_right_g=zeros_f.at[0].set(root_best.right_sum_grad),
        cand_right_h=zeros_f.at[0].set(root_best.right_sum_hess),
        leaf_depth=zeros_i.at[0].set(1),
        done=jnp.asarray(False),
    )

    def make_partition_branch(k: int):
        W = table[k]

        def branch(op):
            pane, start, cnt, feat, thr = op
            cs = jnp.minimum(start, P - W)        # clamp: slice stays
            delta = start - cs                    # in-pane; mask realigns
            seg = jax.lax.dynamic_slice(pane, (jnp.int32(0), cs), (R, W))
            pfeat = feat if c2p_arr is None else c2p_arr[feat]
            fbin = jax.lax.dynamic_index_in_dim(
                seg[:F], pfeat, axis=0, keepdims=False).astype(jnp.int32)
            fbin = fbin & 255                     # int8 pane -> uint8 bin
            lane = jnp.arange(W, dtype=jnp.int32)
            inseg = (lane >= delta) & (lane < delta + cnt)
            go_right = fbin > thr
            mask3 = jnp.where(inseg,
                              jnp.where(go_right, 0, 1), -1).astype(jnp.int8)
            plcnt = jnp.sum(inseg & ~go_right).astype(jnp.int32)
            new_seg = partition_segment(seg, mask3, delta, cnt, plcnt,
                                        use_pallas=use_pallas_partition,
                                        overlap=partition_overlap,
                                        interpret=interpret)
            pane2 = jax.lax.dynamic_update_slice(pane, new_seg,
                                                 (jnp.int32(0), cs))
            return pane2, plcnt

        return branch

    def make_hist_branch(k: int):
        W = table[k]

        def branch(op):
            pane2, sstart, scnt, salt = op
            cs2 = jnp.minimum(sstart, P - W)
            d2 = sstart - cs2
            hseg = jax.lax.dynamic_slice(pane2, (jnp.int32(0), cs2),
                                         (R, W))
            hbins, hg, hh, hvalid = unpack_values(hseg, F)
            lane2 = jnp.arange(W, dtype=jnp.int32)
            hmask = (lane2 >= d2) & (lane2 < d2 + scnt) & hvalid
            return hist_of(hbins, hg, hh, hmask, salt=salt)

        return branch

    partition_branches = [make_partition_branch(k) for k in range(K)]
    hist_branches = [make_hist_branch(k) for k in range(K)]

    def body(_, state: _CompactState) -> _CompactState:
        best_leaf = jnp.argmax(state.cand_gain).astype(jnp.int32)
        best_gain = state.cand_gain[best_leaf]
        should_split = jnp.logical_and(~state.done, best_gain > 0.0)

        def do_split(state: _CompactState) -> _CompactState:
            tree = state.tree
            bl = best_leaf
            nl = tree.num_leaves
            node = nl - 1
            new_leaf = nl

            feat = state.cand_feature[bl]
            thr = state.cand_threshold[bl]

            # --- record the node (Tree::Split, tree.cpp:50-83)
            p = tree.leaf_parent[bl]
            pp = jnp.maximum(p, 0)
            lc_at_p = jnp.where((p >= 0) & (tree.left_child[pp] == ~bl),
                                node, tree.left_child[pp])
            rc_at_p = jnp.where((p >= 0) & (tree.right_child[pp] == ~bl),
                                node, tree.right_child[pp])
            left_child = (tree.left_child.at[pp].set(lc_at_p)
                          .at[node].set(~bl))
            right_child = (tree.right_child.at[pp].set(rc_at_p)
                           .at[node].set(~new_leaf))

            # --- original-order leaf ids (score updates need them; the
            # pane's permutation never leaves this function)
            ofeat = feat if c2p_arr is None else c2p_arr[feat]
            obin = jax.lax.dynamic_index_in_dim(
                bins, ofeat, axis=0, keepdims=False).astype(jnp.int32)
            leaf_ids = jnp.where((tree.leaf_ids == bl) & (obin > thr),
                                 new_leaf, tree.leaf_ids)

            # --- partition the parent's lane range at ITS tier (local,
            # collective-free: shards may take different branches)
            start = state.seg_start[bl]
            cnt = state.seg_cnt[bl]
            pane2, plcnt = jax.lax.switch(
                state.seg_bucket[bl], partition_branches,
                (state.pane, start, cnt, feat, thr))
            prcnt = cnt - plcnt

            # --- smaller-child histogram at the CHILD's own tier.  The
            # directly-built side is the VALID-smaller one, exactly like
            # the masked grower (same direct/subtracted f32 rounding);
            # its physical span picks the slice tier — pmax-synced across
            # shards so the collectives inside the branch line up
            lcnt = state.cand_left_cnt[bl]
            rcnt = state.cand_right_cnt[bl]
            left_small = lcnt <= rcnt
            scnt = jnp.where(left_small, plcnt, prcnt)
            sstart = jnp.where(left_small, start, start + plcnt)
            hk_span = scnt
            if s.hist_axis is not None:
                # tier-selector sync: a scalar pmax per split — tiny on
                # the wire but a full collective latency, so it belongs
                # in the interconnect inventory
                _tl.record_collective(
                    "leafcompact/tier_pmax", "pmax", s.hist_axis,
                    _tl._tree_nbytes(hk_span), loop=L - 1, phase="grow")
                hk_span = jax.lax.pmax(hk_span, s.hist_axis)
            small_hist = jax.lax.switch(
                bucket_of(hk_span), hist_branches,
                (pane2, sstart, scnt, new_leaf))

            parent_hist = state.hist_cache[bl]
            large_hist = parent_hist - small_hist
            lhist = jnp.where(left_small, small_hist, large_hist)
            rhist = jnp.where(left_small, large_hist, small_hist)

            lg, lh = state.cand_left_g[bl], state.cand_left_h[bl]
            rg, rh = state.cand_right_g[bl], state.cand_right_h[bl]
            depth = state.leaf_depth[bl] + 1

            # finder before the cache scatter: the packed-SplitInfo
            # allgather overlaps the HBM writeback (ISSUE 9 overlap seam;
            # pure program order, bit-identical values)
            lbest, rbest = best_of_pair(lhist, rhist, lg, lh,
                                        lcnt.astype(f32), rg, rh,
                                        rcnt.astype(f32), depth)
            hist_cache = (state.hist_cache.at[bl].set(lhist)
                          .at[new_leaf].set(rhist))

            tree = tree._replace(
                num_leaves=nl + 1,
                split_feature=tree.split_feature.at[node].set(feat),
                threshold_bin=tree.threshold_bin.at[node].set(thr),
                split_gain=tree.split_gain.at[node].set(best_gain),
                left_child=left_child,
                right_child=right_child,
                leaf_parent=tree.leaf_parent.at[bl].set(node)
                                            .at[new_leaf].set(node),
                leaf_value=tree.leaf_value
                               .at[bl].set(state.cand_left_out[bl])
                               .at[new_leaf].set(state.cand_right_out[bl]),
                leaf_count=tree.leaf_count.at[bl].set(lcnt)
                                          .at[new_leaf].set(rcnt),
                leaf_ids=leaf_ids,
            )
            return state._replace(
                tree=tree,
                pane=pane2,
                seg_start=state.seg_start.at[new_leaf].set(start + plcnt),
                seg_cnt=state.seg_cnt.at[bl].set(plcnt)
                                     .at[new_leaf].set(prcnt),
                seg_bucket=state.seg_bucket.at[bl].set(bucket_of(plcnt))
                                           .at[new_leaf].set(
                                               bucket_of(prcnt)),
                hist_cache=hist_cache,
                cand_gain=state.cand_gain.at[bl].set(lbest.gain)
                                         .at[new_leaf].set(rbest.gain),
                cand_feature=state.cand_feature.at[bl].set(lbest.feature)
                                               .at[new_leaf]
                                               .set(rbest.feature),
                cand_threshold=state.cand_threshold
                                    .at[bl].set(lbest.threshold)
                                    .at[new_leaf].set(rbest.threshold),
                cand_left_out=state.cand_left_out
                                   .at[bl].set(lbest.left_output)
                                   .at[new_leaf].set(rbest.left_output),
                cand_right_out=state.cand_right_out
                                    .at[bl].set(lbest.right_output)
                                    .at[new_leaf].set(rbest.right_output),
                cand_left_cnt=state.cand_left_cnt
                                   .at[bl].set(lbest.left_count)
                                   .at[new_leaf].set(rbest.left_count),
                cand_right_cnt=state.cand_right_cnt
                                    .at[bl].set(lbest.right_count)
                                    .at[new_leaf].set(rbest.right_count),
                cand_left_g=state.cand_left_g
                                 .at[bl].set(lbest.left_sum_grad)
                                 .at[new_leaf].set(rbest.left_sum_grad),
                cand_left_h=state.cand_left_h
                                 .at[bl].set(lbest.left_sum_hess)
                                 .at[new_leaf].set(rbest.left_sum_hess),
                cand_right_g=state.cand_right_g
                                  .at[bl].set(lbest.right_sum_grad)
                                  .at[new_leaf].set(rbest.right_sum_grad),
                cand_right_h=state.cand_right_h
                                  .at[bl].set(lbest.right_sum_hess)
                                  .at[new_leaf].set(rbest.right_sum_hess),
                leaf_depth=state.leaf_depth.at[bl].set(depth)
                                           .at[new_leaf].set(depth),
            )

        def no_split(state: _CompactState) -> _CompactState:
            return state._replace(done=jnp.asarray(True))

        # profiler alignment (ISSUE 2): label the compacted split body so
        # profile_dir= traces group its partition/histogram ops per split
        with jax.named_scope("leafcompact_split"):
            return jax.lax.cond(should_split, do_split, no_split, state)

    state = jax.lax.fori_loop(0, L - 1, body, state)
    return state if return_state else state.tree


# ======================================================= jitted wrappers

# module-level jits shared across boosters, wrapped in the cost registry
# (lightgbm_tpu/costmodel.py): with telemetry armed, the compiled
# program's cost_analysis/compile seconds feed the roofline/compile
# blocks.  One jitted entry per policy under the HISTORICAL instrument
# names, so recorded roofline/compile trajectories stay comparable.
from .. import costmodel as _costmodel  # noqa: E402 (after jax imports)

_SEG_STATICS = tuple(k for k in _GROW_STATICS if k != "policy")


def _grow_tree_leafwise_fn(bins, grad, hess, row_mask, feature_mask,
                           num_bins, **kwargs) -> TreeArrays:
    return grow_tree_unified(bins, grad, hess, row_mask, feature_mask,
                             num_bins, policy="leafwise", **kwargs)


def _grow_tree_depthwise_fn(bins, grad, hess, row_mask, feature_mask,
                            num_bins, **kwargs) -> TreeArrays:
    return grow_tree_unified(bins, grad, hess, row_mask, feature_mask,
                             num_bins, policy="depthwise", **kwargs)


def _grow_tree_leafcompact_fn(bins, grad, hess, row_mask, feature_mask,
                              num_bins, **kwargs) -> TreeArrays:
    return grow_tree_unified(bins, grad, hess, row_mask, feature_mask,
                             num_bins, policy="leafcompact", **kwargs)


grow_tree = _costmodel.instrument(
    "grow/leafwise",
    jax.jit(_grow_tree_leafwise_fn, static_argnames=_SEG_STATICS),
    phase="grow")
grow_tree_depthwise_jit = _costmodel.instrument(
    "grow/depthwise",
    jax.jit(_grow_tree_depthwise_fn, static_argnames=_SEG_STATICS),
    phase="grow")
grow_tree_leafcompact = _costmodel.instrument(
    "grow/leafcompact",
    jax.jit(_grow_tree_leafcompact_fn, static_argnames=_SEG_STATICS),
    phase="grow")


# ============================================== leaf-wise segmentation


@functools.partial(jax.jit, static_argnames=_SEG_STATICS)
def _grow_init(bins, grad, hess, row_mask, feature_mask, num_bins,
               **kwargs) -> _GrowState:
    return grow_tree_unified(bins, grad, hess, row_mask, feature_mask,
                             num_bins, policy="leafwise", loop_count=0,
                             return_state=True, **kwargs)


# donate the carried state: without aliasing, input and output copies of
# hist_cache [L,F,B,3] + leaf_ids [N] (~120 MB at bench scale) would both
# be live at every segment boundary
@functools.partial(jax.jit, static_argnames=_SEG_STATICS + ("loop_count",),
                   donate_argnums=(6,))
def _grow_segment(bins, grad, hess, row_mask, feature_mask, num_bins,
                  state, *, loop_count, **kwargs) -> _GrowState:
    return grow_tree_unified(bins, grad, hess, row_mask, feature_mask,
                             num_bins, policy="leafwise", init_state=state,
                             loop_count=loop_count, return_state=True,
                             **kwargs)


def grow_tree_segmented(bins, grad, hess, row_mask, feature_mask, num_bins,
                        *, segments: int, **kwargs) -> TreeArrays:
    """Leaf-wise growth split across ``segments`` device dispatches.

    A 255-leaf leaf-wise tree is 254 sequential full-data histogram passes
    in ONE XLA dispatch; at tens of millions of rows that single dispatch
    can run minutes (and trips this environment's ~60 s per-dispatch
    execution watchdog, BASELINE.md).  The split loop's body never reads
    the loop index, so running fori_loop(0, L-1) as ceil((L-1)/segments)-
    sized pieces with the _GrowState carried device-resident between
    dispatches is program-identical — same trees, bit for bit.  Equal-size
    segments share one compiled program (the count, not the start, is the
    static)."""
    L = kwargs["num_leaves"]
    total = max(L - 1, 1)
    per = -(-total // max(segments, 1))
    state = _grow_init(bins, grad, hess, row_mask, feature_mask, num_bins,
                       **kwargs)
    done = 0
    while done < total:
        n = min(per, total - done)
        state = _grow_segment(bins, grad, hess, row_mask, feature_mask,
                              num_bins, state, loop_count=n, **kwargs)
        done += n
    return state.tree
