"""Compacted leaf-wise grower — reference-parity growth at reference-like
cost.

The plain leaf-wise grower (grower.py) sweeps ALL N rows for every one of
the num_leaves-1 histogram passes, because its DataPartition is a [N]
leaf-id vector and the smaller child is selected by a mask.  The reference
never does that: DataPartition keeps each leaf's rows contiguous in a
permuted index array (data_partition.hpp:93-139) and ConstructHistogram
walks only the leaf's own rows (serial_tree_learner.cpp:262-283,
dense_bin.hpp:46-112), so total per-tree histogram work is the
geometric-series sum of smaller-child sizes (~N·log L), not N·(L-1).

This grower restores that asymptotic on TPU terms.  Indices can't be
followed on a TPU (XLA gathers at 11M rows lower to per-row scalar
addressing — PROFILE.md's measured dead end), so the DATA is kept
physically partitioned instead: one [F+9, P] int8 "plane pane" (bin rows,
grad/hess as f32 bit-planes, validity) in which every leaf owns a
contiguous lane range.  Each split

1. stably partitions the parent's range in a streaming sweep
   (ops/compact.py — Pallas MXU selection-matmul kernel on TPU, stable
   argsort oracle elsewhere), and
2. histograms ONLY the physically-smaller child's range, deriving the
   sibling by parent-minus-smaller subtraction exactly as before.

jit needs static shapes, so ranges are sliced at bucketed widths
(ops/compact.bucket_table: halving block-rounded tiers); a lax.switch over
the parent's tier picks the compiled width, and lane masks handle the
bucket slack.  The child histogram runs over the parent's own partitioned
segment with the child's lane range masked — per-split cost is the parent
tier's width, whose sum over the tree is the geometric series (~N·log L),
not N·(L-1).

Equivalence to grower.grow_tree: the partition is stable, so the smaller
child's rows are visited in the same relative order as the masked
full-data pass (whose non-member lanes contribute exact +0.0 terms); the
directly-built child follows the masked grower's valid-smaller rule, so
direct/subtracted rounding matches too.  Measured caveat (tests/
test_leafcompact.py): on XLA **CPU** the int8 path's dequantize multiply
gets contracted into the parent-minus-smaller subtraction as a
single-rounding FMA in SOME program contexts — sub-ulp dust that neither
``lax.optimization_barrier`` nor a bitcast round-trip nor
``reduce_precision`` suppresses (all verified ignored by the fusion
pipeline).  This grower matches a jit-free replay of the identical ops
BIT FOR BIT (the masked grower is the one carrying the FMA dust there);
int8 CPU cross-grower comparisons are therefore structure-exact but
value-tolerant, while f32 histograms (no trailing dequantize multiply)
and the TPU paths are bit-identical across growers.

Runs under the serial learner AND the data-parallel learner's BOTH
histogram-reduction schedules (parallel/learners.DataParallelLearner):
each shard keeps its LOCAL rows physically partitioned, and the
per-split smaller-child histograms are either psum'd whole
(``dp_schedule=psum``) or psum_scatter'd by contiguous feature block
with an owned-feature search + packed SplitInfo allreduce
(``reduce_scatter`` — the reference's N-machine ownership schedule,
data_parallel_tree_learner.cpp:135-235, in its native growth order).
The hist_reduce/int_hist_reduce/split_finder/own_slice seams below
carry both; the histogram slice tier is pmax-synchronized so the
collectives inside the tier switch stay uniform across shards.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.compact import (BLOCK, bucket_table, pack_planes, pane_rows,
                           partition_segment, unpack_values)
from ..ops.histogram import build_histogram
from .grower import TreeArrays
from ..ops.split import find_best_split


class _CompactState(NamedTuple):
    tree: TreeArrays
    pane: jax.Array             # [F+9, P] int8 — partitioned plane pane
    seg_start: jax.Array        # [L] i32 — leaf -> lane range start
    seg_cnt: jax.Array          # [L] i32 — physical lane count
    seg_bucket: jax.Array       # [L] i32 — static width tier
    hist_cache: jax.Array       # [L, F, B, 3] (owned Fb block under the
                                # reduce_scatter ownership schedule)
    cand_gain: jax.Array        # [L]
    cand_feature: jax.Array
    cand_threshold: jax.Array
    cand_left_out: jax.Array
    cand_right_out: jax.Array
    cand_left_cnt: jax.Array
    cand_right_cnt: jax.Array
    cand_left_g: jax.Array
    cand_left_h: jax.Array
    cand_right_g: jax.Array
    cand_right_h: jax.Array
    leaf_depth: jax.Array       # [L] i32
    done: jax.Array             # bool


def _grow_tree_leafcompact_fn(bins, grad, hess, row_mask, feature_mask,
                              num_bins, *, num_leaves: int,
                              num_bins_max: int,
                              min_data_in_leaf: int,
                              min_sum_hessian_in_leaf: float,
                              max_depth: int = -1,
                              hist_backend: str = "matmul",
                              hist_chunk: int = 16384,
                              compute_dtype=jnp.float32,
                              packing=None,
                              use_pallas_partition: bool = False,
                              partition_overlap: bool = True,
                              interpret: bool = False) -> TreeArrays:
    return grow_tree_leafcompact_impl(
        bins, grad, hess, row_mask, feature_mask, num_bins,
        num_leaves=num_leaves, num_bins_max=num_bins_max,
        min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian_in_leaf=min_sum_hessian_in_leaf,
        max_depth=max_depth, hist_backend=hist_backend,
        hist_chunk=hist_chunk, compute_dtype=compute_dtype,
        packing=packing,
        use_pallas_partition=use_pallas_partition,
        partition_overlap=partition_overlap, interpret=interpret)


# module-level jit wrapped in the cost registry (costmodel.instrument) so
# the compacted grower's compiled programs self-report cost_analysis +
# compile seconds to the roofline/compile blocks when telemetry is armed
from .. import costmodel as _costmodel  # noqa: E402

grow_tree_leafcompact = _costmodel.instrument(
    "grow/leafcompact",
    jax.jit(_grow_tree_leafcompact_fn,
            static_argnames=("num_leaves", "num_bins_max",
                             "min_data_in_leaf", "min_sum_hessian_in_leaf",
                             "max_depth", "hist_backend", "hist_chunk",
                             "compute_dtype", "packing",
                             "use_pallas_partition",
                             "partition_overlap", "interpret")),
    phase="grow")


def grow_tree_leafcompact_impl(bins, grad, hess, row_mask, feature_mask,
                               num_bins, *, num_leaves: int,
                               num_bins_max: int, min_data_in_leaf: int,
                               min_sum_hessian_in_leaf: float,
                               max_depth: int = -1,
                               hist_backend: str = "matmul",
                               hist_chunk: int = 16384,
                               compute_dtype=jnp.float32,
                               packing=None,
                               use_pallas_partition: bool = False,
                               partition_overlap: bool = True,
                               interpret: bool = False,
                               hist_reduce=None, hist_axis=None,
                               int_hist_reduce=None, split_finder=None,
                               stat_reduce=None, own_slice=None,
                               root_hist_reduce=None,
                               return_state: bool = False):
    """Core (not jitted; callers wrap it).  ``return_state`` exposes the
    full _CompactState for differential debugging against
    grower.grow_tree_impl's state.

    hist_reduce/hist_axis/stat_reduce: the data-parallel (psum) seams,
    same contract as grower.grow_tree_impl — each shard keeps its LOCAL
    rows physically partitioned and the per-split histograms are reduced
    globally.  Collectives may not sit inside per-shard-divergent
    control flow, so the per-split work is TWO switches: the partition
    switch (local, collective-free — each shard picks its own tier) and
    the histogram switch, whose tier selector is pmax-synchronized
    across shards (every shard takes the same branch, so the psum
    inside it lines up).

    int_hist_reduce/split_finder/own_slice/root_hist_reduce: the
    reduce_scatter OWNERSHIP seams, same contract as
    grower.grow_tree_impl — hist_reduce becomes a feature-block
    psum_scatter (int_hist_reduce its int-domain twin for the quantized
    path), so every per-split histogram and the hist cache hold only
    this shard's OWNED block; split_finder must then be the owned-search
    + SplitInfo-allreduce composite returning GLOBAL feature indices,
    and feature_mask/num_bins the owned slices
    (learners.DataParallelLearner._compact_grow_fn).  The root is built
    replicated at full F (root_hist_reduce, then own_slice caches the
    owned block) so root stats stay exact on feature-padding shards.
    The PANE keeps all F features either way — the winning feature is
    global, and partitioning needs its bin row."""
    F, N = bins.shape
    R = pane_rows(F)            # plane-pane rows (ops/compact.pack_planes)
    L = num_leaves
    B = num_bins_max
    f32 = jnp.float32
    # wire-metrics hook point (ISSUE 5): label any seam the learner did
    # not already wrap (collective_span passes wrapped fns through)
    from .. import telemetry as _tl
    hist_reduce = _tl.collective_span(
        "leafcompact/hist_reduce", hist_reduce, kind="reduce",
        axis=hist_axis, loop=L - 1, phase="grow")
    int_hist_reduce = _tl.collective_span(
        "leafcompact/int_hist_reduce", int_hist_reduce, kind="reduce",
        axis=hist_axis, loop=L - 1, phase="grow")
    stat_reduce = _tl.collective_span(
        "leafcompact/root_stats", stat_reduce, kind="reduce",
        axis=hist_axis, phase="grow")
    root_hist_reduce = _tl.collective_span(
        "leafcompact/root_hist", root_hist_reduce, kind="reduce",
        axis=hist_axis, phase="grow")
    c2p_arr = (jnp.asarray(packing.c2p, jnp.int32)
               if packing is not None and len(packing.widths) > 1 else None)
    table = bucket_table(N, min_width=max(BLOCK, (-(-N // BLOCK) * BLOCK)
                                          >> 9))
    P = table[0]
    K = len(table)
    table_arr = jnp.asarray(table, jnp.int32)

    def bucket_of(x):
        return (jnp.sum(table_arr >= jnp.maximum(x, 1)) - 1).astype(
            jnp.int32)

    def hist_of(hbins, hg, hh, hmask, salt=0):
        hist = build_histogram(hbins, hg, hh, hmask, B,
                               backend=hist_backend, chunk=hist_chunk,
                               compute_dtype=compute_dtype,
                               axis_name=hist_axis,
                               int_reduce=int_hist_reduce, salt=salt,
                               packing=packing)
        # the quantized path reduces its INT accumulators internally over
        # hist_axis (grower.grow_tree_impl's rule, kept identical) — psum
        # by default, the ownership feature-block scatter when
        # int_hist_reduce is set
        if hist_reduce is not None and not (
                str(compute_dtype).startswith("int8")
                and hist_axis is not None):
            hist = hist_reduce(hist)
        return hist

    finder = split_finder or find_best_split

    def _finder(hist, sum_g, sum_h, cnt):
        return finder(hist, sum_g, sum_h, cnt, num_bins,
                      feature_mask, float(min_data_in_leaf),
                      float(min_sum_hessian_in_leaf))

    def _depth_gate(res, depth):
        if max_depth > 0:
            res = res._replace(gain=jnp.where(depth >= max_depth,
                                              -jnp.inf, res.gain))
        return res

    def best_of(hist, sum_g, sum_h, cnt, depth):
        return _depth_gate(_finder(hist, sum_g, sum_h, cnt), depth)

    def best_of_pair(lhist, rhist, lg, lh, lc, rg, rh, rc, depth):
        """Both children's candidate searches in ONE batched finder call
        (vmap over a [2, F, B, 3] stack): the finder's cumsum/argmax work
        is tiny, so per-call XLA overhead — paid 2x per split otherwise —
        is the cost that matters.  Elementwise math is identical to two
        single calls (both children share the same depth)."""
        both = _depth_gate(
            jax.vmap(_finder)(jnp.stack([lhist, rhist]),
                              jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                              jnp.stack([lc, rc])), depth)
        lbest = jax.tree.map(lambda x: x[0], both)
        rbest = jax.tree.map(lambda x: x[1], both)
        return lbest, rbest

    # ---- root (BeforeTrain): full-data pass over the ORIGINAL arrays —
    # identical to grower.grow_tree's root, so the two growers share root
    # histograms bit for bit
    if own_slice is not None:
        # ownership (reduce_scatter) schedule: build the ROOT replicated
        # — full F, plain psum — so root stats are exact on every shard
        # including feature-PADDING shards (whose owned block is all
        # zeros), then cache only the owned slice (grow_tree_impl's rule)
        full = build_histogram(bins, grad, hess, row_mask, B,
                               backend=hist_backend, chunk=hist_chunk,
                               compute_dtype=compute_dtype,
                               axis_name=hist_axis, packing=packing)
        if root_hist_reduce is not None and not (
                str(compute_dtype).startswith("int8")
                and hist_axis is not None):
            full = root_hist_reduce(full)
        root_hist = own_slice(full)
    else:
        full = root_hist = hist_of(bins, grad, hess, row_mask)
    if str(compute_dtype).startswith("int8"):
        # any single feature's bins sum to the exact quantized totals
        # (grower.grow_tree's int8 root-stat rule, kept bit-identical;
        # under the ownership schedule the stats must come from the
        # replicated full-F root, not the owned block — a feature-padding
        # shard's block is all zeros)
        root_stats = jnp.sum(full[0], axis=0)
    else:
        maskf = row_mask.astype(f32)
        root_stats = jnp.stack([jnp.sum(grad * maskf),
                                jnp.sum(hess * maskf), jnp.sum(maskf)])
        if stat_reduce is not None:
            root_stats = stat_reduce(root_stats)
    root_g, root_h, root_c = root_stats[0], root_stats[1], root_stats[2]
    root_best = best_of(root_hist, root_g, root_h, root_c,
                        jnp.asarray(1, jnp.int32))

    neg_inf = jnp.full((L,), -jnp.inf, dtype=f32)
    zeros_i = jnp.zeros((L,), dtype=jnp.int32)
    zeros_f = jnp.zeros((L,), dtype=f32)

    tree = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        split_gain=jnp.zeros((L - 1,), f32),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_value=zeros_f,
        leaf_count=zeros_i.at[0].set(root_c.astype(jnp.int32)),
        leaf_ids=jnp.zeros((N,), jnp.int32),
    )
    state = _CompactState(
        tree=tree,
        pane=pack_planes(bins, grad, hess, row_mask, P),
        seg_start=zeros_i,
        seg_cnt=zeros_i.at[0].set(N),
        seg_bucket=zeros_i.at[0].set(bucket_of(N)),
        # owned-block shape under the ownership schedule, full F otherwise
        hist_cache=jnp.zeros((L,) + root_hist.shape, f32).at[0].set(
            root_hist),
        cand_gain=neg_inf.at[0].set(root_best.gain),
        cand_feature=zeros_i.at[0].set(root_best.feature),
        cand_threshold=zeros_i.at[0].set(root_best.threshold),
        cand_left_out=zeros_f.at[0].set(root_best.left_output),
        cand_right_out=zeros_f.at[0].set(root_best.right_output),
        cand_left_cnt=zeros_i.at[0].set(root_best.left_count),
        cand_right_cnt=zeros_i.at[0].set(root_best.right_count),
        cand_left_g=zeros_f.at[0].set(root_best.left_sum_grad),
        cand_left_h=zeros_f.at[0].set(root_best.left_sum_hess),
        cand_right_g=zeros_f.at[0].set(root_best.right_sum_grad),
        cand_right_h=zeros_f.at[0].set(root_best.right_sum_hess),
        leaf_depth=zeros_i.at[0].set(1),
        done=jnp.asarray(False),
    )

    def make_partition_branch(k: int):
        W = table[k]

        def branch(op):
            pane, start, cnt, feat, thr = op
            cs = jnp.minimum(start, P - W)        # clamp: slice stays
            delta = start - cs                    # in-pane; mask realigns
            seg = jax.lax.dynamic_slice(pane, (jnp.int32(0), cs), (R, W))
            pfeat = feat if c2p_arr is None else c2p_arr[feat]
            fbin = jax.lax.dynamic_index_in_dim(
                seg[:F], pfeat, axis=0, keepdims=False).astype(jnp.int32)
            fbin = fbin & 255                     # int8 pane -> uint8 bin
            lane = jnp.arange(W, dtype=jnp.int32)
            inseg = (lane >= delta) & (lane < delta + cnt)
            go_right = fbin > thr
            mask3 = jnp.where(inseg,
                              jnp.where(go_right, 0, 1), -1).astype(jnp.int8)
            plcnt = jnp.sum(inseg & ~go_right).astype(jnp.int32)
            new_seg = partition_segment(seg, mask3, delta, cnt, plcnt,
                                        use_pallas=use_pallas_partition,
                                        overlap=partition_overlap,
                                        interpret=interpret)
            pane2 = jax.lax.dynamic_update_slice(pane, new_seg,
                                                 (jnp.int32(0), cs))
            return pane2, plcnt

        return branch

    def make_hist_branch(k: int):
        W = table[k]

        def branch(op):
            pane2, sstart, scnt, salt = op
            cs2 = jnp.minimum(sstart, P - W)
            d2 = sstart - cs2
            hseg = jax.lax.dynamic_slice(pane2, (jnp.int32(0), cs2),
                                         (R, W))
            hbins, hg, hh, hvalid = unpack_values(hseg, F)
            lane2 = jnp.arange(W, dtype=jnp.int32)
            hmask = (lane2 >= d2) & (lane2 < d2 + scnt) & hvalid
            return hist_of(hbins, hg, hh, hmask, salt=salt)

        return branch

    partition_branches = [make_partition_branch(k) for k in range(K)]
    hist_branches = [make_hist_branch(k) for k in range(K)]

    def body(_, state: _CompactState) -> _CompactState:
        best_leaf = jnp.argmax(state.cand_gain).astype(jnp.int32)
        best_gain = state.cand_gain[best_leaf]
        should_split = jnp.logical_and(~state.done, best_gain > 0.0)

        def do_split(state: _CompactState) -> _CompactState:
            tree = state.tree
            bl = best_leaf
            nl = tree.num_leaves
            node = nl - 1
            new_leaf = nl

            feat = state.cand_feature[bl]
            thr = state.cand_threshold[bl]

            # --- record the node (Tree::Split, tree.cpp:50-83)
            p = tree.leaf_parent[bl]
            pp = jnp.maximum(p, 0)
            lc_at_p = jnp.where((p >= 0) & (tree.left_child[pp] == ~bl),
                                node, tree.left_child[pp])
            rc_at_p = jnp.where((p >= 0) & (tree.right_child[pp] == ~bl),
                                node, tree.right_child[pp])
            left_child = (tree.left_child.at[pp].set(lc_at_p)
                          .at[node].set(~bl))
            right_child = (tree.right_child.at[pp].set(rc_at_p)
                           .at[node].set(~new_leaf))

            # --- original-order leaf ids (score updates need them; the
            # pane's permutation never leaves this function)
            ofeat = feat if c2p_arr is None else c2p_arr[feat]
            obin = jax.lax.dynamic_index_in_dim(
                bins, ofeat, axis=0, keepdims=False).astype(jnp.int32)
            leaf_ids = jnp.where((tree.leaf_ids == bl) & (obin > thr),
                                 new_leaf, tree.leaf_ids)

            # --- partition the parent's lane range at ITS tier (local,
            # collective-free: shards may take different branches)
            start = state.seg_start[bl]
            cnt = state.seg_cnt[bl]
            pane2, plcnt = jax.lax.switch(
                state.seg_bucket[bl], partition_branches,
                (state.pane, start, cnt, feat, thr))
            prcnt = cnt - plcnt

            # --- smaller-child histogram at the CHILD's own tier.  The
            # directly-built side is the VALID-smaller one, exactly like
            # the masked grower (same direct/subtracted f32 rounding);
            # its physical span picks the slice tier — pmax-synced across
            # shards so the collectives inside the branch line up
            lcnt = state.cand_left_cnt[bl]
            rcnt = state.cand_right_cnt[bl]
            left_small = lcnt <= rcnt
            scnt = jnp.where(left_small, plcnt, prcnt)
            sstart = jnp.where(left_small, start, start + plcnt)
            hk_span = scnt
            if hist_axis is not None:
                # tier-selector sync: a scalar pmax per split — tiny on
                # the wire but a full collective latency, so it belongs
                # in the interconnect inventory
                _tl.record_collective(
                    "leafcompact/tier_pmax", "pmax", hist_axis,
                    _tl._tree_nbytes(hk_span), loop=L - 1, phase="grow")
                hk_span = jax.lax.pmax(hk_span, hist_axis)
            small_hist = jax.lax.switch(
                bucket_of(hk_span), hist_branches,
                (pane2, sstart, scnt, new_leaf))

            parent_hist = state.hist_cache[bl]
            large_hist = parent_hist - small_hist
            lhist = jnp.where(left_small, small_hist, large_hist)
            rhist = jnp.where(left_small, large_hist, small_hist)
            hist_cache = (state.hist_cache.at[bl].set(lhist)
                          .at[new_leaf].set(rhist))

            lg, lh = state.cand_left_g[bl], state.cand_left_h[bl]
            rg, rh = state.cand_right_g[bl], state.cand_right_h[bl]
            depth = state.leaf_depth[bl] + 1

            lbest, rbest = best_of_pair(lhist, rhist, lg, lh,
                                        lcnt.astype(f32), rg, rh,
                                        rcnt.astype(f32), depth)

            tree = tree._replace(
                num_leaves=nl + 1,
                split_feature=tree.split_feature.at[node].set(feat),
                threshold_bin=tree.threshold_bin.at[node].set(thr),
                split_gain=tree.split_gain.at[node].set(best_gain),
                left_child=left_child,
                right_child=right_child,
                leaf_parent=tree.leaf_parent.at[bl].set(node)
                                            .at[new_leaf].set(node),
                leaf_value=tree.leaf_value
                               .at[bl].set(state.cand_left_out[bl])
                               .at[new_leaf].set(state.cand_right_out[bl]),
                leaf_count=tree.leaf_count.at[bl].set(lcnt)
                                          .at[new_leaf].set(rcnt),
                leaf_ids=leaf_ids,
            )
            return state._replace(
                tree=tree,
                pane=pane2,
                seg_start=state.seg_start.at[new_leaf].set(start + plcnt),
                seg_cnt=state.seg_cnt.at[bl].set(plcnt)
                                     .at[new_leaf].set(prcnt),
                seg_bucket=state.seg_bucket.at[bl].set(bucket_of(plcnt))
                                           .at[new_leaf].set(
                                               bucket_of(prcnt)),
                hist_cache=hist_cache,
                cand_gain=state.cand_gain.at[bl].set(lbest.gain)
                                         .at[new_leaf].set(rbest.gain),
                cand_feature=state.cand_feature.at[bl].set(lbest.feature)
                                               .at[new_leaf]
                                               .set(rbest.feature),
                cand_threshold=state.cand_threshold
                                    .at[bl].set(lbest.threshold)
                                    .at[new_leaf].set(rbest.threshold),
                cand_left_out=state.cand_left_out
                                   .at[bl].set(lbest.left_output)
                                   .at[new_leaf].set(rbest.left_output),
                cand_right_out=state.cand_right_out
                                    .at[bl].set(lbest.right_output)
                                    .at[new_leaf].set(rbest.right_output),
                cand_left_cnt=state.cand_left_cnt
                                   .at[bl].set(lbest.left_count)
                                   .at[new_leaf].set(rbest.left_count),
                cand_right_cnt=state.cand_right_cnt
                                    .at[bl].set(lbest.right_count)
                                    .at[new_leaf].set(rbest.right_count),
                cand_left_g=state.cand_left_g
                                 .at[bl].set(lbest.left_sum_grad)
                                 .at[new_leaf].set(rbest.left_sum_grad),
                cand_left_h=state.cand_left_h
                                 .at[bl].set(lbest.left_sum_hess)
                                 .at[new_leaf].set(rbest.left_sum_hess),
                cand_right_g=state.cand_right_g
                                  .at[bl].set(lbest.right_sum_grad)
                                  .at[new_leaf].set(rbest.right_sum_grad),
                cand_right_h=state.cand_right_h
                                  .at[bl].set(lbest.right_sum_hess)
                                  .at[new_leaf].set(rbest.right_sum_hess),
                leaf_depth=state.leaf_depth.at[bl].set(depth)
                                           .at[new_leaf].set(depth),
            )

        def no_split(state: _CompactState) -> _CompactState:
            return state._replace(done=jnp.asarray(True))

        # profiler alignment (ISSUE 2): label the compacted split body so
        # profile_dir= traces group its partition/histogram ops per split
        with jax.named_scope("leafcompact_split"):
            return jax.lax.cond(should_split, do_split, no_split, state)

    state = jax.lax.fori_loop(0, L - 1, body, state)
    return state if return_state else state.tree
