"""Depth-wise (level-batched) tree grower — the TPU throughput path.

The reference grows leaf-wise: one histogram rebuild per split, 254
sequential device passes for a 255-leaf tree
(/root/reference/src/treelearner/serial_tree_learner.cpp:119-153).  That
schedule is hostile to a systolic-array machine: each pass is a matmul whose
value operand has only 3 columns (grad/hess/count), so the MXU runs ~2% full
and per-pass fixed costs are paid 254 times.

This grower instead grows the tree LEVEL by level (XGBoost-style
``grow_policy=depthwise``) and builds the histograms of ALL leaves of a
level in ONE leaf-batched matmul pass (ops/histogram.py
``histogram_leafbatch``): the value operand gets 3·P columns for P parent
slots, filling the MXU.  A 255-leaf tree needs 8 batched passes instead of
254 single-leaf passes.  The smaller-child + subtraction trick
(serial_tree_learner.cpp:262-283, feature_histogram.hpp:91-100) is kept at
level granularity: each level histograms only the SMALLER child of every
split parent and derives the sibling by parent − smaller.

Semantics: identical split-finding math as the leaf-wise grower (same
``find_best_split``), but split ORDER is by level, not globally best-first —
a deliberate, documented TPU-first trade (the reference's strict leaf-wise
order remains available as ``grow_policy=leafwise``).  The ``num_leaves``
budget is honored exactly: when a level has more splittable leaves than
budget, the top leaves by gain are split (mirroring best-first within the
level); trees therefore have at most ``num_leaves`` leaves, at depth
``ceil(log2(num_leaves))`` (or ``max_depth``).

The whole tree is ONE jitted straight-line XLA program (levels unrolled in
Python — every level has static shapes [P = 2^d slots]), with no
data-dependent host round-trips.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .. import telemetry
from ..ops.histogram import histogram_leafbatch
from ..ops.split import find_best_split
from .grower import TreeArrays

# out-of-bounds scatter index → mode="drop".  A plain int, NOT jnp.int32:
# creating a jax array at import time would initialize the XLA backend
# before jax.distributed.initialize can run (multi-process bootstrap).
BIG = 1 << 28


def num_levels(num_leaves: int, max_depth: int = -1) -> int:
    """Number of split levels.  Matches the leaf-wise depth rule
    (grower.py: a leaf at depth >= max_depth cannot split, root depth 1), so
    max_depth allows max_depth - 1 split levels."""
    d = max(1, math.ceil(math.log2(max(num_leaves, 2))))
    if max_depth > 0:
        d = min(d, max(max_depth - 1, 1))
    return d


def grow_tree_depthwise(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                        row_mask: jax.Array, feature_mask: jax.Array,
                        num_bins: jax.Array, *, num_leaves: int,
                        num_bins_max: int, min_data_in_leaf: int,
                        min_sum_hessian_in_leaf: float, max_depth: int = -1,
                        hist_chunk: int = 65536, hist_reduce=None,
                        stat_reduce=None, split_finder=None,
                        partition_bins=None, hist_axis=None,
                        compute_dtype=jnp.float32, packing=None,
                        hist_reduce_level=None, int_reduce_level=None,
                        own_slice=None) -> TreeArrays:
    """Grow one depth-wise tree.  Output contract == grow_tree_impl's
    TreeArrays (models/grower.py), so boosting/serialization/prediction are
    policy-agnostic.

    hist_reduce/stat_reduce: collective hooks for the data-parallel learner
    (psum over the mesh), applied to the [C,F,B,3] level histogram and the
    root stat triple respectively.
    split_finder: optional replacement for find_best_split; the
    feature-parallel learner wraps it with the SplitInfo argmax allreduce and
    must return GLOBAL feature indices (vmapped over level slots, so any
    collectives inside are batched).
    partition_bins: optional [F_global, N] matrix used to APPLY splits when
    ``bins`` is only the owned feature slice (feature-parallel).

    ReduceScatter ownership schedule (the reference's bandwidth-optimal
    data-parallel plan, data_parallel_tree_learner.cpp:135-235): the ROOT
    pass reduces in full (root stats must be the replicated global triple),
    ``own_slice`` then cuts each shard's contiguous feature block out of
    the replicated root histogram, and every deeper level reduces via
    ``hist_reduce_level`` (f32: psum_scatter on the feature axis) or
    ``int_reduce_level`` (int8: psum_scatter of the INT accumulators,
    preserving the bit-exactness chain).  ``split_finder`` must then map
    block-local feature ids to global and allreduce the SplitInfo; the
    subtraction trick works unchanged on owned blocks.
    """
    F, N = bins.shape
    L = num_leaves
    D = num_levels(L, max_depth)
    B = num_bins_max
    f32 = jnp.float32
    i32 = jnp.int32

    # wire-metrics hook point (ISSUE 5): label any seam the learner did
    # not already wrap (collective_span passes wrapped fns through); the
    # level reducers trace once per level, so loop stays 1 per trace
    from .. import telemetry as _tl
    hist_reduce = _tl.collective_span(
        "depthwise/hist_reduce", hist_reduce, kind="reduce",
        axis=hist_axis, phase="grow")
    hist_reduce_level = _tl.collective_span(
        "depthwise/level_hist_reduce", hist_reduce_level, kind="reduce",
        axis=hist_axis, phase="grow")
    int_reduce_level = _tl.collective_span(
        "depthwise/level_int_reduce", int_reduce_level, kind="reduce",
        axis=hist_axis, phase="grow")
    stat_reduce = _tl.collective_span(
        "depthwise/root_stats", stat_reduce, kind="reduce", axis=hist_axis,
        phase="grow")

    maskf = row_mask.astype(f32)
    mind = float(min_data_in_leaf)
    minh = float(min_sum_hessian_in_leaf)

    def batch_hist_rows(b, g, h, col_id, col_ok, C, level=False, salt=0):
        # level passes may use the scatter schedule; the root pass always
        # reduces in full
        int_red = int_reduce_level if level else None
        # forward optional kwargs only when set: drop-in replacements
        # (histogram_leafbatch_segsum, test/profiling stubs) don't take
        # them
        extra = {"int_reduce": int_red} if int_red is not None else {}
        if salt and compute_dtype == "int8_sr":
            extra["salt"] = salt
        out = histogram_leafbatch(b, g, h, col_id, col_ok, C, B,
                                  chunk=hist_chunk,
                                  compute_dtype=compute_dtype,
                                  axis_name=hist_axis,
                                  **({"packing": packing}
                                     if packing is not None else {}),
                                  **extra)
        # the quantized path reduces its INT accumulators internally over
        # hist_axis (bit-exactness); applying hist_reduce again would
        # double-count
        if str(compute_dtype).startswith("int8") and hist_axis is not None:
            return out
        red = (hist_reduce_level or hist_reduce) if level else hist_reduce
        if red is not None:
            out = red(out)
        return out

    def batch_hist(col_id, col_ok, C, level=False, salt=0):
        return batch_hist_rows(bins, grad, hess, col_id, col_ok, C,
                               level=level, salt=salt)

    vsplit = jax.vmap(split_finder or find_best_split,
                      in_axes=(0, 0, 0, 0, None, None, None, None))
    if partition_bins is None:
        partition_bins = bins

    # ---- root (BeforeTrain: serial_tree_learner.cpp:155-236).
    # named_scope per level (ISSUE 2): profile_dir= Perfetto traces show
    # the unrolled level structure ("level0/histogram", ...) instead of a
    # flat op soup — unconditional, so it can't perturb program identity
    with jax.named_scope("level0"):
        hists = batch_hist(jnp.zeros((N,), i32), row_mask, 1)  # [1,F,B,3]
    if str(compute_dtype).startswith("int8"):
        # derive root stats from the root histogram: the quantized hist is
        # bit-identical across serial / data-parallel / multi-process (the
        # scale is pmax-synced and int32 sums are order-free), so this
        # makes the WHOLE tree's stat chain reduction-order-free — a row
        # psum here would differ from a serial row sum by ulps and flip
        # near-tie splits between serial and distributed runs.  (Also keeps
        # parent == left + right exactly in quantized space.)
        root_stats = jnp.sum(hists[0, 0], axis=0)          # [3]
    else:
        root_stats = jnp.stack([jnp.sum(grad * maskf),
                                jnp.sum(hess * maskf), jnp.sum(maskf)])
        if stat_reduce is not None:
            root_stats = stat_reduce(root_stats)
    if own_slice is not None:
        # ownership schedule: keep only this shard's contiguous feature
        # block from here on (root stats above came from the full
        # replicated histogram, so they stay bit-identical to the psum
        # schedule)
        hists = own_slice(hists)

    # per-slot level state (slot s at level d holds one candidate leaf)
    alive = jnp.ones((1,), bool)
    leaf_of = jnp.zeros((1,), i32)          # output leaf index per slot
    parent_node = jnp.full((1,), -1, i32)   # node owning this slot's leaf
    slot_g = root_stats[0][None]
    slot_h = root_stats[1][None]
    slot_c = root_stats[2][None]

    slot_id = jnp.zeros((N,), i32)          # row → level-local slot
    out_leaf = jnp.zeros((N,), i32)         # row → output leaf index

    # output tree arrays (static size L)
    leaf_value = jnp.zeros((L,), f32)
    leaf_count = jnp.zeros((L,), i32).at[0].set(root_stats[2].astype(i32))
    leaf_parent = jnp.full((L,), -1, i32)
    split_feature = jnp.zeros((max(L - 1, 1),), i32)
    threshold_bin = jnp.zeros((max(L - 1, 1),), i32)
    split_gain = jnp.zeros((max(L - 1, 1),), f32)
    left_child = jnp.zeros((max(L - 1, 1),), i32)
    right_child = jnp.zeros((max(L - 1, 1),), i32)

    n_nodes = jnp.asarray(0, i32)           # == num_leaves_cur - 1

    for d in range(D):
        P = 1 << d

        # ---- best split per slot (vmapped FindBestThreshold scan).  The
        # span wraps the CALL (not the vmapped body — a batching trace is
        # never "execution"), so eager runs (jax.disable_jit telemetry
        # profiling) attribute real split-search time
        with telemetry.span("split_find") as _sp:
            res = _sp.fence(vsplit(hists, slot_g, slot_h, slot_c, num_bins,
                                   feature_mask, mind, minh))
        can = alive & (res.gain > 0.0) & jnp.isfinite(res.gain)

        # ---- budget: split the top-gain slots first (within-level
        # best-first, matching the leaf-wise selection rule at level scope)
        budget = (L - 1) - n_nodes
        gains_m = jnp.where(can, res.gain, -jnp.inf)
        order = jnp.argsort(-gains_m)                 # best slot first
        rank = jnp.argsort(order).astype(i32)         # slot → rank
        chosen = can & (rank < budget)
        n_chosen = jnp.sum(chosen.astype(i32))

        # ---- index assignment, in slot order (deterministic)
        csum = jnp.cumsum(chosen.astype(i32))
        node_of = n_nodes + csum - 1                  # node per chosen slot
        right_leaf = (n_nodes + 1) + csum - 1         # new leaf per chosen
        bl = leaf_of

        nidx = jnp.where(chosen, node_of, BIG)
        blx = jnp.where(chosen, bl, BIG)
        rlx = jnp.where(chosen, right_leaf, BIG)

        # ---- node records (Tree::Split, tree.cpp:50-83)
        split_feature = split_feature.at[nidx].set(res.feature, mode="drop")
        threshold_bin = threshold_bin.at[nidx].set(res.threshold, mode="drop")
        split_gain = split_gain.at[nidx].set(res.gain, mode="drop")
        left_child = left_child.at[nidx].set(~bl, mode="drop")
        right_child = right_child.at[nidx].set(~right_leaf, mode="drop")

        # parent child-pointer fixup: slot parity says which side this
        # slot's leaf sits on in its parent node (even = left)
        pfix = jnp.where(chosen & (parent_node >= 0), parent_node, BIG)
        if d > 0:
            is_left = (jnp.arange(P, dtype=i32) % 2) == 0
            left_child = left_child.at[
                jnp.where(is_left, pfix, BIG)].set(node_of, mode="drop")
            right_child = right_child.at[
                jnp.where(is_left, BIG, pfix)].set(node_of, mode="drop")

        # ---- leaf records
        leaf_value = leaf_value.at[blx].set(res.left_output, mode="drop")
        leaf_value = leaf_value.at[rlx].set(res.right_output, mode="drop")
        leaf_count = leaf_count.at[blx].set(res.left_count, mode="drop")
        leaf_count = leaf_count.at[rlx].set(res.right_count, mode="drop")
        leaf_parent = leaf_parent.at[blx].set(node_of, mode="drop")
        leaf_parent = leaf_parent.at[rlx].set(node_of, mode="drop")

        n_nodes = n_nodes + n_chosen

        # ---- partition rows (DataPartition::Split as fused masked passes)
        # All per-slot attributes a row needs (split feature, threshold,
        # chosen flag, new right-leaf id, smaller-child side) ride ONE
        # [P, N] one-hot matmul instead of one pass per attribute: the
        # slot-select one-hot is the expensive object (O(P·N) comparisons),
        # so it is generated once and contracted against a packed [P, K]
        # table.
        small_is_right = res.right_count < res.left_count        # ties → left
        with telemetry.span("partition") as _sp:
            # mixed-bin packing stores the matrix rows in packed order;
            # the per-slot partition feature must address that layout
            # (the recorded split_feature above stays canonical)
            feat_part = res.feature
            if packing is not None and len(packing.widths) > 1:
                feat_part = jnp.asarray(packing.c2p, jnp.int32)[res.feature]
            table = jnp.stack([feat_part.astype(f32),
                               res.threshold.astype(f32),
                               chosen.astype(f32),
                               right_leaf.astype(f32),
                               small_is_right.astype(f32)], axis=1)  # [P, 5]
            lsel = (slot_id[None, :] ==
                    jnp.arange(P, dtype=i32)[:, None]).astype(f32)   # [P, N]
            # The table carries integer ids (feature, threshold, leaf).
            # Default TPU matmul precision truncates f32 operands to bf16,
            # which is EXACT for integers <= 256 — and exactly one lsel
            # entry matches per row, so there is no accumulation error
            # either.  Only configs with ids beyond 256 need the 6-pass
            # HIGHEST decomposition (measured 2.27 ms vs 0.72 ms per level
            # at 11M rows).
            ids_bf16_exact = max(F, B, L) <= 256
            attr_prec = (None if ids_bf16_exact
                         else jax.lax.Precision.HIGHEST)
            attrs = jnp.einsum("pn,pk->kn", lsel, table,
                               precision=attr_prec,
                               preferred_element_type=jnp.float32)   # [5, N]
            feat_row = attrs[0].astype(i32)
            thr_row = attrs[1].astype(i32)
            in_chosen = attrs[2] > 0.5
            rl_row = attrs[3].astype(i32)
            small_right_row = attrs[4] > 0.5

            # the row's bin on its slot's split feature: an O(F·N) feature
            # one-hot avoids materializing the old [P, N] row gather, but
            # its cost grows with the dataset width — for wide datasets a
            # direct per-row gather is cheaper than F·N comparisons
            Fg = partition_bins.shape[0]
            if Fg <= 128:
                fsel = (feat_row[None, :]
                        == jnp.arange(Fg, dtype=i32)[:, None])
                # bins < 256 are bf16-exact and one fsel entry matches per
                # row
                row_bin = jnp.einsum(
                    "fn,fn->n", fsel.astype(f32), partition_bins.astype(f32),
                    precision=(None if B <= 256
                               else jax.lax.Precision.HIGHEST)).astype(i32)
            else:
                row_bin = jnp.take_along_axis(
                    partition_bins, feat_row[None, :], axis=0)[0].astype(i32)
            go_right = row_bin > thr_row
            out_leaf = jnp.where(in_chosen & go_right, rl_row, out_leaf)
            slot_id = (2 * slot_id
                       + jnp.where(in_chosen, go_right.astype(i32), 0))
            _sp.fence((out_leaf, slot_id))

        if d + 1 >= D:
            break

        # ---- next-level slot state (children of slot s at 2s / 2s+1)
        def interleave(a, b):
            return jnp.stack([a, b], axis=1).reshape(2 * P, *a.shape[1:])

        alive = interleave(chosen, chosen)
        leaf_of = interleave(bl, right_leaf)
        parent_node = interleave(node_of, node_of)
        slot_g = interleave(res.left_sum_grad, res.right_sum_grad)
        slot_h = interleave(res.left_sum_hess, res.right_sum_hess)
        slot_c = interleave(res.left_count.astype(f32),
                            res.right_count.astype(f32))

        # ---- level histogram: build ONLY the smaller child of every chosen
        # parent in one batched pass, derive the sibling by subtraction
        par_of_row = slot_id // 2
        # Smaller-child choice from the SplitResult counts (integer-valued
        # f32 histogram sums; replicated under the data-parallel learner,
        # whose counts come from psum'd histograms).  Above 2^24 rows per
        # node the f32 rounding could mis-order near-equal children — that
        # only means the pass histograms the slightly larger child (the
        # sibling is still exact via subtraction), a perf non-event, so no
        # recount is needed at any scale.
        sel = in_chosen & (go_right == small_right_row) & row_mask
        # The masked full-N pass is the fastest smaller-child schedule
        # measured on v5e (1M and 11M rows): gathering the selected rows
        # into a compact N/2 buffer first (the masked-dense analog of the
        # reference's per-leaf index lists, data_partition.hpp) costs more
        # in cumsum/scatter/gather plumbing than the halved histogram pass
        # saves — see git history for the removed compaction path.
        with jax.named_scope("level%d" % (d + 1)):
            hist_small = batch_hist(par_of_row, sel, P, level=True,
                                    salt=d + 1)
        hist_large = hists - hist_small
        hsmall_slot = interleave(jnp.where(small_is_right[:, None, None, None],
                                           hist_large, hist_small),
                                 jnp.where(small_is_right[:, None, None, None],
                                           hist_small, hist_large))
        hists = hsmall_slot

    num_leaves_final = n_nodes + 1
    return TreeArrays(
        num_leaves=num_leaves_final,
        split_feature=split_feature[:max(L - 1, 1)],
        threshold_bin=threshold_bin,
        split_gain=split_gain,
        left_child=left_child,
        right_child=right_child,
        leaf_parent=leaf_parent,
        leaf_value=leaf_value,
        leaf_count=leaf_count,
        leaf_ids=out_leaf,
    )


# Module-level jit so repeated boosters with identical shapes/config share
# one compiled program (the unrolled level program takes minutes to compile).
# Wrapped in the cost registry (costmodel.instrument): with telemetry armed
# the compiled program self-reports cost_analysis + compile seconds for the
# roofline/compile blocks.
from .. import costmodel as _costmodel  # noqa: E402

grow_tree_depthwise_jit = _costmodel.instrument(
    "grow/depthwise",
    jax.jit(grow_tree_depthwise,
            static_argnames=("num_leaves", "num_bins_max",
                             "min_data_in_leaf", "min_sum_hessian_in_leaf",
                             "max_depth", "hist_chunk", "compute_dtype",
                             "packing", "hist_axis")),
    phase="grow")
