"""Depth-wise grower — compat shim over ``models/grower_unified.py``.

The three grower modules were collapsed into ONE schedule-parameterized
grower (ISSUE 9); this module keeps the historical depth-wise entry
points (``grow_tree_depthwise`` with keyword seams, the module-level
``grow_tree_depthwise_jit``, ``num_levels``) plus the patchable
``histogram_leafbatch`` attribute, and nothing else (graftlint-proved
surface, pinned by tests/test_graftlint.py).  New code should import
from ``grower_unified`` directly.
"""
from __future__ import annotations

import jax.numpy as jnp

# patchable histogram seam: tests and scripts/profile_phases.py
# monkeypatch THIS attribute (the unified grower resolves it through
# this module at trace time)
from ..ops.histogram import histogram_leafbatch  # noqa: F401

from .grower_unified import (  # noqa: F401
    SeamSchedule, grow_tree_depthwise_jit, grow_tree_unified, num_levels)


def grow_tree_depthwise(bins, grad, hess, row_mask, feature_mask,
                        num_bins, *, num_leaves: int, num_bins_max: int,
                        min_data_in_leaf: int,
                        min_sum_hessian_in_leaf: float, max_depth: int = -1,
                        hist_chunk: int = 65536, hist_reduce=None,
                        stat_reduce=None, split_finder=None,
                        partition_bins=None, hist_axis=None,
                        compute_dtype=jnp.float32, packing=None,
                        hist_reduce_level=None, int_reduce_level=None,
                        own_slice=None):
    """Historical keyword-seam surface over
    ``grow_tree_unified(policy="depthwise")``; returns a
    ``grower_unified.TreeArrays``."""
    schedule = SeamSchedule(
        hist_axis=hist_axis, hist_reduce=hist_reduce,
        stat_reduce=stat_reduce, own_slice=own_slice,
        split_finder=split_finder, hist_reduce_level=hist_reduce_level,
        int_reduce_level=int_reduce_level)
    return grow_tree_unified(
        bins, grad, hess, row_mask, feature_mask, num_bins,
        policy="depthwise", num_leaves=num_leaves,
        num_bins_max=num_bins_max, min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian_in_leaf=min_sum_hessian_in_leaf,
        max_depth=max_depth, hist_chunk=hist_chunk,
        compute_dtype=compute_dtype, packing=packing, schedule=schedule,
        partition_bins=partition_bins)
