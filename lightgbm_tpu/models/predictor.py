"""Batch predictor: parse → predict → write TSV results.

Re-design of /root/reference/src/application/predictor.hpp:23-228.  Per-thread
dense row buffers become a single dense feature matrix; predictions are
vectorized tree replays (models/tree.py) rather than per-row walks.
Output modes match: multiclass tab-joined probabilities, leaf indices,
sigmoid, or raw scores.
"""
from __future__ import annotations

import numpy as np

from ..io import parser as parser_mod
from ..utils import log


class Predictor:
    def __init__(self, boosting, is_sigmoid: bool, is_predict_leaf_index: bool,
                 num_used_model: int):
        self.boosting = boosting
        self.is_sigmoid = is_sigmoid
        self.is_predict_leaf_index = is_predict_leaf_index
        self.num_used_model = num_used_model
        self.num_features = boosting.max_feature_idx + 1
        self.num_class = boosting.num_class

    def predict_matrix(self, features: np.ndarray) -> np.ndarray:
        """Dense [N, num_features] → predictions (rows of the result file)."""
        if features.shape[1] < self.num_features:
            pad = np.zeros((features.shape[0],
                            self.num_features - features.shape[1]))
            features = np.concatenate([features, pad], axis=1)
        features = features[:, :max(self.num_features, 1)]
        if self.num_class > 1:
            return self.boosting.predict_multiclass(features,
                                                    self.num_used_model)
        if self.is_predict_leaf_index:
            return self.boosting.predict_leaf_index(features,
                                                    self.num_used_model)
        if self.is_sigmoid:
            return self.boosting.predict(features, self.num_used_model)
        return self.boosting.predict_raw(features, self.num_used_model)

    def predict_file(self, data_filename: str, result_filename: str,
                     has_header: bool) -> None:
        """Predictor::Predict (predictor.hpp:109-197).

        Streams the file in bounded chunks (the reference predicts
        line-by-line off a pipelined reader; here a prefetcher thread
        reads the next chunk while the current one predicts), so the raw
        feature matrix never materializes whole."""
        parser = parser_mod.create_parser(data_filename, has_header,
                                          self.num_features,
                                          self.boosting.label_idx)
        with open(result_filename, "w") as f:
            for lines in parser_mod.prefetch_chunks(
                    parser_mod.read_line_chunks(
                        data_filename, skip_header=has_header,
                        chunk_lines=500_000)):
                parsed = parser.parse(lines)
                result = self.predict_matrix(parsed.features)
                if result.ndim == 1:
                    for v in result:
                        f.write(_fmt(v) + "\n")
                else:
                    for row in result:
                        f.write("\t".join(_fmt(v) for v in row) + "\n")
        log.info("Finished prediction, result saved to %s" % result_filename)


def _fmt(v) -> str:
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    # std::to_string(double) prints 6 decimals
    return "%.6f" % float(v)
