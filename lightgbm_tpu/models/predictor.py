"""Batch predictor: parse → predict → write TSV results.

Re-design of /root/reference/src/application/predictor.hpp:23-228.  Per-thread
dense row buffers become a single dense feature matrix; predictions run
through the compiled serving engine (lightgbm_tpu/serving.py): the
ensemble is flattened ONCE in __init__ (not once per 500k-row chunk, as
the old per-call device encode did), batches are padded to the engine's
bucket ladder, and every chunk reuses the same compiled programs.
Output modes match: multiclass tab-joined probabilities, leaf indices,
sigmoid, or raw scores.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import parser as parser_mod
from ..utils import log


class Predictor:
    def __init__(self, boosting, is_sigmoid: bool, is_predict_leaf_index: bool,
                 num_used_model: int, serving_options: dict = None):
        self.boosting = boosting
        self.is_sigmoid = is_sigmoid
        self.is_predict_leaf_index = is_predict_leaf_index
        self.num_used_model = num_used_model
        self.num_features = boosting.max_feature_idx + 1
        self.num_class = boosting.num_class
        # engine built ONCE: predict_file's chunk loop must not re-flatten
        # the ensemble per chunk (tests/test_serving.py pins the
        # single-flatten behavior via serving.FLATTEN_COUNT)
        if num_used_model < 0:
            num_models = len(boosting.models)
        elif self.num_class > 1:
            num_models = num_used_model * self.num_class
        else:
            num_models = num_used_model
        self.engine = boosting.serving_engine(num_models,
                                              **(serving_options or {}))

    def predict_matrix(self, features: np.ndarray) -> np.ndarray:
        """Dense [N, num_features] → predictions (rows of the result file)."""
        if features.shape[1] < self.num_features:
            # pad in the INPUT dtype: a float64 default here would silently
            # upcast f32 feature matrices on concatenate
            pad = np.zeros((features.shape[0],
                            self.num_features - features.shape[1]),
                           dtype=features.dtype)
            features = np.concatenate([features, pad], axis=1)
        features = features[:, :max(self.num_features, 1)]
        if self.is_predict_leaf_index:
            return self.engine.leaf_indices(features)
        scores = self.engine.scores(features)
        if self.num_class > 1:
            # softmax (gbdt.cpp:496-508), same transform as
            # GBDT.predict_multiclass
            out = scores.T
            z = out - out.max(axis=1, keepdims=True)
            p = np.exp(z)
            return p / p.sum(axis=1, keepdims=True)
        raw = scores[0]
        if self.is_sigmoid and self.boosting.sigmoid > 0:
            return 1.0 / (1.0 + np.exp(-2.0 * self.boosting.sigmoid * raw))
        return raw

    def predict_file(self, data_filename: str, result_filename: str,
                     has_header: bool, chunk_lines: int = 500_000) -> None:
        """Predictor::Predict (predictor.hpp:109-197) — streamed
        out-of-core scoring (ISSUE 13 axis d).

        The file chunks through the streaming parse→encode path: the
        background pipeline reads AND parses up to ``predict_queue``
        chunks ahead (the PR 8 double-buffer idea applied to scoring —
        host tokenization of chunk i+1 hides behind the device walk of
        chunk i), so neither the raw feature matrix nor the score vector
        ever materializes whole and a 100M+-row file scores in bounded
        host memory.  Scores are row-independent through the engine
        (bucket padding never leaks), so the output file is
        BYTE-IDENTICAL at any chunk length — tests pin streamed ==
        resident.  The ensemble encode is NOT per-chunk: the engine
        built in __init__ carries it.

        A native columnar-binary cache as ``data=`` (header-sniffed,
        ISSUE 18b) scores without any text parse: bin codes are memmapped
        and decoded through each mapper's ``bin_representatives`` —
        values that land in the same bins the original rows did, so the
        trees (whose thresholds ARE bin upper bounds) traverse
        identically."""
        from ..io.dataset import Dataset
        if (os.path.exists(data_filename)
                and Dataset._classify_binary_cache(data_filename)
                == "ours"):
            return self._predict_binary_file(data_filename,
                                             result_filename, chunk_lines)
        parser = parser_mod.create_parser(data_filename, has_header,
                                          self.num_features,
                                          self.boosting.label_idx)
        lines_iter = parser_mod.read_line_chunks(
            data_filename, skip_header=has_header, chunk_lines=chunk_lines)

        def _parsed_features():
            for lines in lines_iter:
                yield parser.parse(lines).features

        depth = max(int(getattr(self.engine, "queue", 2)), 1)
        with open(result_filename, "w") as f:
            for features in parser_mod.prefetch_chunks(_parsed_features(),
                                                       depth=depth):
                result = self.predict_matrix(features)
                self._write_chunk(f, result)
        log.info("Finished prediction, result saved to %s" % result_filename)

    def _predict_binary_file(self, data_filename: str,
                             result_filename: str,
                             chunk_lines: int) -> None:
        """Score a native binary cache directly: memmap the ``[F, N]``
        bin matrix, reconstruct a representative feature matrix per row
        chunk (in the parser's label-removed column space — exactly what
        ``predict_matrix`` expects), and stream the same formatted
        writes as the text path."""
        import pickle

        from ..io.binning import BinMapper
        from ..io.dataset import BINARY_MAGIC

        try:
            with open(data_filename, "rb") as f:
                f.read(len(BINARY_MAGIC))
                size = int.from_bytes(f.read(8), "little")
                header = pickle.loads(f.read(size))
                offset = f.tell()
        except Exception as e:
            log.fatal("Binary file %s is a damaged lightgbm_tpu cache "
                      "(%s) — delete it to regenerate"
                      % (data_filename, e))
        mappers = [BinMapper.from_bytes(b) for b in header["mappers"]]
        reps = [m.bin_representatives() for m in mappers]
        used_map = header["used_feature_map"]
        num_total = int(header["num_total_features"])
        shape = tuple(header["bins_shape"])
        mm = (np.memmap(data_filename,
                        dtype=np.dtype(header["bins_dtype"]), mode="r",
                        offset=offset, shape=shape)
              if shape[0] * shape[1] else None)
        with open(result_filename, "w") as f:
            for s in range(0, shape[1], chunk_lines):
                e = min(s + chunk_lines, shape[1])
                features = np.zeros((e - s, num_total), dtype=np.float64)
                if mm is not None:
                    for j_raw, j_inner in used_map.items():
                        features[:, j_raw] = \
                            reps[j_inner][np.asarray(mm[j_inner, s:e])]
                self._write_chunk(f, self.predict_matrix(features))
        log.info("Finished prediction, result saved to %s"
                 % result_filename)

    @staticmethod
    def _write_chunk(f, result: np.ndarray) -> None:
        if result.ndim == 1:
            for v in result:
                f.write(_fmt(v) + "\n")
        else:
            for row in result:
                f.write("\t".join(_fmt(v) for v in row) + "\n")


def _fmt(v) -> str:
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    # std::to_string(double) prints 6 decimals
    return "%.6f" % float(v)
