"""GBDT boosting loop.

Re-design of /root/reference/src/boosting/gbdt.cpp:19-521 (+ gbdt.h,
score_updater.hpp, boosting.cpp factory).  The host drives iterations; each
iteration's compute — gradients, tree growth, score updates — runs as jitted
device programs on the [F, N] bin matrix.  Per-class trees are interleaved
``models_[iter*num_class + k]`` exactly like gbdt.cpp:175-195.

Score maintenance (ScoreUpdater, score_updater.hpp:15-77) is a device
array [num_class, N]; the leaf-id vector returned by the grower covers ALL
rows (in-bag and out-of-bag), so the reference's separate OOB traversal path
(gbdt.cpp:159-165) collapses into one gather.
"""
from __future__ import annotations

import functools
import json
import os
import time
from typing import Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import faults as faults_mod
from .. import hatches, telemetry, tracing
from ..utils import log
from ..ops.scoring import add_tree_score
from ..ops.lookup import exact_table_lookup as _leaf_lookup
from .grower import grow_tree
from .tree import Tree


# int8 histogram row ceiling: a histogram cell accumulates int8 values in
# an int32, and a cell's magnitude is bounded by 127 x rows-in-cell —
# saturated at iteration 0 of binary logloss, where hessians are uniform
# and every row quantizes to exactly 127; a constant (single-bin) feature
# then concentrates ALL rows into one cell.  Rows beyond 2^31/127 can
# therefore wrap the accumulator (and the int-domain psum across shards
# sums into the same int32 range, so the bound is on GLOBAL rows).
INT8_HIST_MAX_ROWS = (1 << 31) // 127


def check_int8_row_capacity(num_rows: int) -> None:
    """Refuse int8 histograms beyond the int32 accumulator's capacity
    (silent wraparound would corrupt every split)."""
    if num_rows > INT8_HIST_MAX_ROWS:
        log.fatal(
            "hist_dtype=int8 supports at most %d rows (int32 histogram "
            "accumulator: 127 x rows can wrap past 2^31 when rows "
            "concentrate in one bin); got %d rows — use "
            "hist_dtype=float32 or bfloat16 at this scale"
            % (INT8_HIST_MAX_ROWS, num_rows))


class GBDT:
    def __init__(self, config=None):
        self.config = config
        self.models: List[Tree] = []
        self.num_class = 1
        self.label_idx = 0
        self.max_feature_idx = 0
        self.sigmoid = -1.0
        self.iter = 0
        self.train_data = None
        self.objective = None
        self.training_metrics = []
        self.valid_datasets = []
        self.valid_metrics = []
        self.best_score = []
        self.best_iter = []
        self.early_stopping_round = 0
        # training-time score-distribution reference (ISSUE 20): the
        # serialized monitor.ScoreHistogram captured from the live
        # training scores, saved as the model file's
        # ``score_reference=`` metadata line — the baseline the serving
        # drift detector compares live scores against
        self.score_reference: Optional[dict] = None
        self._saved_model_size = -1
        self._model_file = None
        self._learner_factory: Optional[Callable] = None
        self._mp = False            # multi-process data-parallel mode
        self._mp_fp = False         # multi-process feature-parallel mode
        self._host_inputs = False
        self._row_valid = None
        # latest metric values keyed "dataset/metric" — rides the
        # telemetry iteration records (captured only while a sink is
        # active, _consume_metric_values)
        self._last_eval_values = {}
        # training-health monitor (ISSUE 2, lightgbm_tpu/health.py) —
        # created in init() when the health= setting resolves on
        self._health_monitor = None
        # pipelined boosting (ISSUE 6): deferred-readback queues.  _pipe
        # holds ONE dispatched-but-unconsumed per-iteration entry,
        # _pipe_chunk one dispatched chunk record; _pipeline_auto is set
        # by run_training when pipeline="auto" resolves on (direct
        # train_one_iter/train_chunk callers keep synchronous semantics
        # unless the config forces "readback")
        self._pipe = None
        self._pipe_chunk = None
        self._pipeline_auto = False
        # preemption-safe elastic training (ISSUE 14): the live straggler
        # policy (elastic.StragglerMonitor, armed via enable_elastic),
        # the learner factory a mesh shrink rebuilds with, the active
        # async checkpoint writer (run_training-scoped), and the last
        # checkpointed iteration
        self._straggler_monitor = None
        self._elastic_exchange_on = False
        self._ckpt_writer = None
        self._last_ckpt_iter = 0
        self._boundary_t = None
        # written/dropped totals of the last run's checkpoint writer
        # (recorded at close; the bench ckpt lane reads them)
        self._ckpt_stats = None

    # ------------------------------------------------------------------ init

    def init(self, boosting_config, train_data, objective,
             training_metrics=(), learner=None) -> None:
        """GBDT::Init (gbdt.cpp:41-89).  ``learner`` optionally overrides the
        tree-growing callable (serial default; parallel learners plug in via
        lightgbm_tpu.parallel)."""
        self.gbdt_config = boosting_config
        self.tree_config = boosting_config.tree_config
        self.train_data = train_data
        self.objective = objective
        self.num_class = boosting_config.num_class
        self.early_stopping_round = boosting_config.early_stopping_round
        self.training_metrics = list(training_metrics)
        self.max_feature_idx = train_data.num_total_features - 1
        self.label_idx = train_data.label_idx
        self.sigmoid = objective.sigmoid if objective is not None else -1.0
        self._learner = learner or _serial_learner
        if (learner is not None
                and getattr(self.tree_config, "leafwise_segments", 1) > 1
                and not getattr(learner, "supports_leafwise_segments",
                                False)):
            # the data-parallel learner segments its shard_map'd split
            # loop (learners._segmented_grow); the feature-parallel one
            # still runs whole-tree dispatches — say so instead of
            # silently ignoring the setting
            log.warning("leafwise_segments is not supported by %s; "
                        "ignored" % type(learner).__name__)

        N = train_data.num_data
        self.num_bins_max = int(train_data.num_bins.max())
        self.num_features = train_data.num_features
        # [F, B] bin→upper-bound table for vectorized threshold conversion
        self._bin_upper_table = train_data.bin_upper_bounds_matrix()

        # mixed-bin feature packing (ISSUE 6): when the dataset mixes
        # narrow (num_bin <= 64) and wide features, reorder the bin matrix
        # into contiguous bin-width classes so every histogram pass prices
        # each class at ITS width instead of the uniform worst case.  The
        # spec is a static (hashable) layout descriptor threaded through
        # the growers; all histograms are reassembled into canonical
        # feature order before split finding, so trees/splits/ownership
        # are bit-identical to the uniform path.  Feature-parallel keeps
        # the uniform layout — its ownership slices are arbitrary feature
        # subsets that a class-contiguous layout cannot serve.
        mixed_mode = getattr(self.tree_config, "mixed_bin", "auto")
        self._pack_spec = None
        if (learner is not None
                and (type(learner).__name__ == "FeatureParallelLearner"
                     or getattr(learner, "needs_uniform_layout", False))):
            # feature-parallel ownership slices are ARBITRARY (bin-count
            # balanced) feature subsets — no contiguous-block structure a
            # packed layout could commute with
            if mixed_mode == "true":
                log.warning("mixed_bin is not supported by %s; "
                            "keeping the uniform layout"
                            % type(learner).__name__)
        elif (learner is not None
                and getattr(learner, "feature_block_packing", False)):
            # hybrid/voting 2-D mesh (ISSUE 12): the bin-width-class
            # permutation is computed PER owned feature block — it never
            # crosses a block boundary, so packing commutes with block
            # ownership and the owned-block psum / packed-SplitInfo
            # allreduce ride unchanged (io/binning.BlockedPackSpec)
            blk, fs = learner.pack_layout(train_data.num_features)
            self._pack_spec = train_data.plan_packing(
                mode=mixed_mode, block=blk, shards=fs)
            if self._pack_spec is None and mixed_mode == "true":
                log.warning(
                    "mixed_bin=true requested but the block-local plan "
                    "degenerates to the uniform layout (single bin-width "
                    "class, or an ownership block without narrow "
                    "features)")
        else:
            self._pack_spec = train_data.plan_packing(mode=mixed_mode)
        if self._pack_spec is not None:
            blocked = hasattr(self._pack_spec, "block")
            telemetry.count_route("hist_layout", "hist/mixedbin_on")
            if blocked:
                # the block-local variant files an extra marker so the
                # route counters distinguish the layouts (telemetry.py
                # hist/mixedbin_* family)
                telemetry.count("hist/mixedbin_blocked")
            if blocked:
                log.info("mixed-bin packing (block-local, block=%d): %d "
                         "narrow (<=%d bins) + %d wide features PER "
                         "owned block (histogram passes per class: %s)"
                         % (self._pack_spec.block,
                            self._pack_spec.counts[0],
                            self._pack_spec.widths[0],
                            self._pack_spec.counts[1],
                            "x".join(str(w)
                                     for w in self._pack_spec.widths)))
            else:
                log.info("mixed-bin packing: %d narrow (<=%d bins) + %d "
                         "wide features (histogram passes per class: %s)"
                         % (self._pack_spec.counts[0],
                            self._pack_spec.widths[0],
                            self._pack_spec.counts[1],
                            "x".join(str(w)
                                     for w in self._pack_spec.widths)))
        else:
            telemetry.count_route("hist_layout", "hist/mixedbin_off")

        # multi-process data parallelism (the reference's N-machine mode,
        # dataset.cpp:172-216): each process holds a row shard; lift every
        # row-aligned array to a global mesh-sharded jax.Array so the
        # shard_map programs span the whole distributed job.
        self._mp = (jax.process_count() > 1 and learner is not None
                    and type(learner).__name__ == "DataParallelLearner")
        # multi-process feature parallel: every process loads the FULL
        # rows (cli.load_data, matching the reference's FP machines —
        # io/config.cpp:164-172 sets is_parallel_find_bin=false) and the
        # replicated-rows FP chunk program runs over the global mesh with
        # host-side (numpy) inputs.  Only the fused depthwise chunk is
        # lifted; the per-iteration path would push committed local
        # arrays into the global-mesh program, so it fails loudly instead
        # of obscurely (feature_parallel_tree_learner.cpp:9-81 is the
        # reference's N-machine FP).
        self._mp_fp = (jax.process_count() > 1 and learner is not None
                       and type(learner).__name__ == "FeatureParallelLearner")
        if self._mp_fp and self.tree_config.grow_policy != "depthwise":
            log.fatal("multi-process feature-parallel training requires "
                      "grow_policy=depthwise (the fused chunk program); "
                      "leaf-wise feature parallel is single-process only")
        # any multi-process mode keeps replicated inputs host-side (numpy):
        # every process passes identical values into global-mesh programs
        self._host_inputs = self._mp or self._mp_fp
        if self._mp:
            from ..parallel import mesh as _pmesh
            # same mesh the learner's shard_map programs will use
            mesh = _pmesh.get_mesh(
                device_type=getattr(getattr(learner, "config", None),
                                    "device_type", "") or "")
            max_n, counts = _pmesh.global_row_layout(N)
            self._mp_max_n = max_n
            self._mp_local_n = N
            self._mp_mesh = mesh
            self._mp_true_n = int(np.sum(counts))
            # padded-global -> true-global compaction map: process p's true
            # rows live at [p*max_n, p*max_n + counts[p]) of the gathered
            # row axis; metric evaluation slices these out statically
            self._shard_layout = tuple(
                (p * max_n, int(counts[p])) for p in range(len(counts)))
            self._mp_make_global = functools.partial(
                _pmesh.make_global_rows, max_n=max_n, mesh=mesh)
            if objective is not None and not (
                    hasattr(objective, "globalize")
                    or hasattr(objective, "globalize_layout")):
                log.fatal("objective does not support multi-process "
                          "data-parallel training (no row-aligned state "
                          "globalization)")
            self.num_data = max_n * jax.process_count()
            self.bins_device = self._mp_make_global(
                self._bins_host(train_data), row_axis=1)
            # replicated small arrays stay host-side (every process passes
            # identical values into the jitted programs)
            self.num_bins_device = np.asarray(train_data.num_bins)
            valid = np.zeros(max_n, bool)
            valid[:N] = True
            self._row_valid = self._mp_make_global(valid)
            init_score = train_data.metadata.init_score
            score0 = (np.tile(np.asarray(init_score, np.float32),
                              (self.num_class, 1))
                      if init_score is not None
                      else np.zeros((self.num_class, N), np.float32))
            self.score = self._mp_make_global(score0, row_axis=1)
        else:
            self.num_data = N
            # multi-process feature parallel keeps inputs host-side: every
            # process passes identical (replicated) values into the
            # global-mesh chunk program
            _arr0 = np.asarray if self._mp_fp else jnp.asarray
            dev_bins = getattr(train_data, "device_bins", None)
            if dev_bins is not None and not self._host_inputs:
                # streamed dataset (io/streaming.py): the bin matrix is
                # already device-resident with explicit NamedSharding
                # placement — no host copy exists to upload.  Mixed-bin
                # packing reorders by one device-side gather.
                if self._pack_spec is not None:
                    self.bins_device = jnp.take(
                        dev_bins,
                        jnp.asarray(np.asarray(self._pack_spec.perm,
                                               np.int32)), axis=0)
                    # release the unpacked original: keeping both would
                    # DOUBLE peak HBM for the whole run at the 100M-row
                    # scale streaming exists for (the resident path's
                    # duplicate lives on host).  The dataset is consumed
                    # — a second init must re-stream (loud error below).
                    train_data.device_bins = None
                    train_data.device_bins_consumed = True
                else:
                    self.bins_device = dev_bins
            else:
                log.check(
                    not getattr(train_data, "device_bins_consumed", False),
                    "this streamed dataset's device bin matrix was "
                    "consumed by a previous mixed-bin GBDT.init — reload "
                    "the dataset to train another booster on it")
                self.bins_device = _arr0(self._bins_host(train_data))
            self.num_bins_device = _arr0(train_data.num_bins)
            self._row_valid = None
            init_score = train_data.metadata.init_score
            if init_score is not None:
                score0 = np.tile(np.asarray(init_score, np.float32),
                                 (self.num_class, 1))
            else:
                score0 = np.zeros((self.num_class, N), np.float32)
            self.score = _arr0(score0)

        if self.tree_config.hist_dtype == "int8":
            # num_data is the GLOBAL (padded) row count in every mode —
            # the int-domain psum sums all shards' int32 accumulators into
            # the same int32 range, so the capacity bound is global
            check_int8_row_capacity(self.num_data)

        # bagging state (gbdt.cpp:77-88)
        self._bag_rng = np.random.RandomState(boosting_config.bagging_seed)
        self._use_bagging = (boosting_config.bagging_fraction < 1.0
                             and boosting_config.bagging_freq > 0)
        if self._mp:
            # bagging draws over the LOCAL shard (the reference's
            # per-machine Bagging over its partition, gbdt.cpp:106-157);
            # padded phantom rows never enter histograms/root stats
            self._bag_mask = np.ones(N, dtype=bool)
            self._bag_mask_device = self._row_valid
        else:
            self._bag_mask = np.ones(N, dtype=bool)
            # device-side mask caches: uploads pay full link latency, so
            # only re-upload when the host-side mask actually changes
            self._bag_mask_device = jnp.asarray(self._bag_mask)
        # device-side bagging (ISSUE 8, ops/sampling.py): redraws become a
        # threefry key bump + on-device argsort — no host full-N RNG, no
        # mask upload.  The draw counter is the whole rewindable state.
        self._bag_device = self._resolve_bagging_device(boosting_config)
        self._bag_draw_idx = 0
        if self._bag_device:
            from ..ops import sampling as _sampling
            self._bag_base_key = _sampling.bag_key(
                boosting_config.bagging_seed)
            telemetry.count_route("bagging", "bagging/device")
        elif self._use_bagging:
            telemetry.count_route("bagging", "bagging/host")
        self._feat_mask_device = {}
        # per-class feature-fraction RNGs, same seed each
        # (serial_tree_learner.cpp:159-167; one learner per class)
        self._feat_rngs = [np.random.RandomState(self.tree_config.feature_fraction_seed)
                           for _ in range(self.num_class)]

        # GOSS (ISSUE 8): device-side gradient-based one-side sampling —
        # per-iteration top-|grad| rows plus an amplified random
        # remainder, fed through the row-mask seam (ops/sampling.py)
        self._goss_on = bool(getattr(boosting_config, "goss", False))
        if self._goss_on:
            if self._host_inputs and self.tree_config.grow_policy \
                    != "depthwise":
                # multi-process GOSS rides the fused chunk program only
                # (the selection is traced in-program over the gathered
                # global gradient scores); the per-iteration multi-
                # process path would run the device draw over committed
                # local arrays and is not supported
                log.fatal(
                    "goss=true in multi-process training requires the "
                    "fused chunk path: grow_policy=depthwise (and a "
                    "device formulation for every configured metric); "
                    "per-iteration multi-process GOSS is unsupported")
            from ..ops import sampling as _sampling
            self._goss_key = _sampling.bag_key(
                boosting_config.bagging_seed)
            # selection runs over the GLOBAL true rows in every mode
            # (the DP chunk gathers scores and selects on the compacted
            # global layout — identical to the serial draw)
            sel_n = self._mp_true_n if self._mp else N
            (self._goss_top_cnt, self._goss_other_cnt,
             self._goss_amp) = _sampling.goss_counts(
                sel_n, boosting_config.top_rate,
                boosting_config.other_rate)
            log.info("GOSS: keeping top %d rows by |grad| + %d amplified "
                     "(x%.3f) random rows per iteration"
                     % (self._goss_top_cnt, self._goss_other_cnt,
                        self._goss_amp))

        if objective is not None:
            if self._mp and hasattr(objective, "globalize_layout"):
                # global-score objectives (lambdarank) build their
                # per-query tables directly over the padded-global row
                # layout (a local init would be discarded immediately).
                # That layout is only valid when the row shards are
                # query-atomic (dataset.cpp:189-206) — queries from an
                # in-file group column are extracted AFTER sharding and
                # get cut per-record, which would silently mis-train
                if (train_data.metadata.query_boundaries is not None
                        and not getattr(train_data, "shard_query_atomic",
                                        True)):
                    log.fatal(
                        "distributed lambdarank requires query-atomic row "
                        "sharding: supply query ids via a .query side "
                        "file (an in-file group column is extracted after "
                        "sharding and splits queries across machines)")
                objective.globalize_layout(
                    self._mp_global_metadata(), self._shard_layout,
                    self.num_data)
            else:
                objective.init(train_data.metadata, N)
                if self._mp:
                    # lift row-aligned objective state to global sharded
                    # arrays
                    objective.globalize(self._mp_make_global)
        if self._mp and self.training_metrics:
            # training metrics see the GLOBAL rows: rebuild the global
            # metadata on every process (order matches the gathered global
            # score, so values are exactly the serial run's — stronger than
            # the reference's per-machine training metrics, gbdt.cpp:225-259)
            for metric in self.training_metrics:
                metric.init("training", self._mp_global_metadata(),
                            self._mp_true_n)
        else:
            for metric in self.training_metrics:
                metric.init("training", train_data.metadata, N)

        # training-health monitor (ISSUE 2): "auto" follows the telemetry
        # registry, so metrics_out= runs get health blocks with no extra
        # flag; health=true forces it on for library users without a sink
        from .. import health as _health
        if _health.resolve_enabled(getattr(boosting_config, "health",
                                           "auto")):
            self._health_monitor = _health.HealthMonitor(
                on_anomaly=getattr(boosting_config, "on_anomaly", "warn"),
                divergence_rounds=getattr(boosting_config,
                                          "health_divergence_rounds", 0),
                quantized=self.tree_config.hist_dtype == "int8")
        else:
            self._health_monitor = None

        # one-shot dataset-residency report (memory gauges), filed at
        # train start — after add_valid_dataset calls — by _file_residency
        self._residency_filed = False

    def _bins_host(self, train_data) -> np.ndarray:
        """Host-side bin matrix in the booster's storage layout: canonical
        feature order, or packed bin-width-class order under mixed-bin
        (one row gather, paid once at init)."""
        if self._pack_spec is None:
            return train_data.bins
        perm = np.asarray(self._pack_spec.perm, np.int64)
        return np.ascontiguousarray(train_data.bins[perm])

    def _file_residency(self) -> None:
        """File the one-shot dataset-residency report on the first
        training entry (any path), so BENCH/PROFILE rounds stop
        hand-measuring HBM footprints."""
        if self._residency_filed or not telemetry.memory_enabled():
            return
        self._residency_filed = True
        telemetry.set_residency(self._residency_report())

    def _residency_report(self) -> dict:
        """Static device-memory footprint of this booster's training state:
        the bin matrix, row-aligned score/metadata arrays, and the
        histogram scratch the configured grower will carry."""
        F, B = self.num_features, self.num_bins_max
        L = _effective_num_leaves(self.tree_config)
        md = self.train_data.metadata
        md_bytes = sum(int(np.asarray(a).nbytes) for a in
                       (md.label, md.weights, md.init_score,
                        md.query_boundaries) if a is not None)
        if self.tree_config.grow_policy == "depthwise":
            # widest level: P parent slots, each [F, B, 3] f32, live twice
            # across the subtraction (hists + hist_small)
            from .grower_depthwise import num_levels
            P = 1 << max(num_levels(L, self.tree_config.max_depth) - 1, 0)
            hist_scratch = 2 * P * F * B * 3 * 4
        else:
            # leaf-wise: the [L, F, B, 3] f32 histogram cache
            hist_scratch = L * F * B * 3 * 4
        return {
            "num_rows": int(self.num_data),
            "num_features": int(F),
            "num_bins_max": int(B),
            "bin_matrix_bytes": int(self.bins_device.nbytes),
            "score_bytes": int(self.score.nbytes),
            "metadata_bytes": int(md_bytes),
            "hist_scratch_bytes": int(hist_scratch),
            "valid_bins_bytes": int(sum(e["bins"].nbytes
                                        for e in self.valid_datasets)),
        }

    def health_summary(self):
        """Cumulative health totals (None when the monitor is off) —
        bench.py attaches this to its JSON line."""
        return (self._health_monitor.summary()
                if self._health_monitor is not None else None)

    def _mp_global_metadata(self):
        """Cached all-process Metadata view (labels/weights/query layout in
        process order — the compacted-global row coordinate system)."""
        md = getattr(self, "_mp_global_md", None)
        if md is None:
            from ..parallel.mesh import gather_ragged_rows
            md = self._mp_global_md = self.train_data.metadata.global_view(
                gather_ragged_rows)
        return md

    def add_valid_dataset(self, valid_data, valid_metrics, name=None) -> None:
        """GBDT::AddDataset (gbdt.cpp:92-105).

        Multi-process mode matches the reference's N-machine layout: every
        process loads the FULL validation file (application.cpp:166-177
        LoadValidationData takes no rank partition), so valid bins/scores
        ride replicated — host-side numpy here, every process passing
        identical values into the global-mesh programs."""
        idx = len(self.valid_datasets)
        name = name or f"valid_{idx + 1}"
        _arr = np.asarray if self._host_inputs else jnp.asarray
        entry = {
            "data": valid_data,
            "bins": _arr(valid_data.bins),
            "score": _arr(
                np.tile(valid_data.metadata.init_score, (self.num_class, 1))
                if valid_data.metadata.init_score is not None
                else np.zeros((self.num_class, valid_data.num_data), np.float32)),
            "name": name,
        }
        self.valid_datasets.append(entry)
        for metric in valid_metrics:
            metric.init(name, valid_data.metadata, valid_data.num_data)
        self.valid_metrics.append(list(valid_metrics))
        self.best_score.append([-1.0] * len(valid_metrics))
        self.best_iter.append([0] * len(valid_metrics))

    # ------------------------------------------------------------- iteration

    def _resolve_bagging_device(self, boosting_config) -> bool:
        """The ``bagging_device=`` resolution rule, single-homed: the env
        hatch (LGBM_TPU_HOST_BAGGING=1) beats the config; "auto" is on
        for accelerator backends only (the host path's numpy stream is
        the historical draw — CPU runs keep it so recorded models stay
        stable); explicit "true" forces the device draw anywhere it CAN
        apply.  It cannot apply (warns and falls back on "true"):
        multi-process shards (draws are per-local-shard host state) and
        per-query bagging (the atomic-query draw is a host loop)."""
        if not self._use_bagging:
            return False
        if hatches.flag("LGBM_TPU_HOST_BAGGING"):
            return False
        mode = getattr(boosting_config, "bagging_device", "auto")
        if mode == "false":
            return False
        capable = (not self._host_inputs
                   and self.train_data.metadata.query_boundaries is None
                   and self.train_data.metadata.queries is None)
        if mode == "true":
            if not capable:
                log.warning("bagging_device=true cannot apply here "
                            "(multi-process shard or per-query bagging); "
                            "keeping the host draw")
            return capable
        return capable and jax.default_backend() != "cpu"

    def _draw_bag_mask(self, it: int) -> None:
        """Host-side bagging draw (GBDT::Bagging, gbdt.cpp:106-157):
        per-record, or per-query when query boundaries exist.  Updates
        ``_bag_mask`` only; device upload is the per-iteration path's concern
        (the chunked path ships masks in one batched transfer).

        Called once per (iteration, class) pair like the reference
        (Bagging(iter_, curr_class) inside the per-class loop,
        gbdt.cpp:175-177): on a redraw iteration each class tree gets a
        fresh draw from the single shared RNG stream."""
        if not self._use_bagging or it % self.gbdt_config.bagging_freq != 0:
            return
        if tracing.active():
            # here (not _bagging) so the chunked path's batched draws
            # land on the flight-recorder timeline too — one event per
            # actual RNG advance, replay redraws included
            tracing.event("bagging_draw", iter=int(it))
        frac = self.gbdt_config.bagging_fraction
        if self._bag_device:
            # device draw (ISSUE 8, ops/sampling.py): the redraw is a key
            # bump — fold_in(base_key, draw_idx) — and an on-device exact-
            # count mask; no host RNG advances and nothing crosses the
            # link.  _bag_draw_idx is the WHOLE rewindable stream state
            # (the rollback machinery restores an integer instead of
            # MT19937 state).  Per-query bagging never reaches here
            # (_resolve_bagging_device keeps it on the host path).
            from ..ops import sampling as _sampling
            n = self.num_data
            bag_cnt = int(frac * n)
            self._bag_mask_device = _sampling.bag_mask_for_draw(
                self._bag_base_key, self._bag_draw_idx, n, bag_cnt)
            self._bag_draw_idx += 1
            log.info("re-bagging, using %d data to train" % bag_cnt)
            return
        qb = self.train_data.metadata.query_boundaries
        # multi-process: bag the LOCAL shard, like the reference's
        # per-machine Bagging over its own partition (gbdt.cpp:106-157)
        n = self._mp_local_n if self._mp else self.num_data
        mask = np.zeros(n, dtype=bool)
        if qb is None:
            bag_cnt = int(frac * n)
            idx = self._bag_rng.choice(n, bag_cnt, replace=False)
            mask[idx] = True
        else:
            nq = qb.size - 1
            bag_q = int(nq * frac)
            qidx = self._bag_rng.choice(nq, bag_q, replace=False)
            for q in qidx:
                mask[qb[q]:qb[q + 1]] = True
            bag_cnt = int(mask.sum())
        log.info("re-bagging, using %d data to train" % bag_cnt)
        self._bag_mask = mask
        self._bag_mask_device = None

    def _bagging(self, it: int) -> None:
        with telemetry.span("bagging"):
            self._draw_bag_mask(it)
            if self._bag_mask_device is None:
                if self._mp:
                    self._bag_mask_device = self._mp_make_global(
                        self._bag_mask)
                else:
                    self._bag_mask_device = jnp.asarray(self._bag_mask)

    def _goss_masks(self, grad, hess):
        """Per-iteration GOSS selection (ISSUE 8, ops/sampling.py): keep
        the top_rate fraction of rows by summed |gradient|, sample an
        other_rate fraction of the remainder, amplify the sampled
        remainder's gradients AND hessians by (1-top_rate)/other_rate.
        Runs entirely on device; the returned mask feeds the growers'
        row-mask seam (the same seam bagging uses), so a sampled
        iteration never materializes full-row host intermediates.  The
        draw is a pure function of (seed, iteration) — the pipelined
        rollback machinery needs NO snapshot for it.

        Returns ``(grad, hess, None)`` untouched when GOSS is off."""
        if not self._goss_on:
            return grad, hess, None
        if self._host_inputs:
            # defensive: init() fatals unless the chunk path will serve
            # multi-process GOSS; a direct per-iteration call must not
            # silently run the draw over committed local arrays
            log.fatal("per-iteration multi-process GOSS is unsupported; "
                      "use the fused chunk path (grow_policy=depthwise)")
        from ..ops import sampling as _sampling
        with telemetry.span("goss") as sp:
            g, h, mask = _sampling.goss_select(
                jax.random.fold_in(self._goss_key, self.iter),
                grad, hess, self._goss_top_cnt, self._goss_other_cnt,
                self._goss_amp)
            sp.fence(mask)
        telemetry.count("goss/iterations")
        if tracing.active():
            tracing.event("goss_draw", iter=int(self.iter))
        return g, h, mask

    def _feature_sample(self, cls: int) -> np.ndarray:
        frac = self.tree_config.feature_fraction
        F = self.num_features
        if frac >= 1.0:
            return np.ones(F, dtype=bool)
        used_cnt = max(int(F * frac), 1)
        mask = np.zeros(F, dtype=bool)
        mask[self._feat_rngs[cls].choice(F, used_cnt, replace=False)] = True
        return mask

    # ------------------------------------------------------ pipelined loop

    def _pipeline_on(self) -> bool:
        """The ``pipeline=`` resolution rule, single-homed: the env hatch
        (LGBM_TPU_PIPELINE) beats the config; "auto" is on only inside
        run_training (``_pipeline_auto``); multi-process runs stay
        synchronous (replicated host inputs make deferred consumption a
        cross-host ordering hazard for no measured win)."""
        env = hatches.choice("LGBM_TPU_PIPELINE", ("off", "readback"))
        mode = env or getattr(
            getattr(self, "gbdt_config", None), "pipeline", "off")
        if mode == "off":
            on = False
        elif mode == "readback":
            on = True
        else:
            on = self._pipeline_auto
        return on and not self._host_inputs and jax.process_count() == 1

    def _rng_snapshot(self):
        """Host RNG/mask state needed to rewind a dispatched-but-discarded
        iteration (pipelined rollback): bagging stream + mask caches and
        the per-class feature-fraction streams.  None-components skip the
        copy when the corresponding sampling is off."""
        bag = self._bag_snapshot()
        ff = ([r.get_state() for r in self._feat_rngs]
              if self.tree_config.feature_fraction < 1.0 else None)
        return (bag, ff)

    def _rng_restore(self, snap) -> None:
        if snap is None:
            return
        bag, ff = snap
        self._bag_restore(bag)
        if ff is not None:
            for r, s in zip(self._feat_rngs, ff):
                r.set_state(s)

    def _bag_snapshot(self):
        """The bagging stream's full rewindable state, mode-aware: the
        device stream is (draw counter, current device mask) — an integer
        plus an immutable array reference; the host stream is (MT19937
        state, host mask copy, device mask cache)."""
        if not self._use_bagging:
            return None
        if self._bag_device:
            return ("device", self._bag_draw_idx, self._bag_mask_device)
        return ("host", self._bag_rng.get_state(), self._bag_mask.copy(),
                self._bag_mask_device)

    def _bag_restore(self, snap) -> None:
        if snap is None:
            return
        if snap[0] == "device":
            _, self._bag_draw_idx, self._bag_mask_device = snap
        else:
            _, state, mask, mask_dev = snap
            self._bag_rng.set_state(state)
            self._bag_mask = mask
            self._bag_mask_device = mask_dev

    def flush_pipeline(self) -> bool:
        """Consume every deferred readback (pipelined boosting).  Called
        by run_training at loop end; direct train_one_iter/train_chunk
        callers that force pipeline=readback must call it before reading
        ``models``/scores.  Returns True when the consumed work says
        training stopped (degenerate tree or early stopping)."""
        stop = False
        if self._pipe is not None:
            entry, self._pipe = self._pipe, None
            stop = self._consume_iter_entry(entry, newer=None)
        if self._pipe_chunk is not None:
            rec, self._pipe_chunk = self._pipe_chunk, None
            stop = self._consume_chunk(rec, newer_inflight=False) or stop
        return stop

    # --------------------------------------- checkpoint / elastic (ISSUE 14)

    def _consumed_iteration(self) -> int:
        """The number of fully CONSUMED boosting iterations — the point a
        checkpoint describes.  Pipelined per-iteration mode advances
        ``self.iter`` at dispatch, so the in-flight entry's own iteration
        number is the consumed count; the chunk path advances at
        consumption, so ``self.iter`` is already right."""
        if self._pipe is not None:
            return int(self._pipe["iter_no"])
        return int(self.iter)

    def checkpoint_fingerprint(self) -> dict:
        """The semantic config fields a restored run must match exactly
        (compared field-by-field on load; a mismatch names the field).
        Topology fields (num_machines / tree_learner / feature_shards)
        are deliberately absent — an elastic restart changes them by
        design and the continuation budget is topology's, not the
        model's."""
        bc, tc = self.gbdt_config, self.tree_config
        return {
            "objective": (type(self.objective).__name__
                          if self.objective is not None else None),
            "num_class": int(self.num_class),
            "learning_rate": float(bc.learning_rate),
            "bagging_fraction": float(bc.bagging_fraction),
            "bagging_freq": int(bc.bagging_freq),
            "bagging_seed": int(bc.bagging_seed),
            # the RESOLVED stream, not the knob: "auto" resolving to a
            # different stream on restore would silently fork the draws
            "bagging_stream": ("device" if self._bag_device
                               else "host" if self._use_bagging else "off"),
            "feature_fraction": float(tc.feature_fraction),
            "feature_fraction_seed": int(tc.feature_fraction_seed),
            "goss": bool(getattr(bc, "goss", False)),
            "top_rate": float(getattr(bc, "top_rate", 0.0)),
            "other_rate": float(getattr(bc, "other_rate", 0.0)),
            "num_leaves": int(tc.num_leaves),
            "max_depth": int(tc.max_depth),
            "min_data_in_leaf": int(tc.min_data_in_leaf),
            "min_sum_hessian_in_leaf": float(tc.min_sum_hessian_in_leaf),
            "grow_policy": str(tc.grow_policy),
            "hist_dtype": str(tc.hist_dtype),
            "quant_rounding": str(tc.quant_rounding),
            "early_stopping_round": int(bc.early_stopping_round),
        }

    def _dataset_fingerprint(self) -> dict:
        """Topology-independent dataset identity: true global rows (not
        the padded per-topology layout), feature counts, valid-set
        count."""
        return {
            "num_features": int(self.num_features),
            "num_total_features": int(self.train_data.num_total_features),
            "num_rows": int(self._mp_true_n if self._mp
                            else self.train_data.num_data),
            "num_valid": len(self.valid_datasets),
        }

    def _topology_info(self) -> dict:
        lc = getattr(self._learner, "config", None)
        nm = (int(lc.network_config.num_machines)
              if lc is not None else 1)
        return {
            "tree_learner": (type(self._learner).__name__
                             if self._learner is not _serial_learner
                             else "serial"),
            "num_machines": nm,
            "process_count": int(jax.process_count()),
        }

    def checkpoint_state(self) -> dict:
        """Raw consistent snapshot of the CONSUMED training state, cheap
        enough for the hot loop (list copy + RNG get_state; tree
        serialization happens on the writer thread,
        checkpoint.serialize_state).  Pipelined mode snapshots the state
        as-of the consumed boundary: the in-flight entry's pre-dispatch
        RNG snapshot IS that state (scores are never stored — the
        restore replays the trees, which the rollback machinery already
        proved bitwise-equal to the in-grow updates)."""
        if self._pipe is not None:
            it = int(self._pipe["iter_no"])
            rng = self._pipe["pre_rng"]
            score_ref = self._pipe["score_before"]
            valid_ref = self._pipe["valid_before"]
        elif self._pipe_chunk is not None:
            rec = self._pipe_chunk
            it = int(self.iter)
            rng = (rec["bag_state"], rec["ff_states"])
            score_ref = rec["score_before"]
            valid_ref = tuple(rec["valid_before"])
        else:
            it = int(self.iter)
            rng = self._rng_snapshot()
            score_ref = self.score
            valid_ref = tuple(e["score"] for e in self.valid_datasets)
        if self._mp:
            # compact to TRUE global rows now — the gather is a
            # collective and must run on the main thread; single-process
            # scores stay device references the writer thread reads
            score_ref = self._host_global_score(score_ref)
        return {
            "iteration": it,
            "num_class": int(self.num_class),
            "models": tuple(self.models),
            "best_score": [list(r) for r in self.best_score],
            "best_iter": [list(r) for r in self.best_iter],
            "rng": rng,
            "score": score_ref,
            "valid_scores": list(valid_ref),
            "config": self.checkpoint_fingerprint(),
            "dataset": self._dataset_fingerprint(),
            "topology": self._topology_info(),
        }

    def restore_checkpoint(self, payload) -> None:
        """Continue training from a checkpoint payload (a loaded dict, or
        a path).  Must be called on a FRESHLY initialized booster (after
        ``init`` + ``add_valid_dataset``): the config/dataset
        fingerprints are compared field-by-field (loud reject naming the
        field), trees, RNG streams and the raw f32 scores are restored
        exactly — bit-identical continuation on the same topology; on a
        different one the stored TRUE-row scores re-lift onto the new
        layout and the continuation lands in the documented
        cross-schedule budget class."""
        from .. import checkpoint as ckpt_mod
        if isinstance(payload, str):
            payload = ckpt_mod.load_checkpoint(payload)
        log.check(self.train_data is not None,
                  "restore_checkpoint requires init() first")
        if self.models or self.iter:
            log.fatal("restore_checkpoint requires a freshly initialized "
                      "booster (input_model continuation and checkpoint "
                      "resume are mutually exclusive)")
        try:
            ckpt_mod.check_fingerprint(payload,
                                       self.checkpoint_fingerprint(),
                                       self._dataset_fingerprint())
        except ckpt_mod.CheckpointError as e:
            log.fatal(str(e))
        topo = payload.get("topology", {})
        here = self._topology_info()
        if topo.get("num_machines") not in (None, here["num_machines"]):
            log.info("elastic restart: checkpoint topology "
                     "num_machines=%s -> %s (mesh re-factored on the "
                     "surviving machine count)"
                     % (topo.get("num_machines"), here["num_machines"]))
        self.models = [ckpt_mod.tree_from_json(t)
                       for t in payload["trees"]]
        self.iter = int(payload["iteration"])
        self.best_score = [list(map(float, r))
                           for r in payload["best_score"]]
        self.best_iter = [list(map(int, r)) for r in payload["best_iter"]]
        rng = payload["rng"]
        self._restore_bag_json(rng["bagging"])
        ff = rng["feature_fraction"]
        if ff is not None:
            if len(ff) != len(self._feat_rngs):
                log.fatal("checkpoint rng field 'feature_fraction' has %d "
                          "streams, this run has %d classes"
                          % (len(ff), len(self._feat_rngs)))
            for r, s in zip(self._feat_rngs, ff):
                r.set_state(ckpt_mod._rng_state_from_json(s))
        # install the stored raw f32 scores (true rows), re-lifted onto
        # THIS topology's layout
        stored = ckpt_mod.array_from_json(payload["score"])
        n_true = self._mp_true_n if self._mp else self.train_data.num_data
        if tuple(stored.shape) != (self.num_class, n_true):
            log.fatal("checkpoint field 'score' has shape %s, this run "
                      "needs (%d, %d)" % (tuple(stored.shape),
                                          self.num_class, n_true))
        if self._mp:
            counts = [c for _, c in self._shard_layout]
            off = sum(counts[:jax.process_index()])
            local = stored[:, off:off + self._mp_local_n]
            self.score = self._mp_make_global(local, row_axis=1)
        elif self._host_inputs:
            self.score = np.asarray(stored)
        else:
            self.score = jnp.asarray(stored)
        vs = payload["valid_scores"]
        if len(vs) != len(self.valid_datasets):
            log.fatal("checkpoint field 'valid_scores' has %d sets, this "
                      "run configured %d validation dataset(s)"
                      % (len(vs), len(self.valid_datasets)))
        for entry, sj in zip(self.valid_datasets, vs):
            s = ckpt_mod.array_from_json(sj)
            entry["score"] = (np.asarray(s) if self._host_inputs
                              else jnp.asarray(s))
        # a restarted CLI run rewrites its incremental model file from
        # scratch (fresh header + every tree)
        if self._model_file is not None and not self._model_file.closed:
            self._model_file.close()
        self._saved_model_size = -1
        self._model_file = None
        self._last_ckpt_iter = self.iter
        telemetry.count("ckpt/restored")
        log.info("restored checkpoint at iteration %d (%d trees)"
                 % (self.iter, len(self.models)))

    def _restore_bag_json(self, obj) -> None:
        """Restore the bagging stream from its checkpoint form.  The
        resolved stream mode already matched via the config fingerprint
        (``bagging_stream``); device mode restores the draw counter and
        reconstructs the current mask (a pure function of it), host mode
        restores the MT19937 state + current mask."""
        if obj is None:
            return
        if obj["mode"] == "device":
            self._bag_draw_idx = int(obj["draw_idx"])
            if self._bag_draw_idx > 0:
                from ..ops import sampling as _sampling
                n = self.num_data
                bag_cnt = int(self.gbdt_config.bagging_fraction * n)
                self._bag_mask_device = _sampling.bag_mask_for_draw(
                    self._bag_base_key, self._bag_draw_idx - 1, n, bag_cnt)
            return
        from .. import checkpoint as ckpt_mod
        mask = ckpt_mod._mask_from_json(obj["mask"])
        n_local = self._mp_local_n if self._mp else self.train_data.num_data
        if mask.size != n_local:
            log.fatal("checkpoint rng field 'bagging' mask covers %d rows "
                      "but this process's shard has %d — host-path "
                      "bagging state is per-shard, so an elastic restart "
                      "across a different process layout must use "
                      "bagging_device=true (or bagging off)"
                      % (mask.size, n_local))
        self._bag_rng.set_state(ckpt_mod._rng_state_from_json(obj["state"]))
        self._bag_mask = mask
        self._bag_mask_device = None

    def enable_elastic(self, learner_factory, monitor=None,
                       exchange=None):
        """Arm the live straggler mesh-shrink policy (ISSUE 14):
        ``learner_factory(num_machines)`` builds the learner for a shrunk
        mesh (the CLI passes ``create_parallel_learner`` over a mutated
        config — ``factor_machines`` then re-runs on the surviving
        count).  ``monitor`` defaults to a fresh
        ``elastic.StragglerMonitor(straggler_k)``; feed it observations
        from merged timeline rows or let the per-iteration cross-host
        time exchange drive it (``exchange``: None = auto, on for true
        multi-process runs; True/False force).  Returns the monitor so
        harnesses can inject observations."""
        from .. import elastic as elastic_mod
        self._learner_factory = learner_factory
        if monitor is None:
            monitor = elastic_mod.StragglerMonitor(
                k=int(getattr(self.gbdt_config, "straggler_k", 3)
                      if hasattr(self, "gbdt_config") else 3))
        self._straggler_monitor = monitor
        if exchange is None:
            exchange = jax.process_count() > 1
        self._elastic_exchange_on = bool(exchange)
        return monitor

    def _elastic_step(self) -> bool:
        """One iteration-boundary pass of the live straggler policy:
        exchange per-host iteration times (when armed), consult the
        monitor, and execute the drain-at-boundary mesh shrink when a
        persistent straggler is flagged.  Returns True when draining the
        pipeline surfaced a stop (training must end)."""
        mon = self._straggler_monitor
        if mon is None:
            return False
        now = time.perf_counter()
        if self._elastic_exchange_on and hasattr(self._learner, "_mesh"):
            if self._boundary_t is not None:
                from .. import elastic as elastic_mod
                gathered = elastic_mod.exchange_times(
                    self._learner._mesh(), now - self._boundary_t,
                    iteration=self._consumed_iteration())
                mon.observe(self._consumed_iteration(),
                            elastic_mod.host_times_from_gather(
                                gathered,
                                slots_per_host=jax.local_device_count()))
        self._boundary_t = now
        flagged = mon.take_flagged()
        if flagged is None:
            return False
        return self._elastic_shrink(flagged)

    def _elastic_shrink(self, flagged: str) -> bool:
        """Drain-at-iteration-boundary mesh shrink: checkpoint, drop the
        flagged slot, re-factor the mesh on the surviving machine count,
        restore, resume.  Returns True when the drain surfaced a stop
        (no shrink then — training is over anyway)."""
        from .. import checkpoint as ckpt_mod
        from .. import elastic as elastic_mod
        if self._learner_factory is None or not callable(
                self._learner_factory):
            log.warning("persistent straggler %s flagged but no learner "
                        "factory is registered (enable_elastic); cannot "
                        "shrink the mesh" % flagged)
            self._straggler_monitor = None
            return False
        lc = getattr(self._learner, "config", None)
        cur = (int(lc.network_config.num_machines)
               if lc is not None else 1)
        if cur <= 1:
            log.warning("persistent straggler %s flagged but the mesh is "
                        "already minimal (num_machines=1); cannot shrink"
                        % flagged)
            self._straggler_monitor = None
            return False
        # drain: consume every in-flight pipelined readback so the
        # checkpoint describes a clean iteration boundary
        if self.flush_pipeline():
            return True
        state = self.checkpoint_state()
        if self._ckpt_writer is not None:
            self._ckpt_writer.write_sync(state)
        if jax.process_count() > 1:
            # a live process cannot be evicted from jax.distributed
            # in-process: the shrink IS the checkpoint+restart protocol —
            # drain, persist, and tell the supervisor to restart the
            # survivors (task=train with the same checkpoint_dir re-runs
            # factor_machines on the surviving count).  Without a
            # configured checkpoint writer there is nothing durable to
            # restart FROM — exiting would lose the whole run, so keep
            # training at the degraded pace and say why.
            if self._ckpt_writer is None:
                log.warning(
                    "persistent straggler %s flagged, but no checkpoint "
                    "is configured (checkpoint_interval=0) — a "
                    "multi-process shrink restarts survivors from a "
                    "checkpoint, so none can happen; continuing at the "
                    "straggler's pace.  Arm checkpoint_interval/"
                    "checkpoint_dir to make shrinks recoverable."
                    % flagged)
                self._straggler_monitor = None
                return False
            log.fatal("persistent straggler %s: checkpoint written; "
                      "multi-process mesh shrink requires restarting the "
                      "surviving processes from the checkpoint "
                      "(task=train, same checkpoint_dir)" % flagged)
        # survivor agreement on the OLD mesh before tearing it down: each
        # host votes keep(1)/drop(0) per slot; pmin commits everyone to
        # the most conservative plan (single-process: trivially agreed,
        # but the same seam multi-host supervisors consume)
        try:
            drop_slot = int(str(flagged).lstrip("p").split("@")[0])
        except ValueError:
            drop_slot = cur - 1
        drop_slot = min(max(drop_slot, 0), cur - 1)
        votes = np.ones(cur, np.int32)
        votes[drop_slot] = 0
        if hasattr(self._learner, "_mesh"):
            agreed = elastic_mod.agree_survivors(self._learner._mesh(),
                                                 votes,
                                                 iteration=state["iteration"])
            new_m = int(np.asarray(agreed).sum())
        else:
            new_m = cur - 1
        new_m = max(min(new_m, cur - 1), 1)
        log.warning("elastic mesh shrink: persistent straggler %s — "
                    "draining at iteration %d, re-factoring %d -> %d "
                    "machines" % (flagged, state["iteration"], cur, new_m))
        payload = ckpt_mod.serialize_state(state)
        new_learner = self._learner_factory(new_m)
        valids = [(e["data"], self.valid_metrics[i], e["name"])
                  for i, e in enumerate(self.valid_datasets)]
        # init() rebuilds device state but not the progress bookkeeping
        # __init__ owns — reset it so the restore sees a fresh booster
        # (valid sets re-add below; best_score/best_iter re-append there
        # and are then overwritten by the restore)
        self.models = []
        self.iter = 0
        self.valid_datasets = []
        self.valid_metrics = []
        self.best_score = []
        self.best_iter = []
        self.init(self.gbdt_config, self.train_data, self.objective,
                  self.training_metrics, learner=new_learner)
        for vd, ms, name in valids:
            self.add_valid_dataset(vd, ms, name=name)
        self.restore_checkpoint(payload)
        if self._straggler_monitor is not None:
            self._straggler_monitor.reset()
        telemetry.count("elastic/shrinks")
        if tracing.active():
            tracing.event("elastic_shrink", iter=int(self.iter))
        return False

    def train_one_iter(self, is_eval: bool = True) -> bool:
        """GBDT::TrainOneIter (gbdt.cpp:167-214).  Returns True when
        training must stop (early stopping or no splittable leaf).

        Pipelined mode (pipeline=readback): this call DISPATCHES iteration
        i and consumes iteration i-1's deferred model readback — the
        device work is dispatched in exactly the synchronous order, only
        the host wait moves one iteration later, so trees/scores/metrics
        are exact-identical (stops are discovered one call late and the
        surplus dispatched iteration is rolled back from snapshots)."""
        if self._pipeline_on():
            self._file_residency()
            if self._pipe_chunk is not None:
                # mixing chunked and per-iteration paths mid-pipeline:
                # drain the chunk first (ordering)
                if self.flush_pipeline():
                    return True
            entry = self._dispatch_one_iter(is_eval)
            prev, self._pipe = self._pipe, entry
            if prev is not None and self._consume_iter_entry(prev,
                                                             newer=entry):
                self._pipe = None
                return True
            return False
        if self._pipe is not None or self._pipe_chunk is not None:
            # pipeline turned off with work in flight: drain first
            if self.flush_pipeline():
                return True
        self._file_residency()
        mon = self._health_monitor
        with telemetry.span("gradient") as sp:
            grad, hess = self.objective.get_gradients(
                self.score if self.num_class > 1 else self.score[0])
            sp.fence((grad, hess))
        if self.num_class == 1:
            grad = grad[None]
            hess = hess[None]
        # GOSS selection runs ONCE per iteration over all classes'
        # gradients (the amplified grad/hess feed the growers; health and
        # the next iteration's gradients see the raw arrays)
        g_grow, h_grow, goss_mask = self._goss_masks(grad, hess)

        for cls in range(self.num_class):
            self._bagging(self.iter)
            feature_mask = self._feature_sample(cls)
            row_mask = (goss_mask if goss_mask is not None
                        else self._bag_mask_device)
            key = feature_mask.tobytes()
            if key not in self._feat_mask_device:
                # one live entry suffices: the per-class feature RNGs share
                # one seed and advance in lockstep
                # (serial_tree_learner.cpp:159-167 parity), so every class
                # draws the SAME mask within an iteration — one upload per
                # redraw, hits for classes 1..C-1
                self._feat_mask_device.clear()
                self._feat_mask_device[key] = (
                    np.asarray(feature_mask) if self._mp
                    else jnp.asarray(feature_mask))

            with telemetry.span("grow") as sp:
                tree_arrays = self._learner(
                    self, self.bins_device, g_grow[cls], h_grow[cls],
                    row_mask, self._feat_mask_device[key])
                sp.fence(tree_arrays)

            # ONE host round-trip for everything the host needs (each
            # device_get pays full tunnel latency; fetching the 8 small
            # arrays separately costs ~0.5s/tree on a tunneled TPU).  Start
            # the copy asynchronously, dispatch the device-side score update
            # first, and only then block — the link latency overlaps with
            # device compute.
            small = tree_arrays._replace(leaf_ids=None)
            try:
                for arr in jax.tree.leaves(small):
                    arr.copy_to_host_async()
            except Exception:
                pass

            # train score via leaf partition (fast path, gbdt.cpp:216-218 +
            # OOB, 159-165 — unified because leaf_ids cover all rows); the
            # shrinkage (gbdt.cpp:188) is applied on device, so this needs
            # nothing from the host
            lr = jnp.float32(self.gbdt_config.learning_rate)
            # zero the contribution of a degenerate (unsplit) tree on device:
            # the reference rejects such trees before any score update
            # (gbdt.cpp:182-185), and this keeps that invariant without
            # waiting for num_leaves on the host
            with telemetry.span("score_update") as sp:
                shrunk = jnp.where(tree_arrays.num_leaves > 1,
                                   tree_arrays.leaf_value * lr, 0.0)
                self.score = self.score.at[cls].add(
                    _leaf_lookup(shrunk, tree_arrays.leaf_ids))
                sp.fence(self.score)
            # valid scores via tree replay (gbdt.cpp:220-222); the grower's
            # arrays are already statically padded to num_leaves-1, so the
            # replay jit compiles once and uses no host data
            if self.valid_datasets:
                max_nodes = len(tree_arrays.split_feature)
                with telemetry.span("valid_update") as sp:
                    for entry in self.valid_datasets:
                        new_cls = add_tree_score(
                            entry["bins"], entry["score"][cls],
                            tree_arrays.split_feature,
                            tree_arrays.threshold_bin,
                            tree_arrays.left_child,
                            tree_arrays.right_child,
                            shrunk,
                            tree_arrays.num_leaves,
                            max_nodes=max_nodes)
                        if self._mp:
                            # valid state stays host-side numpy in
                            # multi-process mode (replicated inputs to the
                            # global programs)
                            entry["score"][cls] = np.asarray(new_cls)
                        else:
                            entry["score"] = entry["score"].at[cls].set(
                                new_cls)
                        sp.fence(new_cls)

            # now block on the (already in-flight) host copy for the model
            with telemetry.span("model_readback"):
                host = jax.device_get(small)
            num_leaves = int(host.num_leaves)
            if mon is not None:
                # tree-derived health counts ride the readback for free
                mon.add_tree(num_leaves, host.split_gain, host.leaf_count)
            if num_leaves <= 1:
                log.info("Can't training anymore, there isn't any leaf meets "
                         "split requirements.")
                if mon is not None:
                    # the iteration produced no tree, but its gradients may
                    # be the REASON (NaN/Inf gains reject every split):
                    # record the health block and apply the policy before
                    # stopping, so the stop is explained, not silent
                    hvec = mon.grad_health_async(grad, hess, self.score)
                    block = mon.assemble(hvec)
                    if telemetry.sink_active():
                        dp, dt = telemetry.take_phase_deltas()
                        telemetry.emit_iteration(
                            self.iter + 1, dp, dt,
                            eval_metrics=self._last_eval_values,
                            health=block,
                            memory=telemetry.take_memory_record(),
                            extra={"stopped": "degenerate_tree"})
                    mon.apply_policy(block, self.iter + 1)
                return True

            tree = self._to_host_tree(host)
            tree.shrinkage(self.gbdt_config.learning_rate)
            self.models.append(tree)

        # dispatch the health program over this iteration's arrays (async:
        # the host copy overlaps the eval phase; fetched at assemble)
        hvec = (mon.grad_health_async(grad, hess, self.score)
                if mon is not None else None)
        met_early_stopping = False
        if is_eval:
            with telemetry.span("eval"):
                met_early_stopping = self.output_metric(self.iter + 1)
        self.iter += 1
        health_block = mon.assemble(hvec) if mon is not None else None
        if telemetry.sink_active():
            dp, dt = telemetry.take_phase_deltas()
            telemetry.emit_iteration(self.iter, dp, dt,
                                     eval_metrics=self._last_eval_values,
                                     health=health_block,
                                     memory=telemetry.take_memory_record())
        if mon is not None:
            # AFTER the record is written: a halt must not lose the
            # record that explains it
            mon.apply_policy(health_block, self.iter)
        if met_early_stopping:
            log.info("Early stopping at iteration %d, the best iteration "
                     "round is %d"
                     % (self.iter, self.iter - self.early_stopping_round))
            # pop back the last early_stopping_round models (gbdt.cpp:205-210)
            del self.models[len(self.models)
                            - self.early_stopping_round * self.num_class:]
        return met_early_stopping

    def _dispatch_one_iter(self, is_eval: bool) -> dict:
        """Dispatch one boosting iteration's device work (gradients, per-
        class grow + async model copy + score/valid updates) WITHOUT the
        model readback — exactly train_one_iter's dispatch sequence.  The
        returned entry carries everything the deferred consumption needs:
        the in-flight small-array handles, post-update score/valid
        references per class (functional updates make these free), and
        host RNG snapshots for exact rollback when a stop is discovered
        late."""
        mon = self._health_monitor
        pre_rng = self._rng_snapshot()
        with telemetry.span("gradient") as sp:
            grad, hess = self.objective.get_gradients(
                self.score if self.num_class > 1 else self.score[0])
            sp.fence((grad, hess))
        if self.num_class == 1:
            grad = grad[None]
            hess = hess[None]
        entry = {"iter_no": self.iter, "is_eval": is_eval, "cls": [],
                 "grad": grad, "hess": hess, "pre_rng": pre_rng,
                 "mon": mon,
                 # pre-dispatch score references (functional updates make
                 # these free): the CONSUMED-boundary state a checkpoint
                 # taken while this entry is in flight must describe
                 "score_before": self.score,
                 "valid_before": tuple(e["score"]
                                       for e in self.valid_datasets)}
        g_grow, h_grow, goss_mask = self._goss_masks(grad, hess)
        lr = jnp.float32(self.gbdt_config.learning_rate)
        for cls in range(self.num_class):
            cls_pre = self._rng_snapshot()
            self._bagging(self.iter)
            feature_mask = self._feature_sample(cls)
            row_mask = (goss_mask if goss_mask is not None
                        else self._bag_mask_device)
            key = feature_mask.tobytes()
            if key not in self._feat_mask_device:
                self._feat_mask_device.clear()
                self._feat_mask_device[key] = jnp.asarray(feature_mask)
            with telemetry.span("grow") as sp:
                tree_arrays = self._learner(
                    self, self.bins_device, g_grow[cls], h_grow[cls],
                    row_mask, self._feat_mask_device[key])
                sp.fence(tree_arrays)
            small = tree_arrays._replace(leaf_ids=None)
            try:
                for arr in jax.tree.leaves(small):
                    arr.copy_to_host_async()
            except Exception:
                pass
            with telemetry.span("score_update") as sp:
                shrunk = jnp.where(tree_arrays.num_leaves > 1,
                                   tree_arrays.leaf_value * lr, 0.0)
                self.score = self.score.at[cls].add(
                    _leaf_lookup(shrunk, tree_arrays.leaf_ids))
                sp.fence(self.score)
            if self.valid_datasets:
                max_nodes = len(tree_arrays.split_feature)
                with telemetry.span("valid_update") as sp:
                    for v_entry in self.valid_datasets:
                        new_cls = add_tree_score(
                            v_entry["bins"], v_entry["score"][cls],
                            tree_arrays.split_feature,
                            tree_arrays.threshold_bin,
                            tree_arrays.left_child,
                            tree_arrays.right_child,
                            shrunk,
                            tree_arrays.num_leaves,
                            max_nodes=max_nodes)
                        v_entry["score"] = v_entry["score"].at[cls].set(
                            new_cls)
                        sp.fence(new_cls)
            entry["cls"].append({
                "small": small,
                "pre_rng": cls_pre,
                "score_after": self.score,
                "valid_after": tuple(e["score"]
                                     for e in self.valid_datasets),
            })
        # dispatch-time increment: the next dispatched iteration's bagging
        # draws key off self.iter; stops discovered at consumption reset it
        self.iter += 1
        return entry

    def _pipe_restore(self, rec, rng_target) -> None:
        """Rewind booster state to exactly ``rec``'s post-update point
        (score/valid refs) and the given RNG snapshot (None = already
        correct)."""
        self.score = rec["score_after"]
        for e, s in zip(self.valid_datasets, rec["valid_after"]):
            e["score"] = s
        self._rng_restore(rng_target)

    def _consume_iter_entry(self, entry, newer) -> bool:
        """Deferred consumption of one dispatched iteration: model
        readback, host tree construction, health/eval/early-stop
        bookkeeping — the synchronous path's tail, verbatim in order.
        ``newer`` is the already-dispatched next iteration (rolled back
        when this one stops) or None on flush."""
        mon = entry["mon"]
        C = self.num_class
        it = entry["iter_no"]
        for cls, rec in enumerate(entry["cls"]):
            with telemetry.span("model_readback"):
                host = jax.device_get(rec["small"])
            num_leaves = int(host.num_leaves)
            if mon is not None:
                mon.add_tree(num_leaves, host.split_gain, host.leaf_count)
            if num_leaves <= 1:
                log.info("Can't training anymore, there isn't any leaf "
                         "meets split requirements.")
                # synchronous semantics: state ends after THIS class's
                # (zero) score update, with later classes' and any newer
                # iteration's dispatched work undone
                if cls + 1 < C:
                    rng_target = entry["cls"][cls + 1]["pre_rng"]
                elif newer is not None:
                    rng_target = newer["pre_rng"]
                else:
                    rng_target = None
                self._pipe_restore(rec, rng_target)
                self.iter = it
                if mon is not None:
                    hvec = mon.grad_health_async(entry["grad"],
                                                 entry["hess"], self.score)
                    block = mon.assemble(hvec)
                    if telemetry.sink_active():
                        dp, dt = telemetry.take_phase_deltas()
                        telemetry.emit_iteration(
                            it + 1, dp, dt,
                            eval_metrics=self._last_eval_values,
                            health=block,
                            memory=telemetry.take_memory_record(),
                            extra={"stopped": "degenerate_tree"})
                    mon.apply_policy(block, it + 1)
                return True
            tree = self._to_host_tree(host)
            tree.shrinkage(self.gbdt_config.learning_rate)
            self.models.append(tree)

        last = entry["cls"][-1]
        hvec = (mon.grad_health_async(entry["grad"], entry["hess"],
                                      last["score_after"])
                if mon is not None else None)
        met_early_stopping = False
        if entry["is_eval"]:
            with telemetry.span("eval"):
                met_early_stopping = self._output_metric_at(it + 1, last)
        health_block = mon.assemble(hvec) if mon is not None else None
        if telemetry.sink_active():
            dp, dt = telemetry.take_phase_deltas()
            telemetry.emit_iteration(it + 1, dp, dt,
                                     eval_metrics=self._last_eval_values,
                                     health=health_block,
                                     memory=telemetry.take_memory_record())
        if mon is not None:
            from ..health import TrainingHealthError
            try:
                mon.apply_policy(health_block, it + 1)
            except TrainingHealthError:
                # halt must leave the booster at exactly iteration it+1:
                # undo the newer dispatched iteration before re-raising
                if newer is not None:
                    self._pipe_restore(last, newer["pre_rng"])
                self.iter = it + 1
                self._pipe = None
                raise
        if met_early_stopping:
            log.info("Early stopping at iteration %d, the best iteration "
                     "round is %d"
                     % (it + 1, it + 1 - self.early_stopping_round))
            del self.models[len(self.models)
                            - self.early_stopping_round * self.num_class:]
            if newer is not None:
                self._pipe_restore(last, newer["pre_rng"])
            self.iter = it + 1
            return True
        return False

    def _output_metric_at(self, iteration: int, rec) -> bool:
        """output_metric over a pipelined entry's own score snapshot: the
        live ``self.score`` may already carry the NEXT iteration's update,
        so swap the entry's references in for the evaluation and restore
        the newest state after (stop paths re-restore from snapshots
        anyway)."""
        cur_score = self.score
        cur_valid = [e["score"] for e in self.valid_datasets]
        self.score = rec["score_after"]
        for e, s in zip(self.valid_datasets, rec["valid_after"]):
            e["score"] = s
        try:
            return self.output_metric(iteration)
        finally:
            self.score = cur_score
            for e, s in zip(self.valid_datasets, cur_valid):
                e["score"] = s

    def run_training(self, num_iterations: int, is_eval: bool,
                     save_fn: Optional[Callable] = None,
                     chunk_size: int = 8,
                     progress_fn: Optional[Callable] = None) -> None:
        """Drive the full training loop (Application::Train,
        application.cpp:239-257), fusing iterations into device chunks when
        no per-iteration metric output is needed.  Any exception escaping
        the loop (TrainingHealthError halts included) crash-flushes a
        final telemetry summary record before re-raising, so an aborted
        run keeps its tail records."""
        if self._mp_fp and not self.chunkable_for(is_eval):
            # the per-iteration fallback would push committed local arrays
            # into the global-mesh program and fail obscurely mid-train
            log.fatal("multi-process feature-parallel training requires "
                      "the fused chunk path: grow_policy=depthwise and a "
                      "device formulation for every configured metric")
        if self._mp and self._goss_on and not self.chunkable_for(is_eval):
            # multi-process GOSS exists only inside the chunk program
            # (the selection gathers the global gradient scores there)
            log.fatal("goss=true in multi-process training requires the "
                      "fused chunk path: grow_policy=depthwise and a "
                      "device formulation for every configured metric")
        # hung-collective flight recorder (ISSUE 5): with stall_timeout=
        # configured, a watchdog thread records span/collective events in
        # a ring buffer and — if no event lands for the timeout — dumps
        # the ring + in-flight phase/iteration/collective + thread stacks
        # to the sink BEFORE the environment's opaque ~60 s dispatch
        # watchdog kills the job.  Armed here, next to the crash-flush,
        # so both abnormal-end paths leave a record.
        wd_armed = telemetry.arm_watchdog()
        if wd_armed:
            telemetry.watchdog_checkin(phase="run_training",
                                       iteration=self.iter)
        # pipelined boosting (ISSUE 6): pipeline="auto" resolves ON inside
        # this driver — run_training owns the loop AND the flush, so the
        # deferred readbacks can never leak to a caller.  Explicit
        # "readback"/"off" (or LGBM_TPU_PIPELINE) win either way.
        # With a save_fn, auto stays OFF: the in-loop checkpoint must see
        # every finished tree (a deferred readback would persist each
        # snapshot one iteration/chunk stale — callers who accept that
        # lag opt in with pipeline=readback explicitly).
        self._pipeline_auto = save_fn is None
        # asynchronous periodic checkpoints (ISSUE 14): snapshots ride a
        # background writer thread, OFF the pipelined readback path — the
        # hot loop only pays the cheap raw snapshot (checkpoint_state);
        # pipelining stays on, so a checkpoint describes the CONSUMED
        # boundary (at most one iteration/chunk behind the dispatch)
        ckpt_interval = int(getattr(self.gbdt_config,
                                    "checkpoint_interval", 0) or 0)
        ckpt_writer = None
        if ckpt_interval > 0:
            from .. import checkpoint as ckpt_mod
            ckpt_dir = getattr(self.gbdt_config, "checkpoint_dir", "")
            log.check(bool(ckpt_dir),
                      "checkpoint_interval > 0 requires checkpoint_dir")
            ckpt_writer = ckpt_mod.CheckpointWriter(
                ckpt_dir,
                keep=int(getattr(self.gbdt_config, "checkpoint_keep", 2)))
            self._ckpt_writer = ckpt_writer
            self._last_ckpt_iter = self._consumed_iteration()
        self._boundary_t = time.perf_counter()

        def _boundary() -> bool:
            """Iteration-boundary housekeeping: enqueue the async
            checkpoint, run the live straggler policy, and fire the
            fault-injection hatch (faults.maybe_fire — the harness's
            between-iterations kill/stall point).  Returns True when the
            elastic drain surfaced a stop."""
            if ckpt_writer is not None:
                done = self._consumed_iteration()
                if done - self._last_ckpt_iter >= ckpt_interval:
                    ckpt_writer.submit(self.checkpoint_state())
                    self._last_ckpt_iter = done
            stop = False
            if self._straggler_monitor is not None:
                stop = self._elastic_step()
            faults_mod.maybe_fire(self._consumed_iteration())
            return stop
        try:
            if not self.chunkable_for(is_eval) or (num_iterations < chunk_size
                                                   and not self._mp_fp):
                # short runs use the per-iteration path: its grower program
                # is module-jitted (shared across boosters), while a chunk
                # shorter than chunk_size would waste the surplus iterations
                # it computes
                for _ in range(num_iterations):
                    finished = self.train_one_iter(is_eval=is_eval)
                    if wd_armed:
                        telemetry.watchdog_checkin(iteration=self.iter)
                    if save_fn is not None:
                        save_fn()
                    if progress_fn is not None:
                        progress_fn(self.iter)
                    if finished:
                        break
                    if _boundary():
                        break
            else:
                done = 0
                while done < num_iterations:
                    # always run the full-size chunk program (a shorter tail
                    # chunk would re-trace the scan and pay a second multi-
                    # minute compile); surplus iterations are rolled back
                    stop = self.train_chunk(chunk_size,
                                            limit=num_iterations - done,
                                            is_eval=is_eval)
                    if wd_armed:
                        telemetry.watchdog_checkin(iteration=self.iter)
                    if save_fn is not None:
                        save_fn()
                    if progress_fn is not None:
                        progress_fn(self.iter)
                    if stop:
                        break
                    if _boundary():
                        break
                    done += chunk_size
            # drain the deferred readbacks (pipelined mode; no-op
            # otherwise) so callers see fully-consistent models/scores
            if self._pipe is not None or self._pipe_chunk is not None:
                self.flush_pipeline()
                if wd_armed:
                    telemetry.watchdog_checkin(iteration=self.iter)
                if save_fn is not None:
                    save_fn()
                if progress_fn is not None:
                    progress_fn(self.iter)
            if ckpt_writer is not None:
                # final checkpoint, synchronous: a restart after a clean
                # finish sees the complete run
                ckpt_writer.write_sync(self.checkpoint_state())
        except BaseException as e:
            # crash-flush (ISSUE 4): an exception escaping training —
            # TrainingHealthError halts included — must not lose the
            # run's tail records.  Write the final summary (marked with
            # the exception type) and flush the sink before re-raising.
            # No collectives here: a crashed process cannot be assumed
            # able to join the cross-host aggregation, and the peer
            # processes are raising the same (host-replicated) error
            # rather than waiting in an allgather.
            #
            # Pipelined mode: a dispatched-but-unconsumed iteration/chunk
            # may hold a COMPLETED readback whose trees and telemetry
            # record the synchronous path would already have banked —
            # consume it best-effort (the crash may be unrelated to the
            # device) so the crash loses no finished work; if consumption
            # itself fails, drop the queue and keep the original error.
            try:
                if self._pipe is not None or self._pipe_chunk is not None:
                    self.flush_pipeline()
            except BaseException:
                pass
            finally:
                self._pipe = None
                self._pipe_chunk = None
            if ckpt_writer is not None:
                # best-effort final checkpoint: a clean exception
                # (TrainingHealthError halt, injected raise) leaves the
                # consumed state consistent and restartable; if the state
                # is torn, the write fails quietly and the last periodic
                # checkpoint stands
                try:
                    ckpt_writer.write_sync(self.checkpoint_state())
                except Exception:
                    pass
            if telemetry.sink_active():
                try:
                    extra = {"aborted": type(e).__name__,
                             "iterations": self.iter}
                    if self._health_monitor is not None:
                        extra["health"] = self._health_monitor.summary()
                    telemetry.emit_summary(extra=extra)
                except Exception:
                    pass
            # flight-recorder crash dump (ISSUE 16): the ring's last-N
            # events land beside the checkpoint — best-effort, after the
            # summary, never masking the real fault
            tracing.dump_on_fault(type(e).__name__)
            raise
        finally:
            self._pipeline_auto = False
            if ckpt_writer is not None:
                ckpt_writer.close()
                self._ckpt_stats = {"written": ckpt_writer.written,
                                    "dropped": ckpt_writer.dropped}
                self._ckpt_writer = None
            if wd_armed:
                telemetry.disarm_watchdog()
        if self._host_inputs:
            # fold every host's route counters into the leader before the
            # summary.  COLLECTIVE, hence outside any telemetry.enabled()
            # gate: a host whose config lacks metrics_out must still join
            # the allgather or the enabled hosts would hang in it (every
            # process reaches this point — run_training's control flow is
            # host-replicated)
            from ..parallel.learners import aggregate_telemetry
            aggregate_telemetry()
        if telemetry.sink_active():
            extra = {"iterations": self.iter}
            if self._health_monitor is not None:
                extra["health"] = self._health_monitor.summary()
            telemetry.emit_summary(extra=extra)

    # ------------------------------------------------------- chunked training

    @property
    def supports_chunking(self) -> bool:
        """True when fused multi-iteration training applies: serial learner
        (the parallel learners own their shard_map programs), a
        chunk-traceable objective, and device formulations for every
        configured metric (metrics/device.py) — metric values and valid
        scores are then computed INSIDE the chunk program and early
        stopping is applied post-hoc with identical semantics."""
        if (self._learner is not _serial_learner
                or not hasattr(self.objective, "chunk_spec")):
            return False
        return self._metrics_device_capable()

    def _metrics_device_capable(self) -> bool:
        """Every configured metric has a device (pure-JAX) formulation
        (metrics/device.py), so evaluation can run inside chunk programs."""
        from ..metrics import Metric as _MetricBase
        for ms in [self.training_metrics] + self.valid_metrics:
            for m in ms:
                if type(m).device_spec is _MetricBase.device_spec:
                    return False
        return True

    def _needs_eval(self, is_eval: bool) -> bool:
        return bool(is_eval
                    and (self.training_metrics or self.valid_datasets)
                    and (self.gbdt_config.output_freq > 0
                         or self.early_stopping_round > 0))

    def chunk_supported(self, is_eval: bool) -> bool:
        """Whether train_chunk can run at all: serial learner with full
        eval support (supports_chunking), or the data-parallel learner
        with row-shardable objective state — including in-program metric
        evaluation and early stopping (train metrics run on the
        all_gathered global score inside the shard_map chunk; AUC's
        global sort included.  Validation sets ride replicated).

        GOSS (ISSUE 12) runs INSIDE the chunk program on every path:
        the selection is traced into the scan body on each iteration's
        raw in-program gradients (serial/FP: the full replicated rows;
        DP: the |grad| scores all_gathered over the data axis, selected
        on the compacted true rows, sliced back per shard — a pure
        function of the globally-identical gradients, so every shard
        computes the identical selection), so sampled iterations keep
        the fused-k dispatch instead of forcing the per-iteration
        path."""
        if self.supports_chunking:
            return True
        from ..parallel.learners import (DataParallelLearner,
                                         FeatureParallelLearner)
        if (isinstance(self._learner, DataParallelLearner)
                and hasattr(self.objective, "chunk_spec")
                and (getattr(self.objective, "rows_aligned_params", False)
                     or getattr(self.objective, "needs_global_score",
                                False))):
            # eval-free runs never trace metric fns; otherwise every
            # metric needs a device formulation
            return (not self._needs_eval(is_eval)
                    or self._metrics_device_capable())
        if (isinstance(self._learner, FeatureParallelLearner)
                and hasattr(self.objective, "chunk_spec")):
            # rows are replicated under feature ownership, so ANY
            # chunk-traceable objective works (lambdarank included)
            return (not self._needs_eval(is_eval)
                    or self._metrics_device_capable())
        return False

    def chunkable_for(self, is_eval: bool) -> bool:
        """run_training's chunking decision: chunk_supported AND a
        chunk-safe grower/histogram combination.

        The round-1 "leaf-wise chunk crash" was root-caused to this
        environment's ~60 s per-dispatch execution watchdog (BASELINE.md;
        a plain matmul fori_loop reproduces it — not a grower bug): a
        fused leaf-wise chunk is ONE dispatch of k x 254 histogram passes
        and crosses the cap at production shapes (f32: k=3 x 500k; int8:
        k~22 x 1M).  Fused leaf-wise is also measured SLOWER than the
        per-iteration leaf-wise path (int8 in-scan 2.95 s/iter at 1M vs
        0.63 s/iter per-iteration f32 — per-pass quantization overhead
        dominates the C=1 passes), so leaf-wise stays per-iteration on
        every count.  Direct train_chunk calls remain available for
        leaf-wise on CPU (used by tests)."""
        return (self.chunk_supported(is_eval)
                and self.tree_config.grow_policy == "depthwise")

    def _metric_spec(self, metric):
        """Cached device_spec per metric instance (NDCG builds large padded
        tables; no reason to rebuild them per chunk)."""
        cache = getattr(self, "_metric_spec_cache", None)
        if cache is None:
            cache = self._metric_spec_cache = {}
        spec = cache.get(id(metric))
        if spec is None:
            spec = cache[id(metric)] = metric.device_spec()
        return spec

    def train_chunk(self, k: int, limit: int = -1,
                    is_eval: bool = False) -> bool:
        """Run ``k`` boosting iterations as ONE device program.

        The reference pays a host round-trip per split; the per-iteration
        path above pays several per iteration (gradient dispatch, grow,
        score update, model readback — each ~100 ms of link latency on a
        tunneled TPU).  This path lax.scans the whole iteration body —
        gradients → tree growth → score update — over k iterations, so the
        host is touched ONCE per chunk: upload of the per-iteration
        bagging/feature masks, readback of the k stacked tree arrays.

        Semantics match k calls of train_one_iter exactly (same RNG draws
        for bagging/feature sampling, same degenerate-tree stop, same
        per-iteration metric/early-stopping bookkeeping — metric values and
        valid-set scores are computed inside the program and consumed on the
        host post-hoc).  Returns True when training must stop.

        ``limit`` < k keeps only the first ``limit`` iterations and rolls
        the RNG streams and scores back to that point — used by run_training
        to serve a short tail with the full-size compiled program instead of
        re-compiling a second program for the remainder.  An early stop at
        iteration i similarly rolls back to i+1 kept iterations before the
        reference's model pop-back.
        """
        if not self.chunk_supported(is_eval):
            raise RuntimeError(
                "train_chunk requires a chunk-traceable objective and the "
                "serial, data-parallel or feature-parallel learner; any "
                "configured metric "
                "must have a device formulation (metrics/device.py) when "
                "evaluation is consumed, and goss=true is per-iteration "
                "only (see chunk_supported); use "
                "train_one_iter / run_training")
        if self._pipe is not None:
            # per-iteration entries pending (path switch): drain first
            if self.flush_pipeline():
                return True
        if self._pipeline_on():
            # pipelined: dispatch THIS chunk before consuming the previous
            # one, so the previous chunk's stacked-tree transfer (async
            # copy started at its dispatch) overlaps this chunk's device
            # execution.  A stop discovered in the previous chunk discards
            # this dispatch wholesale — the rollback rebuilds score/valid/
            # RNG from snapshots, so nothing of the surplus dispatch
            # survives (exact synchronous semantics).
            rec = self._dispatch_chunk(k, limit, is_eval)
            prev, self._pipe_chunk = self._pipe_chunk, rec
            if prev is not None and self._consume_chunk(
                    prev, newer_inflight=True):
                self._pipe_chunk = None
                return True
            return False
        if self._pipe_chunk is not None:
            # pipeline turned off with a chunk in flight: drain first
            if self.flush_pipeline():
                return True
        rec = self._dispatch_chunk(k, limit, is_eval)
        return self._consume_chunk(rec, newer_inflight=False)

    def _dispatch_chunk(self, k: int, limit: int, is_eval: bool) -> dict:
        """Dispatch one k-iteration chunk program (mask draws, program
        invocation, post-chunk score/valid installation, async readback
        start) and return the consumption record: output handles plus the
        pre-chunk snapshots _consume_chunk's stop paths rebuild from."""
        self._file_residency()
        mon = self._health_monitor
        has_bag = self._use_bagging
        has_ff = self.tree_config.feature_fraction < 1.0
        obj_key, obj_params, grad_fn = self.objective.chunk_spec()
        dp = self._learner is not _serial_learner
        pad = 0
        # no consumer -> no in-program evaluation: with output_freq == 0
        # and no early stopping the per-iteration path evaluates nothing
        # either
        eval_each = self._needs_eval(is_eval)
        train_specs = ([self._metric_spec(m)
                        for m in self.training_metrics]
                       if eval_each else [])
        valid_specs = ([[self._metric_spec(m) for m in ms]
                        for ms in self.valid_metrics] if eval_each else
                       [[] for _ in self.valid_metrics])
        from ..parallel.learners import FeatureParallelLearner
        fp = isinstance(self._learner, FeatureParallelLearner)
        # in-chunk GOSS (ISSUE 12): the static selection parameters ride
        # the program builders (and their cache keys); the per-iteration
        # key stream fold_in(PRNGKey(seed), iteration) matches the
        # per-iteration path's _goss_masks draw exactly
        goss = ((int(self.gbdt_config.bagging_seed), self._goss_top_cnt,
                 self._goss_other_cnt, float(self._goss_amp))
                if self._goss_on else None)
        if dp:
            extra = {} if fp else {
                "needs_global_score": getattr(self.objective,
                                              "needs_global_score", False)}
            if self._mp:
                extra["shard_layout"] = self._shard_layout
            extra["health"] = mon is not None
            extra["goss"] = goss
            fn, num_shards = self._learner.chunk_program(
                self, obj_key, grad_fn, obj_params, has_bag, has_ff,
                train_metric_fns=tuple(s[2] for s in train_specs),
                valid_metric_fns=tuple(tuple(s[2] for s in specs)
                                       for specs in valid_specs),
                n_valid=len(self.valid_datasets), **extra)
            # feature-parallel replicates rows — no shard padding
            pad = 0 if fp else (-self.num_data) % num_shards
        else:
            fn = _get_chunk_program(
                obj_key, grad_fn, self.num_class,
                float(self.gbdt_config.learning_rate),
                self.tree_config.grow_policy,
                num_leaves=_effective_num_leaves(self.tree_config),
                num_bins_max=self.num_bins_max,
                min_data_in_leaf=self.tree_config.min_data_in_leaf,
                min_sum_hessian_in_leaf=(
                    self.tree_config.min_sum_hessian_in_leaf),
                max_depth=self.tree_config.max_depth,
                hist_chunk=self.tree_config.hist_chunk,
                hist_dtype=self.tree_config.hist_dtype,
                quant_rounding=self.tree_config.quant_rounding,
                leafwise_compact=leafwise_compact_on(self.tree_config),
                num_features=self.num_features,
                packing=self._pack_spec,
                has_bag=has_bag, has_ff=has_ff,
                train_metric_fns=tuple(s[2] for s in train_specs),
                valid_metric_fns=tuple(tuple(s[2] for s in specs)
                                       for specs in valid_specs),
                health_fn=(mon.chunk_health_fn(None)
                           if mon is not None else None),
                goss=goss)

        C, N, F = self.num_class, self.num_data, self.num_features
        # snapshots for early/degenerate stops and tail truncation: training
        # must then look exactly like it stopped at that iteration — RNG
        # streams and train/valid scores included
        bag_state = self._bag_snapshot()
        ff_states = ([r.get_state() for r in self._feat_rngs]
                     if has_ff else None)
        score_before = self.score
        valid_before = [e["score"] for e in self.valid_datasets]
        # self.iter advances at CONSUMPTION; a pending pipelined chunk
        # means this dispatch's bagging-freq phase must start past its
        # planned iterations
        prev_rec = self._pipe_chunk
        base_iter = self.iter + (prev_rec["planned"]
                                 if prev_rec is not None else 0)
        if tracing.active():
            # chunk boundary on the flight-recorder timeline (ISSUE 16)
            tracing.event("train_chunk", base_iter=int(base_iter),
                          k=int(k))
        # in-chunk GOSS key stream: global iteration numbers ride the
        # scan xs (fold_in(PRNGKey(seed), iteration) in-program — the
        # rollback machinery needs NO snapshot, the draw is a pure
        # function of the iteration)
        if goss is not None:
            goss_iters = (np.asarray if self._host_inputs else jnp.asarray)(
                np.arange(base_iter, base_iter + k, dtype=np.int32))
            goss_args = (goss_iters,)
            telemetry.count("goss/iterations", k)
        else:
            goss_args = ()

        # multi-process runs keep replicated inputs host-side (every process
        # passes identical values; a committed local jnp array would clash
        # with the global-mesh program)
        _arr = np.asarray if self._host_inputs else jnp.asarray
        if has_bag and self._bag_device:
            # device bagging (ISSUE 8): the chunk's [k, C, N] mask stack
            # is computed ON DEVICE from the draw counter — the host
            # contributes k*C key bumps instead of k*C full-N draws plus
            # one k*C*N bool upload.  Non-redraw iterations carry the
            # previous device mask, exactly like the host stacking loop.
            masks = []
            for i in range(k):
                for cls in range(C):
                    self._draw_bag_mask(base_iter + i)
                    masks.append(self._bag_mask_device)
            rm = jnp.stack(masks).reshape(k, C, N)
            row_masks = (jnp.pad(rm, ((0, 0), (0, 0), (0, pad)))
                         if pad else rm)
        elif has_bag:
            # multi-process: local draws padded to the process block, then
            # lifted to one global row-sharded mask array
            width = self._mp_max_n if self._mp else N + pad
            fill = self._mp_local_n if self._mp else N
            rms = np.zeros((k, C, width), dtype=bool)
            for i in range(k):
                for cls in range(C):
                    self._draw_bag_mask(base_iter + i)
                    rms[i, cls, :fill] = self._bag_mask
            row_masks = (self._mp_make_global(rms, row_axis=2)
                         if self._mp else _arr(rms))
        else:
            row_masks = _arr(np.zeros((k, 1), bool))   # scan driver only
        if has_ff:
            fms = np.empty((k, C, F), dtype=bool)
            for i in range(k):
                for cls in range(C):
                    fms[i, cls] = self._feature_sample(cls)
            feat_masks = _arr(fms)
        else:
            feat_masks = _arr(np.zeros((k, 1), bool))

        if fp:
            own, ownmask = self._learner.chunk_args(self, num_shards)
            # multi-process FP: objective/metric device params were built
            # as process-local jnp arrays; ship them host-side ONCE so
            # every process passes identical replicated values to the
            # global-mesh program (the params are constant across chunks)
            if self._mp_fp:
                ck = (len(train_specs),
                      tuple(len(s) for s in valid_specs))
                cached = getattr(self, "_fp_host_params", None)
                if cached is None or cached[0] != ck:
                    cached = self._fp_host_params = (ck, jax.tree.map(
                        np.asarray,
                        (obj_params,
                         tuple(s[1] for s in train_specs),
                         tuple(tuple(s[1] for s in specs)
                               for specs in valid_specs))))
                obj_in, train_in, valid_in = cached[1]
            else:
                obj_in = obj_params
                train_in = tuple(s[1] for s in train_specs)
                valid_in = tuple(tuple(s[1] for s in specs)
                                 for specs in valid_specs)
            with telemetry.span("train_chunk") as sp:
                new_score, vscores_out, stacked, mvals, hvals = sp.fence(fn(
                    self.score, self.bins_device, self.num_bins_device,
                    own, ownmask, row_masks, feat_masks, obj_in,
                    train_in,
                    tuple(e["bins"] for e in self.valid_datasets),
                    tuple(e["score"] for e in self.valid_datasets),
                    valid_in, *goss_args))
            self.score = new_score
        elif dp:
            # pad rows to the shard grid once per booster; padded rows are
            # masked out of histograms/stats by valid_rows and their score
            # lane is sliced off again below
            cache = getattr(self, "_dp_chunk_inputs", None)
            if cache is None or cache[0] != num_shards:
                bins_p = (jnp.pad(self.bins_device, ((0, 0), (0, pad)))
                          if pad else self.bins_device)
                if getattr(self.objective, "needs_global_score", False):
                    # per-query tables are NOT row-aligned; they ride
                    # replicated and the gradient fn handles the padded
                    # score length itself
                    obj_p = obj_params
                else:
                    obj_p = jax.tree.map(
                        lambda l: (jnp.pad(l, [(0, pad)] + [(0, 0)]
                                           * (l.ndim - 1))
                                   if pad and getattr(l, "ndim", 0) >= 1
                                   else l),
                        obj_params)
                if self._mp:
                    # multi-process: per-process padding is interleaved
                    # (each rank's block ends with phantom rows), and
                    # num_data is already device-aligned (pad == 0)
                    valid_rows = self._row_valid
                else:
                    valid_rows = jnp.arange(N + pad) < N
                cache = (num_shards, bins_p, obj_p, valid_rows)
                self._dp_chunk_inputs = cache
            _, bins_p, obj_p, valid_rows = cache
            score_in = (jnp.pad(self.score, ((0, 0), (0, pad)))
                        if pad else self.score)
            with telemetry.span("train_chunk") as sp:
                new_score, vscores_out, stacked, mvals, hvals = sp.fence(fn(
                    score_in, bins_p, self.num_bins_device, valid_rows,
                    row_masks, feat_masks, obj_p,
                    tuple(s[1] for s in train_specs),
                    tuple(e["bins"] for e in self.valid_datasets),
                    tuple(e["score"] for e in self.valid_datasets),
                    tuple(tuple(s[1] for s in specs)
                          for specs in valid_specs), *goss_args))
            self.score = new_score[:, :N] if pad else new_score
        else:
            with telemetry.span("train_chunk") as sp:
                self.score, vscores_out, stacked, mvals, hvals = sp.fence(fn(
                    self.score, self.bins_device, self.num_bins_device,
                    row_masks, feat_masks, obj_params,
                    tuple(s[1] for s in train_specs),
                    tuple(e["bins"] for e in self.valid_datasets),
                    tuple(e["score"] for e in self.valid_datasets),
                    tuple(tuple(s[1] for s in specs)
                          for specs in valid_specs), *goss_args))
        # post-chunk valid scores install NOW (the next dispatch reads
        # them); stop paths rebuild from valid_before absolutely, so the
        # early install is semantics-neutral
        vscores_out = tuple(np.asarray(s) if self._host_inputs else s
                            for s in vscores_out)
        for e, s in zip(self.valid_datasets, vscores_out):
            e["score"] = s
        # start the stacked-tree/metric/health transfers immediately: the
        # copies then overlap whatever the device runs next (pipelined
        # mode: the following chunk)
        try:
            for arr in jax.tree.leaves((stacked, mvals, hvals)):
                arr.copy_to_host_async()
        except Exception:
            pass
        return {
            "k": k, "limit": limit, "eval_each": eval_each, "mon": mon,
            "planned": k if limit < 0 else min(k, limit),
            "stacked": stacked, "mvals": mvals, "hvals": hvals,
            "vscores_out": vscores_out,
            "bag_state": bag_state, "ff_states": ff_states,
            "score_before": score_before, "valid_before": valid_before,
        }

    def _consume_chunk(self, rec: dict, newer_inflight: bool) -> bool:
        """Deferred consumption of one dispatched chunk: model readback,
        host tree construction, per-iteration metric/health/early-stop
        bookkeeping, surplus rollback — the synchronous tail of
        train_chunk, verbatim in order.  ``newer_inflight``: a younger
        chunk was already dispatched, so every stop path must roll back
        through the snapshots (erasing the younger chunk's installed
        score/valid/RNG state) even when this chunk kept all k
        iterations."""
        k, limit, eval_each, mon = (rec["k"], rec["limit"],
                                    rec["eval_each"], rec["mon"])
        stacked, mvals, hvals = rec["stacked"], rec["mvals"], rec["hvals"]
        vscores_out = rec["vscores_out"]
        bag_state, ff_states = rec["bag_state"], rec["ff_states"]
        score_before = rec["score_before"]
        valid_before = rec["valid_before"]
        C = self.num_class
        with telemetry.span("model_readback"):
            host = jax.device_get(stacked)
            mvals_host = np.asarray(mvals) if eval_each else None
            # stacked [k, H] in-program health vectors, one per iteration
            hvals_host = np.asarray(hvals) if mon is not None else None

        # per-iteration telemetry records: the fused program's phases are
        # indivisible from the host, so its wall time is amortized evenly
        # across the chunk's iterations (marked "amortized_over"); the
        # memory gauges are LEVELS, not durations — every record of the
        # chunk carries the same post-chunk sample
        if telemetry.sink_active():
            _chunk_dp, _chunk_dt = telemetry.take_phase_deltas()
            _chunk_mem = telemetry.take_memory_record()
            _scale = 1.0 / max(k, 1)

            def _emit(i: int, health=None, stopped=None) -> None:
                extra = {"amortized_over": k}
                if stopped:
                    extra["stopped"] = stopped
                telemetry.emit_iteration(
                    self.iter + i + 1,
                    {p: v * _scale for p, v in _chunk_dp.items()},
                    {p: v * _scale for p, v in _chunk_dt.items()},
                    eval_metrics=self._last_eval_values,
                    health=health, memory=_chunk_mem,
                    extra=extra)
        else:
            def _emit(i: int, health=None, stopped=None) -> None:
                pass

        keep_iters = k if limit < 0 else min(k, limit)
        esr = self.early_stopping_round
        for i in range(keep_iters):
            for cls in range(C):
                sub = jax.tree.map(lambda a: a[i, cls], host)
                nl = int(sub.num_leaves)
                if mon is not None:
                    mon.add_tree(nl, sub.split_gain, sub.leaf_count)
                if nl <= 1:
                    log.info("Can't training anymore, there isn't any leaf "
                             "meets split requirements.")
                    # the degenerate pair consumed its RNG draws but
                    # produced no tree
                    self._rollback_chunk(i * C + cls + 1, i * C + cls,
                                         bag_state, ff_states, score_before,
                                         valid_before)
                    if mon is not None:
                        # explain the stop (NaN/Inf gains reject every
                        # split): assemble this iteration's in-program
                        # vector and apply the policy before returning —
                        # marked like the per-iteration path so the
                        # rolled-back record is distinguishable from a
                        # trained iteration
                        block = mon.assemble(hvals_host[i])
                        _emit(i, health=block, stopped="degenerate_tree")
                        self.iter += i
                        mon.apply_policy(block, self.iter + 1)
                    else:
                        self.iter += i
                    return True
                tree = self._to_host_tree(sub)
                tree.shrinkage(self.gbdt_config.learning_rate)
                self.models.append(tree)
            if eval_each:
                train_vals, valid_vals = self._split_metric_values(
                    mvals_host[i])
                if self._consume_metric_values(self.iter + i + 1,
                                               train_vals, valid_vals):
                    kept = i + 1
                    health_i = (mon.assemble(hvals_host[i])
                                if mon is not None else None)
                    _emit(i, health=health_i)
                    log.info("Early stopping at iteration %d, the best "
                             "iteration round is %d"
                             % (self.iter + kept, self.iter + kept - esr))
                    # first restore state to exactly `kept` iterations
                    # (reference semantics: scores keep the popped trees'
                    # contributions, so roll back only the surplus scan
                    # iterations), THEN pop the early-stopping window
                    if kept < k or newer_inflight:
                        self._rollback_chunk(kept * C, kept * C, bag_state,
                                             ff_states, score_before,
                                             valid_before)
                    del self.models[len(self.models) - esr * C:]
                    self.iter += kept
                    if mon is not None:
                        mon.apply_policy(health_i, self.iter)
                    return True
            health_i = (mon.assemble(hvals_host[i])
                        if mon is not None else None)
            _emit(i, health=health_i)
            if mon is not None:
                from ..health import TrainingHealthError
                try:
                    mon.apply_policy(health_i, self.iter + i + 1)
                except TrainingHealthError:
                    # halt must leave the booster CONSISTENT at i+1 kept
                    # iterations, exactly like the early-stop branch: the
                    # scan already applied the whole chunk's score
                    # updates, so roll the surplus back before raising
                    kept = i + 1
                    if kept < k or newer_inflight:
                        self._rollback_chunk(kept * C, kept * C, bag_state,
                                             ff_states, score_before,
                                             valid_before)
                    self.iter += kept
                    self._pipe_chunk = None
                    raise
        if keep_iters < k:
            # tail truncation: only possible on the LAST chunk of a run
            # (limit < k), so no newer chunk can be in flight
            self._rollback_chunk(keep_iters * C, keep_iters * C,
                                 bag_state, ff_states, score_before,
                                 valid_before)
        # else: score/valid already installed at dispatch
        self.iter += keep_iters
        return False

    def _split_metric_values(self, vals: np.ndarray):
        """Unpack one iteration's concatenated device metric vector into
        (train_vals, valid_vals) lists shaped like the host eval path."""
        off = 0

        def take(metric):
            nonlocal off
            n = metric.n_values()
            out = [float(v) for v in vals[off:off + n]]
            off += n
            return out

        train_vals = [take(m) for m in self.training_metrics]
        valid_vals = [[take(m) for m in ms] for ms in self.valid_metrics]
        return train_vals, valid_vals

    def _rollback_chunk(self, replay_pairs: int, kept_trees: int,
                        bag_state, ff_states, score_before,
                        valid_before=()) -> None:
        """Restore exact per-iteration semantics after a chunk that kept
        fewer iterations than it ran (mid-chunk degenerate-tree stop, early
        stop, or a run_training tail served by the full-size program):
        rewind the bagging/feature RNG streams and replay exactly
        ``replay_pairs`` (iteration, class) draws, and rebuild the train and
        valid scores from the pre-chunk scores plus this chunk's
        ``kept_trees`` trees (the scan had already applied the discarded
        iterations' updates on device)."""
        C = self.num_class
        if bag_state is not None:
            self._bag_restore(bag_state)
            for p in range(replay_pairs):
                self._draw_bag_mask(self.iter + p // C)
        if ff_states is not None:
            for r, s in zip(self._feat_rngs, ff_states):
                r.set_state(s)
            for p in range(replay_pairs):
                self._feature_sample(p % C)

        kept = self.models[len(self.models) - kept_trees:] \
            if kept_trees > 0 else []
        max_nodes = max(_effective_num_leaves(self.tree_config) - 1, 1)
        train_fmap = (np.asarray(self._pack_spec.c2p, np.int32)
                      if getattr(self, "_pack_spec", None) is not None
                      else None)
        score = score_before
        vscores = list(valid_before)
        for m, tree in enumerate(kept):
            cls_m = m % C
            score = _replay_tree(score, self.bins_device, tree, cls_m,
                                 max_nodes, feat_map=train_fmap)
            for v, entry in enumerate(self.valid_datasets):
                vscores[v] = _replay_tree(vscores[v], entry["bins"], tree,
                                          cls_m, max_nodes)
        self.score = score
        for entry, s in zip(self.valid_datasets, vscores):
            entry["score"] = s

    def _to_host_tree(self, host) -> Tree:
        """Build the host Tree from an already-device_get'd TreeArrays."""
        n = int(host.num_leaves)
        split_feature = np.asarray(host.split_feature)[:n - 1]
        threshold_bin = np.asarray(host.threshold_bin)[:n - 1]
        # real-valued thresholds from bin upper bounds in float64 on host
        # (serial_tree_learner.cpp:418 BinToValue), via the precomputed
        # [F, B] upper-bound table
        thresholds = self._bin_upper_table[split_feature, threshold_bin]
        real_feature = self.train_data.real_feature_idx[split_feature]
        return Tree(
            num_leaves=n,
            split_feature=split_feature,
            split_feature_real=real_feature,
            threshold_bin=threshold_bin,
            threshold=thresholds,
            split_gain=np.asarray(host.split_gain, np.float64)[:n - 1],
            left_child=np.asarray(host.left_child)[:n - 1],
            right_child=np.asarray(host.right_child)[:n - 1],
            leaf_parent=np.asarray(host.leaf_parent)[:n],
            leaf_value=np.asarray(host.leaf_value, np.float64)[:n],
        )

    # --------------------------------------------------------------- metrics

    def _host_global_score(self, score=None) -> np.ndarray:
        """Training score as a host [C, N_true] array.  Multi-process mode
        replicates the row-sharded global score across the mesh (one
        all_gather) and compacts out the per-process padding blocks.
        ``score`` defaults to the live array (checkpoint_state passes the
        consumed-boundary reference)."""
        if score is None:
            score = self.score
        if not self._mp:
            return np.asarray(score)
        prog = getattr(self, "_mp_replicate_prog", None)
        if prog is None:
            from jax.sharding import NamedSharding, PartitionSpec
            prog = self._mp_replicate_prog = jax.jit(
                lambda s: s,
                out_shardings=NamedSharding(self._mp_mesh, PartitionSpec()))
        full = np.asarray(prog(score))
        return np.concatenate([full[:, s:s + ln]
                               for s, ln in self._shard_layout], axis=1)

    def output_metric(self, iteration: int) -> bool:
        """GBDT::OutputMetric (gbdt.cpp:225-259), host-eval path."""
        freq = self.gbdt_config.output_freq
        eval_now = freq > 0 and iteration % freq == 0
        train_vals = None
        if eval_now and self.training_metrics:
            score_np = self._host_global_score()
            flat = (score_np.reshape(-1) if self.num_class > 1
                    else score_np[0])
            train_vals = [m.eval(flat) for m in self.training_metrics]
        valid_vals = None
        if self.valid_datasets and (eval_now
                                    or self.early_stopping_round > 0):
            valid_vals = []
            for i, entry in enumerate(self.valid_datasets):
                score_np = np.asarray(entry["score"])
                flat = (score_np.reshape(-1) if self.num_class > 1
                        else score_np[0])
                valid_vals.append([m.eval(flat)
                                   for m in self.valid_metrics[i]])
        return self._consume_metric_values(iteration, train_vals, valid_vals)

    def _consume_metric_values(self, iteration: int, train_vals,
                               valid_vals) -> bool:
        """Shared logging + early-stopping bookkeeping over metric VALUES
        (computed on host by output_metric, or on device by train_chunk).
        Mirrors gbdt.cpp:225-259: train metrics print on output_freq
        boundaries; valid metrics additionally drive the best-score /
        early-stop state every iteration."""
        freq = self.gbdt_config.output_freq
        eval_now = freq > 0 and iteration % freq == 0
        ret = False
        if telemetry.sink_active():
            vals = {}
            if train_vals is not None:
                for metric, values in zip(self.training_metrics, train_vals):
                    vals["training/" + metric.name] = list(values)
            if valid_vals is not None:
                for i, entry in enumerate(self.valid_datasets):
                    for j, metric in enumerate(self.valid_metrics[i]):
                        vals[entry["name"] + "/" + metric.name] = list(
                            valid_vals[i][j])
            if vals:
                self._last_eval_values = vals
        if self._health_monitor is not None:
            # eval-divergence tracking (health_divergence_rounds consecutive
            # worsening iterations flag an anomaly; both eval paths — host
            # and in-chunk — land here every iteration)
            mon = self._health_monitor
            if train_vals is not None:
                for metric, values in zip(self.training_metrics, train_vals):
                    mon.observe_eval("training/" + metric.name,
                                     float(values[-1]),
                                     metric.is_bigger_better)
            if valid_vals is not None:
                for i, entry in enumerate(self.valid_datasets):
                    for j, metric in enumerate(self.valid_metrics[i]):
                        mon.observe_eval(
                            entry["name"] + "/" + metric.name,
                            float(valid_vals[i][j][-1]),
                            metric.is_bigger_better)
        if eval_now and train_vals is not None:
            for metric, values in zip(self.training_metrics, train_vals):
                log.info("Iteration:%d, %s : %s"
                         % (iteration, metric.name,
                            " ".join(str(v) for v in values)))
        if valid_vals is not None:
            for i in range(len(self.valid_datasets)):
                for j, metric in enumerate(self.valid_metrics[i]):
                    values = valid_vals[i][j]
                    if eval_now:
                        log.info("Iteration:%d, %s : %s"
                                 % (iteration, metric.name,
                                    " ".join(str(v) for v in values)))
                    if not ret and self.early_stopping_round > 0:
                        bigger_better = metric.is_bigger_better
                        last = values[-1]
                        if (self.best_score[i][j] < 0
                                or (not bigger_better
                                    and last < self.best_score[i][j])
                                or (bigger_better
                                    and last > self.best_score[i][j])):
                            self.best_score[i][j] = last
                            self.best_iter[i][j] = iteration
                        elif (iteration - self.best_iter[i][j]
                                >= self.early_stopping_round):
                            ret = True
        return ret

    # ------------------------------------------------------------ prediction

    # device batch prediction pays ~one dispatch of link latency; below this
    # rows x trees volume the host numpy walk wins
    _DEVICE_PREDICT_THRESHOLD = 20_000_000

    def capture_score_reference(self) -> Optional[dict]:
        """Serialize the live training scores into a
        monitor.ScoreHistogram dict — the drift-detection baseline
        (ISSUE 20).  Recaptured from the CURRENT scores on every call
        while the booster holds score state, so a mid-training
        checkpoint save cannot freeze an early-iteration reference into
        a later final model (the elastic resume path compares final
        model text byte-for-byte).  A booster with no score state
        (fresh load, prediction-only) keeps the reference
        ``models_from_string`` parsed, or returns None."""
        score = getattr(self, "score", None)
        if score is None:
            return self.score_reference
        try:
            from ..monitor import ScoreHistogram
            values = np.asarray(score, dtype=np.float64)
            # true rows only: per-topology padding rows accumulate leaf
            # values too, and two topologies pad differently — the
            # reference must not depend on the mesh shape
            n = int(getattr(self, "num_data", 0)) or values.shape[-1]
            values = values[..., :n].ravel()
            if values.size == 0:
                return None
            hist = ScoreHistogram()
            hist.record_many(values)
            self.score_reference = hist.to_dict()
        except Exception:
            return None
        return self.score_reference

    def export_flat(self, num_models: int = -1):
        """Flatten the first ``num_models`` trees (all when < 0) into a
        serving.FlatEnsemble: stacked per-node tensors + the host-built
        f64 rank-code tables.  This is the once-per-model encode the old
        per-call ``_device_predict_encode`` re-ran on every predict."""
        from ..serving import FlatEnsemble
        models = self.models if num_models < 0 else self.models[:num_models]
        flat = FlatEnsemble.from_models(models, self.num_class)
        # the drift reference rides the flattened ensemble so a
        # ServingFront can register it without ever touching the booster
        flat.score_reference = self.capture_score_reference()
        return flat

    def serving_engine(self, num_models: int = -1, **options):
        """The cached compiled serving engine over the first
        ``num_models`` trees (serving.ServingEngine: bucketed batch
        shapes, donated buffers, breadth-first lockstep scoring).  The
        cache key includes the model count, so continued training (or a
        pipeline rollback popping trees) re-flattens naturally."""
        if num_models < 0:
            num_models = len(self.models)
        key = (len(self.models), num_models, tuple(sorted(options.items())))
        cached = getattr(self, "_serve_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from ..serving import ServingEngine
        engine = ServingEngine(self.export_flat(num_models), **options)
        self._serve_cache = (key, engine)
        return engine

    def _device_predict_encode(self, features: np.ndarray, models):
        """Back-compat shim over serving.FlatEnsemble: rank-encoded codes
        plus the stacked per-tree arrays (the old per-call flatten).  New
        code should use export_flat()/serving_engine() — those cache the
        flatten across calls."""
        from ..serving import FlatEnsemble
        flat = FlatEnsemble.from_models(models, self.num_class)
        codes = flat.encode(features)
        return codes, (flat.split_feature, flat.threshold_rank,
                       flat.left_child, flat.right_child, flat.leaf_value,
                       flat.num_leaves), flat.max_nodes

    def _predict_scores_device(self, features: np.ndarray,
                               models) -> np.ndarray:
        """[num_class, N] raw ensemble sums via the compiled serving
        engine (models must be a prefix of self.models — every caller
        passes self.models[:n])."""
        engine = self.serving_engine(len(models))
        return engine.scores(features)

    def predict_raw(self, features: np.ndarray,
                    num_used_model: int = -1) -> np.ndarray:
        """Batch PredictRaw (gbdt.cpp:470-479); features [N, cols] raw."""
        if num_used_model < 0:
            num_used_model = len(self.models)
        models = self.models[:num_used_model]
        if features.shape[0] * max(len(models), 1) \
                >= self._DEVICE_PREDICT_THRESHOLD:
            return self._predict_scores_device(features, models)[0]
        out = np.zeros(features.shape[0], dtype=np.float64)
        for tree in models:
            out += tree.predict(features)
        return out

    def predict(self, features: np.ndarray,
                num_used_model: int = -1) -> np.ndarray:
        """Predict with sigmoid transform when applicable (gbdt.cpp:481-494)."""
        ret = self.predict_raw(features, num_used_model)
        if self.sigmoid > 0:
            ret = 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * ret))
        return ret

    def predict_multiclass(self, features: np.ndarray,
                           num_used_model: int = -1) -> np.ndarray:
        """[N, num_class] softmax probabilities (gbdt.cpp:496-508)."""
        if num_used_model < 0:
            num_used_model = len(self.models) // self.num_class
        models = self.models[:num_used_model * self.num_class]
        if features.shape[0] * max(len(models), 1) \
                >= self._DEVICE_PREDICT_THRESHOLD:
            out = self._predict_scores_device(features, models).T
        else:
            out = np.zeros((features.shape[0], self.num_class),
                           dtype=np.float64)
            for i in range(num_used_model):
                for j in range(self.num_class):
                    out[:, j] += self.models[i * self.num_class
                                             + j].predict(features)
        z = out - out.max(axis=1, keepdims=True)
        p = np.exp(z)
        return p / p.sum(axis=1, keepdims=True)

    def predict_leaf_index(self, features: np.ndarray,
                           num_used_model: int = -1) -> np.ndarray:
        """[N, num_models] leaf indices (gbdt.cpp:510-519)."""
        if num_used_model < 0:
            num_used_model = len(self.models)
        models = self.models[:num_used_model]
        if features.shape[0] * max(len(models), 1) \
                >= self._DEVICE_PREDICT_THRESHOLD:
            return self.serving_engine(len(models)).leaf_indices(features)
        cols = []
        for tree in models:
            if tree.num_leaves == 1:
                cols.append(np.zeros(features.shape[0], dtype=np.int32))
            else:
                cols.append(tree.leaf_index_by_replay(features))
        return np.stack(cols, axis=1)

    # -------------------------------------------------------------- model IO

    def save_model_to_file(self, is_finish: bool, filename: str) -> None:
        """Incremental text save (gbdt.cpp:307-348): header once, then newly
        finished trees appended each call, withholding the trailing
        early-stopping window until finish."""
        if self._saved_model_size == -1:
            self._model_file = open(filename, "w")
            self._model_file.write("gbdt\n")
            self._model_file.write("num_class=%d\n" % self.num_class)
            self._model_file.write("label_index=%d\n" % self.label_idx)
            self._model_file.write("max_feature_idx=%d\n" % self.max_feature_idx)
            self._model_file.write("sigmoid=%s\n" % _fmt(self.sigmoid))
            self._model_file.write("\n")
            self._saved_model_size = 0
        if self._model_file is None or self._model_file.closed:
            return
        rest = len(self.models) - self.early_stopping_round * self.num_class
        for i in range(self._saved_model_size, rest):
            self._model_file.write("Tree=%d\n" % i)
            self._model_file.write(self.models[i].to_string() + "\n")
        self._saved_model_size = max(self._saved_model_size, rest)
        self._model_file.flush()
        if is_finish:
            for i in range(max(self._saved_model_size, 0), len(self.models)):
                self._model_file.write("Tree=%d\n" % i)
                self._model_file.write(self.models[i].to_string() + "\n")
            reference = self.capture_score_reference()
            if reference is not None:
                # training-time score distribution, the serving drift
                # detector's comparison baseline (ISSUE 20).  Written at
                # FINISH, not in the header: the header goes out on the
                # first incremental save, which would freeze an
                # early-iteration distribution into the final model
                # (find_value parses it wherever it sits).
                self._model_file.write(
                    "score_reference=%s\n"
                    % json.dumps(reference, separators=(",", ":")))
            self._model_file.write("\n" + self.feature_importance() + "\n")
            self._model_file.close()

    def models_from_string(self, model_str: str) -> None:
        """GBDT::ModelsFromString (gbdt.cpp:350-441)."""
        self.models = []
        lines = model_str.split("\n")

        def find_value(key):
            for line in lines:
                if key in line and "=" in line:
                    return line.split("=", 1)[1].strip()
            return None

        num_class = find_value("num_class=")
        if num_class is None:
            log.fatal("Model file doesn't contain number of class")
        self.num_class = int(num_class)
        label_index = find_value("label_index=")
        if label_index is None:
            log.fatal("Model file doesn't contain label index")
        self.label_idx = int(label_index)
        max_feature_idx = find_value("max_feature_idx=")
        if max_feature_idx is None:
            log.fatal("Model file doesn't contain max_feature_idx")
        self.max_feature_idx = int(max_feature_idx)
        sigmoid = find_value("sigmoid=")
        self.sigmoid = float(sigmoid) if sigmoid is not None else -1.0
        reference = find_value("score_reference=")
        if reference is not None:
            try:
                self.score_reference = json.loads(reference)
            except Exception:
                self.score_reference = None

        i = 0
        while i < len(lines):
            if "Tree=" in lines[i]:
                i += 1
                start = i
                while i < len(lines) and "Tree=" not in lines[i]:
                    i += 1
                self.models.append(Tree.from_string("\n".join(lines[start:i])))
            else:
                i += 1
        log.info("%d models has been loaded" % len(self.models))

    @classmethod
    def from_model_file(cls, filename: str) -> "GBDT":
        """Boosting::CreateBoosting from file (boosting.cpp:6-57)."""
        with open(filename, "r") as f:
            content = f.read()
        first_line = content.split("\n", 1)[0].strip()
        if first_line != "gbdt":
            log.fatal("Unknown boosting type %s" % first_line)
        self = cls()
        self.models_from_string(content)
        return self

    def feature_importance(self) -> str:
        """Split-count importances (gbdt.cpp:443-468)."""
        importances = np.zeros(self.max_feature_idx + 1, dtype=np.int64)
        for tree in self.models:
            for f in tree.split_feature_real:
                importances[f] += 1
        names = (self.train_data.feature_names if self.train_data is not None
                 else [f"Column_{i}" for i in range(self.max_feature_idx + 1)])
        pairs = sorted(zip(importances, names),
                       key=lambda p: -p[0])
        out = ["", "feature importances:"]
        for cnt, name in pairs:
            out.append(f"{name}={cnt}")
        return "\n".join(out) + "\n"


# Compiled k-iteration chunk programs, shared process-wide.  Keyed ONLY on
# hashable statics — per-dataset arrays (labels, weights, bins) enter as
# runtime inputs via obj_params, so the traced HLO is data-independent and a
# cross-validation loop or repeated lgb.train calls re-use one compile (and
# the persistent XLA cache can hit across processes).
_CHUNK_PROGRAMS: dict = {}


def make_chunk_body(*, grad_fn, obj_params, num_class: int, lrf, grow_fn,
                    has_bag: bool, has_ff: bool, bins, num_bins,
                    base_mask=None, max_nodes: int = 1,
                    valid_bins=(), valid_mparams=(),
                    train_metric_fns=(), train_mparams=(),
                    valid_metric_fns=(), health_fn=None, goss_fn=None):
    """The per-iteration boosting body shared by the serial chunk program
    and the data-parallel shard_map chunk (parallel/learners.py):
    gradients → per-class grow → train-score update (+ valid-score replay
    and in-program metric evaluation when configured).  ``grow_fn`` carries
    the grower statics — and, for the data-parallel case, the psum
    hist/stat reducers; ``base_mask`` is the always-on row validity mask
    (shard padding) and composes with the per-iteration bagging mask.
    ``health_fn`` (health.make_health_fn) accumulates the per-iteration
    training-health vector in-program — the fused chunk is the only place
    those per-iteration values exist; the vector is pure extra reductions
    over the existing arrays, never fed back into them.

    ``goss_fn`` (ISSUE 12): in-program GOSS selection — called as
    ``(iteration, grad, hess) -> (grad', hess', mask)`` on each
    iteration's RAW gradients before the per-class grows, exactly where
    the per-iteration path runs ``gbdt._goss_masks``.  The selection
    mask replaces the bagging row mask (GOSS excludes bagging by config)
    and the amplified grad'/hess' feed the growers; health and the next
    iteration's gradients keep the raw arrays.  When set, the scan xs
    carry a third element: the per-iteration GLOBAL iteration numbers
    (the GOSS key stream is ``fold_in(PRNGKey(seed), iteration)``, same
    as the per-iteration path — fused == per-iteration selection is
    bit-identical)."""
    F, N = bins.shape
    n_valid = len(valid_bins)

    def body(carry, xs):
        score, vscores = carry
        if goss_fn is None:
            rmask, fmask = xs
        else:
            rmask, fmask, goss_it = xs
        grad, hess = grad_fn(obj_params,
                             score if num_class > 1 else score[0])
        if num_class == 1:
            grad, hess = grad[None], hess[None]
        if goss_fn is not None:
            g_grow, h_grow, goss_mask = goss_fn(goss_it, grad, hess)
        else:
            g_grow, h_grow, goss_mask = grad, hess, None
        outs = []
        vscores = list(vscores)
        ones = (base_mask if base_mask is not None
                else jnp.ones((N,), jnp.bool_))
        for cls in range(num_class):
            if goss_mask is not None:
                rm = goss_mask & ones
            else:
                rm = (rmask[cls] & ones) if has_bag else ones
            fm = fmask[cls] if has_ff else jnp.ones((F,), jnp.bool_)
            ta = grow_fn(bins, g_grow[cls], h_grow[cls], rm, fm, num_bins)
            shrunk = jnp.where(ta.num_leaves > 1, ta.leaf_value * lrf, 0.0)
            score = score.at[cls].add(_leaf_lookup(shrunk, ta.leaf_ids))
            # valid scores by tree replay (gbdt.cpp:220-222)
            for v in range(n_valid):
                vscores[v] = vscores[v].at[cls].set(add_tree_score(
                    valid_bins[v], vscores[v][cls], ta.split_feature,
                    ta.threshold_bin, ta.left_child, ta.right_child,
                    shrunk, ta.num_leaves, max_nodes=max_nodes))
            outs.append(ta._replace(leaf_ids=jnp.zeros((0,), jnp.int32)))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *outs)

        # in-program metric evaluation (Metric::Eval on CPU threads in the
        # reference; here the scores never leave the device)
        mv = []
        for f, p in zip(train_metric_fns, train_mparams):
            mv.append(f(p, score if num_class > 1 else score[0]))
        for v in range(n_valid):
            sv = vscores[v] if num_class > 1 else vscores[v][0]
            for f, p in zip(valid_metric_fns[v], valid_mparams[v]):
                mv.append(f(p, sv))
        mvals = jnp.concatenate(mv) if mv else jnp.zeros((0,), jnp.float32)
        hvec = (health_fn(grad, hess, score) if health_fn is not None
                else jnp.zeros((0,), jnp.float32))
        return (score, tuple(vscores)), (stacked, mvals, hvec)

    return body


def _get_chunk_program(obj_key, grad_fn, num_class: int, lr: float,
                       grow_policy: str, *, num_leaves: int,
                       num_bins_max: int, min_data_in_leaf: int,
                       min_sum_hessian_in_leaf: float, max_depth: int,
                       hist_chunk: int = 0, hist_dtype: str = "float32",
                       quant_rounding: str = "nearest",
                       leafwise_compact: bool = False,
                       num_features: int = 0,
                       packing=None,
                       has_bag: bool, has_ff: bool,
                       train_metric_fns: tuple = (),
                       valid_metric_fns: tuple = (),
                       health_fn=None, goss=None):
    # the RESOLVED pallas-partition/DMA-overlap bits (and the backend
    # identity) are part of the key: __graft_entry__ flips
    # LGBM_TPU_NO_PALLAS mid-process (PROFILE.md's A/B flips
    # LGBM_TPU_PARTITION_NO_OVERLAP), and a stale program would keep the
    # old kernel routing
    from ..ops.compact import pallas_partition_ok, partition_overlap_on
    use_pp = leafwise_compact and grow_policy != "depthwise" \
        and pallas_partition_ok(num_features)
    key = (obj_key, id(grad_fn), num_class, lr, grow_policy, num_leaves,
           num_bins_max, min_data_in_leaf, min_sum_hessian_in_leaf,
           max_depth, hist_chunk, hist_dtype, quant_rounding,
           leafwise_compact, use_pp, use_pp and partition_overlap_on(),
           packing, goss,
           jax.default_backend(), has_bag, has_ff,
           tuple(id(f) for f in train_metric_fns),
           tuple(tuple(id(f) for f in fns) for fns in valid_metric_fns),
           id(health_fn) if health_fn is not None else None)
    prog = _CHUNK_PROGRAMS.get(key)
    if prog is not None:
        return prog

    grower_kwargs = dict(
        num_leaves=num_leaves, num_bins_max=num_bins_max,
        min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian_in_leaf=min_sum_hessian_in_leaf, max_depth=max_depth,
        packing=packing,
        **_tuning_kwargs(hist_chunk, hist_dtype, quant_rounding))
    if grow_policy == "depthwise":
        from .grower_depthwise import grow_tree_depthwise as grow
    elif leafwise_compact:
        # the resolved leafwise_compact flag keeps the chunk path (used
        # by direct train_chunk calls — leaf-wise production training is
        # per-iteration) on the SAME grower as the per-iteration path
        import functools as _ft
        from .grower_leafcompact import grow_tree_leafcompact_impl
        grow = _ft.partial(
            grow_tree_leafcompact_impl,
            use_pallas_partition=use_pp,
            partition_overlap=partition_overlap_on())
    else:
        from .grower import grow_tree_impl as grow
    lrf = jnp.float32(lr)
    max_nodes = max(num_leaves - 1, 1)
    goss_fn = make_goss_fn(goss) if goss is not None else None

    def chunk_fn(score, bins, num_bins, row_masks, feat_masks, obj_params,
                 train_mparams, valid_bins, valid_scores, valid_mparams,
                 goss_iters=None):
        body = make_chunk_body(
            grad_fn=grad_fn, obj_params=obj_params, num_class=num_class,
            lrf=lrf,
            grow_fn=lambda *a: grow(*a, **grower_kwargs),
            has_bag=has_bag, has_ff=has_ff, bins=bins, num_bins=num_bins,
            max_nodes=max_nodes, valid_bins=valid_bins,
            valid_mparams=valid_mparams,
            train_metric_fns=train_metric_fns, train_mparams=train_mparams,
            valid_metric_fns=valid_metric_fns, health_fn=health_fn,
            goss_fn=goss_fn)
        xs = ((row_masks, feat_masks) if goss_fn is None
              else (row_masks, feat_masks, goss_iters))
        (score, vscores), (stacked, mvals, hvals) = jax.lax.scan(
            body, (score, tuple(valid_scores)), xs)
        return score, vscores, stacked, mvals, hvals

    from .. import costmodel
    prog = costmodel.instrument("chunk/serial", jax.jit(chunk_fn),
                                phase="train_chunk")
    _CHUNK_PROGRAMS[key] = prog
    return prog


def make_goss_fn(goss):
    """In-program GOSS selection over FULL rows (the serial chunk scan
    and the feature-parallel chunk, whose rows are replicated): the
    per-iteration ``_goss_masks`` draw traced into the chunk body.
    ``goss`` is the static ``(seed, top_cnt, other_cnt, amp)`` tuple;
    the key stream is ``fold_in(PRNGKey(seed), iteration)`` — exactly
    the per-iteration path's, so fused == per-iteration selection is
    bit-identical.  The data-parallel variant (gathered global scores,
    padded-row layouts) lives in parallel/learners.chunk_program."""
    seed, top_cnt, other_cnt, amp = goss
    from ..ops import sampling as _sampling

    def goss_fn(it, grad, hess):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), it)
        mask, w = _sampling.goss_mask_weights(
            key, _sampling.goss_row_scores(grad), top_cnt, other_cnt,
            amp)
        return grad * w, hess * w, mask
    return goss_fn


def _tuning_kwargs(hist_chunk: int, hist_dtype: str,
                   quant_rounding: str = "nearest") -> dict:
    """Grower kwargs for the TPU tuning knobs (TreeConfig extensions)."""
    kwargs = {}
    if hist_chunk > 0:
        kwargs["hist_chunk"] = hist_chunk
    if hist_dtype == "bfloat16":
        kwargs["compute_dtype"] = jnp.bfloat16
    elif hist_dtype == "int8":
        # string sentinel (hashable jit static): quantized-gradient path,
        # dispatched per backend in the histogram ops; the "_sr" variant
        # rounds stochastically (unbiased, value-keyed bits)
        kwargs["compute_dtype"] = ("int8_sr"
                                   if quant_rounding == "stochastic"
                                   else "int8")
    return kwargs


def leafwise_compact_on(tree_config) -> bool:
    """Single home of the leafwise_compact resolution rule: "auto" means
    on for the TPU backend (the compacted grower's Pallas partition is
    TPU-scheduled; CPU keeps the masked grower so golden tests stay on
    the historical path), explicit "true"/"false" win.  Shared by the
    serial learner, both chunk-program builders, and the data-parallel
    learner."""
    c = getattr(tree_config, "leafwise_compact", "auto")
    if c == "auto":
        return jax.default_backend() == "tpu"
    return c == "true"


def _serial_learner(gbdt: GBDT, bins, grad, hess, row_mask, feature_mask):
    """Default learner: single-device tree growth, leaf-wise (reference
    parity) or depth-wise (TPU throughput) per ``grow_policy``."""
    kwargs = dict(
        num_leaves=_effective_num_leaves(gbdt.tree_config),
        num_bins_max=gbdt.num_bins_max,
        min_data_in_leaf=gbdt.tree_config.min_data_in_leaf,
        min_sum_hessian_in_leaf=gbdt.tree_config.min_sum_hessian_in_leaf,
        max_depth=gbdt.tree_config.max_depth,
        packing=gbdt._pack_spec,
        **_tuning_kwargs(gbdt.tree_config.hist_chunk,
                         gbdt.tree_config.hist_dtype,
                         gbdt.tree_config.quant_rounding))
    if gbdt.tree_config.grow_policy == "depthwise":
        from .grower_depthwise import grow_tree_depthwise_jit
        return grow_tree_depthwise_jit(bins, grad, hess, row_mask,
                                       feature_mask, gbdt.num_bins_device,
                                       **kwargs)
    if leafwise_compact_on(gbdt.tree_config):
        # compacted growth subsumes leafwise_segments: each split touches
        # only the smaller child's rows, so whole-tree dispatches stay
        # short even at bench scale (grower_leafcompact.py)
        from ..ops.compact import pallas_partition_ok, partition_overlap_on
        from .grower_leafcompact import grow_tree_leafcompact
        # both bits are jit STATICS, so an env flip re-dispatches here
        # (the chunk-program caches carry them in their keys instead)
        return grow_tree_leafcompact(
            bins, grad, hess, row_mask, feature_mask, gbdt.num_bins_device,
            use_pallas_partition=pallas_partition_ok(gbdt.num_features),
            partition_overlap=partition_overlap_on(),
            **kwargs)
    segments = getattr(gbdt.tree_config, "leafwise_segments", 1)
    if segments > 1:
        from .grower import grow_tree_segmented
        return grow_tree_segmented(
            bins, grad, hess, row_mask, feature_mask, gbdt.num_bins_device,
            segments=segments, **kwargs)
    return grow_tree(
        bins, grad, hess, row_mask, feature_mask, gbdt.num_bins_device,
        **kwargs)


def _replay_tree(score, bins, tree, cls_m: int, max_nodes: int,
                 feat_map=None):
    """Apply one host tree's score contribution to class ``cls_m`` of a
    [C, N] score by replaying the split sequence on the binned matrix —
    the chunk rollback's rebuild rule, factored out of
    ``_rollback_chunk``.  NOT bitwise-equal to the in-grow f32 update:
    the host tree's shrunk leaf values went through an f64
    learning-rate product, which can round 1 ulp away from the device's
    f32 product — both rollback sides share this path, so the rollback
    equivalence pins hold; checkpoints store raw scores instead
    (lightgbm_tpu/checkpoint.py).

    ``feat_map``: canonical inner feature -> row of ``bins``; the TRAIN
    matrix is in packed (mixed-bin) feature order while
    ``tree.split_feature`` is canonical, valid matrices are canonical."""
    pad = lambda a: np.pad(np.asarray(a), (0, max_nodes - len(a)))
    sf = np.asarray(tree.split_feature)
    if feat_map is not None and len(sf):
        sf = feat_map[sf]
    leaf_vals = np.zeros(max_nodes + 1, np.float32)
    leaf_vals[:tree.num_leaves] = tree.leaf_value
    new_cls = add_tree_score(
        bins, score[cls_m],
        pad(sf),
        pad(tree.threshold_bin),
        pad(tree.left_child),
        pad(tree.right_child),
        leaf_vals,
        np.int32(tree.num_leaves),
        max_nodes=max_nodes)
    if isinstance(score, np.ndarray):
        # multi-process valid scores stay host-side numpy
        score = score.copy()
        score[cls_m] = np.asarray(new_cls)
        return score
    return score.at[cls_m].set(new_cls)


def _effective_num_leaves(tree_config) -> int:
    """num_leaves capped by 2^(max_depth-1) (config.h:159-163)."""
    n = tree_config.num_leaves
    if tree_config.max_depth > 0:
        n = min(n, 1 << (tree_config.max_depth - 1))
    return max(n, 2)


def _fmt(x: float) -> str:
    return repr(float(x))
