"""Leaf-wise tree grower — the device-side tree learner.

TPU-native re-design of SerialTreeLearner
(/root/reference/src/treelearner/serial_tree_learner.cpp:10-440).  The whole
tree grows inside ONE jitted function: a ``lax.fori_loop`` over the
``num_leaves - 1`` splits with fully static shapes, so a boosting iteration is
a single XLA program with no host round-trips per split.

Inversions of the reference's pointer design (SURVEY §7.0):
- DataPartition's permuted index lists (data_partition.hpp) become a
  ``[N]`` leaf-id vector; Split is a masked where-update.
- The LRU histogram pool (utils/lru_pool.h) becomes a dense
  ``[num_leaves, F, B, 3]`` histogram cache carried through the loop.
- The smaller-leaf + histogram-subtraction trick
  (serial_tree_learner.cpp:262-283, feature_histogram.hpp:91-100) is kept:
  each split builds ONE masked histogram (the smaller child) and derives the
  sibling by parent − smaller.
- Data-dependent leaf choice (serial_tree_learner.cpp:140-150) is a masked
  argmax over per-leaf candidate gains; early stop (best gain ≤ 0) is a
  ``done`` flag that short-circuits the remaining iterations via lax.cond.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histogram
from ..ops.split import SplitResult, find_best_split


class TreeArrays(NamedTuple):
    """Fixed-shape device tree (mirrors tree.h:124-149)."""
    num_leaves: jax.Array       # i32 scalar
    split_feature: jax.Array    # [L-1] i32
    threshold_bin: jax.Array    # [L-1] i32
    split_gain: jax.Array       # [L-1] f32
    left_child: jax.Array       # [L-1] i32 (~leaf encoding)
    right_child: jax.Array      # [L-1] i32
    leaf_parent: jax.Array      # [L] i32
    leaf_value: jax.Array       # [L] f32
    leaf_count: jax.Array       # [L] i32
    leaf_ids: jax.Array         # [N] i32 — final row → leaf partition


class _GrowState(NamedTuple):
    tree: TreeArrays
    hist_cache: jax.Array       # [L, F, B, 3]
    cand_gain: jax.Array        # [L]
    cand_feature: jax.Array     # [L]
    cand_threshold: jax.Array   # [L]
    cand_left_out: jax.Array    # [L]
    cand_right_out: jax.Array
    cand_left_cnt: jax.Array    # [L] i32
    cand_right_cnt: jax.Array
    cand_left_g: jax.Array
    cand_left_h: jax.Array
    cand_right_g: jax.Array
    cand_right_h: jax.Array
    leaf_sum_g: jax.Array       # [L]
    leaf_sum_h: jax.Array
    leaf_cnt: jax.Array         # [L] i32
    leaf_depth: jax.Array       # [L] i32
    done: jax.Array             # bool scalar


def _grow_tree_fn(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                  row_mask: jax.Array, feature_mask: jax.Array,
                  num_bins: jax.Array, *, num_leaves: int, num_bins_max: int,
                  min_data_in_leaf: int, min_sum_hessian_in_leaf: float,
                  max_depth: int = -1, hist_backend: str = "matmul",
                  hist_chunk: int = 16384,
                  compute_dtype=jnp.float32, packing=None) -> TreeArrays:
    """Grow one tree on a single device (TreeLearner::Train,
    serial_tree_learner.cpp:119-153).  See ``grow_tree_impl`` for the
    customization seam used by the parallel learners.
    """
    return grow_tree_impl(
        bins, grad, hess, row_mask, feature_mask, num_bins,
        num_leaves=num_leaves, num_bins_max=num_bins_max,
        min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian_in_leaf=min_sum_hessian_in_leaf,
        max_depth=max_depth, hist_backend=hist_backend,
        hist_chunk=hist_chunk, compute_dtype=compute_dtype,
        packing=packing)


# module-level jit shared across boosters, wrapped in the cost registry
# (lightgbm_tpu/costmodel.py): with telemetry armed, the compiled program's
# cost_analysis/compile seconds feed the roofline/compile blocks
from .. import costmodel as _costmodel  # noqa: E402 (after jax imports)

grow_tree = _costmodel.instrument(
    "grow/leafwise",
    jax.jit(_grow_tree_fn,
            static_argnames=("num_leaves", "num_bins_max",
                             "min_data_in_leaf", "min_sum_hessian_in_leaf",
                             "max_depth", "hist_backend", "hist_chunk",
                             "compute_dtype", "packing")),
    phase="grow")


def grow_tree_impl(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                   row_mask: jax.Array, feature_mask: jax.Array,
                   num_bins: jax.Array, *, num_leaves: int, num_bins_max: int,
                   min_data_in_leaf: int, min_sum_hessian_in_leaf: float,
                   max_depth: int = -1, hist_backend: str = "matmul",
                   hist_chunk: int = 16384, compute_dtype=jnp.float32,
                   packing=None,
                   hist_reduce=None, hist_axis=None, int_hist_reduce=None,
                   split_finder=None, partition_bins=None,
                   stat_reduce=None, own_slice=None, root_hist_reduce=None,
                   init_state=None, loop_count=None,
                   return_state: bool = False):
    """Core grower (not jitted; callers wrap it).

    Parameters
    ----------
    bins : [F, N] integer bin matrix (the Dataset layout; N may be the local
        row shard under shard_map)
    grad, hess : [N] f32 gradients/hessians from the objective
    row_mask : [N] bool — bagging × validity mask; masked rows still get leaf
        ids (OOB score updates come free, unlike gbdt.cpp:159-165)
    feature_mask : [F] bool — feature_fraction sample
        (serial_tree_learner.cpp:159-167), possibly ∧ per-shard feature
        ownership for the feature-parallel learner
    num_bins : [F] i32 real bin counts
    packing : optional io/binning.PackSpec (STATIC) — mixed-bin layout:
        ``bins`` is stored in packed bin-width-class feature order; the
        histogram routes run one pass per class and hand back
        CANONICAL-order histograms, so num_bins/feature_mask/split
        results stay canonical.  Only partition-time feature indexing
        translates through the spec's canonical->packed map.
    hist_reduce : optional callable hist→hist; the data-parallel learner
        passes ``lambda h: psum(h, 'data')`` (the ReduceScatter+Allgather
        contract of data_parallel_tree_learner.cpp:135-165).  Under the
        reduce_scatter ownership schedule it is instead a feature-block
        psum_scatter, so every histogram (and the cache) holds only this
        shard's OWNED feature block — the split_finder must then be the
        owned-search + SplitInfo-allreduce composite and feature_mask /
        num_bins the owned slices (learners._scatter_grow_fn_leafwise)
    int_hist_reduce : optional int-domain feature-block scatter for the
        quantized path (forwarded to build_histogram's int_reduce so the
        accumulators never leave the exact int domain)
    split_finder : optional callable with find_best_split's signature; the
        feature-parallel learner wraps it with the packed SplitInfo argmax
        allreduce (feature_parallel_tree_learner.cpp:46-79) and must return
        GLOBAL feature indices
    partition_bins : optional [F_global, N] matrix used to apply splits; the
        feature-parallel learner histograms only its OWNED feature slice
        (``bins``) but applies splits on the replicated full matrix, exactly
        like the reference where every worker holds all data and Split is
        local (feature_parallel_tree_learner.cpp:9-81)
    init_state / loop_count / return_state : dispatch-segmentation seam
        (grow_tree_segmented): resume from a carried _GrowState instead of
        the root init, run only ``loop_count`` split attempts, and return
        the full state so the caller can continue in a later dispatch.  The
        body never reads the loop index, so splitting fori_loop(0, L-1)
        into count-sized pieces is EXACTLY the same program.
    """
    F, N = bins.shape
    L = num_leaves
    B = num_bins_max
    f32 = jnp.float32
    finder = split_finder or find_best_split
    if partition_bins is None:
        partition_bins = bins
    # wire-metrics hook point (ISSUE 5): any seam not already labeled by
    # the learner that built it (telemetry.collective_span passes wrapped
    # fns through) gets a grower-generic site here, so custom learners'
    # collectives still show up in the interconnect block.  The wrappers
    # call the seam unchanged — traced programs are bit-identical.
    from .. import telemetry as _tl
    hist_reduce = _tl.collective_span(
        "leafwise/hist_reduce", hist_reduce, kind="reduce", axis=hist_axis,
        loop=L - 1, phase="grow")
    int_hist_reduce = _tl.collective_span(
        "leafwise/int_hist_reduce", int_hist_reduce, kind="reduce",
        axis=hist_axis, loop=L - 1, phase="grow")
    stat_reduce = _tl.collective_span(
        "leafwise/root_stats", stat_reduce, kind="reduce", axis=hist_axis,
        phase="grow")
    root_hist_reduce = _tl.collective_span(
        "leafwise/root_hist", root_hist_reduce, kind="reduce",
        axis=hist_axis, phase="grow")

    def hist_of(mask, salt=0):
        hist = build_histogram(bins, grad, hess, mask, B,
                               backend=hist_backend, chunk=hist_chunk,
                               compute_dtype=compute_dtype,
                               axis_name=hist_axis,
                               int_reduce=int_hist_reduce, salt=salt,
                               packing=packing)
        # the quantized path reduces its INT accumulators internally over
        # hist_axis (bit-exactness; ops/hist_pallas.quantize_values) —
        # psum by default, the ownership feature-block scatter when
        # int_hist_reduce is set
        if hist_reduce is not None and not (
                str(compute_dtype).startswith("int8")
                and hist_axis is not None):
            hist = hist_reduce(hist)
        return hist

    def best_of(hist, sum_g, sum_h, cnt, depth):
        res = finder(hist, sum_g, sum_h, cnt, num_bins, feature_mask,
                     float(min_data_in_leaf),
                     float(min_sum_hessian_in_leaf))
        if max_depth > 0:
            # depth-limited leaves cannot split (serial_tree_learner.cpp:240-249)
            blocked = depth >= max_depth
            res = res._replace(gain=jnp.where(blocked, -jnp.inf, res.gain))
        return res

    # ---- root init (BeforeTrain, serial_tree_learner.cpp:155-236);
    # skipped entirely when resuming from a carried state (segmentation)
    def _root_state() -> _GrowState:
        if own_slice is not None:
            # ownership (reduce_scatter) schedule: build the ROOT
            # replicated — full F, plain psum — so root stats are exact on
            # every shard including feature-PADDING shards (whose owned
            # block is all zeros), then cache only the owned slice.  The
            # depthwise scatter path does the same (learners.py own_slice).
            full = build_histogram(bins, grad, hess, row_mask, B,
                                   backend=hist_backend, chunk=hist_chunk,
                                   compute_dtype=compute_dtype,
                                   axis_name=hist_axis, packing=packing)
            if root_hist_reduce is not None and not (
                    str(compute_dtype).startswith("int8")
                    and hist_axis is not None):
                full = root_hist_reduce(full)
            root_hist = own_slice(full)
        else:
            full = root_hist = hist_of(row_mask)
        if str(compute_dtype).startswith("int8"):
            # quantized mode: derive root stats from the histogram — the
            # int accumulators are bit-identical across serial/
            # data-parallel (see grower_depthwise.py root-stat note), and
            # any feature's bins sum to the same exact quantized totals, so
            # this also holds under feature-parallel ownership slices
            # (``full``: under the reduce_scatter schedule the stats must
            # come from the replicated full-F root, not the owned block —
            # a feature-padding shard's block is all zeros)
            root_stats = jnp.sum(full[0], axis=0)
        else:
            # root sums come from the gradient vectors, not from any one
            # feature's histogram: per-feature f32 bin-order rounding would
            # make the totals shard-dependent under feature-parallel
            # ownership (the reference likewise computes root sums once
            # from gradients, serial_tree_learner.cpp:178-198 /
            # data_parallel root-sum allreduce)
            maskf = row_mask.astype(f32)
            root_stats = jnp.stack([jnp.sum(grad * maskf),
                                    jnp.sum(hess * maskf), jnp.sum(maskf)])
            if stat_reduce is not None:
                root_stats = stat_reduce(root_stats)
        root_g, root_h, root_c = root_stats[0], root_stats[1], root_stats[2]
        root_best = best_of(root_hist, root_g, root_h, root_c,
                            jnp.asarray(1, jnp.int32))

        neg_inf = jnp.full((L,), -jnp.inf, dtype=f32)
        zeros_i = jnp.zeros((L,), dtype=jnp.int32)
        zeros_f = jnp.zeros((L,), dtype=f32)

        tree = TreeArrays(
            num_leaves=jnp.asarray(1, jnp.int32),
            split_feature=jnp.zeros((L - 1,), jnp.int32),
            threshold_bin=jnp.zeros((L - 1,), jnp.int32),
            split_gain=jnp.zeros((L - 1,), f32),
            left_child=jnp.zeros((L - 1,), jnp.int32),
            right_child=jnp.zeros((L - 1,), jnp.int32),
            leaf_parent=jnp.full((L,), -1, jnp.int32),
            leaf_value=zeros_f,
            leaf_count=zeros_i.at[0].set(root_c.astype(jnp.int32)),
            leaf_ids=jnp.zeros((N,), jnp.int32),
        )
        return _GrowState(
            tree=tree,
            hist_cache=jnp.zeros((L,) + root_hist.shape,
                                 f32).at[0].set(root_hist),
            cand_gain=neg_inf.at[0].set(root_best.gain),
            cand_feature=zeros_i.at[0].set(root_best.feature),
            cand_threshold=zeros_i.at[0].set(root_best.threshold),
            cand_left_out=zeros_f.at[0].set(root_best.left_output),
            cand_right_out=zeros_f.at[0].set(root_best.right_output),
            cand_left_cnt=zeros_i.at[0].set(root_best.left_count),
            cand_right_cnt=zeros_i.at[0].set(root_best.right_count),
            cand_left_g=zeros_f.at[0].set(root_best.left_sum_grad),
            cand_left_h=zeros_f.at[0].set(root_best.left_sum_hess),
            cand_right_g=zeros_f.at[0].set(root_best.right_sum_grad),
            cand_right_h=zeros_f.at[0].set(root_best.right_sum_hess),
            leaf_sum_g=zeros_f.at[0].set(root_g),
            leaf_sum_h=zeros_f.at[0].set(root_h),
            leaf_cnt=zeros_i.at[0].set(root_c.astype(jnp.int32)),
            leaf_depth=zeros_i.at[0].set(1),
            done=jnp.asarray(False),
        )

    state = init_state if init_state is not None else _root_state()

    def body(_, state: _GrowState) -> _GrowState:
        # pick the best leaf to split (FindBestSplitsForLeaves argmax,
        # serial_tree_learner.cpp:140-147)
        best_leaf = jnp.argmax(state.cand_gain).astype(jnp.int32)
        best_gain = state.cand_gain[best_leaf]
        should_split = jnp.logical_and(~state.done, best_gain > 0.0)

        def do_split(state: _GrowState) -> _GrowState:
            tree = state.tree
            bl = best_leaf
            nl = tree.num_leaves
            node = nl - 1
            new_leaf = nl

            feat = state.cand_feature[bl]
            thr = state.cand_threshold[bl]

            # --- record the node (Tree::Split, tree.cpp:50-83)
            p = tree.leaf_parent[bl]
            pp = jnp.maximum(p, 0)
            lc_at_p = jnp.where((p >= 0) & (tree.left_child[pp] == ~bl),
                                node, tree.left_child[pp])
            rc_at_p = jnp.where((p >= 0) & (tree.right_child[pp] == ~bl),
                                node, tree.right_child[pp])
            left_child = tree.left_child.at[pp].set(lc_at_p).at[node].set(~bl)
            right_child = (tree.right_child.at[pp].set(rc_at_p)
                           .at[node].set(~new_leaf))

            # --- partition rows (DataPartition::Split as masked where,
            # data_partition.hpp:93-139).  Under mixed-bin packing the
            # matrix rows are in packed order while ``feat`` is canonical:
            # translate through the (trace-time constant) c2p map
            pfeat = feat
            if packing is not None and len(packing.widths) > 1:
                pfeat = jnp.asarray(packing.c2p, jnp.int32)[feat]
            fbin = jax.lax.dynamic_index_in_dim(
                partition_bins, pfeat, axis=0, keepdims=False).astype(jnp.int32)
            go_right = fbin > thr
            leaf_ids = jnp.where((tree.leaf_ids == bl) & go_right,
                                 new_leaf, tree.leaf_ids)

            # --- child histograms: build the smaller, subtract for the larger
            # (serial_tree_learner.cpp:262-283)
            lcnt = state.cand_left_cnt[bl]
            rcnt = state.cand_right_cnt[bl]
            left_is_smaller = lcnt <= rcnt
            small_leaf = jnp.where(left_is_smaller, bl, new_leaf)
            small_mask = row_mask & (leaf_ids == small_leaf)
            # salt = the new leaf index: varies per split pass so the
            # stochastic-rounding bits decorrelate across passes
            small_hist = hist_of(small_mask, salt=new_leaf)
            parent_hist = state.hist_cache[bl]
            large_hist = parent_hist - small_hist
            lhist = jnp.where(left_is_smaller, small_hist, large_hist)
            rhist = jnp.where(left_is_smaller, large_hist, small_hist)
            hist_cache = state.hist_cache.at[bl].set(lhist).at[new_leaf].set(rhist)

            # --- child stats
            lg, lh = state.cand_left_g[bl], state.cand_left_h[bl]
            rg, rh = state.cand_right_g[bl], state.cand_right_h[bl]
            depth = state.leaf_depth[bl] + 1

            # --- new candidate splits for both children
            lbest = best_of(lhist, lg, lh, lcnt.astype(f32), depth)
            rbest = best_of(rhist, rg, rh, rcnt.astype(f32), depth)

            tree = tree._replace(
                num_leaves=nl + 1,
                split_feature=tree.split_feature.at[node].set(feat),
                threshold_bin=tree.threshold_bin.at[node].set(thr),
                split_gain=tree.split_gain.at[node].set(best_gain),
                left_child=left_child,
                right_child=right_child,
                leaf_parent=tree.leaf_parent.at[bl].set(node)
                                            .at[new_leaf].set(node),
                leaf_value=tree.leaf_value.at[bl].set(state.cand_left_out[bl])
                                          .at[new_leaf].set(state.cand_right_out[bl]),
                leaf_count=tree.leaf_count.at[bl].set(lcnt)
                                          .at[new_leaf].set(rcnt),
                leaf_ids=leaf_ids,
            )
            return state._replace(
                tree=tree,
                hist_cache=hist_cache,
                cand_gain=state.cand_gain.at[bl].set(lbest.gain)
                                         .at[new_leaf].set(rbest.gain),
                cand_feature=state.cand_feature.at[bl].set(lbest.feature)
                                               .at[new_leaf].set(rbest.feature),
                cand_threshold=state.cand_threshold.at[bl].set(lbest.threshold)
                                                   .at[new_leaf].set(rbest.threshold),
                cand_left_out=state.cand_left_out.at[bl].set(lbest.left_output)
                                                 .at[new_leaf].set(rbest.left_output),
                cand_right_out=state.cand_right_out.at[bl].set(lbest.right_output)
                                                   .at[new_leaf].set(rbest.right_output),
                cand_left_cnt=state.cand_left_cnt.at[bl].set(lbest.left_count)
                                                 .at[new_leaf].set(rbest.left_count),
                cand_right_cnt=state.cand_right_cnt.at[bl].set(lbest.right_count)
                                                   .at[new_leaf].set(rbest.right_count),
                cand_left_g=state.cand_left_g.at[bl].set(lbest.left_sum_grad)
                                             .at[new_leaf].set(rbest.left_sum_grad),
                cand_left_h=state.cand_left_h.at[bl].set(lbest.left_sum_hess)
                                             .at[new_leaf].set(rbest.left_sum_hess),
                cand_right_g=state.cand_right_g.at[bl].set(lbest.right_sum_grad)
                                               .at[new_leaf].set(rbest.right_sum_grad),
                cand_right_h=state.cand_right_h.at[bl].set(lbest.right_sum_hess)
                                               .at[new_leaf].set(rbest.right_sum_hess),
                leaf_sum_g=state.leaf_sum_g.at[bl].set(lg).at[new_leaf].set(rg),
                leaf_sum_h=state.leaf_sum_h.at[bl].set(lh).at[new_leaf].set(rh),
                leaf_cnt=state.leaf_cnt.at[bl].set(lcnt).at[new_leaf].set(rcnt),
                leaf_depth=state.leaf_depth.at[bl].set(depth)
                                           .at[new_leaf].set(depth),
            )

        def no_split(state: _GrowState) -> _GrowState:
            return state._replace(done=jnp.asarray(True))

        # profiler alignment (ISSUE 2): the whole split body is labeled in
        # HLO metadata so profile_dir= traces group the per-split ops
        with jax.named_scope("leafwise_split"):
            return jax.lax.cond(should_split, do_split, no_split, state)

    count = L - 1 if loop_count is None else loop_count
    state = jax.lax.fori_loop(0, count, body, state)
    return state if return_state else state.tree


_GROW_STATICS = ("num_leaves", "num_bins_max", "min_data_in_leaf",
                 "min_sum_hessian_in_leaf", "max_depth", "hist_backend",
                 "hist_chunk", "compute_dtype", "packing")


@functools.partial(jax.jit, static_argnames=_GROW_STATICS)
def _grow_init(bins, grad, hess, row_mask, feature_mask, num_bins,
               **kwargs) -> _GrowState:
    return grow_tree_impl(bins, grad, hess, row_mask, feature_mask,
                          num_bins, loop_count=0, return_state=True,
                          **kwargs)


# donate the carried state: without aliasing, input and output copies of
# hist_cache [L,F,B,3] + leaf_ids [N] (~120 MB at bench scale) would both
# be live at every segment boundary
@functools.partial(jax.jit, static_argnames=_GROW_STATICS + ("loop_count",),
                   donate_argnums=(6,))
def _grow_segment(bins, grad, hess, row_mask, feature_mask, num_bins,
                  state, *, loop_count, **kwargs) -> _GrowState:
    return grow_tree_impl(bins, grad, hess, row_mask, feature_mask,
                          num_bins, init_state=state,
                          loop_count=loop_count, return_state=True,
                          **kwargs)


def grow_tree_segmented(bins, grad, hess, row_mask, feature_mask, num_bins,
                        *, segments: int, **kwargs) -> TreeArrays:
    """grow_tree split across ``segments`` device dispatches.

    A 255-leaf leaf-wise tree is 254 sequential full-data histogram passes
    in ONE XLA dispatch; at tens of millions of rows that single dispatch
    can run minutes (and trips this environment's ~60 s per-dispatch
    execution watchdog, BASELINE.md).  The split loop's body never reads
    the loop index, so running fori_loop(0, L-1) as ceil((L-1)/segments)-
    sized pieces with the _GrowState carried device-resident between
    dispatches is program-identical — same trees, bit for bit.  Equal-size
    segments share one compiled program (the count, not the start, is the
    static).
    """
    L = kwargs["num_leaves"]
    total = max(L - 1, 1)
    per = -(-total // max(segments, 1))
    state = _grow_init(bins, grad, hess, row_mask, feature_mask, num_bins,
                       **kwargs)
    done = 0
    while done < total:
        n = min(per, total - done)
        state = _grow_segment(bins, grad, hess, row_mask, feature_mask,
                              num_bins, state, loop_count=n, **kwargs)
        done += n
    return state.tree
