"""Leaf-wise grower — compat shim over ``models/grower_unified.py``.

The three grower modules were collapsed into ONE schedule-parameterized
grower (ISSUE 9): growth policy (leafwise/depthwise/leafcompact) and a
declarative :class:`~.grower_unified.SeamSchedule` are parameters there;
this module keeps the historical leaf-wise entry points (``grow_tree``,
``grow_tree_impl`` with keyword seams, ``grow_tree_segmented``) plus the
patchable ``build_histogram`` attribute, and nothing else — the graftlint
AST pass (ISSUE 10) proved the old ``BIG``/``TreeArrays``/``_GrowState``/
``_grow_init``/``_grow_segment`` re-exports unreferenced outside
``grower_unified`` itself, and tests/test_graftlint.py pins this surface
so dead exports cannot regrow.  New code should import from
``grower_unified`` directly.
"""
from __future__ import annotations

import jax.numpy as jnp

# patchable histogram seam: tests/scripts monkeypatch THIS attribute
# (the unified grower resolves it through this module at trace time)
from ..ops.histogram import build_histogram  # noqa: F401

from .grower_unified import (  # noqa: F401
    SeamSchedule, grow_tree, grow_tree_segmented, grow_tree_unified)


def grow_tree_impl(bins, grad, hess, row_mask, feature_mask, num_bins, *,
                   num_leaves: int, num_bins_max: int,
                   min_data_in_leaf: int, min_sum_hessian_in_leaf: float,
                   max_depth: int = -1, hist_backend: str = "matmul",
                   hist_chunk: int = 16384, compute_dtype=jnp.float32,
                   packing=None,
                   hist_reduce=None, hist_axis=None, int_hist_reduce=None,
                   split_finder=None, partition_bins=None,
                   stat_reduce=None, own_slice=None, root_hist_reduce=None,
                   init_state=None, loop_count=None,
                   return_state: bool = False):
    """Historical keyword-seam surface over
    ``grow_tree_unified(policy="leafwise")`` — the individual seam kwargs
    assemble into one SeamSchedule."""
    schedule = SeamSchedule(
        hist_axis=hist_axis, hist_reduce=hist_reduce,
        int_hist_reduce=int_hist_reduce, stat_reduce=stat_reduce,
        root_hist_reduce=root_hist_reduce, own_slice=own_slice,
        split_finder=split_finder)
    return grow_tree_unified(
        bins, grad, hess, row_mask, feature_mask, num_bins,
        policy="leafwise", num_leaves=num_leaves,
        num_bins_max=num_bins_max, min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian_in_leaf=min_sum_hessian_in_leaf,
        max_depth=max_depth, hist_backend=hist_backend,
        hist_chunk=hist_chunk, compute_dtype=compute_dtype,
        packing=packing, schedule=schedule, partition_bins=partition_bins,
        init_state=init_state, loop_count=loop_count,
        return_state=return_state)
