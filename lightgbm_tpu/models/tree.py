"""Tree model: fixed-shape arrays + reference-compatible text format.

Re-design of /root/reference/src/io/tree.cpp and include/LightGBM/tree.h.
The node encoding is identical (internal node k was created by the k-th
split; leaf references are stored bitwise-complemented, ``~leaf``;
tree.cpp:50-83), so the text format round-trips with the reference's
``Tree::ToString`` / ``Tree(string)`` (tree.cpp:111-180).

TPU-first difference: prediction is not a per-row pointer walk
(tree.h:163-187) but a vectorized REPLAY of the split sequence — node k
always split leaf ``~left_child[k]`` into (that leaf, leaf k+1), so applying
the recorded splits in creation order reassigns every row's leaf id with
[num_leaves-1] masked vector ops.  This is exactly the partition the grower
performed, and works for both binned matrices and raw feature values.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..utils import log


class Tree:
    """One decision tree (flat arrays, tree.h:124-149)."""

    def __init__(self, num_leaves: int,
                 split_feature: np.ndarray,       # inner feature idx [L-1]
                 split_feature_real: np.ndarray,  # original column idx [L-1]
                 threshold_bin: np.ndarray,       # [L-1]
                 threshold: np.ndarray,           # real-valued [L-1] float64
                 split_gain: np.ndarray,          # [L-1]
                 left_child: np.ndarray,          # [L-1] (~leaf encoding)
                 right_child: np.ndarray,         # [L-1]
                 leaf_parent: np.ndarray,         # [L]
                 leaf_value: np.ndarray):         # [L] float64
        self.num_leaves = int(num_leaves)
        n = self.num_leaves
        self.split_feature = np.asarray(split_feature, dtype=np.int32)[:n - 1]
        self.split_feature_real = np.asarray(split_feature_real,
                                             dtype=np.int32)[:n - 1]
        self.threshold_bin = np.asarray(threshold_bin, dtype=np.int32)[:n - 1]
        self.threshold = np.asarray(threshold, dtype=np.float64)[:n - 1]
        self.split_gain = np.asarray(split_gain, dtype=np.float64)[:n - 1]
        self.left_child = np.asarray(left_child, dtype=np.int32)[:n - 1]
        self.right_child = np.asarray(right_child, dtype=np.int32)[:n - 1]
        self.leaf_parent = np.asarray(leaf_parent, dtype=np.int32)[:n]
        self.leaf_value = np.asarray(leaf_value, dtype=np.float64)[:n]

    def shrinkage(self, rate: float) -> None:
        """Scale leaf outputs by the learning rate (tree.h:94-98)."""
        self.leaf_value = self.leaf_value * rate

    # ----------------------------------------------------------- prediction

    def leaf_index_by_replay(self, feature_values: np.ndarray) -> np.ndarray:
        """Vectorized leaf assignment from RAW feature values.

        ``feature_values`` is [N, num_total_features] in the original column
        space; comparisons are ``value <= threshold`` → left (tree.h:177-187).
        """
        n_rows = feature_values.shape[0]
        leaf = np.zeros(n_rows, dtype=np.int32)
        split_leaf = self._split_leaf_sequence()
        for k in range(self.num_leaves - 1):
            col = self.split_feature_real[k]
            go_right = feature_values[:, col] > self.threshold[k]
            leaf = np.where((leaf == split_leaf[k]) & go_right,
                            np.int32(k + 1), leaf)
        return leaf

    def leaf_index_by_replay_binned(self, bins: np.ndarray) -> np.ndarray:
        """Same replay on a binned [F, N] matrix (training-data path,
        compare ``bin <= threshold_bin``)."""
        n_rows = bins.shape[1]
        leaf = np.zeros(n_rows, dtype=np.int32)
        split_leaf = self._split_leaf_sequence()
        for k in range(self.num_leaves - 1):
            go_right = bins[self.split_feature[k]] > self.threshold_bin[k]
            leaf = np.where((leaf == split_leaf[k]) & go_right,
                            np.int32(k + 1), leaf)
        return leaf

    def _split_leaf_sequence(self) -> np.ndarray:
        """leaf id split by each node, in creation order.

        Node k's right child is always the NEW leaf ``~(k+1)``
        (tree.cpp:70-71), so the left child at creation time was the old leaf.
        When ``left_child[k]`` is still a leaf (< 0) that's ``~left_child[k]``;
        when it later became node m, the old leaf id is recorded in
        ``leaf_parent``: the leaf l with ``leaf_parent[l] == k`` and
        ``l != k+1``... reconstruction is simpler top-down: replay
        structurally.
        """
        if self.num_leaves <= 1:
            return np.zeros(0, dtype=np.int32)
        split_leaf = np.zeros(self.num_leaves - 1, dtype=np.int32)
        # simulate: leaves start {0}; node k splits some current leaf l into
        # (l, k+1).  Which leaf? The one whose descendant chain reaches node
        # k.  Walk the tree: root node 0 split leaf 0.  For node k>0, its
        # parent node p has it as left or right child; the leaf id it split
        # is the leaf id that traveled down that edge: left edge keeps the
        # parent's split leaf id, right edge carries p+1.
        parent_node = np.full(self.num_leaves - 1, -1, dtype=np.int32)
        is_left_edge = np.zeros(self.num_leaves - 1, dtype=bool)
        for k in range(self.num_leaves - 1):
            lc, rc = self.left_child[k], self.right_child[k]
            if lc >= 0:
                parent_node[lc] = k
                is_left_edge[lc] = True
            if rc >= 0:
                parent_node[rc] = k
                is_left_edge[rc] = False
        for k in range(self.num_leaves - 1):
            if k == 0:
                split_leaf[k] = 0
            else:
                p = parent_node[k]
                split_leaf[k] = split_leaf[p] if is_left_edge[k] else p + 1
        return split_leaf

    def predict(self, feature_values: np.ndarray) -> np.ndarray:
        """Batch raw-feature prediction → leaf outputs."""
        if self.num_leaves == 1:
            return np.full(feature_values.shape[0], self.leaf_value[0])
        return self.leaf_value[self.leaf_index_by_replay(feature_values)]

    def predict_binned(self, bins: np.ndarray) -> np.ndarray:
        if self.num_leaves == 1:
            return np.full(bins.shape[1], self.leaf_value[0])
        return self.leaf_value[self.leaf_index_by_replay_binned(bins)]

    # ------------------------------------------------------------ text form

    def to_string(self) -> str:
        """Tree::ToString (tree.cpp:111-130) — same keys, same order."""
        n = self.num_leaves
        lines = [
            f"num_leaves={n}",
            "split_feature=" + " ".join(str(int(x)) for x in self.split_feature_real),
            "split_gain=" + " ".join(_num_to_str(x) for x in self.split_gain),
            "threshold=" + " ".join(_num_to_str(x) for x in self.threshold),
            "left_child=" + " ".join(str(int(x)) for x in self.left_child),
            "right_child=" + " ".join(str(int(x)) for x in self.right_child),
            "leaf_parent=" + " ".join(str(int(x)) for x in self.leaf_parent),
            "leaf_value=" + " ".join(_num_to_str(x) for x in self.leaf_value),
            "",
        ]
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        """Tree::Tree(const std::string&) (tree.cpp:132-180)."""
        key_vals = {}
        for line in text.split("\n"):
            if "=" in line:
                key, val = line.split("=", 1)
                key, val = key.strip(), val.strip()
                if key and val:
                    key_vals[key] = val
        required = ("num_leaves", "split_feature", "split_gain", "threshold",
                    "left_child", "right_child", "leaf_parent", "leaf_value")
        for key in required:
            if key not in key_vals:
                log.fatal("tree model string format error")
        n = int(key_vals["num_leaves"])

        def ints(key, cnt):
            vals = [int(x) for x in key_vals[key].split()] if cnt > 0 else []
            return np.array(vals[:cnt], dtype=np.int32)

        def floats(key, cnt):
            vals = [float(x) for x in key_vals[key].split()] if cnt > 0 else []
            return np.array(vals[:cnt], dtype=np.float64)

        split_feature_real = ints("split_feature", n - 1)
        return cls(
            num_leaves=n,
            split_feature=split_feature_real,  # inner == real after load
            split_feature_real=split_feature_real,
            threshold_bin=np.zeros(max(n - 1, 0), dtype=np.int32),
            threshold=floats("threshold", n - 1),
            split_gain=floats("split_gain", n - 1),
            left_child=ints("left_child", n - 1),
            right_child=ints("right_child", n - 1),
            leaf_parent=ints("leaf_parent", n),
            leaf_value=floats("leaf_value", n),
        )


def _num_to_str(x) -> str:
    """Number formatting compatible with C++ ostream double output."""
    x = float(x)
    if x == float("inf"):
        return "inf"
    if x == float("-inf"):
        return "-inf"
    if x != x:
        return "nan"
    return repr(x)
