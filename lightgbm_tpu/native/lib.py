"""ctypes loader for the native C++ helper library.

The reference is a pure C++ program; in this framework the device compute is
XLA and the host runtime keeps native C++ for the text-parsing hot path
(utils/text_reader.h + parser.hpp equivalents).  Built by
``lightgbm_tpu/native/build.sh`` (g++ -O3 -fopenmp -shared).
"""
from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

_LIB = None
_TRIED = False


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "liblgbm_native.so")


def _load():
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        path = _lib_path()
        if os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
                lib.parse_delimited.restype = ctypes.c_int
                lib.parse_delimited.argtypes = [
                    ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char,
                    ctypes.c_longlong, ctypes.c_longlong,
                    ctypes.POINTER(ctypes.c_double),
                ]
                _LIB = lib
            except OSError:
                _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def parse_delimited(lines: List[str], delimiter: str) -> Optional[np.ndarray]:
    """Parse uniform delimited lines into a float64 matrix, or None to make
    the caller fall back to the Python path."""
    lib = _load()
    if lib is None or not lines:
        return None
    ncols = lines[0].count(delimiter) + 1
    nrows = len(lines)
    blob = ("\n".join(lines) + "\n").encode()
    out = np.empty((nrows, ncols), dtype=np.float64)
    rc = lib.parse_delimited(
        blob, len(blob), delimiter.encode()[0] if delimiter != "\t" else 9,
        nrows, ncols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if rc != 0:
        return None
    return out
