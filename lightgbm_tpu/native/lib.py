"""ctypes loader for the native C++ helper library.

The reference is a pure C++ program; in this framework the device compute is
XLA and the host runtime keeps native C++ for the text-parsing hot path
(utils/text_reader.h + parser.hpp equivalents).  Built by
``lightgbm_tpu/native/build.sh`` (g++ -O3 -fopenmp -shared).
"""
from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

_LIB = None
_TRIED = False


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "liblgbm_native.so")


def _build():
    """Compile the helper at first use (PipelineReader has no Python
    analog fast enough for Higgs-scale CSVs; a one-time ~3 s g++ build
    makes the native path the default).  Failures are silent — callers
    fall back to the vectorized/pure-Python parsers."""
    import shutil
    import subprocess
    if shutil.which("g++") is None:
        return
    src = os.path.join(os.path.dirname(__file__), "src", "lgbm_native.cpp")
    if not os.path.exists(src):
        return
    # compile to a temp path and rename into place: another process may
    # race first use, and a killed build must not leave a corrupt .so
    # that permanently disables the native path
    tmp = _lib_path() + ".%d.tmp" % os.getpid()
    try:
        subprocess.run(
            ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", "-std=c++17",
             src, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _lib_path())
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _src_path() -> str:
    return os.path.join(os.path.dirname(__file__), "src", "lgbm_native.cpp")


def _load():
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        path = _lib_path()
        stale = False
        try:
            # rebuild when the source is newer than the cached .so (new
            # exported symbols must not silently disappear behind a stale
            # binary)
            stale = (os.path.exists(path)
                     and os.path.getmtime(_src_path())
                     > os.path.getmtime(path))
        except OSError:
            pass
        if not os.path.exists(path) or stale:
            _build()
        if os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
                lib.parse_delimited.restype = ctypes.c_int
                lib.parse_delimited.argtypes = [
                    ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char,
                    ctypes.c_longlong, ctypes.c_longlong,
                    ctypes.POINTER(ctypes.c_double),
                ]
                _LIB = lib
            except Exception:   # bad/incomplete .so: missing symbols too
                _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def set_num_threads(n: int) -> None:
    """Cap the native OpenMP pool (Application ctor parity,
    application.cpp:30-34).  No-op when the library is unavailable or the
    cached .so predates the symbol."""
    lib = _load()
    if lib is None or n <= 0:
        return
    try:
        lib.set_num_threads(ctypes.c_int(int(n)))
    except AttributeError:
        pass


def parse_delimited(lines: List[str], delimiter: str) -> Optional[np.ndarray]:
    """Parse uniform delimited lines into a float64 matrix, or None to make
    the caller fall back to the Python path."""
    lib = _load()
    if lib is None or not lines:
        return None
    ncols = lines[0].count(delimiter) + 1
    nrows = len(lines)
    blob = ("\n".join(lines) + "\n").encode()
    out = np.empty((nrows, ncols), dtype=np.float64)
    rc = lib.parse_delimited(
        blob, len(blob), delimiter.encode()[0] if delimiter != "\t" else 9,
        nrows, ncols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if rc != 0:
        return None
    return out
