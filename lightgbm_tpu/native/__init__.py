"""Native C++ acceleration library (text parsing, binning kernels).

Built from native/src/*.cpp into a shared library loaded via ctypes; every
entry point has a NumPy fallback so the framework works without the build.
"""
