#!/bin/sh
# Build the native host-runtime library (see src/lgbm_native.cpp).
set -e
cd "$(dirname "$0")"
g++ -O3 -fopenmp -shared -fPIC -std=c++17 src/lgbm_native.cpp -o liblgbm_native.so
echo "built $(pwd)/liblgbm_native.so"
