// Native host-runtime kernels for lightgbm_tpu.
//
// The reference implements its whole runtime in C++ (parsers at
// src/io/parser.hpp, pipelined text reading at utils/text_reader.h /
// pipeline_reader.h, locale-free Atof at utils/common.h).  In this
// framework the device compute is XLA; this library keeps the HOST hot
// paths native: delimited text -> float64 matrix parsing (OpenMP over
// rows) and value->bin quantization.  Loaded via ctypes
// (lightgbm_tpu/native/lib.py); every entry point has a NumPy fallback.
//
// Build: lightgbm_tpu/native/build.sh  (g++ -O3 -fopenmp -shared -fPIC)

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// Locale-free float parse; na/nan/garbage parse as 0 like the reference's
// Atof (utils/common.h:177-178 treats na/nan as 0).
inline double parse_token(const char* begin, const char* end) {
  while (begin < end && (*begin == ' ' || *begin == '\t')) ++begin;
  if (begin >= end) return 0.0;
  char buf[64];
  size_t len = static_cast<size_t>(end - begin);
  if (len >= sizeof(buf)) len = sizeof(buf) - 1;
  std::memcpy(buf, begin, len);
  buf[len] = '\0';
  char* parse_end = nullptr;
  double value = std::strtod(buf, &parse_end);
  if (parse_end == buf) return 0.0;  // na / nan / unparseable
  if (std::isnan(value)) return 0.0;
  return value;
}

}  // namespace

extern "C" {

// Parse `nrows` lines of `delim`-separated numbers from `blob` into the
// preallocated row-major out[nrows*ncols].  Returns 0 on success, nonzero
// when any line has the wrong column count (caller falls back to Python
// for the precise reference-style error).
int parse_delimited(const char* blob, long long blob_len, char delim,
                    long long nrows, long long ncols, double* out) {
  // pass 1: line starts
  std::vector<const char*> starts;
  starts.reserve(static_cast<size_t>(nrows) + 1);
  const char* p = blob;
  const char* end = blob + blob_len;
  starts.push_back(p);
  for (const char* q = p; q < end; ++q) {
    if (*q == '\n' && q + 1 < end) starts.push_back(q + 1);
  }
  if (static_cast<long long>(starts.size()) < nrows) return 1;

  int bad = 0;
  // pass 2: parse rows in parallel
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < nrows; ++i) {
    const char* line = starts[static_cast<size_t>(i)];
    const char* line_end =
        (i + 1 < static_cast<long long>(starts.size()))
            ? starts[static_cast<size_t>(i + 1)] - 1
            : end;
    while (line_end > line && (line_end[-1] == '\n' || line_end[-1] == '\r'))
      --line_end;
    long long col = 0;
    const char* tok = line;
    for (const char* q = line; q <= line_end; ++q) {
      if (q == line_end || *q == delim) {
        if (col < ncols) out[i * ncols + col] = parse_token(tok, q);
        ++col;
        tok = q + 1;
      }
    }
    if (col != ncols) {
#pragma omp atomic write
      bad = 1;
    }
  }
  return bad;
}

// Quantize values[n] into bins via upper-bound binary search
// (BinMapper::ValueToBin, include/LightGBM/bin.h:296-309): first bin whose
// upper bound >= value; bounds has num_bin entries, last is +inf.
void value_to_bin(const double* values, long long n, const double* bounds,
                  int num_bin, unsigned char* out) {
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    int lo = 0, hi = num_bin - 1;
    double v = values[i];
    while (lo < hi) {
      int mid = (lo + hi - 1) / 2;
      if (v <= bounds[mid]) {
        hi = mid;
      } else {
        lo = mid + 1;
      }
    }
    out[i] = static_cast<unsigned char>(lo);
  }
}

int num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

// Application::Application (application.cpp:30-34): the num_threads config
// caps the OpenMP pool for every native parallel region.
void set_num_threads(int n) {
#if defined(_OPENMP)
  if (n > 0) omp_set_num_threads(n);
#else
  (void)n;
#endif
}

}  // extern "C"
