"""Layer 2: jaxpr invariant rules over the canonical traced programs.

The AST layer proves source-level coverage; this layer proves what XLA
will actually execute.  Programs are traced with ``jax.make_jaxpr`` (no
compilation) and their closed jaxprs walked recursively (shard_map /
scan / cond / while sub-jaxprs included):

- **J1 jaxpr-dtype-discipline** — two checks on every (sub)jaxpr:
  (a) *int-domain purity*: walking BACKWARD from any integer-operand
  collective (the int8 accumulator exchange) along integer/bool value
  vars — crossing sub-jaxpr boundaries via the loop-carry/shard_map
  operand bindings, stopping at comparisons (selection logic is control,
  not value) — every float->int convert on the chain must be a GENUINE
  quantization (its float region rounds/clamps before casting); an
  int->float convert reached first means an integer value was laundered
  through float arithmetic and re-cast — the silent-f32-contamination
  class that would break the serial == distributed bit-identity chain;
  (b) *no id narrowing*: no
  ``convert_element_type`` from a >=32-bit integer into a dtype whose
  exact-integer capacity is below the program's global feature/bin width
  (bf16 holds 256 consecutive ints, f16 2048, int8 127 — the PR 9
  bf16-split-id bug as a general rule).
- **J2 jaxpr-collective-census** — the multiset of collective eqns in
  the jaxpr, by kind, must agree with the telemetry seam inventory
  recorded while tracing the SAME program (``trace_census``): a kind
  with eqns but zero declared sites is an unwrapped exchange the gated
  wire-byte model cannot see; a declared kind with no eqns (or fewer
  eqns than declared traces) is a stale seam record.  One telemetry
  record may legitimately cover SEVERAL eqns (a tree-mapped allgather
  files once for ~10 leaf gathers; quantize files one record for its
  two scale pmaxes), so the per-kind relation is
  ``eqns >= declared_traces`` with exact presence/absence — drift in
  either direction is a finding.

Census arming: ``begin_census()`` / ``end_census()`` (or the
``trace_census()`` context manager) arm the telemetry registry in
trace-census mode so ``record_collective`` files sites during the
``make_jaxpr`` trace.  The mode is process-global like every telemetry
state; tests/conftest.py's leak guard fails any test that leaves it
armed (``trace_census_active()``).
"""
from __future__ import annotations

import collections
import contextlib
from typing import Dict, List, Optional

from .findings import Finding

# jaxpr primitive name -> telemetry collective kind
_PRIM_KINDS = {
    "psum": "psum",
    "psum2": "psum",
    "reduce_scatter": "psum_scatter",
    "psum_scatter": "psum_scatter",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "pmax": "pmax",
    "pmin": "pmin",
    "ppermute": "ppermute",
}

# exact-consecutive-integer capacity per destination dtype (J1b): the
# largest n such that every integer in [0, n] is representable
_INT_CAPACITY = {
    "int8": 127, "uint8": 255, "int16": 32767, "uint16": 65535,
    "bfloat16": 256, "float16": 2048, "float32": 1 << 24,
    "float64": 1 << 53,
}


def _subjaxprs(eqn):
    for val in eqn.params.values():
        for item in (val if isinstance(val, (list, tuple)) else (val,)):
            inner = getattr(item, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner            # ClosedJaxpr
            elif hasattr(item, "eqns"):
                yield item             # raw Jaxpr


def _walk_jaxprs(jaxpr):
    """Yield the jaxpr and every nested sub-jaxpr (shard_map / scan /
    while / cond bodies), depth-first."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in _subjaxprs(eqn):
            yield from _walk_jaxprs(sub)


def collective_census(jaxpr) -> "collections.Counter":
    """Multiset of collective eqns by normalized kind, all levels."""
    census: collections.Counter = collections.Counter()
    for jx in _walk_jaxprs(jaxpr):
        for eqn in jx.eqns:
            kind = _PRIM_KINDS.get(eqn.primitive.name)
            if kind is not None:
                census[kind] += 1
    return census


# --------------------------------------------------------------- J1 checks

def _dtype_of(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def _is_int(dt) -> bool:
    return dt is not None and dt.kind in ("i", "u")


def _is_float(dt) -> bool:
    return dt is not None and dt.kind == "f"


def _build_dataflow(jaxpr):
    """Cross-level backward-dataflow maps: ``produced`` (id(var) ->
    producing eqn, any level) and ``alias`` (id(sub-jaxpr invar) -> the
    enclosing eqn's operand it binds to), so a slice can follow a value
    INTO a scan/while/cond/shard_map body — the int8 accumulator psum
    lives inside loop bodies while contamination can be introduced in
    the enclosing trace and carried in.

    Operand binding is positional: pjit/shard_map/closed_call and scan
    bind sub invars 1:1 with eqn invars; cond branches bind to
    ``invars[1:]`` (after the branch index); while bodies bind to the
    TAIL (cond-consts precede body-consts + carry in the eqn's
    operands).  Id-keyed throughout — jaxpr Literals are unhashable and
    var identity is stable per trace."""
    produced, alias = {}, {}

    def visit(jx):
        jx = getattr(jx, "jaxpr", jx)
        for eqn in jx.eqns:
            for var in eqn.outvars:
                produced[id(var)] = eqn
            for sub in _subjaxprs(eqn):
                inner = getattr(sub, "jaxpr", sub)
                si, oi = list(inner.invars), list(eqn.invars)
                if len(si) == len(oi):
                    pairs = zip(si, oi)
                elif len(si) == len(oi) - 1:
                    pairs = zip(si, oi[1:])
                elif len(si) < len(oi):
                    pairs = zip(si, oi[-len(si):])
                else:
                    pairs = ()
                for s, o in pairs:
                    alias[id(s)] = o
                visit(inner)
    visit(jaxpr)
    return produced, alias


# eqns that mark a GENUINE quantization step: a float region that rounds
# or clamps before converting to int is quantizing by design, not
# laundering an int value through float arithmetic
_QUANT_MARKERS = frozenset({"round", "floor", "ceil", "clamp", "sign",
                            "nextafter"})


def _is_bool(dt) -> bool:
    return dt is not None and dt.kind == "b"


def _float_region_launders(var0, produced, alias):
    """From the float input of a float->int convert, walk the float
    region backward: hitting a quantization marker ends that path
    (genuine quantize rounds/clamps before casting); hitting an
    int->float convert FIRST means an integer value was laundered
    through float arithmetic and re-cast — the contamination signature.
    Returns the laundering convert's input dtype, or None."""
    stack = [var0]
    seen = set()
    while stack:
        var = stack.pop()
        if id(var) in seen:
            continue
        seen.add(id(var))
        src = produced.get(id(var))
        if src is None:
            outer = alias.get(id(var))
            if outer is not None:
                stack.append(outer)
            continue
        name = src.primitive.name
        if name in _QUANT_MARKERS:
            continue
        if name == "convert_element_type":
            in_dt = _dtype_of(src.invars[0])
            if _is_int(in_dt):
                return in_dt
            continue   # bool->float masks and f->f widenings are benign
        stack.extend(v for v in src.invars
                     if not (_is_int(_dtype_of(v))
                             or _is_bool(_dtype_of(v))))
    return None


# comparison eqns mark CONTROL boundaries on the int value chain: which
# rows/leaves a reduction covers is selection logic (argmax over f32
# gains, smaller-child count compares — f32 counts are exact integers
# under the count lane's 1.0 scale), not the accumulator's value path
_CMP_PRIMS = frozenset({"eq", "ne", "lt", "le", "gt", "ge"})


def _check_int_chain(eqn, kind, produced, alias, program) -> List[Finding]:
    """Backward slice from an integer-operand collective, following ONLY
    integer/bool vars and stopping at comparisons (the value chain of an
    int reduction; selection logic is control, not value).  Every
    float->int convert on the chain is a quantization boundary whose
    float region must quantize (round/clamp) rather than launder an int
    value (``_float_region_launders``)."""
    findings: List[Finding] = []
    stack = list(eqn.invars)
    seen = set()
    while stack:
        var = stack.pop()
        if id(var) in seen:
            continue
        seen.add(id(var))
        dt = _dtype_of(var)
        if dt is not None and not (_is_int(dt) or _is_bool(dt)):
            continue
        src = produced.get(id(var))
        if src is None:
            # a sub-jaxpr invar: follow the binding out to the enclosing
            # eqn's operand (loop carries, shard_map args)
            outer = alias.get(id(var))
            if outer is not None:
                stack.append(outer)
            continue
        if src.primitive.name in _CMP_PRIMS:
            continue
        if src.primitive.name == "convert_element_type":
            in_dt = _dtype_of(src.invars[0])
            if _is_float(in_dt):
                laundered = _float_region_launders(src.invars[0],
                                                   produced, alias)
                if laundered is not None:
                    findings.append(Finding(
                        "J1", program, 0, program,
                        "convert_element_type->float32",
                        "float conversion on the int8 accumulator path "
                        "BEFORE the int-domain %s (%s laundered through "
                        "float arithmetic with no quantization step) — "
                        "the serial==distributed bit-identity chain is "
                        "contaminated" % (kind, laundered)))
                continue   # boundary either way
        stack.extend(src.invars)
    return findings


def check_dtype_discipline(jaxpr, *, program: str, feature_width: int = 0,
                           bin_width: int = 0) -> List[Finding]:
    """J1 over every (sub)jaxpr level of ``jaxpr``.  ``feature_width`` /
    ``bin_width`` are the GLOBAL widths of the traced schema — narrowing
    is judged against them, not any owned slice (the PR 9 lesson)."""
    findings: List[Finding] = []
    needed = max(int(feature_width), int(bin_width))
    produced, alias = _build_dataflow(jaxpr)
    for jx in _walk_jaxprs(jaxpr):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            kind = _PRIM_KINDS.get(name)
            # ---- (a) int-domain purity backward from int collectives
            if kind in ("psum", "psum_scatter") and all(
                    _is_int(_dtype_of(v)) for v in eqn.invars):
                findings.extend(_check_int_chain(eqn, kind, produced,
                                                 alias, program))
            # ---- (b) id narrowing below the global feature/bin width
            if name == "convert_element_type" and needed > 0:
                in_dt = _dtype_of(eqn.invars[0])
                out_dt = _dtype_of(eqn.outvars[0])
                if (_is_int(in_dt) and in_dt.itemsize >= 4
                        and out_dt is not None):
                    cap = _INT_CAPACITY.get(str(out_dt))
                    if cap is not None and needed > cap:
                        findings.append(Finding(
                            "J1", program, 0, program,
                            "convert_element_type %s->%s" % (in_dt, out_dt),
                            "integer narrowing below the global "
                            "feature/bin width (%d > %s-exact %d) — ids "
                            "beyond the representable range silently "
                            "corrupt (the PR 9 bf16-split-id class)"
                            % (needed, out_dt, cap)))
    return findings


# ----------------------------------------------------- trace-mode census

_census_armed = False


def trace_census_active() -> bool:
    """True while the trace-mode telemetry arming is live — the
    tests/conftest.py leak-guard check."""
    return _census_armed


def begin_census() -> None:
    """Arm telemetry (no sink) and zero the collective registry so the
    next ``make_jaxpr`` trace files a clean seam inventory.  Process-
    global state: pair with ``end_census`` (prefer ``trace_census``).

    REFUSES to arm over an already-enabled telemetry session: the census
    must reset the registry to read cleanly, and resetting would destroy
    the session's accumulated inventory (route counters, collective
    sites, phase times) — callers running the jaxpr layer mid-training
    must disable telemetry around it, not lose their data silently."""
    global _census_armed
    from .. import telemetry
    if _census_armed:
        raise RuntimeError("trace census already armed (unbalanced "
                           "begin_census)")
    if telemetry.enabled():
        raise RuntimeError(
            "telemetry is already enabled — the trace census would reset "
            "(destroy) the session's accumulated registry; disable "
            "telemetry before running the graftlint jaxpr layer")
    telemetry.enable()
    telemetry.reset()
    _census_armed = True


def end_census() -> Dict[str, dict]:
    """Collect the seam inventory recorded since ``begin_census`` and
    return telemetry to its resting (disabled) state."""
    global _census_armed
    from .. import telemetry
    sites = telemetry.collectives()
    telemetry.disable()
    telemetry.reset()
    _census_armed = False
    return sites


@contextlib.contextmanager
def trace_census():
    """``with trace_census() as holder: jaxpr = jax.make_jaxpr(fn)(*args)``
    — afterwards ``holder.sites`` is the recorded seam inventory."""
    class _Holder:
        sites: Dict[str, dict] = {}
    holder = _Holder()
    begin_census()
    try:
        yield holder
    finally:
        holder.sites = end_census()


def traced_inventory(fn, *args) -> "tuple[object, Dict[str, dict]]":
    """Trace ``fn(*args)`` under the census: returns (closed_jaxpr,
    telemetry seam inventory recorded during that trace)."""
    import jax
    with trace_census() as holder:
        jaxpr = jax.make_jaxpr(fn)(*args)
    return jaxpr, holder.sites


def declared_census(sites: Dict[str, dict]) -> "collections.Counter":
    """Telemetry seam inventory -> declared {kind: traced_calls} multiset.
    Sites filed with the grower-generic kind ``"reduce"`` (wrap_schedule's
    fallback label for custom learners) are counted under a wildcard key
    that matches any reduction kind."""
    declared: collections.Counter = collections.Counter()
    for rec in sites.values():
        declared[rec.get("kind", "reduce")] += int(rec.get("traced_calls", 1))
    return declared


# kinds a generic ``kind="reduce"`` site (wrap_schedule's fallback label)
# may legitimately stand in for — NEVER an all_gather/all_to_all/ppermute
_REDUCTION_KINDS = frozenset({"psum", "psum_scatter", "pmax", "pmin"})


def check_collective_census(program: str, jaxpr,
                            sites: Dict[str, dict]) -> List[Finding]:
    """J2: jaxpr collective census vs the declared seam inventory."""
    actual = collective_census(jaxpr)
    declared = declared_census(sites)
    generic = declared.pop("reduce", 0)
    findings: List[Finding] = []
    for kind, n in sorted(actual.items()):
        if declared.get(kind, 0) == 0 and not (
                generic and kind in _REDUCTION_KINDS):
            findings.append(Finding(
                "J2", program, 0, program, kind,
                "%d %s eqn(s) in the traced program but ZERO declared "
                "telemetry sites — the wire-byte model cannot see this "
                "exchange" % (n, kind)))
    if generic and not any(actual.get(k, 0) for k in _REDUCTION_KINDS):
        findings.append(Finding(
            "J2", program, 0, program, "reduce",
            "declared %d generic reduce site call(s) but the jaxpr "
            "contains no reduction eqns — a stale seam record misprices "
            "the wire series" % generic))
    for kind, n in sorted(declared.items()):
        have = actual.get(kind, 0)
        if have == 0:
            findings.append(Finding(
                "J2", program, 0, program, kind,
                "declared %d traced %s site call(s) but the jaxpr "
                "contains none — a stale seam record misprices the "
                "wire series" % (n, kind)))
        elif have < n:
            findings.append(Finding(
                "J2", program, 0, program, kind,
                "jaxpr has %d %s eqn(s) but %d declared traced calls — "
                "declared traces exceed what XLA executes" % (have, kind, n)))
    return findings
